"""Benchmarks over the BASELINE.md configs; prints ONE JSON line.

Default (no args): when the accelerator probe succeeds, the FULL sweep —
every config below runs and the one JSON line carries a per-config
record under ``configs`` (headline fields = config 1, trials/hour), so a
single driver invocation captures complete evidence for every BASELINE
row. On CPU fallback the default degrades to the single fast config
(``trials``) — the cross-platform numbers would be meaningless and the
heavy configs would take hours on 1 core.

``--config trials``: AutoML trials/hour on the PR1 reference config —
K full trials (propose -> train -> evaluate) of JaxFeedForward on a
synthetic fashion-MNIST-shaped dataset.

``--config serving``: ensemble-inference QPS through the real serving
path (Predictor HTTP -> bus scatter/gather -> InferenceWorker AOT
predict), BASELINE config[3].

``--config multitenant``: aggregate trials/hour of two concurrent train
jobs contending for chip ranges, BASELINE config[4] (needs >= 2 devices;
run on the CPU mesh via JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8).

``--config analysis``: static-analysis gate smoke — runs
``python -m rafiki_tpu.analysis --json`` and records the per-code
finding counts (value = NEW findings; healthy is exactly 0). Excluded
from the sweep: it is a gate, not a perf figure.

``--config chaos``: closed-loop recovery under a seeded fault plan
(docs/robustness.md) — availability (headline; 1.0 = zero dropped
queries while replicas are being hard-killed and respawned) and
time-to-full-recovery per injure->recover cycle, plus the
injection-site hot-path A/B (fault plane disabled vs armed-empty).
Excluded from the sweep: it injures its own stack.

``--config lm-serving``: the continuous-batching generative A/B
(docs/serving.md "Generative serving") — one LM zoo model served
through the paged-KV engine + DecodeScheduler with per-step admission
(decode width W) vs run-to-completion FIFO (width 1), same mixed
short/long workload. Judged on the ``rafiki_tpu_lm_tokens_total`` /
``rafiki_tpu_lm_decode_dispatches_total`` counter pair
(tokens/dispatch must rise toward W on the continuous side and pin at
~1 on the static side), the short-finishes-while-long-resident
latency split, a prefix-cache hit, and the generate-off
zero-``rafiki_tpu_lm_*``-series gate. Excluded from the sweep: judged
on counter deltas, not a throughput figure.

``--config slo``: the SLO plane's alert loop closed end to end
(docs/observability.md "SLOs & alerting") — chaos-injected worker
latency (``worker.slow``) drives a latency objective healthy ->
burning -> firing -> an SLO-triggered autoscale scale-up -> resolved
after the fault clears, with the alert ring, budget-gauge deltas and
the OFF side's zero-``rafiki_tpu_slo_*``-series gate recorded.
Excluded from the sweep: it injures its own stack. Needs >= 2
devices (the scale-up replica lands on the free chip); on a 1-device
accelerator box run the CPU mesh via JAX_PLATFORMS=cpu.

The reference publishes no numbers (BASELINE.md): the first recorded run
of each config on TPU establishes its baseline; the BASELINES table
below holds those recorded figures per platform channel; update them
when re-baselining.

Measurement methodology (r4 verdict items 2/6): every config measures
ADAPTIVE windows after warm-up — more windows until the best two agree
within 10% (capped), reporting the best (measuring the framework, not
the box's worst moment) plus ``n_windows``/``spread``/``windows`` so a
noisy figure is visibly noisy in the artifact rather than silently
canonical. Between sweep configs an idle gate waits for the host to
quiesce (the 1-core sandbox: one config's teardown tail depresses the
next config's window) and records the busy fraction it started at.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# The same v5e-1 chip is reachable over two measurement channels with
# very different sync latencies: "axon" (the shared tunnel; ~0.2-0.7 s
# per device->host sync, >2x run-to-run variance) and "tpu" (direct
# attachment). Comparing a direct-chip value against a tunnel-recorded
# baseline reads as a ~5x "win" that is pure channel artifact — so
# baselines are PER PLATFORM, vs_baseline only ever compares within one
# channel, and any other platform (cpu) carries vs_baseline = null.
# None => the next run on that channel establishes the baseline (1.0).
BASELINE_PLATFORMS = ("axon", "tpu")
BASELINES = {
    # Recorded from the first tunneled v5e-1 run (BASELINE.md,
    # 2026-07-30, round 1).
    "axon": {
        "automl_trials_per_hour": 268.0,
        "ensemble_inference_qps": 1097.0,
        "serving_openloop_qps": None,
        # r6: cross-request micro-batching config — first recorded run
        # on each channel establishes the baseline.
        "serving_concurrent_qps": None,
        # r5: single-chip time-sliced tenancy made this runnable on
        # one chip; the first recorded run establishes the baseline.
        "multitenant_trials_per_hour": None,
        "densenet_train_images_per_sec": 1504.0,
        "enas_trials_per_hour": 254.1,
        # r5: flagship LM roofline config — first recorded run on each
        # channel establishes the baseline.
        "lm_train_tokens_per_sec": None,
        # The XLA O(T^2) attention is the "reference implementation"
        # the Pallas kernel replaces; its measured throughput is the
        # baseline.
        "flash_attention_tflops": 16.5,
    },
    # Recorded from the first direct-attached v5e-1 sweep
    # (BENCH_builder_r04_tpu.json, 2026-07-31, round 4).
    "tpu": {
        "automl_trials_per_hour": 1411.6,
        "ensemble_inference_qps": 1704.5,
        "serving_openloop_qps": 3301.4,
        # r6: cross-request micro-batching config — first recorded run
        # on each channel establishes the baseline.
        "serving_concurrent_qps": None,
        # r5: single-chip time-sliced tenancy made this runnable on
        # one chip; the first recorded run establishes the baseline.
        "multitenant_trials_per_hour": None,
        "densenet_train_images_per_sec": 1553.4,
        "enas_trials_per_hour": 967.5,
        # r5: flagship LM roofline config — first recorded run on each
        # channel establishes the baseline.
        "lm_train_tokens_per_sec": None,
        # XLA O(T^2) attention measured 12.9 TFLOP/s on the direct
        # chip (B=2 H=8 T=8192 D=128 bf16 causal) — the honest
        # reference for the kernel's speedup on this channel.
        "flash_attention_tflops": 12.9,
    },
}

N_TRIALS = 3
N_TRAIN, N_VAL = 4096, 512
IMAGE_SHAPE = (28, 28, 1)
N_CLASSES = 10


class _UtilProbe:
    """Captures ``chip_util`` records the models log (the MfuMeter →
    TrialLog path) so bench rows report the north-star utilization
    (BASELINE.json: ≥90% during train) alongside throughput."""

    def __init__(self):
        self.values = []
        self._prior = None

    def __enter__(self) -> "_UtilProbe":
        from rafiki_tpu.model.logger import logger

        self._logger = logger
        # The sink binding is thread-local; save whatever this thread had
        # installed and chain to it so a probe never swallows records a
        # surrounding harness (or a prior probe) was collecting.
        self._prior = logger.current_sink()
        logger.set_sink(self._collect)
        return self

    def __exit__(self, *exc) -> None:
        self._logger.set_sink(self._prior)

    def _collect(self, rec) -> None:
        util = (rec.get("values") or {}).get("chip_util")
        if util is not None:
            self.values.append(float(util))
        if self._prior is not None:
            self._prior(rec)

    def fields(self) -> dict:
        if not self.values:
            return {}
        # Mean over the run is the defensible sustained-utilization
        # statistic (a single 90% epoch must not read as the north star
        # met); the peak rides along for context.
        return {"chip_util": round(float(np.mean(self.values)), 4),
                "chip_util_peak": round(max(self.values), 4)}


def _settled(vals, target_spread: float = 0.10) -> bool:
    """The ONE settle criterion every config uses: the best two windows
    agree within ``target_spread`` of the best."""
    top = sorted(vals, reverse=True)[:2]
    return len(top) >= 2 and (top[0] - top[1]) <= target_spread * top[0]


def _adaptive_windows(window_fn, *, min_windows: int = 2,
                      max_windows: int = 4,
                      target_spread: float = 0.10):
    """Run measurement windows until the best two agree within
    ``target_spread`` (or the cap): a quiet box stops at ``min_windows``,
    a noisy one earns more. ``window_fn`` returns the window's rate
    (higher = better). Returns ``(best, fields)`` where ``fields``
    carries ``n_windows``/``spread``/``windows`` for the bench record —
    the spread is the artifact reader's noise indicator (r4: depressed
    in-sweep values were indistinguishable from real regressions)."""
    vals = []
    while True:
        vals.append(float(window_fn()))
        if len(vals) >= min_windows:
            if _settled(vals, target_spread) or len(vals) >= max_windows:
                break
    best = max(vals)
    return best, {
        "n_windows": len(vals),
        "spread": round((best - min(vals)) / best, 3) if best else 0.0,
        "windows": [round(v, 2) for v in vals],
    }


def _closed_loop_window(url: str, body: dict, n_clients: int,
                        duration: float, count_by: int = 1) -> float:
    """One closed-loop measurement window: ``n_clients`` threads POST
    ``body`` to ``url`` as fast as replies come back for ``duration``
    seconds; returns the achieved rate (x ``count_by`` per reply).
    The shared harness for serving A/Bs — per-window client code kept
    drifting between configs (r13 review)."""
    import threading

    import requests

    counts = [0] * n_clients
    errors: list = []
    stop = threading.Event()

    def client(i: int) -> None:
        session = requests.Session()
        try:
            while not stop.is_set():
                r = session.post(url, json=body, timeout=300)
                r.raise_for_status()
                counts[i] += count_by
        except Exception as e:  # surfaced to the caller below
            errors.append(e)
            stop.set()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join()
    if errors:
        raise RuntimeError(f"bench client failed: {errors[0]}")
    return sum(counts) / (time.monotonic() - t0)


def _host_busy_fraction(dt: float = 0.5) -> float:
    """Whole-host CPU busy fraction over a short sample (/proc/stat)."""
    def snap():
        vals = [int(x) for x in
                open("/proc/stat").readline().split()[1:]]
        return sum(vals), vals[3] + vals[4]  # total, idle+iowait
    try:
        t1, i1 = snap()
        time.sleep(dt)
        t2, i2 = snap()
        return 1.0 - (i2 - i1) / max(t2 - t1, 1)
    except OSError:  # non-Linux: no idle gate, just the cooldown
        time.sleep(dt)
        return 0.0


def _idle_gate(cooldown: float = 3.0, busy_max: float = 0.5,
               max_wait: float = None) -> float:
    """Cooldown + idle gate between sweep configs: let the previous
    config's teardown (worker threads, HTTP servers, tempdir sweeps)
    drain before the next window opens. Returns the busy fraction at
    release, recorded as ``host_busy_at_start``.

    ``RAFIKI_TPU_BENCH_IDLE_MAX_WAIT`` caps the busy-wait (bench-only
    knob, like RAFIKI_TPU_BENCH_CONFIGS): the tier-1 sweep-contract
    test runs on a deliberately busy box where waiting out the full
    gate is pure test-budget burn."""
    import gc

    if max_wait is None:
        try:
            max_wait = float(os.environ.get(
                "RAFIKI_TPU_BENCH_IDLE_MAX_WAIT", 45.0))
        except ValueError:
            max_wait = 45.0
    gc.collect()
    time.sleep(cooldown)
    t0 = time.time()
    busy = _host_busy_fraction()
    while busy > busy_max and time.time() - t0 < max_wait:
        time.sleep(2.0)
        busy = _host_busy_fraction()
    return round(busy, 3)


def main() -> dict:
    """Config[trials]: the FULL production trial lifecycle — a
    TrialRunner (propose -> load/stage -> train -> eval -> persist)
    against real stores, with the r9 residency caches warm and the
    persist tail pipelined. Emits the per-phase breakdown (mean seconds
    per trial per phase, from the same ``rafiki_tpu_trial_phase_seconds``
    histogram production scrapes) and an A/B window with BOTH caches
    forced off (the r5 reload-and-restage-every-trial behavior), so the
    artifact shows where the win comes from: on a single device it must
    be host/H2D elimination, not parallelism."""
    import tempfile

    from rafiki_tpu.advisor import PrefetchAdvisor, make_advisor
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.datasets import make_synthetic_image_dataset
    from rafiki_tpu.model import dataset as _mod_dataset
    from rafiki_tpu.model import jax_model as _mod_jax
    from rafiki_tpu.models.feedforward import JaxFeedForward
    from rafiki_tpu.observe import phases as _phases
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    def phase_breakdown(before, after):
        """Mean seconds per TRIAL per phase between two
        ``phase_totals`` snapshots. Normalised by the trial count (the
        ``train`` phase fires once per trial), not each phase's own
        observation count — ``load``/``stage`` are observed twice per
        trial (train + eval) and dividing by their own counts would
        halve exactly the numbers this breakdown exists to show."""
        n_trials = after["train"]["count"] - before["train"]["count"]
        out = {}
        for p in _phases.PHASES:
            s = after[p]["sum"] - before[p]["sum"]
            out[p] = round(s / n_trials, 4) if n_trials else None
        return out

    def cache_delta(before, after):
        return {c: {e: after[c].get(e, 0) - before[c].get(e, 0)
                    for e in ("hit", "miss")}
                for c in ("dataset", "stage")}

    def cache_snap():
        return {c: _phases.cache_counts(c) for c in ("dataset", "stage")}

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset(
            tmp, n_train=N_TRAIN, n_val=N_VAL, image_shape=IMAGE_SHAPE,
            n_classes=N_CLASSES)
        meta = MetaStore(":memory:")
        params = ParamStore(tmp + "/params")

        # PrefetchAdvisor pipelines the GP refit (grows to O(seconds)
        # of host time with trial history) behind the device compute —
        # SURVEY §7's async proposal queue. The context manager flushes
        # the dangling prefetch even when a trial errors out.
        with PrefetchAdvisor(make_advisor(
                JaxFeedForward.get_knob_config(), seed=0)) as advisor:
            runner = TrialRunner(
                JaxFeedForward, advisor, train_path, val_path, meta,
                params, sub_train_job_id="bench-trials",
                budget={BudgetOption.MODEL_TRIAL_COUNT: 10_000},
                pipeline_persist=True)
            # Warm-up trial (outside the timed window): first XLA
            # compile is ~20-40s and would otherwise dominate the
            # measurement.
            runner.run_one()
            runner.drain_persist()

            def window() -> float:
                t0 = time.time()
                for _ in range(N_TRIALS):
                    runner.run_one()
                # The drain keeps the figure honest: a window must not
                # end with its last trial's persistence still pending.
                runner.drain_persist()
                return N_TRIALS / ((time.time() - t0) / 3600.0)

            ph0, ca0 = _phases.phase_totals(), cache_snap()
            with _UtilProbe() as probe:
                trials_per_hour, fields = _adaptive_windows(window)
            breakdown = phase_breakdown(ph0, _phases.phase_totals())
            caches = cache_delta(ca0, cache_snap())

            # A/B: both residency caches forced OFF (and cleared) —
            # every trial re-parses the dataset from disk and re-ships
            # it to the device, the r5 behavior. Same adaptive-window
            # estimator as the ON side (best-of-settled-windows vs a
            # single off sample would bias the ratio upward on a noisy
            # box); same process, same warm XLA executables, so the
            # ratio is the caches' contribution alone.
            cache_envs = {_mod_dataset.DATASET_CACHE_ENV: "0",
                          _mod_jax.STAGE_CACHE_ENV: "0"}
            prior_env = {k: os.environ.get(k) for k in cache_envs}
            os.environ.update(cache_envs)
            _mod_dataset.clear_dataset_cache()
            _mod_jax.clear_stage_cache()
            try:
                ph1 = _phases.phase_totals()
                tph_off, fields_off = _adaptive_windows(window)
                breakdown_off = phase_breakdown(
                    ph1, _phases.phase_totals())
            finally:
                for k, v in prior_env.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
            runner.close()
        meta.close()
        params.close()

    return _emit("automl_trials_per_hour", trials_per_hour,
                 "trials/hour", **fields, **probe.fields(),
                 pipeline_persist=True,
                 phase_seconds_per_trial=breakdown,
                 cache_events=caches,
                 trials_per_hour_caches_off=round(tph_off, 2),
                 n_windows_caches_off=fields_off["n_windows"],
                 spread_caches_off=fields_off["spread"],
                 phase_seconds_per_trial_caches_off=breakdown_off,
                 caches_speedup=round(trials_per_hour / tph_off, 3)
                 if tph_off else None)


def _emit(metric: str, value: float, unit: str, **extra) -> dict:
    """Build (and return) one config's record. The caller — single-config
    mode or the sweep — owns printing; config functions just return this.
    The baseline is resolved per (platform, metric) from BASELINES."""
    import jax

    platform = jax.default_backend()
    baseline = BASELINES.get(platform, {}).get(metric)
    if platform not in BASELINE_PLATFORMS:
        # Recorded baselines are TPU figures; a CPU/other-platform value
        # compared against them is nonsense (a 9x "win" from a CPU run
        # is the bug this guards against).
        vs = None
    elif baseline is None:
        vs = 1.0  # this run establishes the baseline
    else:
        vs = round(value / baseline, 3)
    rec = {"metric": metric, "value": round(value, 2), "unit": unit,
           "vs_baseline": vs, "platform": platform, **extra}
    if "chip_util" in rec:
        rec["chip_util_basis"] = ("spec-peak" if platform in
                                  BASELINE_PLATFORMS
                                  else "calibrated-cpu-roofline")
    return rec


def _http_predict_buckets(host: str, http_service: str) -> dict:
    """Cumulative /predict latency buckets {le: count} from one
    predictor frontend's own exposition — snapshot-diffable. The ONE
    copy every A/B config (zipf, serving-concurrent, autoscale)
    scrapes with, so label/+Inf handling cannot drift between them."""
    import requests

    from rafiki_tpu.observe.metrics import parse_exposition

    metrics = parse_exposition(
        requests.get(f"http://{host}/metrics", timeout=30).text)
    out = {}
    for labels, v in metrics.get(
            "rafiki_tpu_http_request_seconds_bucket", []):
        if labels.get("service") != http_service or \
                labels.get("route") != "/predict":
            continue
        le = labels.get("le")
        bound = float("inf") if le == "+Inf" else float(le)
        out[bound] = out.get(bound, 0) + int(v)
    return out


def _bucket_delta_percentiles_ms(before: dict, after: dict,
                                 qs=(0.5, 0.95, 0.99)):
    """Percentiles (ms) of only the observations BETWEEN two bucket
    snapshots (cumulative-bucket deltas stay cumulative)."""
    from rafiki_tpu.observe.metrics import bucket_percentile

    deltas = sorted((le, after.get(le, 0) - before.get(le, 0))
                    for le in after)
    if not deltas or deltas[-1][1] <= 0:
        return None
    out = []
    for q in qs:
        v = bucket_percentile(deltas, q)
        out.append(round(v * 1e3, 3) if v is not None else None)
    return out


def main_serving() -> dict:
    """Config[3]: ensemble QPS through Predictor HTTP + workers."""
    import tempfile

    import requests

    from rafiki_tpu.cache import encode_payload
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.platform import LocalPlatform

    import jax

    n_chips = len(jax.devices())
    max_models = min(2, n_chips)  # ensemble size bounded by the slice

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)
        platform = LocalPlatform(workdir=tmp + "/plat", http=True)
        try:
            user = platform.admin.create_user("b@x.c", "pw",
                                              UserType.MODEL_DEVELOPER)
            model = platform.admin.create_model(
                user["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = platform.admin.create_train_job(
                user["id"], "bench", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: max_models},
                train_path, val_path)
            assert platform.admin.wait_until_train_job_done(job["id"],
                                                            timeout=1200)
            inf = platform.admin.create_inference_job(
                user["id"], job["id"], max_models=max_models)
            host = platform.admin.get_inference_job(
                inf["id"])["predictor_host"]

            val = load_image_dataset(val_path)
            batch = [encode_payload(val.images[i % val.size])
                     for i in range(64)]
            url = f"http://{host}/predict"
            # Warm-up (first request pays worker registration waits).
            requests.post(url, json={"queries": batch}, timeout=300)

            # Concurrent clients: measure server capacity, not one
            # client's request latency. Enough in-flight batches that the
            # workers' burst merging (many frames -> one chip call -> one
            # host sync) is actually exercised.
            import threading

            def window() -> float:
                counts = [0] * 16
                errors: list = []
                stop = threading.Event()

                def client(i: int) -> None:
                    session = requests.Session()
                    try:
                        while not stop.is_set():
                            r = session.post(url, json={"queries": batch},
                                             timeout=300)
                            r.raise_for_status()
                            counts[i] += len(batch)
                    except Exception as e:  # a dead client would silently
                        errors.append(e)    # deflate the measured QPS
                        stop.set()

                threads = [threading.Thread(target=client, args=(i,))
                           for i in range(len(counts))]
                t0 = time.time()
                for t in threads:
                    t.start()
                time.sleep(20.0)
                stop.set()
                for t in threads:
                    t.join()
                elapsed = time.time() - t0
                if errors:
                    raise RuntimeError(f"bench client failed: {errors[0]}")
                return sum(counts) / elapsed

            qps, fields = _adaptive_windows(window)
            platform.admin.stop_inference_job(inf["id"])
        finally:
            platform.shutdown()
    return _emit("ensemble_inference_qps", qps, "queries/s",
                 **_serving_wire_fields(), **fields)


def main_serving_openloop() -> dict:
    """Open-loop serving: ensemble QPS at saturation with request
    arrival decoupled from completion (VERDICT r1 item 5).

    The closed-loop config[3] cannot show the worker's one-burst-in-
    flight pipelining: each client waits for its own reply, so the
    ~0.2-0.7 s per-burst device->host sync on the tunneled TPU gates
    every client equally. Here ALL bursts are enqueued up front (the
    queue never starves) and the total drain time is measured — the
    overlap of burst N's readback with burst N+1's compute is directly
    visible.

    Methodology (r4 verdict item 6): ONE platform serves TWO inference
    jobs of the same trained trial — one in "auto" pipeline mode (its
    decision + measured sync latency are read back from the worker
    registration and recorded) and one FORCED to the opposite mode —
    and their windows are interleaved A/B/A/B, so the pipelined and
    unpipelined figures come from the same contention conditions and
    their ratio measures the mode, not the box's mood swings.
    """
    import tempfile

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.platform import LocalPlatform

    n_bursts, burst = 40, 64

    def start_job(admin, cache, user_id, job_id, queries):
        """Create one inference job, wait for its worker, pay its
        warm-up burst; returns (inf_id, workers, worker_info)."""
        inf = admin.create_inference_job(user_id, job_id, max_models=1)
        deadline = time.time() + 600
        workers = cache.running_workers(inf["id"])
        while not workers and time.time() < deadline:
            time.sleep(0.5)
            workers = cache.running_workers(inf["id"])
        assert workers, "no inference workers registered"
        for w in workers:
            cache.send_query_batch(w, queries, batch_id=f"warm-{inf['id']}",
                                   pre_encoded=True)
        assert cache.gather_prediction_batches(
            f"warm-{inf['id']}", len(workers), timeout=600)
        info = cache.running_worker_info(inf["id"])
        return inf["id"], workers, info[workers[0]]

    def one_window(cache, workers, queries, tag) -> float:
        t0 = time.time()
        for i in range(n_bursts):  # arrival: all up front
            for w in workers:
                cache.send_query_batch(w, queries,
                                       batch_id=f"{tag}{i}",
                                       pre_encoded=True)
        for i in range(n_bursts):
            got = cache.gather_prediction_batches(
                f"{tag}{i}", len(workers), timeout=300)
            assert len(got) == len(workers), \
                f"burst {i}: {len(got)}/{len(workers)} replies"
        return n_bursts * burst / (time.time() - t0)

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)
        os.environ.pop("RAFIKI_TPU_SERVING_PIPELINE", None)
        platform = LocalPlatform(workdir=f"{tmp}/plat")
        try:
            admin = platform.admin
            cache = Cache(platform.bus)
            user = admin.create_user("ol@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
            model = admin.create_model(
                user["id"], "ff-ol", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = admin.create_train_job(
                user["id"], "ol", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 1},
                train_path, val_path)
            assert admin.wait_until_train_job_done(job["id"],
                                                   timeout=1200)
            val = load_image_dataset(val_path)
            queries = [encode_payload(val.images[i % val.size])
                       for i in range(burst)]

            # Job A: auto mode (the production default) — its worker
            # measures the sync latency and decides; the decision is
            # read back from the registration info.
            inf_a, workers_a, info_a = start_job(admin, cache,
                                                 user["id"], job["id"],
                                                 queries)
            auto_pipeline = bool(info_a.get("pipeline"))
            # Job B: forced to the opposite mode, so the A/B ratio is
            # the pipelining effect under identical conditions.
            os.environ["RAFIKI_TPU_SERVING_PIPELINE"] = \
                "0" if auto_pipeline else "1"
            try:
                inf_b, workers_b, info_b = start_job(admin, cache,
                                                     user["id"],
                                                     job["id"], queries)
            finally:
                os.environ.pop("RAFIKI_TPU_SERVING_PIPELINE", None)

            # The forcing must have actually taken: if both workers
            # ended up in the same mode the A/B ratio would be a
            # fabricated ~1.0 with made-up on/off labels.
            forced_pipeline = bool(info_b.get("pipeline"))
            assert forced_pipeline != auto_pipeline, (
                f"forced worker did not take the opposite mode "
                f"(auto={auto_pipeline}, forced={forced_pipeline})")

            # Interleaved adaptive windows: A then B per round, until
            # both series settle (same criterion as _adaptive_windows;
            # cap 4 rounds each).
            vals_a: list = []
            vals_b: list = []
            for _ in range(4):
                vals_a.append(one_window(cache, workers_a, queries,
                                         f"a{len(vals_a)}-"))
                vals_b.append(one_window(cache, workers_b, queries,
                                         f"b{len(vals_b)}-"))
                if _settled(vals_a) and _settled(vals_b):
                    break
            admin.stop_inference_job(inf_a)
            admin.stop_inference_job(inf_b)
        finally:
            platform.shutdown()

    best_a, best_b = max(vals_a), max(vals_b)
    qps_on = best_a if auto_pipeline else best_b
    qps_off = best_b if auto_pipeline else best_a
    value = best_a  # headline = the auto (production-default) mode
    return _emit(
        "serving_openloop_qps", value, "queries/s",
        **_serving_wire_fields(),
        # n_windows/spread describe the series behind the headline (the
        # auto job), matching _adaptive_windows' semantics elsewhere;
        # the forced series is fully visible in windows_forced.
        n_windows=len(vals_a),
        spread=round((best_a - min(vals_a)) / best_a, 3),
        windows_auto=[round(v, 2) for v in vals_a],
        windows_forced=[round(v, 2) for v in vals_b],
        auto_pipeline=auto_pipeline,
        forced_pipeline=forced_pipeline,
        auto_sync_latency_ms=info_a.get("sync_latency_ms"),
        qps_pipeline_on=round(qps_on, 2),
        qps_pipeline_off=round(qps_off, 2),
        pipeline_speedup=round(qps_on / qps_off, 3))


#: --workload override for serving-concurrent (set by _main_cli):
#: None = the default uniform-traffic matrix; "zipf[:s[:keys]]" = the
#: edge-cache + tier A/B under zipf-keyed traffic.
_WORKLOAD = None

#: --quant override for serving-concurrent (set by _main_cli): "int8"
#: runs the quantized-serving A/B + the accuracy-delta gate instead of
#: the uniform matrix; _main_cli exits non-zero when the gate fails, so
#: the invocation doubles as a CI regression gate.
_QUANT = None
_QUANT_TOL = 0.02

#: --stacked override for serving-concurrent (set by _main_cli): runs
#: the stacked-ensemble A/B (vmap-stacked multi-member bin vs the same
#: bin served per-member) instead of the uniform matrix. The OFF side
#: runs FIRST and is asserted to expose ZERO stacked series.
_STACKED = False


def _serving_wire_fields() -> dict:
    """``wire_format``/``quant`` on every serving record: which wire
    and dtype mode the measured stack actually ran (r4 verdict
    discipline — a mode must be recoverable from the artifact)."""
    from rafiki_tpu.observe import wire as _ow

    return {"wire_format": _ow.packed_wire_mode(),
            "quant": _ow.quant_mode() or None}


def _serving_quant_ab(mode: str) -> dict:
    """``--quant int8`` — the quantized-ensemble serving A/B plus the
    ACCURACY-DELTA GATE (ISSUE r13).

    Gate first, stack second: one JaxFeedForward is trained directly
    and its predict-path accuracy on the SAME eval split is measured
    f32 vs int8 — ``|Δaccuracy| <= tolerance`` or the record says
    ``accuracy_gate: "fail"`` and ``_main_cli`` exits non-zero (a
    quantized mode that silently degrades accuracy must fail the
    bench, not ship a throughput number). Then one platform trains a
    1-trial job and serves it twice — job G with
    ``RAFIKI_TPU_SERVING_QUANT=int8``, job H without — interleaved
    closed-loop windows per round; the
    ``rafiki_tpu_serving_quant_total`` delta proves the quantized path
    actually served the measured queries (counter evidence per r9
    discipline; the throughput ratio on this box is noise-dominated
    and recorded with windows+spread)."""
    import tempfile

    import requests

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.models.feedforward import JaxFeedForward
    from rafiki_tpu.observe.metrics import parse_exposition
    from rafiki_tpu.platform import LocalPlatform

    n_clients, window_s = 8, 8.0
    quant_env = NodeConfig.env_name("serving_quant")

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)

        # --- Accuracy-delta gate (model-level; the serving stack adds
        # nothing to judging the quantizer itself) ---
        model = JaxFeedForward(hidden_layer_count=2,
                               hidden_layer_units=64,
                               learning_rate=3e-3, batch_size=64,
                               max_epochs=3)
        model.train(train_path)
        val = load_image_dataset(val_path)

        def accuracy() -> float:
            probs = model.predict_proba(val.images)
            return float((probs.argmax(-1) == val.labels).mean())

        acc_f32 = accuracy()
        report = model.enable_serving_quant(mode)
        acc_q = accuracy()
        model.enable_serving_quant("")
        delta = abs(acc_f32 - acc_q)
        gate = "pass" if delta <= _QUANT_TOL else "fail"

        # --- Serving A/B: same stack, quant on (G) vs off (H) ---
        os.environ.pop(quant_env, None)
        share_env = "RAFIKI_TPU_MAX_CHIP_SHARE"
        prior_share = os.environ.get(share_env)
        os.environ.setdefault(share_env, "8")
        platform = LocalPlatform(workdir=f"{tmp}/plat")
        try:
            admin = platform.admin
            cache = Cache(platform.bus)
            user = admin.create_user("cc@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
            mrow = admin.create_model(
                user["id"], "ff-cc", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = admin.create_train_job(
                user["id"], "cc", TaskType.IMAGE_CLASSIFICATION,
                [mrow["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 1},
                train_path, val_path)
            assert admin.wait_until_train_job_done(job["id"],
                                                   timeout=1200)
            val_ds = load_image_dataset(val_path)
            batch = [encode_payload(val_ds.images[i % val_ds.size])
                     for i in range(4)]

            def start_job(want_quant):
                inf = admin.create_inference_job(user["id"], job["id"],
                                                 max_models=1)
                deadline = time.time() + 600
                while not cache.running_workers(inf["id"]) \
                        and time.time() < deadline:
                    time.sleep(0.5)
                info = cache.running_worker_info(inf["id"])
                assert info, "no workers registered"
                served_quant = {i.get("quant") for i in info.values()}
                assert served_quant == ({mode} if want_quant
                                        else {None}), served_quant
                host = admin.get_inference_job(inf["id"])[
                    "predictor_host"]
                r = requests.post(f"http://{host}/predict",
                                  json={"queries": batch}, timeout=300)
                r.raise_for_status()
                return inf["id"], host

            os.environ[quant_env] = mode
            try:
                inf_g, host_g = start_job(True)
            finally:
                os.environ.pop(quant_env, None)
            inf_h, host_h = start_job(False)

            def one_window(url):
                return _closed_loop_window(
                    url, {"queries": batch}, n_clients, window_s,
                    count_by=len(batch))

            def quant_served(host):
                m = parse_exposition(requests.get(
                    f"http://{host}/metrics", timeout=30).text)
                return sum(v for labels, v in m.get(
                    "rafiki_tpu_serving_quant_total", [])
                    if labels.get("mode") == mode)

            url_g = f"http://{host_g}/predict"
            url_h = f"http://{host_h}/predict"
            one_window(url_g)  # warm (untimed): XLA quant variants
            one_window(url_h)
            served0 = quant_served(host_g)
            vals_g: list = []
            vals_h: list = []
            for _ in range(3):
                vals_g.append(one_window(url_g))
                vals_h.append(one_window(url_h))
                if _settled(vals_g) and _settled(vals_h):
                    break
            served = quant_served(host_g) - served0
            assert served > 0, "quant counter did not move"
            for inf in (inf_g, inf_h):
                admin.stop_inference_job(inf)
        finally:
            platform.shutdown()
            if prior_share is None:
                os.environ.pop(share_env, None)
            else:
                os.environ[share_env] = prior_share

    best_g, best_h = max(vals_g), max(vals_h)
    return _emit(
        "serving_concurrent_qps", best_g, "queries/s",
        **{**_serving_wire_fields(), "quant": mode},
        n_clients=n_clients,
        n_windows=len(vals_g),
        spread=round((best_g - min(vals_g)) / best_g, 3),
        spread_off=round((best_h - min(vals_h)) / best_h, 3),
        windows_quant_on=[round(v, 2) for v in vals_g],
        windows_quant_off=[round(v, 2) for v in vals_h],
        qps_quant_on=round(best_g, 2),
        qps_quant_off=round(best_h, 2),
        quant_speedup=round(best_g / best_h, 3),
        quant_queries_served=int(served),
        quant_layers_int8=report.get("n_int8"),
        quant_layers_f32=report.get("n_f32"),
        accuracy_f32=round(acc_f32, 4),
        accuracy_int8=round(acc_q, 4),
        accuracy_delta=round(delta, 4),
        accuracy_tolerance=_QUANT_TOL,
        accuracy_gate=gate)


def _serving_stacked_ab() -> dict:
    """``--stacked`` — the compiled-megabatch ensemble A/B (ISSUE
    r16): ONE worker owning the node's whole chip slice serves a
    2-member same-family bin, stacked (one vmapped dispatch per
    burst) vs per-member (one dispatch per member per burst).

    Order matters for the disabled-plane evidence: the OFF side
    deploys and serves FIRST and its /metrics are asserted to carry
    ZERO stacked series (the registry is process-global, so this is
    only judgeable before the ON side exists). The judged evidence is
    counter deltas per the r9 discipline: ``stacked_dispatch_total``
    strictly up over a counted request phase, dispatches/query =
    delta/queries, and the per-member equivalent is ``members ×`` that
    by construction (the same burst stream costs one dispatch per
    member per-member — the unit gate in tests/test_stacked.py counts
    the real calls); the qps ratio is recorded with per-side
    windows+spread (multichip channel judges throughput)."""
    import tempfile

    import requests

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe.metrics import parse_exposition
    from rafiki_tpu.platform import LocalPlatform

    n_clients, window_s, per_request = 8, 8.0, 16
    counted_requests = 40  # the dispatch-accounting phase (side S)
    stacked_env = NodeConfig.env_name("serving_stacked")

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)
        prior_stacked = os.environ.get(stacked_env)
        os.environ[stacked_env] = "off"  # OFF side deploys first
        platform = LocalPlatform(workdir=f"{tmp}/plat")
        try:
            import jax

            n_devices = len(jax.devices())
            admin = platform.admin
            cache = Cache(platform.bus)
            user = admin.create_user("cc@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
            mrow = admin.create_model(
                user["id"], "ff-cc", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = admin.create_train_job(
                user["id"], "cc", TaskType.IMAGE_CLASSIFICATION,
                [mrow["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
                train_path, val_path)
            assert admin.wait_until_train_job_done(job["id"],
                                                   timeout=1200)
            val_ds = load_image_dataset(val_path)
            batch = [encode_payload(val_ds.images[i % val_ds.size])
                     for i in range(per_request)]
            whole_slice = platform.services.allocator.n_chips

            def start_job(want_stacked):
                # chips_per_worker = the WHOLE slice: only one group
                # fits, so both trials pack onto ONE worker whose
                # mesh spans every device — the compiled-megabatch
                # deploy shape (the second job's group time-slices
                # the same slice; windows interleave per round, and
                # the judged evidence is counter deltas anyway).
                inf = admin.create_inference_job(
                    user["id"], job["id"], max_models=2,
                    chips_per_worker=max(1, whole_slice))
                deadline = time.time() + 600
                while not cache.running_workers(inf["id"]) \
                        and time.time() < deadline:
                    time.sleep(0.5)
                info = cache.running_worker_info(inf["id"])
                assert len(info) == 1, \
                    f"expected ONE packed worker, got {len(info)}"
                (reg,) = info.values()
                members = str(reg["trial_id"]).split(",")
                assert len(members) == 2, members
                assert bool(reg.get("stacked")) is want_stacked, reg
                host = admin.get_inference_job(inf["id"])[
                    "predictor_host"]
                r = requests.post(f"http://{host}/predict",
                                  json={"queries": batch}, timeout=300)
                r.raise_for_status()
                return inf["id"], host, len(members)

            def stacked_series(host):
                m = parse_exposition(requests.get(
                    f"http://{host}/metrics", timeout=30).text)
                return {k: m[k] for k in (
                    "rafiki_tpu_serving_stacked_dispatch_total",
                    "rafiki_tpu_serving_dispatches_per_query_ratio")
                    if m.get(k)}

            def dispatch_total(host, mode):
                m = parse_exposition(requests.get(
                    f"http://{host}/metrics", timeout=30).text)
                return sum(v for labels, v in m.get(
                    "rafiki_tpu_serving_stacked_dispatch_total", [])
                    if labels.get("mode") == mode)

            inf_p, host_p, _ = start_job(False)
            # The disabled-plane gate, judged while the ON side does
            # not exist yet: a full serve registered NOTHING stacked.
            off_series = stacked_series(host_p)
            assert not off_series, off_series

            os.environ[stacked_env] = "on"
            try:
                inf_s, host_s, members = start_job(True)
            finally:
                os.environ[stacked_env] = "off"

            # Counted phase: a known query volume against the stacked
            # side pins dispatches/query from counter deltas.
            d0 = dispatch_total(host_s, "stacked")
            for _ in range(counted_requests):
                r = requests.post(f"http://{host_s}/predict",
                                  json={"queries": batch}, timeout=300)
                r.raise_for_status()
            d_stacked = dispatch_total(host_s, "stacked") - d0
            n_queries = counted_requests * per_request
            # The MEASURED gates: the counter moved, and the stacked
            # side paid at most ONE ensemble dispatch per request
            # (i.e. per burst) — a regression to per-member dispatch
            # under the stacked counter would show ~members x here.
            assert d_stacked > 0, "stacked dispatch counter flat"
            assert d_stacked <= counted_requests, \
                (d_stacked, counted_requests)
            dpq_stacked = d_stacked / n_queries
            # The per-member figure is DERIVED (members x stacked):
            # the off side exposes zero stacked series by design, so
            # its dispatches are uncounted here — the measured
            # members-vs-one comparison lives in tests/test_stacked.py
            # (real dispatch-call counting on the same burst).
            dpq_permember = members * dpq_stacked

            def one_window(url):
                return _closed_loop_window(
                    url, {"queries": batch}, n_clients, window_s,
                    count_by=len(batch))

            url_s = f"http://{host_s}/predict"
            url_p = f"http://{host_p}/predict"
            one_window(url_s)  # warm (untimed)
            one_window(url_p)
            vals_s: list = []
            vals_p: list = []
            for _ in range(3):
                vals_s.append(one_window(url_s))
                vals_p.append(one_window(url_p))
                if _settled(vals_s) and _settled(vals_p):
                    break
            fallback = dispatch_total(host_s, "fallback")
            for inf in (inf_s, inf_p):
                admin.stop_inference_job(inf)
        finally:
            platform.shutdown()
            if prior_stacked is None:
                os.environ.pop(stacked_env, None)
            else:
                os.environ[stacked_env] = prior_stacked

    best_s, best_p = max(vals_s), max(vals_p)
    return _emit(
        "serving_concurrent_qps", best_s, "queries/s",
        **_serving_wire_fields(),
        stacked=True,
        n_devices=n_devices,
        n_members=members,
        n_clients=n_clients,
        n_windows=len(vals_s),
        spread=round((best_s - min(vals_s)) / best_s, 3),
        spread_off=round((best_p - min(vals_p)) / best_p, 3),
        windows_stacked_on=[round(v, 2) for v in vals_s],
        windows_stacked_off=[round(v, 2) for v in vals_p],
        qps_stacked_on=round(best_s, 2),
        qps_stacked_off=round(best_p, 2),
        stacked_speedup=round(best_s / best_p, 3),
        stacked_dispatches=int(d_stacked),
        stacked_fallback_dispatches=int(fallback),
        counted_queries=int(n_queries),
        dispatches_per_query_stacked=round(dpq_stacked, 5),
        dispatches_per_query_permember_derived=round(dpq_permember, 5),
        off_new_series=0)


def _serving_zipf_ab(workload: str) -> dict:
    """``--workload zipf:<s>:<keys>`` — the edge cache + tiered serving
    A/B (ISSUE r12): cache+tier ON vs OFF, same stack otherwise, under
    zipf-keyed single-query traffic (the regime the cache exists for:
    most requests repeat a small hot key set).

    ONE platform trains a 2-trial job and serves it twice at
    ``max_models=2`` (two bins, so the tier path is real): job E with
    ``RAFIKI_TPU_SERVING_CACHE_BYTES=64MB`` +
    ``RAFIKI_TPU_SERVING_TIER_THRESHOLD``, job F with both popped (the
    disabled path every other config also runs). 8 closed-loop clients
    send single-query requests whose key rank is drawn zipf(s) over
    ``keys`` distinct query frames; E/F windows interleave per round so
    box noise lands on both. Sides record their own windows + spread;
    p50 comes from each predictor's OWN http histogram as bucket
    deltas around the measured phase. The OFF side's /metrics is also
    asserted to carry ZERO cache/tier series (the disabled-mode
    discipline, recorded as ``off_new_series``)."""
    import tempfile
    import threading

    import numpy as np
    import requests

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe.metrics import parse_exposition
    from rafiki_tpu.platform import LocalPlatform

    parts = workload.split(":")
    zipf_s = float(parts[1]) if len(parts) > 1 and parts[1] else 1.1
    n_keys = int(parts[2]) if len(parts) > 2 and parts[2] else 64
    n_clients, window_s, rounds = 8, 10.0, 4
    cache_env = NodeConfig.env_name("serving_cache_bytes")
    ttl_env = NodeConfig.env_name("serving_cache_ttl_s")
    tier_env = NodeConfig.env_name("serving_tier_threshold")

    def start_job(admin, cache, user_id, job_id, warm_batch, want=2):
        inf = admin.create_inference_job(user_id, job_id, max_models=2)
        deadline = time.time() + 600
        while len(cache.running_workers(inf["id"])) < want \
                and time.time() < deadline:
            time.sleep(0.5)
        n_workers = len(cache.running_workers(inf["id"]))
        assert n_workers >= want, f"{n_workers}/{want} bins registered"
        host = admin.get_inference_job(inf["id"])["predictor_host"]
        r = requests.post(f"http://{host}/predict",
                          json={"queries": warm_batch}, timeout=300)
        r.raise_for_status()
        return inf["id"], host

    http_buckets = _http_predict_buckets
    delta_percentiles_ms = _bucket_delta_percentiles_ms

    def zipf_window(url, frames, probs, seed, duration=None):
        counts = [0] * n_clients
        errors: list = []
        stop = threading.Event()

        def client(i: int) -> None:
            rng = np.random.default_rng(seed * 1000 + i)
            session = requests.Session()
            try:
                while not stop.is_set():
                    k = int(rng.choice(len(frames), p=probs))
                    r = session.post(url, json={"query": frames[k]},
                                     timeout=300)
                    r.raise_for_status()
                    counts[i] += 1
            except Exception as e:  # surfaced by the caller
                errors.append(e)
                stop.set()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        time.sleep(duration if duration is not None else window_s)
        stop.set()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(f"bench client failed: {errors[0]}")
        return sum(counts) / (time.monotonic() - t0)

    def service_samples(host, name):
        metrics = parse_exposition(
            requests.get(f"http://{host}/metrics", timeout=30).text)
        return metrics.get(name, [])

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)
        for env in (cache_env, ttl_env, tier_env):
            os.environ.pop(env, None)
        # Two A/B jobs x two bins on a small box: lift the time-sliced
        # tenancy cap so both stacks fit (same move as the uniform
        # matrix; restored afterwards).
        share_env = "RAFIKI_TPU_MAX_CHIP_SHARE"
        prior_share = os.environ.get(share_env)
        os.environ.setdefault(share_env, "8")
        platform = LocalPlatform(workdir=f"{tmp}/plat")
        try:
            admin = platform.admin
            cache = Cache(platform.bus)
            user = admin.create_user("cc@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
            model = admin.create_model(
                user["id"], "ff-cc", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = admin.create_train_job(
                user["id"], "cc", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
                train_path, val_path)
            assert admin.wait_until_train_job_done(job["id"],
                                                   timeout=1200)
            val = load_image_dataset(val_path)
            frames = [encode_payload(val.images[i % val.size])
                      for i in range(n_keys)]
            ranks = np.arange(1, n_keys + 1, dtype=np.float64)
            probs = ranks ** -zipf_s
            probs /= probs.sum()
            warm = frames[:8]

            # Job E: cache + tier ON. TTL far beyond the run so only
            # promotion/eviction could drop entries mid-measurement.
            os.environ[cache_env] = str(64 << 20)
            os.environ[ttl_env] = "600"
            os.environ[tier_env] = "0.05"
            try:
                inf_e, host_e = start_job(admin, cache, user["id"],
                                          job["id"], warm)
            finally:
                for env in (cache_env, ttl_env, tier_env):
                    os.environ.pop(env, None)
            # Job F: both OFF — the disabled path, same stack.
            inf_f, host_f = start_job(admin, cache, user["id"],
                                      job["id"], warm)

            stats_e = requests.get(f"http://{host_e}/stats",
                                   timeout=30).json()
            stats_f = requests.get(f"http://{host_f}/stats",
                                   timeout=30).json()
            assert stats_e.get("cache"), stats_e
            assert stats_e.get("tier_threshold"), stats_e
            assert stats_f.get("cache") is None, stats_f
            assert not stats_f.get("tier_threshold"), stats_f

            url_e = f"http://{host_e}/predict"
            url_f = f"http://{host_f}/predict"
            # Warm (untimed): XLA batch buckets + second-touch
            # admission (a key must miss twice before it caches).
            zipf_window(url_e, frames, probs, seed=99, duration=4.0)
            zipf_window(url_f, frames, probs, seed=99, duration=4.0)
            before_e = http_buckets(host_e, stats_e["http_service"])
            before_f = http_buckets(host_f, stats_f["http_service"])
            # Cache events are snapshot-delta'd around the measured
            # phase exactly like the latency buckets: the warm windows
            # exist to PAY the second-touch admission misses, and
            # counting them would understate the measured hit rate.
            ev_before = dict((requests.get(f"http://{host_e}/stats",
                                           timeout=30).json()["cache"]
                              or {}).get("events", {}))
            vals_e: list = []
            vals_f: list = []
            for r in range(rounds):
                vals_e.append(zipf_window(url_e, frames, probs, seed=r))
                vals_f.append(zipf_window(url_f, frames, probs, seed=r))
                if _settled(vals_e) and _settled(vals_f):
                    break
            p50_e = delta_percentiles_ms(
                before_e, http_buckets(host_e, stats_e["http_service"]))
            p50_f = delta_percentiles_ms(
                before_f, http_buckets(host_f, stats_f["http_service"]))
            stats_e = requests.get(f"http://{host_e}/stats",
                                   timeout=30).json()
            ev_after = (stats_e.get("cache") or {}).get("events", {})
            events = {k: v - ev_before.get(k, 0)
                      for k, v in ev_after.items()
                      if v - ev_before.get(k, 0)}
            hits = events.get("hit", 0)
            misses = events.get("miss", 0)
            tier_mix = {
                labels["outcome"]: int(v)
                for labels, v in service_samples(
                    host_e, "rafiki_tpu_serving_tier_total")
                if labels.get("service") == stats_e.get("service")}
            avoided = {
                labels["source"]: round(v, 3)
                for labels, v in service_samples(
                    host_e,
                    "rafiki_tpu_serving_chip_seconds_avoided_total")
                if labels.get("service") == stats_e.get("service")}
            # Disabled mode must register ZERO cache/tier series on F.
            off_series = [
                (name, labels)
                for name in ("rafiki_tpu_serving_cache_total",
                             "rafiki_tpu_serving_cache_bytes",
                             "rafiki_tpu_serving_tier_total",
                             "rafiki_tpu_serving_chip_seconds_"
                             "avoided_total")
                for labels, _ in service_samples(host_f, name)
                if labels.get("service") == stats_f.get("service")]
            assert not off_series, off_series
            for inf in (inf_e, inf_f):
                admin.stop_inference_job(inf)
        finally:
            platform.shutdown()
            if prior_share is None:
                os.environ.pop(share_env, None)
            else:
                os.environ[share_env] = prior_share

    best_e, best_f = max(vals_e), max(vals_f)
    return _emit(
        "serving_concurrent_qps", best_e, "queries/s",
        **_serving_wire_fields(),
        workload=f"zipf:{zipf_s}:{n_keys}",
        n_clients=n_clients,
        n_windows=len(vals_e),
        spread=round((best_e - min(vals_e)) / best_e, 3),
        spread_off=round((best_f - min(vals_f)) / best_f, 3),
        windows_cache_tier_on=[round(v, 2) for v in vals_e],
        windows_cache_tier_off=[round(v, 2) for v in vals_f],
        qps_cache_tier_on=round(best_e, 2),
        qps_cache_tier_off=round(best_f, 2),
        cache_tier_speedup=round(best_e / best_f, 3),
        latency_ms_p50_p95_p99_on=p50_e,
        latency_ms_p50_p95_p99_off=p50_f,
        cache_hit_rate=round(hits / (hits + misses), 3)
        if (hits + misses) else None,
        cache_events=events,
        coalesce_count=events.get("coalesce", 0),
        tier_outcomes=tier_mix,
        chip_seconds_avoided=avoided,
        off_new_series=0)


def main_serving_concurrent() -> dict:
    """Closed-loop concurrent serving: N clients against the predictor
    HTTP frontend — micro-batcher ON vs OFF (ISSUE r6) and replica
    sharding ON vs OFF (ISSUE r8); with ``--workload zipf:<s>:<keys>``
    the edge-cache + tier A/B instead (``_serving_zipf_ab``).

    The closed-loop config[3] (``serving``) hammers with 16 clients of
    64-query batches — big enough that per-request scatter overhead
    amortizes. Real app traffic is many SMALL requests, where the r5
    frontend paid one worker scan + bus scatter + blocking gather per
    request; this config measures exactly that regime (8 clients x
    4-query requests) and the fixes. ONE platform serves FOUR inference
    jobs of the same trained trial:

    - A: micro-batcher + replica sharding (production default), with a
      second same-bin replica attached (``attach_inference_workers``)
      so each super-batch is sliced across both;
    - C: micro-batcher, sharding OFF, the SAME two replicas — one
      rotating replica eats each whole super-batch (the r6 path), so
      the A/C ratio isolates data-parallel sharding;
    - B: micro-batcher off (the r5 one-scatter-per-request baseline),
      also holding two replicas so the A/B ratio compares frontends at
      equal worker capacity;
    - D: micro-batcher with the fill window PINNED to the old fixed
      5 ms; a low-offered-load trickle against A (adaptive) vs D
      (fixed) compares added p99 — the adaptive window's reason to
      exist.

    The micro-batch ratio (A/B) runs the small-request regime the
    batcher exists for. The SHARDING ratio (A/C) runs its own windows
    of BIG requests (``shard_request`` queries each): slicing a
    super-batch only pays when the slice carries real compute, and
    small-batch windows would measure per-shard overhead against
    scheduler noise. Heavy windows are interleaved A/B/A-big/C-big per
    round so each ratio measures its mechanism, not the box's mood.
    The trickle percentiles are BUCKET DELTAS of the predictors' own
    ``rafiki_tpu_http_request_seconds`` histograms (snapshot before and
    after the trickle), so the heavy phase's tail cannot pollute them.
    """
    import tempfile
    import threading

    import requests

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe.metrics import (histogram_percentiles_ms,
                                            parse_exposition)
    from rafiki_tpu.platform import LocalPlatform

    if _QUANT:
        return _serving_quant_ab(_QUANT)
    if _STACKED:
        return _serving_stacked_ab()
    if _WORKLOAD and _WORKLOAD.startswith("zipf"):
        return _serving_zipf_ab(_WORKLOAD)

    n_clients, per_request = 8, 4
    shard_request = 32  # queries/request in the sharding A/B windows
    window_s = 12.0
    trickle_n, trickle_gap_s = 150, 0.02
    mb_env = NodeConfig.env_name("serving_microbatch")
    shard_env = NodeConfig.env_name("serving_shard_replicas")
    fwmin_env = NodeConfig.env_name("serving_fill_window_min")

    def start_job(admin, cache, user_id, job_id, warm_batch,
                  replicas=0):
        inf = admin.create_inference_job(user_id, job_id, max_models=1)
        deadline = time.time() + 600
        while not cache.running_workers(inf["id"]) \
                and time.time() < deadline:
            time.sleep(0.5)
        assert cache.running_workers(inf["id"]), "no workers registered"
        for _ in range(replicas):
            attached = admin.attach_inference_workers(inf["id"])
            assert attached, "replica attach failed (chips exhausted?)"
        want = 1 + replicas
        while len(cache.running_workers(inf["id"])) < want \
                and time.time() < deadline:
            time.sleep(0.5)
        n_workers = len(cache.running_workers(inf["id"]))
        assert n_workers >= want, \
            f"{n_workers}/{want} replicas registered"
        host = admin.get_inference_job(inf["id"])["predictor_host"]
        url = f"http://{host}/predict"
        r = requests.post(url, json={"queries": warm_batch}, timeout=300)
        r.raise_for_status()
        return inf["id"], host

    def http_buckets(host, stats):
        return _http_predict_buckets(host, stats.get("http_service"))

    delta_percentiles_ms = _bucket_delta_percentiles_ms

    def trickle_round(url, queries, k):
        """Low offered load: sequential single-REAL-query requests
        (same encoded image frames as the heavy phase — a scalar would
        measure the worker's error path, not serving), gaps far beyond
        the adaptive ceiling — the regime where a fixed fill window is
        pure added latency. Rounds are interleaved across the compared
        jobs by the caller so a slow phase of the box lands on both."""
        for i in range(k):
            r = requests.post(url,
                              json={"query": queries[i % len(queries)]},
                              timeout=60)
            r.raise_for_status()
            assert "error" not in str(r.json().get("prediction"))[:40]
            time.sleep(trickle_gap_s)

    def one_window(url, batch, duration=None):
        counts = [0] * n_clients
        errors: list = []
        stop = threading.Event()

        def client(i: int) -> None:
            session = requests.Session()
            try:
                while not stop.is_set():
                    r = session.post(url, json={"queries": batch},
                                     timeout=300)
                    r.raise_for_status()
                    counts[i] += len(batch)
            except Exception as e:
                errors.append(e)
                stop.set()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        time.sleep(duration if duration is not None else window_s)
        stop.set()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        if errors:
            raise RuntimeError(f"bench client failed: {errors[0]}")
        return sum(counts) / elapsed

    def server_latency(host, stats):
        """End-to-end /predict percentiles from the predictor's own
        /metrics histogram — the number production scrapes read."""
        metrics = parse_exposition(
            requests.get(f"http://{host}/metrics", timeout=30).text)
        return histogram_percentiles_ms(
            metrics.get("rafiki_tpu_http_request_seconds_bucket", []),
            service=stats.get("http_service", ""), route="/predict")

    def stage_latency(host, stats):
        """Per-stage (fill/scatter/gather) percentiles from the
        unified registry's stage histogram."""
        metrics = parse_exposition(
            requests.get(f"http://{host}/metrics", timeout=30).text)
        buckets = metrics.get("rafiki_tpu_serving_stage_seconds_bucket",
                              [])
        return {stage: histogram_percentiles_ms(
                    buckets, service=stats.get("service", ""),
                    stage=stage)
                for stage in ("fill", "scatter", "gather")}

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)
        for env in (mb_env, shard_env, fwmin_env):
            os.environ.pop(env, None)
        import jax

        n_devices = len(jax.devices())
        # Four A/B jobs (+ replicas) of one tiny model may co-own one
        # chip on small boxes; lift the time-sliced tenancy cap so the
        # comparison matrix fits. Restored afterwards — a sweep's later
        # configs (multitenant) must measure the production default.
        share_env = "RAFIKI_TPU_MAX_CHIP_SHARE"
        prior_share = os.environ.get(share_env)
        os.environ.setdefault(share_env, "8")
        platform = LocalPlatform(workdir=f"{tmp}/plat")
        try:
            admin = platform.admin
            cache = Cache(platform.bus)
            user = admin.create_user("cc@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
            model = admin.create_model(
                user["id"], "ff-cc", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = admin.create_train_job(
                user["id"], "cc", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 1},
                train_path, val_path)
            assert admin.wait_until_train_job_done(job["id"],
                                                   timeout=1200)
            val = load_image_dataset(val_path)
            batch = [encode_payload(val.images[i % val.size])
                     for i in range(per_request)]
            batch_big = [encode_payload(val.images[i % val.size])
                         for i in range(shard_request)]

            # Job A: micro-batcher + sharding (production default),
            # 2 same-bin replicas.
            inf_a, host_a = start_job(admin, cache, user["id"],
                                      job["id"], batch, replicas=1)
            # Job C: same 2 replicas, sharding OFF — one rotating
            # replica eats each whole super-batch.
            os.environ[shard_env] = "0"
            try:
                inf_c, host_c = start_job(admin, cache, user["id"],
                                          job["id"], batch, replicas=1)
            finally:
                os.environ.pop(shard_env, None)
            # Job B: the r5 one-scatter-per-request path — with the
            # SAME 2 replicas as A/C (its direct path round-robins
            # across them), so microbatch_speedup compares frontends at
            # equal worker capacity instead of crediting A's second
            # replica to the batcher.
            os.environ[mb_env] = "0"
            try:
                inf_b, host_b = start_job(admin, cache, user["id"],
                                          job["id"], batch, replicas=1)
            finally:
                os.environ.pop(mb_env, None)
            # Job D: fill window PINNED at the old fixed 5 ms (the
            # adaptive window's trickle comparator; single worker).
            os.environ[fwmin_env] = "0.005"
            try:
                inf_d, host_d = start_job(admin, cache, user["id"],
                                          job["id"], batch)
            finally:
                os.environ.pop(fwmin_env, None)
            # The forcings must have taken, or the ratios are fiction.
            stats_b = requests.get(f"http://{host_b}/stats",
                                   timeout=30).json()
            assert stats_b.get("microbatch") is False, stats_b
            stats_c = requests.get(f"http://{host_c}/stats",
                                   timeout=30).json()
            assert stats_c.get("shard_replicas") is False, stats_c
            stats_a = requests.get(f"http://{host_a}/stats",
                                   timeout=30).json()
            assert stats_a.get("shard_replicas") is True, stats_a
            stats_d = requests.get(f"http://{host_d}/stats",
                                   timeout=30).json()
            assert stats_d["knobs"]["fill_window_min"] == 0.005, stats_d

            url_a, url_b, url_c, url_d = (
                f"http://{host_a}/predict", f"http://{host_b}/predict",
                f"http://{host_c}/predict", f"http://{host_d}/predict")
            # Warm windows (untimed): the workers AOT-compile per
            # power-of-two batch bucket, and only the coalesced load
            # decides which buckets the timed windows will hit — run
            # the real concurrency pattern once per mode so no XLA
            # compile lands inside a measurement.
            one_window(url_a, batch, duration=5.0)
            one_window(url_b, batch, duration=5.0)
            one_window(url_a, batch_big, duration=5.0)
            one_window(url_c, batch_big, duration=5.0)
            vals_a: list = []
            vals_b: list = []
            vals_a_big: list = []
            vals_c_big: list = []
            for _ in range(4):
                vals_a.append(one_window(url_a, batch))
                vals_b.append(one_window(url_b, batch))
                vals_a_big.append(one_window(url_a, batch_big))
                vals_c_big.append(one_window(url_c, batch_big))
                if _settled(vals_a) and _settled(vals_b) \
                        and _settled(vals_a_big) \
                        and _settled(vals_c_big):
                    break
            # Low-offered-load trickle: adaptive (A) vs pinned 5 ms
            # (D), p99 from bucket DELTAS so the heavy phase can't
            # pollute the tail; rounds interleaved A/D/A/D... so box
            # noise (GC, scheduler) lands on both jobs alike.
            stats_a = requests.get(f"http://{host_a}/stats",
                                   timeout=30).json()
            before_a = http_buckets(host_a, stats_a)
            before_d = http_buckets(host_d, stats_d)
            rounds = 3
            for _ in range(rounds):
                trickle_round(url_a, batch, trickle_n // rounds)
                trickle_round(url_d, batch, trickle_n // rounds)
            trickle_a = delta_percentiles_ms(
                before_a, http_buckets(host_a, stats_a))
            trickle_d = delta_percentiles_ms(
                before_d, http_buckets(host_d, stats_d))
            stats_a = requests.get(f"http://{host_a}/stats",
                                   timeout=30).json()
            stats_c = requests.get(f"http://{host_c}/stats",
                                   timeout=30).json()
            stats_b = requests.get(f"http://{host_b}/stats",
                                   timeout=30).json()
            # Server-side histograms (the unified registry), not
            # client-side re-derivation: bench and production read the
            # same numbers.
            lat_a = server_latency(host_a, stats_a)
            lat_b = server_latency(host_b, stats_b)
            stages_a = stage_latency(host_a, stats_a)
            for inf in (inf_a, inf_b, inf_c, inf_d):
                admin.stop_inference_job(inf)

            # --- Packed-wire A/B (r13): fresh single-replica jobs
            # AFTER the matrix released its chips. Side P = the packed
            # default; side Q deployed under "compat" (legacy per-query
            # frames, wire accounting kept) for BOTH its predictor and
            # worker — the measured legacy side. The judged evidence on
            # this box is the COUNTER deltas (wire bytes + host
            # copies), attributed per serial window; the qps ratio is
            # noise-dominated here and rides along with windows+spread.
            from rafiki_tpu.cache import WIRE_NDBATCH

            packed_env = NodeConfig.env_name("serving_packed_wire")
            prior_packed = os.environ.get(packed_env)
            inf_p, host_p = start_job(admin, cache, user["id"],
                                      job["id"], batch)
            os.environ[packed_env] = "compat"
            try:
                inf_q, host_q = start_job(admin, cache, user["id"],
                                          job["id"], batch)
            finally:
                if prior_packed is None:
                    os.environ.pop(packed_env, None)
                else:
                    os.environ[packed_env] = prior_packed
            # The negotiation must have taken, or the A/B is fiction.
            info_p = cache.running_worker_info(inf_p)
            info_q = cache.running_worker_info(inf_q)
            assert all(WIRE_NDBATCH in (i.get("wire") or ())
                       for i in info_p.values()), info_p
            assert all(not (i.get("wire") or [])
                       for i in info_q.values()), info_q

            def wire_counters():
                m = parse_exposition(requests.get(
                    f"http://{host_p}/metrics", timeout=30).text)
                b = {(la.get("format"), la.get("direction")): v
                     for la, v in m.get(
                         "rafiki_tpu_serving_wire_bytes_total", [])}
                c = {la.get("site"): v for la, v in m.get(
                    "rafiki_tpu_serving_host_copies_total", [])}
                return b, c

            def packed_window(url, host):
                """One measured window with counter deltas attributed
                to it (windows are serial, so the global wire counters
                move only for the side being driven)."""
                b0, c0 = wire_counters()
                q0 = requests.get(f"http://{host}/stats",
                                  timeout=30).json()["queries"]
                qps = one_window(url, batch)
                b1, c1 = wire_counters()
                q1 = requests.get(f"http://{host}/stats",
                                  timeout=30).json()["queries"]
                db = {k: b1.get(k, 0) - b0.get(k, 0) for k in b1}
                dc = {k: c1.get(k, 0) - c0.get(k, 0) for k in c1}
                return qps, db, dc, q1 - q0

            url_p = f"http://{host_p}/predict"
            url_q = f"http://{host_q}/predict"
            one_window(url_p, batch, duration=4.0)  # warm (untimed)
            one_window(url_q, batch, duration=4.0)
            vals_p: list = []
            vals_q: list = []
            agg = {"p": [{}, {}, 0], "q": [{}, {}, 0]}

            def fold(side, db, dc, nq):
                for k, v in db.items():
                    agg[side][0][k] = agg[side][0].get(k, 0) + v
                for k, v in dc.items():
                    agg[side][1][k] = agg[side][1].get(k, 0) + v
                agg[side][2] += nq

            for _ in range(3):
                qps, db, dc, nq = packed_window(url_p, host_p)
                vals_p.append(qps)
                fold("p", db, dc, nq)
                qps, db, dc, nq = packed_window(url_q, host_q)
                vals_q.append(qps)
                fold("q", db, dc, nq)
                if _settled(vals_p) and _settled(vals_q):
                    break

            def side_fields(side):
                db, dc, nq = agg[side]
                scatter = {f: v for (f, d), v in db.items()
                           if d == "scatter"}
                return {
                    "queries": int(nq),
                    "wire_bytes_scatter": {f: int(v) for f, v
                                           in scatter.items() if v},
                    "wire_bytes_per_query": round(
                        sum(scatter.values()) / nq, 1) if nq else None,
                    "host_copies": {k: int(v) for k, v in dc.items()
                                    if v},
                }

            side_p, side_q = side_fields("p"), side_fields("q")
            # The acceptance contract, asserted so the config doubles
            # as a regression gate: the packed side does NO stack/pad
            # copies and ships strictly fewer scatter bytes/query. The
            # byte margin scales with 1/tensor-size — ~3-4% on these
            # 784-byte images (framing overhead amortized), 25%+ on
            # small feature vectors (pinned by the codec unit gate in
            # tests/test_wire_codec.py) — so the bench gate is
            # monotone and the measured ratio rides the record.
            assert side_p["host_copies"].get("stack", 0) == 0, side_p
            assert side_p["host_copies"].get("pad", 0) == 0, side_p
            assert side_q["host_copies"].get("stack", 0) > 0, side_q
            assert side_p["wire_bytes_scatter"].get("packed", 0) > 0, \
                side_p
            assert side_p["wire_bytes_per_query"] < \
                side_q["wire_bytes_per_query"], (side_p, side_q)
            # --- Trace-plane overhead (r17): tail-sampling ON vs OFF
            # on the packed job, judged the r9 way — counter deltas
            # (spans actually written, tail verdicts) are the stable
            # evidence; the latency deltas ride along for the overhead
            # question. The OFF side runs first under the process
            # default (eager span writes); the ON side arms
            # TRACE_TAIL_SAMPLE so only error/slow/sampled traces
            # reach the store.
            stats_p = requests.get(f"http://{host_p}/stats",
                                   timeout=30).json()
            tail_env = NodeConfig.env_name("trace_tail_sample")
            prior_tail = os.environ.get(tail_env)

            def spans_total():
                m = parse_exposition(requests.get(
                    f"http://{host_p}/metrics", timeout=30).text)
                total = sum(v for _, v in m.get(
                    "rafiki_tpu_trace_spans_total", []))
                verdicts = {la.get("verdict"): int(v) for la, v in
                            m.get("rafiki_tpu_trace_tail_total", [])}
                return total, verdicts

            def trace_window():
                s0, v0 = spans_total()
                b0 = _http_predict_buckets(host_p,
                                           stats_p.get("http_service"))
                q0 = requests.get(f"http://{host_p}/stats",
                                  timeout=30).json()["queries"]
                qps = one_window(url_p, batch, duration=4.0)
                s1, v1 = spans_total()
                b1 = _http_predict_buckets(host_p,
                                           stats_p.get("http_service"))
                q1 = requests.get(f"http://{host_p}/stats",
                                  timeout=30).json()["queries"]
                lat = _bucket_delta_percentiles_ms(b0, b1)
                # spans/query from THIS window's own query delta — the
                # packed A/B's cumulative count is a different workload
                # and would skew the figure by its size ratio.
                spans = int(s1 - s0)
                return {"qps": round(qps, 2),
                        "queries": int(q1 - q0),
                        "spans_written": spans,
                        "spans_per_query": round(
                            spans / max(1, q1 - q0), 4),
                        "tail_verdicts": {k: v1.get(k, 0) - v0.get(k, 0)
                                          for k in v1},
                        "latency_ms_p50_p95_p99": lat}

            trace_off = trace_window()
            os.environ[tail_env] = "0.05"
            try:
                trace_on = trace_window()
            finally:
                if prior_tail is None:
                    os.environ.pop(tail_env, None)
                else:
                    os.environ[tail_env] = prior_tail
            # Tail sampling must actually have dropped fast traces:
            # fewer spans per query reach the store on the armed side.
            assert trace_on["tail_verdicts"].get("dropped", 0) > 0, \
                trace_on
            trace_plane = {"tail_off": trace_off, "tail_on": trace_on}

            # --- Disabled-side zero-series gate (r17 acceptance): this
            # whole config ran WITHOUT attribution/exemplars, so the
            # exposition must carry ZERO bin/tenant series and no
            # exemplar annotations anywhere.
            raw = requests.get(f"http://{host_p}/metrics",
                               timeout=30).text
            assert "rafiki_tpu_serving_bin_" not in raw, \
                "attribution-off side exposed bin series"
            assert "rafiki_tpu_serving_tenant_" not in raw, \
                "attribution-off side exposed tenant series"
            assert " # {" not in raw, \
                "exemplars-off side exposed exemplar annotations"

            packed_ab = {
                "wire_bytes_ratio": round(
                    side_p["wire_bytes_per_query"]
                    / side_q["wire_bytes_per_query"], 3),
                "packed": {**side_p, "windows": [round(v, 2)
                                                 for v in vals_p],
                           "qps_best": round(max(vals_p), 2),
                           "spread": round((max(vals_p) - min(vals_p))
                                           / max(vals_p), 3)},
                "perquery": {**side_q, "windows": [round(v, 2)
                                                   for v in vals_q],
                             "qps_best": round(max(vals_q), 2),
                             "spread": round((max(vals_q) - min(vals_q))
                                             / max(vals_q), 3)},
                "qps_ratio": round(max(vals_p) / max(vals_q), 3),
            }
            for inf in (inf_p, inf_q):
                admin.stop_inference_job(inf)
        finally:
            platform.shutdown()
            if prior_share is None:
                os.environ.pop(share_env, None)
            else:
                os.environ[share_env] = prior_share

    best_a, best_b = max(vals_a), max(vals_b)
    best_a_big, best_c_big = max(vals_a_big), max(vals_c_big)
    return _emit(
        "serving_concurrent_qps", best_a, "queries/s",
        **_serving_wire_fields(),
        packed_ab=packed_ab,
        trace_plane=trace_plane,
        n_windows=len(vals_a),
        spread=round((best_a - min(vals_a)) / best_a, 3),
        windows_microbatch=[round(v, 2) for v in vals_a],
        windows_direct=[round(v, 2) for v in vals_b],
        windows_shard_on=[round(v, 2) for v in vals_a_big],
        windows_shard_off=[round(v, 2) for v in vals_c_big],
        n_clients=n_clients,
        queries_per_request=per_request,
        qps_microbatch_on=round(best_a, 2),
        qps_microbatch_off=round(best_b, 2),
        microbatch_speedup=round(best_a / best_b, 3),
        # Replica sharding A/B: both jobs hold 2 same-bin replicas;
        # only A slices super-batches across them. Measured in its own
        # big-request windows — slicing pays in compute-per-shard, so
        # tiny-batch windows would measure per-shard overhead against
        # scheduler noise. n_devices tells the reader whether the
        # replicas actually held separate devices (data parallelism) or
        # co-owned one chip (where sharding can only add overhead).
        n_devices=n_devices,
        n_replicas_per_bin=2,
        shard_queries_per_request=shard_request,
        qps_shard_on=round(best_a_big, 2),
        qps_shard_off=round(best_c_big, 2),
        shard_speedup=round(best_a_big / best_c_big, 3),
        coalescing_factor=stats_a.get("coalescing_factor"),
        mean_batch_queries=stats_a.get("mean_batch_queries"),
        rejected_429=stats_a.get("rejected"),
        # Adaptive fill window at low offered load (trickle), p50/p95/
        # p99 ms: "added p99" vs the pinned-5ms job is the window cost.
        fill_window_s=stats_a.get("fill_window_s"),
        trickle_ms_p50_p95_p99_adaptive=trickle_a,
        trickle_ms_p50_p95_p99_fixed=trickle_d,
        # From the predictors' /metrics histograms (bucket-resolution,
        # cumulative over warm + timed windows) — the same series a
        # production scrape reads.
        latency_ms_p50_p95_p99_on=lat_a,
        latency_ms_p50_p95_p99_off=lat_b,
        stage_ms_p50_p95_p99=stages_a)


def main_lm_serving() -> dict:
    """Config[lm-serving]: the continuous-batching generative A/B
    (docs/serving.md "Generative serving"). Both sides run the SAME
    paged-KV engine + DecodeScheduler + token-frame wire over the bus;
    the only difference is the compiled decode width: W=4 with
    per-step admission (continuous) vs W=1 (run-to-completion FIFO —
    a sequence must finish before the next one gets the chip). The
    judged evidence is structural, not a wall-clock race:

    - ``rafiki_tpu_lm_tokens_total`` / ``..._decode_dispatches_total``
      deltas per side — tokens/dispatch must rise above 1 toward W on
      the continuous side and pin at ~1.0 on the static side (each
      dispatch carries one token for one sequence);
    - the latency split — short (4-token) requests submitted behind
      long (24-token) ones must finish well before the longs on the
      continuous side (they join the next step), while the static side
      serializes them behind the whole long decode;
    - a prefix-cache hit (same prompt twice, sequentially: the second
      prefill is skipped whole);
    - the generate-off gate, checked FIRST (registration is
      process-sticky): zero ``rafiki_tpu_lm_*`` series before the
      knob flips on.
    """
    import threading

    from rafiki_tpu.bus.memory import MemoryBus
    from rafiki_tpu.cache import Cache
    from rafiki_tpu.models import JaxTransformerLM
    from rafiki_tpu.observe import lm as obs_lm
    from rafiki_tpu.observe import metrics as obs_metrics
    from rafiki_tpu.worker.decode_scheduler import DecodeScheduler

    lm_families = (
        "rafiki_tpu_lm_tokens_total",
        "rafiki_tpu_lm_decode_dispatches_total",
        "rafiki_tpu_lm_prefill_total",
        "rafiki_tpu_lm_time_to_first_token_seconds",
    )

    # Disabled gate first: a generate-off process must expose ZERO lm
    # series (once a family registers it is process-immortal, so this
    # is only provable before the knob flips).
    os.environ.pop(obs_lm.GENERATE_ENV, None)
    obs_lm.reset_for_tests()
    assert not obs_lm.serving()
    off_series = sum(
        1 for n in lm_families
        if obs_metrics.registry().find(n) is not None)
    assert off_series == 0, f"{off_series} lm series while off"

    os.environ[obs_lm.GENERATE_ENV] = "1"
    obs_lm.reset_for_tests()

    knobs = JaxTransformerLM.validate_knobs({
        "d_model": 256, "n_layers": 2, "seq_len": 256, "batch_size": 2,
        "learning_rate": 1e-3, "train_steps": 20, "vocab_size": 512,
        "quick_train": False})
    model = JaxTransformerLM(**knobs)
    model._params = model._init_params()
    rng = np.random.default_rng(7)
    # Mixed workload, longs FIRST so the static side's shorts queue
    # behind a full long decode: 2x24 + 6x4 = 72 tokens per window.
    reqs = [(rng.integers(0, 512, size=9).tolist(), 24, "long")
            for _ in range(2)]
    reqs += [(rng.integers(0, 512, size=5).tolist(), 4, "short")
             for _ in range(6)]
    total_tokens = sum(n for _, n, _ in reqs)

    def counter_sum(name):
        fam = obs_metrics.registry().find(name)
        return sum(v for _, v in fam.samples()) if fam else 0.0

    def run_side(width):
        bus = MemoryBus()
        cache = Cache(bus)
        eng = model.make_generator(page_size=4, n_pages=64,
                                   decode_batch=width, max_new_cap=32,
                                   prefix_cache_entries=4)
        sched = DecodeScheduler(eng, cache, "bench-lm",
                                idle_wait=0.002)
        th = threading.Thread(target=sched.loop, daemon=True)
        th.start()

        def drain(qids):
            """Poll every live stream; returns per-qid done times."""
            got, done = {q: 0 for q in qids}, {}
            deadline = time.time() + 180
            while len(done) < len(qids) and time.time() < deadline:
                for q in qids:
                    if q in done:
                        continue
                    for fr in cache.pop_token_frames(q, timeout=0.005):
                        got[q] += len(fr.get("tok", ()))
                        if fr.get("done"):
                            assert fr.get("finish") in ("length", "eos"), fr
                            done[q] = time.time()
            assert len(done) == len(qids), \
                f"{len(done)}/{len(qids)} streams finished"
            return got, done

        def window():
            t0 = time.time()
            submitted = {}
            for tokens, max_new, kind in reqs:
                qid = cache.send_generate("bench-lm", tokens,
                                          max_new=max_new,
                                          temperature=0.0)
                submitted[qid] = (kind, time.time())
            for it in cache.pop_queries("bench-lm", timeout=2.0):
                sched.submit(it)
            got, done = drain(submitted)
            window.lat = {"short": [], "long": []}
            for q, (kind, ts) in submitted.items():
                window.lat[kind].append((done[q] - ts) * 1e3)
            return sum(got.values()) / (max(done.values()) - t0)

        window()  # warm-up: pays prefill/decode compile + first-touch
        c0_tok = counter_sum("rafiki_tpu_lm_tokens_total")
        c0_disp = counter_sum("rafiki_tpu_lm_decode_dispatches_total")
        tps, fields = _adaptive_windows(window)
        d_tok = counter_sum("rafiki_tpu_lm_tokens_total") - c0_tok
        d_disp = counter_sum(
            "rafiki_tpu_lm_decode_dispatches_total") - c0_disp
        per_dispatch = d_tok / max(d_disp, 1.0)

        # Prefix-cache probe (sequential, outside the timed windows):
        # the same prompt twice — the second prefill is skipped whole.
        cached = 0
        if width > 1:
            probe = rng.integers(0, 512, size=8).tolist()
            skipped0 = eng.prefill_skipped_total
            for _ in range(2):
                qid = cache.send_generate("bench-lm", probe,
                                          max_new=3, temperature=0.0)
                for it in cache.pop_queries("bench-lm", timeout=2.0):
                    sched.submit(it)
                drain({qid: 0})
            cached = eng.prefill_skipped_total - skipped0
            assert cached >= 1, "prefix cache never hit"

        lat = window.lat
        sched.close(join=th)
        return tps, fields, per_dispatch, lat, cached

    try:
        tps_c, fields_c, tpd_c, lat_c, cached = run_side(4)
        tps_s, fields_s, tpd_s, lat_s, _ = run_side(1)
    finally:
        model.destroy()
        os.environ.pop(obs_lm.GENERATE_ENV, None)
        obs_lm.reset_for_tests()

    # The structural gate: per-step admission batches decode work;
    # run-to-completion pays a dispatch per token. The first token of
    # every request comes from its PREFILL (no decode dispatch), so
    # the static ratio sits at max_new/(max_new-1) per request — ~1.13
    # on this mix — not exactly 1.0.
    assert tpd_c > 1.5, f"continuous tokens/dispatch {tpd_c:.2f}"
    assert tpd_s <= 1.2, f"static tokens/dispatch {tpd_s:.2f}"

    def ms(vals):
        return round(sum(vals) / max(len(vals), 1), 1)

    return _emit(
        "lm_serving_tokens_per_sec", tps_c, "tokens/s",
        tokens_per_window=total_tokens,
        decode_batch=4,
        tps_continuous=round(tps_c, 2), tps_static=round(tps_s, 2),
        continuous_speedup=round(tps_c / tps_s, 3) if tps_s else None,
        tokens_per_dispatch_continuous=round(tpd_c, 3),
        tokens_per_dispatch_static=round(tpd_s, 3),
        short_ms_mean_continuous=ms(lat_c["short"]),
        long_ms_mean_continuous=ms(lat_c["long"]),
        short_ms_mean_static=ms(lat_s["short"]),
        long_ms_mean_static=ms(lat_s["long"]),
        prefill_cached_hits=int(cached),
        off_lm_series=off_series,
        windows_static=fields_s["windows"],
        spread_static=fields_s["spread"],
        **fields_c)


def main_multitenant() -> dict:
    """Config[4]: aggregate trials/hour, two jobs contending for chips.

    Runs on ANY device count — including the one-chip v5e-1 — via the
    allocator's time-sliced tenancy (resident-runner threads co-own a
    chip when no exclusive placement exists), so the judged channel
    gets a real number instead of a "needs >= 2 devices" error (r4
    verdict item 3). Fairness rides the record: per-job elapsed times
    and their ratio (1.0 = perfectly fair time-slicing), plus whether
    the jobs' execution windows actually overlapped.
    """
    import tempfile

    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.platform import LocalPlatform

    import jax

    n_chips = len(jax.devices())
    trials_per_job = 4

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256)
        platform = LocalPlatform(workdir=tmp + "/plat")
        try:
            t0 = time.time()
            jobs = []
            for i in range(2):
                user = platform.admin.create_user(
                    f"t{i}@x.c", "pw", UserType.MODEL_DEVELOPER)
                model = platform.admin.create_model(
                    user["id"], f"ff{i}", TaskType.IMAGE_CLASSIFICATION,
                    "rafiki_tpu.models.feedforward:JaxFeedForward")
                jobs.append(platform.admin.create_train_job(
                    user["id"], f"app{i}", TaskType.IMAGE_CLASSIFICATION,
                    [model["id"]],
                    {BudgetOption.MODEL_TRIAL_COUNT: trials_per_job,
                     BudgetOption.CHIP_COUNT: max(1, n_chips // 2)},
                    train_path, val_path))
            for j in jobs:
                assert platform.admin.wait_until_train_job_done(
                    j["id"], timeout=1800)
            elapsed = time.time() - t0
            windows = []
            for j in jobs:
                trials = platform.meta.get_trials_of_train_job(j["id"])
                windows.append((min(t["started_at"] for t in trials),
                                max(t["finished_at"] for t in trials)))
        finally:
            platform.shutdown()
    total = 2 * trials_per_job
    (a0, a1), (b0, b1) = windows
    per_job = [round(a1 - a0, 2), round(b1 - b0, 2)]
    return _emit("multitenant_trials_per_hour",
                 total / (elapsed / 3600.0), "trials/hour",
                 n_devices=n_chips,
                 time_sliced=(n_chips < 2),
                 per_job_seconds=per_job,
                 fairness=round(min(per_job) / max(per_job), 3),
                 overlapped=bool(a0 < b1 and b0 < a1))


def main_densenet() -> dict:
    """Config[1]: flagship DenseNet-121 training throughput (CIFAR-10
    shapes). A first train() pays the XLA compile; the timed second run
    reuses the cached AOT step, so the figure is steady-state."""
    import tempfile

    from rafiki_tpu.datasets import make_synthetic_image_dataset
    from rafiki_tpu.models import JaxDenseNet

    epochs, batch = 6, 128  # min of the model's max_epochs knob range
    knobs = JaxDenseNet.validate_knobs({
        "arch": "densenet_121", "growth_rate": 32, "learning_rate": 0.1,
        "batch_size": batch, "weight_decay": 1e-4, "max_epochs": epochs,
        "early_stop_epochs": 5, "quick_train": False})

    with tempfile.TemporaryDirectory() as tmp:
        train_path, _ = make_synthetic_image_dataset(
            tmp, n_train=2048, n_val=256, image_shape=(32, 32, 3),
            n_classes=N_CLASSES)
        warm = JaxDenseNet(**knobs)
        warm.train(train_path)
        warm.destroy()

        images = (2048 // batch) * batch * epochs

        def window() -> float:
            m = JaxDenseNet(**knobs)
            t0 = time.time()
            m.train(train_path)
            elapsed = time.time() - t0
            m.destroy()
            return images / elapsed

        with _UtilProbe() as probe:
            rate, fields = _adaptive_windows(window)

    return _emit("densenet_train_images_per_sec", rate, "images/s",
                 **fields, **probe.fields())


def main_enas() -> dict:
    """Config[2]: ENAS architecture search — controller advisor proposing
    architectures into weight-shared quick trials on the masked supernet."""
    import tempfile

    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.models import JaxEnas
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    n_trials = 6

    with tempfile.TemporaryDirectory() as tmp:
        train_path, val_path = make_synthetic_image_dataset_compat(
            tmp, n_train=2048, n_val=256, image_shape=(32, 32, 3))
        meta = MetaStore(":memory:")
        params = ParamStore(tmp + "/params")
        # Budget covers warm-up + the adaptive-window cap (4 windows).
        advisor = make_advisor(JaxEnas.get_knob_config(), seed=0,
                               total_trials=4 * n_trials + 1)
        runner = TrialRunner(
            JaxEnas, advisor, train_path, val_path, meta, params,
            sub_train_job_id="bench-enas",
            budget={BudgetOption.MODEL_TRIAL_COUNT: 4 * n_trials + 1})
        runner.run_one()  # warm-up: pays the one supernet compile

        def window() -> float:
            t0 = time.time()
            for _ in range(n_trials):
                runner.run_one()
            return n_trials / ((time.time() - t0) / 3600.0)

        with _UtilProbe() as probe:
            rate, fields = _adaptive_windows(window)

    return _emit("enas_trials_per_hour", rate, "trials/hour",
                 **fields, **probe.fields())


def main_roofline() -> dict:
    """Roofline config: flagship-scale ``JaxTransformerLM`` training on
    one chip — the evidence path toward the ≥90%-utilization north star
    (r4 verdict item 1: "prove the stack can saturate a chip"). The
    shape (d_model=2048, 8 layers, T=2048, bf16, Pallas flash both
    passes, selective remat) was swept on the v5e-1: its step runs at
    ~0.54 spec-peak MFU, and the record's ``chip_util`` field carries
    the sustained mean from the model's own MfuMeter plumbing."""
    import tempfile

    from rafiki_tpu.datasets import make_synthetic_token_dataset
    from rafiki_tpu.models import JaxTransformerLM

    import jax

    if jax.default_backend() not in BASELINE_PLATFORMS:
        raise SystemExit("roofline bench needs the TPU (flagship shape "
                         "would take hours on CPU)")
    steps, b, t = 200, 4, 2048
    knobs = JaxTransformerLM.validate_knobs({
        "d_model": 2048, "n_layers": 8, "seq_len": t, "batch_size": b,
        "learning_rate": 3e-4, "train_steps": steps,
        "vocab_size": 32768, "quick_train": False})

    with tempfile.TemporaryDirectory() as tmp:
        train_path, _ = make_synthetic_token_dataset(
            tmp, n_train=1 << 20, n_val=1 << 14)
        warm = JaxTransformerLM(**knobs)
        warm.train(train_path)  # pays the XLA compile (step cache)
        warm.destroy()

        def window() -> float:
            m = JaxTransformerLM(**knobs)
            t0 = time.time()
            m.train(train_path)
            elapsed = time.time() - t0
            m.destroy()
            return steps * b * t / elapsed

        with _UtilProbe() as probe:
            rate, fields = _adaptive_windows(window)

    return _emit("lm_train_tokens_per_sec", rate, "tokens/s",
                 **fields, **probe.fields())


def main_attention() -> dict:
    """Flash-attention kernel throughput (bf16, causal, T=8192) on the
    real chip. The tunneled TPU hides up to ~0.7 s of compute inside its
    sync latency, so the op loops inside ONE jit via lax.scan and the
    measured window subtracts that constant (see BASELINE.md notes)."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.ops import flash_attention

    if jax.default_backend() not in ("tpu", "axon"):
        raise SystemExit("attention bench needs the TPU (the CPU "
                         "interpreter path would take hours at T=8192)")
    B, H, T, D = 2, 8, 8192, 128
    N = 400
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.bfloat16)
    flops = B * H * T * T * D * 2 * 2 / 2  # causal

    @jax.jit
    def looped(q, k, v):
        def body(qq, _):
            return qq + flash_attention(qq, k, v, causal=True) * 1e-6, ()
        qq, _ = jax.lax.scan(body, q, None, length=N)
        return qq

    # One jitted probe reused across windows: a fresh lambda per sync
    # would recompile inside the timed interval.
    probe = jax.jit(lambda x: x.reshape(-1)[:1].astype(jnp.float32))

    def sync(o):
        return np.asarray(probe(o))

    sync(looped(q, k, v))  # compile + warm
    # The ~0.7 s sync constant is a property of the axon tunnel; a
    # directly attached chip has none.
    overhead = 0.7 if jax.default_backend() == "axon" else 0.0

    def window() -> float:
        t0 = time.time()
        sync(looped(q, k, v))
        per_iter = max(time.time() - t0 - overhead, 1e-9) / N
        return flops / per_iter / 1e12

    tflops, fields = _adaptive_windows(window)
    return _emit("flash_attention_tflops", tflops, "TFLOP/s", **fields)


def main_analysis() -> dict:
    """Static-analysis smoke (docs/analysis.md): run the suite's own
    ``--json`` CLI on this checkout and fold the per-code finding counts
    into the bench record. The headline value is NEW findings — 0 is the
    only healthy number (the suite is a gate, not a throughput metric),
    so this config never participates in the perf sweep and vs_baseline
    stays null off-accelerator like every other record."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.abspath(__file__))
    out = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--json"],
        capture_output=True, text=True, cwd=root, timeout=600)
    try:
        report = json.loads(out.stdout)
    except ValueError:
        raise RuntimeError(
            f"analysis CLI emitted no JSON (rc {out.returncode}): "
            f"{out.stderr.strip()[:500]}")
    return _emit(
        "analysis_new_findings", float(report["new"]), "findings",
        exit_code=out.returncode,
        files=report["files"],
        checkers=report["checkers"],
        counts_per_code=report["counts_per_code"],
        by_status=report["by_status"],
        stale_baseline=len(report["stale_baseline"]))


def main_chaos() -> dict:
    """Config[chaos]: closed-loop recovery under a seeded fault plan
    (docs/robustness.md). Not a perf figure — the config injures its own
    stack — so like ``analysis`` it never joins the sweep. Two parts:

    - **Hot-path A/B** of the injection sites themselves: MemoryBus
      push+pop ops/s with the fault plane DISABLED (construction stores
      ``None`` — byte-for-byte the pre-fault path) vs ARMED with an
      empty plan (hooks live, nothing fires). ``test_faults.py`` proves
      the disabled behavior unchanged; this records the speed side of
      the zero-overhead contract, and the armed/disabled ratio bounds
      what arming costs production.
    - **The chaos loop**: a 2-bin ensemble serving stack built with the
      plane armed-quiet, then repeatedly injured under the seeded plan —
      one replica dies HARD mid-load (meta row RUNNING, registration
      stale), ``supervise()`` respawns it, the Predictor folds the
      respawn back into its shard plans. Availability (headline) is
      answered/total over EVERY query sent across all cycles — 1.0
      means the partial-bin degrade dropped nothing while the loop
      closed; time-to-full-recovery per cycle (hard death -> full-bin
      plans restored) feeds the adaptive-windows estimator so the
      record carries ``n_windows``/``spread`` like every other config.
    """
    import tempfile
    import threading

    import requests

    from rafiki_tpu import faults
    from rafiki_tpu.bus.memory import MemoryBus
    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.constants import (BudgetOption, ServiceStatus,
                                      ServiceType, TaskType, UserType)
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe.metrics import registry
    from rafiki_tpu.platform import LocalPlatform

    # Seeded so the probabilistic bus jitter replays: same plan + seed
    # = same per-rule decision sequence (docs/robustness.md).
    seed = int(os.environ.get(faults.SEED_ENV, "0") or "0")
    plan = "worker.crash:n=1;bus.delay:p=0.02,ms=2"

    # --- Hot-path A/B: disabled vs armed-empty ------------------------
    n_ops = 3000

    def bus_window(bus):
        def window() -> float:
            t0 = time.time()
            for i in range(n_ops):
                bus.push("bench-q", i)
                bus.pop("bench-q")
            return 2 * n_ops / (time.time() - t0)
        return window

    faults.set_plan(None)  # hard-disarm (overrides any env plan)
    ops_off, _ = _adaptive_windows(bus_window(MemoryBus()))
    faults.set_plan("")    # armed, zero rules: hooks live, silent
    ops_armed, _ = _adaptive_windows(bus_window(MemoryBus()))

    # --- Chaos loop (plane stays armed-quiet through construction, so
    # every bus/http/worker site built below holds a live hook) -------
    counts = {"total": 0, "answered": 0}
    try:
        with tempfile.TemporaryDirectory() as tmp:
            train_path, val_path = make_synthetic_image_dataset_compat(
                tmp, n_train=1024, n_val=256)
            platform = LocalPlatform(workdir=tmp + "/plat", http=True,
                                     supervise_interval=0)
            try:
                user = platform.admin.create_user(
                    "chaos@x.c", "pw", UserType.MODEL_DEVELOPER)
                model = platform.admin.create_model(
                    user["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
                    "rafiki_tpu.models.feedforward:JaxFeedForward")
                job = platform.admin.create_train_job(
                    user["id"], "chaos", TaskType.IMAGE_CLASSIFICATION,
                    [model["id"]],
                    {BudgetOption.MODEL_TRIAL_COUNT: 2},
                    train_path, val_path)
                assert platform.admin.wait_until_train_job_done(
                    job["id"], timeout=1200)
                inf = platform.admin.create_inference_job(
                    user["id"], job["id"], max_models=2)
                host = platform.admin.get_inference_job(
                    inf["id"])["predictor_host"]
                url = f"http://{host}/predict"
                pred_svc = next(
                    s for s in platform.meta.get_services()
                    if s["service_type"] == ServiceType.PREDICT)
                psvc = platform.container.get(pred_svc["id"])
                # Bound the partial-bin wait for queries caught
                # mid-crash (the dead bin has no sibling to resubmit
                # to, so they pay one full gather before degrading).
                psvc.predictor.gather_timeout = 4.0
                cache = Cache(platform.bus)

                val = load_image_dataset(val_path)
                batch = [encode_payload(val.images[i]) for i in range(3)]

                def predict() -> None:
                    counts["total"] += 1
                    r = requests.post(url, json={"queries": batch},
                                      timeout=300)
                    if r.status_code != 200:
                        return
                    preds = r.json().get("predictions") or []
                    if len(preds) == len(batch) and \
                            all(p is not None for p in preds):
                        counts["answered"] += 1

                predict()  # warm: registration waits, EWMAs seeded
                deadline = time.monotonic() + 120
                while len(cache.running_workers(inf["id"])) < 2:
                    # Both bins must serve BEFORE the injuring starts —
                    # a 1-replica stack has no full-bin state to
                    # restore and the cycle would "measure" nothing.
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            "only %d/2 replicas registered; chaos "
                            "needs both bins live before injuring"
                            % len(cache.running_workers(inf["id"])))
                    time.sleep(0.2)

                def live_inference_ids():
                    return [s["id"] for s in platform.meta.get_services()
                            if s["service_type"] == ServiceType.INFERENCE
                            and s["status"] == ServiceStatus.RUNNING]

                def cycle() -> float:
                    """Injure once, recover fully; seconds from the hard
                    death to restored full-bin shard plans."""
                    faults.set_plan(plan, seed=seed)
                    dead_at = None
                    deadline = time.monotonic() + 120
                    while dead_at is None:
                        if time.monotonic() > deadline:
                            raise RuntimeError("injected crash never "
                                               "fired")
                        predict()
                        for sid in live_inference_ids():
                            w = platform.container.get(sid)
                            if w is not None and not w.running:
                                dead_at = time.monotonic()
                    restarted = platform.services.supervise()
                    if len(restarted) != 1:
                        raise RuntimeError(
                            f"supervise respawned {len(restarted)} "
                            "workers, expected 1")
                    deadline = time.monotonic() + 300
                    while len(psvc.predictor._choose_workers()) < 2:
                        if time.monotonic() > deadline:
                            raise RuntimeError("respawned replica never "
                                               "rejoined the plan")
                        predict()
                        time.sleep(0.05)
                    predict()  # full-bin ensembles again
                    return time.monotonic() - dead_at

                recoveries: list = []

                def window() -> float:
                    s = cycle()
                    recoveries.append(round(s, 2))
                    return 1.0 / s  # higher = better for the estimator

                rate, fields = _adaptive_windows(window)
                fields.pop("windows", None)  # rates; recoveries carry it
                platform.admin.stop_inference_job(inf["id"])
            finally:
                platform.shutdown()
    finally:
        faults.set_plan(None)

    reg = registry()
    c = reg.find("rafiki_tpu_fault_injections_total")
    injections = {f"{lab['site']}.{lab['kind']}": v
                  for lab, v in (c.samples() if c is not None else [])}
    c = reg.find("rafiki_tpu_node_restarts_total")
    respawns = (c.value(service_type=ServiceType.INFERENCE)
                if c is not None else 0.0)
    c = reg.find("rafiki_tpu_serving_replica_quarantines_total")
    quarantines = (sum(v for _, v in c.samples())
                   if c is not None else 0.0)

    availability = (counts["answered"] / counts["total"]
                    if counts["total"] else 0.0)
    return _emit(
        "chaos_availability", availability, "fraction", **fields,
        fault_plan=plan, fault_seed=seed,
        time_to_full_recovery_s=round(1.0 / rate, 2),
        recovery_s_windows=recoveries,
        queries_total=counts["total"],
        queries_answered=counts["answered"],
        inference_respawns=respawns,
        replica_quarantines=quarantines,
        fault_injections=injections,
        bus_ops_per_s_disabled=round(ops_off, 1),
        bus_ops_per_s_armed_empty=round(ops_armed, 1),
        fault_hook_overhead_ratio=round(ops_armed / ops_off, 3)
        if ops_off else None)


def main_autoscale() -> dict:
    """Config[autoscale]: the closed serving control loop, A/B'd
    (docs/autoscaling.md). Not a sweep member — like chaos it builds,
    ramps, and rescales its own stack.

    One scenario, run twice at EQUAL initial capacity: a trained 2-bin
    ensemble (1 chip per bin), an idle-ish "donor" train job burning 2
    chips on a 4-chip node with time-sliced sharing OFF — zero free
    exclusive chips, so the FIRST starved scale-up must preempt the
    donor — and a ramped closed-loop load (2 -> 6 -> 16 clients)
    against a small admission queue. The OFF side runs
    FIRST and its registry is asserted to expose ZERO autoscale series;
    the ON side runs the autoscaler on a 0.5 s supervise cadence.
    Judged on counter deltas (the r9 discipline): scale-up actions
    taken, chips reclaimed from the idle donor, and backpressure 429s
    — the ON side must reject STRICTLY fewer under the same ramp
    (replicas + the reclaimed chip drain the queue the OFF side can
    only bounce). Per-phase p50/p99 from the predictor's own http
    histogram is the latency story; on this 1-core box the honest
    throughput ratio needs the multi-chip channel, but preemption is
    real compute here — time-sliced silicon means a reclaimed chip IS
    reclaimed CPU. A flapping-guard (oscillation inside the hysteresis
    band produces zero actions) is pinned as a unit test in
    tests/test_autoscaler.py.
    """
    import tempfile
    import threading

    import requests

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe.metrics import registry
    from rafiki_tpu.platform import LocalPlatform

    phases = [(2, 5.0), (6, 8.0), (16, 14.0)]  # (clients, seconds)
    batch_n = 4

    # A deliberately tight admission bound: the ramp must OVERFLOW it
    # (the 429s are the judged signal), and the queue must drain batch
    # by batch so the drain rate — what the autoscaler improves — is
    # what decides how often it overflows.
    knob_env = {
        "RAFIKI_TPU_CHIP_SHARE": "0",
        NodeConfig.env_name("serving_queue_cap"): "12",
        NodeConfig.env_name("serving_max_batch"): "8",
        NodeConfig.env_name("serving_max_inflight"): "1",
        NodeConfig.env_name("autoscale_up_cooldown_s"): "1.0",
        NodeConfig.env_name("autoscale_down_cooldown_s"): "120.0",
        NodeConfig.env_name("autoscale_max_replicas"): "3",
        NodeConfig.env_name("autoscale_idle_sweeps"): "2",
        # The donor's tiny trials measure ~0.001-0.1 MFU against the
        # calibrated-CPU peak; 0.3 classifies that low-utilization
        # training as preemptible with margin while a genuinely busy
        # job (the contract the unit tests pin) would not be.
        NodeConfig.env_name("autoscale_mfu_floor"): "0.3",
    }
    auto_env = NodeConfig.env_name("autoscale")

    http_buckets = _http_predict_buckets

    def delta_p(before, after):
        return _bucket_delta_percentiles_ms(before, after,
                                            qs=(0.5, 0.99))

    def donor_train_workers(plat, job_id):
        from rafiki_tpu.constants import ServiceType

        n = 0
        for sub in plat.meta.get_sub_train_jobs(job_id):
            for w in plat.meta.get_train_job_workers(sub["id"]):
                svc = plat.meta.get_service(w["service_id"])
                if svc["service_type"] == ServiceType.TRAIN and \
                        svc["status"] in ("STARTED", "DEPLOYING",
                                          "RUNNING"):
                    n += 1
        return n

    def ramp(url, batch, counts):
        """The shared load shape: closed-loop clients per phase, each
        posting 4-query requests; a 429 backs off 50 ms and counts.
        Per-client count SLOTS, folded after join (the zipf config's
        pattern): `counts[k] += 1` from 16 threads is a lost-update
        race on the judged A/B metric."""
        for n_clients, dur in phases:
            stop = threading.Event()
            errors: list = []
            rejected = [0] * n_clients
            served = [0] * n_clients

            def client(i: int) -> None:
                session = requests.Session()
                try:
                    while not stop.is_set():
                        r = session.post(url, json={"queries": batch},
                                         timeout=300)
                        if r.status_code == 429:
                            rejected[i] += 1
                            time.sleep(0.05)
                        else:
                            r.raise_for_status()
                            served[i] += batch_n
                except Exception as e:  # surfaced by the caller
                    errors.append(e)
                    stop.set()

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n_clients)]
            for t in threads:
                t.start()
            time.sleep(dur)
            stop.set()
            for t in threads:
                t.join()
            if errors:
                raise RuntimeError(f"ramp client failed: {errors[0]}")
            counts["429"] += sum(rejected)
            counts["served"] += sum(served)

    def run_side(autoscale_on: bool) -> dict:
        prior = {k: os.environ.get(k) for k in
                 list(knob_env) + [auto_env]}
        os.environ.update(knob_env)
        if autoscale_on:
            os.environ[auto_env] = "1"
        else:
            os.environ.pop(auto_env, None)
        side: dict = {"429": 0, "served": 0}
        try:
            with tempfile.TemporaryDirectory() as tmp:
                train_path, val_path = \
                    make_synthetic_image_dataset_compat(
                        tmp, n_train=2048, n_val=256)
                plat = LocalPlatform(
                    workdir=f"{tmp}/plat", http=True,
                    supervise_interval=0.5 if autoscale_on else 0)
                try:
                    admin = plat.admin
                    u = admin.create_user("as@x.c", "pw",
                                          UserType.MODEL_DEVELOPER)
                    mdl = admin.create_model(
                        u["id"], "ff-as", TaskType.IMAGE_CLASSIFICATION,
                        "rafiki_tpu.models.feedforward:JaxFeedForward")
                    job = admin.create_train_job(
                        u["id"], "as", TaskType.IMAGE_CLASSIFICATION,
                        [mdl["id"]],
                        {BudgetOption.MODEL_TRIAL_COUNT: 2},
                        train_path, val_path)
                    assert admin.wait_until_train_job_done(job["id"],
                                                           timeout=1200)
                    donor = admin.create_train_job(
                        u["id"], "as-donor",
                        TaskType.IMAGE_CLASSIFICATION, [mdl["id"]],
                        {BudgetOption.MODEL_TRIAL_COUNT: 100000,
                         BudgetOption.CHIP_COUNT: 2},
                        train_path, val_path)
                    inf = admin.create_inference_job(u["id"], job["id"],
                                                     max_models=2)
                    cache = Cache(plat.bus)
                    deadline = time.time() + 600
                    while len(cache.running_workers(inf["id"])) < 2 \
                            and time.time() < deadline:
                        time.sleep(0.5)
                    assert len(cache.running_workers(inf["id"])) >= 2
                    host = admin.get_inference_job(
                        inf["id"])["predictor_host"]
                    url = f"http://{host}/predict"
                    val = load_image_dataset(val_path)
                    batch = [encode_payload(val.images[i])
                             for i in range(batch_n)]
                    requests.post(url, json={"queries": batch},
                                  timeout=300).raise_for_status()
                    stats = requests.get(f"http://{host}/stats",
                                         timeout=30).json()
                    before = http_buckets(host, stats["http_service"])
                    side["replicas_before"] = len(
                        plat.services.active_inference_workers(
                            inf["id"]))
                    side["donor_workers_before"] = \
                        donor_train_workers(plat, donor["id"])
                    ramp(url, batch, side)
                    time.sleep(2.0)  # quiet tail (decisions settle)
                    side["latency_ms_p50_p99"] = delta_p(
                        before, http_buckets(host,
                                             stats["http_service"]))
                    side["replicas_after"] = len(
                        plat.services.active_inference_workers(
                            inf["id"]))
                    side["donor_workers_after"] = \
                        donor_train_workers(plat, donor["id"])
                    if autoscale_on:
                        snap = admin.get_autoscale()
                        side["decisions"] = [
                            {k: d.get(k) for k in
                             ("epoch", "action", "reason", "bin",
                              "target")}
                            for d in snap["decisions"]][:32]
                        c = registry().find(
                            "rafiki_tpu_autoscale_actions_total")
                        side["actions"] = {
                            f"{lab['action']}:{lab['reason']}": int(v)
                            for lab, v in (c.samples() if c else [])}
                        r = registry().find(
                            "rafiki_tpu_autoscale_reclaimed_chips_total")
                        side["chips_reclaimed"] = \
                            int(r.value()) if r else 0
                    else:
                        # The disabled side must have registered ZERO
                        # autoscale series (it runs FIRST, so the
                        # process registry cannot have been fed by the
                        # ON side).
                        side["autoscale_series"] = sum(
                            len(m.samples()) for m in
                            (registry().find(n) for n in (
                                "rafiki_tpu_autoscale_actions_total",
                                "rafiki_tpu_autoscale_target_replicas",
                                "rafiki_tpu_autoscale_actual_replicas",
                                "rafiki_tpu_autoscale_reclaimed_"
                                "chips_total"))
                            if m is not None)
                        assert side["autoscale_series"] == 0, side
                    admin.stop_train_job(donor["id"])
                    admin.stop_inference_job(inf["id"])
                finally:
                    plat.shutdown()
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return side

    off = run_side(False)
    on = run_side(True)

    # The acceptance gates: the control loop must have acted, reclaimed
    # idle training compute, and strictly reduced backpressure.
    scale_ups = sum(v for k, v in on.get("actions", {}).items()
                    if k.startswith("scale_up:"))
    assert scale_ups >= 1, on.get("actions")
    assert on.get("chips_reclaimed", 0) >= 1, on.get("actions")
    assert on["donor_workers_after"] < on["donor_workers_before"], on
    assert on["429"] < off["429"], (on["429"], off["429"])
    assert off["autoscale_series"] == 0

    avoided = off["429"] - on["429"]
    return _emit(
        "autoscale_backpressure_avoided", avoided, "rejections",
        ramp_phases=[{"clients": c, "seconds": s} for c, s in phases],
        queries_per_request=batch_n,
        backpressure_429_on=on["429"],
        backpressure_429_off=off["429"],
        served_on=on["served"], served_off=off["served"],
        latency_ms_p50_p99_on=on["latency_ms_p50_p99"],
        latency_ms_p50_p99_off=off["latency_ms_p50_p99"],
        replicas_on=[on["replicas_before"], on["replicas_after"]],
        replicas_off=[off["replicas_before"], off["replicas_after"]],
        donor_workers_on=[on["donor_workers_before"],
                          on["donor_workers_after"]],
        donor_workers_off=[off["donor_workers_before"],
                           off["donor_workers_after"]],
        scale_up_actions=scale_ups,
        actions=on.get("actions", {}),
        chips_reclaimed=on.get("chips_reclaimed", 0),
        decisions=on.get("decisions", []),
        off_new_series=off["autoscale_series"])


def main_slo() -> dict:
    """Config[slo]: the SLO plane's judgment + actuation loop, closed
    (docs/observability.md "SLOs & alerting"). Not a sweep member —
    like chaos it injures its own stack.

    OFF side FIRST (the zero-series gate): a platform WITHOUT
    ``RAFIKI_TPU_SLO_RULES`` serves real traffic and runs a supervise
    sweep — asserted to hold no engine, restart nothing, and expose
    ZERO ``rafiki_tpu_slo_*`` series (the process registry cannot have
    been fed by the later ON side).

    ON side: a 1-bin trained ensemble on a 2-chip node with a
    ``p95<250ms`` latency objective (fast/slow burn windows 2 s / 4 s,
    burn threshold 2, for 0.5 s, resolve 3 s) and the autoscaler armed
    with its QUEUE thresholds made untriggerable — a scale-up can only
    come from SLO pressure. Supervise sweeps are driven manually so
    the phase boundaries are deterministic: healthy ticks (state ok,
    budget untouched), then ``worker.slow:p=1,ms=600`` makes every
    burst breach -> pending -> firing (the alert ring carries the
    transitions; the budget gauge drops), the firing alert drives
    >= 1 ``scale_up:slo_firing`` autoscale action onto the free chip,
    then the plan clears and the fast window's recovery resolves the
    alert. Judged on the ring + counters, not throughput.
    """
    import tempfile

    import requests

    from rafiki_tpu import faults
    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe.metrics import registry
    from rafiki_tpu.platform import LocalPlatform

    slo_families = ("rafiki_tpu_slo_budget_remaining_ratio",
                    "rafiki_tpu_slo_burn_rate",
                    "rafiki_tpu_slo_alerts_total")

    def slo_series_count() -> int:
        return sum(len(m.samples()) for m in
                   (registry().find(n) for n in slo_families)
                   if m is not None)

    rules = ("predict-p95:p95<250ms,window=60,fast=2,slow=4,burn=2,"
             "for=0.5,resolve=3")
    on_env = {
        NodeConfig.env_name("slo_rules"): rules,
        "RAFIKI_TPU_AUTOSCALE": "1",
        # Queue thresholds untriggerable: the ONLY scale-up pressure
        # left is the firing SLO (reason slo_firing, asserted below).
        NodeConfig.env_name("autoscale_queue_high"): "1.0",
        NodeConfig.env_name("autoscale_queue_low"): "0.0",
        NodeConfig.env_name("autoscale_up_cooldown_s"): "1.0",
        NodeConfig.env_name("autoscale_down_cooldown_s"): "3600",
        NodeConfig.env_name("autoscale_mfu_floor"): "0",
        NodeConfig.env_name("autoscale_max_replicas"): "2",
    }

    def build_stack(plat):
        admin = plat.admin
        u = admin.create_user("slo@x.c", "pw",
                              UserType.MODEL_DEVELOPER)
        mdl = admin.create_model(
            u["id"], "ff-slo", TaskType.IMAGE_CLASSIFICATION,
            "rafiki_tpu.models.feedforward:JaxFeedForward")
        job = admin.create_train_job(
            u["id"], "slo", TaskType.IMAGE_CLASSIFICATION,
            [mdl["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
            build_stack.train_path, build_stack.val_path)
        assert admin.wait_until_train_job_done(job["id"], timeout=1200)
        inf = admin.create_inference_job(u["id"], job["id"],
                                         max_models=1)
        cache = Cache(plat.bus)
        deadline = time.time() + 600
        while not cache.running_workers(inf["id"]) and \
                time.time() < deadline:
            time.sleep(0.5)
        assert cache.running_workers(inf["id"])
        host = plat.admin.get_inference_job(inf["id"])["predictor_host"]
        val = load_image_dataset(build_stack.val_path)
        batch = [encode_payload(val.images[i]) for i in range(4)]
        return inf, f"http://{host}/predict", batch

    def tick(url, batch, plat, n_posts=3):
        for _ in range(n_posts):
            requests.post(url, json={"queries": batch},
                          timeout=300).raise_for_status()
        plat.services.supervise()

    record: dict = {}
    prior = {k: os.environ.get(k) for k in on_env}
    try:
        with tempfile.TemporaryDirectory() as tmp:
            build_stack.train_path, build_stack.val_path = \
                make_synthetic_image_dataset_compat(tmp, n_train=2048,
                                                    n_val=256)
            # --- OFF side (runs FIRST: the zero-series gate) ---------
            for k in on_env:
                os.environ.pop(k, None)
            plat = LocalPlatform(workdir=f"{tmp}/off", http=True,
                                 supervise_interval=0, n_chips=2)
            try:
                inf, url, batch = build_stack(plat)
                tick(url, batch, plat)
                assert plat.slo_engine is None
                assert plat.services.slo_engine is None
                assert plat.services.supervise() == []
                record["off_slo_series"] = slo_series_count()
                assert record["off_slo_series"] == 0
                plat.admin.stop_inference_job(inf["id"])
            finally:
                plat.shutdown()

            # --- ON side ---------------------------------------------
            os.environ.update(on_env)
            # Fault hooks resolve at CONSTRUCTION (r11): the stack must
            # build with the plane armed-quiet so the mid-run set_plan
            # swap can actually injure the live workers.
            faults.set_plan("")
            plat = LocalPlatform(workdir=f"{tmp}/on", http=True,
                                 supervise_interval=0, n_chips=2)
            try:
                assert plat.slo_engine is not None
                eng = plat.slo_engine
                inf, url, batch = build_stack(plat)

                def inst_state() -> str:
                    snap = eng.snapshot()["objectives"][0]
                    insts = snap["instances"]
                    return insts[0]["state"] if insts else "no-data"

                def budget() -> float:
                    snap = eng.snapshot()["objectives"][0]
                    insts = snap["instances"]
                    return insts[0]["budget_remaining"] if insts \
                        else 1.0

                # Healthy phase: basis + clean sweeps. The FIRST
                # served request's cold-start latency can legitimately
                # breach the objective (that is the plane working, not
                # a bug) — keep serving fast traffic until the
                # instance settles ok (the fast window ages the blip
                # out) instead of asserting the very first reading.
                deadline = time.monotonic() + 90
                while True:
                    tick(url, batch, plat)
                    if inst_state() == "ok" and eng.epoch > 3:
                        break
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"SLO never settled healthy: "
                            f"{eng.snapshot()}")
                    time.sleep(0.2)
                record["budget_healthy"] = budget()

                # Injury: every worker dispatch sleeps 600 ms — every
                # /predict breaches the 250 ms threshold.
                faults.set_plan("worker.slow:p=1,ms=600")
                t_injured = time.monotonic()
                deadline = time.monotonic() + 90
                while inst_state() != "firing":
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"SLO never fired: {eng.snapshot()}")
                    tick(url, batch, plat)
                    time.sleep(0.1)
                record["time_to_fire_s"] = round(
                    time.monotonic() - t_injured, 2)
                record["budget_firing"] = budget()
                # <= not <: a cold-start breach inside the 60 s budget
                # window may have floored the healthy-phase gauge to 0
                # already (the state machine, not the floor-clamped
                # gauge, is the healthy/firing evidence).
                assert record["budget_firing"] <= \
                    record["budget_healthy"]

                # The firing alert is scale-up pressure: keep sweeping
                # until the autoscaler acts (reason slo_firing; the
                # free second chip absorbs the replica).
                deadline = time.monotonic() + 60

                def slo_scale_ups() -> int:
                    c = registry().find(
                        "rafiki_tpu_autoscale_actions_total")
                    return int(c.value(action="scale_up",
                                       reason="slo_firing")) \
                        if c is not None else 0

                while slo_scale_ups() < 1:
                    if time.monotonic() > deadline:
                        snap = plat.admin.get_autoscale()
                        raise RuntimeError(
                            f"no SLO-triggered scale-up: {snap}")
                    tick(url, batch, plat)
                    time.sleep(0.1)
                record["slo_scale_up_actions"] = slo_scale_ups()
                record["replicas_after_scale_up"] = len(
                    plat.services.active_inference_workers(inf["id"]))
                # The action must have ACTUATED — a launched replica
                # that immediately dies (e.g. a chip index past the
                # real device count: on CPU run with
                # XLA_FLAGS=--xla_force_host_platform_device_count=8,
                # like multitenant) would make this evidence hollow.
                assert record["replicas_after_scale_up"] >= 2, record
                record["autoscale_decisions"] = [
                    {k: d.get(k) for k in
                     ("epoch", "action", "reason", "bin", "target",
                      "applied", "error", "service_id")
                     if k in d}
                    for d in plat.admin.get_autoscale()["decisions"]
                    [:8]]

                # Recovery: clear the plan; the fast window drains and
                # the alert resolves after resolve_s of quiet.
                faults.set_plan(None)
                t_cleared = time.monotonic()
                deadline = time.monotonic() + 90
                while inst_state() != "ok":
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"SLO never resolved: {eng.snapshot()}")
                    tick(url, batch, plat)
                    time.sleep(0.2)
                record["time_to_resolve_s"] = round(
                    time.monotonic() - t_cleared, 2)
                record["budget_resolved"] = budget()

                alerts = plat.admin.get_alerts()["alerts"]
                record["alert_ring"] = [
                    {k: a.get(k) for k in
                     ("transition", "burn_fast", "burn_slow",
                      "budget_remaining")}
                    for a in alerts[::-1]]  # oldest first
                transitions = [a["transition"] for a in alerts[::-1]]
                assert "firing" in transitions and \
                    "resolved" in transitions, transitions
                c = registry().find("rafiki_tpu_slo_alerts_total")
                record["alerts_total"] = {
                    lab["state"]: int(v) for lab, v in c.samples()}
                plat.admin.stop_inference_job(inf["id"])
            finally:
                plat.shutdown()
    finally:
        faults.set_plan(None)
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    return _emit(
        "slo_time_to_fire_s", record["time_to_fire_s"], "seconds",
        rules=rules,
        time_to_resolve_s=record["time_to_resolve_s"],
        budget_healthy=record["budget_healthy"],
        budget_firing=record["budget_firing"],
        budget_resolved=record["budget_resolved"],
        slo_scale_up_actions=record["slo_scale_up_actions"],
        replicas_after_scale_up=record["replicas_after_scale_up"],
        autoscale_decisions=record.get("autoscale_decisions", []),
        alerts_total=record["alerts_total"],
        alert_ring=record["alert_ring"],
        off_slo_series=record["off_slo_series"])


def main_replay() -> dict:
    """Config[replay]: the trace-replay capacity engine, closed loop
    (docs/capacity.md). Not a sweep member — it records its OWN serving
    stack's workload and judges the simulator against it.

    Act 1, the recorder gate: the OFF side runs FIRST — a platform
    without ``RAFIKI_TPU_WORKLOAD_RECORD`` serves real traffic and is
    asserted to expose ZERO ``rafiki_tpu_workload_*`` series and to
    write no ``workload.jsonl`` (the resolve-once gates are reset
    between sides through the same seam the unit tests use, so the
    process registry cannot have been fed by the later ON side).

    Act 2, calibration: the ON side arms the recorder AND the serving
    attribution ledger, serves a short paced ramp (client think time
    keeps the single replica below saturation — an open-loop replay
    of a saturated closed loop amplifies the queueing tail), and the
    recorded trace replays against a fleet model FIT from the live
    exposition's per-bin device-seconds histogram, replicas pinned
    (the live side runs no autoscaler). The headline is sim p50 /
    live p50 (the p99 ratio rides along as a finding — an i.i.d.
    redraw of the fit recurs one-off live stalls through the sim's
    tail): the simulator is a policy RANKER, not a latency oracle
    (docs/capacity.md spells out what is modeled), so the gate is a
    generous band, not equality.

    Act 3, the predictive A/B (pure simulation, deterministic): the
    canned ramp trace against a slow-provisioning fleet, reactive vs
    predictive with the periodicity table learned from the trace
    itself. The predictive side must apply >= 1 ``scale_up:predicted``
    and reject STRICTLY fewer — the same strictly-fewer-429s
    discipline the autoscale config judges the live loop on.
    """
    import tempfile
    import threading

    import requests

    from rafiki_tpu.admin import capacity
    from rafiki_tpu.admin.autoscaler import PolicyKnobs
    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.config import NodeConfig
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.observe import attribution, replay, workload
    from rafiki_tpu.observe.metrics import registry
    from rafiki_tpu.platform import LocalPlatform

    phases = [(2, 4.0), (4, 6.0)]  # (clients, seconds)
    batch_n = 4
    knob_env = {
        NodeConfig.env_name("serving_queue_cap"): "32",
        NodeConfig.env_name("serving_max_batch"): "8",
        NodeConfig.env_name("serving_max_inflight"): "1",
    }
    rec_env = {workload.WORKLOAD_ENV: "1",
               attribution.ATTRIBUTION_ENV: "1"}

    def workload_series() -> int:
        m = registry().find("rafiki_tpu_workload_requests_total")
        return len(m.samples()) if m is not None else 0

    def reset_gates() -> None:
        workload.reset_for_tests()
        attribution.reset_for_tests()

    def build(plat):
        admin = plat.admin
        u = admin.create_user("cap@x.c", "pw",
                              UserType.MODEL_DEVELOPER)
        mdl = admin.create_model(
            u["id"], "ff-cap", TaskType.IMAGE_CLASSIFICATION,
            "rafiki_tpu.models.feedforward:JaxFeedForward")
        job = admin.create_train_job(
            u["id"], "cap", TaskType.IMAGE_CLASSIFICATION,
            [mdl["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
            build.train_path, build.val_path)
        assert admin.wait_until_train_job_done(job["id"], timeout=1200)
        inf = admin.create_inference_job(u["id"], job["id"],
                                         max_models=1)
        cache = Cache(plat.bus)
        deadline = time.time() + 600
        while not cache.running_workers(inf["id"]) and \
                time.time() < deadline:
            time.sleep(0.5)
        assert cache.running_workers(inf["id"])
        host = admin.get_inference_job(inf["id"])["predictor_host"]
        val = load_image_dataset(build.val_path)
        batch = [encode_payload(val.images[i]) for i in range(batch_n)]
        url = f"http://{host}/predict"
        requests.post(url, json={"queries": batch},
                      timeout=300).raise_for_status()
        return inf, host, url, batch

    def ramp(url, batch, counts):
        # main_autoscale's load shape, shortened: per-client count
        # slots, folded after join (lost-update-free).
        for n_clients, dur in phases:
            stop = threading.Event()
            errors: list = []
            rejected = [0] * n_clients
            served = [0] * n_clients

            def client(i: int) -> None:
                session = requests.Session()
                try:
                    while not stop.is_set():
                        r = session.post(url, json={"queries": batch},
                                         timeout=300)
                        if r.status_code == 429:
                            rejected[i] += 1
                            time.sleep(0.05)
                        else:
                            r.raise_for_status()
                            served[i] += 1
                            # Think time paces the loop below the
                            # single replica's capacity. Zero-think
                            # closed loops run at utilization ~1, and
                            # an OPEN-loop replay of a saturated
                            # trace amplifies the queueing tail into
                            # numbers the live (self-throttling) side
                            # never saw — the calibration band only
                            # means something at rho < 1.
                            time.sleep(0.03)
                except Exception as e:  # surfaced by the caller
                    errors.append(e)
                    stop.set()

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n_clients)]
            for t in threads:
                t.start()
            time.sleep(dur)
            stop.set()
            for t in threads:
                t.join()
            if errors:
                raise RuntimeError(f"ramp client failed: {errors[0]}")
            counts["429"] += sum(rejected)
            counts["served"] += sum(served)

    record: dict = {}
    prior = {k: os.environ.get(k) for k in
             list(knob_env) + list(rec_env)}
    os.environ.update(knob_env)
    try:
        with tempfile.TemporaryDirectory() as tmp:
            build.train_path, build.val_path = \
                make_synthetic_image_dataset_compat(tmp, n_train=2048,
                                                    n_val=256)

            # --- OFF side (runs FIRST: the zero-series gate) ---------
            for k in rec_env:
                os.environ.pop(k, None)
            reset_gates()
            plat = LocalPlatform(workdir=f"{tmp}/off", http=True,
                                 supervise_interval=0)
            try:
                inf, host, url, batch = build(plat)
                for _ in range(8):
                    requests.post(url, json={"queries": batch},
                                  timeout=300).raise_for_status()
                assert not workload.active()
                record["off_workload_series"] = workload_series()
                assert record["off_workload_series"] == 0
                off_store = workload.workload_path(
                    plat.services.log_dir)
                assert not os.path.exists(off_store), off_store
                plat.admin.stop_inference_job(inf["id"])
            finally:
                plat.shutdown()

            # --- ON side: record, then replay what was recorded ------
            os.environ.update(rec_env)
            reset_gates()
            plat = LocalPlatform(workdir=f"{tmp}/on", http=True,
                                 supervise_interval=0)
            try:
                assert workload.active()
                inf, host, url, batch = build(plat)
                stats = requests.get(f"http://{host}/stats",
                                     timeout=30).json()
                before = _http_predict_buckets(host,
                                               stats["http_service"])
                side = {"429": 0, "served": 0}
                ramp(url, batch, side)
                record["live_429"] = side["429"]
                record["live_served"] = side["served"]
                live_p = _bucket_delta_percentiles_ms(
                    before,
                    _http_predict_buckets(host, stats["http_service"]),
                    qs=(0.5, 0.99))
                assert live_p is not None
                record["live_ms_p50_p99"] = live_p
                m = registry().find("rafiki_tpu_workload_requests_total")
                record["on_workload_total"] = \
                    int(sum(v for _, v in m.samples())) if m else 0
                exposition = requests.get(f"http://{host}/metrics",
                                          timeout=30).text
                trace = workload.load(plat.services.log_dir)
                plat.admin.stop_inference_job(inf["id"])
            finally:
                plat.shutdown()
    finally:
        reset_gates()
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # The recorder captured the ramp line for line: the trace IS the
    # counter total (one store segment, no roll at this volume).
    assert trace, "recorder wrote no workload records"
    record["trace_records"] = len(trace)
    assert record["trace_records"] == record["on_workload_total"], \
        (record["trace_records"], record["on_workload_total"])

    # --- Calibration: the recorded trace vs the live p99 -------------
    # Two fits, two jobs. The trace fit (edge-measured compute_ms) is
    # what the live p99 is judged against: it carries the scatter/
    # gather + HTTP overhead the edge actually pays. The ledger fit
    # (device-kernel histogram) is recorded alongside as the honest
    # kernel-vs-edge gap — the attribution path must WORK (non-None),
    # but its ratio is a finding, not a gate.
    #
    # build()'s single warmup post pays the one-time serving compile;
    # the live percentiles are bucket DELTAS snapshotted after it, so
    # the warmup sits outside the live population. Drop its record
    # (the earliest arrival) before fitting/replaying: the i.i.d.
    # service redraw would otherwise recur the compile stall all
    # through the open-loop replay and judge the fit on a tail the
    # live side was never measured on.
    trace = trace[1:]
    sim_kn = replay.SimKnobs(queue_cap=32.0, max_batch=8)
    pinned = PolicyKnobs(max_replicas=1)  # pinned, like the stack
    fleet = replay.FleetModel.from_trace(trace)
    assert fleet is not None, "trace carries no served compute samples"
    sim_report = replay.simulate(trace, fleet=fleet, sim=sim_kn,
                                 policy=pinned)
    sim_p50 = sim_report["latency_ms"]["p50"]
    sim_p99 = sim_report["latency_ms"]["p99"]
    live_p50, live_p99 = live_p
    assert sim_p50 and live_p50, (sim_p50, live_p50)
    ratio = round(sim_p50 / live_p50, 3)
    record["sim_live_p99_ratio"] = \
        round(sim_p99 / live_p99, 3) if live_p99 else None
    record["sim_ms_p50_p99"] = [sim_p50, sim_p99]
    record["sim_rejected"] = sim_report["rejected"]
    ledger_fleet = replay.FleetModel.from_exposition(exposition)
    assert ledger_fleet is not None, \
        "attribution ledger exposed no device-seconds buckets to fit"
    record["fleet_bins"] = [b.name for b in ledger_fleet.bins]
    ledger_p99 = replay.simulate(
        trace, fleet=ledger_fleet, sim=sim_kn,
        policy=pinned)["latency_ms"]["p99"]
    record["ledger_sim_p99_ratio"] = \
        round(ledger_p99 / live_p99, 3) if ledger_p99 else None
    # The fidelity claim docs/capacity.md makes: same order of
    # magnitude AT THE MEDIAN, not equality. The gate deliberately
    # sits at p50: the empirical fit redraws service times i.i.d.,
    # so a one-off mid-ramp stall (a fused-shape compile, say) that
    # delayed ONE live request — below the live p99 rank — recurs
    # throughout the replay and lands above the sim's p99 rank far
    # more often than not. The tail ratio is still recorded
    # (sim_live_p99_ratio) as the honest finding it is.
    assert 1 / 3 <= ratio <= 3.0, (sim_p50, live_p50)

    # --- Predictive A/B (simulated, deterministic) --------------------
    ab_trace = capacity.canned_trace("ramp")
    table = capacity.learn_periodicity(ab_trace, period_s=120.0,
                                       bin_s=10.0)
    ab_sim = replay.SimKnobs(provision_delay_s=6.0, queue_cap=48.0)
    reactive = replay.simulate(ab_trace, sim=ab_sim,
                               policy=PolicyKnobs(),
                               periodicity=table)
    predictive = replay.simulate(
        ab_trace, sim=ab_sim,
        policy=PolicyKnobs(predict_horizon_s=15.0),
        periodicity=table)
    pred_ups = predictive["actions"].get("scale_up:predicted", 0)
    assert pred_ups >= 1, predictive["actions"]
    assert predictive["rejected"] < reactive["rejected"], \
        (predictive["rejected"], reactive["rejected"])

    return _emit(
        "replay_sim_live_p50_ratio", ratio, "ratio",
        ramp_phases=[{"clients": c, "seconds": s} for c, s in phases],
        queries_per_request=batch_n,
        live_ms_p50_p99=record["live_ms_p50_p99"],
        sim_ms_p50_p99=record["sim_ms_p50_p99"],
        sim_live_p99_ratio=record["sim_live_p99_ratio"],
        live_served=record["live_served"],
        live_429=record["live_429"],
        sim_rejected=record["sim_rejected"],
        ledger_sim_p99_ratio=record["ledger_sim_p99_ratio"],
        trace_records=record["trace_records"],
        fleet_bins=record["fleet_bins"],
        off_workload_series=record["off_workload_series"],
        ab_rejected_reactive=reactive["rejected"],
        ab_rejected_predictive=predictive["rejected"],
        ab_predicted_scale_ups=pred_ups,
        ab_actions_reactive=reactive["actions"],
        ab_actions_predictive=predictive["actions"])


def main_cluster() -> dict:
    """Config[cluster]: the cluster serving fabric, counter-judged
    (docs/cluster.md). Never joins the sweep — it is a topology + A/B
    gate, not a throughput figure. Three phases, strict order (the
    zero-series assertion must run before any phase registers cluster
    series):

    - **OFF baseline** (zero-series contract): fabric disabled, two
      frontends each recompute every unique key themselves — cluster
      recompute == frontends x uniques, and NO node/relay/fabric
      series exist in the registry.
    - **Relay**: two peered per-node brokers; a remote-node sharded
      scatter pays exactly ONE inter-node hop per leg (the
      ``rafiki_tpu_bus_relay_total{direction="out"}`` delta is 1 for
      the query leg and 1 for the reply leg), and a dead peer degrades
      to the local-fallback path without wedging the sender.
    - **ON**: the same workload with the fabric armed — every unique
      key is computed ONCE cluster-wide (the second frontend's misses
      convert to peer hits), and a promote-path invalidation on one
      frontend gossips to the other, whose next query provably MISSES
      and rescatters.

    Headline: recompute_off / recompute_on (2.0 for two frontends =
    the fabric halved duplicate chip-seconds).
    """
    import threading
    import urllib.request

    import requests

    from rafiki_tpu.bus import connect, serve_broker
    from rafiki_tpu.bus.memory import MemoryBus
    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.observe.metrics import registry
    from rafiki_tpu.predictor.app import PredictorService

    fabric_env = "RAFIKI_TPU_CLUSTER_FABRIC"
    saved_env = os.environ.pop(fabric_env, None)
    uniques = 8
    hot_tail = 6  # extra queries of the hottest key per frontend

    def start_worker(cache: Cache, worker_id: str, served: dict,
                     stop: threading.Event) -> threading.Thread:
        def loop() -> None:
            while not stop.is_set():
                for it in cache.pop_queries(worker_id, timeout=0.1):
                    n = len(it["queries"])
                    served["n"] += n
                    cache.send_prediction_batch(
                        it["batch_id"], worker_id, [[0.8, 0.2]] * n,
                        shard=it.get("shard"), compute_s=0.001 * n,
                        origin_node=it.get("onode"))
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    def make_frontend(bus, sid: str, job: str) -> PredictorService:
        svc = PredictorService(sid, job, meta=None, bus=bus,
                               host="127.0.0.1", cache_bytes=1 << 20,
                               cache_admit_after=1, microbatch=False)
        svc.predictor.worker_wait_timeout = 10.0
        svc.predictor.gather_timeout = 10.0
        svc._http.start()
        if svc._fabric:  # what start() would do, minus the meta store
            svc.predictor.cache.register_frontend(
                job, svc.stats.service, f"127.0.0.1:{svc.port}")
        return svc

    def stop_frontend(svc: PredictorService, job: str) -> None:
        # Manual teardown (stop() updates the meta store we don't have).
        if svc._fabric:
            svc.predictor.cache.unregister_frontend(
                job, svc.stats.service)
        svc._http.stop()
        svc.stats.close()
        svc.predictor.close()
        svc.edge_cache.close()
        if svc._m_fabric is not None:
            svc._m_fabric.remove(service=svc.stats.service)

    def post(svc: PredictorService, path: str, payload: dict) -> dict:
        r = requests.post(f"http://127.0.0.1:{svc.port}{path}",
                          json=payload, timeout=30)
        r.raise_for_status()
        return r.json()

    def fabric_events(svc: PredictorService) -> dict:
        c = registry().find("rafiki_tpu_serving_fabric_total")
        if c is None:
            return {}
        return {lab["event"]: int(v) for lab, v in c.samples()
                if lab.get("service") == svc.stats.service}

    def run_workload(frontends, keys) -> None:
        # Every frontend sees every key once (frontend-major, so the
        # second frontend's first touch is always a fabric-probe
        # opportunity), then a hot tail on the hottest key — the
        # zipf head that dominates real serving traffic.
        for svc in frontends:
            for q in keys:
                post(svc, "/predict", {"query": q})
        for svc in frontends:
            for _ in range(hot_tail):
                post(svc, "/predict", {"query": keys[0]})

    keys = [encode_payload([float(r), 1.0 + float(r)])
            for r in range(uniques)]
    record: dict = {}
    try:
        # --- Phase OFF: zero-series contract + per-frontend recompute
        for name in ("rafiki_tpu_serving_fabric_total",
                     "rafiki_tpu_bus_relay_total",
                     "rafiki_tpu_node_peers"):
            if registry().find(name) is not None:
                raise RuntimeError(
                    f"{name} exists before any cluster phase ran — "
                    "the fabric-off zero-series contract is broken")
        bus = MemoryBus()
        wcache = Cache(bus)
        served = {"n": 0}
        stop = threading.Event()
        wcache.register_worker("job-off", "w-off",
                               info={"trial_id": "t", "score": 0.9})
        wt = start_worker(wcache, "w-off", served, stop)
        fa = fb = None
        try:
            fa = make_frontend(bus, "cfa-off", "job-off")
            fb = make_frontend(bus, "cfb-off", "job-off")
            assert not fa._fabric and not fb._fabric
            run_workload([fa, fb], keys)
            recompute_off = served["n"]
        finally:
            for svc in (fa, fb):
                if svc is not None:
                    stop_frontend(svc, "job-off")
            stop.set()
            wt.join(timeout=5)
        if recompute_off != 2 * uniques:
            raise RuntimeError(
                f"fabric-off recompute {recompute_off} != frontends x "
                f"uniques {2 * uniques} — the baseline is not the "
                "per-frontend-duplicate shape the A/B assumes")
        if registry().find("rafiki_tpu_serving_fabric_total") is not None:
            raise RuntimeError("fabric-off frontends registered the "
                               "fabric series (zero-series contract)")

        # --- Phase Relay: one inter-node hop per leg ------------------
        broker_a = serve_broker("127.0.0.1", 0, native=False,
                                node_id="vm/a")
        broker_b = serve_broker("127.0.0.1", 0, native=False,
                                node_id="vm/b")
        try:
            broker_a.add_peer("vm/b", broker_b.uri)
            broker_b.add_peer("vm/a", broker_a.uri)
            bus_a, bus_b = connect(broker_a.uri), connect(broker_b.uri)
            cache_a, cache_b = Cache(bus_a), Cache(bus_b)
            rserved = {"n": 0}
            rstop = threading.Event()
            cache_b.register_worker("job-r", "wb",
                                    info={"trial_id": "t", "score": 0.9})
            rt = start_worker(cache_b, "wb", rserved, rstop)
            relay = registry().find("rafiki_tpu_bus_relay_total")
            if relay is None:
                raise RuntimeError("node-scoped brokers registered no "
                                   "relay series")

            def relay_counts() -> dict:
                return {lab["direction"]: int(v)
                        for lab, v in relay.samples()}

            base = relay_counts()
            bid = cache_a.send_query_shards(
                [("wb", 0, 1, 0)], [keys[0]],
                worker_nodes={"wb": "vm/b"}, local_node="vm/a")
            t0 = time.monotonic()
            while relay_counts().get("out", 0) - base.get("out", 0) < 1:
                if time.monotonic() - t0 > 10:
                    raise RuntimeError("query leg never relayed")
                time.sleep(0.01)
            after_query = relay_counts()
            replies = cache_a.gather_prediction_batches(bid, 1,
                                                        timeout=10.0)
            after_reply = relay_counts()
            query_hops = (after_query.get("out", 0) - base.get("out", 0))
            total_hops = (after_reply.get("out", 0) - base.get("out", 0))
            if query_hops != 1 or total_hops != 2:
                raise RuntimeError(
                    f"remote scatter paid {query_hops} query-leg and "
                    f"{total_hops - query_hops} reply-leg hops; the "
                    "relay contract is exactly one per leg "
                    f"(counts {base} -> {after_reply})")
            if after_reply.get("fallback", 0):
                raise RuntimeError("healthy-peer relay took the "
                                   "fallback path")
            if len(replies) != 1 or rserved["n"] != 1:
                raise RuntimeError(
                    f"remote scatter served {rserved['n']} and "
                    f"gathered {len(replies)} replies, expected 1/1")
            # Dead peer: the forward degrades to the LOCAL broker
            # without wedging the sender.
            rstop.set()
            rt.join(timeout=5)
            broker_b.stop()
            t0 = time.monotonic()
            bus_a.relay_push("vm/b", "dead-q", {"v": 42})
            dead_elapsed = time.monotonic() - t0
            fb_delta = (relay_counts().get("fallback", 0)
                        - after_reply.get("fallback", 0))
            landed = bus_a.pop("dead-q", timeout=2.0)
            if fb_delta != 1 or landed != {"v": 42}:
                raise RuntimeError(
                    f"dead-peer relay: fallback delta {fb_delta}, "
                    f"local delivery {landed!r} — expected 1 and the "
                    "pushed frame")
            relay_record = {
                "relay_out": after_reply.get("out", 0),
                "relay_in": after_reply.get("in", 0),
                "relay_fallback_after_death": fb_delta,
                "dead_peer_send_s": round(dead_elapsed, 3),
            }
        finally:
            broker_b.stop()
            broker_a.stop()

        # --- Phase ON: fabric A/B over the same workload --------------
        os.environ[fabric_env] = "1"
        os.environ["RAFIKI_TPU_CLUSTER_PROBE_TIMEOUT_S"] = "2.0"
        bus2 = MemoryBus()
        wcache2 = Cache(bus2)
        served2 = {"n": 0}
        stop2 = threading.Event()
        wcache2.register_worker("job-on", "w-on",
                                info={"trial_id": "t", "score": 0.9})
        wt2 = start_worker(wcache2, "w-on", served2, stop2)
        ga = gb = None
        try:
            ga = make_frontend(bus2, "cfa-on", "job-on")
            gb = make_frontend(bus2, "cfb-on", "job-on")
            assert ga._fabric and gb._fabric
            run_workload([ga, gb], keys)
            recompute_on = served2["n"]
            ev_a, ev_b = fabric_events(ga), fabric_events(gb)
            peer_hits = ev_a.get("peer_hit", 0) + ev_b.get("peer_hit", 0)
            if recompute_on >= 2 * uniques:
                raise RuntimeError(
                    f"fabric-on recompute {recompute_on} is not below "
                    f"frontends x uniques {2 * uniques}")
            if recompute_on != uniques:
                raise RuntimeError(
                    f"fabric-on recompute {recompute_on} != uniques "
                    f"{uniques}: each key must be computed once "
                    f"cluster-wide (events A={ev_a} B={ev_b})")
            if peer_hits < uniques:
                raise RuntimeError(
                    f"only {peer_hits} peer hits for {uniques} uniques "
                    "x 1 extra frontend — the second frontend did not "
                    f"serve from its peer (A={ev_a} B={ev_b})")
            # Promote-path invalidation on A gossips to B: B's next
            # query of the hottest key must MISS and rescatter.
            epoch_b = gb.edge_cache.epoch
            post(ga, "/cache/invalidate", {})
            t0 = time.monotonic()
            while gb.edge_cache.epoch <= epoch_b:
                if time.monotonic() - t0 > 5:
                    raise RuntimeError("gossiped invalidation never "
                                       "reached the peer frontend")
                time.sleep(0.01)
            before = served2["n"]
            post(gb, "/predict", {"query": keys[0]})
            if served2["n"] != before + 1:
                raise RuntimeError(
                    "promote-then-query on the non-promoting frontend "
                    f"did not rescatter (served {served2['n']} vs "
                    f"{before} + 1) — a stale entry survived the "
                    "gossiped invalidation")
            ev_a, ev_b = fabric_events(ga), fabric_events(gb)
            if not ev_a.get("gossip_sent") or not ev_b.get("gossip_recv"):
                raise RuntimeError(
                    f"invalidation gossip not counter-proven: A={ev_a} "
                    f"B={ev_b}")
            record = {
                "recompute_off": recompute_off,
                "recompute_on": recompute_on,
                "uniques": uniques,
                "frontends": 2,
                "peer_hits": peer_hits,
                "fabric_events_a": ev_a,
                "fabric_events_b": ev_b,
                **relay_record,
            }
        finally:
            for svc in (ga, gb):
                if svc is not None:
                    stop_frontend(svc, "job-on")
            stop2.set()
            wt2.join(timeout=5)
    finally:
        if saved_env is None:
            os.environ.pop(fabric_env, None)
        else:
            os.environ[fabric_env] = saved_env
        os.environ.pop("RAFIKI_TPU_CLUSTER_PROBE_TIMEOUT_S", None)

    return _emit("cluster_fabric_recompute_ratio",
                 record["recompute_off"] / record["recompute_on"],
                 "ratio", **record)


def make_synthetic_image_dataset_compat(tmp: str, n_train: int, n_val: int,
                                        image_shape=IMAGE_SHAPE):
    from rafiki_tpu.datasets import make_synthetic_image_dataset

    return make_synthetic_image_dataset(
        tmp, n_train=n_train, n_val=n_val, image_shape=image_shape,
        n_classes=N_CLASSES)


# Metric identity per config, used for the guaranteed-parseable error
# record when a config cannot run (dead TPU tunnel, missing devices, a
# crash): the driver must ALWAYS get its one JSON line and rc 0.
_CONFIGS = {
    "trials": (main, "automl_trials_per_hour", "trials/hour"),
    "serving": (main_serving, "ensemble_inference_qps", "queries/s"),
    "serving-openloop": (main_serving_openloop, "serving_openloop_qps",
                         "queries/s"),
    "serving-concurrent": (main_serving_concurrent,
                           "serving_concurrent_qps", "queries/s"),
    "multitenant": (main_multitenant, "multitenant_trials_per_hour",
                    "trials/hour"),
    "densenet": (main_densenet, "densenet_train_images_per_sec",
                 "images/s"),
    "enas": (main_enas, "enas_trials_per_hour", "trials/hour"),
    "roofline": (main_roofline, "lm_train_tokens_per_sec", "tokens/s"),
    "attention": (main_attention, "flash_attention_tflops", "TFLOP/s"),
    # Not in _SWEEP_ORDER: a gate (0 new findings), not a perf figure —
    # run explicitly via --config analysis.
    "analysis": (main_analysis, "analysis_new_findings", "findings"),
    # Not in _SWEEP_ORDER either: the chaos config injures its own
    # serving stack (seeded fault plan -> recovery loop); its value is
    # availability + time-to-full-recovery, not throughput.
    "chaos": (main_chaos, "chaos_availability", "fraction"),
    # Not in _SWEEP_ORDER: an A/B experiment that rescales its own
    # stack under a ramp (autoscaler on/off at equal initial
    # capacity); judged on counter deltas, not a throughput figure.
    "autoscale": (main_autoscale, "autoscale_backpressure_avoided",
                  "rejections"),
    # Not in _SWEEP_ORDER: the generative A/B is judged on the
    # tokens-per-dispatch counter pair (a structural batching gate),
    # not a cross-platform throughput figure.
    "lm-serving": (main_lm_serving, "lm_serving_tokens_per_sec",
                   "tokens/s"),
    # Not in _SWEEP_ORDER: the SLO config chaos-injures its own stack
    # to drive a latency objective healthy -> firing -> resolved;
    # judged on the alert ring + the SLO-triggered autoscale action.
    "slo": (main_slo, "slo_time_to_fire_s", "seconds"),
    # Not in _SWEEP_ORDER: the capacity engine's closed loop — records
    # its own stack's workload, replays it against the fitted fleet
    # model (the calibration figure), and runs the reactive-vs-
    # predictive policy A/B in simulation; judged on the calibration
    # band + strictly-fewer simulated 429s, not a throughput figure.
    "replay": (main_replay, "replay_sim_live_p50_ratio", "ratio"),
    # Not in _SWEEP_ORDER: the cluster config is a topology + A/B gate
    # (zero-series contract, exactly-one-relay-hop, fabric peer hits,
    # gossiped invalidation) judged entirely on counters — the ratio
    # headline is structural (2.0 for two frontends), not a perf figure.
    "cluster": (main_cluster, "cluster_fabric_recompute_ratio", "ratio"),
}


# Sweep execution order: cheap kernels and single-process loops first
# (they establish the headline even if a later platform-heavy config
# wedges), then the heavy roofline/attention configs, then the serving
# stacks, then multitenant (runnable on any device count since r5 —
# one chip runs it time-sliced).
_SWEEP_ORDER = ["trials", "densenet", "enas", "roofline", "attention",
                "serving", "serving-openloop", "serving-concurrent",
                "multitenant"]


def _run_config(name: str, platform: str) -> dict:
    """One config → one record, whatever happens (the driver must always
    get its JSON line; a crash in config N must not lose configs 1..N-1)."""
    import sys
    import traceback

    fn, metric, unit = _CONFIGS[name]
    t0 = time.time()
    try:
        rec = fn()
    except SystemExit as e:  # unmet precondition (devices, platform)
        if e.code in (0, None):
            raise  # a clean exit is not an unmet precondition
        rec = {"metric": metric, "value": 0.0, "unit": unit,
               "vs_baseline": None, "platform": platform,
               "error": str(e)}
    except Exception as e:
        traceback.print_exc(file=sys.stderr)
        rec = {"metric": metric, "value": 0.0, "unit": unit,
               "vs_baseline": None, "platform": platform,
               "error": f"{type(e).__name__}: {e}"}
    rec["seconds"] = round(time.time() - t0, 1)
    print(f"[bench] {name}: {rec.get('value')} {rec.get('unit')} "
          f"in {rec['seconds']}s"
          + (f" ERROR {rec['error']}" if "error" in rec else ""),
          file=sys.stderr)
    return rec


def _main_cli() -> None:
    import argparse
    import os

    global _QUANT, _QUANT_TOL, _WORKLOAD, _STACKED

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--config", default=None, choices=sorted(_CONFIGS) + ["sweep"],
        help="one config, or 'sweep' for all. Default: sweep on the "
             "accelerator, 'trials' on CPU fallback.")
    parser.add_argument(
        "--workload", default=None,
        help="serving-concurrent traffic shape: default = the uniform "
             "matrix; 'zipf[:<s>[:<keys>]]' (e.g. zipf:1.1:64) = the "
             "edge-cache + tiered-serving A/B under zipf-keyed "
             "single-query traffic.")
    parser.add_argument(
        "--quant", default=None, choices=["int8"],
        help="serving-concurrent quantized-ensemble A/B + accuracy-"
             "delta gate (f32 vs int8 on the same eval split). The "
             "process exits NON-ZERO when the gate fails, so this "
             "invocation doubles as a CI regression gate.")
    parser.add_argument(
        "--quant-tol", type=float, default=_QUANT_TOL,
        help="accuracy-delta tolerance for --quant (|acc_f32 - "
             "acc_int8| must not exceed it; default %(default)s).")
    parser.add_argument(
        "--stacked", action="store_true",
        help="serving-concurrent stacked-ensemble A/B: ONE packed "
             "worker serves a 2-member bin vmap-stacked (one device "
             "dispatch per burst) vs per-member; counter-gated "
             "(stacked dispatches up, off side zero stacked series).")
    parser.add_argument(
        "--devices", type=int, default=None,
        help="force this many (virtual, on CPU fallback) devices — "
             "the multichip channel's knob (e.g. 8 for the "
             "MULTICHIP record).")
    args = parser.parse_args()
    if args.stacked:
        if args.config != "serving-concurrent":
            parser.error("--stacked only applies to "
                         "--config serving-concurrent")
        if args.quant is not None or args.workload is not None:
            parser.error("--stacked, --quant and --workload are "
                         "separate experiments; pick one")
        _STACKED = True
    if args.quant is not None:
        if args.config != "serving-concurrent":
            parser.error("--quant only applies to "
                         "--config serving-concurrent")
        if args.workload is not None:
            parser.error("--quant and --workload are separate "
                         "experiments; pick one")
        _QUANT = args.quant
        _QUANT_TOL = args.quant_tol
    if args.workload is not None:
        if not args.workload.startswith("zipf"):
            parser.error(f"unknown --workload {args.workload!r} "
                         f"(expected zipf[:<s>[:<keys>]])")
        if args.config != "serving-concurrent":
            # The zipf A/B needs serving-concurrent's device
            # provisioning (4 virtual devices below); silently riding
            # a sweep would hang the 2-bin deploys AND replace the
            # sweep's serving baseline with a different experiment.
            parser.error("--workload only applies to "
                         "--config serving-concurrent")
        _WORKLOAD = args.workload

    # Resolve the platform BEFORE any backend touch. The site hook
    # latches jax_platforms to the accelerator regardless of
    # JAX_PLATFORMS=cpu, and a dead tunnel hangs backend init — so this
    # probes with a deadline and degrades to CPU (round-1 BENCH artifact
    # was rc 1 for exactly this reason).
    try:
        from rafiki_tpu.jaxenv import ensure_platform

        # ensure_platform runs for its probe/config side effect; the
        # records name the backend jax actually reports ("tpu", not the
        # plugin name "axon") so error records match success records.
        # serving-concurrent's replica-sharding A/B needs each replica
        # on its OWN device (co-owners of one chip serialize on its
        # queue — sharding there measures pure overhead), so a CPU
        # fallback for that config gets 2 virtual devices (no-op when
        # the accelerator serves, or when XLA_FLAGS already pins one);
        # the zipf workload variant deploys TWO 2-bin jobs (cache+tier
        # on vs off) and only the first group of a deploy may
        # time-slice, so it needs 4.
        # chaos needs allocation headroom for 2 replica bins PLUS a
        # respawn while the just-finished train worker may still hold
        # its chip — on a 1-device box the second bin would never
        # launch and the recovery loop would have nothing to restore.
        # autoscale gets exactly 4: 2 serving bins + 2 donor train
        # workers at exclusive placement = ZERO free chips, so the
        # FIRST starved scale-up preempts the idle donor (the judged
        # causal chain, with minimal mid-ramp compile churn).
        # slo needs the 2-chip node's SECOND chip actually backed by a
        # device: the SLO-triggered scale-up's replica lands there, and
        # on a 1-device box its mesh build would die on a chip index
        # past the real device count (hollow evidence).
        ensure_platform(n_virtual_devices=(
            args.devices if args.devices
            else (4 if _WORKLOAD else 2)
            if args.config == "serving-concurrent"
            else 3 if args.config == "chaos"
            else 4 if args.config == "autoscale"
            else 2 if args.config == "slo" else None))
        import jax

        platform = jax.default_backend()
    except Exception:
        platform = "unknown"

    config = args.config
    if config is None:
        config = "sweep" if platform in BASELINE_PLATFORMS else "trials"

    if config != "sweep":
        rec = _run_config(config, platform)
        print(json.dumps(rec))
        if _QUANT and rec.get("accuracy_gate") != "pass":
            # The one JSON line is printed either way; the exit code is
            # the gate (a --quant run that errored never proved the
            # accuracy contract, so it fails too).
            import sys

            sys.exit(1)
        return

    # Full sweep: ONE line, headline = config 1 (trials/hour), every
    # config's record under "configs". RAFIKI_TPU_BENCH_CONFIGS can
    # subset (comma-separated) when a manual run wants fewer. A mistyped
    # or effectively-empty subset must not cost the JSON line: unknown
    # names are reported and skipped, an empty result falls back to the
    # full order.
    import sys

    subset = os.environ.get("RAFIKI_TPU_BENCH_CONFIGS", "").strip()
    names = [n.strip() for n in subset.split(",") if n.strip()]
    unknown = [n for n in names if n not in _CONFIGS]
    if unknown:
        print(f"[bench] ignoring unknown config name(s) {unknown} in "
              f"RAFIKI_TPU_BENCH_CONFIGS (valid: {sorted(_CONFIGS)})",
              file=sys.stderr)
    names = [n for n in names if n in _CONFIGS] or _SWEEP_ORDER
    configs = {}
    for i, name in enumerate(names):
        # Idle gate between configs (not before the first): the prior
        # config's teardown tail must not depress this one's windows.
        busy = _idle_gate() if i else round(_host_busy_fraction(), 3)
        configs[name] = _run_config(name, platform)
        configs[name]["host_busy_at_start"] = busy
    headline = configs.get("trials") or next(iter(configs.values()))
    print(json.dumps({**headline, "sweep": True, "configs": configs}))


if __name__ == "__main__":
    _main_cli()
