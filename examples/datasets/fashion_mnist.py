"""Prepare fashion-MNIST in the platform dataset format.

Parity: SURVEY.md §2 "Dataset prep scripts". With ``--raw-dir`` pointing
at the standard IDX files (what the upstream script downloads), converts
them; with ``--synthetic``, writes a shape-identical synthetic stand-in
(this environment has no network).

    python examples/datasets/fashion_mnist.py --out-dir data/ --synthetic
    python examples/datasets/fashion_mnist.py --out-dir data/ \
        --raw-dir ~/downloads/fashion-mnist/
"""

import argparse


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out-dir", required=True)
    p.add_argument("--raw-dir", help="directory with the IDX ubyte files")
    p.add_argument("--synthetic", action="store_true",
                   help="generate a synthetic stand-in instead")
    args = p.parse_args()

    if args.synthetic:
        from rafiki_tpu.datasets import make_synthetic_image_dataset
        train, val = make_synthetic_image_dataset(
            args.out_dir, n_train=8192, n_val=1024,
            image_shape=(28, 28, 1), n_classes=10, name="fashion_mnist")
    else:
        if not args.raw_dir:
            raise SystemExit("--raw-dir or --synthetic is required")
        from rafiki_tpu.datasets import prepare_fashion_mnist
        train, val = prepare_fashion_mnist(args.raw_dir, args.out_dir)
    print("train:", train)
    print("val:  ", val)


if __name__ == "__main__":
    main()
