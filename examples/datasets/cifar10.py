"""Prepare CIFAR-10 in the platform dataset format.

Parity: SURVEY.md §2 "Dataset prep scripts". With ``--raw-dir`` pointing
at ``cifar-10-batches-py`` (what the upstream script downloads), converts
it; with ``--synthetic``, writes a shape-identical synthetic stand-in.

    python examples/datasets/cifar10.py --out-dir data/ --synthetic
    python examples/datasets/cifar10.py --out-dir data/ \
        --raw-dir ~/downloads/cifar10/
"""

import argparse


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out-dir", required=True)
    p.add_argument("--raw-dir", help="directory holding cifar-10-batches-py")
    p.add_argument("--synthetic", action="store_true",
                   help="generate a synthetic stand-in instead")
    args = p.parse_args()

    if args.synthetic:
        from rafiki_tpu.datasets import make_synthetic_image_dataset
        train, val = make_synthetic_image_dataset(
            args.out_dir, n_train=8192, n_val=1024,
            image_shape=(32, 32, 3), n_classes=10, name="cifar10")
    else:
        if not args.raw_dir:
            raise SystemExit("--raw-dir or --synthetic is required")
        from rafiki_tpu.datasets import prepare_cifar10
        train, val = prepare_cifar10(args.raw_dir, args.out_dir)
    print("train:", train)
    print("val:  ", val)


if __name__ == "__main__":
    main()
