"""Quickstart: the full app-developer flow through the Client SDK.

Parity: SURVEY.md §2 "Quickstart scripts" / §3.1-§3.3 — the upstream
quickstart creates a user, uploads a model, runs a train job, deploys an
inference job, and queries the predictor. Same flow here.

Run against a live Admin:

    python examples/scripts/quickstart.py --train data/x_train.npz \
        --val data/x_val.npz --admin-host 127.0.0.1 --admin-port 3000

Or fully self-contained (starts an in-process platform and uses a
synthetic dataset):

    python examples/scripts/quickstart.py --local --synthetic
"""

import argparse
import tempfile

import numpy as np

FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--admin-host", default="127.0.0.1")
    p.add_argument("--admin-port", type=int, default=3000)
    p.add_argument("--local", action="store_true",
                   help="start an in-process platform (no external admin)")
    p.add_argument("--synthetic", action="store_true",
                   help="use a synthetic fashion-MNIST-shaped dataset")
    p.add_argument("--train", help="train dataset path (.npz/.zip)")
    p.add_argument("--val", help="validation dataset path")
    p.add_argument("--model-class", default=FF_CLASS)
    p.add_argument("--trials", type=int, default=2)
    args = p.parse_args()

    from rafiki_tpu.client import Client
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.model import load_image_dataset

    workdir = tempfile.mkdtemp(prefix="rafiki_quickstart_")
    platform = None
    if args.local:
        from rafiki_tpu.platform import LocalPlatform
        platform = LocalPlatform(workdir=workdir, http=True)
        args.admin_port = platform.admin_port

    if args.synthetic:
        from rafiki_tpu.datasets import make_synthetic_image_dataset
        args.train, args.val = make_synthetic_image_dataset(
            workdir, n_train=2048, n_val=256, image_shape=(28, 28, 1),
            n_classes=10, name="fashion_mnist")
    if not args.train or not args.val:
        raise SystemExit("--train/--val or --synthetic is required")

    try:
        # 1. Bootstrap users (superadmin creates a model developer).
        root = Client(args.admin_host, args.admin_port)
        root.login("superadmin@rafiki", "rafiki")
        try:
            root.create_user("dev@example.com", "pw",
                             UserType.MODEL_DEVELOPER)
        except Exception:
            pass  # already exists from a previous run

        dev = Client(args.admin_host, args.admin_port)
        dev.login("dev@example.com", "pw")

        # 2. Register the model template.
        model = dev.create_model("quickstart-ff",
                                 TaskType.IMAGE_CLASSIFICATION,
                                 args.model_class)
        print("model:", model["id"])

        # 3. Train job: the Advisor searches the model's knob space.
        job = dev.create_train_job(
            "quickstart-app", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
            {BudgetOption.MODEL_TRIAL_COUNT: args.trials},
            args.train, args.val)
        print("train job:", job["id"])
        done = dev.wait_until_train_job_done(job["id"], timeout=3600)
        assert done["status"] == "STOPPED", done
        best = dev.get_best_trials_of_train_job(job["id"], max_count=2)
        print("best trials:", [(t["id"][:8], round(t["score"], 4))
                               for t in best])

        # 4. Deploy the ensemble and query it.
        inf = dev.create_inference_job(job["id"], max_models=1)
        host = dev.get_inference_job(inf["id"])["predictor_host"]
        print("predictor:", host)
        val_ds = load_image_dataset(args.val)
        out = dev.predict(host, queries=[val_ds.images[i] for i in range(4)])
        preds = out["predictions"]
        acc = float(np.mean([int(np.argmax(pr)) == val_ds.labels[i]
                             for i, pr in enumerate(preds)]))
        print(f"served {len(preds)} predictions; sample accuracy {acc:.2f}")

        dev.stop_inference_job(inf["id"])
        print("QUICKSTART OK")
    finally:
        if platform is not None:
            platform.shutdown()


if __name__ == "__main__":
    from rafiki_tpu.jaxenv import ensure_platform

    # Resolve the JAX platform up front: honors JAX_PLATFORMS=cpu (the
    # site hook's config latch otherwise ignores it) and falls back to
    # CPU instead of hanging when the TPU tunnel is unreachable.
    ensure_platform()
    main()
