"""Accuracy parity on REAL data (SURVEY.md §7: "accuracy parity is
demonstrable"; VERDICT r1 item 3).

The sandbox has zero egress, so fashion-MNIST / CIFAR-10 cannot be
fetched (their converters in ``rafiki_tpu.datasets.prep`` run whenever
the standard distribution files exist). scikit-learn bundles real
datasets inside the package, so parity is demonstrated on those: the UCI
handwritten digits (1,797 real 8×8 scans), breast-cancer (Wisconsin) and
wine tables. Expected bands are the published accuracies of the same
model families on these datasets (SVM on digits ≈ 0.97+, trees ≈ 0.85,
small MLPs ≈ 0.95+).

Run:  python examples/scripts/accuracy_parity.py
Exits non-zero if any model lands below its band — the reproducible
one-script check BASELINE.md's accuracy table points at.

``--fast`` runs only the sub-minute rows (Sk models, FeedForward, CNN,
the tabular MLPs) — the pre-commit tier's parity gate, so a parity
regression in a default-tier change surfaces within minutes instead of
at the next nightly full run (VERDICT r3 item 8).
"""

import tempfile

RESULTS = []


def record(model: str, dataset: str, acc: float, band: float) -> None:
    ok = acc >= band
    RESULTS.append((model, dataset, acc, band, ok))
    print(f"{model:18s} {dataset:14s} acc={acc:.4f} "
          f"(expected >= {band:.2f}) {'OK' if ok else 'BELOW BAND'}",
          flush=True)


def run_image(model_class, knobs, train, val, name, band) -> None:
    model = model_class(**model_class.validate_knobs(knobs))
    model.train(train)
    acc = float(model.evaluate(val))
    model.destroy()
    record(model_class.__name__, name, acc, band)


def run_enas_search(train, val, band: float) -> None:
    """ENAS on the real digits: weight-shared search trials, then the
    final-phase from-scratch retrain of the best architecture — the
    full advisor->runner loop, not a fixed arch (BASELINE config[2])."""
    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.models import JaxEnas
    from rafiki_tpu.store import MetaStore, ParamStore

    with tempfile.TemporaryDirectory() as tmp:
        from rafiki_tpu.worker.runner import TrialRunner

        total = 9  # 8 weight-shared search trials + 1 final retrain
        advisor = make_advisor(JaxEnas.get_knob_config(), seed=0,
                               total_trials=total)
        runner = TrialRunner(
            JaxEnas, advisor, train, val, MetaStore(":memory:"),
            ParamStore(tmp + "/params"), sub_train_job_id="parity-enas",
            budget={BudgetOption.MODEL_TRIAL_COUNT: total})
        best = 0.0
        for _ in range(total):
            trial = runner.run_one()
            if trial.get("score") is not None:
                best = max(best, float(trial["score"]))
    record("JaxEnas(search)", "digits", best, band)


def main(fast: bool = False) -> None:
    from rafiki_tpu.datasets import (prepare_bundled_pos_corpus,
                                     prepare_sklearn_digits,
                                     prepare_sklearn_tabular)
    from rafiki_tpu.models import (JaxCnn, JaxDenseNet, JaxFeedForward,
                                   JaxPosTagger, JaxTabMlpClf,
                                   JaxTransformerTagger, JaxViT, SkDt,
                                   SkSvm)

    with tempfile.TemporaryDirectory() as tmp:
        train, val = prepare_sklearn_digits(tmp + "/digits")

        run_image(SkSvm, {"C": 10.0, "kernel": "rbf", "max_iter": 1000},
                  train, val, "digits", 0.95)
        run_image(SkDt, {"max_depth": 12, "criterion": "gini",
                         "min_samples_leaf": 1}, train, val, "digits", 0.75)
        run_image(JaxFeedForward,
                  {"hidden_layer_count": 2, "hidden_layer_units": 128,
                   "learning_rate": 3e-3, "batch_size": 64,
                   "max_epochs": 5}, train, val, "digits", 0.90)
        run_image(JaxCnn,
                  {"width_16ths": 16, "learning_rate": 3e-3,
                   "batch_size": 64, "weight_decay": 1e-4,
                   "max_epochs": 12, "early_stop_epochs": 5},
                  train, val, "digits", 0.90)
        if not fast:
            run_image(JaxViT,
                      {"depth": 4, "learning_rate": 1e-3, "batch_size": 64,
                       "weight_decay": 1e-4, "max_epochs": 25},
                      train, val, "digits", 0.90)
            # Flagship CNN family (BASELINE config[1]): the DenseNet-BC
            # architecture at its tiny preset — the 8x8 digits cannot
            # feed a 121-layer stack meaningfully, but the family (dense
            # blocks, BN, SGD-cosine recipe) is exactly the one the 121
            # preset scales up.
            run_image(JaxDenseNet,
                      {"arch": "densenet_tiny", "growth_rate": 12,
                       "learning_rate": 0.05, "batch_size": 64,
                       "weight_decay": 1e-4, "max_epochs": 30,
                       "early_stop_epochs": 5, "quick_train": False},
                      train, val, "digits", 0.90)
            # Flagship search family (BASELINE config[2]): full ENAS
            # loop. Band: the searched arch must land in the same band
            # as the hand-designed JaxCnn above — search must not lose
            # accuracy.
            run_enas_search(train, val, 0.90)

            # Sequence taggers on the bundled REAL English corpus
            # (examples/datasets/english_pos; hand-tagged Universal
            # tagset; 679 sentences / 6,599 tokens after the r5
            # extension). Bands sit ~2-3 points under the worst of
            # three measured data-split seeds (BiLSTM 0.913-0.920,
            # Transformer 0.871-0.889) — they constrain, not decorate.
            ctr, cva = prepare_bundled_pos_corpus(tmp + "/pos")
            for cls, knobs, band in (
                    (JaxPosTagger,
                     {"embed_dim": 64, "hidden": 128,
                      "learning_rate": 1e-2, "batch_size": 32,
                      "max_epochs": 20}, 0.89),
                    (JaxTransformerTagger,
                     {"d_model": 128, "n_heads": 4, "n_layers": 2,
                      "learning_rate": 3e-3, "batch_size": 32,
                      "max_epochs": 30, "max_len": 64, "dropout": 0.1},
                     0.84)):
                model = cls(**cls.validate_knobs(knobs))
                model.train(ctr)
                acc = float(model.evaluate(cva))
                model.destroy()
                record(cls.__name__, "english_pos", acc, band)

        for dataset, band in (("breast_cancer", 0.90), ("wine", 0.90)):
            train, val = prepare_sklearn_tabular(dataset, f"{tmp}/{dataset}")
            model = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(
                {"hidden": 64, "depth": 2, "learning_rate": 3e-3,
                 "batch_size": 32, "max_epochs": 40}))
            model.train(train)
            acc = float(model.evaluate(val))
            model.destroy()
            record("JaxTabMlpClf", dataset, acc, band)

    failed = [r for r in RESULTS if not r[4]]
    print(f"\nACCURACY PARITY {'FAILED' if failed else 'OK'} "
          f"({len(RESULTS) - len(failed)}/{len(RESULTS)} in band)")
    if failed:
        raise SystemExit(1)


if __name__ == "__main__":
    import argparse

    from rafiki_tpu.jaxenv import ensure_platform

    parser = argparse.ArgumentParser()
    parser.add_argument("--fast", action="store_true",
                        help="sub-minute rows only (pre-commit tier)")
    args = parser.parse_args()
    # Resolve the JAX platform up front: honors JAX_PLATFORMS=cpu (the
    # site hook's config latch otherwise ignores it) and falls back to
    # CPU instead of hanging when the TPU tunnel is unreachable.
    ensure_platform()
    main(fast=args.fast)
