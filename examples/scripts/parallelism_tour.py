"""Parallelism tour: every intra-trial mode on one mesh, end to end.

The platform's trial compute runs over a ``("dp", "pp", "ep", "sp",
"tp")`` mesh built from the trial's chip group (SURVEY.md §2.9; absent
upstream — trial-level parallelism was Rafiki's only axis). This tour
trains the SAME transformer tagger under each mode and prints the
scores, demonstrating that a model knob — not a rewrite — selects the
strategy:

- dp (always on): batch data parallelism; grads psum over ICI.
- sp=ring:     sequence shards rotate K/V one ICI neighbour per step.
- sp=alltoall: Ulysses — one all_to_all to head-sharding and back.
- ep:          Switch-MoE FFN, expert stack sharded; XLA derives the
               dispatch/combine all-to-alls from parameter shardings.
- pp:          GPipe microbatch pipeline over the encoder blocks.
- pp x sp:     ring attention inside the pipelined stages.
- pp x ep:     MoE stages with each stage's expert slice over ep.

Run on the 8-device virtual CPU mesh (no TPU needed):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/scripts/parallelism_tour.py

On a real slice the same knobs map onto ICI; nothing changes but speed.
"""

import tempfile


def main() -> None:
    import jax

    from rafiki_tpu.datasets import make_synthetic_corpus_dataset
    from rafiki_tpu.models import JaxTransformerTagger

    n = len(jax.devices())
    if n < 2 or n % 2:
        raise SystemExit(f"need an even device count >= 2, have {n} "
                         "(set XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8)")

    with tempfile.TemporaryDirectory() as tmp:
        train, val = make_synthetic_corpus_dataset(
            tmp, n_train=96, n_val=24, vocab=64, n_tags=4, max_len=24)
        base = dict(d_model=64, n_heads=4, n_layers=2,
                    learning_rate=1e-2, batch_size=16, max_epochs=8,
                    max_len=32, dropout=0.0, vocab_size=1024)
        modes = [
            ("dp only", {}),
            ("sp ring", dict(sequence_parallel=2)),
            ("sp alltoall", dict(sequence_parallel=2,
                                 sp_schedule="alltoall")),
            ("ep moe", dict(moe_experts=4, expert_parallel=2)),
            ("pp gpipe", dict(pipeline_parallel=2)),
        ]
        if n % 4 == 0:
            # Composed modes need 4 mesh cells beyond dp.
            modes += [
                ("pp x sp", dict(pipeline_parallel=2,
                                 sequence_parallel=2)),
                ("pp x ep", dict(pipeline_parallel=2, moe_experts=4,
                                 expert_parallel=2)),
            ]
        for name, extra in modes:
            model = JaxTransformerTagger(**base, **extra)
            shape = dict(model.mesh.shape)
            model.train(train)
            score = float(model.evaluate(val))
            model.destroy()
            axes = "x".join(f"{a}{v}" for a, v in shape.items() if v > 1)
            print(f"{name:12s} mesh[{axes:12s}] token-acc={score:.4f}",
                  flush=True)
    print("PARALLELISM TOUR OK")


if __name__ == "__main__":
    from rafiki_tpu.jaxenv import ensure_platform

    # Resolve the JAX platform up front: honors JAX_PLATFORMS=cpu (the
    # site hook's config latch otherwise ignores it) and falls back to
    # CPU instead of hanging when the TPU tunnel is unreachable.
    ensure_platform()
    main()
