"""ENAS architecture-search quickstart (BASELINE config[2]).

Parity: SURVEY.md §3.5 — runs the controller-driven cell search over
``JaxEnas``: search trials train briefly on shared supernet weights (one
compiled XLA graph for every proposed architecture), then the final
phase retrains the controller's best architecture from scratch.

    python examples/scripts/enas_search.py --synthetic --trials 10
"""

import argparse
import tempfile


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--train")
    p.add_argument("--val")
    p.add_argument("--trials", type=int, default=10)
    args = p.parse_args()

    from rafiki_tpu.advisor import EnasAdvisor
    from rafiki_tpu.constants import BudgetOption, TrialStatus
    from rafiki_tpu.models import JaxEnas
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker import TrialRunner

    workdir = tempfile.mkdtemp(prefix="rafiki_enas_")
    if args.synthetic:
        from rafiki_tpu.datasets import make_synthetic_image_dataset
        args.train, args.val = make_synthetic_image_dataset(
            workdir, n_train=4096, n_val=512, image_shape=(32, 32, 3),
            n_classes=10, name="cifar10")
    if not args.train or not args.val:
        raise SystemExit("--train/--val or --synthetic is required")

    meta = MetaStore(":memory:")
    params = ParamStore(workdir + "/params")
    user = meta.create_user("enas@example.com", "h", "MODEL_DEVELOPER")
    model = meta.create_model(user["id"], "enas", "IMAGE_CLASSIFICATION",
                              "rafiki_tpu.models.enas:JaxEnas", {})
    budget = {BudgetOption.MODEL_TRIAL_COUNT: args.trials}
    job = meta.create_train_job(user["id"], "enas-app",
                                "IMAGE_CLASSIFICATION", budget,
                                args.train, args.val, "RUNNING")
    sub = meta.create_sub_train_job(job["id"], model["id"], "RUNNING")

    advisor = EnasAdvisor(JaxEnas.get_knob_config(), seed=0,
                          total_trials=args.trials)
    runner = TrialRunner(JaxEnas, advisor, args.train, args.val,
                         meta, params, sub["id"], model_id=model["id"],
                         budget=budget)
    runner.run()

    trials = sorted(meta.get_trials(sub["id"], TrialStatus.COMPLETED),
                    key=lambda t: t["no"])
    for t in trials:
        phase = ("final" if not t["knobs"].get("share_params") else "search")
        print(f"trial {t['no']:>3} [{phase}]  score={t['score']:.4f}")
    best = max(trials, key=lambda t: t["score"])
    print("best architecture:", best["knobs"]["arch"])
    print("ENAS_SEARCH OK")


if __name__ == "__main__":
    from rafiki_tpu.jaxenv import ensure_platform

    # Resolve the JAX platform up front: honors JAX_PLATFORMS=cpu (the
    # site hook's config latch otherwise ignores it) and falls back to
    # CPU instead of hanging when the TPU tunnel is unreachable.
    ensure_platform()
    main()
