"""Model-developer quickstart: upload a custom model file and train it.

Parity: SURVEY.md §2 "Quickstart scripts" + §3.4 — the upstream
model-developer flow: write a BaseModel subclass in a file, upload it
(the platform stores the source and re-materialises the class inside
workers), then run a train job against it.

    python examples/scripts/model_developer.py --local --synthetic
"""

import argparse
import os
import tempfile

MODEL_FILE = os.path.join(os.path.dirname(__file__), "..", "models",
                          "my_model.py")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--admin-host", default="127.0.0.1")
    p.add_argument("--admin-port", type=int, default=3000)
    p.add_argument("--local", action="store_true")
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--train")
    p.add_argument("--val")
    p.add_argument("--model-file", default=MODEL_FILE)
    args = p.parse_args()

    from rafiki_tpu.client import Client
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType

    workdir = tempfile.mkdtemp(prefix="rafiki_mdev_")
    platform = None
    if args.local:
        from rafiki_tpu.platform import LocalPlatform
        platform = LocalPlatform(workdir=workdir, http=True)
        args.admin_port = platform.admin_port
    if args.synthetic:
        from rafiki_tpu.datasets import make_synthetic_image_dataset
        args.train, args.val = make_synthetic_image_dataset(
            workdir, n_train=1024, n_val=128)
    if not args.train or not args.val:
        raise SystemExit("--train/--val or --synthetic is required")

    try:
        root = Client(args.admin_host, args.admin_port)
        root.login("superadmin@rafiki", "rafiki")
        try:
            root.create_user("mdev@example.com", "pw",
                             UserType.MODEL_DEVELOPER)
        except Exception:
            pass

        dev = Client(args.admin_host, args.admin_port)
        dev.login("mdev@example.com", "pw")

        # Upload the model FILE: the class is re-created from this source
        # inside each worker, exactly like upstream's model upload.
        model = dev.create_model("my-model", TaskType.IMAGE_CLASSIFICATION,
                                 "MyModel", model_file_path=args.model_file)
        print("uploaded model:", model["id"])

        job = dev.create_train_job(
            "mdev-app", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
            {BudgetOption.MODEL_TRIAL_COUNT: 2}, args.train, args.val)
        done = dev.wait_until_train_job_done(job["id"], timeout=3600)
        assert done["status"] == "STOPPED", done
        best = dev.get_best_trials_of_train_job(job["id"], max_count=1)
        print("best trial score:", round(best[0]["score"], 4))
        print("MODEL_DEVELOPER OK")
    finally:
        if platform is not None:
            platform.shutdown()


if __name__ == "__main__":
    from rafiki_tpu.jaxenv import ensure_platform

    # Resolve the JAX platform up front: honors JAX_PLATFORMS=cpu (the
    # site hook's config latch otherwise ignores it) and falls back to
    # CPU instead of hanging when the TPU tunnel is unreachable.
    ensure_platform()
    main()
