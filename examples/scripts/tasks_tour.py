"""Task tour: every supported task type end-to-end in one process.

Parity: SURVEY.md §2 "Constants" task types — IMAGE_CLASSIFICATION,
POS_TAGGING, TABULAR_CLASSIFICATION, TABULAR_REGRESSION each run the full
propose → train → evaluate → predict cycle through ``test_model_class``
(the §3.4 model-developer seam) on synthetic data.

    python examples/scripts/tasks_tour.py
"""

import tempfile


def main() -> None:
    from rafiki_tpu.constants import TaskType
    from rafiki_tpu.datasets import (make_synthetic_corpus_dataset,
                                     make_synthetic_image_dataset,
                                     make_synthetic_tabular_dataset)
    from rafiki_tpu.model import (load_corpus_dataset, load_image_dataset,
                                  load_tabular_dataset, test_model_class)
    from rafiki_tpu.models import (JaxFeedForward, JaxPosTagger,
                                   JaxTabMlpClf, JaxTabMlpReg,
                                   JaxTransformerTagger)

    workdir = tempfile.mkdtemp(prefix="rafiki_tour_")

    # 1. Image classification
    tr, va = make_synthetic_image_dataset(workdir, n_train=2048, n_val=256,
                                          image_shape=(28, 28, 1),
                                          n_classes=10)
    r = test_model_class(
        JaxFeedForward, TaskType.IMAGE_CLASSIFICATION, tr, va,
        test_queries=[load_image_dataset(va).images[0]],
        knobs={"hidden_layer_count": 2, "hidden_layer_units": 64,
               "learning_rate": 1e-3, "batch_size": 64, "max_epochs": 5})
    print(f"IMAGE_CLASSIFICATION  JaxFeedForward  acc={r.score:.3f}")

    # 2. POS tagging
    tr, va = make_synthetic_corpus_dataset(workdir, n_train=512, n_val=128,
                                           vocab=200, n_tags=8)
    r = test_model_class(
        JaxPosTagger, TaskType.POS_TAGGING, tr, va,
        test_queries=load_corpus_dataset(va).sentences[:2],
        knobs={"embed_dim": 32, "hidden": 64, "learning_rate": 5e-3,
               "batch_size": 32, "max_epochs": 8, "max_len": 64,
               "vocab_size": 16384})
    print(f"POS_TAGGING           JaxPosTagger    token-acc={r.score:.3f}")

    # 2b. POS tagging with the attention-ops Transformer (flash/ring)
    r = test_model_class(
        JaxTransformerTagger, TaskType.POS_TAGGING, tr, va,
        test_queries=load_corpus_dataset(va).sentences[:2],
        knobs={"d_model": 64, "n_heads": 2, "n_layers": 2,
               "learning_rate": 1e-2, "batch_size": 32, "max_epochs": 15,
               "max_len": 64, "dropout": 0.0, "vocab_size": 16384,
               "sequence_parallel": 1})
    print(f"POS_TAGGING           JaxTransformerTagger token-acc={r.score:.3f}")

    # 3. Tabular classification
    tr, va = make_synthetic_tabular_dataset(workdir, n_train=1024,
                                            n_val=256, n_features=10,
                                            n_classes=4, name="tc")
    r = test_model_class(
        JaxTabMlpClf, TaskType.TABULAR_CLASSIFICATION, tr, va,
        test_queries=[load_tabular_dataset(va).features[0]],
        knobs={"hidden": 64, "depth": 2, "learning_rate": 5e-3,
               "batch_size": 64, "max_epochs": 15})
    print(f"TABULAR_CLASSIFICATION JaxTabMlpClf   acc={r.score:.3f}")

    # 4. Tabular regression
    tr, va = make_synthetic_tabular_dataset(workdir, n_train=1024,
                                            n_val=256, n_features=10,
                                            n_classes=0, name="treg")
    r = test_model_class(
        JaxTabMlpReg, TaskType.TABULAR_REGRESSION, tr, va,
        test_queries=[load_tabular_dataset(va).features[0]],
        knobs={"hidden": 64, "depth": 2, "learning_rate": 5e-3,
               "batch_size": 64, "max_epochs": 15})
    print(f"TABULAR_REGRESSION    JaxTabMlpReg    R2={r.score:.3f}")
    print("TASKS TOUR OK")


if __name__ == "__main__":
    from rafiki_tpu.jaxenv import ensure_platform

    # Resolve the JAX platform up front: honors JAX_PLATFORMS=cpu (the
    # site hook's config latch otherwise ignores it) and falls back to
    # CPU instead of hanging when the TPU tunnel is unreachable.
    ensure_platform()
    main()
