"""Example custom model, uploaded as source by a model developer.

Parity: SURVEY.md §3.4 — upstream model developers write a ``BaseModel``
subclass in a file and upload it; workers re-materialise the class from
the stored source (``rafiki_tpu.utils.model_loader``). This file is that
workflow's example: a logistic-regression-style single-layer JAX model.

Local self-check (the model-developer loop):

    python examples/models/my_model.py
"""

import flax.linen as nn

from rafiki_tpu.model import CategoricalKnob, FixedKnob, FloatKnob
from rafiki_tpu.model.jax_model import JaxModel


class _Linear(nn.Module):
    n_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dense(self.n_classes)(x.reshape((x.shape[0], -1)))


class MyModel(JaxModel):
    """Single linear layer: the smallest possible JaxModel."""

    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-3, 1e-1, is_exp=True),
            "batch_size": CategoricalKnob([32, 64]),
            "max_epochs": FixedKnob(3),
        }

    def create_module(self, n_classes, image_shape):
        return _Linear(n_classes=n_classes)


if __name__ == "__main__":
    import tempfile

    from rafiki_tpu.datasets import make_synthetic_image_dataset
    from rafiki_tpu.model import test_model_class

    tmp = tempfile.mkdtemp()
    train, val = make_synthetic_image_dataset(tmp, n_train=512, n_val=128)
    result = test_model_class(MyModel, "IMAGE_CLASSIFICATION", train, val,
                              test_queries=None)
    print("score:", result.score)
