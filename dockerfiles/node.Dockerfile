# rafiki-tpu platform node image.
#
# Parity: SURVEY.md §2 "Dockerfiles" — upstream built four CUDA/
# nvidia-docker images (admin, worker, predictor, web). The TPU rebuild's
# resident-runner design needs ONE image: every role (Admin REST + web
# dashboard, train workers, inference workers, predictor) runs inside the
# `python -m rafiki_tpu serve` process, scheduled onto chip groups. On a
# multi-host slice, run this image on every host with RAFIKI_TPU_BUS_URI
# pointing at host 0's bus (TCP over DCN).
#
# Build:  docker build -f dockerfiles/node.Dockerfile -t rafiki-tpu .
# Run:    docker run --privileged --net=host \
#           -e RAFIKI_TPU_WORKDIR=/data -v rafiki-data:/data rafiki-tpu
# (--privileged + host networking are the standard requirements for TPU
#  VM containers; no nvidia-docker runtime is involved anywhere.)

FROM python:3.11-slim

# g++ builds the native bus broker (rafiki_tpu/bus/native_broker.cpp);
# the platform falls back to the pure-Python broker without it.
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

# libtpu + jax come from the TPU release wheel index; everything else is
# pure-python.
RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    flax optax safetensors numpy requests scikit-learn pillow

WORKDIR /app
COPY rafiki_tpu /app/rafiki_tpu

ENV RAFIKI_TPU_WORKDIR=/data \
    RAFIKI_TPU_ADMIN_PORT=3000
EXPOSE 3000

ENTRYPOINT ["python", "-m", "rafiki_tpu", "serve"]
CMD ["--workdir", "/data", "--port", "3000"]
