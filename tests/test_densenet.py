"""JaxDenseNet (PyDenseNet parity, SURVEY.md §2/§7 step 8) tests.

Uses the tiny preset + small growth rate so a full end-to-end trial runs in
seconds on the CPU mesh; the 121 preset is exercised shape-only.
"""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import load_image_dataset, test_model_class
from rafiki_tpu.models import JaxDenseNet
from rafiki_tpu.models.densenet import _BLOCK_CONFIGS, _DenseNet

TINY_KNOBS = {"arch": "densenet_tiny", "growth_rate": 8,
              "learning_rate": 0.1, "batch_size": 64,
              "weight_decay": 1e-4, "max_epochs": 20,
              "early_stop_epochs": 5, "quick_train": False}


@pytest.mark.slow
@pytest.mark.slower
def test_densenet_end_to_end(synth_image_data):
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(3)]
    result = test_model_class(
        JaxDenseNet, TaskType.IMAGE_CLASSIFICATION,
        train_path, val_path, test_queries=queries, knobs=TINY_KNOBS)
    # Synthetic 4-class data: chance is 0.25.
    assert result.score > 0.5, f"score too low: {result.score}"
    assert len(result.predictions) == 3


def test_densenet_121_shapes():
    """The full DenseNet-121 config builds and has the canonical topology."""
    module = _DenseNet(block_config=_BLOCK_CONFIGS["densenet_121"],
                       growth_rate=32, n_classes=10)
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: module.init(jax.random.key(0), x, train=False))
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree.leaves(variables["params"]))
    # DenseNet-BC-121 with a CIFAR stem: ~7M params (torchvision's
    # ImageNet-stem DenseNet-121 is 7.98M; ours drops the 7x7 stem).
    assert 5e6 < n_params < 9e6, n_params
    # 3 transitions => spatial 32 -> 4 before global pool; check logits.
    logits = jax.eval_shape(
        lambda v, a: module.apply(v, a, train=False), variables, x)
    assert logits.shape == (1, 10)


@pytest.mark.slow
def test_densenet_batchnorm_updates(synth_image_data):
    """batch_stats must exist, update during train, and round-trip."""
    train_path, _ = synth_image_data
    m = JaxDenseNet(**{**TINY_KNOBS, "max_epochs": 1})
    m.train(train_path)
    params = m.dump_parameters()
    bs_keys = [k for k in params if k.startswith("batch_stats/")]
    assert bs_keys, "DenseNet must expose BatchNorm running stats"
    # Stats init to mean=0 / var=1; training must have moved them.
    moved = any(np.abs(params[k]).sum() > 0 for k in bs_keys
                if k.endswith("/mean"))
    moved |= any(np.abs(params[k] - 1.0).sum() > 1e-3 for k in bs_keys
                 if k.endswith("/var"))
    assert moved, "running stats never updated from their init values"


def test_densenet_augmentation_preserves_shape(rng):
    m = JaxDenseNet(**TINY_KNOBS)
    imgs = jnp.asarray(rng.random((8, 16, 16, 1)).astype(np.float32))
    out = m.augment_in_graph(imgs, jax.random.key(0))
    assert out.shape == imgs.shape
    assert out.dtype == imgs.dtype
    assert not np.allclose(np.asarray(out), np.asarray(imgs))
    # Below the 16-pixel floor the CIFAR crop recipe would destroy the
    # content (±4 crop on an 8x8 scan) — tiny images pass through.
    tiny = jnp.asarray(rng.random((8, 12, 12, 1)).astype(np.float32))
    assert m.augment_in_graph(tiny, jax.random.key(0)) is tiny
