"""Quickstart scripts as end-to-end tests (SURVEY.md §4: the upstream
quickstarts are the de-facto integration suite). Each runs as a real
subprocess on the virtual CPU mesh, exactly as a user would run it.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *argv, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, script), *argv],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_quickstart_local_synthetic():
    r = _run("examples/scripts/quickstart.py", "--local", "--synthetic",
             "--trials", "2")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "QUICKSTART OK" in r.stdout


@pytest.mark.slow
def test_model_developer_upload_flow():
    r = _run("examples/scripts/model_developer.py", "--local", "--synthetic")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MODEL_DEVELOPER OK" in r.stdout


def test_dataset_prep_converters(tmp_path):
    """The real-data converters parse the standard distribution formats
    (synthesised here byte-for-byte: IDX and CIFAR pickle batches)."""
    import gzip
    import pickle
    import struct

    from rafiki_tpu.datasets import prepare_cifar10, prepare_fashion_mnist
    from rafiki_tpu.model import load_image_dataset

    rng = np.random.default_rng(0)

    # fashion-MNIST IDX files (train gz, test plain: both paths).
    raw = tmp_path / "fm"
    raw.mkdir()

    def idx_images(path, n, gz):
        data = rng.integers(0, 256, size=(n, 28, 28), dtype=np.uint8)
        blob = struct.pack(">IIII", 0x803, n, 28, 28) + data.tobytes()
        (gzip.open if gz else open)(str(path), "wb").write(blob)

    def idx_labels(path, n, gz):
        data = rng.integers(0, 10, size=n, dtype=np.uint8)
        blob = struct.pack(">II", 0x801, n) + data.tobytes()
        (gzip.open if gz else open)(str(path), "wb").write(blob)

    idx_images(raw / "train-images-idx3-ubyte.gz", 64, True)
    idx_labels(raw / "train-labels-idx1-ubyte.gz", 64, True)
    idx_images(raw / "t10k-images-idx3-ubyte", 16, False)
    idx_labels(raw / "t10k-labels-idx1-ubyte", 16, False)
    train, val = prepare_fashion_mnist(str(raw), str(tmp_path / "fm_out"))
    ds = load_image_dataset(train)
    assert ds.size == 64 and tuple(ds.image_shape) == (28, 28, 1)
    assert load_image_dataset(val).size == 16

    # CIFAR-10 python batches.
    craw = tmp_path / "cifar" / "cifar-10-batches-py"
    craw.mkdir(parents=True)
    for name, n in [(f"data_batch_{i}", 20) for i in range(1, 6)] + \
                   [("test_batch", 10)]:
        batch = {b"data": rng.integers(0, 256, size=(n, 3072),
                                       dtype=np.uint8),
                 b"labels": rng.integers(0, 10, size=n).tolist()}
        with open(craw / name, "wb") as f:
            pickle.dump(batch, f)
    train, val = prepare_cifar10(str(tmp_path / "cifar"),
                                 str(tmp_path / "cifar_out"))
    ds = load_image_dataset(train)
    assert ds.size == 100 and tuple(ds.image_shape) == (32, 32, 3)
    assert load_image_dataset(val).size == 10


@pytest.mark.slow
def test_dataset_prep_cli_synthetic(tmp_path):
    r = _run("examples/datasets/cifar10.py", "--out-dir", str(tmp_path),
             "--synthetic", timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    from rafiki_tpu.model import load_image_dataset
    ds = load_image_dataset(str(tmp_path / "cifar10_train.npz"))
    assert tuple(ds.image_shape) == (32, 32, 3)


@pytest.mark.slow
def test_tasks_tour():
    r = _run("examples/scripts/tasks_tour.py", timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "TASKS TOUR OK" in r.stdout


def test_sklearn_real_dataset_converters(tmp_path):
    """Real-data path (zero-egress sandbox): the bundled-sklearn
    converters produce valid platform datasets from genuinely real
    scans/tables."""
    from rafiki_tpu.datasets import (prepare_sklearn_digits,
                                     prepare_sklearn_tabular)
    from rafiki_tpu.model import load_image_dataset, load_tabular_dataset

    train, val = prepare_sklearn_digits(str(tmp_path / "d"))
    tr, va = load_image_dataset(train), load_image_dataset(val)
    assert tuple(tr.image_shape) == (8, 8, 1)
    assert tr.size + va.size == 1797 and va.size == 359
    assert set(tr.labels) == set(range(10))

    train, val = prepare_sklearn_tabular("wine", str(tmp_path / "w"))
    ds = load_tabular_dataset(train)
    assert ds.n_classes == 3 and ds.features.shape[1] == 13


@pytest.mark.slow
@pytest.mark.slower
def test_accuracy_parity_script():
    """The one-script accuracy-parity check (BASELINE.md table) stays
    reproducible: every model lands in its published band."""
    r = _run("examples/scripts/accuracy_parity.py", timeout=2400)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ACCURACY PARITY OK" in r.stdout


@pytest.mark.slow
def test_accuracy_parity_fast_tier():
    """VERDICT r3 item 8: the sub-minute parity rows (Sk models, FF,
    CNN, tabular) gate the pre-commit tier, so a parity regression in a
    default-tier change surfaces within minutes, not at the nightly
    full run."""
    r = _run("examples/scripts/accuracy_parity.py", "--fast", timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ACCURACY PARITY OK" in r.stdout
    # The fast tier covers exactly the cheap rows.
    for name in ("SkSvm", "SkDt", "JaxFeedForward", "JaxCnn",
                 "JaxTabMlpClf"):
        assert name in r.stdout
    assert "JaxDenseNet" not in r.stdout  # nightly-only row


@pytest.mark.slow
def test_parallelism_tour():
    # 7 modes (r4 adds the pp x sp and pp x ep compositions), each a
    # fresh XLA compile on the 1-core CPU mesh — the long timeout is
    # compile time, not training.
    r = _run("examples/scripts/parallelism_tour.py", timeout=1800)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PARALLELISM TOUR OK" in r.stdout
    import re

    scores = {m.group(1).strip(): float(m.group(2)) for m in re.finditer(
        r"(\S[\w x]+?)\s+mesh\[.*?\] token-acc=([\d.]+)", r.stdout)}
    # Ring attention reproduces the dp compute EXACTLY (same reduction
    # order), and pp x sp reproduces pp exactly (the sp axis changes
    # nothing about the pipeline's math).
    assert scores["dp only"] == scores["sp ring"]
    assert scores["pp gpipe"] == scores["pp x sp"]
    # Ulysses (head re-sharding) and GPipe (microbatched matmuls)
    # regroup bf16 reductions, so tiny per-step differences amplify
    # over 8 epochs of training — equivalent quality, not bit equality.
    # (Until r4 this held bit-exactly by COINCIDENCE: with the old
    # sequentially-consumed data-order RNG the accumulated bf16 drift
    # never flipped a val prediction. The r4 switch to per-epoch
    # epoch_rng — required for checkpoint-resume step identity —
    # changed the data order and surfaced the latent approximation;
    # the regrouping code paths themselves are unchanged.)
    dense = [scores[k] for k in ("dp only", "sp ring", "sp alltoall",
                                 "pp gpipe", "pp x sp")]
    assert max(dense) - min(dense) < 0.02, scores
    # The MoE modes train the same (different, routed) model; pp x ep
    # must land in the same band as unpipelined MoE.
    assert abs(scores["ep moe"] - scores["pp x ep"]) < 0.07, scores
