"""AshaAdvisor: rung ladders, promotion policy, platform integration."""

import numpy as np
import pytest

from rafiki_tpu.advisor import AshaAdvisor, make_advisor
from rafiki_tpu.advisor.asha import _budget_ladder
from rafiki_tpu.model.knobs import (CategoricalKnob, FixedKnob, FloatKnob,
                                    IntegerKnob)

CONFIG = {
    "width": IntegerKnob(8, 64),
    "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
    "max_epochs": IntegerKnob(1, 27),
}


def test_budget_ladder_shapes():
    assert _budget_ladder(IntegerKnob(1, 27), 3) == [1, 3, 9, 27]
    assert _budget_ladder(IntegerKnob(2, 20), 3) == [2, 6, 18, 20]
    assert _budget_ladder(IntegerKnob(5, 5), 3) == [5]
    assert _budget_ladder(CategoricalKnob([5, 10, 20, 40]), 2) == \
        [5, 10, 20, 40]
    assert _budget_ladder(CategoricalKnob([3, 4, 30]), 3) == [3, 30]
    assert _budget_ladder(CategoricalKnob(["a", "b"]), 3) == []
    assert _budget_ladder(FixedKnob(7), 3) == []
    assert _budget_ladder(None, 3) == []


def test_new_configs_start_at_rung_zero():
    adv = AshaAdvisor(CONFIG, seed=0)
    for _ in range(5):
        p = adv.propose()
        assert p.knobs["max_epochs"] == 1  # rung-0 budget
        assert 8 <= p.knobs["width"] <= 64


def test_promotion_reuses_config_at_higher_budget():
    adv = AshaAdvisor(CONFIG, seed=0, eta=3)
    proposals = [adv.propose() for _ in range(6)]
    scores = [0.1, 0.9, 0.2, 0.8, 0.3, 0.4]
    for p, s in zip(proposals, scores):
        adv.feedback(p, s)
    # 6 completed at rung 0 -> floor(6/3)=2 promotable; the next two
    # proposals must be the two best configs, warm-starting with the
    # rung-1 DELTA budget (3-1=2) and a full-budget cold-start fallback.
    p7 = adv.propose()
    p8 = adv.propose()
    promoted = [p7, p8]
    budgets = {p.knobs["max_epochs"] for p in promoted}
    assert budgets == {2}
    assert all(p.meta["cold_start_knobs"] == {"max_epochs": 3}
               for p in promoted)
    promoted_widths = {p.knobs["width"] for p in promoted}
    best_widths = {proposals[1].knobs["width"], proposals[3].knobs["width"]}
    assert promoted_widths == best_widths
    # And learning rate (the config identity) is carried over unchanged.
    assert {p.knobs["learning_rate"] for p in promoted} == \
        {proposals[1].knobs["learning_rate"],
         proposals[3].knobs["learning_rate"]}


def test_promotions_climb_to_top_rung():
    rng = np.random.default_rng(0)
    adv = AshaAdvisor(CONFIG, seed=1, eta=3, total_trials=60)
    seen_budgets = set()
    while True:
        p = adv.propose()
        if p is None:
            break
        seen_budgets.add(p.knobs["max_epochs"])
        # Score correlated with width: halving should drive the widest
        # configs upward through every rung.
        adv.feedback(p, p.knobs["width"] / 64 + rng.normal(0, 0.01))
    # Proposals carry rung DELTAS (warm-start): ladder 1/3/9/27 ->
    # deltas 1, 2, 6, 18.
    assert seen_budgets == {1, 2, 6, 18}
    best_knobs, _ = adv.best()
    assert best_knobs["width"] >= 40


def test_forget_refunds_promotion():
    adv = AshaAdvisor(CONFIG, seed=0, eta=2)
    proposals = [adv.propose() for _ in range(2)]
    adv.feedback(proposals[0], 0.9)
    adv.feedback(proposals[1], 0.1)
    promo = adv.propose()
    # IntegerKnob(1,27), eta=2: rung-1 full budget 2, delta 2-1=1.
    assert promo.knobs["max_epochs"] == 1
    assert promo.meta["cold_start_knobs"] == {"max_epochs": 2}
    adv.forget(promo)
    # The promotion slot is refunded: the same config is re-promotable.
    promo2 = adv.propose()
    assert promo2.knobs["max_epochs"] == 1
    assert promo2.knobs["width"] == promo.knobs["width"]


def test_degenerates_without_budget_knob():
    adv = AshaAdvisor({"x": IntegerKnob(1, 4)}, seed=0)
    p = adv.propose()
    assert 1 <= p.knobs["x"] <= 4
    adv.feedback(p, 0.5)
    assert adv.propose() is not None


def test_registry_selects_asha():
    adv = make_advisor(CONFIG, advisor_type="asha", total_trials=3)
    assert isinstance(adv, AshaAdvisor)
    assert [adv.propose() is not None for _ in range(3)] == [True] * 3
    assert adv.propose() is None  # budget enforced


def test_promotions_warm_start_from_own_config(tmp_path):
    """A promoted trial must receive ITS configuration's rung-r weights
    as shared params; rung-0 trials cold start."""
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.model.base import BaseModel
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    received = []  # (width, shared-params marker or None)

    class FakeModel(BaseModel):
        @staticmethod
        def get_knob_config():
            return CONFIG

        def __init__(self, **knobs):
            super().__init__(**knobs)
            self._params = {}

        def train(self, path, *, shared_params=None, **kw):
            marker = (None if shared_params is None
                      else float(np.asarray(
                          shared_params["marker"]).reshape(-1)[0]))
            received.append((self.knobs["width"], marker,
                             self.knobs["max_epochs"]))
            self._params = {"marker":
                            np.asarray(float(self.knobs["width"]))}

        def evaluate(self, path):
            return self.knobs["width"] / 64.0  # wider = better

        def predict(self, queries):
            return [0 for _ in queries]

        def dump_parameters(self):
            return dict(self._params)

        def load_parameters(self, params):
            self._params = dict(params)

    adv = AshaAdvisor(CONFIG, seed=3, eta=3, total_trials=10)
    runner = TrialRunner(FakeModel, adv, "tr", "va", MetaStore(":memory:"),
                         ParamStore(str(tmp_path / "p")),
                         sub_train_job_id="asha-warm",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 10})
    runner.run()

    rung0 = [r for r in received if r[1] is None]
    promotions = [r for r in received if r[1] is not None]
    assert promotions, "no promotion ever warm-started"
    for width, marker, _ in promotions:
        # the warm-start came from the SAME config's earlier params
        assert marker == float(width)
    assert len(rung0) + len(promotions) == len(received)
    # Promotions trained only the rung DELTA (ladder 1/3/9/27 under
    # eta=3 -> deltas 2/6/18), never a full rung budget from scratch.
    assert {e for _, _, e in promotions} <= {2, 6, 18}
    assert all(e == 1 for _, _, e in rung0)


def test_promotion_records_cumulative_budget(tmp_path):
    """Review finding r2: a promotion EXECUTES the rung delta but must
    RECORD the cumulative budget — retraining from scratch with the
    recorded knobs (advisor.best(), trial rows) reproduces the scored
    model."""
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    adv = AshaAdvisor(CONFIG, seed=0, eta=3)
    proposals = [adv.propose() for _ in range(3)]
    for p, s in zip(proposals, [0.9, 0.1, 0.2]):
        adv.feedback(p, s)
    promo = adv.propose()
    assert promo.knobs["max_epochs"] == 2            # executed delta
    assert promo.meta["record_knobs"] == {"max_epochs": 3}
    adv.feedback(promo, 0.95)
    best_knobs, _ = adv.best()
    assert best_knobs["max_epochs"] == 3             # reproducible

    # And through the TrialRunner: trial rows carry ladder budgets
    # (1/3/9/27), never the executed deltas (2/6/18).
    log = []
    meta = MetaStore(":memory:")
    adv2 = AshaAdvisor(CONFIG, seed=3, eta=3, total_trials=8)
    runner = TrialRunner(_make_fake_model(log), adv2, "tr", "va", meta,
                         ParamStore(str(tmp_path / "p")),
                         sub_train_job_id="asha-rec",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 8})
    runner.run()
    trials = meta.get_trials("asha-rec")
    recorded = {t["knobs"]["max_epochs"] for t in trials
                if t["status"] == "COMPLETED"}
    assert recorded <= {1, 3, 9, 27}, recorded
    executed = {e for e, _ in log}
    assert executed & {2, 6, 18}, (
        f"no promotion ever executed a delta: {executed}")


def test_promotion_cold_start_pays_full_budget(tmp_path):
    """If the warm-start params vanished, the runner applies the
    proposal's cold_start_knobs so the promoted trial retrains the FULL
    rung budget (scores stay rung-comparable)."""
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    epochs_seen = []

    class FakeModel(_make_fake_model(epochs_seen)):
        pass

    adv = AshaAdvisor(CONFIG, seed=3, eta=3, total_trials=4)
    store = ParamStore(str(tmp_path / "p"))
    runner = TrialRunner(FakeModel, adv, "tr", "va", MetaStore(":memory:"),
                         store, sub_train_job_id="asha-cold",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 4})
    # Run rung-0 trials until a promotion is pending, then clear the
    # param store to simulate expiry.
    for _ in range(3):
        runner.run_one()
    promo = adv.propose()
    assert promo.meta.get("cold_start_knobs"), "expected a promotion"
    import shutil

    shutil.rmtree(str(tmp_path / "p"), ignore_errors=True)
    runner.run_one(promo)
    # The last trial ran with the FULL rung budget (3), not the delta.
    assert epochs_seen[-1][1] is None  # no shared params arrived
    assert epochs_seen[-1][0] == 3


def _make_fake_model(log):
    from rafiki_tpu.model.base import BaseModel

    class _Fake(BaseModel):
        @staticmethod
        def get_knob_config():
            return CONFIG

        def __init__(self, **knobs):
            super().__init__(**knobs)
            self._params = {}

        def train(self, path, *, shared_params=None, **kw):
            log.append((self.knobs["max_epochs"], shared_params))
            self._params = {"w": np.asarray(1.0)}

        def evaluate(self, path):
            return self.knobs["width"] / 64.0

        def predict(self, queries):
            return [0 for _ in queries]

        def dump_parameters(self):
            return dict(self._params)

        def load_parameters(self, params):
            self._params = dict(params)

    return _Fake


def test_asha_through_platform(tmp_path, synth_image_data):
    """End-to-end: a train job with advisor_type=asha schedules rung-0
    budgets through real workers."""
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.platform import LocalPlatform

    train_path, val_path = synth_image_data
    p = LocalPlatform(workdir=str(tmp_path / "plat"), supervise_interval=0)
    try:
        dev = p.admin.create_user("dev@x.c", "pw",
                                  UserType.MODEL_DEVELOPER)
        model = p.admin.create_model(
            dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
            "rafiki_tpu.models.feedforward:JaxFeedForward")
        job = p.admin.create_train_job(
            dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 3},
            train_path, val_path, advisor_type="asha")
        assert p.admin.wait_until_train_job_done(job["id"], timeout=600)
        detail = p.admin.get_train_job(job["id"])
        assert detail["sub_train_jobs"][0]["n_completed"] == 3
    finally:
        p.shutdown()
