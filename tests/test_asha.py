"""AshaAdvisor: rung ladders, promotion policy, platform integration."""

import numpy as np
import pytest

from rafiki_tpu.advisor import AshaAdvisor, make_advisor
from rafiki_tpu.advisor.asha import _budget_ladder
from rafiki_tpu.model.knobs import (CategoricalKnob, FixedKnob, FloatKnob,
                                    IntegerKnob)

CONFIG = {
    "width": IntegerKnob(8, 64),
    "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
    "max_epochs": IntegerKnob(1, 27),
}


def test_budget_ladder_shapes():
    assert _budget_ladder(IntegerKnob(1, 27), 3) == [1, 3, 9, 27]
    assert _budget_ladder(IntegerKnob(2, 20), 3) == [2, 6, 18, 20]
    assert _budget_ladder(IntegerKnob(5, 5), 3) == [5]
    assert _budget_ladder(CategoricalKnob([5, 10, 20, 40]), 2) == \
        [5, 10, 20, 40]
    assert _budget_ladder(CategoricalKnob([3, 4, 30]), 3) == [3, 30]
    assert _budget_ladder(CategoricalKnob(["a", "b"]), 3) == []
    assert _budget_ladder(FixedKnob(7), 3) == []
    assert _budget_ladder(None, 3) == []


def test_new_configs_start_at_rung_zero():
    adv = AshaAdvisor(CONFIG, seed=0)
    for _ in range(5):
        p = adv.propose()
        assert p.knobs["max_epochs"] == 1  # rung-0 budget
        assert 8 <= p.knobs["width"] <= 64


def test_promotion_reuses_config_at_higher_budget():
    adv = AshaAdvisor(CONFIG, seed=0, eta=3)
    proposals = [adv.propose() for _ in range(6)]
    scores = [0.1, 0.9, 0.2, 0.8, 0.3, 0.4]
    for p, s in zip(proposals, scores):
        adv.feedback(p, s)
    # 6 completed at rung 0 -> floor(6/3)=2 promotable; the next two
    # proposals must be the two best configs at the FULL rung-1 budget
    # (checkpoint resume executes only the delta — the proposal itself
    # is the reproducible record).
    p7 = adv.propose()
    p8 = adv.propose()
    promoted = [p7, p8]
    budgets = {p.knobs["max_epochs"] for p in promoted}
    assert budgets == {3}
    assert all("cold_start_knobs" not in p.meta for p in promoted)
    promoted_widths = {p.knobs["width"] for p in promoted}
    best_widths = {proposals[1].knobs["width"], proposals[3].knobs["width"]}
    assert promoted_widths == best_widths
    # And learning rate (the config identity) is carried over unchanged.
    assert {p.knobs["learning_rate"] for p in promoted} == \
        {proposals[1].knobs["learning_rate"],
         proposals[3].knobs["learning_rate"]}
    # A promotion shares its configuration's checkpoint scope with the
    # rung-0 trial that produced it, and pins the ladder-top schedule.
    rung0_scopes = {p.meta["ckpt_scope"] for p in proposals}
    assert all(p.meta["ckpt_scope"] in rung0_scopes for p in promoted)
    assert all(p.meta["train_kwargs"] ==
               {"schedule_total_epochs": 27} for p in promoted + proposals)


def test_promotions_climb_to_top_rung():
    rng = np.random.default_rng(0)
    adv = AshaAdvisor(CONFIG, seed=1, eta=3, total_trials=60)
    seen_budgets = set()
    while True:
        p = adv.propose()
        if p is None:
            break
        seen_budgets.add(p.knobs["max_epochs"])
        # Score correlated with width: halving should drive the widest
        # configs upward through every rung.
        adv.feedback(p, p.knobs["width"] / 64 + rng.normal(0, 0.01))
    # Proposals carry the FULL cumulative rung budgets (ladder 1/3/9/27);
    # checkpoint resume turns them into deltas at execution time.
    assert seen_budgets == {1, 3, 9, 27}
    best_knobs, _ = adv.best()
    assert best_knobs["width"] >= 40


def test_forget_refunds_promotion():
    adv = AshaAdvisor(CONFIG, seed=0, eta=2)
    proposals = [adv.propose() for _ in range(2)]
    adv.feedback(proposals[0], 0.9)
    adv.feedback(proposals[1], 0.1)
    promo = adv.propose()
    # IntegerKnob(1,27), eta=2: rung-1 full cumulative budget 2.
    assert promo.knobs["max_epochs"] == 2
    assert promo.meta["ckpt_scope"].startswith("asha-cfg-")
    adv.forget(promo)
    # The promotion slot is refunded: the same config is re-promotable.
    promo2 = adv.propose()
    assert promo2.knobs["max_epochs"] == 2
    assert promo2.knobs["width"] == promo.knobs["width"]


def test_degenerates_without_budget_knob():
    adv = AshaAdvisor({"x": IntegerKnob(1, 4)}, seed=0)
    p = adv.propose()
    assert 1 <= p.knobs["x"] <= 4
    adv.feedback(p, 0.5)
    assert adv.propose() is not None


def test_registry_selects_asha():
    adv = make_advisor(CONFIG, advisor_type="asha", total_trials=3)
    assert isinstance(adv, AshaAdvisor)
    assert [adv.propose() is not None for _ in range(3)] == [True] * 3
    assert adv.propose() is None  # budget enforced


def test_promotions_resume_own_configs_checkpoint(tmp_path):
    """A promoted trial must receive ITS configuration's checkpoint dir
    (the scope its rung-0 trial wrote), with a final-epoch save
    requested, and the scoped dir must survive trial completion so the
    NEXT rung can resume it."""
    import os

    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    log = []  # (max_epochs, shared) via _make_fake_model
    kwargs_seen = []

    class FakeModel(_make_fake_model(log)):
        def train(self, path, *, shared_params=None, **kw):
            kwargs_seen.append((self.knobs["width"], dict(kw)))
            # Scoped checkpoints must already exist for a promotion:
            # rung 0 of the same config "wrote" them (marker file).
            super().train(path, shared_params=shared_params, **kw)
            os.makedirs(kw["checkpoint_dir"], exist_ok=True)

    adv = AshaAdvisor(CONFIG, seed=3, eta=3, total_trials=10)
    runner = TrialRunner(FakeModel, adv, "tr", "va", MetaStore(":memory:"),
                         ParamStore(str(tmp_path / "p")),
                         sub_train_job_id="asha-warm",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 10})
    runner.run()

    assert kwargs_seen, "no trials ran"
    by_width = {}
    for w, kw in kwargs_seen:
        by_width.setdefault(w, []).append(kw)
    promoted = {w: kws for w, kws in by_width.items() if len(kws) > 1}
    assert promoted, "no configuration was ever promoted"
    for w, kws in promoted.items():
        # Same config -> same scoped checkpoint dir across rungs, and
        # every rung requests its final state on disk + the ladder-top
        # schedule shape.
        dirs = {kw["checkpoint_dir"] for kw in kws}
        assert len(dirs) == 1
        d = dirs.pop()
        assert "asha-cfg-" in d
        assert os.path.isdir(d), "scoped dir was deleted mid-bracket"
        assert all(kw["checkpoint_final_epoch"] for kw in kws)
        assert all(kw["schedule_total_epochs"] == 27 for kw in kws)
    # Job over: the worker-level sweep clears every scope of this job.
    runner.cleanup_scoped_checkpoints()
    root = os.path.join(str(tmp_path / "p"), "ckpt")
    assert not os.path.isdir(root) or not [
        n for n in os.listdir(root) if n.startswith("asha-warm-")]


def test_promotion_budgets_are_cumulative_through_runner(tmp_path):
    """Trial rows and advisor.best() carry the full cumulative rung
    budgets — the proposal IS the reproducible record (no
    record/executed split since checkpoint-resume landed)."""
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    adv = AshaAdvisor(CONFIG, seed=0, eta=3)
    proposals = [adv.propose() for _ in range(3)]
    for p, s in zip(proposals, [0.9, 0.1, 0.2]):
        adv.feedback(p, s)
    promo = adv.propose()
    assert promo.knobs["max_epochs"] == 3            # full rung-1 budget
    assert "record_knobs" not in promo.meta
    adv.feedback(promo, 0.95)
    best_knobs, _ = adv.best()
    assert best_knobs["max_epochs"] == 3             # reproducible

    # And through the TrialRunner: trial rows carry ladder budgets
    # (1/3/9/27) only.
    log = []
    meta = MetaStore(":memory:")
    adv2 = AshaAdvisor(CONFIG, seed=3, eta=3, total_trials=8)
    runner = TrialRunner(_make_fake_model(log), adv2, "tr", "va", meta,
                         ParamStore(str(tmp_path / "p")),
                         sub_train_job_id="asha-rec",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 8})
    runner.run()
    trials = meta.get_trials("asha-rec")
    recorded = {t["knobs"]["max_epochs"] for t in trials
                if t["status"] == "COMPLETED"}
    assert recorded <= {1, 3, 9, 27}, recorded
    executed = {e for e, _ in log}
    assert executed == recorded


def test_rung_resume_is_step_identical_to_uninterrupted_run(tmp_path,
                                                            synth_image_data):
    """The verdict's acceptance test: a promoted rung-1 trial — rung 0
    trained 2 epochs, checkpointed its final state, rung 1 resumed and
    trained to 6 — must score EXACTLY what one uninterrupted 6-epoch
    run of the same configuration scores (same seed, same data order,
    same lr schedule, same optimizer state at every step)."""
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.model.knobs import FixedKnob
    from rafiki_tpu.models.feedforward import JaxFeedForward
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    class AshaFF(JaxFeedForward):
        @staticmethod
        def get_knob_config():
            cfg = dict(JaxFeedForward.get_knob_config())
            # eta=3 ladder over [2,6]: rungs at 2 and 6 epochs. One
            # batch size keeps the XLA step cache shared across trials.
            cfg["max_epochs"] = IntegerKnob(2, 6)
            cfg["batch_size"] = FixedKnob(64)
            return cfg

    train_path, val_path = synth_image_data
    meta = MetaStore(":memory:")
    adv = AshaAdvisor(AshaFF.get_knob_config(), seed=0, eta=3,
                      total_trials=4)
    runner = TrialRunner(AshaFF, adv, train_path, val_path, meta,
                         ParamStore(str(tmp_path / "p")),
                         sub_train_job_id="asha-ident",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 4})
    rows = runner.run()
    promoted = [r for r in rows if r["status"] == "COMPLETED"
                and r["knobs"]["max_epochs"] == 6]
    assert promoted, "no rung-1 promotion completed"
    promo = promoted[0]

    # Uninterrupted run: identical knobs, full budget, same schedule
    # shape the rungs pinned — no checkpointing involved.
    knobs = AshaFF.validate_knobs(dict(promo["knobs"]))
    model = AshaFF(**knobs)
    try:
        model.train(train_path, schedule_total_epochs=6)
        ref_score = float(model.evaluate(val_path))
    finally:
        model.destroy()
    assert promo["score"] == pytest.approx(ref_score, abs=1e-6), (
        "rung resume diverged from the uninterrupted run")


def _make_fake_model(log):
    from rafiki_tpu.model.base import BaseModel

    class _Fake(BaseModel):
        @staticmethod
        def get_knob_config():
            return CONFIG

        def __init__(self, **knobs):
            super().__init__(**knobs)
            self._params = {}

        def train(self, path, *, shared_params=None, **kw):
            log.append((self.knobs["max_epochs"], shared_params))
            self._params = {"w": np.asarray(1.0)}

        def evaluate(self, path):
            return self.knobs["width"] / 64.0

        def predict(self, queries):
            return [0 for _ in queries]

        def dump_parameters(self):
            return dict(self._params)

        def load_parameters(self, params):
            self._params = dict(params)

    return _Fake


def test_asha_through_platform(tmp_path, synth_image_data):
    """End-to-end: a train job with advisor_type=asha schedules rung-0
    budgets through real workers."""
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.platform import LocalPlatform

    train_path, val_path = synth_image_data
    p = LocalPlatform(workdir=str(tmp_path / "plat"), supervise_interval=0)
    try:
        dev = p.admin.create_user("dev@x.c", "pw",
                                  UserType.MODEL_DEVELOPER)
        model = p.admin.create_model(
            dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
            "rafiki_tpu.models.feedforward:JaxFeedForward")
        job = p.admin.create_train_job(
            dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 3},
            train_path, val_path, advisor_type="asha")
        assert p.admin.wait_until_train_job_done(job["id"], timeout=600)
        detail = p.admin.get_train_job(job["id"])
        assert detail["sub_train_jobs"][0]["n_completed"] == 3
    finally:
        p.shutdown()


def test_stop_train_services_sweeps_scoped_checkpoints(tmp_path):
    """Review finding r4: a stopped or error-terminated job must not
    leak scoped rung checkpoints. Every stop path funnels through
    ServicesManager.stop_train_services, which sweeps each sub-job's
    scoped dirs (the workers' own budget-exhausted sweep never runs for
    such jobs)."""
    import os

    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.constants import TrainJobStatus, UserType
    from rafiki_tpu.container.manager import ThreadContainerManager
    from rafiki_tpu.store import MetaStore

    meta = MetaStore(":memory:")
    user = meta.create_user("a@b.c", "x", UserType.MODEL_DEVELOPER)
    model = meta.create_model(user["id"], "m", "IMAGE_CLASSIFICATION",
                              "mod:Cls", {})
    job = meta.create_train_job(user["id"], "app", "IMAGE_CLASSIFICATION",
                                {}, "tr", "va",
                                status=TrainJobStatus.RUNNING)
    sub = meta.create_sub_train_job(job["id"], model["id"],
                                    status="RUNNING")
    params_dir = str(tmp_path / "params")
    scoped = os.path.join(params_dir, "ckpt", f"{sub['id']}-asha-cfg-0")
    other = os.path.join(params_dir, "ckpt", "othersub-asha-cfg-0")
    os.makedirs(scoped)
    os.makedirs(other)
    # No services exist, so the container manager is never exercised;
    # a None ctx keeps the test free of platform plumbing.
    sm = ServicesManager(meta, ThreadContainerManager(ctx=None),
                         params_dir=params_dir, node_id="n1")
    sm.stop_train_services(job["id"])
    assert not os.path.isdir(scoped)      # this job's dirs swept
    assert os.path.isdir(other)           # other jobs' dirs untouched
