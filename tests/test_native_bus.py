"""Native C++ bus broker: platform integration + concurrency hammer.

Protocol-level parity with the Python broker is covered by the
parametrized fixture in test_bus.py; these tests drive the broker
through the real platform stack and under concurrent load.
"""

import threading

import pytest

from rafiki_tpu.bus import BusClient, serve_broker
from rafiki_tpu.bus.native import NativeBusServer
from rafiki_tpu.constants import BudgetOption, TaskType, UserType
from rafiki_tpu.platform import LocalPlatform

pytestmark = pytest.mark.skipif(
    not NativeBusServer.available(),
    reason="no C++ toolchain for the native broker")


def test_platform_job_over_native_broker(tmp_path, synth_image_data):
    """The full train-job call stack with every bus op crossing the C++
    broker (workers, advisor RPC, caches)."""
    train_path, val_path = synth_image_data
    server = NativeBusServer().start()
    try:
        p = LocalPlatform(workdir=str(tmp_path / "plat"),
                          bus_uri=server.uri, supervise_interval=0)
        try:
            dev = p.admin.create_user("dev@x.c", "pw",
                                      UserType.MODEL_DEVELOPER)
            model = p.admin.create_model(
                dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = p.admin.create_train_job(
                dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
                train_path, val_path)
            assert p.admin.wait_until_train_job_done(job["id"],
                                                     timeout=600)
            trials = p.admin.get_train_job(job["id"])
            assert trials["sub_train_jobs"][0]["n_completed"] == 2
        finally:
            p.shutdown()
    finally:
        server.stop()


def test_native_broker_concurrent_hammer():
    """Many threads, interleaved blocking pops and pushes, large-ish
    payloads with non-ASCII strings — exercises the broker's frame
    reassembly, waiter parking, and JSON splicing."""
    server = NativeBusServer().start()
    try:
        payload = {"blob": "é" * 2000, "n": 1.5, "nested": [1, [2, {"x": None}]]}
        errors = []

        def pingpong(tid):
            try:
                c = BusClient(server.host, server.port)
                for i in range(100):
                    c.push(f"h{tid}", {"i": i, **payload})
                    got = c.pop(f"h{tid}", timeout=5.0)
                    assert got["i"] == i and got["blob"] == payload["blob"]
                c.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=pingpong, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors

        # Cross-thread wakeup through the broker
        c1 = BusClient(server.host, server.port)
        c2 = BusClient(server.host, server.port)
        got = []
        t = threading.Thread(
            target=lambda: got.append(c1.pop("wake", timeout=10.0)))
        t.start()
        c2.push("wake", {"v": 42})
        t.join(timeout=10)
        assert got == [{"v": 42}]
        c1.close()
        c2.close()
    finally:
        server.stop()


def test_parked_pop_not_misdelivered_after_fd_reuse():
    """A client that dies with a parked pop must never cause its queued
    response to land on a NEW connection that recycles the same fd
    (waiters carry a connection generation, not just the fd)."""
    import json
    import socket
    import struct
    import time

    server = NativeBusServer().start()
    hdr = struct.Struct(">I")

    def frame(obj):
        d = json.dumps(obj).encode()
        return hdr.pack(len(d)) + d

    try:
        c1 = socket.create_connection((server.host, server.port))
        c1.sendall(frame({"op": "pop", "queue": "q", "timeout": 30}))
        time.sleep(0.2)
        c1.close()
        time.sleep(0.2)

        c2 = BusClient(server.host, server.port)
        assert c2.ping()
        c2.push("q", {"v": 1})
        assert c2.ping()  # response stream must stay in lockstep
        assert c2.pop("q", timeout=1.0) == {"v": 1}
        c2.close()
    finally:
        server.stop()


def test_native_push_many_single_round_trip():
    """A multi-queue scatter over the NATIVE broker must be one
    ``push_many`` op — not W ``push`` round-trips — and must fulfil
    parked waiters exactly like per-item pushes. Verified through the
    ``rafiki_tpu_bus_op_seconds`` op label: the scatter adds one
    push_many observation and zero push observations."""
    from rafiki_tpu.observe import metrics

    server = NativeBusServer().start()
    try:
        c = BusClient(server.host, server.port)
        hist = metrics.registry().histogram("rafiki_tpu_bus_op_seconds")
        before_many = hist.count(backend="tcp", op="push_many",
                                 kind="query")
        before_push = hist.count(backend="tcp", op="push", kind="query")
        items = [(f"q:w{i}", {"batch_id": "b1", "queries": [i],
                              "shard": f"s{i}"}) for i in range(5)]
        c.push_many(items)
        assert not getattr(c, "_no_push_many", False), \
            "native broker negotiated the per-item fallback"
        assert hist.count(backend="tcp", op="push_many",
                          kind="query") == before_many + 1
        assert hist.count(backend="tcp", op="push",
                          kind="query") == before_push
        for i in range(5):
            got = c.pop(f"q:w{i}", timeout=2.0)
            assert got == {"batch_id": "b1", "queries": [i],
                           "shard": f"s{i}"}
        # A parked blocking pop is fulfilled by push_many directly.
        got2 = []
        t = threading.Thread(
            target=lambda: got2.append(c.pop("q:park", timeout=10.0)))
        t.start()
        c2 = BusClient(server.host, server.port)
        c2.push_many([("q:park", {"v": 7})])
        t.join(timeout=10)
        assert got2 == [{"v": 7}]
        c.close()
        c2.close()
    finally:
        server.stop()


def test_sharded_scatter_is_one_push_many_on_native_path():
    """End to end: a replica-SHARDED Predictor scatter over the native
    broker is exactly one query-kind push_many round-trip (not one
    push per shard), per the ``rafiki_tpu_bus_op_seconds`` op label."""
    import time

    from rafiki_tpu.cache import Cache
    from rafiki_tpu.observe import metrics
    from rafiki_tpu.predictor.predictor import Predictor

    server = NativeBusServer().start()
    try:
        worker_bus = BusClient(server.host, server.port)
        cache = Cache(worker_bus)
        cache.register_worker("job", "wA1", info={"trial_id": "tA"})
        cache.register_worker("job", "wA2", info={"trial_id": "tA"})
        stop = threading.Event()

        def worker_loop(wid):
            c = Cache(BusClient(server.host, server.port))
            while not stop.is_set():
                for it in c.pop_queries(wid, timeout=0.1):
                    c.send_prediction_batch(
                        it["batch_id"], wid,
                        [q * 2 for q in it["queries"]],
                        shard=it.get("shard"))

        threads = [threading.Thread(target=worker_loop, args=(w,),
                                    daemon=True)
                   for w in ("wA1", "wA2")]
        [t.start() for t in threads]
        hist = metrics.registry().histogram("rafiki_tpu_bus_op_seconds")
        before_many = hist.count(backend="tcp", op="push_many",
                                 kind="query")
        before_push = hist.count(backend="tcp", op="push", kind="query")
        p = Predictor("job", BusClient(server.host, server.port),
                      gather_timeout=10.0, worker_wait_timeout=10.0)
        assert p.predict(list(range(8))) == [float(q * 2)
                                             for q in range(8)]
        assert hist.count(backend="tcp", op="push_many",
                          kind="query") == before_many + 1
        assert hist.count(backend="tcp", op="push",
                          kind="query") == before_push
        stop.set()
        [t.join(timeout=5) for t in threads]
        time.sleep(0)  # let client sockets settle before teardown
    finally:
        server.stop()


def test_push_many_unknown_op_fallback(monkeypatch):
    """Against an OLD broker (predating the push_many op) the client
    negotiates a permanent per-item fallback instead of failing the
    scatter: same delivered frames, W push round-trips."""
    from rafiki_tpu.bus import BusServer
    from rafiki_tpu.bus.tcp import _Handler

    real_dispatch = _Handler._dispatch

    def old_dispatch(bus, req):
        if req.get("op") == "push_many":
            raise ValueError(f"unknown op: {req.get('op')!r}")
        return real_dispatch(bus, req)

    monkeypatch.setattr(_Handler, "_dispatch",
                        staticmethod(old_dispatch))
    server = BusServer().start()
    try:
        c = BusClient(server.host, server.port)
        c.push_many([("q:a", 1), ("q:b", 2)])
        assert getattr(c, "_no_push_many", False) is True
        assert c.pop("q:a", timeout=1.0) == 1
        assert c.pop("q:b", timeout=1.0) == 2
        # The fallback is sticky: later scatters go straight per-item.
        c.push_many([("q:a", 3)])
        assert c.pop("q:a", timeout=1.0) == 3
        c.close()
    finally:
        server.stop()


def test_serve_broker_fallback_selects():
    server = serve_broker()
    try:
        assert BusClient(server.host, server.port).ping()
    finally:
        server.stop()


def test_broker_crash_is_not_a_clean_shutdown():
    # A child broker dying on its own must surface as an error (process
    # supervisors restart on nonzero exit), while stop() stays clean.
    server = NativeBusServer().start()
    server._proc.kill()
    with pytest.raises(RuntimeError, match="exited with status"):
        server.serve_forever()
    server.stop()  # idempotent after the crash

    server2 = NativeBusServer().start()
    server2.stop()  # deliberate stop: no error
