"""Native C++ bus broker: platform integration + concurrency hammer.

Protocol-level parity with the Python broker is covered by the
parametrized fixture in test_bus.py; these tests drive the broker
through the real platform stack and under concurrent load.
"""

import threading

import pytest

from rafiki_tpu.bus import BusClient, serve_broker
from rafiki_tpu.bus.native import NativeBusServer
from rafiki_tpu.constants import BudgetOption, TaskType, UserType
from rafiki_tpu.platform import LocalPlatform

pytestmark = pytest.mark.skipif(
    not NativeBusServer.available(),
    reason="no C++ toolchain for the native broker")


def test_platform_job_over_native_broker(tmp_path, synth_image_data):
    """The full train-job call stack with every bus op crossing the C++
    broker (workers, advisor RPC, caches)."""
    train_path, val_path = synth_image_data
    server = NativeBusServer().start()
    try:
        p = LocalPlatform(workdir=str(tmp_path / "plat"),
                          bus_uri=server.uri, supervise_interval=0)
        try:
            dev = p.admin.create_user("dev@x.c", "pw",
                                      UserType.MODEL_DEVELOPER)
            model = p.admin.create_model(
                dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
                "rafiki_tpu.models.feedforward:JaxFeedForward")
            job = p.admin.create_train_job(
                dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
                train_path, val_path)
            assert p.admin.wait_until_train_job_done(job["id"],
                                                     timeout=600)
            trials = p.admin.get_train_job(job["id"])
            assert trials["sub_train_jobs"][0]["n_completed"] == 2
        finally:
            p.shutdown()
    finally:
        server.stop()


def test_native_broker_concurrent_hammer():
    """Many threads, interleaved blocking pops and pushes, large-ish
    payloads with non-ASCII strings — exercises the broker's frame
    reassembly, waiter parking, and JSON splicing."""
    server = NativeBusServer().start()
    try:
        payload = {"blob": "é" * 2000, "n": 1.5, "nested": [1, [2, {"x": None}]]}
        errors = []

        def pingpong(tid):
            try:
                c = BusClient(server.host, server.port)
                for i in range(100):
                    c.push(f"h{tid}", {"i": i, **payload})
                    got = c.pop(f"h{tid}", timeout=5.0)
                    assert got["i"] == i and got["blob"] == payload["blob"]
                c.close()
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=pingpong, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors

        # Cross-thread wakeup through the broker
        c1 = BusClient(server.host, server.port)
        c2 = BusClient(server.host, server.port)
        got = []
        t = threading.Thread(
            target=lambda: got.append(c1.pop("wake", timeout=10.0)))
        t.start()
        c2.push("wake", {"v": 42})
        t.join(timeout=10)
        assert got == [{"v": 42}]
        c1.close()
        c2.close()
    finally:
        server.stop()


def test_parked_pop_not_misdelivered_after_fd_reuse():
    """A client that dies with a parked pop must never cause its queued
    response to land on a NEW connection that recycles the same fd
    (waiters carry a connection generation, not just the fd)."""
    import json
    import socket
    import struct
    import time

    server = NativeBusServer().start()
    hdr = struct.Struct(">I")

    def frame(obj):
        d = json.dumps(obj).encode()
        return hdr.pack(len(d)) + d

    try:
        c1 = socket.create_connection((server.host, server.port))
        c1.sendall(frame({"op": "pop", "queue": "q", "timeout": 30}))
        time.sleep(0.2)
        c1.close()
        time.sleep(0.2)

        c2 = BusClient(server.host, server.port)
        assert c2.ping()
        c2.push("q", {"v": 1})
        assert c2.ping()  # response stream must stay in lockstep
        assert c2.pop("q", timeout=1.0) == {"v": 1}
        c2.close()
    finally:
        server.stop()


def test_serve_broker_fallback_selects():
    server = serve_broker()
    try:
        assert BusClient(server.host, server.port).ping()
    finally:
        server.stop()


def test_broker_crash_is_not_a_clean_shutdown():
    # A child broker dying on its own must surface as an error (process
    # supervisors restart on nonzero exit), while stop() stays clean.
    server = NativeBusServer().start()
    server._proc.kill()
    with pytest.raises(RuntimeError, match="exited with status"):
        server.serve_forever()
    server.stop()  # idempotent after the crash

    server2 = NativeBusServer().start()
    server2.stop()  # deliberate stop: no error
