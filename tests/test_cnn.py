"""JaxCnn: VGG-style zoo model with traced width mask."""

import pytest

import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import load_image_dataset, test_model_class
from rafiki_tpu.models import JaxCnn

KNOBS = {"width_16ths": 8, "learning_rate": 3e-3, "batch_size": 64,
         "weight_decay": 1e-4, "max_epochs": 10, "early_stop_epochs": 5}


@pytest.mark.slow
def test_cnn_end_to_end(synth_image_data):
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(2)]
    result = test_model_class(
        JaxCnn, TaskType.IMAGE_CLASSIFICATION, train_path, val_path,
        test_queries=queries, knobs=KNOBS)
    assert result.score > 0.5  # 4 classes; chance is 0.25
    for pred in result.predictions:
        assert len(pred) == ds.n_classes
        assert abs(sum(pred) - 1.0) < 1e-3


@pytest.mark.slow
def test_cnn_width_mask_shares_one_executable(synth_image_data):
    """Different width knobs must reuse the SAME compiled train step
    (that's the point of routing width through extra_apply_inputs)."""
    train_path, _ = synth_image_data
    from rafiki_tpu.model.jax_model import _STEP_CACHE, clear_step_cache

    clear_step_cache()
    base = dict(KNOBS, max_epochs=1, early_stop_epochs=0)
    m1 = JaxCnn(**dict(base, width_16ths=16))
    m1.train(train_path)
    n_after_first = len(_STEP_CACHE)
    m2 = JaxCnn(**dict(base, width_16ths=4, learning_rate=1e-3))
    m2.train(train_path)
    assert len(_STEP_CACHE) == n_after_first  # no new compiled entries
    m1.destroy()
    m2.destroy()

    # The mask must actually change the function: same params, same
    # input, different width masks -> different outputs.
    import jax
    import jax.numpy as jnp
    from rafiki_tpu.models.cnn import _Cnn

    module = _Cnn(n_classes=4)
    x = jnp.asarray(np.random.default_rng(0).random((1, 12, 12, 1)),
                    jnp.float32)
    variables = module.init(jax.random.key(0), x)
    full = (np.arange(16) < 16).astype(np.float32)
    quarter = (np.arange(16) < 4).astype(np.float32)
    out_full = module.apply(variables, x, width_16ths=jnp.asarray(full))
    out_q = module.apply(variables, x, width_16ths=jnp.asarray(quarter))
    assert not np.allclose(np.asarray(out_full), np.asarray(out_q))
