"""Typed node config (SURVEY.md §5 "Config / flag system" rebuild)."""

import pytest

from rafiki_tpu.config import NodeConfig


def test_defaults_validate():
    cfg = NodeConfig.from_env(env={})
    assert cfg.port == 3000 and cfg.workdir == "./rafiki_workdir"
    # serving_pipeline defaults to None = auto (workers measure their
    # sync latency at startup and decide).
    assert cfg.serving_pipeline is None and not cfg.checkpoint_trials
    assert cfg.n_chips is None and cfg.bus_uri == ""


def test_env_parsing_and_types():
    cfg = NodeConfig.from_env(env={
        "RAFIKI_TPU_PORT": "8080",
        "RAFIKI_TPU_N_CHIPS": "4",
        "RAFIKI_TPU_BUS_URI": "tcp://10.0.0.1:6380",
        "RAFIKI_TPU_SUPERVISE_INTERVAL": "2.5",
        "RAFIKI_TPU_SERVING_PIPELINE": "0",
        "RAFIKI_TPU_CKPT": "1",
        "RAFIKI_TPU_TRACE_DIR": "/tmp/traces",
        "RAFIKI_TPU_PROBE_TIMEOUT": "15",
    })
    assert cfg.port == 8080 and cfg.n_chips == 4
    assert cfg.bus_uri == "tcp://10.0.0.1:6380"
    assert cfg.supervise_interval == 2.5
    assert cfg.serving_pipeline is False
    assert cfg.checkpoint_trials is True
    assert cfg.trace_dir == "/tmp/traces"
    assert cfg.probe_timeout == 15.0


def test_cli_overrides_beat_env():
    cfg = NodeConfig.from_env(env={"RAFIKI_TPU_PORT": "8080"},
                              port=9090, workdir=None)
    assert cfg.port == 9090                   # explicit override wins
    assert cfg.workdir == "./rafiki_workdir"  # None = not given


def test_validation_errors():
    with pytest.raises(ValueError):
        NodeConfig.from_env(env={}, port=-1)
    with pytest.raises(ValueError):
        NodeConfig.from_env(env={}, n_chips=0)
    with pytest.raises(ValueError):
        NodeConfig.from_env(env={}, log_level="loud")
    with pytest.raises(ValueError):
        NodeConfig.from_env(env={}, bus_uri="redis://x")
    with pytest.raises(ValueError):
        NodeConfig.from_env(env={}, coordinator="h:1")  # partial triple
    with pytest.raises(ValueError):
        NodeConfig.from_env(env={"RAFIKI_TPU_PORT": "not-a-number"})


def test_multihost_triple_accepted():
    cfg = NodeConfig.from_env(env={}, coordinator="h:1234",
                              num_processes=2, process_id=0)
    assert cfg.coordinator == "h:1234"


def test_apply_env_round_trip(monkeypatch):
    # setenv (not delenv) so monkeypatch restores the pre-test state
    # even though apply_env() mutates os.environ during the test.
    monkeypatch.setenv("RAFIKI_TPU_SERVING_PIPELINE", "1")
    monkeypatch.setenv("RAFIKI_TPU_CKPT", "")
    cfg = NodeConfig.from_env(env={}, serving_pipeline=False,
                              checkpoint_trials=True)
    cfg.apply_env()
    import os

    assert os.environ["RAFIKI_TPU_SERVING_PIPELINE"] == "0"
    assert os.environ["RAFIKI_TPU_CKPT"] == "1"
    # Workers constructed now resolve the node's validated values.
    from rafiki_tpu.bus import MemoryBus
    from rafiki_tpu.worker.inference import InferenceWorker

    w = InferenceWorker("s", "j", "t", None, None, MemoryBus())
    assert w.pipeline is False


def test_serving_microbatch_knobs(monkeypatch):
    """Micro-batcher knobs: env parsing, validation bounds, and the
    apply_env -> PredictorService handoff."""
    cfg = NodeConfig.from_env(env={})
    assert cfg.serving_microbatch is True
    assert cfg.serving_fill_window == 0.005
    assert cfg.serving_max_inflight == 2
    cfg = NodeConfig.from_env(env={
        "RAFIKI_TPU_SERVING_MICROBATCH": "0",
        "RAFIKI_TPU_SERVING_FILL_WINDOW": "0.02",
        "RAFIKI_TPU_SERVING_MAX_BATCH": "256",
        "RAFIKI_TPU_SERVING_MAX_INFLIGHT": "3",
        "RAFIKI_TPU_SERVING_QUEUE_CAP": "512",
    })
    assert cfg.serving_microbatch is False
    assert cfg.serving_fill_window == 0.02
    assert cfg.serving_max_batch == 256
    assert cfg.serving_max_inflight == 3
    assert cfg.serving_queue_cap == 512
    with pytest.raises(ValueError, match="serving_fill_window"):
        NodeConfig.from_env(env={}, serving_fill_window=-0.1)
    with pytest.raises(ValueError, match="serving_max_batch"):
        NodeConfig.from_env(env={}, serving_queue_cap=0)

    # apply_env exports the knobs; a PredictorService constructed after
    # (in-process or spawned) resolves the node's validated values.
    for var in ("RAFIKI_TPU_SERVING_MICROBATCH",
                "RAFIKI_TPU_SERVING_FILL_WINDOW",
                "RAFIKI_TPU_SERVING_MAX_BATCH",
                "RAFIKI_TPU_SERVING_MAX_INFLIGHT",
                "RAFIKI_TPU_SERVING_QUEUE_CAP"):
        monkeypatch.setenv(var, "unset-sentinel")
    NodeConfig.from_env(env={}, serving_fill_window=0.03,
                        serving_queue_cap=128).apply_env()
    import os

    assert os.environ["RAFIKI_TPU_SERVING_MICROBATCH"] == "1"
    assert os.environ["RAFIKI_TPU_SERVING_FILL_WINDOW"] == "0.03"
    assert os.environ["RAFIKI_TPU_SERVING_QUEUE_CAP"] == "128"
    from rafiki_tpu.bus import MemoryBus
    from rafiki_tpu.predictor.app import PredictorService

    svc = PredictorService("s", "j", None, MemoryBus())
    assert svc.batcher is not None
    assert svc.batcher.fill_window == 0.03
    assert svc.batcher.queue_cap == 128


def test_from_config_platform(tmp_path):
    from rafiki_tpu.platform import LocalPlatform

    cfg = NodeConfig.from_env(env={}, workdir=str(tmp_path / "n"),
                              supervise_interval=0.0)
    p = LocalPlatform.from_config(cfg)
    try:
        assert p.workdir == str(tmp_path / "n")
        assert p.app is None
    finally:
        p.shutdown()


def test_trial_lifecycle_knobs(monkeypatch):
    """r9: the residency-cache budgets + advisor prefetch are NodeConfig
    fields with env parity and apply_env export."""
    cfg = NodeConfig.from_env(env={
        "RAFIKI_TPU_DATASET_CACHE_BYTES": "1024",
        "RAFIKI_TPU_STAGE_CACHE_BYTES": "0",
        "RAFIKI_TPU_ADVISOR_PREFETCH": "off",
    })
    assert cfg.dataset_cache_bytes == 1024
    assert cfg.stage_cache_bytes == 0
    assert cfg.advisor_prefetch is False
    import os

    # setenv sentinels (not delenv): apply_env() mutates os.environ
    # outside monkeypatch's bookkeeping, and a delenv of an ABSENT var
    # registers no undo — the non-default budgets below (stage cache 0!)
    # would otherwise leak into every later test in the session.
    for var in ("RAFIKI_TPU_DATASET_CACHE_BYTES",
                "RAFIKI_TPU_STAGE_CACHE_BYTES",
                "RAFIKI_TPU_ADVISOR_PREFETCH"):
        monkeypatch.setenv(var, "unset-sentinel")
    cfg.apply_env()
    assert os.environ["RAFIKI_TPU_DATASET_CACHE_BYTES"] == "1024"
    assert os.environ["RAFIKI_TPU_STAGE_CACHE_BYTES"] == "0"
    assert os.environ["RAFIKI_TPU_ADVISOR_PREFETCH"] == "0"
    # the caches honor the exported budgets immediately
    from rafiki_tpu.model.dataset import dataset_cache_budget

    assert dataset_cache_budget() == 1024
    with pytest.raises(ValueError):
        NodeConfig(dataset_cache_bytes=-1).validate()


def test_every_nodeconfig_knob_is_documented():
    """Tier-1 gate: scripts/check_knob_docs.py asserts every NodeConfig
    env knob appears in docs/ops.md, so a new knob can't silently go
    undocumented."""
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo_root, "scripts", "check_knob_docs.py"),
         repo_root],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "documented in docs/ops.md" in proc.stdout


def test_knob_docs_check_catches_missing(tmp_path):
    import os
    import shutil
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    (tmp_path / "rafiki_tpu").mkdir()
    shutil.copy(os.path.join(repo_root, "rafiki_tpu", "config.py"),
                tmp_path / "rafiki_tpu" / "config.py")
    (tmp_path / "docs").mkdir()
    # RAFIKI_TPU_METRICS_PORT present must NOT count as documenting
    # RAFIKI_TPU_METRICS (delimited-token match, not substring).
    (tmp_path / "docs" / "ops.md").write_text(
        "| `RAFIKI_TPU_WORKDIR` | only one knob documented |\n"
        "also mentions RAFIKI_TPU_METRICS_PORT in passing\n")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo_root, "scripts", "check_knob_docs.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "RAFIKI_TPU_DATASET_CACHE_BYTES" in proc.stdout
    assert "NodeConfig.metrics (RAFIKI_TPU_METRICS)" in proc.stdout
    assert "RAFIKI_TPU_WORKDIR" not in proc.stdout
