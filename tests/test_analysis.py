"""The repo-native static-analysis suite (docs/analysis.md).

Three layers, all tier-1:

- **Fixture corpus**: per checker, one tree of true positives and one
  of correct code that must stay finding-free (the false-positive
  guard) — ``tests/analysis_fixtures/``.
- **Mutation gates**: deleting the PR 2 series ``.remove()`` calls or
  widening the PR 4 never-donate guard in a copy of the REAL source
  makes the suite fail — the acceptance property that the checkers
  actually protect the invariants they claim to.
- **Integration**: the suite runs clean on this repo against the
  committed baseline (zero new findings), and the baseline itself
  stays short and reason-annotated.
"""

import json
import os
import shutil
import subprocess
import sys

from rafiki_tpu.analysis import core
from rafiki_tpu.analysis.core import (
    Finding,
    load_baseline,
    run_suite,
    save_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")


def _tree(tmp_path, *fixture_files):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir(exist_ok=True)
    for name in fixture_files:
        shutil.copy(os.path.join(FIXTURES, name), pkg / name)
    return str(tmp_path)


def _codes(report):
    return sorted({f.code for f in report.findings})


def _run(root, checker):
    return run_suite(root, only=[checker])


# --- Fixture corpus: true positive + false-positive guard per checker


def test_guarded_state_true_positives(tmp_path):
    report = _run(_tree(tmp_path, "guarded_tp.py"), "guarded-state")
    codes = _codes(report)
    assert "RTA101" in codes and "RTA102" in codes and "RTA103" in codes
    by_anchor = {f.anchor for f in report.findings}
    assert "UnguardedAccess._depth@depth" in by_anchor
    # module-global arm: a global guarded by a module lock at some
    # accesses but read bare in a free function
    assert "guarded_tp:_mod_depth@mod_depth" in by_anchor
    assert "SelfDeadlock:_lock->_lock" in by_anchor
    assert "LockOrderCycle:_a<->_b" in by_anchor
    # the blocking sleep AND the open() under the lock
    assert any("time.sleep" in f.message for f in report.findings)
    assert any("open()" in f.message for f in report.findings)


def test_guarded_state_false_positive_guard(tmp_path):
    report = _run(_tree(tmp_path, "guarded_fp.py"), "guarded-state")
    assert report.findings == [], [f.render() for f in report.findings]


def test_thread_lifecycle_true_positives(tmp_path):
    report = _run(_tree(tmp_path, "thread_tp.py"), "thread-lifecycle")
    codes = _codes(report)
    assert codes == ["RTA201", "RTA202"]


def test_thread_lifecycle_false_positive_guard(tmp_path):
    report = _run(_tree(tmp_path, "thread_fp.py"), "thread-lifecycle")
    assert report.findings == [], [f.render() for f in report.findings]


def test_series_lifecycle_true_positive(tmp_path):
    report = _run(_tree(tmp_path, "series_tp.py"), "series-lifecycle")
    assert _codes(report) == ["RTA301"]
    anchors = {f.anchor for f in report.findings}
    assert "label:service" in anchors
    # r17 attribution-ledger shape: a hashed tenant key and a bin id
    # are dynamic labels exactly like a service id.
    assert "label:tenant" in anchors
    assert "label:bin" in anchors


def test_series_lifecycle_false_positive_guard(tmp_path):
    report = _run(_tree(tmp_path, "series_fp.py"), "series-lifecycle")
    assert report.findings == [], [f.render() for f in report.findings]


def test_donation_true_positives(tmp_path):
    report = _run(_tree(tmp_path, "donation_tp.py"), "donation")
    codes = _codes(report)
    assert "RTA401" in codes and "RTA402" in codes
    # the cache-tainted array reached the donated slot via the
    # dispatch forwarder, not a direct call
    assert any("data_dev" in f.message for f in report.findings
               if f.code == "RTA401")
    # r13: taint flows through neutral-named helper RETURNS (and a
    # helper-calls-helper chain) into the donated slot
    assert any("resident" in f.message for f in report.findings
               if f.code == "RTA401")


def test_donation_false_positive_guard(tmp_path):
    report = _run(_tree(tmp_path, "donation_fp.py"), "donation")
    assert report.findings == [], [f.render() for f in report.findings]


def test_drift_true_positives(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "drift_tp"), root)
    report = _run(root, "drift")
    codes = _codes(report)
    assert codes == ["RTA501", "RTA502", "RTA503", "RTA504", "RTA505",
                     "RTA506"]
    msgs = "\n".join(f.message for f in report.findings)
    assert "rafiki_tpu_serving_widgets" in msgs          # shape
    assert "'mystery'" in msgs                           # subsystem
    assert "rafiki_tpu_bus_retries_seconds" in msgs      # counter unit
    assert "rafiki_tpu_renamed_away_total" in msgs       # dashboard
    assert "RAFIKI_TPU_MYSTERY_KNOB" in msgs             # docs + parity
    assert "RAFIKI_TPU_ROGUE_TWEAK" in msgs              # rogue env
    # RTA506 fires on BOTH sources: the consumed-series vocabulary in
    # observe/slo.py and a docs/slo rules file's metric override.
    assert "rafiki_tpu_serving_gone_seconds" in msgs     # slo module
    assert "rafiki_tpu_serving_vanished_seconds" in msgs  # rules file
    # ...but a rule naming a registered series stays clean.
    assert "rafiki_tpu_bus_wait_seconds'" not in msgs


def test_drift_false_positive_guard(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "drift_fp"), root)
    report = _run(root, "drift")
    assert report.findings == [], [f.render() for f in report.findings]


def test_concurrency_true_positives(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "concurrency_tp"), root)
    report = _run(root, "concurrency")
    codes = _codes(report)
    assert codes == ["RTA104", "RTA105", "RTA106"]
    by_anchor = {f.anchor: f for f in report.findings}
    # The cross-class cycle was found through a >=3-frame cross-module
    # chain: the message must name the intermediate frames.
    cyc = by_anchor["Coordinator._lock<->StatsSink._lock"]
    assert "Coordinator._tick" in cyc.message
    assert "Coordinator._note" in cyc.message
    assert "sink.py" in cyc.message  # the reverse path's module
    # Blocking two module-function frames down, none of it in admit().
    blk = by_anchor["Admission.admit->_backoff:time.sleep()"]
    assert "_backoff -> _pause" in blk.message
    # Thread-root pair sharing an attribute: Thread target and an HTTP
    # route handler both fire.
    assert "Poller._latest:cross-root" in by_anchor
    assert "MiniService._hits:cross-root" in by_anchor
    # Cross-class root: the owner registers Thread(target=
    # self.consumer.loop); the finding lands on the CONSUMER's class.
    cc = by_anchor["BusConsumer._seen:cross-root"]
    assert "'loop'" in cc.message and cc.path.endswith("consumer.py")
    # Executor form of the same blindness: the owner's
    # pool.submit(self.stage.drain) makes drain a root on the
    # consumer's class too.
    sc = by_anchor["SubmitConsumer._polled:cross-root"]
    assert "'drain'" in sc.message and sc.path.endswith("consumer.py")
    # Module-global lock, chained blocking (free functions only the
    # whole-program pass can see)...
    mg = by_anchor["publish->_settle:time.sleep()"]
    assert "rafiki_tpu.registry._REG_LOCK" in mg.message
    # ...the direct form RTA102 can never reach...
    assert "drain:time.sleep():direct" in by_anchor
    # ...and a lock-order cycle between a CLASS lock and a MODULE one.
    assert "Journal._lock<->rafiki_tpu.registry._REG_LOCK" in by_anchor
    # r19 carry: the DOTTED spelling (``registry._REG_LOCK`` from a
    # ``from rafiki_tpu import registry`` import) must unify with the
    # bare name — a free function blocking under it...
    dd = by_anchor["flush:time.sleep():direct"]
    assert "rafiki_tpu.registry._REG_LOCK" in dd.message
    assert dd.path.endswith("dotted.py")
    # ...and a class-vs-module cycle reached only through the dotted
    # reference.
    assert "Ledger._lock<->rafiki_tpu.registry._REG_LOCK" in by_anchor
    # socketserver shape: ``FrameServer((h, p), FrameHandler)`` makes
    # handle() a per-connection thread root on the HANDLER class.
    hh = by_anchor["FrameHandler._hits:cross-root"]
    assert "'handle'" in hh.message and hh.path.endswith("server.py")
    # Spawn-PARAMETER root: the owner hands self.worker.loop to a
    # DIFFERENT class's register_consumer(fn) — which is what calls
    # Thread(target=fn) — and the root still lands on the worker.
    sp = by_anchor["ParamWorker._seen:cross-root"]
    assert "'loop'" in sp.message and sp.path.endswith("spawnhelper.py")
    # Module<->module lock-order cycle: two free functions, no class
    # anywhere — only the module-owner cycle arm sees both directions.
    assert ("rafiki_tpu.modlocks._FLUSH_LOCK<->"
            "rafiki_tpu.modlocks._INGEST_LOCK") in by_anchor


def test_concurrency_false_positive_guard(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "concurrency_fp"), root)
    report = _run(root, "concurrency")
    assert report.findings == [], [f.render() for f in report.findings]


def test_import_hygiene_true_positives(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "imports_tp"), root)
    report = _run(root, "import-hygiene")
    codes = _codes(report)
    assert codes == ["RTA601", "RTA602"]
    msgs = "\n".join(f.message for f in report.findings)
    assert "builds/starts a thread" in msgs
    assert "binds a socket/server" in msgs
    assert "spawns a process" in msgs
    assert "APP_DEBUG" in msgs       # module-level env read
    assert "APP_LEASE" in msgs       # class-BODY env read (executes
    #                                  on import — the NODE_LEASE bug)
    assert "APP_ELSE" in msgs        # else-arm of a __main__ guard
    assert "APP_INVERTED" in msgs    # body of an inverted guard
    assert "APP_SUB_LEASE" in msgs   # os.environ["X"] subscript read
    jax_f = [f for f in report.findings if f.code == "RTA602"]
    assert len(jax_f) == 1
    # The finding names the import chain from the bus root.
    assert "rafiki_tpu/bus/broker.py -> rafiki_tpu/heavy.py" \
        in jax_f[0].message


def test_import_hygiene_false_positive_guard(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "imports_fp"), root)
    report = _run(root, "import-hygiene")
    assert report.findings == [], [f.render() for f in report.findings]


def test_flow_true_positives(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "flow_tp"), root)
    report = _run(root, "flow")
    assert _codes(report) == ["RTA701", "RTA702", "RTA703"]
    by_anchor = {f.anchor: f for f in report.findings}
    # RTA701: a family pushed but never popped, a family popped but
    # never pushed, and a control-frame op token on each unbalanced
    # side (produced-never-dispatched / dispatched-never-produced).
    assert "queue:work:" in by_anchor
    assert "queue:lost:" in by_anchor
    assert "op-token:__flush__" in by_anchor
    assert "op-token:__drain2__" in by_anchor
    # RTA702: a client typo that matches no served route, and a served
    # route no in-tree caller reaches.
    typo = by_anchor["route-call:GET /thingz"]
    assert typo.path.endswith("client.py")
    assert "route:POST /orphan" in by_anchor
    # RTA703: every off-path leak class for the fabric flag — an
    # import-time thread in the owned module, owned-module effects in
    # unprotected functions, an owned-prefix series registered outside
    # the owned module, and an ungated constructor of an owned class.
    flag = "RAFIKI_TPU_CLUSTER_FABRIC"
    assert f"{flag}:import-effect:Thread()" in by_anchor
    assert (f"{flag}:offpath:NodeRegistry.__init__:"
            "rafiki_tpu_node_peers") in by_anchor
    assert f"{flag}:offpath:spawn_pinger:Thread()" in by_anchor
    assert f"{flag}:series:rafiki_tpu_serving_fabric_total" in by_anchor
    assert (f"{flag}:unguarded-ctor:NodeRegistry@"
            "Platform.__init__") in by_anchor


def test_flow_false_positive_guard(tmp_path):
    root = str(tmp_path / "t")
    shutil.copytree(os.path.join(FIXTURES, "flow_fp"), root)
    report = _run(root, "flow")
    assert report.findings == [], [f.render() for f in report.findings]


# --- Waivers -----------------------------------------------------------


def test_waiver_with_reason_suppresses(tmp_path):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "    def b(self):\n"
        "        # rta: disable=RTA101 benign monotonic peek\n"
        "        return self._n\n")
    report = run_suite(str(tmp_path), only=["guarded-state"])
    assert report.new == []
    waived = [f for f in report.findings if f.status == "waived"]
    assert len(waived) == 1
    assert waived[0].reason == "benign monotonic peek"


def test_waiver_without_reason_is_its_own_finding(tmp_path):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "    def b(self):\n"
        "        # rta: disable=RTA101\n"
        "        return self._n\n")
    report = run_suite(str(tmp_path), only=["guarded-state"])
    new_codes = sorted(f.code for f in report.new)
    # the reasonless waiver does NOT suppress, and is flagged itself
    assert new_codes == ["RTA001", "RTA101"]


def test_waiver_class_form_covers_all_codes(tmp_path):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "            # rta: disable=RTA1xx startup-only path, held <1ms\n"
        "            time.sleep(0.001)\n")
    report = run_suite(str(tmp_path), only=["guarded-state"])
    assert report.new == []
    assert any(f.status == "waived" and f.code == "RTA102"
               for f in report.findings)


def test_stale_waiver_true_positive(tmp_path):
    """A reasoned waiver whose finding no longer fires is RTA003 —
    and the unknown-code form is covered by a FULL run."""
    root = _tree(tmp_path, "stale_waiver_tp.py")
    report = run_suite(root, only=["guarded-state"])
    stale = [f for f in report.new if f.code == "RTA003"]
    # Only the RTA101 waiver under --checker scoping (RTA999 belongs
    # to no ran checker, so the scoped run cannot judge it).
    assert len(stale) == 1 and "RTA101" in stale[0].message
    full = run_suite(root)
    msgs = [f.message for f in full.new if f.code == "RTA003"]
    assert len(msgs) == 2 and any("RTA999" in m for m in msgs)


def test_stale_waiver_false_positive_guard(tmp_path):
    """A waiver that suppresses a live finding (same-line and
    comment-above forms) is never stale."""
    report = run_suite(_tree(tmp_path, "stale_waiver_fp.py"),
                       only=["guarded-state"])
    assert not [f for f in report.new if f.code == "RTA003"]
    assert len([f for f in report.findings
                if f.status == "waived"]) == 2


def test_stale_waiver_is_unwaivable(tmp_path):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f():\n"
        "    # rta: disable=RTA003 trying to silence the detector\n"
        "    # rta: disable=RTA101 stale reason\n"
        "    return 1\n")
    report = run_suite(str(tmp_path), only=["guarded-state"])
    codes = sorted(f.code for f in report.new)
    assert codes.count("RTA003") >= 1  # the stale RTA101 waiver
    # ... and the RTA003-waiver itself is both inert and stale.
    assert codes.count("RTA003") == 2


def test_stale_waiver_skipped_in_changed_mode(tmp_path):
    """--changed runs see a partial file view; stale-waiver judgment
    would be unsound there and must not fire."""
    root = _tree(tmp_path, "stale_waiver_tp.py")
    report = run_suite(root, changed={"rafiki_tpu/stale_waiver_tp.py"})
    assert not [f for f in report.findings if f.code == "RTA003"]


def test_fixing_waived_finding_without_deleting_waiver_fails_suite(
        tmp_path):
    """Mutation gate on REAL source: jax_model.py's RTA301 waiver is
    live because the train loop samples per-trial labels; removing
    the labeled samples while keeping the comment must turn the suite
    red with RTA003 (the rotting-disable class)."""
    clean = _mutated_tree(tmp_path / "clean",
                          "rafiki_tpu/model/jax_model.py", [])
    report = run_suite(clean, only=["series-lifecycle"])
    assert not [f for f in report.new
                if f.code in ("RTA003", "RTA301")]
    mutated = _mutated_tree(
        tmp_path / "mut", "rafiki_tpu/model/jax_model.py",
        [(", **_mlabels)", ")")])
    report = run_suite(mutated, only=["series-lifecycle"])
    assert any(f.code == "RTA003" for f in report.new)


def test_waiver_inside_string_literal_is_inert(tmp_path):
    """Waiver-shaped text in a string/docstring is not a comment: it
    must neither suppress the adjacent finding nor mint an RTA001."""
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "    def b(self):\n"
        '        s = "# rta: disable=RTA101 just a string"\n'
        "        return self._n, s\n")
    report = run_suite(str(tmp_path), only=["guarded-state"])
    new_codes = sorted(f.code for f in report.new)
    assert new_codes == ["RTA101"]  # not waived, and no RTA001


def test_thread_in_module_level_block_is_flagged(tmp_path):
    """A non-daemon, never-joined Thread built under a module-level
    if/try block is still module-level — the checker must descend."""
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading\n"
        "if True:\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n")
    report = run_suite(str(tmp_path), only=["thread-lifecycle"])
    assert any(f.code == "RTA201" for f in report.new), \
        [f.render() for f in report.findings]


# --- Baseline ----------------------------------------------------------


def test_baseline_freezes_and_unreviewed_fails(tmp_path):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "    def b(self):\n"
        "        return self._n\n")
    # A full run needs a loadable NodeConfig (RTA503) even in a bare
    # fixture tree.
    (pkg / "config.py").write_text(
        "import dataclasses\n\n\n"
        "@dataclasses.dataclass\n"
        "class NodeConfig:\n"
        "    pass\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "ops.md").write_text("# Ops\n")
    ident = "RTA101:rafiki_tpu/mod.py:C._n@b"
    # A reviewed reason freezes the finding.
    report = run_suite(str(tmp_path), only=["guarded-state"],
                       baseline={ident: "pre-existing, tracked in r10"})
    assert report.new == []
    assert any(f.status == "baselined" for f in report.findings)
    # An UNREVIEWED placeholder keeps failing via RTA002.
    report = run_suite(str(tmp_path), only=["guarded-state"],
                       baseline={ident: "UNREVIEWED: fill me in"})
    assert any(f.code == "RTA002" for f in report.new)
    # A stale entry is reported for pruning, not a failure — but only
    # on a FULL run: a scoped run never produces findings for
    # unscanned checkers/files, so its "missing" entries aren't fixed.
    stale_bl = {ident: "ok reason",
                "RTA101:rafiki_tpu/gone.py:X._y@z": "fixed long ago"}
    report = run_suite(str(tmp_path), baseline=stale_bl)
    assert report.new == []
    assert report.stale_baseline == ["RTA101:rafiki_tpu/gone.py:X._y@z"]
    report = run_suite(str(tmp_path), only=["guarded-state"],
                       baseline=stale_bl)
    assert report.new == []
    assert report.stale_baseline == []


def test_update_baseline_round_trip(tmp_path):
    findings = [Finding(code="RTA101", path="rafiki_tpu/m.py", line=3,
                        message="msg", anchor="C._n@b")]
    path = str(tmp_path / "baseline.json")
    save_baseline(path, findings, prior={})
    loaded = load_baseline(path)
    ident = "RTA101:rafiki_tpu/m.py:C._n@b"
    assert ident in loaded and loaded[ident].startswith("UNREVIEWED")
    # a human writes the reason; re-saving preserves it
    save_baseline(path, findings,
                  prior={ident: "benign: snapshot read"})
    assert load_baseline(path)[ident] == "benign: snapshot read"
    # meta-findings are never frozen: the classifier ignores baseline
    # entries for them, so saving them would only accrete dead weight
    save_baseline(path, findings + [
        Finding(code="RTA001", path="rafiki_tpu/m.py", line=9,
                message="waiver without a reason", anchor="waiver:9")],
        prior={ident: "benign: snapshot read"})
    assert list(load_baseline(path)) == [ident]


def test_update_baseline_refuses_changed_scope(tmp_path):
    """--changed --update-baseline would rewrite the baseline from a
    partial report, silently dropping every frozen entry outside the
    changed set — the CLI must refuse the combination."""
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--changed",
         "--update-baseline",
         "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 2
    assert "requires a full run" in proc.stderr
    assert not (tmp_path / "bl.json").exists()


# --- Mutation gates: the suite protects the real invariants -----------


def _mutated_tree(tmp_path, rel_src, replacements, dst_name=None):
    """Copy ONE real source file into a fixture tree, applying textual
    mutations. ``dst_name`` may carry subdirectories (to preserve a
    package path the checker keys on, e.g. ``bus/base.py``)."""
    with open(os.path.join(REPO, rel_src), encoding="utf-8") as f:
        text = f.read()
    for old, new in replacements:
        assert old in text, f"mutation target {old!r} missing in {rel_src}"
        text = text.replace(old, new)
    dst = tmp_path / "rafiki_tpu" / (dst_name or
                                     os.path.basename(rel_src))
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(text)
    return str(tmp_path)


def test_deleting_serving_stats_remove_fails_suite(tmp_path):
    """PR 2 invariant: ServingStats.close() must drop its per-instance
    series; deleting the .remove() call is a suite failure."""
    clean = _mutated_tree(tmp_path / "clean",
                          "rafiki_tpu/observe/serving.py", [])
    report = run_suite(clean, only=["series-lifecycle"])
    assert not [f for f in report.new if f.code == "RTA301"]
    mutated = _mutated_tree(tmp_path / "mut",
                            "rafiki_tpu/observe/serving.py",
                            [("m.remove(service=self.service)", "pass")])
    report = run_suite(mutated, only=["series-lifecycle"])
    assert any(f.code == "RTA301" and f.anchor == "label:service"
               for f in report.new)


def test_deleting_trial_series_remove_fails_suite(tmp_path):
    """PR 2 invariant: TrialRunner must drop the per-trial train
    series at trial end; deleting the .remove() call is a failure."""
    clean = _mutated_tree(tmp_path / "clean",
                          "rafiki_tpu/worker/runner.py", [])
    report = run_suite(clean, only=["series-lifecycle"])
    assert not [f for f in report.new if f.code == "RTA301"]
    mutated = _mutated_tree(tmp_path / "mut",
                            "rafiki_tpu/worker/runner.py",
                            [("m.remove(trial=trial_id[:12])", "pass")])
    report = run_suite(mutated, only=["series-lifecycle"])
    assert any(f.code == "RTA301" and f.anchor == "label:trial"
               for f in report.new)


def test_donating_staged_arrays_fails_suite(tmp_path):
    """PR 4 invariant: the staged dataset arrays are never donated;
    widening donate_argnums to cover them is a suite failure."""
    clean = _mutated_tree(tmp_path / "clean",
                          "rafiki_tpu/model/jax_model.py", [])
    report = run_suite(clean, only=["donation"])
    assert not [f for f in report.new if f.code.startswith("RTA4")]
    mutated = _mutated_tree(tmp_path / "mut",
                            "rafiki_tpu/model/jax_model.py",
                            [("donate_argnums=(0,)",
                              "donate_argnums=(0, 1, 2)")])
    report = run_suite(mutated, only=["donation"])
    assert any(f.code == "RTA401" for f in report.new), \
        [f.render() for f in report.new]


def test_unguarded_cross_thread_write_fails_suite(tmp_path):
    """r14 breaker-class invariant: _PersistStage state is shared
    between the executor-submitted tail and the trial loop ONLY under
    its lock; stripping the locks (the unguarded-cross-thread-write
    mutation) must turn the suite red via RTA106."""
    clean = _mutated_tree(tmp_path / "clean",
                          "rafiki_tpu/worker/runner.py", [])
    report = run_suite(clean, only=["concurrency"])
    assert not [f for f in report.new if f.code == "RTA106"], \
        [f.render() for f in report.new]
    mutated = _mutated_tree(tmp_path / "mut",
                            "rafiki_tpu/worker/runner.py",
                            [("with self._lock:", "if True:")])
    report = run_suite(mutated, only=["concurrency"])
    cross = [f for f in report.new if f.code == "RTA106"]
    assert any(f.anchor == "_PersistStage._pending:cross-root"
               for f in cross), [f.render() for f in report.new]


def test_unguarded_decode_admission_queue_fails_suite(tmp_path):
    """r18 invariant: DecodeScheduler._pending is the ONE piece of
    state shared between the serve-loop thread (submit) and the decode
    loop — a thread the scheduler never constructs itself
    (InferenceWorker registers Thread(target=self._gen_sched.loop)),
    so only the cross-class root inventory can see the pair. Stripping
    the Condition must turn the suite red via RTA106."""
    for name, reps in (
            ("clean", []),
            ("mut", [("with self._cv:", "if True:")])):
        root = _mutated_tree(tmp_path / name,
                             "rafiki_tpu/worker/decode_scheduler.py",
                             reps, dst_name="worker/decode_scheduler.py")
        _mutated_tree(tmp_path / name, "rafiki_tpu/worker/inference.py",
                      [], dst_name="worker/inference.py")
        report = run_suite(root, only=["concurrency"])
        cross = [f for f in report.new if f.code == "RTA106" and
                 f.anchor == "DecodeScheduler._pending:cross-root"]
        if name == "clean":
            assert cross == [], [f.render() for f in cross]
        else:
            assert cross, [f.render() for f in report.new]
            assert "'loop'" in cross[0].message


def test_blocking_under_module_lock_fails_suite(tmp_path):
    """r17 carry: the workload recorder's module-global gate lock sits
    on the request hot path; introducing a sleep under it must turn
    the suite red via RTA105. Free functions are invisible to the
    per-class RTA102 — this gate proves the module-lock plane actually
    protects the real source."""
    clean = _mutated_tree(tmp_path / "clean",
                          "rafiki_tpu/observe/workload.py", [])
    report = run_suite(clean, only=["concurrency"])
    assert not [f for f in report.new if f.code == "RTA105"], \
        [f.render() for f in report.new]
    mutated = _mutated_tree(
        tmp_path / "mut", "rafiki_tpu/observe/workload.py",
        [("    with _lock:\n"
          "        rec = _state[0] if _state is not None else None\n"
          "        _log_dir = log_dir or None",
          "    with _lock:\n"
          "        time.sleep(0.01)\n"
          "        rec = _state[0] if _state is not None else None\n"
          "        _log_dir = log_dir or None")])
    report = run_suite(mutated, only=["concurrency"])
    assert any(f.code == "RTA105" and
               f.anchor == "configure:time.sleep():direct"
               for f in report.new), [f.render() for f in report.new]


def test_handler_thread_root_fails_suite(tmp_path):
    """r19 carry: the TCP broker's ``_Handler`` runs ``handle()`` on a
    per-connection thread because ``_Server((host, port), _Handler)``
    registers it — a root no ``threading.Thread`` scan can see.
    Introducing an unguarded cross-root attribute on the handler must
    turn the suite red via RTA106; the clean source must stay green."""
    clean = _mutated_tree(tmp_path / "clean", "rafiki_tpu/bus/tcp.py", [])
    report = run_suite(clean, only=["concurrency"])
    assert not [f for f in report.new
                if f.code == "RTA106" and "_Handler" in f.anchor], \
        [f.render() for f in report.new]
    mutated = _mutated_tree(
        tmp_path / "mut", "rafiki_tpu/bus/tcp.py",
        [("class _Handler(socketserver.BaseRequestHandler):\n"
          "    def handle(self):",
          "class _Handler(socketserver.BaseRequestHandler):\n"
          "    def frames_served(self):\n"
          "        return self._frames\n"
          "\n"
          "    def handle(self):\n"
          "        self._frames = getattr(self, \"_frames\", 0) + 1")])
    report = run_suite(mutated, only=["concurrency"])
    assert any(f.code == "RTA106" and
               f.anchor == "_Handler._frames:cross-root"
               for f in report.new), [f.render() for f in report.new]


def test_cross_class_lock_inversion_fails_suite(tmp_path):
    """RTA104 gate: the batcher already takes MicroBatcher._cond ->
    ServingStats._lock (stats calls under the admission lock).
    Re-introducing the reverse order — a method that freezes the stats
    lock and then reaches for the admission lock, the accretion shape
    r12-era review had to catch by hand — must fail the suite."""
    inversion = (
        "    def freeze_stats(self):\n"
        "        with self.stats._lock:\n"
        "            with self._cond:\n"
        "                return len(self._queue)\n"
        "\n"
        "    def _retry_after(self) -> float:")
    for name, reps in (("clean", []),
                       ("mut", [("    def _retry_after(self) -> float:",
                                 inversion)])):
        root = _mutated_tree(tmp_path / name,
                             "rafiki_tpu/predictor/batcher.py", reps)
        _mutated_tree(tmp_path / name,
                      "rafiki_tpu/observe/serving.py", [])
        report = run_suite(root, only=["concurrency"])
        cycles = [f for f in report.new if f.code == "RTA104"]
        if name == "clean":
            assert cycles == [], [f.render() for f in cycles]
        else:
            assert any(f.anchor ==
                       "MicroBatcher._cond<->ServingStats._lock"
                       for f in cycles), \
                [f.render() for f in report.new]


def test_eager_jax_on_bus_path_fails_suite(tmp_path):
    """PR 2 lazy-import invariant, now enforced: observe.metrics is
    import-time reachable from the bus package, so adding an eager
    `import jax` there must fail the suite via RTA602."""
    for name, reps in (("clean", []),
                       ("mut", [("import json",
                                 "import jax\nimport json")])):
        root = _mutated_tree(tmp_path / name,
                             "rafiki_tpu/observe/metrics.py", reps,
                             dst_name="observe/metrics.py")
        _mutated_tree(tmp_path / name, "rafiki_tpu/bus/base.py", [],
                      dst_name="bus/base.py")
        report = run_suite(root, only=["import-hygiene"])
        eager = [f for f in report.new if f.code == "RTA602"]
        if name == "clean":
            assert eager == [], [f.render() for f in eager]
        else:
            assert any(f.path == "rafiki_tpu/observe/metrics.py"
                       for f in eager), \
                [f.render() for f in report.new]


def test_renamed_queue_prefix_fails_suite(tmp_path):
    """RTA701 gate: renaming the cache's per-worker push prefix while
    the pop side keeps the old name leaves an orphan producer — the
    exact stringly-typed drift the serving split makes possible."""
    for name, reps in (("clean", []),
                       ("mut", [('push(f"q:{worker_id}"',
                                 'push(f"qx:{worker_id}"')])):
        root = _mutated_tree(tmp_path / name, "rafiki_tpu/cache.py",
                             reps)
        _mutated_tree(tmp_path / name, "rafiki_tpu/bus/base.py", [],
                      dst_name="bus/base.py")
        _mutated_tree(tmp_path / name, "rafiki_tpu/bus/__init__.py",
                      [], dst_name="bus/__init__.py")
        report = run_suite(root, only=["flow"])
        orphan = [f for f in report.new if f.code == "RTA701"]
        if name == "clean":
            assert orphan == [], [f.render() for f in orphan]
        else:
            assert any(f.anchor == "queue:qx:" for f in orphan), \
                [f.render() for f in report.new]


def test_typod_client_route_fails_suite(tmp_path):
    """RTA702 gate: a typo'd path in the client SDK matches no served
    route tuple, and the real route simultaneously goes caller-less."""
    for name, reps in (("clean", []),
                       ("mut", [('("POST", "/models"',
                                 '("POST", "/modelz"')])):
        root = _mutated_tree(tmp_path / name,
                             "rafiki_tpu/client/client.py", reps,
                             dst_name="client/client.py")
        _mutated_tree(tmp_path / name, "rafiki_tpu/admin/app.py", [],
                      dst_name="admin/app.py")
        report = run_suite(root, only=["flow"])
        anchors = {f.anchor for f in report.new}
        if name == "clean":
            assert "route-call:POST /models" not in anchors, anchors
            assert "route:POST /models" not in anchors, anchors
        else:
            assert "route-call:POST /modelz" in anchors, anchors
            assert "route:POST /models" in anchors, anchors


def test_unguarding_fabric_registry_fails_suite(tmp_path):
    """RTA703 gate: widening the cluster-fabric construction gate to
    ``if True:`` makes the node registry — its heartbeat thread and
    its rafiki_tpu_node_peers gauge — reachable with the flag off."""
    gate = 'if _pb(os.environ.get("RAFIKI_TPU_CLUSTER_FABRIC", "0")):'
    for name, reps in (("clean", []), ("mut", [(gate, "if True:")])):
        root = _mutated_tree(tmp_path / name,
                             "rafiki_tpu/platform.py", reps)
        _mutated_tree(tmp_path / name, "rafiki_tpu/admin/nodes.py",
                      [], dst_name="admin/nodes.py")
        report = run_suite(root, only=["flow"])
        offpath = [f for f in report.new if f.code == "RTA703"]
        if name == "clean":
            assert offpath == [], [f.render() for f in offpath]
        else:
            assert any("unguarded-ctor:NodeRegistry" in f.anchor
                       for f in offpath), \
                [f.render() for f in report.new]


# --- CLI: --explain ----------------------------------------------------


def test_cli_explain():
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--explain",
         "RTA104"], capture_output=True, text=True, timeout=120,
        cwd=REPO)
    assert proc.returncode == 0
    assert "cross-class lock-order cycle" in proc.stdout
    assert "fix   :" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--explain",
         "RTA999"], capture_output=True, text=True, timeout=120,
        cwd=REPO)
    assert proc.returncode == 2
    assert "unknown code" in proc.stderr


def test_catalog_covers_every_registered_code():
    from rafiki_tpu.analysis.catalog import CATALOG

    codes = {c for ch in core.all_checkers() for c in ch.codes}
    codes |= {"RTA000", "RTA001", "RTA002"}
    assert codes <= set(CATALOG), sorted(codes - set(CATALOG))


# --- Integration: this repo, the committed baseline -------------------


def test_repo_is_clean_against_committed_baseline():
    baseline = load_baseline(core.baseline_path())
    report = run_suite(REPO, baseline=baseline)
    assert report.new == [], "\n".join(f.render() for f in report.new)


def test_committed_baseline_is_short_and_reasoned():
    baseline = load_baseline(core.baseline_path())
    assert 0 < len(baseline) <= 25
    for ident, reason in baseline.items():
        assert reason and not reason.startswith("UNREVIEWED"), ident
        assert len(reason) > 15, f"{ident}: reason too thin"


def test_cli_json_exit_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["new"] == 0
    assert data["files"] > 50
    # per-code counts is what bench.py --config analysis records
    assert all(k.startswith("RTA") for k in data["counts_per_code"])


def test_changed_mode_scopes_per_file_checkers(tmp_path):
    pkg = tmp_path / "rafiki_tpu"
    pkg.mkdir()
    bad = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._n = 0\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._n += 1\n"
           "    def b(self):\n"
           "        return self._n\n")
    (pkg / "one.py").write_text(bad)
    (pkg / "two.py").write_text(bad)
    full = run_suite(str(tmp_path), only=["guarded-state"])
    assert len(full.new) == 2
    scoped = run_suite(str(tmp_path), changed={"rafiki_tpu/one.py"},
                       only=["guarded-state"])
    assert [f.path for f in scoped.new] == ["rafiki_tpu/one.py"]
    # nothing changed -> nothing to analyze, repo checkers skipped too
    empty = run_suite(str(tmp_path), changed=set())
    assert empty.findings == []


def test_flow_codes_clean_on_real_tree():
    """RTA701–703 acceptance: the distributed-surface checkers run
    green on this repo; inline waivers carry the reviewed exceptions
    (browser/curl-only routes)."""
    report = run_suite(REPO, only=["flow"])
    assert report.new == [], "\n".join(f.render() for f in report.new)
    assert "flow" in report.timings
    waived = {f.code for f in report.findings if f.status == "waived"}
    assert "RTA702" in waived


def test_diff_mode_cli_and_timings(tmp_path):
    """--diff <base> scopes like --changed but against an explicit
    git base, and reports per-checker wall time."""
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--diff",
         "HEAD"],
        capture_output=True, text=True, timeout=240, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "timings:" in proc.stderr
    # the wall times also land in the JSON report
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--json",
         "--checker", "donation"],
        capture_output=True, text=True, timeout=240, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert "donation" in data["timings_s"]
    # --changed and --diff are mutually exclusive scoping modes
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--changed",
         "--diff", "HEAD"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 2
    # --update-baseline refuses the partial view exactly like
    # --changed
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.analysis", "--diff",
         "HEAD", "--update-baseline",
         "--baseline", str(tmp_path / "bl.json")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 2
    assert "requires a full run" in proc.stderr
    assert not (tmp_path / "bl.json").exists()


def test_renaming_slo_consumed_series_fails_suite(tmp_path):
    """RTA506 gate (ISSUE r19): the SLO plane's consumed-series
    vocabulary and the committed docs/slo rules must reference
    registered names; renaming either side turns the suite red."""

    def tree(name, slo_reps, rules_reps):
        root = tmp_path / name
        for rel in ("rafiki_tpu/observe/slo.py",
                    "rafiki_tpu/admin/slo_engine.py",
                    "rafiki_tpu/observe/attribution.py",
                    "rafiki_tpu/observe/serving.py",
                    "rafiki_tpu/utils/service.py"):
            with open(os.path.join(REPO, rel), encoding="utf-8") as f:
                text = f.read()
            if rel.endswith("observe/slo.py"):
                for old, new in slo_reps:
                    assert old in text
                    text = text.replace(old, new)
            dst = root / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            dst.write_text(text)
        with open(os.path.join(REPO, "docs/slo/serving.json"),
                  encoding="utf-8") as f:
            rules = f.read()
        for old, new in rules_reps:
            assert old in rules
            rules = rules.replace(old, new)
        dst = root / "docs" / "slo" / "serving.json"
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(rules)
        return str(root)

    def rta506(root):
        return [f for f in run_suite(root, only=["drift"]).new
                if f.code == "RTA506"]

    assert rta506(tree("clean", [], [])) == []
    # (a) the engine vocabulary names a series nobody registers
    mutated = tree("mut-vocab",
                   [('("latency", "job"): '
                     '"rafiki_tpu_http_request_seconds"',
                     '("latency", "job"): '
                     '"rafiki_tpu_http_request_millis"')], [])
    assert any(f.anchor == "rafiki_tpu_http_request_millis"
               for f in rta506(mutated))
    # (b) a committed rules file references a renamed metric
    mutated = tree("mut-rules", [],
                   [("rafiki_tpu_serving_tenant_request_seconds",
                     "rafiki_tpu_serving_tenant_latency_seconds")])
    assert any(f.anchor == "rafiki_tpu_serving_tenant_latency_seconds"
               for f in rta506(mutated))
