"""MetaStore + ParamStore unit tests (SURVEY.md §4: sqlite-backed)."""

import threading

import numpy as np
import pytest

from rafiki_tpu.constants import (ParamsType, TrainJobStatus, TrialStatus)
from rafiki_tpu.store import MetaStore, ParamStore


@pytest.fixture()
def meta():
    m = MetaStore(":memory:")
    yield m
    m.close()


@pytest.fixture()
def pstore(tmp_path):
    p = ParamStore(str(tmp_path / "params"))
    yield p
    p.close()


def _mk_job(meta):
    user = meta.create_user("dev@x.com", "hash", "MODEL_DEVELOPER")
    model = meta.create_model(user["id"], "m1", "IMAGE_CLASSIFICATION",
                              "pkg.mod:Cls", {"lr": {"kind": "float"}})
    job = meta.create_train_job(user["id"], "app1", "IMAGE_CLASSIFICATION",
                                {"MODEL_TRIAL_COUNT": 3}, "/t", "/v",
                                TrainJobStatus.STARTED)
    sub = meta.create_sub_train_job(job["id"], model["id"], "STARTED")
    return user, model, job, sub


class TestMetaStore:
    def test_users(self, meta):
        u = meta.create_user("a@b.c", "h", "ADMIN")
        assert meta.get_user_by_email("a@b.c")["id"] == u["id"]
        assert meta.get_user_by_email("missing@x.y") is None

    def test_app_versioning(self, meta):
        u = meta.create_user("a@b.c", "h", "ADMIN")
        j1 = meta.create_train_job(u["id"], "app", "T", {}, "/t", "/v", "S")
        j2 = meta.create_train_job(u["id"], "app", "T", {}, "/t", "/v", "S")
        assert (j1["app_version"], j2["app_version"]) == (1, 2)
        latest = meta.get_train_job_by_app(u["id"], "app")
        assert latest["id"] == j2["id"]
        assert meta.get_train_job_by_app(u["id"], "app", 1)["id"] == j1["id"]

    def test_trial_lifecycle_and_best(self, meta):
        _, model, job, sub = _mk_job(meta)
        ids = []
        for i, score in enumerate([0.5, 0.9, 0.7]):
            t = meta.create_trial(sub["id"], model["id"], no=i + 1,
                                  status=TrialStatus.RUNNING,
                                  knobs={"lr": 0.1 * (i + 1)})
            meta.mark_trial_completed(t["id"], score, params_id=f"p{i}")
            ids.append(t["id"])
        bad = meta.create_trial(sub["id"], model["id"], no=4,
                                status=TrialStatus.RUNNING)
        meta.mark_trial_errored(bad["id"], "boom")

        trials = meta.get_trials(sub["id"])
        assert len(trials) == 4
        assert meta.get_trials(sub["id"], TrialStatus.COMPLETED)[0]["knobs"] \
            == {"lr": 0.1}
        best = meta.get_best_trials_of_train_job(job["id"], max_count=2)
        assert [t["score"] for t in best] == [0.9, 0.7]
        assert best[0]["params_id"] == "p1"

    def test_trial_logs(self, meta):
        _, model, _, sub = _mk_job(meta)
        t = meta.create_trial(sub["id"], model["id"], no=1, status="RUNNING")
        meta.add_trial_log(t["id"], {"type": "values", "values": {"loss": 1.0}})
        meta.add_trial_log(t["id"], {"type": "values", "values": {"loss": 0.5}})
        logs = meta.get_trial_logs(t["id"])
        assert [r["record"]["values"]["loss"] for r in logs] == [1.0, 0.5]

    def test_services_and_workers(self, meta):
        _, _, job, sub = _mk_job(meta)
        svc = meta.create_service("TRAIN", "RUNNING", chips=[0, 1, 2, 3])
        meta.add_train_job_worker(svc["id"], sub["id"])
        assert meta.get_service(svc["id"])["chips"] == [0, 1, 2, 3]
        workers = meta.get_train_job_workers(sub["id"])
        assert workers[0]["service_id"] == svc["id"]

    def test_file_backed_cross_instance(self, tmp_path):
        path = str(tmp_path / "meta.db")
        m1 = MetaStore(path)
        u = m1.create_user("x@y.z", "h", "ADMIN")
        m2 = MetaStore(path)  # second process in real deployments
        assert m2.get_user(u["id"])["email"] == "x@y.z"
        m1.close()
        m2.close()

    def test_concurrent_trial_writes(self, meta):
        _, model, _, sub = _mk_job(meta)

        def writer(k):
            for i in range(20):
                t = meta.create_trial(sub["id"], model["id"],
                                      no=k * 100 + i, status="RUNNING")
                meta.mark_trial_completed(t["id"], 0.1, None)

        threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert len(meta.get_trials(sub["id"], TrialStatus.COMPLETED)) == 80


class TestParamStore:
    def test_roundtrip(self, pstore):
        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                  "_meta/n_classes": np.asarray(10)}
        pid = pstore.save(params, session_id="s", worker_id="w0", score=0.5)
        out = pstore.load(pid)
        np.testing.assert_array_equal(out["w"], params["w"])
        # safetensors flattens 0-d arrays to shape (1,)
        assert int(out["_meta/n_classes"].reshape(-1)[0]) == 10

    def test_noncontiguous_ok(self, pstore):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4).T  # not C-contig
        pid = pstore.save({"w": arr}, session_id="s")
        np.testing.assert_array_equal(pstore.load(pid)["w"], arr)

    def test_sharing_policies(self, pstore):
        mk = lambda v: {"w": np.asarray([v], np.float32)}
        pstore.save(mk(1.0), session_id="s", worker_id="w0", score=0.3)
        pstore.save(mk(2.0), session_id="s", worker_id="w1", score=0.9)
        pstore.save(mk(3.0), session_id="s", worker_id="w0", score=0.6)

        assert pstore.retrieve(ParamsType.NONE, session_id="s") is None
        got = pstore.retrieve(ParamsType.GLOBAL_RECENT, session_id="s")
        assert float(got["w"][0]) == 3.0
        got = pstore.retrieve(ParamsType.GLOBAL_BEST, session_id="s")
        assert float(got["w"][0]) == 2.0
        got = pstore.retrieve(ParamsType.LOCAL_BEST, session_id="s",
                              worker_id="w0")
        assert float(got["w"][0]) == 3.0
        got = pstore.retrieve(ParamsType.LOCAL_RECENT, session_id="s",
                              worker_id="w1")
        assert float(got["w"][0]) == 2.0
        # unseen session → cold start
        assert pstore.retrieve(ParamsType.GLOBAL_BEST, session_id="zz") is None

    def test_delete(self, pstore):
        pid = pstore.save({"w": np.zeros(2, np.float32)}, session_id="s")
        assert pstore.exists(pid)
        pstore.delete(pid)
        assert not pstore.exists(pid)
        assert pstore.retrieve(ParamsType.GLOBAL_RECENT, session_id="s") is None

    def test_write_behind_row_lands_after_file(self, pstore):
        """Cross-process contract (ADVICE r5): the sqlite index row must
        never exist before its .safetensors file — a shared-volume
        reader that sees the row and load()s must find the file. The
        in-process view keeps read-your-writes throughout the flush
        window via _pending."""
        import os

        import jax.numpy as jnp

        orig = pstore._flush_to_disk
        gate = threading.Event()

        def slow_flush(pid, tree):
            gate.wait(10)
            orig(pid, tree)

        pstore._flush_to_disk = slow_flush
        pid = pstore.save({"w": jnp.full((3,), 2.0)}, session_id="wb",
                          worker_id="w0", score=0.7)
        try:
            # flush window: no row, no file — but full in-process
            # visibility (retrieve + listing + exists)
            with pstore._lock:
                n = pstore._db.execute(
                    "SELECT COUNT(*) FROM params WHERE id = ?",
                    (pid,)).fetchone()[0]
            assert n == 0, "index row committed before the file landed"
            assert not os.path.exists(pstore._path(pid))
            got = pstore.retrieve(ParamsType.GLOBAL_BEST, session_id="wb")
            assert got is not None and float(np.asarray(got["w"])[0]) == 2.0
            assert pstore.session_params_ids("wb") == [pid]
            assert pstore.exists(pid)
        finally:
            gate.set()
        pstore.flush()
        assert os.path.exists(pstore._path(pid))
        with pstore._lock:
            n = pstore._db.execute(
                "SELECT COUNT(*) FROM params WHERE id = ?",
                (pid,)).fetchone()[0]
        assert n == 1
        assert pstore.session_params_ids("wb") == [pid]
        np.testing.assert_array_equal(pstore.load(pid)["w"],
                                      np.full((3,), 2.0, np.float32))

    def test_write_behind_policy_ranks_pending_against_indexed(self, pstore):
        """A pending (unflushed) save must compete in the sharing
        policies exactly as an indexed one: BEST by score, RECENT by
        creation order."""
        import jax.numpy as jnp

        mk = lambda v: {"w": np.asarray([v], np.float32)}
        pstore.save(mk(1.0), session_id="s", worker_id="w0", score=0.9)
        orig = pstore._flush_to_disk
        gate = threading.Event()
        pstore._flush_to_disk = \
            lambda pid, tree: (gate.wait(10), orig(pid, tree))
        pstore.save({"w": jnp.full((1,), 2.0)}, session_id="s",
                    worker_id="w0", score=0.3)
        try:
            # RECENT -> the pending save; BEST -> the indexed one
            got = pstore.retrieve(ParamsType.GLOBAL_RECENT, session_id="s")
            assert float(np.asarray(got["w"])[0]) == 2.0
            got = pstore.retrieve(ParamsType.GLOBAL_BEST, session_id="s")
            assert float(np.asarray(got["w"])[0]) == 1.0
        finally:
            gate.set()
        pstore.flush()

    def test_delete_racing_writer_leaves_no_orphan(self, pstore):
        """delete() while the writer thread is mid-save_file must leave
        neither an orphaned .safetensors nor an index row (ADVICE r5:
        the flushed file used to land after delete's os.remove)."""
        import os
        import time

        import jax.numpy as jnp

        orig = pstore._flush_to_disk
        in_flush = threading.Event()
        gate = threading.Event()

        def slow_flush(pid, tree):
            in_flush.set()
            gate.wait(10)
            orig(pid, tree)

        pstore._flush_to_disk = slow_flush
        pid = pstore.save({"w": jnp.zeros((2,))}, session_id="race")
        assert in_flush.wait(10), "writer never started the flush"
        pstore.delete(pid)          # mid-save_file
        gate.set()
        # delete() already removed pid from _pending, so flush() cannot
        # wait on it; a follow-up save is processed FIFO after the raced
        # item — once IT is flushed, the raced item is fully settled.
        pstore.save({"w": jnp.zeros((2,))}, session_id="race2")
        pstore.flush()
        deadline = time.monotonic() + 10
        while pstore._pending and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not os.path.exists(pstore._path(pid)), \
            "orphaned .safetensors after delete raced the writer"
        with pstore._lock:
            n = pstore._db.execute(
                "SELECT COUNT(*) FROM params WHERE id = ?",
                (pid,)).fetchone()[0]
        assert n == 0
        assert not pstore.exists(pid)
