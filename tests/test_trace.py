"""End-to-end request tracing: ids, envelope carry, spans, stitching.

Covers the ISSUE-2 test checklist: trace-id propagation across the
memory and tcp buses (including the old-frame fallback), the HTTP edge
(mint + honor + echo of ``X-Trace-Id``), span recording through the
shared JSONL sink, and the admin's ``GET /trace/<id>`` stitcher.
"""

import json
import os
import threading
import time

import pytest
import requests

from rafiki_tpu.bus import BusClient, BusServer, MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.observe import trace


@pytest.fixture()
def span_sink(tmp_path):
    """Point the process span sink at a temp dir; always restore."""
    trace.configure(str(tmp_path))
    yield str(tmp_path)
    trace.configure(None)


@pytest.fixture(params=["memory", "tcp"])
def bus(request):
    if request.param == "memory":
        yield MemoryBus()
        return
    server = BusServer().start()
    client = BusClient(server.host, server.port)
    yield client
    client.close()
    server.stop()


# --- Context / header parsing ---

def test_start_trace_mints_and_parses():
    ctx = trace.start_trace(None)
    assert ctx is not None and len(ctx.trace_id) == 32
    parsed = trace.start_trace(f"{ctx.trace_id}-{ctx.span_id}")
    assert parsed.trace_id == ctx.trace_id
    assert parsed.parent_id == ctx.span_id
    bare = trace.start_trace("sometid")
    assert bare.trace_id == "sometid" and bare.parent_id is None
    # a standard dashed UUID is taken WHOLE, never split at its dashes
    dashed = "550e8400-e29b-41d4-a716-446655440000"
    got = trace.start_trace(dashed)
    assert got.trace_id == dashed and got.parent_id is None


def test_sample_rate_zero_suppresses_fresh_traces(monkeypatch):
    monkeypatch.setenv(trace.TRACE_SAMPLE_ENV, "0")
    assert trace.start_trace(None) is None
    # ...but an incoming id is ALWAYS honored
    assert trace.start_trace("abc123").trace_id == "abc123"
    monkeypatch.setenv(trace.TRACE_SAMPLE_ENV, "not-a-number")
    assert trace.sample_rate() == 1.0


def test_thread_local_current_context():
    assert trace.current() is None
    ctx = trace.TraceContext("t1")
    with trace.use(ctx):
        assert trace.current() is ctx
        with trace.use(None):
            assert trace.current() is None
        assert trace.current() is ctx
    assert trace.current() is None


# --- Envelope inject/extract (old-frame fallback) ---

def test_inject_extract_roundtrip():
    ctxs = [trace.TraceContext("t" * 32), trace.TraceContext("u" * 32)]
    frame = {"batch_id": "b1", "queries": [1, 2],
             trace.ENVELOPE_KEY: trace.inject(ctxs)}
    out = trace.extract(frame)
    assert [c.trace_id for c in out] == ["t" * 32, "u" * 32]
    # extraction CONTINUES the propagated span: downstream child spans
    # parent onto the sender's span
    assert out[0].span_id == ctxs[0].span_id
    # envelope is POPPED: downstream frame handling never sees it
    assert trace.ENVELOPE_KEY not in frame


def test_old_frames_and_malformed_envelopes_fall_back():
    assert trace.extract({"batch_id": "b", "queries": []}) == []
    assert trace.extract("not-a-dict") == []
    assert trace.extract({trace.ENVELOPE_KEY: "garbage"}) == []
    assert trace.extract({trace.ENVELOPE_KEY: {"ids": "nope"}}) == []
    assert trace.inject([]) is None
    assert trace.inject([None]) is None


def test_envelope_caps_trace_count():
    ctxs = [trace.TraceContext(f"t{i}") for i in range(100)]
    env = trace.inject(ctxs)
    assert len(env["ids"]) == trace.MAX_ENVELOPE_TRACES


# --- Propagation across the bus (memory + tcp) ---

def test_trace_rides_bus_envelope(bus):
    cache = Cache(bus)
    ctx = trace.TraceContext("cafe" * 8)
    cache.send_query_batch_fanout(["wA", "wB"], [{"v": 1}],
                                  trace_ctxs=[ctx])
    for w in ("wA", "wB"):
        items = cache.pop_queries(w, timeout=5.0)
        assert len(items) == 1
        got = trace.extract(items[0])
        assert [c.trace_id for c in got] == ["cafe" * 8]
        assert got[0].span_id == ctx.span_id
        # payload untouched by the envelope
        assert items[0]["queries"] == [{"v": 1}]


def test_ambient_context_injected_on_direct_path(bus):
    cache = Cache(bus)
    with trace.use(trace.TraceContext("beef" * 8)):
        cache.send_query_batch("wC", [1, 2])
        cache.send_query("wC", 3)
    items = cache.pop_queries("wC", timeout=5.0)
    assert len(items) == 2
    for it in items:
        assert [c.trace_id for c in trace.extract(it)] == ["beef" * 8]


def test_untraced_frames_stay_old_shape(bus):
    """No ambient context -> the frame has NO trace key at all (an old
    consumer sees byte-identical frames)."""
    cache = Cache(bus)
    cache.send_query_batch_fanout(["wD"], [{"v": 1}])
    item = cache.pop_queries("wD", timeout=5.0)[0]
    assert trace.ENVELOPE_KEY not in item


# --- Span sink + stitching ---

def test_record_and_collect_spans(span_sink):
    tid = "deadbeef" * 4
    ctx = trace.TraceContext(tid)
    t0 = time.time()
    trace.record_event("http POST /predict", "admin", [ctx], t0, 0.010,
                       child=False)
    trace.record_event("worker.predict", "w1", [ctx], t0 + 0.002, 0.005,
                       attrs={"n_queries": 4})
    out = trace.collect_trace(span_sink, tid)
    assert out["n_spans"] == 2
    names = [s["name"] for s in out["spans"]]
    assert names == ["http POST /predict", "worker.predict"]  # ordered
    assert out["spans"][0]["offset_ms"] == 0.0
    assert out["spans"][1]["offset_ms"] == pytest.approx(2.0, abs=1.0)
    # the child span parents onto the propagated span
    assert out["spans"][1]["parent_id"] == ctx.span_id
    assert out["spans"][1]["attrs"]["n_queries"] == 4
    # unknown trace -> empty, not an error
    assert trace.collect_trace(span_sink, "nope")["n_spans"] == 0


def test_collect_skips_corrupt_lines(span_sink):
    tid = "feed" * 8
    with open(trace.span_log_path(span_sink), "a") as f:
        f.write(f"{tid} not json\n")
        f.write(json.dumps({"trace_id": tid, "name": "ok",
                            "start_s": 1.0, "dur_ms": 1}) + "\n")
    out = trace.collect_trace(span_sink, tid)
    assert out["n_spans"] == 1 and out["spans"][0]["name"] == "ok"


def _spam(ctx, n, name="spam"):
    for _ in range(n):
        trace.record_event(name, "s", [ctx], 1.0, 0.001)


def test_multi_segment_store_indexed_read(span_sink, monkeypatch):
    """ISSUE r17 acceptance: a multi-segment store serves
    GET /trace/<id> via the sidecar index — frozen segments are seek+
    readline at indexed offsets, never a full-file scan — including a
    trace whose spans straddle a segment roll."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))  # 1 KiB
    monkeypatch.setenv(trace.TRACE_RETAIN_SEGMENTS_ENV, "3")
    straddle = trace.TraceContext("ab" * 16)
    filler = trace.TraceContext("cd" * 16)
    # One straddle span early, spam until at least two rolls happened,
    # one straddle span late: its spans now live in a frozen segment
    # AND the active file.
    trace.record_event("first", "s", [straddle], 1.0, 0.001)
    path = trace.span_log_path(span_sink)
    for _ in range(100):
        _spam(filler, 5)
        if os.path.exists(path + ".2"):
            break
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    trace.record_event("last", "s", [straddle], 2.0, 0.001)
    # Roll-time sidecar indexes exist for the frozen generations.
    assert os.path.exists(trace.index_path(path + ".1"))
    out = trace.collect_trace(span_sink, straddle.trace_id)
    names = {s["name"] for s in out["spans"]}
    assert "first" in names and "last" in names
    # The read-path evidence: every frozen segment was an INDEXED
    # read, and the bytes it cost are the matching lines only — far
    # below the segment size (the no-full-scan pin).
    frozen = [d for d in out["segments"]
              if d["segment"] != trace.SPAN_FILE]
    assert frozen, out["segments"]
    for diag in frozen:
        assert diag["mode"] == "index", out["segments"]
        seg = os.path.join(span_sink, diag["segment"])
        if diag["n_spans"] == 0:
            assert diag["bytes_read"] == 0, diag
        else:
            assert diag["bytes_read"] < os.path.getsize(seg) / 2, diag
    # The filler trace is found through the same index path.
    assert trace.collect_trace(span_sink,
                               filler.trace_id)["n_spans"] > 0
    # Warm repeat on the ACTIVE segment scans zero new bytes (the
    # incremental cache only ever reads the appended tail).
    again = trace.collect_trace(span_sink, straddle.trace_id)
    active = [d for d in again["segments"]
              if d["segment"] == trace.SPAN_FILE]
    assert active and active[0]["mode"] == "scan_tail"
    span_bytes = sum(d["n_spans"] for d in again["segments"])
    assert span_bytes  # sanity: the trace is still found


def test_index_rebuilt_when_sidecar_missing(span_sink, monkeypatch):
    """A frozen segment whose .idx vanished (partial copy, manual
    cleanup) is re-indexed lazily — and the rebuilt sidecar persists
    for the next reader."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))
    ctx = trace.TraceContext("ee" * 16)
    _spam(ctx, 20)
    path = trace.span_log_path(span_sink)
    assert os.path.exists(path + ".1")
    os.remove(trace.index_path(path + ".1"))
    out = trace.collect_trace(span_sink, ctx.trace_id)
    assert out["n_spans"] > 0
    modes = {d["segment"]: d["mode"] for d in out["segments"]}
    assert modes.get(trace.SPAN_FILE + ".1") == "index_rebuilt"
    assert os.path.exists(trace.index_path(path + ".1"))
    out2 = trace.collect_trace(span_sink, ctx.trace_id)
    modes2 = {d["segment"]: d["mode"] for d in out2["segments"]}
    assert modes2.get(trace.SPAN_FILE + ".1") == "index"


def test_retention_bounds_segments_and_bytes(span_sink, monkeypatch):
    """The generation chain is bounded by BOTH knobs: at most
    RETAIN_SEGMENTS rolled files, and oldest generations are deleted
    when the rolled chain exceeds RETAIN_MB (the newest rolled segment
    always survives)."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))
    monkeypatch.setenv(trace.TRACE_RETAIN_SEGMENTS_ENV, "2")
    ctx = trace.TraceContext("aa" * 16)
    path = trace.span_log_path(span_sink)
    _spam(ctx, 200)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # count bound enforced
    # Byte budget below one segment: only .1 survives the next roll.
    monkeypatch.setenv(trace.TRACE_RETAIN_MB_ENV, str(0.5 / 1024))
    _spam(ctx, 40)
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2"), "byte budget not enforced"


def test_tail_sampling_verdicts(span_sink, monkeypatch):
    """Error and slow traces always persist; fast ones drop at
    sample=0 — and a straggler span arriving after the drop verdict is
    suppressed, not resurrected as an orphan."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "100")
    trace.reset_tail_for_tests()
    try:
        # Error outcome: buffered spans flush.
        err = trace.start_trace(None)
        assert err is not None and err.tail
        trace.record_event("edge", "svc", [err], 1.0, 0.01, child=False)
        assert trace.collect_trace(span_sink,
                                   err.trace_id)["n_spans"] == 0
        trace.complete(err, 0.01, error=True)
        assert trace.collect_trace(span_sink,
                                   err.trace_id)["n_spans"] == 1
        # Slow outcome: kept despite sample=0.
        slow = trace.start_trace(None)
        trace.record_event("edge", "svc", [slow], 1.0, 0.2, child=False)
        trace.complete(slow, 0.2, error=False)
        assert trace.collect_trace(span_sink,
                                   slow.trace_id)["n_spans"] == 1
        # Fast + ok at sample 0: dropped, late spans suppressed.
        fast = trace.start_trace(None)
        trace.record_event("edge", "svc", [fast], 1.0, 0.001,
                           child=False)
        trace.complete(fast, 0.001, error=False)
        assert trace.collect_trace(span_sink,
                                   fast.trace_id)["n_spans"] == 0
        trace.record_event("late.worker", "w", [fast], 1.1, 0.001)
        assert trace.collect_trace(span_sink,
                                   fast.trace_id)["n_spans"] == 0
        # An honored X-Trace-Id bypasses tail sampling entirely.
        honored = trace.start_trace("ff" * 16)
        assert honored is not None and not honored.tail
        trace.record_event("edge", "svc", [honored], 1.0, 0.001,
                           child=False)
        assert trace.collect_trace(span_sink,
                                   "ff" * 16)["n_spans"] == 1
    finally:
        trace.reset_tail_for_tests()


def test_tail_sampling_seeded_rate(span_sink, monkeypatch):
    """Fast traces keep at exactly the seeded RNG's decision sequence
    for the configured rate — 100% of error/slow traces survive a
    seeded mixed workload while fast ones sample (the r17 acceptance
    shape)."""
    import random as _random

    rate = 0.3
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, str(rate))
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "50")
    trace.reset_tail_for_tests()
    trace.seed_tail(42)
    try:
        kept_fast = 0
        n_fast = 0
        rng = _random.Random(42)  # mirror of the module's seeded rng
        expected_kept = 0
        for i in range(60):
            ctx = trace.start_trace(None)
            assert ctx is not None
            trace.record_event("edge", "svc", [ctx], 1.0, 0.001,
                               child=False)
            if i % 5 == 0:   # error: must survive
                trace.complete(ctx, 0.001, error=True)
                assert trace.collect_trace(
                    span_sink, ctx.trace_id)["n_spans"] == 1
            elif i % 5 == 1:  # slow: must survive
                trace.complete(ctx, 0.5, error=False)
                assert trace.collect_trace(
                    span_sink, ctx.trace_id)["n_spans"] == 1
            else:            # fast: seeded coin
                n_fast += 1
                if rng.random() < rate:
                    expected_kept += 1
                trace.complete(ctx, 0.001, error=False)
                kept_fast += trace.collect_trace(
                    span_sink, ctx.trace_id)["n_spans"]
        assert kept_fast == expected_kept
        assert 0 < kept_fast < n_fast  # genuinely sampling
    finally:
        trace.reset_tail_for_tests()


def test_tail_pending_overflow_flushes(span_sink, monkeypatch):
    """A pending trace overflowing the per-trace span cap (an edge
    that never completes) flushes to the store — retain on doubt,
    never silent loss."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    trace.reset_tail_for_tests()
    try:
        ctx = trace.start_trace(None)
        for _ in range(trace._PENDING_MAX_SPANS + 5):
            trace.record_event("s", "svc", [ctx], 1.0, 0.001)
        out = trace.collect_trace(span_sink, ctx.trace_id)
        assert out["n_spans"] > trace._PENDING_MAX_SPANS
        # Completion after the overflow is a no-op (already flushed).
        trace.complete(ctx, 0.001, error=False)
        assert trace.collect_trace(span_sink,
                                   ctx.trace_id)["n_spans"] > 0
    finally:
        trace.reset_tail_for_tests()


def test_tail_sampling_at_http_edge(span_sink, monkeypatch):
    """The JsonHttpServer edge delivers the verdict: a 5xx response
    keeps its trace's spans, a fast 200 at sample=0 drops them."""
    from rafiki_tpu.utils.service import JsonHttpServer

    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "60000")
    trace.reset_tail_for_tests()

    def ok(params, body, ctx):
        return 200, {"ok": True}

    def boom(params, body, ctx):
        raise RuntimeError("kaput")

    server = JsonHttpServer([("GET", "/ok", ok), ("GET", "/boom", boom)],
                            host="127.0.0.1", name="tail-svc").start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        r_ok = requests.get(base + "/ok", timeout=10)
        tid_ok = r_ok.headers["X-Trace-Id"].split("-")[0]
        r_boom = requests.get(base + "/boom", timeout=10)
        assert r_boom.status_code == 500
        tid_boom = r_boom.headers["X-Trace-Id"].split("-")[0]
        assert trace.collect_trace(span_sink, tid_ok)["n_spans"] == 0
        out = trace.collect_trace(span_sink, tid_boom)
        assert out["n_spans"] == 1
        assert out["spans"][0]["attrs"]["status"] == 500
    finally:
        server.stop()
        trace.reset_tail_for_tests()


def test_span_log_rotates_at_size_cap(span_sink, monkeypatch):
    """The sink rolls spans.jsonl to one .1 generation at the size cap
    (a client forcing X-Trace-Id must not be able to fill the disk),
    and collect_trace reads both generations."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))  # 1 KiB
    old_tid = "aa" * 16
    ctx = trace.TraceContext(old_tid)
    for _ in range(20):  # ~170 bytes/line -> crosses 1 KiB
        trace.record_event("spam", "s", [ctx], 1.0, 0.001)
    assert os.path.exists(trace.span_log_path(span_sink) + ".1")
    new_tid = "bb" * 16
    trace.record_event("after-roll", "s", [trace.TraceContext(new_tid)],
                       2.0, 0.001)
    # both generations are stitched
    assert trace.collect_trace(span_sink, old_tid)["n_spans"] > 0
    assert trace.collect_trace(span_sink, new_tid)["n_spans"] == 1
    # total on-disk span data stays bounded (~2 generations of the cap)
    total = sum(os.path.getsize(p)
                for p in (trace.span_log_path(span_sink),
                          trace.span_log_path(span_sink) + ".1")
                if os.path.exists(p))
    assert total < 3 * 1024


def test_span_context_manager_noops_without_sink():
    trace.configure(None)
    with trace.span("x", service="s"):  # no sink, no ctx: pure no-op
        pass
    with trace.use(trace.TraceContext("t1")):
        with trace.span("y", service="s"):
            pass  # sink unconfigured: still a no-op, no crash


# --- HTTP edge (JsonHttpServer) ---

def test_http_edge_mints_echoes_and_honors_trace_ids(span_sink):
    from rafiki_tpu.utils.service import JsonHttpServer

    seen = []

    def handler(params, body, ctx):
        seen.append(trace.current())
        return 200, {"ok": True}

    server = JsonHttpServer([("GET", "/thing/<id>", handler)],
                            host="127.0.0.1", name="edge-svc").start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # Fresh mint: response echoes the new id, handler saw the ctx.
        r = requests.get(base + "/thing/a", timeout=10)
        tid = r.headers["X-Trace-Id"].split("-")[0]
        assert len(tid) == 32
        assert seen[-1] is not None and seen[-1].trace_id == tid
        # Incoming id honored end to end.
        r = requests.get(base + "/thing/b", timeout=10,
                         headers={"X-Trace-Id": "abc" + "0" * 29})
        assert r.headers["X-Trace-Id"].startswith("abc" + "0" * 29)
        # The edge span landed in the sink, labeled by route PATTERN.
        out = trace.collect_trace(span_sink, tid)
        assert out["n_spans"] == 1
        assert out["spans"][0]["name"] == "http GET /thing/<id>"
        assert out["spans"][0]["service"] == "edge-svc"
    finally:
        server.stop()


# --- Through the serving path (predictor frontend + worker shape) ---

class _EchoWorker:
    """Bus-level stand-in mirroring InferenceWorker's frame handling."""

    def __init__(self, bus, worker_id="w1", job_id="job"):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.stop_flag = threading.Event()
        self.trace_ids = []
        self.cache.register_worker(job_id, worker_id,
                                   info={"trial_id": "t1"})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            ctxs = trace.extract_frames(items)
            self.trace_ids.extend(c.trace_id for c in ctxs)
            for it in items:
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [[float(q), 0.0] for q in it["queries"]])

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


def test_predict_trace_visible_at_edge_envelope_and_spans(span_sink):
    """The acceptance shape: one /predict through the micro-batcher
    yields ONE trace id at the HTTP edge, inside the bus envelope, and
    in the span log (edge + scatter + gather spans)."""
    from rafiki_tpu.predictor.app import PredictorService

    bus = MemoryBus()
    worker = _EchoWorker(bus)
    svc = PredictorService("tsvc", "job", meta=None, bus=bus,
                           host="127.0.0.1")
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    svc.batcher.start()
    svc._http.start()
    try:
        r = requests.post(f"http://127.0.0.1:{svc.port}/predict",
                          json={"queries": [1, 2]}, timeout=30)
        assert r.status_code == 200
        tid = r.headers["X-Trace-Id"].split("-")[0]
        deadline = time.time() + 5
        while time.time() < deadline and tid not in worker.trace_ids:
            time.sleep(0.05)
        assert tid in worker.trace_ids, "envelope never reached worker"
        # gather span is recorded after the response is sliced out;
        # give the gather thread a beat.
        for _ in range(50):
            out = trace.collect_trace(span_sink, tid)
            if out["n_spans"] >= 3:
                break
            time.sleep(0.05)
        names = {s["name"] for s in out["spans"]}
        assert "http POST /predict" in names
        assert "predictor.scatter" in names
        assert "predictor.gather" in names
    finally:
        svc._http.stop()
        svc.batcher.stop()
        worker.stop()


def test_inference_worker_records_predict_span(span_sink):
    """The real InferenceWorker's dispatch/complete path pops the
    envelope and records the worker span."""
    from rafiki_tpu.worker.inference import InferenceWorker

    bus = MemoryBus()
    worker = InferenceWorker("wsvc", "job", "t1", meta=None, params=None,
                            bus=bus)

    class _Model:
        def predict_submit(self, queries):
            return lambda: [[float(q)] for q in queries]

    worker._model = _Model()
    ctx = trace.TraceContext("ab" * 16)
    items = [{"batch_id": "b1", "queries": [1, 2],
              trace.ENVELOPE_KEY: trace.inject([ctx])}]
    handle = worker._dispatch_batch(items)
    worker._complete_batch(*handle)
    out = trace.collect_trace(span_sink, "ab" * 16)
    assert out["n_spans"] == 1
    span = out["spans"][0]
    assert span["name"] == "worker.predict"
    assert span["service"] == "wsvc"
    assert span["parent_id"] == ctx.span_id
    assert span["attrs"]["trial_id"] == "t1"
    # the reply actually went out
    reply = bus.pop("r:b1", timeout=2.0)
    assert reply["predictions"] == [[1.0], [2.0]]


# --- Admin stitching over REST ---

def test_admin_trace_route_and_metrics(tmp_path):
    """GET /trace/<id> on admin stitches the platform's span log; GET
    /metrics serves the registry (the admin-frontend acceptance leg)."""
    from rafiki_tpu.platform import LocalPlatform

    platform = LocalPlatform(workdir=str(tmp_path / "plat"), http=True,
                             supervise_interval=0)
    try:
        tid = "11" * 16
        ctx = trace.TraceContext(tid)
        trace.record_event("http POST /predict", "predictor-x", [ctx],
                           time.time(), 0.02, child=False)
        trace.record_event("worker.predict", "w1", [ctx],
                           time.time() + 0.001, 0.01)
        base = f"http://127.0.0.1:{platform.app.port}"
        tok = requests.post(base + "/tokens", json={
            "email": "superadmin@rafiki", "password": "rafiki"},
            timeout=10).json()["token"]
        hdr = {"Authorization": f"Bearer {tok}"}
        out = requests.get(f"{base}/trace/{tid}", headers=hdr,
                           timeout=10).json()
        assert out["trace_id"] == tid and out["n_spans"] == 2
        assert out["spans"][0]["name"] == "http POST /predict"
        # unauthenticated -> 401 like every other admin read
        assert requests.get(f"{base}/trace/{tid}",
                            timeout=10).status_code == 401
        # /metrics needs no auth (scrape endpoint) and is valid text
        m = requests.get(base + "/metrics", timeout=10)
        assert m.status_code == 200 and "# TYPE" in m.text
        assert "rafiki_tpu_http_request_seconds" in m.text
        # /status surfaces the mfu map (empty here, but present)
        status = requests.get(base + "/status", headers=hdr,
                              timeout=10).json()
        assert "mfu" in status
        # /trial_phases feeds the dashboard's phase-breakdown panel:
        # all six phases present (zero-count here — no resident trials)
        # and authenticated like every other admin read.
        tp = requests.get(base + "/trial_phases", headers=hdr,
                          timeout=10).json()
        assert set(tp["phases"]) == {"propose", "load", "stage",
                                     "train", "eval", "persist"}
        assert set(tp["caches"]) == {"dataset", "stage"}
        assert "resident" in tp and "enabled" in tp
        assert requests.get(base + "/trial_phases",
                            timeout=10).status_code == 401
    finally:
        platform.shutdown()
        trace.configure(None)


# --- Advisor RPC trace propagation (ISSUE-3 satellite) ---

def test_advisor_rpc_carries_trace_context(span_sink):
    """RemoteAdvisor injects the caller's context into proposal and
    feedback frames; the AdvisorWorker records advisor.<op> spans under
    the same trace id. Old frames (no envelope) stay span-free."""
    from rafiki_tpu.advisor import RandomAdvisor
    from rafiki_tpu.advisor.worker import AdvisorWorker, RemoteAdvisor
    from rafiki_tpu.model.knobs import IntegerKnob

    bus = MemoryBus()
    advisor = RandomAdvisor({"x": IntegerKnob(1, 9)})
    worker = AdvisorWorker(advisor, bus, "sub1").start()
    remote = RemoteAdvisor(bus, "sub1", timeout=10.0)
    try:
        tid = "ad" * 16
        with trace.use(trace.TraceContext(tid)):
            prop = remote.propose()
            assert prop is not None
            remote.feedback(prop, 0.5)
        # feedback is fire-and-forget; give the worker a beat
        deadline = time.time() + 5
        names = set()
        while time.time() < deadline and len(names) < 2:
            out = trace.collect_trace(span_sink, tid)
            names = {s["name"] for s in out["spans"]}
            time.sleep(0.05)
        assert names == {"advisor.propose", "advisor.feedback"}, names
        for s in trace.collect_trace(span_sink, tid)["spans"]:
            assert s["service"].startswith("advisor-")
        # Untraced caller -> old-shape frames -> no spans, RPC still fine
        assert remote.propose() is not None
        assert trace.collect_trace(span_sink, "ee" * 16)["n_spans"] == 0
    finally:
        worker.stop()


# --- Cross-process tail verdicts (ISSUE r19 satellite) -----------------

def test_envelope_carries_tail_marks_and_old_consumers_survive():
    edge = trace.TraceContext("aa" * 16, tail=True)
    plain = trace.TraceContext("bb" * 16)
    env = trace.inject([plain, edge])
    assert env["ids"] == [["bb" * 16, plain.span_id],
                          ["aa" * 16, edge.span_id]]
    assert env["tail"] == [1]
    out = trace.extract({"_trace": dict(env)})
    assert [c.tail for c in out] == [False, True]
    # an old consumer reading only "ids" loses nothing: the pair shape
    # is unchanged, the extra key is additive
    legacy = [(tid, sid) for tid, sid in env["ids"]]
    assert len(legacy) == 2
    # malformed tail marks degrade to untailed, never to no-trace
    out = trace.extract({"_trace": {"ids": [["cc" * 16, "d" * 16]],
                                    "tail": ["bogus", 7]}})
    assert len(out) == 1 and not out[0].tail


def test_remote_worker_honors_edge_verdict(span_sink, monkeypatch):
    """The orphan-rate satellite: a subprocess worker's spans for a
    tail-pending trace it did NOT mint hold until the edge's verdict
    sidecar line says kept/dropped — a dropped trace's worker spans no
    longer survive as orphans."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    trace.reset_tail_for_tests()
    try:
        from rafiki_tpu.observe.metrics import registry as _registry

        c0 = _registry().find("rafiki_tpu_trace_tail_total")
        base_dropped = (c0.value(verdict="remote_dropped")
                        if c0 is not None else 0.0)
        # Worker side: contexts arrive via the envelope with the tail
        # mark; their ids are unknown to this process's pending buffer
        # (exactly the subprocess case).
        dropped_tid, kept_tid = "ab" * 16, "cd" * 16
        for tid in (dropped_tid, kept_tid):
            [ctx] = trace.extract(
                {"_trace": {"ids": [[tid, "e" * 16]], "tail": [0]}})
            trace.record_event("worker.predict", "w1", [ctx],
                               time.time(), 0.002)
        # neither trace's spans hit the store yet (held)
        for tid in (dropped_tid, kept_tid):
            assert trace.collect_trace(span_sink, tid)["n_spans"] == 0
        # the edge (another process) writes its verdicts
        trace._write_verdict(dropped_tid, "dropped")
        trace._write_verdict(kept_tid, "kept")
        trace.flush_remote_tail()
        assert trace.collect_trace(span_sink,
                                   dropped_tid)["n_spans"] == 0
        assert trace.collect_trace(span_sink,
                                   kept_tid)["n_spans"] == 1
        c = _registry().find("rafiki_tpu_trace_tail_total")
        assert c.value(verdict="remote_dropped") == base_dropped + 1
        # a STRAGGLER span arriving after the known drop verdict is
        # suppressed immediately (no re-hold)
        [late] = trace.extract(
            {"_trace": {"ids": [[dropped_tid, "f" * 16]],
                        "tail": [0]}})
        trace.record_event("worker.late", "w1", [late], time.time(),
                           0.001)
        trace.flush_remote_tail()
        assert trace.collect_trace(span_sink,
                                   dropped_tid)["n_spans"] == 0
    finally:
        trace.reset_tail_for_tests()


def test_remote_hold_expires_to_retain_on_doubt(span_sink,
                                                monkeypatch):
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setattr(trace, "_REMOTE_HOLD_S", 0.05)
    trace.reset_tail_for_tests()
    try:
        tid = "ef" * 16
        [ctx] = trace.extract(
            {"_trace": {"ids": [[tid, "a" * 16]], "tail": [0]}})
        trace.record_event("worker.predict", "w1", [ctx],
                           time.time(), 0.002)
        assert trace.collect_trace(span_sink, tid)["n_spans"] == 0
        time.sleep(0.1)
        # the sweep rides the next span write; no verdict ever came
        trace.record_event("other", "w1",
                           [trace.TraceContext("ba" * 16)],
                           time.time(), 0.001)
        assert trace.collect_trace(span_sink, tid)["n_spans"] == 1
    finally:
        trace.reset_tail_for_tests()


def test_remote_hold_caps_spans_per_trace(span_sink, monkeypatch):
    """The remote hold is bounded per TRACE, not just per trace count:
    one dense remote trace hits the same span cap as the local pending
    buffer and overflows to disk (retain-on-doubt), never growing an
    unbounded in-memory list for the hold window."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setattr(trace, "_PENDING_MAX_SPANS", 5)
    trace.reset_tail_for_tests()
    try:
        tid = "fe" * 16
        for i in range(8):
            [ctx] = trace.extract(
                {"_trace": {"ids": [[tid, "a" * 16]], "tail": [0]}})
            trace.record_event(f"worker.s{i}", "w1", [ctx],
                               time.time(), 0.001)
        # spans 1..5 buffered; the 6th overflowed all six to disk;
        # 7..8 re-hold (bounded again) awaiting a verdict
        assert trace.collect_trace(span_sink, tid)["n_spans"] == 6
        with trace._tail_lock:
            held = trace._remote_pending.get(tid)
            assert held is not None and len(held[1]) == 2
    finally:
        trace.reset_tail_for_tests()


def test_edge_complete_writes_verdict_sidecar(span_sink, monkeypatch):
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    trace.reset_tail_for_tests()
    try:
        dropped = trace.start_trace(None)
        trace.complete(dropped, 0.001)           # fast/ok -> dropped
        kept = trace.start_trace(None)
        trace.complete(kept, 0.001, error=True)  # error -> kept
        lines = [json.loads(x) for x in
                 open(os.path.join(span_sink,
                                   trace.VERDICT_FILE))]
        verdicts = {r["t"]: r["v"] for r in lines}
        assert verdicts[dropped.trace_id] == "dropped"
        assert verdicts[kept.trace_id] == "kept"
    finally:
        trace.reset_tail_for_tests()


# --- Segment compaction (ISSUE r19 satellite) --------------------------

def test_compaction_rewrites_frozen_segment_and_marks_index(
        span_sink, monkeypatch):
    path = os.path.join(span_sink, trace.SPAN_FILE)
    for tid in ("aa" * 16, "bb" * 16, "cc" * 16):
        trace.record_event("worker.predict", "w",
                           [trace.TraceContext(tid)], time.time(),
                           0.001)
    os.replace(path, path + ".1")  # freeze (as a roll would)
    trace._build_index(path + ".1")
    assert not trace.segment_compacted(path + ".1")
    trace._write_verdict("bb" * 16, "dropped")
    [out] = trace.compact_segments(span_sink)
    assert (out["removed"], out["kept"]) == (1, 2)
    assert trace.segment_compacted(path + ".1")
    content = open(path + ".1").read()
    assert "bb" * 16 not in content and "aa" * 16 in content
    # diagnostics report the compacted marker; the surviving trace
    # still stitches via the rebuilt index
    res = trace.collect_trace(span_sink, "aa" * 16)
    assert res["n_spans"] == 1
    assert [d.get("compacted") for d in res["segments"]
            if d["segment"].endswith(".1")] == [True]
    # a second pass skips the already-compacted segment
    assert trace.compact_segments(span_sink) == []
    from rafiki_tpu.observe.metrics import registry as _registry

    c = _registry().find("rafiki_tpu_trace_store_total")
    assert c.value(event="compact") >= 1
    # a later KEPT verdict for the same id protects it from erasure
    trace._write_verdict("aa" * 16, "dropped")
    trace._write_verdict("aa" * 16, "kept")
    assert "aa" * 16 not in trace._dropped_verdict_ids()


def test_stale_index_is_detected_and_rebuilt(span_sink):
    """A reader racing compaction (segment already replaced, index not
    yet) must not seek the old generation's offsets into the new file:
    the index records its segment's byte size, a mismatch loads as
    missing, and the lookup rebuilds from the file it actually has."""
    path = os.path.join(span_sink, trace.SPAN_FILE)
    for tid in ("aa" * 16, "bb" * 16, "cc" * 16):
        trace.record_event("worker.predict", "w",
                           [trace.TraceContext(tid)], time.time(),
                           0.001)
    os.replace(path, path + ".1")
    trace._build_index(path + ".1")
    # simulate the compaction window: rewrite the segment (first line
    # removed, every later offset shifted) leaving the OLD index
    with open(path + ".1", "rb") as f:
        lines = f.readlines()
    with open(path + ".1.tmp", "wb") as f:
        f.write(b"".join(lines[1:]))
    os.replace(path + ".1.tmp", path + ".1")
    assert trace._load_index_data(path + ".1") is None  # stale by size
    res = trace.collect_trace(span_sink, "cc" * 16)
    assert res["n_spans"] == 1
    [d] = [d for d in res["segments"] if d["segment"].endswith(".1")]
    assert d["mode"] == "index_rebuilt"


def test_roll_triggers_compaction_of_older_segment(span_sink,
                                                   monkeypatch):
    """The idle-time trigger: with tail sampling armed, each roll
    compacts one OLDER frozen segment — never the just-rolled .1
    (verdicts may still be pending) and never .2 (a co-writing
    process's append handle may still chase the renames into it; an
    inode-swapping rewrite under that handle would lose its spans)."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0.5")
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, "0.0005")  # ~500 bytes
    trace.reset_tail_for_tests()
    try:
        path = os.path.join(span_sink, trace.SPAN_FILE)
        # two frozen generations; the ORPHAN sits in the older one
        # (.2, about to shift to .3 — the compaction candidate)
        trace.record_event("orphan", "w",
                           [trace.TraceContext("dd" * 16)],
                           time.time(), 0.001)
        os.replace(path, path + ".2")
        trace.configure(span_sink)  # reopen: the handle chased the move
        trace._build_index(path + ".2")
        trace.record_event("recent", "w",
                           [trace.TraceContext("cc" * 16)],
                           time.time(), 0.001)
        os.replace(path, path + ".1")
        trace.configure(span_sink)
        trace._build_index(path + ".1")
        trace._write_verdict("dd" * 16, "dropped")
        # now overflow the active file so a real roll fires:
        # .2 -> .3, .1 -> .2, active -> .1
        big_attrs = {"pad": "x" * 200}
        for i in range(5):
            trace.record_event("spanny", "w",
                               [trace.TraceContext("ee" * 16)],
                               time.time(), 0.001, attrs=big_attrs)
        deadline = time.time() + 5
        while not os.path.exists(path + ".3") and \
                time.time() < deadline:
            trace.record_event("spanny", "w",
                               [trace.TraceContext("ee" * 16)],
                               time.time(), 0.001, attrs=big_attrs)
        assert os.path.exists(path + ".3")
        # the roll compacted the shifted .3: the orphan is gone —
        # while the two newest generations stayed untouched
        assert trace.segment_compacted(path + ".3")
        assert "dd" * 16 not in open(path + ".3").read()
        assert not trace.segment_compacted(path + ".2")
    finally:
        trace.reset_tail_for_tests()
