"""End-to-end request tracing: ids, envelope carry, spans, stitching.

Covers the ISSUE-2 test checklist: trace-id propagation across the
memory and tcp buses (including the old-frame fallback), the HTTP edge
(mint + honor + echo of ``X-Trace-Id``), span recording through the
shared JSONL sink, and the admin's ``GET /trace/<id>`` stitcher.
"""

import json
import os
import threading
import time

import pytest
import requests

from rafiki_tpu.bus import BusClient, BusServer, MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.observe import trace


@pytest.fixture()
def span_sink(tmp_path):
    """Point the process span sink at a temp dir; always restore."""
    trace.configure(str(tmp_path))
    yield str(tmp_path)
    trace.configure(None)


@pytest.fixture(params=["memory", "tcp"])
def bus(request):
    if request.param == "memory":
        yield MemoryBus()
        return
    server = BusServer().start()
    client = BusClient(server.host, server.port)
    yield client
    client.close()
    server.stop()


# --- Context / header parsing ---

def test_start_trace_mints_and_parses():
    ctx = trace.start_trace(None)
    assert ctx is not None and len(ctx.trace_id) == 32
    parsed = trace.start_trace(f"{ctx.trace_id}-{ctx.span_id}")
    assert parsed.trace_id == ctx.trace_id
    assert parsed.parent_id == ctx.span_id
    bare = trace.start_trace("sometid")
    assert bare.trace_id == "sometid" and bare.parent_id is None
    # a standard dashed UUID is taken WHOLE, never split at its dashes
    dashed = "550e8400-e29b-41d4-a716-446655440000"
    got = trace.start_trace(dashed)
    assert got.trace_id == dashed and got.parent_id is None


def test_sample_rate_zero_suppresses_fresh_traces(monkeypatch):
    monkeypatch.setenv(trace.TRACE_SAMPLE_ENV, "0")
    assert trace.start_trace(None) is None
    # ...but an incoming id is ALWAYS honored
    assert trace.start_trace("abc123").trace_id == "abc123"
    monkeypatch.setenv(trace.TRACE_SAMPLE_ENV, "not-a-number")
    assert trace.sample_rate() == 1.0


def test_thread_local_current_context():
    assert trace.current() is None
    ctx = trace.TraceContext("t1")
    with trace.use(ctx):
        assert trace.current() is ctx
        with trace.use(None):
            assert trace.current() is None
        assert trace.current() is ctx
    assert trace.current() is None


# --- Envelope inject/extract (old-frame fallback) ---

def test_inject_extract_roundtrip():
    ctxs = [trace.TraceContext("t" * 32), trace.TraceContext("u" * 32)]
    frame = {"batch_id": "b1", "queries": [1, 2],
             trace.ENVELOPE_KEY: trace.inject(ctxs)}
    out = trace.extract(frame)
    assert [c.trace_id for c in out] == ["t" * 32, "u" * 32]
    # extraction CONTINUES the propagated span: downstream child spans
    # parent onto the sender's span
    assert out[0].span_id == ctxs[0].span_id
    # envelope is POPPED: downstream frame handling never sees it
    assert trace.ENVELOPE_KEY not in frame


def test_old_frames_and_malformed_envelopes_fall_back():
    assert trace.extract({"batch_id": "b", "queries": []}) == []
    assert trace.extract("not-a-dict") == []
    assert trace.extract({trace.ENVELOPE_KEY: "garbage"}) == []
    assert trace.extract({trace.ENVELOPE_KEY: {"ids": "nope"}}) == []
    assert trace.inject([]) is None
    assert trace.inject([None]) is None


def test_envelope_caps_trace_count():
    ctxs = [trace.TraceContext(f"t{i}") for i in range(100)]
    env = trace.inject(ctxs)
    assert len(env["ids"]) == trace.MAX_ENVELOPE_TRACES


# --- Propagation across the bus (memory + tcp) ---

def test_trace_rides_bus_envelope(bus):
    cache = Cache(bus)
    ctx = trace.TraceContext("cafe" * 8)
    cache.send_query_batch_fanout(["wA", "wB"], [{"v": 1}],
                                  trace_ctxs=[ctx])
    for w in ("wA", "wB"):
        items = cache.pop_queries(w, timeout=5.0)
        assert len(items) == 1
        got = trace.extract(items[0])
        assert [c.trace_id for c in got] == ["cafe" * 8]
        assert got[0].span_id == ctx.span_id
        # payload untouched by the envelope
        assert items[0]["queries"] == [{"v": 1}]


def test_ambient_context_injected_on_direct_path(bus):
    cache = Cache(bus)
    with trace.use(trace.TraceContext("beef" * 8)):
        cache.send_query_batch("wC", [1, 2])
        cache.send_query("wC", 3)
    items = cache.pop_queries("wC", timeout=5.0)
    assert len(items) == 2
    for it in items:
        assert [c.trace_id for c in trace.extract(it)] == ["beef" * 8]


def test_untraced_frames_stay_old_shape(bus):
    """No ambient context -> the frame has NO trace key at all (an old
    consumer sees byte-identical frames)."""
    cache = Cache(bus)
    cache.send_query_batch_fanout(["wD"], [{"v": 1}])
    item = cache.pop_queries("wD", timeout=5.0)[0]
    assert trace.ENVELOPE_KEY not in item


# --- Span sink + stitching ---

def test_record_and_collect_spans(span_sink):
    tid = "deadbeef" * 4
    ctx = trace.TraceContext(tid)
    t0 = time.time()
    trace.record_event("http POST /predict", "admin", [ctx], t0, 0.010,
                       child=False)
    trace.record_event("worker.predict", "w1", [ctx], t0 + 0.002, 0.005,
                       attrs={"n_queries": 4})
    out = trace.collect_trace(span_sink, tid)
    assert out["n_spans"] == 2
    names = [s["name"] for s in out["spans"]]
    assert names == ["http POST /predict", "worker.predict"]  # ordered
    assert out["spans"][0]["offset_ms"] == 0.0
    assert out["spans"][1]["offset_ms"] == pytest.approx(2.0, abs=1.0)
    # the child span parents onto the propagated span
    assert out["spans"][1]["parent_id"] == ctx.span_id
    assert out["spans"][1]["attrs"]["n_queries"] == 4
    # unknown trace -> empty, not an error
    assert trace.collect_trace(span_sink, "nope")["n_spans"] == 0


def test_collect_skips_corrupt_lines(span_sink):
    tid = "feed" * 8
    with open(trace.span_log_path(span_sink), "a") as f:
        f.write(f"{tid} not json\n")
        f.write(json.dumps({"trace_id": tid, "name": "ok",
                            "start_s": 1.0, "dur_ms": 1}) + "\n")
    out = trace.collect_trace(span_sink, tid)
    assert out["n_spans"] == 1 and out["spans"][0]["name"] == "ok"


def _spam(ctx, n, name="spam"):
    for _ in range(n):
        trace.record_event(name, "s", [ctx], 1.0, 0.001)


def test_multi_segment_store_indexed_read(span_sink, monkeypatch):
    """ISSUE r17 acceptance: a multi-segment store serves
    GET /trace/<id> via the sidecar index — frozen segments are seek+
    readline at indexed offsets, never a full-file scan — including a
    trace whose spans straddle a segment roll."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))  # 1 KiB
    monkeypatch.setenv(trace.TRACE_RETAIN_SEGMENTS_ENV, "3")
    straddle = trace.TraceContext("ab" * 16)
    filler = trace.TraceContext("cd" * 16)
    # One straddle span early, spam until at least two rolls happened,
    # one straddle span late: its spans now live in a frozen segment
    # AND the active file.
    trace.record_event("first", "s", [straddle], 1.0, 0.001)
    path = trace.span_log_path(span_sink)
    for _ in range(100):
        _spam(filler, 5)
        if os.path.exists(path + ".2"):
            break
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    trace.record_event("last", "s", [straddle], 2.0, 0.001)
    # Roll-time sidecar indexes exist for the frozen generations.
    assert os.path.exists(trace.index_path(path + ".1"))
    out = trace.collect_trace(span_sink, straddle.trace_id)
    names = {s["name"] for s in out["spans"]}
    assert "first" in names and "last" in names
    # The read-path evidence: every frozen segment was an INDEXED
    # read, and the bytes it cost are the matching lines only — far
    # below the segment size (the no-full-scan pin).
    frozen = [d for d in out["segments"]
              if d["segment"] != trace.SPAN_FILE]
    assert frozen, out["segments"]
    for diag in frozen:
        assert diag["mode"] == "index", out["segments"]
        seg = os.path.join(span_sink, diag["segment"])
        if diag["n_spans"] == 0:
            assert diag["bytes_read"] == 0, diag
        else:
            assert diag["bytes_read"] < os.path.getsize(seg) / 2, diag
    # The filler trace is found through the same index path.
    assert trace.collect_trace(span_sink,
                               filler.trace_id)["n_spans"] > 0
    # Warm repeat on the ACTIVE segment scans zero new bytes (the
    # incremental cache only ever reads the appended tail).
    again = trace.collect_trace(span_sink, straddle.trace_id)
    active = [d for d in again["segments"]
              if d["segment"] == trace.SPAN_FILE]
    assert active and active[0]["mode"] == "scan_tail"
    span_bytes = sum(d["n_spans"] for d in again["segments"])
    assert span_bytes  # sanity: the trace is still found


def test_index_rebuilt_when_sidecar_missing(span_sink, monkeypatch):
    """A frozen segment whose .idx vanished (partial copy, manual
    cleanup) is re-indexed lazily — and the rebuilt sidecar persists
    for the next reader."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))
    ctx = trace.TraceContext("ee" * 16)
    _spam(ctx, 20)
    path = trace.span_log_path(span_sink)
    assert os.path.exists(path + ".1")
    os.remove(trace.index_path(path + ".1"))
    out = trace.collect_trace(span_sink, ctx.trace_id)
    assert out["n_spans"] > 0
    modes = {d["segment"]: d["mode"] for d in out["segments"]}
    assert modes.get(trace.SPAN_FILE + ".1") == "index_rebuilt"
    assert os.path.exists(trace.index_path(path + ".1"))
    out2 = trace.collect_trace(span_sink, ctx.trace_id)
    modes2 = {d["segment"]: d["mode"] for d in out2["segments"]}
    assert modes2.get(trace.SPAN_FILE + ".1") == "index"


def test_retention_bounds_segments_and_bytes(span_sink, monkeypatch):
    """The generation chain is bounded by BOTH knobs: at most
    RETAIN_SEGMENTS rolled files, and oldest generations are deleted
    when the rolled chain exceeds RETAIN_MB (the newest rolled segment
    always survives)."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))
    monkeypatch.setenv(trace.TRACE_RETAIN_SEGMENTS_ENV, "2")
    ctx = trace.TraceContext("aa" * 16)
    path = trace.span_log_path(span_sink)
    _spam(ctx, 200)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # count bound enforced
    # Byte budget below one segment: only .1 survives the next roll.
    monkeypatch.setenv(trace.TRACE_RETAIN_MB_ENV, str(0.5 / 1024))
    _spam(ctx, 40)
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".2"), "byte budget not enforced"


def test_tail_sampling_verdicts(span_sink, monkeypatch):
    """Error and slow traces always persist; fast ones drop at
    sample=0 — and a straggler span arriving after the drop verdict is
    suppressed, not resurrected as an orphan."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "100")
    trace.reset_tail_for_tests()
    try:
        # Error outcome: buffered spans flush.
        err = trace.start_trace(None)
        assert err is not None and err.tail
        trace.record_event("edge", "svc", [err], 1.0, 0.01, child=False)
        assert trace.collect_trace(span_sink,
                                   err.trace_id)["n_spans"] == 0
        trace.complete(err, 0.01, error=True)
        assert trace.collect_trace(span_sink,
                                   err.trace_id)["n_spans"] == 1
        # Slow outcome: kept despite sample=0.
        slow = trace.start_trace(None)
        trace.record_event("edge", "svc", [slow], 1.0, 0.2, child=False)
        trace.complete(slow, 0.2, error=False)
        assert trace.collect_trace(span_sink,
                                   slow.trace_id)["n_spans"] == 1
        # Fast + ok at sample 0: dropped, late spans suppressed.
        fast = trace.start_trace(None)
        trace.record_event("edge", "svc", [fast], 1.0, 0.001,
                           child=False)
        trace.complete(fast, 0.001, error=False)
        assert trace.collect_trace(span_sink,
                                   fast.trace_id)["n_spans"] == 0
        trace.record_event("late.worker", "w", [fast], 1.1, 0.001)
        assert trace.collect_trace(span_sink,
                                   fast.trace_id)["n_spans"] == 0
        # An honored X-Trace-Id bypasses tail sampling entirely.
        honored = trace.start_trace("ff" * 16)
        assert honored is not None and not honored.tail
        trace.record_event("edge", "svc", [honored], 1.0, 0.001,
                           child=False)
        assert trace.collect_trace(span_sink,
                                   "ff" * 16)["n_spans"] == 1
    finally:
        trace.reset_tail_for_tests()


def test_tail_sampling_seeded_rate(span_sink, monkeypatch):
    """Fast traces keep at exactly the seeded RNG's decision sequence
    for the configured rate — 100% of error/slow traces survive a
    seeded mixed workload while fast ones sample (the r17 acceptance
    shape)."""
    import random as _random

    rate = 0.3
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, str(rate))
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "50")
    trace.reset_tail_for_tests()
    trace.seed_tail(42)
    try:
        kept_fast = 0
        n_fast = 0
        rng = _random.Random(42)  # mirror of the module's seeded rng
        expected_kept = 0
        for i in range(60):
            ctx = trace.start_trace(None)
            assert ctx is not None
            trace.record_event("edge", "svc", [ctx], 1.0, 0.001,
                               child=False)
            if i % 5 == 0:   # error: must survive
                trace.complete(ctx, 0.001, error=True)
                assert trace.collect_trace(
                    span_sink, ctx.trace_id)["n_spans"] == 1
            elif i % 5 == 1:  # slow: must survive
                trace.complete(ctx, 0.5, error=False)
                assert trace.collect_trace(
                    span_sink, ctx.trace_id)["n_spans"] == 1
            else:            # fast: seeded coin
                n_fast += 1
                if rng.random() < rate:
                    expected_kept += 1
                trace.complete(ctx, 0.001, error=False)
                kept_fast += trace.collect_trace(
                    span_sink, ctx.trace_id)["n_spans"]
        assert kept_fast == expected_kept
        assert 0 < kept_fast < n_fast  # genuinely sampling
    finally:
        trace.reset_tail_for_tests()


def test_tail_pending_overflow_flushes(span_sink, monkeypatch):
    """A pending trace overflowing the per-trace span cap (an edge
    that never completes) flushes to the store — retain on doubt,
    never silent loss."""
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    trace.reset_tail_for_tests()
    try:
        ctx = trace.start_trace(None)
        for _ in range(trace._PENDING_MAX_SPANS + 5):
            trace.record_event("s", "svc", [ctx], 1.0, 0.001)
        out = trace.collect_trace(span_sink, ctx.trace_id)
        assert out["n_spans"] > trace._PENDING_MAX_SPANS
        # Completion after the overflow is a no-op (already flushed).
        trace.complete(ctx, 0.001, error=False)
        assert trace.collect_trace(span_sink,
                                   ctx.trace_id)["n_spans"] > 0
    finally:
        trace.reset_tail_for_tests()


def test_tail_sampling_at_http_edge(span_sink, monkeypatch):
    """The JsonHttpServer edge delivers the verdict: a 5xx response
    keeps its trace's spans, a fast 200 at sample=0 drops them."""
    from rafiki_tpu.utils.service import JsonHttpServer

    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "60000")
    trace.reset_tail_for_tests()

    def ok(params, body, ctx):
        return 200, {"ok": True}

    def boom(params, body, ctx):
        raise RuntimeError("kaput")

    server = JsonHttpServer([("GET", "/ok", ok), ("GET", "/boom", boom)],
                            host="127.0.0.1", name="tail-svc").start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        r_ok = requests.get(base + "/ok", timeout=10)
        tid_ok = r_ok.headers["X-Trace-Id"].split("-")[0]
        r_boom = requests.get(base + "/boom", timeout=10)
        assert r_boom.status_code == 500
        tid_boom = r_boom.headers["X-Trace-Id"].split("-")[0]
        assert trace.collect_trace(span_sink, tid_ok)["n_spans"] == 0
        out = trace.collect_trace(span_sink, tid_boom)
        assert out["n_spans"] == 1
        assert out["spans"][0]["attrs"]["status"] == 500
    finally:
        server.stop()
        trace.reset_tail_for_tests()


def test_span_log_rotates_at_size_cap(span_sink, monkeypatch):
    """The sink rolls spans.jsonl to one .1 generation at the size cap
    (a client forcing X-Trace-Id must not be able to fill the disk),
    and collect_trace reads both generations."""
    monkeypatch.setenv(trace.TRACE_MAX_MB_ENV, str(1 / 1024))  # 1 KiB
    old_tid = "aa" * 16
    ctx = trace.TraceContext(old_tid)
    for _ in range(20):  # ~170 bytes/line -> crosses 1 KiB
        trace.record_event("spam", "s", [ctx], 1.0, 0.001)
    assert os.path.exists(trace.span_log_path(span_sink) + ".1")
    new_tid = "bb" * 16
    trace.record_event("after-roll", "s", [trace.TraceContext(new_tid)],
                       2.0, 0.001)
    # both generations are stitched
    assert trace.collect_trace(span_sink, old_tid)["n_spans"] > 0
    assert trace.collect_trace(span_sink, new_tid)["n_spans"] == 1
    # total on-disk span data stays bounded (~2 generations of the cap)
    total = sum(os.path.getsize(p)
                for p in (trace.span_log_path(span_sink),
                          trace.span_log_path(span_sink) + ".1")
                if os.path.exists(p))
    assert total < 3 * 1024


def test_span_context_manager_noops_without_sink():
    trace.configure(None)
    with trace.span("x", service="s"):  # no sink, no ctx: pure no-op
        pass
    with trace.use(trace.TraceContext("t1")):
        with trace.span("y", service="s"):
            pass  # sink unconfigured: still a no-op, no crash


# --- HTTP edge (JsonHttpServer) ---

def test_http_edge_mints_echoes_and_honors_trace_ids(span_sink):
    from rafiki_tpu.utils.service import JsonHttpServer

    seen = []

    def handler(params, body, ctx):
        seen.append(trace.current())
        return 200, {"ok": True}

    server = JsonHttpServer([("GET", "/thing/<id>", handler)],
                            host="127.0.0.1", name="edge-svc").start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # Fresh mint: response echoes the new id, handler saw the ctx.
        r = requests.get(base + "/thing/a", timeout=10)
        tid = r.headers["X-Trace-Id"].split("-")[0]
        assert len(tid) == 32
        assert seen[-1] is not None and seen[-1].trace_id == tid
        # Incoming id honored end to end.
        r = requests.get(base + "/thing/b", timeout=10,
                         headers={"X-Trace-Id": "abc" + "0" * 29})
        assert r.headers["X-Trace-Id"].startswith("abc" + "0" * 29)
        # The edge span landed in the sink, labeled by route PATTERN.
        out = trace.collect_trace(span_sink, tid)
        assert out["n_spans"] == 1
        assert out["spans"][0]["name"] == "http GET /thing/<id>"
        assert out["spans"][0]["service"] == "edge-svc"
    finally:
        server.stop()


# --- Through the serving path (predictor frontend + worker shape) ---

class _EchoWorker:
    """Bus-level stand-in mirroring InferenceWorker's frame handling."""

    def __init__(self, bus, worker_id="w1", job_id="job"):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.stop_flag = threading.Event()
        self.trace_ids = []
        self.cache.register_worker(job_id, worker_id,
                                   info={"trial_id": "t1"})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            ctxs = trace.extract_frames(items)
            self.trace_ids.extend(c.trace_id for c in ctxs)
            for it in items:
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [[float(q), 0.0] for q in it["queries"]])

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


def test_predict_trace_visible_at_edge_envelope_and_spans(span_sink):
    """The acceptance shape: one /predict through the micro-batcher
    yields ONE trace id at the HTTP edge, inside the bus envelope, and
    in the span log (edge + scatter + gather spans)."""
    from rafiki_tpu.predictor.app import PredictorService

    bus = MemoryBus()
    worker = _EchoWorker(bus)
    svc = PredictorService("tsvc", "job", meta=None, bus=bus,
                           host="127.0.0.1")
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    svc.batcher.start()
    svc._http.start()
    try:
        r = requests.post(f"http://127.0.0.1:{svc.port}/predict",
                          json={"queries": [1, 2]}, timeout=30)
        assert r.status_code == 200
        tid = r.headers["X-Trace-Id"].split("-")[0]
        deadline = time.time() + 5
        while time.time() < deadline and tid not in worker.trace_ids:
            time.sleep(0.05)
        assert tid in worker.trace_ids, "envelope never reached worker"
        # gather span is recorded after the response is sliced out;
        # give the gather thread a beat.
        for _ in range(50):
            out = trace.collect_trace(span_sink, tid)
            if out["n_spans"] >= 3:
                break
            time.sleep(0.05)
        names = {s["name"] for s in out["spans"]}
        assert "http POST /predict" in names
        assert "predictor.scatter" in names
        assert "predictor.gather" in names
    finally:
        svc._http.stop()
        svc.batcher.stop()
        worker.stop()


def test_inference_worker_records_predict_span(span_sink):
    """The real InferenceWorker's dispatch/complete path pops the
    envelope and records the worker span."""
    from rafiki_tpu.worker.inference import InferenceWorker

    bus = MemoryBus()
    worker = InferenceWorker("wsvc", "job", "t1", meta=None, params=None,
                            bus=bus)

    class _Model:
        def predict_submit(self, queries):
            return lambda: [[float(q)] for q in queries]

    worker._model = _Model()
    ctx = trace.TraceContext("ab" * 16)
    items = [{"batch_id": "b1", "queries": [1, 2],
              trace.ENVELOPE_KEY: trace.inject([ctx])}]
    handle = worker._dispatch_batch(items)
    worker._complete_batch(*handle)
    out = trace.collect_trace(span_sink, "ab" * 16)
    assert out["n_spans"] == 1
    span = out["spans"][0]
    assert span["name"] == "worker.predict"
    assert span["service"] == "wsvc"
    assert span["parent_id"] == ctx.span_id
    assert span["attrs"]["trial_id"] == "t1"
    # the reply actually went out
    reply = bus.pop("r:b1", timeout=2.0)
    assert reply["predictions"] == [[1.0], [2.0]]


# --- Admin stitching over REST ---

def test_admin_trace_route_and_metrics(tmp_path):
    """GET /trace/<id> on admin stitches the platform's span log; GET
    /metrics serves the registry (the admin-frontend acceptance leg)."""
    from rafiki_tpu.platform import LocalPlatform

    platform = LocalPlatform(workdir=str(tmp_path / "plat"), http=True,
                             supervise_interval=0)
    try:
        tid = "11" * 16
        ctx = trace.TraceContext(tid)
        trace.record_event("http POST /predict", "predictor-x", [ctx],
                           time.time(), 0.02, child=False)
        trace.record_event("worker.predict", "w1", [ctx],
                           time.time() + 0.001, 0.01)
        base = f"http://127.0.0.1:{platform.app.port}"
        tok = requests.post(base + "/tokens", json={
            "email": "superadmin@rafiki", "password": "rafiki"},
            timeout=10).json()["token"]
        hdr = {"Authorization": f"Bearer {tok}"}
        out = requests.get(f"{base}/trace/{tid}", headers=hdr,
                           timeout=10).json()
        assert out["trace_id"] == tid and out["n_spans"] == 2
        assert out["spans"][0]["name"] == "http POST /predict"
        # unauthenticated -> 401 like every other admin read
        assert requests.get(f"{base}/trace/{tid}",
                            timeout=10).status_code == 401
        # /metrics needs no auth (scrape endpoint) and is valid text
        m = requests.get(base + "/metrics", timeout=10)
        assert m.status_code == 200 and "# TYPE" in m.text
        assert "rafiki_tpu_http_request_seconds" in m.text
        # /status surfaces the mfu map (empty here, but present)
        status = requests.get(base + "/status", headers=hdr,
                              timeout=10).json()
        assert "mfu" in status
        # /trial_phases feeds the dashboard's phase-breakdown panel:
        # all six phases present (zero-count here — no resident trials)
        # and authenticated like every other admin read.
        tp = requests.get(base + "/trial_phases", headers=hdr,
                          timeout=10).json()
        assert set(tp["phases"]) == {"propose", "load", "stage",
                                     "train", "eval", "persist"}
        assert set(tp["caches"]) == {"dataset", "stage"}
        assert "resident" in tp and "enabled" in tp
        assert requests.get(base + "/trial_phases",
                            timeout=10).status_code == 401
    finally:
        platform.shutdown()
        trace.configure(None)


# --- Advisor RPC trace propagation (ISSUE-3 satellite) ---

def test_advisor_rpc_carries_trace_context(span_sink):
    """RemoteAdvisor injects the caller's context into proposal and
    feedback frames; the AdvisorWorker records advisor.<op> spans under
    the same trace id. Old frames (no envelope) stay span-free."""
    from rafiki_tpu.advisor import RandomAdvisor
    from rafiki_tpu.advisor.worker import AdvisorWorker, RemoteAdvisor
    from rafiki_tpu.model.knobs import IntegerKnob

    bus = MemoryBus()
    advisor = RandomAdvisor({"x": IntegerKnob(1, 9)})
    worker = AdvisorWorker(advisor, bus, "sub1").start()
    remote = RemoteAdvisor(bus, "sub1", timeout=10.0)
    try:
        tid = "ad" * 16
        with trace.use(trace.TraceContext(tid)):
            prop = remote.propose()
            assert prop is not None
            remote.feedback(prop, 0.5)
        # feedback is fire-and-forget; give the worker a beat
        deadline = time.time() + 5
        names = set()
        while time.time() < deadline and len(names) < 2:
            out = trace.collect_trace(span_sink, tid)
            names = {s["name"] for s in out["spans"]}
            time.sleep(0.05)
        assert names == {"advisor.propose", "advisor.feedback"}, names
        for s in trace.collect_trace(span_sink, tid)["spans"]:
            assert s["service"].startswith("advisor-")
        # Untraced caller -> old-shape frames -> no spans, RPC still fine
        assert remote.propose() is not None
        assert trace.collect_trace(span_sink, "ee" * 16)["n_spans"] == 0
    finally:
        worker.stop()
