import numpy as np
import pytest

from rafiki_tpu.model import (ArchKnob, CategoricalKnob, FixedKnob, FloatKnob,
                              IntegerKnob, PolicyKnob, knob_config_from_json,
                              knob_config_to_json, knobs_to_vector,
                              sample_knobs, searchable_dims, validate_knobs,
                              vector_to_knobs)


CONFIG = {
    "lr": FloatKnob(1e-4, 1e-1, is_exp=True),
    "units": IntegerKnob(16, 256),
    "act": CategoricalKnob(["relu", "gelu", "tanh"]),
    "epochs": FixedKnob(3),
    "share": PolicyKnob("SHARE_PARAMS"),
}


def test_sample_and_validate(rng):
    for _ in range(20):
        knobs = sample_knobs(CONFIG, rng)
        out = validate_knobs(CONFIG, knobs)
        assert 1e-4 <= out["lr"] <= 1e-1
        assert 16 <= out["units"] <= 256
        assert out["act"] in ("relu", "gelu", "tanh")
        assert out["epochs"] == 3


def test_validate_rejects():
    with pytest.raises(ValueError):
        validate_knobs(CONFIG, {})
    knobs = {"lr": 1.0, "units": 32, "act": "relu", "epochs": 3, "share": False}
    with pytest.raises(ValueError):
        validate_knobs(CONFIG, knobs)  # lr out of range
    knobs["lr"] = 1e-2
    knobs["bogus"] = 1
    with pytest.raises(ValueError):
        validate_knobs(CONFIG, knobs)


def test_json_roundtrip(rng):
    cfg2 = knob_config_from_json(knob_config_to_json(CONFIG))
    assert set(cfg2) == set(CONFIG)
    knobs = sample_knobs(cfg2, rng)
    validate_knobs(CONFIG, knobs)


def test_vector_embedding_roundtrip(rng):
    dims = searchable_dims(CONFIG)
    assert dims == 1 + 1 + 3  # lr + units + act one-hot
    for _ in range(10):
        knobs = sample_knobs(CONFIG, rng)
        x = knobs_to_vector(CONFIG, knobs)
        assert x.shape == (dims,)
        assert np.all(x >= 0) and np.all(x <= 1)
        back = vector_to_knobs(CONFIG, x, rng)
        assert back["act"] == knobs["act"]
        assert abs(back["units"] - knobs["units"]) <= 1
        assert np.isclose(np.log(back["lr"]), np.log(knobs["lr"]), atol=0.05)


def test_log_scale_sampling(rng):
    knob = FloatKnob(1e-4, 1.0, is_exp=True)
    samples = [knob.sample(rng) for _ in range(500)]
    # log-uniform → median around geometric mean (1e-2), not arithmetic (0.5)
    assert 1e-3 < np.median(samples) < 1e-1


def test_arch_knob(rng):
    knob = ArchKnob([[0, 1, 2], [0, 1], [0, 1, 2, 3]])
    for _ in range(10):
        v = knob.sample(rng)
        assert knob.validate(v) == v
    with pytest.raises(ValueError):
        knob.validate([0, 5, 0])
    with pytest.raises(ValueError):
        knob.validate([0, 1])


def test_validate_defaults_missing_fixed_knobs():
    """Trial rows recorded before a model gained a new FixedKnob stay
    loadable: missing fixed (deployment) knobs default to their pinned
    value; searchable knobs stay required."""
    from rafiki_tpu.model.knobs import (FixedKnob, IntegerKnob,
                                        validate_knobs)

    config = {"width": IntegerKnob(1, 8), "mode": FixedKnob("ring")}
    out = validate_knobs(config, {"width": 4})
    assert out == {"width": 4, "mode": "ring"}
    with pytest.raises(ValueError, match="Missing knob: width"):
        validate_knobs(config, {"mode": "ring"})
