"""Fault-injection plane: plan grammar, determinism, injection sites,
the zero-overhead-when-disabled contract, and the tcp bus client's
bounded-backoff reconnection semantics (frame-sent vs frame-unsent)."""

import socket
import struct
import threading
import time

import pytest
import requests

from rafiki_tpu import faults
from rafiki_tpu.bus import BusClient, BusServer, MemoryBus
from rafiki_tpu.observe.metrics import registry
from rafiki_tpu.utils.service import JsonHttpServer

COUNTER = "rafiki_tpu_fault_injections_total"


@pytest.fixture(autouse=True)
def _clean_fault_plane(monkeypatch):
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.SEED_ENV, raising=False)
    faults.reset()
    yield
    faults.reset()


def _injections_total() -> float:
    c = registry().find(COUNTER)
    if c is None:
        return 0.0
    return sum(v for _, v in c.samples())


# --- Plan grammar ------------------------------------------------------

class TestPlanGrammar:
    def test_parse_multi_rule(self):
        plan = faults.FaultPlan.parse(
            "bus.drop:op=push; http.error:code=502,route=/predict ;"
            "worker.crash:n=3")
        assert {s for s in plan.rules} == {"bus", "http", "worker"}
        assert plan.rules["http"][0].params["code"] == "502"

    def test_unknown_site_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown"):
            faults.FaultPlan.parse("bus.explode")
        with pytest.raises(ValueError, match="unknown"):
            faults.FaultPlan.parse("gpu.delay:ms=5")

    def test_malformed_param_rejected(self):
        with pytest.raises(ValueError, match="key=value"):
            faults.FaultPlan.parse("bus.delay:ms")
        with pytest.raises(ValueError):
            faults.FaultPlan.parse("bus.delay:ms=abc")
        with pytest.raises(ValueError):
            faults.FaultPlan.parse("worker.crash:n=two")

    def test_unknown_param_key_rejected(self):
        """A typo'd key ("probability=", capital "N=") must fail the
        parse, not silently leave the rule firing on every call with
        defaults — a chaos run would measure the wrong plan while
        claiming the typed one."""
        with pytest.raises(ValueError, match="unknown param"):
            faults.FaultPlan.parse("bus.delay:probability=0.02,ms=2")
        with pytest.raises(ValueError, match="unknown param"):
            faults.FaultPlan.parse("worker.crash:N=2")

    def test_multiple_selection_params_rejected(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            faults.FaultPlan.parse("bus.delay:p=0.5,n=3")

    def test_set_plan_rejects_bad_plan(self):
        with pytest.raises(ValueError):
            faults.set_plan("bus.nope")
        assert not faults.enabled()


# --- Rule selection ----------------------------------------------------

class TestRuleSelection:
    def test_nth_fires_exactly_once(self):
        plan = faults.FaultPlan.parse("bus.drop:n=3,op=push")
        hits = [plan.fire("bus", op="push") for _ in range(10)]
        assert [h is not None for h in hits] == \
            [False, False, True] + [False] * 7

    def test_every_fires_periodically(self):
        plan = faults.FaultPlan.parse("bus.drop:every=3,op=push")
        hits = [plan.fire("bus", op="push") is not None
                for _ in range(9)]
        assert hits == [False, False, True] * 3

    def test_probability_replays_under_same_seed(self):
        def draw(seed):
            plan = faults.FaultPlan.parse("bus.drop:p=0.5,op=push",
                                          seed=seed)
            return [plan.fire("bus", op="push") is not None
                    for _ in range(64)]

        assert draw(7) == draw(7)
        assert draw(7) != draw(8)
        assert any(draw(7)) and not all(draw(7))

    def test_match_filters(self):
        plan = faults.FaultPlan.parse("bus.drop:op=push,kind=query")
        assert plan.fire("bus", op="push", kind="reply") is None
        assert plan.fire("bus", op="pop", kind="query") is None
        assert plan.fire("bus", op="push", kind="query") is not None
        # Unmatched calls must not advance n= counters.
        plan = faults.FaultPlan.parse("http.error:n=2,route=/a")
        assert plan.fire("http", op="GET", route="/b") is None
        assert plan.fire("http", op="GET", route="/a") is None
        assert plan.fire("http", op="GET", route="/a") is not None


# --- Zero-overhead guard ----------------------------------------------

class TestZeroOverheadWhenDisabled:
    def test_site_hook_is_none(self):
        for site in faults.SITES:
            assert faults.site_hook(site) is None

    def test_memory_bus_hot_path_unchanged(self):
        bus = MemoryBus()
        assert bus._fault is None
        before = _injections_total()
        for i in range(50):
            bus.push("q", i)
        assert bus.pop_all("q", timeout=0.0) == list(range(50))
        bus.set("k", {"v": 1})
        assert bus.get("k") == {"v": 1}
        assert _injections_total() == before

    def test_http_server_hot_path_unchanged(self):
        server = JsonHttpServer(
            [("GET", "/ping", lambda p, b, c: (200, {"ok": True}))],
            host="127.0.0.1", name="t-faults-off").start()
        try:
            assert server._fault is None
            before = _injections_total()
            r = requests.get(
                f"http://127.0.0.1:{server.port}/ping", timeout=5)
            assert r.status_code == 200 and r.json() == {"ok": True}
            assert _injections_total() == before
        finally:
            server.stop()

    def test_armed_empty_plan_fires_nothing(self):
        faults.set_plan("")
        assert faults.enabled()
        bus = MemoryBus()
        assert bus._fault is not None
        before = _injections_total()
        bus.push("q", 1)
        assert bus.pop("q") == 1
        assert _injections_total() == before


# --- set_plan re-arming ------------------------------------------------

def test_set_plan_rearms_live_sites():
    faults.set_plan("")  # armed, quiet: sites get hooks
    bus = MemoryBus()
    bus.push("q", 1)
    assert bus.pop("q") == 1
    faults.set_plan("bus.drop:op=push")  # injure mid-flight
    bus.push("q", 2)
    assert bus.pop("q", timeout=0.0) is None
    faults.set_plan(None)  # disarm: same hook object goes quiet
    bus.push("q", 3)
    assert bus.pop("q") == 3


# --- Memory bus sites --------------------------------------------------

class TestMemoryBusInjection:
    def test_drop_loses_push_only(self):
        faults.set_plan("bus.drop:op=push")
        bus = MemoryBus()
        bus.push("q", 1)
        assert bus.pop("q", timeout=0.0) is None
        # Non-push ops ignore a drop verdict entirely.
        faults.set_plan("bus.drop")
        bus._queues.clear()
        bus.push("q2", 1)  # dropped (matches any op)
        faults.set_plan("bus.drop:op=pop")
        bus.push("q2", 2)
        assert bus.pop("q2") == 2

    def test_drop_push_many(self):
        faults.set_plan("bus.drop:op=push_many,kind=query")
        bus = MemoryBus()
        bus.push_many([("q:w1", {"a": 1}), ("q:w2", {"a": 2})])
        assert bus.pop("q:w1", timeout=0.0) is None
        assert bus.pop("q:w2", timeout=0.0) is None
        # reply-kind frames unaffected
        bus.push_many([("r:b1", {"a": 3})])
        assert bus.pop("r:b1") == {"a": 3}

    def test_delay_sleeps(self):
        faults.set_plan("bus.delay:ms=60,op=push")
        bus = MemoryBus()
        t0 = time.monotonic()
        bus.push("q", 1)
        assert time.monotonic() - t0 >= 0.05
        assert bus.pop("q") == 1  # delayed, not lost

    def test_disconnect_raises(self):
        faults.set_plan("bus.disconnect:n=1")
        bus = MemoryBus()
        with pytest.raises(ConnectionError, match="injected"):
            bus.push("q", 1)
        bus.push("q", 2)  # n=1 spent; next op sails through
        assert bus.pop("q") == 2

    def test_injections_are_counted(self):
        faults.set_plan("bus.drop:op=push")
        bus = MemoryBus()
        c_before = _injections_total()
        for i in range(3):
            bus.push("q", i)
        c = registry().find(COUNTER)
        assert c is not None
        assert c.value(site="bus", kind="drop") >= 3
        assert _injections_total() - c_before == 3


# --- HTTP site ---------------------------------------------------------

class TestHttpInjection:
    def _server(self, name):
        return JsonHttpServer(
            [("GET", "/ping", lambda p, b, c: (200, {"ok": True})),
             ("GET", "/boom", lambda p, b, c: (200, {"ok": True}))],
            host="127.0.0.1", name=name).start()

    def test_error_replies_before_dispatch(self):
        faults.set_plan("http.error:n=1,code=503")
        server = self._server("t-faults-err")
        try:
            url = f"http://127.0.0.1:{server.port}/ping"
            r1 = requests.get(url, timeout=5)
            assert r1.status_code == 503
            assert "injected" in r1.json()["error"]
            assert requests.get(url, timeout=5).status_code == 200
        finally:
            server.stop()

    def test_route_filter(self):
        faults.set_plan("http.error:route=/boom,code=500")
        server = self._server("t-faults-route")
        try:
            base = f"http://127.0.0.1:{server.port}"
            assert requests.get(base + "/ping",
                                timeout=5).status_code == 200
            assert requests.get(base + "/boom",
                                timeout=5).status_code == 500
        finally:
            server.stop()

    def test_timeout_stalls_then_serves(self):
        faults.set_plan("http.timeout:ms=80,n=1")
        server = self._server("t-faults-stall")
        try:
            t0 = time.monotonic()
            r = requests.get(f"http://127.0.0.1:{server.port}/ping",
                             timeout=5)
            assert time.monotonic() - t0 >= 0.06
            assert r.status_code == 200
        finally:
            server.stop()


# --- TCP bus client: injection + reconnection --------------------------

class _FrameEatingServer:
    """Accepts connections, reads ONE full frame, then closes the
    connection without replying — the worst-case broker death: the
    client's frame was fully SENT but no response will ever come."""

    def __init__(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self.connections = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        hdr = struct.Struct(">I")
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            self.connections += 1
            try:
                raw = b""
                while len(raw) < hdr.size:
                    chunk = conn.recv(hdr.size - len(raw))
                    if not chunk:
                        break
                    raw += chunk
                if len(raw) == hdr.size:
                    want = hdr.unpack(raw)[0]
                    got = 0
                    while got < want:
                        chunk = conn.recv(min(65536, want - got))
                        if not chunk:
                            break
                        got += len(chunk)
            finally:
                conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class TestTcpReconnect:
    def test_injected_disconnect_drops_socket(self):
        server = BusServer().start()
        try:
            faults.set_plan("bus.disconnect:n=1,op=push")
            client = BusClient(server.host, server.port)
            assert client.ping()  # op filter: ping unaffected
            with pytest.raises(ConnectionError, match="injected"):
                client.push("q", 1)
            # The cached socket was dropped; the next op reconnects.
            client.push("q", 2)
            assert client.pop("q") == 2
            client.close()
        finally:
            server.stop()

    def test_broker_restart_heals_idempotent_ops(self):
        server = BusServer().start()
        host, port = server.host, server.port
        client = BusClient(host, port, retry_base_s=0.02,
                           retry_total_s=10.0)
        client.set("k", {"v": 1})
        server.stop()
        # Restart on the SAME port (allow_reuse_address) — the new
        # broker has fresh (empty) state, like a real process restart.
        server2 = BusServer(host=host, port=port).start()
        try:
            # get retries through the stale socket + any races and
            # completes against the new broker (state forgotten).
            assert client.get("k") is None
            client.set("k", {"v": 2})
            assert client.get("k") == {"v": 2}
            client.close()
        finally:
            server2.stop()

    def test_sent_non_idempotent_op_is_never_replayed(self):
        eater = _FrameEatingServer()
        try:
            client = BusClient("127.0.0.1", eater.port, timeout=5.0,
                               retry_base_s=0.02, retry_total_s=5.0)
            with pytest.raises((ConnectionError, OSError)):
                client.push("q", 1)
            # The frame was fully sent when the connection died: a push
            # must NOT be resent (the broker may have executed it) —
            # exactly one connection means zero replays.
            assert eater.connections == 1
            client.close()
        finally:
            eater.stop()

    def test_sent_idempotent_op_retries_until_budget(self):
        eater = _FrameEatingServer()
        try:
            client = BusClient("127.0.0.1", eater.port, timeout=5.0,
                               retry_base_s=0.02, retry_total_s=0.4)
            t0 = time.monotonic()
            with pytest.raises((ConnectionError, OSError)):
                client.get("k")
            elapsed = time.monotonic() - t0
            # Idempotent read: retried across reconnects until the
            # budget lapsed (>= immediate retry + backed-off attempts).
            assert eater.connections >= 2
            assert elapsed < 5.0  # bounded by the budget, not hung
            client.close()
        finally:
            eater.stop()

    def test_zero_budget_is_legacy_single_resend(self):
        eater = _FrameEatingServer()
        try:
            client = BusClient("127.0.0.1", eater.port, timeout=5.0,
                               retry_total_s=0.0)
            with pytest.raises((ConnectionError, OSError)):
                client.get("k")
            # One immediate reconnect (stale-socket legacy behavior),
            # then fail: exactly two connections.
            assert eater.connections <= 2
            client.close()
        finally:
            eater.stop()

    def test_reconnects_are_counted(self):
        eater = _FrameEatingServer()
        try:
            client = BusClient("127.0.0.1", eater.port, timeout=5.0,
                               retry_base_s=0.02, retry_total_s=0.3)
            c = registry().find("rafiki_tpu_bus_reconnects_total")
            before = c.value() if c is not None else 0.0
            with pytest.raises((ConnectionError, OSError)):
                client.get("k")
            c = registry().find("rafiki_tpu_bus_reconnects_total")
            assert c is not None and c.value() > before
            client.close()
        finally:
            eater.stop()


# --- NodeConfig integration -------------------------------------------

class TestNodeConfigFaultKnobs:
    def test_validate_rejects_bad_plan(self):
        from rafiki_tpu.config import NodeConfig

        with pytest.raises(ValueError):
            NodeConfig(fault_plan="bus.explode").validate()
        NodeConfig(fault_plan="bus.delay:ms=5").validate()

    def test_apply_env_roundtrip(self, monkeypatch, tmp_path):
        from rafiki_tpu.config import NodeConfig

        # setenv (not delenv) so monkeypatch restores the pre-test
        # state even though apply_env() mutates os.environ directly.
        for var in (faults.PLAN_ENV, faults.SEED_ENV,
                    "RAFIKI_TPU_BUS_RETRY_BASE_S",
                    "RAFIKI_TPU_BUS_RETRY_TOTAL_S"):
            monkeypatch.setenv(var, "unset-sentinel")
        cfg = NodeConfig(workdir=str(tmp_path),
                         fault_plan="worker.crash:n=2", fault_seed=9,
                         bus_retry_base_s=0.1, bus_retry_total_s=3.0)
        cfg.validate()
        cfg.apply_env()
        import os

        assert os.environ[faults.PLAN_ENV] == "worker.crash:n=2"
        assert os.environ[faults.SEED_ENV] == "9"
        assert os.environ["RAFIKI_TPU_BUS_RETRY_BASE_S"] == "0.1"
        assert os.environ["RAFIKI_TPU_BUS_RETRY_TOTAL_S"] == "3.0"
        # The plane arms from the env at the next construction.
        faults.reset()
        assert faults.enabled()
        # An empty plan pops the env (absent = disabled).
        NodeConfig(workdir=str(tmp_path)).apply_env()
        assert faults.PLAN_ENV not in os.environ
        faults.reset()
        assert not faults.enabled()
