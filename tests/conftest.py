"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the same Mesh/NamedSharding code paths
XLA uses on a real slice). Must set env before the first jax import.
"""

import os

# Force CPU even though the session presets JAX_PLATFORMS=axon (TPU): the
# sharding tests need 8 virtual devices, and pytest must not hold the chip.
# The axon sitecustomize imports jax at interpreter startup, so the env var
# is already latched into jax.config — override via config, not environ.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# BOTH pins are required. The config update covers the already-imported
# jax (the axon sitecustomize latched JAX_PLATFORMS=axon into jax.config
# at interpreter startup). The env var covers jaxenv.ensure_platform,
# which honors an explicit JAX_PLATFORMS=cpu but otherwise PROBES the
# accelerator — with a live tunnel, a platform test constructing a
# ChipAllocator before any other backend touch would resolve the one
# real chip and see a 1-chip "slice" instead of the 8-device CPU mesh
# (exactly how rounds 1-3 masked this: the dead tunnel degraded the
# probe to CPU and the tests passed by accident).
os.environ["JAX_PLATFORMS"] = "cpu"
assert not jax._src.xla_bridge._backends, \
    "jax backends initialized before conftest could force CPU"

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from rafiki_tpu.datasets import make_synthetic_image_dataset  # noqa: E402


@pytest.fixture(scope="session")
def synth_image_data(tmp_path_factory):
    out = tmp_path_factory.mktemp("data")
    return make_synthetic_image_dataset(str(out), n_train=256, n_val=64,
                                        image_shape=(12, 12, 1), n_classes=4)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
