"""Tabular task support (SURVEY.md §2 task types TABULAR_*)."""

import numpy as np
import pytest

from rafiki_tpu.constants import TaskType
from rafiki_tpu.datasets import make_synthetic_tabular_dataset
from rafiki_tpu.model import load_tabular_dataset, test_model_class
from rafiki_tpu.models import JaxTabMlpClf, JaxTabMlpReg

KNOBS = {"hidden": 32, "depth": 2, "learning_rate": 5e-3,
         "batch_size": 64, "max_epochs": 15}


def test_tabular_csv_roundtrip(tmp_path):
    from rafiki_tpu.model import write_tabular_dataset

    x = np.random.default_rng(0).normal(size=(10, 3)).astype(np.float32)
    y = np.arange(10) % 4
    p = write_tabular_dataset(x, y, str(tmp_path / "t.csv"),
                              feature_names=["a", "b", "c"])
    ds = load_tabular_dataset(p)
    assert ds.size == 10 and ds.n_classes == 4
    assert ds.feature_names == ["a", "b", "c"]
    np.testing.assert_allclose(ds.features, x, rtol=1e-6)
    np.testing.assert_array_equal(ds.targets, y)


def test_tabular_regression_target_detection(tmp_path):
    tr, va = make_synthetic_tabular_dataset(str(tmp_path), n_classes=0)
    ds = load_tabular_dataset(tr)
    assert ds.n_classes is None
    assert ds.targets.dtype == np.float32


def test_tab_classifier_end_to_end(tmp_path):
    tr, va = make_synthetic_tabular_dataset(
        str(tmp_path), n_train=512, n_val=128, n_features=8, n_classes=4)
    ds = load_tabular_dataset(va)
    queries = [ds.features[i] for i in range(3)]
    result = test_model_class(
        JaxTabMlpClf, TaskType.TABULAR_CLASSIFICATION, tr, va,
        test_queries=queries, knobs=KNOBS)
    assert result.score > 0.6  # 4-class linear signal; chance 0.25
    assert len(result.predictions) == 3
    assert all(abs(sum(p) - 1.0) < 1e-3 for p in result.predictions)


def test_tab_regressor_end_to_end(tmp_path):
    tr, va = make_synthetic_tabular_dataset(
        str(tmp_path), n_train=512, n_val=128, n_features=8, n_classes=0)
    ds = load_tabular_dataset(va)
    queries = [ds.features[i] for i in range(3)]
    result = test_model_class(
        JaxTabMlpReg, TaskType.TABULAR_REGRESSION, tr, va,
        test_queries=queries, knobs=KNOBS)
    assert result.score > 0.7  # R^2 on a linear target
    assert all(isinstance(p, float) for p in result.predictions)


def test_tab_params_roundtrip(tmp_path):
    tr, va = make_synthetic_tabular_dataset(
        str(tmp_path), n_train=256, n_val=64, n_classes=3)
    m = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(KNOBS))
    m.train(tr)
    score = m.evaluate(va)
    params = m.dump_parameters()
    assert all(isinstance(v, np.ndarray) for v in params.values())

    m2 = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(KNOBS))
    m2.load_parameters(params)
    assert abs(m2.evaluate(va) - score) < 1e-6


def test_classifier_rejects_regression_dataset(tmp_path):
    tr, _ = make_synthetic_tabular_dataset(str(tmp_path), n_classes=0)
    m = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(KNOBS))
    with pytest.raises(ValueError, match="regression-target"):
        m.train(tr)


def test_checkpoint_resume_step_identical(tmp_path):
    """Custom-loop models honor the same checkpoint-resume contract as
    JaxModel (model/loop_ckpt.py): a run checkpointed at epoch 5 and
    resumed to 10 — on one schedule shape — lands on EXACTLY the params
    an uninterrupted 6-epoch run produces (ASHA rung-resume semantics,
    review finding r4)."""
    tr, _ = make_synthetic_tabular_dataset(
        str(tmp_path), n_train=256, n_val=64, n_features=8, n_classes=4)
    knobs = dict(KNOBS)
    ck = str(tmp_path / "ck")

    leg1 = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(
        dict(knobs, max_epochs=5)))
    leg1.train(tr, checkpoint_dir=ck, checkpoint_final_epoch=True,
               schedule_total_epochs=10)
    leg2 = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(
        dict(knobs, max_epochs=10)))
    leg2.train(tr, checkpoint_dir=ck, checkpoint_final_epoch=True,
               schedule_total_epochs=10)

    ref = JaxTabMlpClf(**JaxTabMlpClf.validate_knobs(
        dict(knobs, max_epochs=10)))
    ref.train(tr, schedule_total_epochs=10)

    import jax

    resumed = jax.tree.leaves(leg2.dump_parameters())
    wanted = jax.tree.leaves(ref.dump_parameters())
    assert len(resumed) == len(wanted)
    for a, b in zip(resumed, wanted):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for m in (leg1, leg2, ref):
        m.destroy()
