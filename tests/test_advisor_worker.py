"""AdvisorWorker RPC: one shared search state over the bus."""

import threading

import pytest

from rafiki_tpu.advisor import make_advisor
from rafiki_tpu.advisor.worker import AdvisorWorker, RemoteAdvisor
from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.model.knobs import FloatKnob, IntegerKnob


def _knob_config():
    return {"lr": FloatKnob(1e-4, 1e-1, is_exp=True), "units": IntegerKnob(8, 64)}


def test_remote_propose_feedback_best():
    bus = MemoryBus()
    advisor = make_advisor(_knob_config(), seed=0)
    worker = AdvisorWorker(advisor, bus, "sub1").start()
    try:
        remote = RemoteAdvisor(bus, "sub1", timeout=10.0)
        p1 = remote.propose()
        p2 = remote.propose()
        assert p1.trial_no == 1 and p2.trial_no == 2
        assert 1e-4 <= p1.knobs["lr"] <= 1e-1
        remote.feedback(p1, 0.7)
        remote.feedback(p2, 0.9)
        # feedback is async; poll briefly for it to land
        import time
        for _ in range(50):
            if advisor.n_trials == 2:
                break
            time.sleep(0.05)
        assert advisor.n_trials == 2
        best = remote.best()
        assert best is not None and best[1] == 0.9
        assert best[0] == p2.knobs
    finally:
        worker.stop()


def test_remote_many_workers_share_search():
    bus = MemoryBus()
    advisor = make_advisor(_knob_config(), seed=0)
    worker = AdvisorWorker(advisor, bus, "sub2").start()
    try:
        seen = []
        lock = threading.Lock()

        def client():
            remote = RemoteAdvisor(bus, "sub2", timeout=10.0)
            for _ in range(5):
                p = remote.propose()
                with lock:
                    seen.append(p.trial_no)
                remote.feedback(p, 0.5)

        threads = [threading.Thread(target=client) for _ in range(3)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        # trial numbers are globally unique across workers
        assert sorted(seen) == list(range(1, 16))
    finally:
        worker.stop()


def test_remote_error_propagates():
    bus = MemoryBus()

    class Boom:
        def propose(self):
            raise RuntimeError("nope")

    worker = AdvisorWorker(Boom(), bus, "sub3").start()
    try:
        remote = RemoteAdvisor(bus, "sub3", timeout=10.0)
        import pytest
        with pytest.raises(RuntimeError, match="nope"):
            remote.propose()
    finally:
        worker.stop()


def test_bus_advisor_with_prefetch_wrapper():
    """The bus-hosted advisor composes with PrefetchAdvisor (the
    platform wires it by default): proposals arrive in propose-call
    order, feedback flows through, and stop() flushes the dangling
    prefetched proposal so its budget slot is refunded."""
    from rafiki_tpu.advisor import PrefetchAdvisor, RandomAdvisor

    bus = MemoryBus()
    inner = RandomAdvisor({"width": IntegerKnob(8, 64)}, seed=0,
                          total_trials=10)
    worker = AdvisorWorker(PrefetchAdvisor(inner), bus, "sub-pf").start()
    try:
        remote = RemoteAdvisor(bus, "sub-pf", timeout=10)
        p1 = remote.propose()
        p2 = remote.propose()
        assert p2.trial_no == p1.trial_no + 1
        remote.feedback(p1, 0.5)
        remote.feedback(p2, 0.7)
        import time

        deadline = time.time() + 5
        while inner.best() is None and time.time() < deadline:
            time.sleep(0.05)  # feedback ops are fire-and-forget pushes
        best = inner.best()
        assert best is not None, "feedback never reached the advisor"
        assert best[1] == 0.7
    finally:
        worker.stop()
    # stop() closed the wrapper: a dangling prefetched proposal was
    # forgotten, so the advisor's pending-state stays balanced.
    with pytest.raises(RuntimeError):
        worker.advisor.propose()
