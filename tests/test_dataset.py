import os

import numpy as np
import pytest

from rafiki_tpu.model import (load_corpus_dataset, load_image_dataset,
                              write_corpus_dataset, write_image_dataset_npz,
                              write_image_files_dataset)
from rafiki_tpu.model import dataset as mod_dataset


@pytest.fixture(autouse=True)
def _fresh_dataset_cache():
    mod_dataset.clear_dataset_cache()
    yield
    mod_dataset.clear_dataset_cache()


def test_npz_roundtrip(tmp_path):
    imgs = np.random.default_rng(0).integers(0, 255, (10, 8, 8, 3), dtype=np.uint8)
    labels = np.arange(10) % 3
    p = write_image_dataset_npz(imgs, labels, str(tmp_path / "d.npz"), 3)
    ds = load_image_dataset(p)
    assert ds.size == 10 and ds.n_classes == 3
    assert ds.image_shape == (8, 8, 3)
    np.testing.assert_array_equal(ds.images, imgs)
    np.testing.assert_array_equal(ds.labels, labels)
    assert ds.normalized().max() <= 1.0


def test_zip_of_pngs_roundtrip(tmp_path):
    imgs = np.random.default_rng(1).integers(0, 255, (6, 8, 8, 1), dtype=np.uint8)
    labels = np.array([0, 1, 2, 0, 1, 2])
    p = write_image_files_dataset(imgs, labels, str(tmp_path / "d.zip"))
    ds = load_image_dataset(p)
    assert ds.size == 6 and ds.n_classes == 3
    np.testing.assert_array_equal(ds.images, imgs)
    np.testing.assert_array_equal(ds.labels, labels)


def test_batching():
    imgs = np.zeros((10, 4, 4, 1), np.uint8)
    labels = np.arange(10)
    from rafiki_tpu.model.dataset import ImageDataset
    ds = ImageDataset(imgs, labels, 10)
    batches = list(ds.batches(4))
    assert [b[0].shape[0] for b in batches] == [4, 4, 2]
    batches = list(ds.batches(4, drop_remainder=True))
    assert [b[0].shape[0] for b in batches] == [4, 4]
    shuffled = list(ds.batches(10, shuffle=True, seed=1))[0][1]
    assert not np.array_equal(shuffled, labels)
    assert set(shuffled) == set(labels)


def _write(tmp_path, name, seed, n=10):
    imgs = np.random.default_rng(seed).integers(
        0, 255, (n, 8, 8, 1), dtype=np.uint8)
    return write_image_dataset_npz(imgs, np.arange(n) % 2,
                                   str(tmp_path / name), 2)


def test_dataset_cache_hit_returns_same_object(tmp_path):
    p = _write(tmp_path, "a.npz", seed=0)
    ds1 = load_image_dataset(p)
    ds2 = load_image_dataset(p)
    assert ds2 is ds1  # no re-parse: the resident object is served


def test_dataset_cache_invalidates_on_rewrite(tmp_path):
    """A rewritten file (new mtime_ns/size fingerprint) is a different
    dataset — never a stale hit."""
    p = _write(tmp_path, "a.npz", seed=0)
    ds1 = load_image_dataset(p)
    _write(tmp_path, "a.npz", seed=1)
    st = os.stat(p)  # force a distinct mtime even on coarse clocks
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    ds2 = load_image_dataset(p)
    assert ds2 is not ds1
    assert not np.array_equal(ds2.images, ds1.images)


def test_dataset_cache_byte_budget_lru(tmp_path, monkeypatch):
    pa = _write(tmp_path, "a.npz", seed=0)
    pb = _write(tmp_path, "b.npz", seed=1)
    pc = _write(tmp_path, "c.npz", seed=2)
    one = mod_dataset._dataset_nbytes(load_image_dataset(pa))
    mod_dataset.clear_dataset_cache()
    # room for exactly two datasets
    monkeypatch.setenv(mod_dataset.DATASET_CACHE_ENV,
                       str(2 * one + 16))
    a = load_image_dataset(pa)
    b = load_image_dataset(pb)
    load_image_dataset(pc)        # evicts a (LRU)
    assert load_image_dataset(pb) is b   # still resident
    assert load_image_dataset(pa) is not a  # was evicted, re-parsed


def test_eviction_prefers_other_owners_entries_first():
    """Cross-sub-job eviction preference (carried r9 item): under
    budget pressure the residency caches evict OTHER jobs' entries
    before the inserting job's own — counter-pinned on the evict
    counter the caches share."""
    from rafiki_tpu.model.dataset import ByteBudgetLRU, stage_owner
    from rafiki_tpu.observe import metrics as obs_metrics

    lru = ByteBudgetLRU("stage")
    budget = 100
    c = obs_metrics.registry().counter(
        "rafiki_tpu_trial_stage_cache_total",
        "Device staging cache events (event=hit|miss|evict)")
    before = c.value(event="evict")
    with stage_owner("jobA"):
        lru.put("a1", "A1", 40, budget)
    with stage_owner("jobB"):
        lru.put("b1", "B1", 40, budget)
    with stage_owner("jobA"):
        # Over budget by one entry: plain LRU would evict a1 (the
        # oldest); the preference evicts jobB's b1 instead, keeping
        # jobA's still-hot dataset resident between ITS trials.
        lru.put("a2", "A2", 40, budget)
    assert lru.get("b1") is None
    assert lru.get("a1") == "A1" and lru.get("a2") == "A2"
    assert c.value(event="evict") == before + 1
    # Same-owner pressure falls back to plain LRU order (a2 was
    # touched by the get above, so a1 is now the LRU victim).
    with stage_owner("jobA"):
        lru.put("a3", "A3", 40, budget)
    assert lru.get("a1") is None
    assert lru.get("a2") == "A2" and lru.get("a3") == "A3"
    assert c.value(event="evict") == before + 2
    # Unowned inserts (direct SDK callers, no TrialRunner context)
    # treat owned entries as foreign too.
    lru.put("u1", "U1", 40, budget)
    assert lru.get("u1") == "U1"
    assert lru.get("a2") is None          # oldest foreign entry
    assert lru.get("a3") == "A3"


def test_dataset_cache_disabled_and_oversized(tmp_path, monkeypatch):
    p = _write(tmp_path, "a.npz", seed=0)
    monkeypatch.setenv(mod_dataset.DATASET_CACHE_ENV, "0")
    assert load_image_dataset(p) is not load_image_dataset(p)
    # a dataset larger than the whole budget is served uncached
    monkeypatch.setenv(mod_dataset.DATASET_CACHE_ENV, "16")
    assert load_image_dataset(p) is not load_image_dataset(p)


def _write_tab(tmp_path, name="t.csv", seed=0, n=16):
    rng = np.random.default_rng(seed)
    return mod_dataset.write_tabular_dataset(
        rng.normal(size=(n, 3)).astype(np.float32),
        rng.integers(0, 2, n), str(tmp_path / name))


def test_tabular_cache_hit_and_counters(tmp_path):
    """r12 carried item: the tabular loader rides the host dataset
    cache — a repeat load is a hit (same resident read-only object),
    counted in the trial dataset-cache family."""
    from rafiki_tpu.model.dataset import load_tabular_dataset
    from rafiki_tpu.observe import phases

    p = _write_tab(tmp_path)
    before = phases.cache_counts("dataset")
    ds1 = load_tabular_dataset(p)
    ds2 = load_tabular_dataset(p)
    assert ds2 is ds1
    after = phases.cache_counts("dataset")
    assert after.get("miss", 0) - before.get("miss", 0) == 1
    assert after.get("hit", 0) - before.get("hit", 0) == 1
    # Shared object = read-only: in-place mutation must raise at ITS
    # call site, not poison later trials.
    with pytest.raises(ValueError):
        ds1.features[0, 0] = 99.0


def test_tabular_cache_keyed_by_label_col_and_rewrite(tmp_path):
    from rafiki_tpu.model.dataset import load_tabular_dataset

    rng = np.random.default_rng(3)
    p = mod_dataset.write_tabular_dataset(
        rng.normal(size=(8, 2)).astype(np.float32),
        rng.integers(0, 2, 8), str(tmp_path / "t.csv"),
        feature_names=["f0", "f1"], target_name="y")
    ds_last = load_tabular_dataset(p)
    ds_f0 = load_tabular_dataset(p, label_col="f0")
    # Different target column = a different dataset, never a shared hit.
    assert ds_f0 is not ds_last
    assert ds_f0.target_name == "f0"
    assert load_tabular_dataset(p) is ds_last
    # A rewritten file invalidates (fingerprint changes).
    _write_tab(tmp_path, "t.csv", seed=9, n=8)
    st = os.stat(p)
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    assert load_tabular_dataset(p) is not ds_last


def test_corpus_roundtrip(tmp_path):
    sents = [["the", "cat", "sat"], ["dogs", "run"]]
    tags = [["DET", "NOUN", "VERB"], ["NOUN", "VERB"]]
    p = write_corpus_dataset(sents, tags, str(tmp_path / "c.zip"))
    ds = load_corpus_dataset(p)
    assert ds.size == 2
    assert ds.sentences[0] == ["the", "cat", "sat"]
    assert [ds.tag_names[t] for t in ds.tags[1]] == ["NOUN", "VERB"]


def test_corpus_splits_share_tag_id_space(tmp_path):
    """A tag absent from the tiny val split must not shift val's tag ids."""
    from rafiki_tpu.datasets import make_synthetic_corpus_dataset

    tr, va = make_synthetic_corpus_dataset(
        str(tmp_path), n_train=64, n_val=2, n_tags=12, max_len=4, seed=3)
    assert (load_corpus_dataset(tr).tag_names
            == load_corpus_dataset(va).tag_names)


def test_bundled_english_pos_corpus(tmp_path):
    """The committed hand-tagged English corpus stays well-formed: every
    tag in the Universal tagset, both splits share one tag-id space,
    and the size matches its README (679 sentences / 6,599 tokens —
    round 5 grew it from the original 329/2,996)."""
    from rafiki_tpu.datasets import prepare_bundled_pos_corpus

    tr, va = prepare_bundled_pos_corpus(str(tmp_path))
    dtr, dva = load_corpus_dataset(tr), load_corpus_dataset(va)
    assert dtr.tag_names == dva.tag_names
    universal = {"NOUN", "VERB", "ADJ", "ADV", "PRON", "DET", "ADP",
                 "NUM", "CONJ", "PRT", "PUNCT", "X"}
    assert set(dtr.tag_names) <= universal
    n_sents = dtr.size + dva.size
    n_tokens = sum(len(s) for s in dtr.sentences + dva.sentences)
    assert n_sents == 679 and n_tokens == 6599, (n_sents, n_tokens)
    # Real language, not synthetic ids: a few high-frequency English
    # words must be present and consistently tagged.
    from collections import Counter
    tag_of = Counter()
    for s, ts in zip(dtr.sentences, dtr.tags):
        for w, t in zip(s, ts):
            if w.lower() == "the":
                tag_of[dtr.tag_names[t]] += 1
    assert set(tag_of) == {"DET"}
