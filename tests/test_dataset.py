import numpy as np

from rafiki_tpu.model import (load_corpus_dataset, load_image_dataset,
                              write_corpus_dataset, write_image_dataset_npz,
                              write_image_files_dataset)


def test_npz_roundtrip(tmp_path):
    imgs = np.random.default_rng(0).integers(0, 255, (10, 8, 8, 3), dtype=np.uint8)
    labels = np.arange(10) % 3
    p = write_image_dataset_npz(imgs, labels, str(tmp_path / "d.npz"), 3)
    ds = load_image_dataset(p)
    assert ds.size == 10 and ds.n_classes == 3
    assert ds.image_shape == (8, 8, 3)
    np.testing.assert_array_equal(ds.images, imgs)
    np.testing.assert_array_equal(ds.labels, labels)
    assert ds.normalized().max() <= 1.0


def test_zip_of_pngs_roundtrip(tmp_path):
    imgs = np.random.default_rng(1).integers(0, 255, (6, 8, 8, 1), dtype=np.uint8)
    labels = np.array([0, 1, 2, 0, 1, 2])
    p = write_image_files_dataset(imgs, labels, str(tmp_path / "d.zip"))
    ds = load_image_dataset(p)
    assert ds.size == 6 and ds.n_classes == 3
    np.testing.assert_array_equal(ds.images, imgs)
    np.testing.assert_array_equal(ds.labels, labels)


def test_batching():
    imgs = np.zeros((10, 4, 4, 1), np.uint8)
    labels = np.arange(10)
    from rafiki_tpu.model.dataset import ImageDataset
    ds = ImageDataset(imgs, labels, 10)
    batches = list(ds.batches(4))
    assert [b[0].shape[0] for b in batches] == [4, 4, 2]
    batches = list(ds.batches(4, drop_remainder=True))
    assert [b[0].shape[0] for b in batches] == [4, 4]
    shuffled = list(ds.batches(10, shuffle=True, seed=1))[0][1]
    assert not np.array_equal(shuffled, labels)
    assert set(shuffled) == set(labels)


def test_corpus_roundtrip(tmp_path):
    sents = [["the", "cat", "sat"], ["dogs", "run"]]
    tags = [["DET", "NOUN", "VERB"], ["NOUN", "VERB"]]
    p = write_corpus_dataset(sents, tags, str(tmp_path / "c.zip"))
    ds = load_corpus_dataset(p)
    assert ds.size == 2
    assert ds.sentences[0] == ["the", "cat", "sat"]
    assert [ds.tag_names[t] for t in ds.tags[1]] == ["NOUN", "VERB"]


def test_corpus_splits_share_tag_id_space(tmp_path):
    """A tag absent from the tiny val split must not shift val's tag ids."""
    from rafiki_tpu.datasets import make_synthetic_corpus_dataset

    tr, va = make_synthetic_corpus_dataset(
        str(tmp_path), n_train=64, n_val=2, n_tags=12, max_len=4, seed=3)
    assert (load_corpus_dataset(tr).tag_names
            == load_corpus_dataset(va).tag_names)


def test_bundled_english_pos_corpus(tmp_path):
    """The committed hand-tagged English corpus stays well-formed: every
    tag in the Universal tagset, both splits share one tag-id space,
    and the size matches its README (679 sentences / 6,599 tokens —
    round 5 grew it from the original 329/2,996)."""
    from rafiki_tpu.datasets import prepare_bundled_pos_corpus

    tr, va = prepare_bundled_pos_corpus(str(tmp_path))
    dtr, dva = load_corpus_dataset(tr), load_corpus_dataset(va)
    assert dtr.tag_names == dva.tag_names
    universal = {"NOUN", "VERB", "ADJ", "ADV", "PRON", "DET", "ADP",
                 "NUM", "CONJ", "PRT", "PUNCT", "X"}
    assert set(dtr.tag_names) <= universal
    n_sents = dtr.size + dva.size
    n_tokens = sum(len(s) for s in dtr.sentences + dva.sentences)
    assert n_sents == 679 and n_tokens == 6599, (n_sents, n_tokens)
    # Real language, not synthetic ids: a few high-frequency English
    # words must be present and consistently tagged.
    from collections import Counter
    tag_of = Counter()
    for s, ts in zip(dtr.sentences, dtr.tags):
        for w, t in zip(s, ts):
            if w.lower() == "the":
                tag_of[dtr.tag_names[t]] += 1
    assert set(tag_of) == {"DET"}
