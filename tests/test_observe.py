"""Observability subsystem (SURVEY.md §5): tracing + utilization metering."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.observe import (MfuMeter, device_peak_flops, flops_of_lowered,
                                trace_session, trial_trace_dir)
from rafiki_tpu.observe.profiling import PEAK_FLOPS_ENV, TRACE_DIR_ENV


def test_trace_dir_off_by_default(monkeypatch):
    monkeypatch.delenv(TRACE_DIR_ENV, raising=False)
    assert trial_trace_dir("t123") is None


def test_trace_dir_per_trial(monkeypatch, tmp_path):
    monkeypatch.setenv(TRACE_DIR_ENV, str(tmp_path))
    assert trial_trace_dir("t123") == str(tmp_path / "t123")


def test_trace_session_noop_without_dir():
    with trace_session(None):
        pass  # must not start the profiler


def test_trace_session_concurrent_skips_not_raises(tmp_path):
    """Only one profiler trace can be active; an overlapping session must
    silently skip (not fail the trial)."""
    with trace_session(str(tmp_path / "a")):
        with trace_session(str(tmp_path / "b")):
            jax.block_until_ready(jnp.ones((4, 4)) @ jnp.ones((4, 4)))
    assert not os.path.isdir(str(tmp_path / "b"))


def test_trace_session_writes_trace(tmp_path):
    d = str(tmp_path / "trace")
    with trace_session(d):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    files = [os.path.join(root, f) for root, _, fs in os.walk(d) for f in fs]
    assert files, "profiler produced no trace files"


def test_flops_of_lowered_matmul():
    @jax.jit
    def f(a, b):
        return a @ b

    a = jnp.ones((64, 128), jnp.float32)
    b = jnp.ones((128, 32), jnp.float32)
    flops = flops_of_lowered(f.lower(a, b))
    if flops is None:
        pytest.skip("backend provides no cost analysis")
    # 2*M*N*K, allow backend slack
    assert flops >= 2 * 64 * 128 * 32 * 0.5


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv(PEAK_FLOPS_ENV, "1e12")
    assert device_peak_flops() == 1e12


def test_mfu_meter_math(monkeypatch):
    monkeypatch.delenv(PEAK_FLOPS_ENV, raising=False)
    m = MfuMeter(flops_per_step=1e9, n_devices=2, peak_flops_per_device=1e12)
    m.tick(10)
    m._t0 -= 1.0  # pretend 1s elapsed
    assert m.achieved_flops == pytest.approx(1e10, rel=0.3)
    assert m.mfu == pytest.approx(1e10 / 2e12, rel=0.3)


def test_mfu_meter_unknown_peak_graceful():
    m = MfuMeter(flops_per_step=None, n_devices=1,
                 peak_flops_per_device=None)
    m.tick(5)
    assert m.achieved_flops is None and m.mfu is None


def test_train_logs_chip_util(monkeypatch, synth_image_data):
    """JaxModel training reports the chip_util metric when a peak is known
    (calibrated here via the env override, since CPU peak is unknown)."""
    monkeypatch.setenv(PEAK_FLOPS_ENV, "1e12")
    from rafiki_tpu.model.logger import logger
    from rafiki_tpu.models import JaxFeedForward

    records = []
    logger.set_sink(records.append)
    try:
        train_path, _ = synth_image_data
        m = JaxFeedForward(**JaxFeedForward.validate_knobs({
            "hidden_layer_count": 1, "hidden_layer_units": 16,
            "learning_rate": 1e-3, "batch_size": 64, "max_epochs": 5}))
        m.train(train_path)
    finally:
        logger.set_sink(None)
    utils = [r["values"]["chip_util"] for r in records
             if r.get("type") == "values"
             and "chip_util" in r.get("values", {})]
    if not utils:  # cost analysis unavailable on this backend
        pytest.skip("no chip_util records (no lowered cost analysis)")
    assert all(u >= 0 for u in utils)
