"""PbtAdvisor: rounds, exploit/explore, weight lineage, integration."""

import numpy as np
import pytest

from rafiki_tpu.advisor import PbtAdvisor, make_advisor
from rafiki_tpu.model.knobs import FloatKnob, IntegerKnob

CONFIG = {
    "width": IntegerKnob(8, 64),
    "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
    "max_epochs": IntegerKnob(1, 40),
}


def test_rounds_cycle_members_with_round_budget():
    adv = PbtAdvisor(CONFIG, seed=0, population=3)
    proposals = [adv.propose() for _ in range(6)]
    # Each proposal trains one round (the budget knob's minimum).
    assert all(p.knobs["max_epochs"] == 1 for p in proposals)
    # Members cycle round-robin; same member keeps its knobs in round 2
    # (nobody scored yet, so no exploitation can occur).
    scopes = [p.meta["params_scope"] for p in proposals]
    assert scopes == ["pbt-0", "pbt-1", "pbt-2"] * 2
    assert all(p.meta["params_save_scope"] == f"pbt-{i % 3}"
               for i, p in enumerate(proposals))
    assert proposals[0].knobs["width"] == proposals[3].knobs["width"]


def test_exploit_copies_winner_and_perturbs():
    adv = PbtAdvisor(CONFIG, seed=1, population=4, quantile=0.25)
    round1 = [adv.propose() for _ in range(4)]
    # Member 2 wins, member 0 loses.
    scores = {0: 0.1, 1: 0.5, 2: 0.9, 3: 0.6}
    for m, p in enumerate(round1):
        adv.feedback(p, scores[m])
    round2 = [adv.propose() for _ in range(4)]
    loser = round2[0]
    # The loser warm-starts from the WINNER's weights but saves its own.
    assert loser.meta["params_scope"] == "pbt-2"
    assert loser.meta["params_save_scope"] == "pbt-0"
    # Its learning rate is the winner's perturbed by x1.2 or /1.2.
    lr_w = round1[2].knobs["learning_rate"]
    lr_l = loser.knobs["learning_rate"]
    assert np.isclose(lr_l, lr_w * 1.2) or np.isclose(lr_l, lr_w / 1.2)
    # Winners and mid-pack keep their own lineage.
    assert round2[2].meta["params_scope"] == "pbt-2"
    assert round2[1].meta["params_scope"] == "pbt-1"


def test_record_knobs_carry_cumulative_epochs():
    adv = PbtAdvisor(CONFIG, seed=0, population=2, epochs_per_round=3)
    p1 = adv.propose()
    assert p1.knobs["max_epochs"] == 3
    assert p1.meta["record_knobs"] == {"max_epochs": 3}
    adv.feedback(p1, 0.5)
    p2 = adv.propose()  # member 1, round 1
    adv.feedback(p2, 0.4)
    p3 = adv.propose()  # member 0, round 2 -> cumulative 6
    assert p3.meta["record_knobs"] == {"max_epochs": 6}


def test_registry_selects_pbt():
    adv = make_advisor(CONFIG, advisor_type="pbt", total_trials=4)
    assert isinstance(adv, PbtAdvisor)
    assert [adv.propose() is not None for _ in range(4)] == [True] * 4
    assert adv.propose() is None  # budget enforced


def test_pbt_weight_lineage_through_runner(tmp_path):
    """End-to-end through the TrialRunner: a losing member's next round
    receives the WINNER's weights as shared params and saves under its
    own scope."""
    from rafiki_tpu.constants import BudgetOption
    from rafiki_tpu.model.base import BaseModel
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    received = []  # (trial_no, marker-or-None)

    class FakeModel(BaseModel):
        @staticmethod
        def get_knob_config():
            return CONFIG

        def __init__(self, **knobs):
            super().__init__(**knobs)
            self._p = {}

        def train(self, path, *, shared_params=None, **kw):
            marker = (None if shared_params is None else
                      float(np.asarray(
                          shared_params["m"]).reshape(-1)[0]))
            # Save a marker equal to this model's width so lineage is
            # traceable: (received marker, marker this trial saves).
            received.append((marker, float(self.knobs["width"])))
            self._p = {"m": np.asarray(float(self.knobs["width"]))}

        def evaluate(self, path):
            return self.knobs["width"] / 64.0  # wider wins

        def predict(self, queries):
            return [0 for _ in queries]

        def dump_parameters(self):
            return dict(self._p)

        def load_parameters(self, params):
            self._p = dict(params)

    adv = PbtAdvisor(CONFIG, seed=5, population=2, quantile=0.5,
                     total_trials=6)
    runner = TrialRunner(FakeModel, adv, "tr", "va", MetaStore(":memory:"),
                         ParamStore(str(tmp_path / "p")),
                         sub_train_job_id="pbt-e2e",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 6})
    runner.run()

    # Round 1 (trials 1-2): cold starts. Later rounds warm-start, and
    # with quantile=0.5 on a 2-member population each round's loser
    # inherits the winner's weights: some member must receive a marker
    # it did not save itself (cross-member lineage via the ParamStore).
    assert received[0][0] is None and received[1][0] is None
    assert all(m is not None for m, _ in received[2:]), received
    last_saved = {}
    cross = False
    for i, (marker, saved) in enumerate(received):
        member = i % 2
        if marker is not None and member in last_saved \
                and marker != last_saved[member]:
            cross = True
        last_saved[member] = saved
    assert cross, f"weights never crossed members: {received}"


def test_pbt_through_platform(tmp_path, synth_image_data):
    """advisor_type="pbt" schedules rounds through real workers."""
    from rafiki_tpu.constants import BudgetOption, TaskType, UserType
    from rafiki_tpu.platform import LocalPlatform

    train_path, val_path = synth_image_data
    p = LocalPlatform(workdir=str(tmp_path / "plat"), supervise_interval=0)
    try:
        dev = p.admin.create_user("dev@x.c", "pw",
                                  UserType.MODEL_DEVELOPER)
        model = p.admin.create_model(
            dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION,
            "rafiki_tpu.models.feedforward:JaxFeedForward")
        job = p.admin.create_train_job(
            dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 3},
            train_path, val_path, advisor_type="pbt")
        assert p.admin.wait_until_train_job_done(job["id"], timeout=600)
        detail = p.admin.get_train_job(job["id"])
        assert detail["sub_train_jobs"][0]["n_completed"] == 3
    finally:
        p.shutdown()


def test_fixed_budget_knob_keeps_value():
    """With no tunable budget knob (FixedKnob max_epochs), rounds train
    the fixed budget and the knob is always present (review finding:
    popping it made validate_knobs raise)."""
    from rafiki_tpu.model.knobs import FixedKnob

    config = {"width": IntegerKnob(8, 64), "max_epochs": FixedKnob(5)}
    adv = PbtAdvisor(config, seed=0, population=2)
    for _ in range(4):
        p = adv.propose()
        assert p.knobs["max_epochs"] == 5
        adv.feedback(p, 0.5)


def test_oversubscribed_workers_no_double_perturb():
    """More workers than members: a member with an in-flight round is
    neither re-perturbed nor double-counted; cumulative records advance
    per issued round."""
    adv = PbtAdvisor(CONFIG, seed=0, population=2, epochs_per_round=2)
    # Simulate 4 parallel proposals before any feedback.
    ps = [adv.propose() for _ in range(4)]
    # Members cycle 0,1,0,1; no exploitation without scores; each
    # member's knobs are stable across its two in-flight rounds.
    assert ps[0].knobs["width"] == ps[2].knobs["width"]
    assert ps[1].knobs["width"] == ps[3].knobs["width"]
    # Cumulative budgets count in-flight rounds: 2, 2, 4, 4.
    assert [p.meta["record_knobs"]["max_epochs"] for p in ps] == \
        [2, 2, 4, 4]
    # After scoring, in-flight drains and exploitation can resume.
    for p, s in zip(ps, [0.1, 0.9, 0.2, 0.8]):
        adv.feedback(p, s)
    p5 = adv.propose()  # member 0, loser, nothing in flight -> exploit
    assert p5.meta["params_scope"] == "pbt-1"


def test_cumulative_record_clamps_at_knob_max():
    config = {"width": IntegerKnob(8, 64), "max_epochs": IntegerKnob(1, 3)}
    adv = PbtAdvisor(config, seed=0, population=2, epochs_per_round=1,
                     quantile=0.5)
    records = []  # member 0's records across its rounds
    for i in range(10):
        p = adv.propose()
        if i % 2 == 0:
            records.append(p.meta["record_knobs"]["max_epochs"])
        adv.feedback(p, 0.5)
    assert records == [1, 2, 3, 3, 3]  # clamped at value_max
    # Cold-start fallback mirrors the record (lost params retrain the
    # cumulative budget, keeping scores comparable).
    p = adv.propose()
    assert p.meta["cold_start_knobs"] == p.meta["record_knobs"]
