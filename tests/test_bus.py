"""Bus + Cache tests: queue semantics identical across both backends."""

import threading
import time

import numpy as np
import pytest

from rafiki_tpu.bus import BusClient, BusServer, MemoryBus, connect
from rafiki_tpu.bus.native import NativeBusServer
from rafiki_tpu.cache import Cache, decode_payload, encode_payload


@pytest.fixture(params=["memory", "tcp", "native"])
def bus(request):
    if request.param == "memory":
        yield MemoryBus()
        return
    if request.param == "native" and not NativeBusServer.available():
        pytest.skip("no C++ toolchain for the native broker")
    server_cls = NativeBusServer if request.param == "native" else BusServer
    server = server_cls().start()
    client = BusClient(server.host, server.port)
    yield client
    client.close()
    server.stop()


class TestBus:
    def test_fifo(self, bus):
        bus.push("q", 1)
        bus.push("q", {"a": [2]})
        assert bus.queue_len("q") == 2
        assert bus.pop("q") == 1
        assert bus.pop("q") == {"a": [2]}
        assert bus.pop("q") is None

    def test_pop_timeout_blocks(self, bus):
        t0 = time.monotonic()
        assert bus.pop("empty", timeout=0.2) is None
        assert time.monotonic() - t0 >= 0.15

    def test_pop_wakes_on_push(self, bus):
        got = []

        def consumer():
            got.append(bus.pop("q2", timeout=5.0))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.1)
        bus.push("q2", "x")
        t.join(timeout=5)
        assert got == ["x"]

    def test_pop_all_drains_burst(self, bus):
        for i in range(5):
            bus.push("q3", i)
        assert bus.pop_all("q3", timeout=1.0) == [0, 1, 2, 3, 4]
        assert bus.pop_all("q3", timeout=0.05) == []

    def test_push_many_multi_queue_fanout(self, bus):
        """One call scatters to several queues in order (the serving
        scatter path). Against the native broker — which predates the
        batched op — this also exercises the unknown-op fallback."""
        bus.push_many([("qa", 1), ("qb", {"x": 2}), ("qa", 3)])
        assert bus.pop("qa", timeout=1.0) == 1
        assert bus.pop("qa", timeout=1.0) == 3
        assert bus.pop("qb", timeout=1.0) == {"x": 2}
        bus.push_many([])  # no-op, must not error
        # a second call goes down whichever path was negotiated
        bus.push_many([("qc", "v")])
        assert bus.pop("qc", timeout=1.0) == "v"

    def test_pop_all_max_items(self, bus):
        for i in range(5):
            bus.push("q4", i)
        assert bus.pop_all("q4", max_items=3, timeout=1.0) == [0, 1, 2]
        assert bus.queue_len("q4") == 2

    def test_kv_and_keys(self, bus):
        bus.set("w:job1:a", {"s": 1})
        bus.set("w:job1:b", {"s": 2})
        bus.set("w:job2:c", {})
        assert bus.get("w:job1:a") == {"s": 1}
        assert bus.keys("w:job1:") == ["w:job1:a", "w:job1:b"]
        bus.delete("w:job1:a")
        assert bus.get("w:job1:a") is None
        assert bus.keys("w:job1:") == ["w:job1:b"]

    def test_ping(self, bus):
        assert bus.ping()

    def test_delete_queue(self, bus):
        bus.push("dq", 1)
        bus.delete_queue("dq")
        assert bus.queue_len("dq") == 0
        assert bus.pop("dq", timeout=0.05) is None


def test_memory_bus_reaps_empty_queues():
    """uuid-keyed one-shot queues must not accumulate (leak) after use."""
    bus = MemoryBus()
    for i in range(100):
        q = f"r:{i}"
        bus.push(q, {"x": i})
        bus.pop(q)
    assert len(bus._queues) == 0
    # timeout-path pops also reap
    for i in range(50):
        bus.pop(f"ghost:{i}", timeout=0.0)
    assert len(bus._queues) == 0


class TestTcpSpecifics:
    def test_concurrent_clients(self):
        server = BusServer().start()
        clients = [BusClient(server.host, server.port) for _ in range(4)]

        def producer(c, k):
            for i in range(25):
                c.push("load", k * 100 + i)

        threads = [threading.Thread(target=producer, args=(c, k))
                   for k, c in enumerate(clients)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        drained = clients[0].pop_all("load", timeout=1.0)
        assert len(drained) == 100
        [c.close() for c in clients]
        server.stop()

    def test_connect_uri(self):
        server = BusServer().start()
        c = connect(server.uri)
        c.push("u", 1)
        assert c.pop("u") == 1
        c.close()
        server.stop()
        assert isinstance(connect(""), MemoryBus)
        # memory:// is a process-local singleton
        assert connect("memory://") is connect("memory://")
        MemoryBus.reset_shared()

    def test_error_response_keeps_connection(self):
        server = BusServer().start()
        c = BusClient(server.host, server.port)
        with pytest.raises(RuntimeError, match="unknown op"):
            c._call({"op": "nope"})
        assert c.ping()  # connection still usable
        c.close()
        server.stop()


class TestCache:
    def test_payload_roundtrip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        enc = encode_payload({"img": arr, "k": [1, arr]})
        dec = decode_payload(enc)
        np.testing.assert_array_equal(dec["img"], arr)
        np.testing.assert_array_equal(dec["k"][1], arr)
        assert dec["k"][0] == 1

    def test_scatter_gather(self):
        cache = Cache(MemoryBus())
        cache.register_worker("job", "w0")
        cache.register_worker("job", "w1")
        assert cache.running_workers("job") == ["w0", "w1"]

        img = np.ones((4, 4, 1), np.uint8)
        qid = None
        for w in cache.running_workers("job"):
            qid = cache.send_query(w, img, query_id=qid)

        # each worker pops, predicts, replies
        for w in ["w0", "w1"]:
            items = cache.pop_queries(w, timeout=1.0)
            assert len(items) == 1
            np.testing.assert_array_equal(items[0]["query"], img)
            cache.send_prediction(items[0]["query_id"], w, [0.25, 0.75])

        preds = cache.gather_predictions(qid, n_workers=2, timeout=2.0)
        assert sorted(p["worker_id"] for p in preds) == ["w0", "w1"]
        assert preds[0]["prediction"] == [0.25, 0.75]

    def test_gather_timeout_partial(self):
        cache = Cache(MemoryBus())
        qid = cache.send_query("w0", [1, 2, 3])
        items = cache.pop_queries("w0", timeout=1.0)
        cache.send_prediction(qid, "w0", "ok")
        # asks for 3 workers but only 1 replies; returns the partial set
        t0 = time.monotonic()
        preds = cache.gather_predictions(qid, n_workers=3, timeout=0.3)
        assert len(preds) == 1
        assert time.monotonic() - t0 < 2.0

    def test_unregister(self):
        cache = Cache(MemoryBus())
        cache.register_worker("j", "w0")
        cache.unregister_worker("j", "w0")
        assert cache.running_workers("j") == []
