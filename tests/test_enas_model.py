"""JaxEnas (TfEnas parity, SURVEY.md §2/§3.5/§7 step 9) tests.

The crux of the TPU redesign is the masked supernet: hundreds of proposed
architectures must run against ONE compiled graph (SURVEY.md §7 "Hard
parts: ENAS on XLA"), and the supernet parameter tree must be
architecture-independent so ParamStore weight sharing overlays every
tensor. A tiny subclass keeps CPU runtime small.
"""

import pytest

import numpy as np

from rafiki_tpu.advisor import EnasAdvisor
from rafiki_tpu.constants import BudgetOption, ParamsType, TrialStatus
from rafiki_tpu.model import FixedKnob, load_image_dataset, test_model_class
from rafiki_tpu.model import jax_model
from rafiki_tpu.models import JaxEnas
from rafiki_tpu.store import MetaStore, ParamStore
from rafiki_tpu.worker import TrialRunner


class TinyEnas(JaxEnas):
    """Test-scale preset: 2 blocks/cell, 3 cells (incl. reductions)."""

    n_blocks = 2
    full_cells, full_channels = 3, 8
    search_cells, search_channels = 3, 8

    @classmethod
    def get_knob_config(cls):
        cfg = super().get_knob_config()
        cfg.update(batch_size=FixedKnob(32), learning_rate=FixedKnob(0.05),
                   max_epochs=FixedKnob(3))
        return cfg


def _sample_arch(seed: int):
    knob = TinyEnas.get_knob_config()["arch"]
    return knob.sample(np.random.default_rng(seed))


def _search_knobs(arch):
    return TinyEnas.validate_knobs({
        "arch": arch, "batch_size": 32, "learning_rate": 0.05,
        "max_epochs": 3, "trial_epochs": 1, "share_params": True,
        "quick_train": True, "downscale": True})


@pytest.mark.slow
def test_supernet_one_compile_many_archs(synth_image_data):
    """Two different architectures must share one compiled train step."""
    train_path, val_path = synth_image_data
    jax_model.clear_step_cache()

    scores = []
    for seed in (0, 1):
        m = TinyEnas(**_search_knobs(_sample_arch(seed)))
        m.train(train_path)
        scores.append(m.evaluate(val_path))
        m.destroy()

    train_entries = [v for k, v in jax_model._STEP_CACHE.items()
                     if k[1] == "train"]
    assert len(train_entries) == 1, \
        "different archs created distinct train steps (recompile per trial)"
    # One set of AOT-compiled chunk executables serves both architectures;
    # the jit callable behind them must never have been traced twice.
    entry = train_entries[0]
    assert entry["exec"] and all(e is not entry["step"]
                                 for e in entry["exec"].values()), \
        "train chunks fell back to jit instead of AOT executables"
    assert entry["step"]._cache_size() <= 1, \
        "train step retraced for the second architecture"
    eval_entries = [v for k, v in jax_model._STEP_CACHE.items()
                    if k[1] == "eval"]
    assert len(eval_entries) == 1
    assert all(0.0 <= s <= 1.0 for s in scores)


@pytest.mark.slow
def test_supernet_param_tree_architecture_independent(synth_image_data):
    """Weight-sharing invariant: same tree for every architecture, and a
    dump from one arch warm-starts a trial of another."""
    train_path, _ = synth_image_data
    m1 = TinyEnas(**_search_knobs(_sample_arch(0)))
    m1.train(train_path)
    dump1 = m1.dump_parameters()
    m1.destroy()

    m2 = TinyEnas(**_search_knobs(_sample_arch(1)))
    m2.train(train_path, shared_params=dump1)
    dump2 = m2.dump_parameters()
    m2.destroy()

    assert set(dump1) == set(dump2), \
        "supernet parameter tree depends on the architecture"
    # Both cell types' op weights exist in the shared tree.
    assert any("_sep3/" in k for k in dump1)
    assert any("_sep5/" in k for k in dump1)


@pytest.mark.slow
def test_enas_fixed_arch_end_to_end(synth_image_data):
    """Final-phase mode: single-path net via test_model_class, incl.
    dump/load round-trip and predict."""
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(3)]
    knobs = {"arch": _sample_arch(2), "batch_size": 32,
             "learning_rate": 0.05, "max_epochs": 3, "trial_epochs": 1,
             "share_params": False, "quick_train": False,
             "downscale": False}
    result = test_model_class(
        TinyEnas, "IMAGE_CLASSIFICATION", train_path, val_path,
        test_queries=queries, knobs=knobs)
    assert len(result.predictions) == 3
    assert all(abs(sum(p) - 1.0) < 1e-3 for p in result.predictions)


def test_enas_fixed_path_params_subset_of_supernet():
    """Single-path parameter names must be a subset of the supernet's
    (same naming scheme ties the two modes together)."""
    import jax
    import jax.numpy as jnp
    from flax import traverse_util

    arch = _sample_arch(3)
    x = jnp.zeros((1, 12, 12, 1), jnp.float32)

    sup = TinyEnas(**_search_knobs(arch))
    sup_mod = sup.create_module(4, (12, 12, 1))
    sup_vars = jax.eval_shape(
        lambda: sup_mod.init(jax.random.key(0), x,
                             arch=sup.extra_apply_inputs()["arch"]))

    fixed = TinyEnas(**{**_search_knobs(arch), "share_params": False,
                        "downscale": False})
    fixed_mod = fixed.create_module(4, (12, 12, 1))
    fixed_vars = jax.eval_shape(
        lambda: fixed_mod.init(jax.random.key(0), x))

    sup_keys = set(traverse_util.flatten_dict(sup_vars["params"], sep="/"))
    fixed_keys = set(traverse_util.flatten_dict(fixed_vars["params"],
                                                sep="/"))
    assert fixed_keys <= sup_keys, fixed_keys - sup_keys


@pytest.mark.slow
@pytest.mark.slower
def test_enas_search_loop_with_advisor_and_sharing(synth_image_data,
                                                   tmp_path):
    """End-to-end miniature of §3.5: EnasAdvisor proposes, TrialRunner
    executes on shared params via the ParamStore, REINFORCE updates flow,
    and the final-phase trial retrains the best arch from scratch."""
    train_path, val_path = synth_image_data
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "params"))
    try:
        user = meta.create_user("e@x.c", "h", "MODEL_DEVELOPER")
        model = meta.create_model(user["id"], "enas", "IMAGE_CLASSIFICATION",
                                  "tests.test_enas_model:TinyEnas", {})
        budget = {BudgetOption.MODEL_TRIAL_COUNT: 4}
        job = meta.create_train_job(user["id"], "app", "IMAGE_CLASSIFICATION",
                                    budget, train_path, val_path, "RUNNING")
        sub = meta.create_sub_train_job(job["id"], model["id"], "RUNNING")

        advisor = EnasAdvisor(TinyEnas.get_knob_config(), seed=0,
                              total_trials=4, final_train_frac=0.25)
        runner = TrialRunner(TinyEnas, advisor, train_path, val_path,
                             meta, params, sub["id"], model_id=model["id"],
                             budget=budget)
        done = runner.run()

        completed = meta.get_trials(sub["id"], TrialStatus.COMPLETED)
        assert len(completed) == 4
        # Search trials requested shared params; the last (final-phase)
        # trial trained from scratch.
        proposals = sorted(completed, key=lambda t: t["no"])
        assert all(t["proposal"]["params_type"] == ParamsType.GLOBAL_RECENT
                   for t in proposals[:-1])
        assert proposals[-1]["proposal"]["params_type"] == ParamsType.NONE
        assert proposals[-1]["knobs"]["share_params"] is False
    finally:
        meta.close()
        params.close()
