"""Online trial promotion into a serving ensemble (r12).

The promotion contract, end to end on a real LocalPlatform: promote a
trained trial into a RUNNING inference job's bin and (a) the new bin's
worker is registered BEFORE the old one is torn down, (b) the
predictor edge cache is invalidated synchronously — after promote()
returns, no request may be answered from a pre-promotion cache entry.
"""

import time

import pytest
import requests

from rafiki_tpu.cache import Cache, encode_payload
from rafiki_tpu.constants import (BudgetOption, ServiceType, TaskType,
                                  UserType)
from rafiki_tpu.model import load_image_dataset
from rafiki_tpu.platform import LocalPlatform

FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"


def _trained_job(platform, synth_image_data, n_trials=2,
                 name="ff-promote"):
    train_path, val_path = synth_image_data
    dev = platform.admin.create_user(f"{name}@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
    model = platform.admin.create_model(
        dev["id"], name, TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
    job = platform.admin.create_train_job(
        dev["id"], name, TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: n_trials},
        train_path, val_path)
    assert platform.admin.wait_until_train_job_done(job["id"],
                                                    timeout=600)
    return dev, job


def test_promote_swaps_bin_and_no_stale_cache_answers(
        tmp_path, synth_image_data, monkeypatch):
    monkeypatch.setenv("RAFIKI_TPU_SERVING_CACHE_BYTES", str(8 << 20))
    monkeypatch.setenv("RAFIKI_TPU_SERVING_CACHE_ADMIT_AFTER", "1")
    platform = LocalPlatform(workdir=str(tmp_path / "plat"),
                             supervise_interval=0)
    try:
        dev, job = _trained_job(platform, synth_image_data)
        best = platform.admin.get_best_trials(job["id"], max_count=2)
        assert len(best) == 2
        served, other = best[0], best[1]
        inf = platform.admin.create_inference_job(dev["id"], job["id"],
                                                  max_models=1)
        host = platform.admin.get_inference_job(
            inf["id"])["predictor_host"]
        pred_row = next(s for s in platform.meta.get_services()
                        if s["service_type"] == ServiceType.PREDICT)
        psvc = platform.container.get(pred_row["id"])
        assert psvc.edge_cache is not None
        cache = Cache(platform.bus)
        deadline = time.time() + 120
        while not cache.running_workers(inf["id"]) and \
                time.time() < deadline:
            time.sleep(0.2)
        info = cache.running_worker_info(inf["id"])
        assert {w["trial_id"] for w in info.values()} == {served["id"]}

        _, val_path = synth_image_data
        ds = load_image_dataset(val_path)
        q = encode_payload(ds.images[0])
        url = f"http://{host}/predict"

        def predict():
            r = requests.post(url, json={"query": q}, timeout=180)
            assert r.status_code == 200, r.text
            return r.json()["prediction"]

        predict()  # miss: populates the cache (first-touch admission)
        predict()  # hit: served from the edge cache
        ev = psvc.edge_cache.info()["events"]
        assert ev["hit"] == 1 and ev["miss"] == 1

        res = platform.admin.promote_trial(inf["id"], other["id"],
                                           replace_trial_id=served["id"])
        assert res["promoted_trial_id"] == other["id"]
        assert res["stopped_service_ids"], "old bin was not torn down"
        # The swap happened on the bus too: one bin, the NEW trial.
        info = cache.running_worker_info(inf["id"])
        assert {w["trial_id"] for w in info.values()} == {other["id"]}
        # Synchronous invalidation: the epoch bumped before promote
        # returned, so the SAME query now misses — it can never be
        # answered from the pre-promotion entry.
        assert psvc.edge_cache.info()["epoch"] >= 1
        predict()
        ev = psvc.edge_cache.info()["events"]
        assert ev["miss"] == 2, \
            "post-promotion request was served a pre-promotion entry"
        assert ev["hit"] == 1
        assert ev["invalidate"] >= 1

        # Promotion is validated: a trial can't be promoted twice, and
        # the replaced trial is no longer a served bin.
        with pytest.raises(ValueError, match="already served"):
            platform.admin.promote_trial(inf["id"], other["id"])
        with pytest.raises(ValueError, match="not a served bin"):
            platform.admin.promote_trial(inf["id"], served["id"],
                                         replace_trial_id="nope")
        platform.admin.stop_inference_job(inf["id"])
    finally:
        platform.shutdown()


def test_promote_validations_reject_foreign_and_incomplete(
        tmp_path, synth_image_data):
    platform = LocalPlatform(workdir=str(tmp_path / "plat"),
                             supervise_interval=0)
    try:
        dev, job = _trained_job(platform, synth_image_data, n_trials=1,
                                name="ff-promote-val")
        inf = platform.admin.create_inference_job(dev["id"], job["id"],
                                                  max_models=1)
        with pytest.raises(ValueError, match="unknown trial"):
            platform.admin.promote_trial(inf["id"], "no-such-trial")
        # A trial from ANOTHER train job must be rejected even if
        # completed: promotion is within one job's ensemble.
        dev2, job2 = _trained_job(platform, synth_image_data,
                                  n_trials=1, name="ff-promote-other")
        foreign = platform.admin.get_best_trials(job2["id"],
                                                 max_count=1)[0]
        with pytest.raises(ValueError, match="does not belong"):
            platform.admin.promote_trial(inf["id"], foreign["id"])
        platform.admin.stop_inference_job(inf["id"])
    finally:
        platform.shutdown()
