"""Continuous-batching decode scheduler + the generative edge path.

Three layers pinned here:

- **DecodeScheduler** (worker/decode_scheduler.py): bus frame →
  admission queue → engine steps → ordered token frames on the reply
  queue, including per-step admission (short requests finish while a
  long one is still resident) and worker-side prefix reuse.
- **Metrics gating** (observe/lm.py): the ``rafiki_tpu_lm_*`` family
  exists ONLY when ``RAFIKI_TPU_SERVING_GENERATE`` is on — the off
  side exposes zero series (asserted FIRST, before any test registers
  the family in the process registry).
- **Edge streaming** (predictor/app.py + utils/service.py): ``POST
  /generate`` streams NDJSON token frames as chunked HTTP while the
  stream is still being produced (proven with a gated fake worker —
  the client reads the first frame BEFORE the last one exists).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from rafiki_tpu.bus.memory import MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.models import JaxTransformerLM
from rafiki_tpu.observe import lm as obs_lm
from rafiki_tpu.observe import metrics as obs_metrics
from rafiki_tpu.worker.decode_scheduler import DecodeScheduler

TINY = {"d_model": 256, "n_layers": 2, "seq_len": 256, "batch_size": 2,
        "learning_rate": 1e-3, "train_steps": 20, "vocab_size": 512,
        "quick_train": False}

LM_FAMILIES = (
    "rafiki_tpu_lm_time_to_first_token_seconds",
    "rafiki_tpu_lm_inter_token_seconds",
    "rafiki_tpu_lm_tokens_total",
    "rafiki_tpu_lm_decode_dispatches_total",
    "rafiki_tpu_lm_prefill_total",
    "rafiki_tpu_lm_preemptions_total",
    "rafiki_tpu_lm_kv_pool_used_ratio",
    "rafiki_tpu_lm_resident_tokens",
)


# --- gating: the OFF side first (no family registered yet) -----------


def test_disabled_gate_exposes_zero_lm_series(monkeypatch):
    monkeypatch.delenv(obs_lm.GENERATE_ENV, raising=False)
    obs_lm.reset_for_tests()
    assert not obs_lm.serving()
    # Observations while off are free no-ops, not lazy registrations.
    obs_lm.observe_ttft(0.1)
    obs_lm.count_tokens(5)
    obs_lm.set_pool_used(0.5)
    for name in LM_FAMILIES:
        assert obs_metrics.registry().find(name) is None, \
            f"{name} registered while the gate is off"
    obs_lm.reset_for_tests()


def test_generate_enabled_spellings():
    assert not obs_lm.generate_enabled("")
    assert not obs_lm.generate_enabled("0")
    assert not obs_lm.generate_enabled("false")
    assert not obs_lm.generate_enabled("off")
    assert obs_lm.generate_enabled("1")
    assert obs_lm.generate_enabled("true")


# --- scheduler over a real engine ------------------------------------


@pytest.fixture(scope="module")
def lm():
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m._params = m._init_params()
    yield m
    m.destroy()


@pytest.fixture()
def sched(lm):
    bus = MemoryBus()
    cache = Cache(bus)
    eng = lm.make_generator(page_size=4, n_pages=64, decode_batch=2,
                            max_new_cap=16, prefix_cache_entries=4)
    s = DecodeScheduler(eng, cache, "w1", idle_wait=0.005)
    t = threading.Thread(target=s.loop, daemon=True)
    t.start()
    yield s, cache
    s.close(join=t)


def _submit(sched, cache, tokens, **kw):
    """Client + worker-loop halves: enqueue a generate frame, pop it
    the way InferenceWorker's serve loop would, hand it to the
    scheduler. Returns the query id the frames stream to."""
    qid = cache.send_generate("w1", tokens, **kw)
    items = cache.pop_queries("w1", timeout=1.0)
    assert len(items) == 1 and items[0].get("op") == "generate"
    sched.submit(items[0])
    return qid


def _collect(cache, qid, timeout=60.0):
    frames = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for fr in cache.pop_token_frames(qid, timeout=0.1):
            frames.append(fr)
            if fr.get("done"):
                return frames
    raise AssertionError(f"stream {qid} did not finish: {frames}")


def test_stream_end_to_end_and_prefix_reuse(sched):
    s, cache = sched
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, 512, size=9).tolist()

    qid = _submit(s, cache, prompt, max_new=6, temperature=0.0)
    frames = _collect(cache, qid)
    assert [f["seq"] for f in frames] == list(range(len(frames)))
    toks = [t for f in frames for t in f["tok"]]
    assert len(toks) == 6  # max_new incl. the admit-time token
    assert frames[-1]["done"] and frames[-1]["finish"] == "length"
    assert frames[-1]["n_tokens"] == 6
    assert all(not f["done"] for f in frames[:-1])

    # Same prompt again: greedy determinism end to end AND the
    # worker-side prefix cache skips the second prefill entirely.
    skipped0 = s.engine.prefill_skipped_total
    qid2 = _submit(s, cache, prompt, max_new=6, temperature=0.0)
    frames2 = _collect(cache, qid2)
    assert [t for f in frames2 for t in f["tok"]] == toks
    assert s.engine.prefill_skipped_total == skipped0 + 1
    assert s.served_total >= 2 and s.errors_total == 0


def test_short_request_finishes_while_long_decodes(sched):
    s, cache = sched
    rng = np.random.default_rng(29)
    p_long = rng.integers(0, 512, size=8).tolist()
    p_short = rng.integers(0, 512, size=5).tolist()

    qid_long = _submit(s, cache, p_long, max_new=14, temperature=0.0)
    # Wait until the long request has produced at least one frame (it
    # is resident), then admit the short one mid-decode.
    first = _collect_partial(cache, qid_long, n=1)
    qid_short = _submit(s, cache, p_short, max_new=3, temperature=0.0)
    short = _collect(cache, qid_short)
    # The short stream FINISHED; the long one is still incomplete
    # (its remaining frames arrive afterwards) — continuous batching,
    # not run-to-completion.
    assert short[-1]["finish"] in ("length", "eos")
    rest = _collect(cache, qid_long)
    toks_long = [t for f in (first + rest) for t in f["tok"]]
    assert len(toks_long) == 14
    assert len([t for f in short for t in f["tok"]]) == 3


def _collect_partial(cache, qid, n, timeout=60.0):
    frames = []
    deadline = time.monotonic() + timeout
    while len(frames) < n and time.monotonic() < deadline:
        frames.extend(cache.pop_token_frames(qid, timeout=0.1))
    assert len(frames) >= n
    return frames


def test_malformed_request_answers_error_frame(sched):
    s, cache = sched
    s.submit({"query_id": "bad-1", "gen": {"tokens": []}})
    frames = _collect(cache, "bad-1", timeout=5.0)
    assert frames[-1]["finish"] == "error" and frames[-1]["done"]


def test_enabled_gate_registers_and_counts(sched, monkeypatch):
    monkeypatch.setenv(obs_lm.GENERATE_ENV, "1")
    obs_lm.reset_for_tests()
    try:
        if not obs_metrics.metrics_enabled():
            pytest.skip("metrics disabled in this environment")
        assert obs_lm.serving()
        s, cache = sched
        prompt = list(range(40, 49))
        qid = _submit(s, cache, prompt, max_new=4, temperature=0.0)
        _collect(cache, qid)
        reg = obs_metrics.registry()
        tokens = reg.find("rafiki_tpu_lm_tokens_total")
        dispatches = reg.find("rafiki_tpu_lm_decode_dispatches_total")
        assert tokens is not None and dispatches is not None
        n_tok = sum(v for _, v in tokens.samples())
        n_disp = sum(v for _, v in dispatches.samples())
        assert n_tok >= 4 and n_disp >= 1
        assert reg.find(
            "rafiki_tpu_lm_time_to_first_token_seconds") is not None
    finally:
        obs_lm.reset_for_tests()


# --- the HTTP edge ----------------------------------------------------


class _FakeGenWorker:
    """A registration + reply-queue impersonation of a generative
    worker: answers each generate frame with ``max_new`` token frames.
    ``gate`` (when given) is waited on before the FINAL frame — the
    streaming test uses it to prove frames reach the client before the
    stream is complete."""

    def __init__(self, bus, job_id, worker_id="gw1", gate=None):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.gate = gate
        self.cache.register_worker(job_id, worker_id,
                                   info={"gen": {"decode_batch": 2}})
        self.stop_flag = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            for it in self.cache.pop_queries(self.worker_id,
                                             timeout=0.1):
                if it.get("op") != "generate":
                    continue
                qid = it["query_id"]
                n = it["gen"]["max_new"]
                for k in range(n):
                    if k == n - 1 and self.gate is not None:
                        assert self.gate.wait(timeout=10.0)
                    fr = {"seq": k, "tok": [100 + k],
                          "done": k == n - 1}
                    if k == n - 1:
                        fr.update(finish="length", n_tokens=n)
                    self.cache.send_token_frame(qid, self.worker_id,
                                                fr)

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


@pytest.fixture()
def edge():
    from rafiki_tpu.predictor.app import PredictorService

    bus = MemoryBus()
    svc = PredictorService("gsvc", "gjob", meta=None, bus=bus,
                           host="127.0.0.1", microbatch=False)
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    svc._http.start()
    yield svc, bus
    svc._http.stop()
    svc.stats.close()
    svc.predictor.close()


def _post(port, path, payload, timeout=15.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_generate_route_streams_ndjson(edge):
    svc, bus = edge
    gate = threading.Event()
    worker = _FakeGenWorker(bus, "gjob", gate=gate)
    try:
        resp = _post(svc.port, "/generate",
                     {"tokens": [1, 2, 3], "max_new": 3})
        assert resp.status == 200
        assert "ndjson" in resp.headers.get("Content-Type", "")
        # The FIRST frame arrives while the final one does not yet
        # exist (the worker is gated): streaming, not buffering.
        line1 = json.loads(resp.readline())
        assert line1["tok"] == [100] and not line1["done"]
        gate.set()
        rest = [json.loads(ln) for ln in resp.read().splitlines()]
        assert rest[-1]["done"] and rest[-1]["finish"] == "length"
        assert [f["tok"][0] for f in [line1] + rest] == [100, 101, 102]
    finally:
        worker.stop()


def test_generate_route_rejects_without_capable_worker(edge):
    svc, bus = edge
    # A classifier-only worker (no "gen" in its registration) must not
    # be picked.
    Cache(bus).register_worker("gjob", "plainw", info={"trial_id": "t"})
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc.port, "/generate", {"tokens": [1], "max_new": 2})
    assert e.value.code == 503


def test_generate_route_validates_body(edge):
    svc, _bus = edge
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc.port, "/generate", {"tokens": []})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc.port, "/generate", {"tokens": [1], "max_new": "x"})
    assert e.value.code == 400
