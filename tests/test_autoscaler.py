"""Metrics-driven autoscaler (r14): policy decision table, the closed
actuation loop (scale-up, graceful drain scale-down, idle-train
preemption shrink/regrow), dry-run, and the disabled-plane guard.

Policy tests are pure (no platform). The e2e lifecycle runs against ONE
shared resident-runner stack (module fixture: a trained 2-bin ensemble
plus a long-running "donor" train job on a 5-chip allocator with chip
sharing OFF, so exclusive capacity genuinely exhausts and preemption is
the only way a starved bin gets chips).
"""

import threading
import time

import pytest
import requests

from rafiki_tpu.admin.autoscaler import (AutoscalePolicy, Autoscaler,
                                         JobSignals, JobState,
                                         PolicyKnobs)
from rafiki_tpu.cache import Cache, encode_payload
from rafiki_tpu.constants import (BudgetOption, ServiceStatus,
                                  ServiceType, TaskType, UserType)
from rafiki_tpu.model import load_image_dataset
from rafiki_tpu.observe.metrics import registry


# --- Policy decision table (pure) ------------------------------------

def _policy(**kw):
    return AutoscalePolicy(PolicyKnobs(**kw))


def _replicas(**bins):
    return dict(bins)


def test_policy_backpressure_scales_fewest_replica_bin_first():
    p = _policy(up_cooldown_s=0.0)
    sig = JobSignals(backpressure_delta=3, queue_depth=0, queue_cap=100)
    out = p.decide(sig, _replicas(a=2, b=1), JobState(), now=100.0)
    assert [(d.action, d.bin, d.reason) for d in out] == \
        [("scale_up", "b", "backpressure")]


def test_policy_queue_high_water_and_p99():
    p = _policy(up_cooldown_s=0.0, queue_high=0.25)
    sig = JobSignals(queue_depth=30, queue_cap=100)
    out = p.decide(sig, _replicas(a=1), JobState(), now=0.0)
    assert out and out[0].reason == "queue_high"
    p99 = _policy(up_cooldown_s=0.0, p99_high_ms=50.0)
    sig = JobSignals(queue_depth=0, queue_cap=100, p99_ms=80.0)
    out = p99.decide(sig, _replicas(a=1), JobState(), now=0.0)
    assert out and out[0].reason == "p99_high"
    # p99 not consulted when the knob is 0 — a slow box must not flap.
    off = _policy(up_cooldown_s=0.0, p99_high_ms=0.0)
    assert off.classify(sig)[0] == "down"


def test_policy_hysteresis_band_holds_and_never_flaps():
    """An oscillating load INSIDE the band (between queue_low and
    queue_high, zero backpressure) must produce zero actions, ever —
    the ISSUE's flapping guard."""
    p = _policy(up_cooldown_s=0.0, down_cooldown_s=0.0,
                queue_low=0.02, queue_high=0.25)
    state = JobState()
    actions = []
    for i in range(50):  # oscillate 5% <-> 20% of the queue
        frac = 0.05 if i % 2 == 0 else 0.20
        sig = JobSignals(queue_depth=frac * 100, queue_cap=100)
        actions += p.decide(sig, _replicas(a=2, b=2), state, now=float(i))
    assert actions == []


def test_policy_up_cooldown_blocks_then_allows():
    p = _policy(up_cooldown_s=10.0)
    sig = JobSignals(backpressure_delta=1, queue_cap=100)
    state = JobState()
    assert p.decide(sig, _replicas(a=1), state, now=0.0)
    state.last_up_mono = 0.0  # actuated
    assert p.decide(sig, _replicas(a=2), state, now=5.0) == []
    assert p.decide(sig, _replicas(a=2), state, now=10.0)


def test_policy_cooldown_asymmetry_up_fast_down_slow():
    """After an action, the next scale-UP waits only up_cooldown while
    a scale-DOWN waits the (longer) down_cooldown from the last action
    in EITHER direction — tearing down a replica right after adding
    one is the textbook flap."""
    p = _policy(up_cooldown_s=5.0, down_cooldown_s=60.0)
    state = JobState()
    state.last_up_mono = 0.0
    up_sig = JobSignals(backpressure_delta=1, queue_cap=100)
    idle_sig = JobSignals(queue_depth=0, queue_cap=100)
    assert p.decide(up_sig, _replicas(a=2), state, now=6.0)      # up ok
    assert p.decide(idle_sig, _replicas(a=2), state, now=30.0) == []
    out = p.decide(idle_sig, _replicas(a=2), state, now=61.0)
    assert [(d.action, d.bin) for d in out] == [("scale_down", "a")]
    # ...and a recent scale-down also re-arms the down cooldown.
    state.last_down_mono = 61.0
    assert p.decide(idle_sig, _replicas(a=2), state, now=100.0) == []


def test_policy_step_bound_and_ceiling():
    p = _policy(up_cooldown_s=0.0, step=2, max_replicas=2)
    sig = JobSignals(backpressure_delta=1, queue_cap=100)
    out = p.decide(sig, _replicas(a=1, b=1, c=2), JobState(), now=0.0)
    # step=2 adds two, fewest-replica bins first; c is at the ceiling.
    assert [(d.action, d.bin) for d in out] == \
        [("scale_up", "a"), ("scale_up", "b")]


def test_policy_down_never_below_one_replica():
    p = _policy(down_cooldown_s=0.0)
    idle = JobSignals(queue_depth=0, queue_cap=100)
    out = p.decide(idle, _replicas(a=3, b=1), JobState(), now=0.0)
    assert [(d.action, d.bin) for d in out] == [("scale_down", "a")]
    assert p.decide(idle, _replicas(a=1, b=1), JobState(),
                    now=0.0) == []


def test_policy_per_bin_signals_target_hot_bin_up_cold_bin_down():
    """The r17 attribution carry (ISSUE r14 follow-on closed): with
    per-bin qps present, a scale-up lands on the HOTTEST bin per
    replica even when another bin has fewer replicas, and a scale-down
    drains the COLDEST bin even when another is more replicated."""
    from rafiki_tpu.admin.autoscaler import BinSignals

    p = _policy(up_cooldown_s=0.0, down_cooldown_s=0.0)
    # Up: "cold" has fewer replicas (the legacy pick); "hot" carries
    # the load — per-bin signals must redirect the capacity.
    sig = JobSignals(backpressure_delta=3, queue_cap=100,
                     bins={"hot": BinSignals(qps=100.0),
                           "cold": BinSignals(qps=1.0)})
    out = p.decide(sig, _replicas(hot=2, cold=1), JobState(), now=0.0)
    assert [(d.action, d.bin) for d in out] == [("scale_up", "hot")]
    # An unmeasured bin ranks below any measured one.
    sig2 = JobSignals(backpressure_delta=3, queue_cap=100,
                      bins={"hot": BinSignals(qps=5.0)})
    out = p.decide(sig2, _replicas(hot=1, mystery=1), JobState(),
                   now=0.0)
    assert out[0].bin == "hot"
    # Down: "hot" is MORE replicated (the legacy victim); the cold bin
    # drains instead.
    idle = JobSignals(queue_depth=0, queue_cap=100,
                      bins={"hot": BinSignals(qps=100.0),
                            "cold": BinSignals(qps=0.5)})
    out = p.decide(idle, _replicas(hot=3, cold=2), JobState(), now=0.0)
    assert [(d.action, d.bin) for d in out] == [("scale_down", "cold")]
    # An UNMEASURED bin (no ledger rows — e.g. a tiered sibling that
    # never sees escalations) ranks COLDEST for the drain: it would
    # otherwise be protected while the only serving bin lost replicas.
    out = p.decide(idle, _replicas(hot=2, mystery=2), JobState(),
                   now=0.0)
    assert [(d.action, d.bin) for d in out] == [("scale_down",
                                                 "mystery")]
    # Never below one replica, per-bin signals or not.
    out = p.decide(idle, _replicas(hot=1, cold=1), JobState(), now=0.0)
    assert out == []


def test_policy_per_bin_fallback_without_ledger():
    """Old workers / attribution off: ``bins`` is None and the legacy
    ordering stands — fewest-replicas-first up, most-replicated down."""
    p = _policy(up_cooldown_s=0.0, down_cooldown_s=0.0)
    sig = JobSignals(backpressure_delta=1, queue_cap=100)
    assert sig.bins is None and sig.bin_signal("a") is None
    out = p.decide(sig, _replicas(a=2, b=1), JobState(), now=0.0)
    assert out[0].bin == "b"
    idle = JobSignals(queue_depth=0, queue_cap=100)
    out = p.decide(idle, _replicas(a=3, b=2), JobState(), now=0.0)
    assert [(d.action, d.bin) for d in out] == [("scale_down", "a")]


def test_signals_fold_per_bin_ledger_rates(monkeypatch):
    """The scrape half: serving_bin_* families in the exposition fold
    into per-bin qps / queue-rate EWMAs keyed by the ledger's bin
    label; a bin that disappears (promotion churn) drops its EWMA."""
    scaler = Autoscaler.__new__(Autoscaler)  # scrape logic only

    stats = {"service": "svc1", "http_service": "http1",
             "knobs": {"queue_cap": 100}, "microbatch": True}

    def expo(binq, binw):
        lines = []
        for b, v in binq.items():
            lines.append('rafiki_tpu_serving_bin_queries_total'
                         f'{{service="svc1",bin="{b}"}} {v}')
        for b, v in binw.items():
            lines.append('rafiki_tpu_serving_bin_queue_seconds_total'
                         f'{{service="svc1",bin="{b}"}} {v}')
        lines.append('rafiki_tpu_serving_requests_total'
                     '{service="svc1"} 10')
        lines.append('rafiki_tpu_serving_rejected_total'
                     '{service="svc1"} 0')
        lines.append('rafiki_tpu_serving_queue_depth_queries'
                     '{service="svc1"} 0')
        return "\n".join(lines) + "\n"

    feed = {"text": expo({"binA": 0, "binB": 0},
                         {"binA": 0.0, "binB": 0.0})}
    monkeypatch.setattr(
        Autoscaler, "_scrape",
        lambda self, host, path: stats if path == "/stats"
        else feed["text"])
    job = {"predictor_host": "x:1"}
    state = JobState()
    assert scaler._signals(job, state, now=0.0) is None  # basis sweep
    feed["text"] = expo({"binA": 50, "binB": 5},
                        {"binA": 2.0, "binB": 0.1})
    sig = scaler._signals(job, state, now=10.0)
    assert sig is not None and sig.bins is not None
    assert sig.bins["binA"].qps == pytest.approx(5.0)
    assert sig.bins["binB"].qps == pytest.approx(0.5)
    assert sig.bins["binA"].queue_rate == pytest.approx(0.2)
    assert sig.bin_signal("binAxxxxxxxxxLONGID") is None
    # bin label matching truncates like the ledger does
    assert sig.bin_signal("binA") is sig.bins["binA"]
    # churn: binB vanishes -> its EWMA is dropped, binA continues
    feed["text"] = expo({"binA": 100}, {"binA": 2.5})
    sig = scaler._signals(job, state, now=20.0)
    assert "binB" not in state.bin_qps_ewma
    assert sig.bins is not None and "binB" not in sig.bins


def test_from_env_builds_knobs(monkeypatch):
    monkeypatch.setenv("RAFIKI_TPU_AUTOSCALE_MAX_REPLICAS", "7")
    monkeypatch.setenv("RAFIKI_TPU_AUTOSCALE_QUEUE_HIGH", "0.5")
    monkeypatch.setenv("RAFIKI_TPU_AUTOSCALE_DRY_RUN", "1")
    scaler = Autoscaler.from_env(services=None, meta=None)
    try:
        assert scaler.policy.knobs.max_replicas == 7
        assert scaler.policy.knobs.queue_high == 0.5
        assert scaler.dry_run is True
    finally:
        scaler.close()


# --- Disabled-plane guard (must run BEFORE any e2e autoscaler use in
# --- this process: the registry is process-global) --------------------

def test_disabled_plane_zero_series_and_supervise_unchanged(tmp_path):
    from rafiki_tpu.platform import LocalPlatform

    plat = LocalPlatform(workdir=str(tmp_path / "p"),
                         supervise_interval=0)
    try:
        assert plat.autoscaler is None
        assert plat.services.autoscaler is None
        assert plat.services.supervise() == []
        for name in ("rafiki_tpu_autoscale_actions_total",
                     "rafiki_tpu_autoscale_target_replicas",
                     "rafiki_tpu_autoscale_actual_replicas",
                     "rafiki_tpu_autoscale_reclaimed_chips_total"):
            m = registry().find(name)
            assert m is None or m.samples() == [], name
    finally:
        plat.shutdown()


def test_platform_constructs_autoscaler_from_env(tmp_path, monkeypatch):
    from rafiki_tpu.platform import LocalPlatform

    monkeypatch.setenv("RAFIKI_TPU_AUTOSCALE", "1")
    monkeypatch.setenv("RAFIKI_TPU_AUTOSCALE_MAX_REPLICAS", "3")
    plat = LocalPlatform(workdir=str(tmp_path / "p"),
                         supervise_interval=0)
    try:
        assert plat.autoscaler is not None
        assert plat.services.autoscaler is plat.autoscaler
        assert plat.autoscaler.policy.knobs.max_replicas == 3
    finally:
        plat.shutdown()
    # close() ran: no stale series survive the platform.
    m = registry().find("rafiki_tpu_autoscale_actions_total")
    assert m is None or m.samples() == []


# --- E2E lifecycle on one shared stack --------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory, synth_image_data):
    """5-chip platform, chip sharing OFF: a trained 2-bin ensemble
    (2 chips) + a long-running donor train job (2 workers, 2 chips) =
    4/5 chips used. One free chip absorbs the first scale-up; the
    second must preempt the donor."""
    import os

    train_path, val_path = synth_image_data
    prior = os.environ.get("RAFIKI_TPU_CHIP_SHARE")
    os.environ["RAFIKI_TPU_CHIP_SHARE"] = "0"
    from rafiki_tpu.platform import LocalPlatform

    tmp = tmp_path_factory.mktemp("autoscale")
    plat = LocalPlatform(workdir=str(tmp / "plat"), http=True,
                         supervise_interval=0, n_chips=5)
    admin = plat.admin
    u = admin.create_user("scale@x.c", "pw", UserType.MODEL_DEVELOPER)
    mdl = admin.create_model(
        u["id"], "ff-scale", TaskType.IMAGE_CLASSIFICATION,
        "rafiki_tpu.models.feedforward:JaxFeedForward")
    job = admin.create_train_job(
        u["id"], "scale", TaskType.IMAGE_CLASSIFICATION, [mdl["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 2}, train_path, val_path)
    assert admin.wait_until_train_job_done(job["id"], timeout=900)
    donor = admin.create_train_job(
        u["id"], "donor", TaskType.IMAGE_CLASSIFICATION, [mdl["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 10000,
         BudgetOption.CHIP_COUNT: 2}, train_path, val_path)
    inf = admin.create_inference_job(u["id"], job["id"], max_models=2)
    cache = Cache(plat.bus)
    deadline = time.time() + 180
    while len(cache.running_workers(inf["id"])) < 2 \
            and time.time() < deadline:
        time.sleep(0.2)
    assert len(cache.running_workers(inf["id"])) >= 2
    host = admin.get_inference_job(inf["id"])["predictor_host"]
    ds = load_image_dataset(val_path)
    batch = [encode_payload(ds.images[i]) for i in range(3)]
    requests.post(f"http://{host}/predict", json={"queries": batch},
                  timeout=300).raise_for_status()
    yield {"plat": plat, "admin": admin, "inf": inf, "donor": donor,
           "host": host, "batch": batch, "cache": cache}
    try:
        admin.stop_train_job(donor["id"])
    except Exception:
        pass
    plat.shutdown()
    if prior is None:
        os.environ.pop("RAFIKI_TPU_CHIP_SHARE", None)
    else:
        os.environ["RAFIKI_TPU_CHIP_SHARE"] = prior


def _donor_train_workers(plat, job_id):
    out = []
    for sub in plat.meta.get_sub_train_jobs(job_id):
        for w in plat.meta.get_train_job_workers(sub["id"]):
            svc = plat.meta.get_service(w["service_id"])
            if svc["service_type"] == ServiceType.TRAIN and \
                    svc["status"] in (ServiceStatus.STARTED,
                                      ServiceStatus.DEPLOYING,
                                      ServiceStatus.RUNNING):
                out.append(svc)
    return out


_OVERLOAD = JobSignals(qps=50.0, queue_depth=900, queue_cap=1000,
                       backpressure_delta=5)
_IDLE = JobSignals(queue_depth=0, queue_cap=1000)


def test_e2e_lifecycle_scale_up_preempt_drain_regrow(stack):
    """The full loop on one stack, in signal order: synthetic
    backpressure scales a bin up (free chip), more backpressure
    preempts the idle donor for the second replica, quiet drains the
    replicas back down (gracefully, under in-flight load) and regrows
    the donor."""
    plat, admin = stack["plat"], stack["admin"]
    inf, donor = stack["inf"], stack["donor"]
    # mfu_floor 0.5: the donor's tiny trials publish a REAL MFU gauge
    # (~0.11 on the calibrated-CPU denominator), so the honest idle
    # verdict needs a floor above it — "below half utilization is
    # preemptible" is a legitimate operator setting, and the
    # truncated-label regression test pins the resolution itself.
    scaler = Autoscaler(plat.services, plat.meta,
                        knobs=PolicyKnobs(up_cooldown_s=0.0,
                                          down_cooldown_s=0.0,
                                          idle_sweeps=2,
                                          mfu_floor=0.5))
    plat.services.autoscaler = scaler
    try:
        assert scaler.sweep() == []  # first sweep = delta basis only
        n0 = len(plat.services.active_inference_workers(inf["id"]))
        assert n0 == 2

        scaler._signals = lambda j, s, n: _OVERLOAD
        acted = scaler.sweep()  # takes the free chip
        assert [d["action"] for d in acted] == ["scale_up"]
        assert acted[0]["applied"] and "preempted_chips" not in acted[0]
        acted = scaler.sweep()  # starved -> preempts the donor
        assert [d["action"] for d in acted] == \
            ["preempt_shrink", "scale_up"] or \
            [d["action"] for d in acted] == ["scale_up"]
        up = [d for d in acted if d["action"] == "scale_up"][0]
        assert up["applied"] and up.get("preempted_chips") == 1
        assert len(_donor_train_workers(plat, donor["id"])) == 1
        assert len(plat.services.active_inference_workers(
            inf["id"])) == 4
        reclaimed = registry().find(
            "rafiki_tpu_autoscale_reclaimed_chips_total")
        assert reclaimed is not None and reclaimed.value() >= 1

        # Graceful scale-down under in-flight load: a client hammers
        # /predict throughout; every request must keep answering.
        stop = threading.Event()
        errors = []

        def client():
            while not stop.is_set():
                try:
                    r = requests.post(
                        f"http://{stack['host']}/predict",
                        json={"queries": stack["batch"]}, timeout=300)
                    r.raise_for_status()
                    assert all(p is not None
                               for p in r.json()["predictions"])
                except Exception as e:  # surfaced below
                    errors.append(e)
                    return

        t = threading.Thread(target=client)
        t.start()
        try:
            scaler._signals = lambda j, s, n: _IDLE
            actions = []
            deadline = time.time() + 60
            while time.time() < deadline:
                actions += [d["action"] for d in scaler.sweep()]
                if "regrow" in actions and len(
                        plat.services.active_inference_workers(
                            inf["id"])) == 2:
                    break
        finally:
            stop.set()
            t.join(timeout=30)
        assert not errors, errors[0]
        assert actions.count("scale_down") >= 2
        assert "regrow" in actions
        assert len(plat.services.active_inference_workers(
            inf["id"])) == 2
        assert len(_donor_train_workers(plat, donor["id"])) == 2
        # Drained replicas are OUT of the bus registry (the Predictor's
        # next scan plans without them).
        assert len(stack["cache"].running_workers(inf["id"])) == 2

        snap = admin.get_autoscale()
        assert snap["enabled"] and snap["epoch"] == scaler.epoch
        kinds = {d["action"] for d in snap["decisions"]}
        assert {"scale_up", "scale_down", "preempt_shrink",
                "regrow"} <= kinds
        assert all("trace_id" in d for d in snap["decisions"])
    finally:
        plat.services.autoscaler = None
        scaler.close()


def test_e2e_real_signals_parse_the_predictor_metrics(stack):
    """No monkeypatching: drive real traffic, let the controller scrape
    the predictor's /metrics, and check the delta signals it derives
    (qps > 0, a sane queue_cap, p99 from the http histogram)."""
    plat = stack["plat"]
    scaler = Autoscaler(plat.services, plat.meta)
    try:
        job = plat.meta.get_inference_job(stack["inf"]["id"])
        state = JobState()
        assert scaler._signals(job, state, time.monotonic()) is None
        for _ in range(5):
            requests.post(f"http://{stack['host']}/predict",
                          json={"queries": stack["batch"]},
                          timeout=300).raise_for_status()
        time.sleep(0.1)
        sig = scaler._signals(job, state, time.monotonic())
        assert sig is not None
        assert sig.qps > 0
        assert sig.queue_cap >= 1
        assert sig.p99_ms is not None and sig.p99_ms > 0
        assert sig.backpressure_delta == 0
    finally:
        scaler.close()


def test_e2e_dry_run_records_without_actuating(stack):
    plat = stack["plat"]
    scaler = Autoscaler(plat.services, plat.meta,
                        knobs=PolicyKnobs(up_cooldown_s=0.0),
                        dry_run=True)
    try:
        scaler.sweep()
        before = len(plat.services.active_inference_workers(
            stack["inf"]["id"]))
        scaler._signals = lambda j, s, n: _OVERLOAD
        acted = scaler.sweep()
        assert acted and acted[0]["action"] == "scale_up"
        assert acted[0]["dry_run"] is True
        assert acted[0]["applied"] is False
        assert len(plat.services.active_inference_workers(
            stack["inf"]["id"])) == before
        counter = registry().find("rafiki_tpu_autoscale_actions_total")
        assert counter.value(action="scale_up",
                             reason="backpressure") >= 1
        assert scaler.snapshot()["dry_run"] is True
    finally:
        scaler.close()


def test_drain_returns_chips_and_unregisters(stack):
    """drain_inference_worker directly: add a replica, drain it —
    registration gone, row STOPPED, chips back."""
    plat, inf = stack["plat"], stack["inf"]
    rows = plat.services.active_inference_workers(inf["id"])
    bin_id = rows[0]["trial_id"]
    free0 = plat.allocator.free_chips
    svc = plat.services.add_inference_worker(inf["id"], bin_id)
    assert svc is not None
    deadline = time.time() + 120
    while svc["id"] not in stack["cache"].running_workers(inf["id"]) \
            and time.time() < deadline:
        time.sleep(0.1)
    res = plat.services.drain_inference_worker(svc["id"])
    assert res["drained"] is True
    assert svc["id"] not in stack["cache"].running_workers(inf["id"])
    assert plat.meta.get_service(svc["id"])["status"] == \
        ServiceStatus.STOPPED
    assert plat.allocator.free_chips == free0


def test_idle_tracking_resolves_truncated_mfu_labels():
    """The train MFU gauge is bound with trial=trial_id[:12]; idle
    detection must resolve that truncated label through the sub-job's
    RUNNING trial rows — a busy sub-job (MFU above floor) must never
    read as idle just because a full-id lookup missed (review
    finding: the label/meta mismatch made EVERY job preemptible)."""
    from rafiki_tpu.observe.metrics import registry as reg
    from rafiki_tpu.store import MetaStore

    meta = MetaStore(":memory:")
    try:
        user = meta.create_user("mfu@x.c", "h", "MODEL_DEVELOPER")
        job = meta.create_train_job(user["id"], "mfu-app",
                                    "IMAGE_CLASSIFICATION", {}, "t",
                                    "v", "RUNNING")
        sub = meta.create_sub_train_job(job["id"], "model-x", "STARTED")
        trial = meta.create_trial(sub["id"], "model-x", 1, "RUNNING")
        scaler = Autoscaler(services=None, meta=meta,
                            knobs=PolicyKnobs(mfu_floor=0.05,
                                              idle_sweeps=1))
        gauge = reg().gauge("rafiki_tpu_train_mfu_ratio", "")
        try:
            gauge.set(0.9, trial=trial["id"][:12])  # busy, truncated
            scaler._track_idle_training()
            assert sub["id"] not in scaler._idle_train
            gauge.set(0.001, trial=trial["id"][:12])  # below floor
            scaler._track_idle_training()
            assert scaler._idle_train.get(sub["id"]) == 1
            gauge.remove(trial=trial["id"][:12])  # no series = idle
            scaler._track_idle_training()
            assert scaler._idle_train.get(sub["id"]) == 2
        finally:
            gauge.remove(trial=trial["id"][:12])
            scaler.close()
    finally:
        meta.close()


def test_signals_skip_microbatch_off_frontends(monkeypatch):
    """A batcher-off frontend has no admission queue — depth 0 and no
    429s forever — so the policy would read permanent 'idle' and drain
    manually attached replicas under live traffic. The controller must
    skip such jobs outright (review finding)."""
    scaler = Autoscaler(services=None, meta=None)
    try:
        state = JobState()

        def fake_scrape(host, path):
            if path == "/stats":
                return {"service": "s", "http_service": "h",
                        "microbatch": False,
                        "knobs": {"queue_cap": 64}}
            return ""

        monkeypatch.setattr(scaler, "_scrape", fake_scrape)
        job = {"predictor_host": "127.0.0.1:1"}
        for _ in range(3):  # never yields a signal, even past sweep 1
            assert scaler._signals(job, state, time.monotonic()) is None
    finally:
        scaler.close()
