"""Unified metrics plane: registry, exposition, histograms, /metrics.

Covers the ISSUE-2 test checklist: exposition format validity,
histogram bucket math, ``/metrics`` presence on JsonHttpServer-based
services (plus the worker runner's standalone metrics server), and the
metric-naming convention check as a tier-1 test.
"""

import math
import os
import subprocess
import sys

import pytest
import requests

from rafiki_tpu.observe.metrics import (Counter, Gauge, Histogram,
                                        MetricsRegistry,
                                        bucket_percentile,
                                        histogram_percentiles_ms,
                                        label_context, bound_labels,
                                        metrics_enabled,
                                        parse_exposition, registry,
                                        serve_metrics)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- Registry / exposition format ---

def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("rafiki_tpu_node_widgets_total", "widgets")
    c.inc()
    c.inc(2, kind="a")
    assert c.value() == 1
    assert c.value(kind="a") == 2
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("rafiki_tpu_node_depth_queries")
    g.set(5, q="x")
    g.dec(2, q="x")
    assert g.value(q="x") == 3
    # get-or-create is idempotent, type-checked
    assert reg.counter("rafiki_tpu_node_widgets_total") is c
    with pytest.raises(TypeError):
        reg.gauge("rafiki_tpu_node_widgets_total")


def test_exposition_format_is_valid_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("rafiki_tpu_node_a_total", "help text").inc(3, svc="s1")
    reg.gauge("rafiki_tpu_node_b_ratio").set(0.5)
    reg.histogram("rafiki_tpu_node_c_seconds",
                  buckets=(0.1, 1.0)).observe(0.05)
    text = reg.expose()
    lines = text.strip().splitlines()
    assert "# HELP rafiki_tpu_node_a_total help text" in lines
    assert "# TYPE rafiki_tpu_node_a_total counter" in lines
    assert 'rafiki_tpu_node_a_total{svc="s1"} 3' in lines
    assert "# TYPE rafiki_tpu_node_b_ratio gauge" in lines
    assert "rafiki_tpu_node_b_ratio 0.5" in lines
    assert "# TYPE rafiki_tpu_node_c_seconds histogram" in lines
    assert 'rafiki_tpu_node_c_seconds_bucket{le="0.1"} 1' in lines
    assert 'rafiki_tpu_node_c_seconds_bucket{le="1"} 1' in lines
    assert 'rafiki_tpu_node_c_seconds_bucket{le="+Inf"} 1' in lines
    assert "rafiki_tpu_node_c_seconds_count 1" in lines
    # every non-comment line is "name[{labels}] value"
    for line in lines:
        if line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        assert name_part and (value == "+Inf" or float(value) is not None)


def test_label_values_escaped():
    reg = MetricsRegistry()
    reg.counter("rafiki_tpu_node_esc_total").inc(
        1, path='ha"h\\a\nb')
    text = reg.expose()
    # json-style escapes: quote, backslash, newline never break the line
    assert len(text.strip().splitlines()) == 2
    parsed = parse_exposition(text)
    labels, value = parsed["rafiki_tpu_node_esc_total"][0]
    assert labels["path"] == 'ha"h\\a\nb' and value == 1


def test_parse_exposition_roundtrip():
    reg = MetricsRegistry()
    reg.counter("rafiki_tpu_node_x_total").inc(7, a="1", b="2")
    reg.histogram("rafiki_tpu_node_y_seconds",
                  buckets=(0.5,)).observe(0.2, op="p")
    parsed = parse_exposition(reg.expose())
    assert ({"a": "1", "b": "2"}, 7.0) in parsed["rafiki_tpu_node_x_total"]
    buckets = parsed["rafiki_tpu_node_y_seconds_bucket"]
    assert ({"op": "p", "le": "0.5"}, 1.0) in buckets
    assert ({"op": "p", "le": "+Inf"}, 1.0) in buckets


# --- Histogram bucket math ---

def test_histogram_bucket_assignment_and_sums():
    h = Histogram("rafiki_tpu_node_h_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 0.5, 5.0):
        h.observe(v)
    cum = h.cumulative_buckets()
    # cumulative: <=0.01 -> 2 (0.005, 0.01 on the boundary), <=0.1 -> 3,
    # <=1.0 -> 4, +Inf -> 5
    assert cum == [(0.01, 2), (0.1, 3), (1.0, 4), (math.inf, 5)]
    assert h.count() == 5
    assert h.sum() == pytest.approx(5.565)


def test_histogram_percentile_interpolation():
    h = Histogram("rafiki_tpu_node_p_seconds", buckets=(1.0, 2.0, 4.0))
    for _ in range(50):
        h.observe(0.5)   # first bucket
    for _ in range(50):
        h.observe(3.0)   # third bucket
    # median at the first bucket's upper bound
    assert h.percentile(0.5) == pytest.approx(1.0)
    # p99 interpolates inside (2.0, 4.0]
    p99 = h.percentile(0.99)
    assert 2.0 < p99 <= 4.0
    # quantile landing in +Inf reports the last finite bound
    h2 = Histogram("rafiki_tpu_node_q_seconds", buckets=(1.0,))
    h2.observe(10.0)
    assert h2.percentile(0.5) == 1.0
    # empty histogram -> None
    assert Histogram("rafiki_tpu_node_r_seconds").percentile(0.5) is None


def test_bucket_percentile_edge_cases():
    assert bucket_percentile([], 0.5) is None
    assert bucket_percentile([(1.0, 0), (math.inf, 0)], 0.5) is None
    # single bucket, all mass: interpolates within [0, bound]
    assert bucket_percentile([(2.0, 10), (math.inf, 10)], 0.5) == \
        pytest.approx(1.0)


def test_histogram_percentiles_ms_filters_labels():
    reg = MetricsRegistry()
    h = reg.histogram("rafiki_tpu_node_f_seconds", buckets=(0.1, 1.0))
    h.observe(0.05, service="a", stage="fill")
    h.observe(0.5, service="b", stage="fill")
    samples = parse_exposition(reg.expose())[
        "rafiki_tpu_node_f_seconds_bucket"]
    p_a = histogram_percentiles_ms(samples, qs=(0.5,), service="a")
    p_b = histogram_percentiles_ms(samples, qs=(0.5,), service="b")
    assert p_a[0] <= 100.0 < p_b[0]
    assert histogram_percentiles_ms(samples, service="zzz") is None


# --- Label context (per-trial attribution) ---

def test_label_context_nests_and_restores():
    assert bound_labels() == {}
    with label_context(trial="t1"):
        assert bound_labels() == {"trial": "t1"}
        with label_context(extra="x"):
            assert bound_labels() == {"trial": "t1", "extra": "x"}
        assert bound_labels() == {"trial": "t1"}
    assert bound_labels() == {}


# --- /metrics on JsonHttpServer services ---

def test_metrics_route_on_any_json_http_server():
    from rafiki_tpu.utils.service import JsonHttpServer

    registry().counter("rafiki_tpu_node_probe_total").inc()
    server = JsonHttpServer(
        [("GET", "/", lambda p, b, c: (200, {"ok": True}))],
        host="127.0.0.1", name="test-svc").start()
    try:
        r = requests.get(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10)
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "rafiki_tpu_node_probe_total" in r.text
        # the request we just made was itself instrumented
        r2 = requests.get(
            f"http://127.0.0.1:{server.port}/metrics", timeout=10)
        assert 'service="test-svc"' in r2.text
        assert "rafiki_tpu_http_request_seconds_bucket" in r2.text
    finally:
        server.stop()


def test_metrics_route_on_predictor_service():
    from rafiki_tpu.bus import MemoryBus
    from rafiki_tpu.predictor.app import PredictorService

    svc = PredictorService("msvc", "job", meta=None, bus=MemoryBus(),
                           host="127.0.0.1")
    svc._http.start()
    try:
        r = requests.get(f"http://127.0.0.1:{svc.port}/metrics",
                         timeout=10)
        assert r.status_code == 200
        assert "# TYPE" in r.text
    finally:
        svc._http.stop()
        if svc.batcher is not None:
            svc.batcher.stop()


def test_worker_runner_metrics_server():
    """Subprocess worker runners get a standalone metrics-only server
    (container/services.py wires it from RAFIKI_TPU_METRICS_PORT)."""
    server = serve_metrics(host="127.0.0.1", port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        assert requests.get(base + "/", timeout=10).json() == {
            "status": "ok"}
        r = requests.get(base + "/metrics", timeout=10)
        assert r.status_code == 200 and "# TYPE" in r.text
    finally:
        server.stop()


def test_metrics_env_disables_route(monkeypatch):
    from rafiki_tpu.utils.service import JsonHttpServer

    monkeypatch.setenv("RAFIKI_TPU_METRICS", "0")
    assert not metrics_enabled()
    server = JsonHttpServer(
        [("GET", "/", lambda p, b, c: (200, {}))],
        host="127.0.0.1", name="off-svc").start()
    try:
        r = requests.get(f"http://127.0.0.1:{server.port}/metrics",
                         timeout=10)
        assert r.status_code == 404
    finally:
        server.stop()
    monkeypatch.delenv("RAFIKI_TPU_METRICS")
    assert metrics_enabled()


# --- ServingStats folded into the registry ---

def test_serving_stats_backed_by_registry():
    from rafiki_tpu.observe import ServingStats

    s = ServingStats()
    s.admitted(4)
    s.admitted(2)
    s.backpressured()
    s.set_queue_depth(6)
    s.dispatched(2, 6, fill_s=0.004, scatter_s=0.001, inflight=1)
    s.gathered(0.02, inflight=0)
    snap = s.snapshot()
    assert snap["requests"] == 2 and snap["queries"] == 6
    assert snap["rejected"] == 1
    assert snap["coalescing_factor"] == 2.0
    assert snap["queue_depth_peak"] == 6
    assert snap["fill"]["count"] == 1
    assert snap["fill"]["mean_ms"] == pytest.approx(4.0, rel=0.01)
    assert snap["gather"]["p95_ms"] > 0
    # the same numbers are in the shared registry under this service's
    # label — /stats and /metrics cannot disagree
    c = registry().counter("rafiki_tpu_serving_requests_total")
    assert c.value(service=s.service) == 2
    # a second instance gets its own series
    s2 = ServingStats()
    assert s2.requests == 0 and s2.service != s.service
    # close() releases the label sets (deploy/stop churn must not grow
    # the registry forever)
    label = s.service
    s.close()
    assert not any(lbl.get("service") == label
                   for lbl, _ in c.samples())
    hist = registry().find("rafiki_tpu_serving_stage_seconds")
    assert hist.count(service=label, stage="fill") == 0


def test_series_remove_matches_label_subset():
    reg = MetricsRegistry()
    c = reg.counter("rafiki_tpu_node_rm_total")
    c.inc(1, service="a", route="/x")
    c.inc(1, service="a", route="/y")
    c.inc(1, service="b", route="/x")
    c.remove(service="a")
    assert c.value(service="a", route="/x") == 0
    assert c.value(service="a", route="/y") == 0
    assert c.value(service="b", route="/x") == 1


def test_trial_gauge_cleared_when_trial_ends():
    """A finished trial's MFU series must not read as live utilization
    forever (TrialRunner removes it in its trial-finally)."""
    g = registry().gauge("rafiki_tpu_train_mfu_ratio")
    g.set(0.5, trial="abcdef123456")
    g.set(0.6, trial="other0000000")
    g.remove(trial="abcdef123456")  # what the runner does
    assert not any(lbl.get("trial") == "abcdef123456"
                   for lbl, _ in g.samples())
    assert g.value(trial="other0000000") == 0.6
    g.remove(trial="other0000000")


# --- Naming convention (tier-1 static check) ---

def test_metric_naming_convention_check_passes():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "scripts", "check_metrics_names.py"),
         REPO_ROOT],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all metric names conform" in proc.stdout


def test_naming_check_catches_violations(tmp_path):
    bad = tmp_path / "rafiki_tpu" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(
        'reg.counter("rafiki_tpu_serving_widgets")\n'        # no unit
        'reg.gauge("rafiki_tpu_mystery_thing_ratio")\n'      # subsystem
        'reg.histogram("rafiki_tpu_bus_wait_seconds")\n')    # ok
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "scripts", "check_metrics_names.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    assert "rafiki_tpu_serving_widgets" in proc.stdout
    assert "rafiki_tpu_mystery_thing_ratio" in proc.stdout
    assert "rafiki_tpu_bus_wait_seconds" not in proc.stdout


# --- Bus instrumentation ---

def test_bus_ops_land_in_histogram():
    from rafiki_tpu.bus import MemoryBus

    h = registry().find("rafiki_tpu_bus_op_seconds")
    bus = MemoryBus()
    before = h.count(backend="memory", op="push", kind="query") if h else 0
    bus.push("q:w9", 1)
    bus.pop("q:w9")
    bus.push_many([("r:abc", 1), ("r:abc", 2)])
    bus.pop_all("r:abc")
    h = registry().find("rafiki_tpu_bus_op_seconds")
    assert h is not None
    assert h.count(backend="memory", op="push", kind="query") == before + 1
    assert h.count(backend="memory", op="push_many", kind="reply") >= 1
    assert h.count(backend="memory", op="pop_all", kind="reply") >= 1


def test_bus_tcp_client_ops_instrumented():
    from rafiki_tpu.bus import BusClient, BusServer

    server = BusServer().start()
    client = BusClient(server.host, server.port)
    try:
        client.push("q:tcp1", {"v": 1})
        assert client.pop("q:tcp1") == {"v": 1}
        # push_many (the serving scatter) must record kind="query"
        # exactly like the memory backend, not "other"
        client.push_many([("q:tcp2", 1), ("q:tcp3", 2)])
        h = registry().find("rafiki_tpu_bus_op_seconds")
        assert h.count(backend="tcp", op="push", kind="query") >= 1
        assert h.count(backend="tcp", op="pop", kind="query") >= 1
        assert h.count(backend="tcp", op="push_many", kind="query") >= 1
    finally:
        client.close()
        server.stop()


# --- Exemplars + exposition hardening (ISSUE r17) ---

def _expose_parse(reg):
    return parse_exposition(reg.expose())


def test_parse_exposition_escaped_label_values_roundtrip():
    """Label values containing ", \\n and \\\\ survive expose -> parse
    exactly (the backslash-run escape scan; a value ENDING in a
    backslash is the case a single-char look-behind gets wrong)."""
    reg = MetricsRegistry()
    c = reg.counter("rafiki_tpu_node_escapes_total")
    values = ['plain', 'has"quote', 'new\nline', 'back\\slash',
              'trailing\\', 'mix\\"both\\', 'a,b{c}d']
    for i, v in enumerate(values):
        c.inc(i + 1, tricky=v)
    parsed = _expose_parse(reg)["rafiki_tpu_node_escapes_total"]
    got = {labels["tricky"]: v for labels, v in parsed}
    assert got == {v: float(i + 1) for i, v in enumerate(values)}


def test_parse_exposition_tolerates_exemplar_annotations():
    from rafiki_tpu.observe.metrics import strip_exemplar

    text = (
        'rafiki_tpu_http_request_seconds_bucket{le="0.25"} 41 '
        '# {trace_id="9f31aa"} 0.187 1754300000.0\n'
        'rafiki_tpu_http_request_seconds_bucket{le="+Inf"} 42 '
        '# {trace_id="9f31aa"} 3.0\n'
        'rafiki_tpu_http_request_seconds_count 42\n'
        # a # INSIDE a quoted value is data, not an annotation
        'rafiki_tpu_node_odd_total{v="a # b"} 7\n')
    out = parse_exposition(text)
    buckets = out["rafiki_tpu_http_request_seconds_bucket"]
    assert [v for _, v in buckets] == [41.0, 42.0]
    assert out["rafiki_tpu_node_odd_total"][0][0]["v"] == "a # b"
    assert strip_exemplar('x{v="a # b"} 7') == 'x{v="a # b"} 7'


def test_histogram_exemplars_record_expose_and_api(monkeypatch):
    from rafiki_tpu.observe import metrics as m
    from rafiki_tpu.observe import trace

    monkeypatch.setenv(m.EXEMPLARS_ENV, "1")
    m.reset_exemplars_for_tests()
    try:
        reg = MetricsRegistry()
        h = reg.histogram("rafiki_tpu_http_request_seconds")
        tid = "ab" * 16
        with trace.use(trace.TraceContext(tid)):
            h.observe(0.003, service="svc", route="/predict")
            h.observe(20.0, service="svc", route="/predict")  # +Inf
        h.observe(0.003, service="svc", route="/other")  # untraced
        ex = h.exemplars(service="svc", route="/predict")
        assert ex["0.005"]["trace_id"] == tid
        assert ex["+Inf"]["trace_id"] == tid
        assert ex["0.005"]["value"] == 0.003
        assert h.exemplars(service="svc", route="/other") == {}
        # Annotations live ONLY in the negotiated OpenMetrics
        # exposition; the classic 0.0.4 text stays clean (a stock
        # Prometheus parser would reject annotated lines).
        text = reg.expose(exemplars=True)
        assert f'# {{trace_id="{tid}"}} 0.003' in text
        assert "trace_id" not in reg.expose()
        # the annotated exposition still parses (bucket values intact)
        parsed = parse_exposition(text)
        buckets = parsed["rafiki_tpu_http_request_seconds_bucket"]
        by_le = {la["le"]: v for la, v in buckets
                 if la.get("route") == "/predict"}
        assert by_le["+Inf"] == 2.0
        # remove() clears the exemplars with the series
        h.remove(service="svc")
        assert h.exemplars(service="svc", route="/predict") == {}
        assert "trace_id" not in reg.expose(exemplars=True)
    finally:
        m.reset_exemplars_for_tests()


def test_metrics_route_exemplars_are_explicit_opt_in(monkeypatch):
    """GET /metrics stays clean classic 0.0.4 text for every scrape —
    including one that NEGOTIATES OpenMetrics via Accept, which stock
    Prometheus does by default — even with exemplars ON; only the
    explicit ?exemplars=1 debug view is annotated."""
    from rafiki_tpu.observe import metrics as m
    from rafiki_tpu.observe import trace
    from rafiki_tpu.utils.service import JsonHttpServer

    monkeypatch.setenv(m.EXEMPLARS_ENV, "1")
    m.reset_exemplars_for_tests()
    server = JsonHttpServer([], host="127.0.0.1",
                            name="exemplar-svc").start()
    try:
        tid = "ef" * 16
        with trace.use(trace.TraceContext(tid)):
            registry().histogram(
                "rafiki_tpu_http_request_seconds").observe(
                    0.004, service="exemplar-svc", route="/x")
        base = f"http://127.0.0.1:{server.port}/metrics"
        classic = requests.get(base, timeout=10)
        assert "version=0.0.4" in classic.headers["Content-Type"]
        assert " # {" not in classic.text
        # a stock-Prometheus-style Accept must NOT flip the format
        neg = requests.get(base, timeout=10, headers={
            "Accept": "application/openmetrics-text; version=1.0.0"})
        assert "version=0.0.4" in neg.headers["Content-Type"]
        assert " # {" not in neg.text
        annotated = requests.get(base + "?exemplars=1", timeout=10)
        assert f'# {{trace_id="{tid}"}}' in annotated.text
        assert parse_exposition(annotated.text)  # still parses
    finally:
        server.stop()
        registry().find("rafiki_tpu_http_request_seconds").remove(
            service="exemplar-svc")
        m.reset_exemplars_for_tests()


def test_exemplars_disabled_by_default(monkeypatch):
    from rafiki_tpu.observe import metrics as m
    from rafiki_tpu.observe import trace

    monkeypatch.delenv(m.EXEMPLARS_ENV, raising=False)
    m.reset_exemplars_for_tests()
    try:
        reg = MetricsRegistry()
        h = reg.histogram("rafiki_tpu_http_request_seconds")
        with trace.use(trace.TraceContext("cd" * 16)):
            h.observe(0.003, service="svc")
        assert h.exemplars(service="svc") == {}
        assert " # {" not in reg.expose()
    finally:
        m.reset_exemplars_for_tests()


def test_exemplars_skip_tail_dropped_traces(tmp_path, monkeypatch):
    """An exemplar must never link a trace whose tail verdict dropped
    its spans (the link would resolve to an empty timeline): pending
    and dropped tail traces are skipped, kept ones qualify."""
    from rafiki_tpu.observe import metrics as m
    from rafiki_tpu.observe import trace

    monkeypatch.setenv(m.EXEMPLARS_ENV, "1")
    monkeypatch.setenv(trace.TRACE_TAIL_SAMPLE_ENV, "0")
    monkeypatch.setenv(trace.TRACE_TAIL_SLOW_MS_ENV, "100")
    m.reset_exemplars_for_tests()
    trace.reset_tail_for_tests()
    trace.configure(str(tmp_path))
    try:
        reg = MetricsRegistry()
        h = reg.histogram("rafiki_tpu_http_request_seconds")
        # Pending: no verdict yet -> no exemplar.
        ctx = trace.start_trace(None)
        assert ctx is not None and ctx.tail
        with trace.use(ctx):
            h.observe(0.001, service="s")
        assert h.exemplars(service="s") == {}
        # Dropped: still no exemplar.
        trace.complete(ctx, 0.001, error=False)
        with trace.use(ctx):
            h.observe(0.001, service="s")
        assert h.exemplars(service="s") == {}
        # Kept (slow): exemplar recorded.
        kept = trace.start_trace(None)
        trace.complete(kept, 0.5, error=False)
        with trace.use(kept):
            h.observe(0.5, service="s")
        ex = h.exemplars(service="s")
        assert any(v["trace_id"] == kept.trace_id for v in ex.values())
    finally:
        trace.configure(None)
        trace.reset_tail_for_tests()
        m.reset_exemplars_for_tests()
