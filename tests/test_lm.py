"""JaxTransformerLM — the flagship causal LM (roofline config model).

No reference counterpart (upstream Rafiki has no LM task — SURVEY.md
§2); the model exists to give the platform a compute-dense training
citizen for the ≥90%-utilization north star. Tests run tiny shapes on
the CPU mesh (the Pallas kernels run in interpreter mode there).
"""

import numpy as np
import pytest

from rafiki_tpu.datasets import make_synthetic_token_dataset
from rafiki_tpu.model.dataset import (load_token_dataset,
                                      write_token_dataset)
from rafiki_tpu.model.logger import logger
from rafiki_tpu.models import JaxTransformerLM

TINY = {"d_model": 256, "n_layers": 2, "seq_len": 256, "batch_size": 4,
        "learning_rate": 1e-2, "train_steps": 200, "vocab_size": 512,
        "quick_train": False}


@pytest.fixture(scope="module")
def token_data(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("lm")
    return make_synthetic_token_dataset(
        str(tmp), n_train=1 << 15, n_val=1 << 12, vocab_size=512,
        branching=2)


def test_token_dataset_roundtrip(tmp_path):
    ids = np.arange(1000, dtype=np.int32) % 64
    path = write_token_dataset(ids, 64, str(tmp_path / "toks"))
    ds = load_token_dataset(path)
    assert ds.vocab_size == 64 and ds.size == 1000
    assert np.array_equal(ds.ids, ids)


def test_token_dataset_rejects_out_of_range(tmp_path):
    path = write_token_dataset(np.asarray([0, 99], np.int32), 64,
                               str(tmp_path / "bad"))
    with pytest.raises(ValueError, match="out of range"):
        load_token_dataset(path)


@pytest.mark.slow
def test_lm_learns_markov_chain(token_data):
    """A branching-2 order-1 chain: a working LM reaches ~1/2 next-token
    accuracy (the chain's ceiling); chance is 1/512. Also covers the
    dump/load roundtrip and the LM-scoring predict contract (a
    chain-consistent continuation must outscore random tokens)."""
    train_path, val_path = token_data
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m.train(train_path)
    acc = m.evaluate(val_path)
    assert acc > 0.35, acc

    params = m.dump_parameters()
    m2 = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m2.load_parameters(params)
    assert abs(m2.evaluate(val_path) - acc) < 1e-6

    ds = load_token_dataset(val_path)
    real = ds.ids[:129].tolist()
    rng = np.random.default_rng(0)
    fake = rng.integers(0, 512, size=129).tolist()
    score_real, score_fake = m2.predict([real, fake])
    assert score_real > score_fake + 1.0, (score_real, score_fake)
    m2.destroy()
    m.destroy()


def test_lm_quick_train_cap(token_data):
    """quick_train caps the step budget (the AutoML trial contract)."""
    train_path, _ = token_data
    knobs = dict(TINY, train_steps=5000, quick_train=True)
    # trial_steps is a FixedKnob (production policy: 30); the cap
    # MECHANISM — min(train_steps, trial_steps) — is what's under
    # test, so override it below validation and keep the 1-core CPU
    # mesh inside the tier-1 wall-clock budget (16 = two fused
    # dispatches at steps_per_dispatch=8, covering the tail-chunk
    # path too).
    m = JaxTransformerLM(**dict(JaxTransformerLM.validate_knobs(knobs),
                                trial_steps=16))
    records = []
    prev = logger.current_sink()
    logger.set_sink(records.append)
    try:
        m.train(train_path)
    finally:
        logger.set_sink(prev)
    steps = [r["values"]["step"] for r in records
             if r.get("type") == "values"
             and "step" in r.get("values", {})]
    assert steps and max(steps) == 16, steps  # capped, not 5000
    assert m.dump_parameters()
    m.destroy()
