"""SLO plane (ISSUE r19): objective grammar, burn-rate math, the
multi-window alert decision table, the engine's scrape-fold, the
autoscaler's SLO pressure signal, the disabled-plane guard, and a
per-tenant p99 objective evaluated end-to-end over a real predictor
frontend's /metrics.
"""

import json
import os
import threading
import time

import pytest
import requests

from rafiki_tpu.admin.autoscaler import (AutoscalePolicy, JobSignals,
                                         JobState, PolicyKnobs)
from rafiki_tpu.admin.slo_engine import SloEngine
from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.observe import attribution as attr
from rafiki_tpu.observe import slo
from rafiki_tpu.observe.metrics import registry

SLO_FAMILIES = ("rafiki_tpu_slo_budget_remaining_ratio",
                "rafiki_tpu_slo_burn_rate",
                "rafiki_tpu_slo_alerts_total")


def _slo_samples():
    out = {}
    for name in SLO_FAMILIES:
        m = registry().find(name)
        out[name] = [] if m is None else m.samples()
    return out


# --- Rules grammar ----------------------------------------------------

def test_inline_grammar_latency_and_ratio():
    objs = slo.parse_rules(
        "p99:p99<50ms,window=60,fast=5,slow=20,burn=2,for=2,resolve=4"
        ";avail:ratio>=0.995,window=120")
    lat, rat = objs
    assert (lat.otype, lat.target, lat.threshold_ms) == \
        ("latency", 0.99, 50.0)
    assert (lat.fast_s, lat.slow_s, lat.for_s, lat.resolve_s) == \
        (5.0, 20.0, 2.0, 4.0)
    assert rat.otype == "ratio" and rat.target == 0.995
    assert lat.source_metric() == "rafiki_tpu_http_request_seconds"
    assert rat.source_metric() == "rafiki_tpu_serving_requests_total"


def test_inline_defaults_follow_window():
    o = slo.parse_rules("x:p95<10ms,window=100")[0]
    assert (o.fast_s, o.slow_s, o.resolve_s) == (20.0, 100.0, 20.0)
    # fractional quantiles parse (p99.9 -> 0.999)
    o = slo.parse_rules("y:p99.9<5ms")[0]
    assert o.target == pytest.approx(0.999)


@pytest.mark.parametrize("bad", [
    "x:p99<50",                      # spec missing ms
    "x:p99<50ms,bogus=1",            # unknown key
    "x:p99<50ms,window=1,window=2",  # duplicate key
    "x:ratio>=1.5",                  # target out of range
    "x:p99<50ms,scope=cluster",      # unknown scope
    "y:ratio>=0.9,scope=bin",        # ratio is job-scope only
    # ratio reads a counter PAIR: a single metric override would be
    # silently half-applied — rejected instead
    "z:ratio>=0.9,metric=rafiki_tpu_serving_requests_total",
    "x:p99<50ms,fast=30,slow=10",    # fast > slow
    "a:p99<1ms;a:p99<2ms",           # duplicate name
    "nospec",                        # not name:spec
])
def test_inline_grammar_rejects_loudly(bad):
    with pytest.raises(ValueError):
        slo.parse_rules(bad)


def test_rules_file_json_and_missing(tmp_path):
    path = tmp_path / "rules.json"
    path.write_text(json.dumps({"objectives": [
        {"name": "p99", "type": "latency", "target": 0.99,
         "threshold_ms": 50, "scope": "tenant", "window_s": 60,
         "fast_window_s": 5, "slow_window_s": 30}]}))
    [o] = slo.parse_rules(str(path))
    assert o.scope == "tenant" and o.fast_s == 5.0
    assert o.source_metric() == \
        "rafiki_tpu_serving_tenant_request_seconds"
    with pytest.raises(ValueError):
        slo.parse_rules(str(tmp_path / "absent.json"))
    path.write_text("{not json")
    with pytest.raises(ValueError):
        slo.parse_rules(str(path))
    # unknown fields in a file are rejected like unknown inline keys
    path.write_text(json.dumps({"objectives": [
        {"name": "x", "type": "latency", "target": 0.9,
         "threshold_ms": 5, "burn": 2}]}))
    with pytest.raises(ValueError):
        slo.parse_rules(str(path))


def test_committed_example_rules_parse():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    objs = slo.parse_rules(os.path.join(repo, "docs", "slo",
                                        "serving.json"))
    assert {o.scope for o in objs} == {"job", "bin", "tenant"}
    assert any(o.otype == "ratio" for o in objs)


def test_nodeconfig_validates_rules_and_exports():
    from rafiki_tpu.config import NodeConfig

    with pytest.raises(ValueError):
        NodeConfig(slo_rules="x:nope").validate()
    cfg = NodeConfig(slo_rules="x:p99<10ms").validate()
    prior = {k: os.environ.get(k) for k in
             ("RAFIKI_TPU_SLO_RULES", "RAFIKI_TPU_SLO_WEBHOOK_URL")}
    try:
        cfg.apply_env()
        assert os.environ["RAFIKI_TPU_SLO_RULES"] == "x:p99<10ms"
        assert "RAFIKI_TPU_SLO_WEBHOOK_URL" not in os.environ
        NodeConfig().validate().apply_env()
        assert "RAFIKI_TPU_SLO_RULES" not in os.environ
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --- Burn-rate math on seeded synthetic series ------------------------

def test_good_total_interpolates_like_bucket_percentile():
    cum = [(0.01, 10), (0.05, 40), (float("inf"), 50)]
    good, total = slo.good_total_from_deltas(cum, 0.03)
    assert total == 50 and good == pytest.approx(25.0)
    # exactly on a bound: the bound's cumulative count
    assert slo.good_total_from_deltas(cum, 0.05)[0] == 40
    # beyond the last finite bound: +Inf events count bad
    assert slo.good_total_from_deltas(cum, 10.0)[0] == 40
    assert slo.good_total_from_deltas([], 0.1) == (0.0, 0.0)
    assert slo.good_total_from_deltas([(0.1, 0), (float("inf"), 0)],
                                      0.05) == (0.0, 0.0)


def test_window_ring_burn_and_budget():
    ring = slo.WindowRing(horizon_s=100)
    # seeded series: 10 sweeps, 100 events each; sweeps 6..9 are 50%
    # bad, earlier ones clean.
    for t in range(6):
        ring.add(float(t), 100, 100)
    for t in range(6, 10):
        ring.add(float(t), 50, 100)
    budget = 0.01  # target 0.99
    t = 9.0
    # fast window (last 2 sweeps at t=8,9): all-bad-half => 50%/1%
    assert ring.burn_rate(t, 1.5, budget) == pytest.approx(50.0)
    # full window: 200 bad / 1000 events = 20% bad -> burn 20
    assert ring.burn_rate(t, 100, budget) == pytest.approx(20.0)
    assert ring.budget_remaining(t, 100, budget) == 0.0
    # clean series: burn 0, budget untouched
    clean = slo.WindowRing(100)
    clean.add(0.0, 100, 100)
    assert clean.burn_rate(0.0, 10, budget) == 0.0
    assert clean.budget_remaining(0.0, 10, budget) == 1.0
    # a light burn leaves a proportional budget
    light = slo.WindowRing(100)
    light.add(0.0, 998, 1000)  # 0.2% bad of a 1% budget
    assert light.budget_remaining(0.0, 10, budget) == \
        pytest.approx(0.8)


# --- Alert decision table ---------------------------------------------

def _obj(**kw):
    kw.setdefault("name", "o")
    kw.setdefault("otype", "latency")
    kw.setdefault("target", 0.99)
    kw.setdefault("threshold_ms", 50.0)
    kw.setdefault("window_s", 300.0)
    kw.setdefault("fast_s", 5.0)
    kw.setdefault("slow_s", 30.0)
    kw.setdefault("burn", 2.0)
    return slo.Objective(**kw).validate()


def test_alert_pending_firing_resolved_lifecycle():
    obj = _obj(for_s=2.0, resolve_s=4.0)
    m = slo.AlertMachine()
    assert m.update(0.0, 3.0, 3.0, obj) == "pending"
    assert m.update(1.0, 3.0, 3.0, obj) is None      # for_s not met
    assert m.update(2.0, 3.0, 3.0, obj) == "firing"
    assert m.state == "firing"
    assert m.update(3.0, 1.0, 3.0, obj) is None      # quiet starts
    assert m.update(6.9, 1.0, 3.0, obj) is None      # resolve_s not met
    assert m.update(7.0, 1.0, 3.0, obj) == "resolved"
    assert m.state == "ok"


def test_alert_pending_clears_without_firing():
    obj = _obj(for_s=5.0)
    m = slo.AlertMachine()
    assert m.update(0.0, 3.0, 3.0, obj) == "pending"
    assert m.update(1.0, 1.0, 3.0, obj) == "cleared"
    assert m.state == "ok"


def test_alert_needs_both_windows_and_fires_immediately_at_for_zero():
    obj = _obj(for_s=0.0)
    m = slo.AlertMachine()
    # fast alone breaching (a blip the slow window absorbs) never arms
    assert m.update(0.0, 9.0, 0.5, obj) is None
    assert m.update(1.0, 0.5, 9.0, obj) is None
    assert m.state == "ok"
    assert m.update(2.0, 9.0, 9.0, obj) == "firing"


def test_alert_flap_guard():
    """Oscillation around the threshold changes nothing: while firing,
    a fast window that dips below threshold for LESS than resolve_s
    never resolves; the quiet clock restarts on each re-breach."""
    obj = _obj(for_s=0.0, resolve_s=5.0)
    m = slo.AlertMachine()
    assert m.update(0.0, 9.0, 9.0, obj) == "firing"
    for t in range(1, 20):  # alternate below/above every second
        tr = m.update(float(t), 0.5 if t % 2 else 9.0, 9.0, obj)
        assert tr is None, (t, tr)
    assert m.state == "firing"
    # sustained quiet resolves exactly once
    transitions = [m.update(20.0 + dt, 0.5, 9.0, obj)
                   for dt in (0.0, 2.0, 5.0, 6.0)]
    assert transitions == [None, None, "resolved", None]


# --- Engine: scrape-fold, scoping, pruning ----------------------------

class _Meta:
    def __init__(self, jobs):
        self.jobs = jobs

    def get_inference_jobs(self, status=None):
        return self.jobs


class _Services:
    log_dir = ""


def _engine(rules, jobs, monkeypatch, feed):
    objectives = slo.parse_rules(rules)
    eng = SloEngine(_Services(), _Meta(jobs), objectives)
    monkeypatch.setattr(
        SloEngine, "_scrape",
        lambda self, host, path:
        {"service": "svc1", "http_service": "http1"}
        if path == "/stats" else feed["text"])
    return eng


def _http_expo(per_le):
    lines = []
    for le, cum in per_le:
        lines.append(
            f'rafiki_tpu_http_request_seconds_bucket{{le="{le}",'
            f'route="/predict",service="http1"}} {cum}')
    return "\n".join(lines) + "\n"


def test_engine_latency_job_scope_fires_and_publishes(monkeypatch):
    feed = {"text": _http_expo([("0.025", 0), ("+Inf", 0)])}
    eng = _engine("p99:p99<25ms,window=30,fast=5,slow=10,burn=1,for=0,"
                  "resolve=3600", [{"id": "j1" * 6,
                                    "predictor_host": "x:1"}],
                  monkeypatch, feed)
    try:
        assert eng.sweep() == []  # basis
        feed["text"] = _http_expo([("0.025", 100), ("+Inf", 100)])
        assert eng.sweep() == []  # healthy
        g = registry().find("rafiki_tpu_slo_budget_remaining_ratio")
        assert g.value(objective="p99", job=("j1" * 6)[:8]) == 1.0
        feed["text"] = _http_expo([("0.025", 100), ("+Inf", 200)])
        [tr] = eng.sweep()        # 100 new events, all bad
        assert tr["transition"] == "firing"
        # both sweeps land inside the 5 s fast window (the test runs
        # in ms): 100 bad of 200 events over a 1% budget = burn 50.
        b = registry().find("rafiki_tpu_slo_burn_rate")
        assert b.value(objective="p99", job=("j1" * 6)[:8],
                       window="fast") == pytest.approx(50.0)
        c = registry().find("rafiki_tpu_slo_alerts_total")
        assert c.value(objective="p99", state="firing") == 1
        assert eng.slo_pressure("j1" * 6) == ""
        assert eng.alerts_snapshot()["firing"] == ["p99"]
        snap = eng.snapshot()
        [inst] = snap["objectives"][0]["instances"]
        assert inst["state"] == "firing"
        assert inst["budget_remaining"] < 1.0
    finally:
        eng.close()
    assert all(s == [] for s in _slo_samples().values())


def test_engine_counter_reset_rebases(monkeypatch):
    feed = {"text": _http_expo([("0.025", 0), ("+Inf", 0)])}
    eng = _engine("p99:p99<25ms,window=30,fast=5,slow=10,burn=1,for=0",
                  [{"id": "j2" * 6, "predictor_host": "x:1"}],
                  monkeypatch, feed)
    try:
        eng.sweep()
        feed["text"] = _http_expo([("0.025", 0), ("+Inf", 50)])
        eng.sweep()  # 50 bad events — would fire next breach
        # a restarted frontend resets the cumulative counts BELOW the
        # basis: the sweep must re-base, not fold a negative delta
        feed["text"] = _http_expo([("0.025", 10), ("+Inf", 10)])
        assert eng.sweep() == []
        feed["text"] = _http_expo([("0.025", 30), ("+Inf", 30)])
        assert eng.sweep() == []  # 20 good events on the new basis
    finally:
        eng.close()


def test_engine_ratio_objective(monkeypatch):
    def expo(req, rej):
        return (f'rafiki_tpu_serving_requests_total{{service="svc1"}}'
                f' {req}\n'
                f'rafiki_tpu_serving_rejected_total{{service="svc1"}}'
                f' {rej}\n')

    feed = {"text": expo(0, 0)}
    eng = _engine("avail:ratio>=0.9,window=30,fast=5,slow=10,burn=1,"
                  "for=0,resolve=3600",
                  [{"id": "j3" * 6, "predictor_host": "x:1"}],
                  monkeypatch, feed)
    try:
        eng.sweep()
        feed["text"] = expo(100, 0)
        assert eng.sweep() == []          # all admitted
        feed["text"] = expo(150, 50)      # 50% rejected this sweep
        [tr] = eng.sweep()
        assert tr["transition"] == "firing"
        # ratio objectives are not latency pressure for the autoscaler
        assert eng.slo_pressure("j3" * 6) is None
    finally:
        eng.close()


def test_engine_bin_and_tenant_scopes_make_per_label_instances(
        monkeypatch):
    job_id = "abcdef012345xyz"

    def expo(bins, tenants):
        lines = []
        for b, (good, bad) in bins.items():
            for le, cum in (("0.025", good), ("+Inf", good + bad)):
                lines.append(
                    f'rafiki_tpu_serving_bin_device_seconds_bucket'
                    f'{{job="{job_id[:12]}",bin="{b}",le="{le}"}} '
                    f'{cum}')
        for t, (good, bad) in tenants.items():
            for le, cum in (("0.025", good), ("+Inf", good + bad)):
                lines.append(
                    f'rafiki_tpu_serving_tenant_request_seconds_bucket'
                    f'{{service="svc1",tenant="{t}",le="{le}"}} {cum}')
        # ANOTHER job's co-resident frontend shares the process
        # registry: its tenant series must NOT fold into this job's
        # instances (the service-label filter).
        lines.append(
            'rafiki_tpu_serving_tenant_request_seconds_bucket'
            '{service="other-svc",tenant="intruder",le="+Inf"} 500')
        return "\n".join(lines) + "\n"

    feed = {"text": expo({"binA": (0, 0), "binB": (0, 0)},
                         {"t1": (0, 0)})}
    eng = _engine(
        "bin-p99:p99<25ms,scope=bin,window=30,fast=5,slow=10,burn=1,"
        "for=0,resolve=3600;"
        "ten-p99:p99<25ms,scope=tenant,window=30,fast=5,slow=10,"
        "burn=1,for=0,resolve=3600",
        [{"id": job_id, "predictor_host": "x:1"}], monkeypatch, feed)
    try:
        eng.sweep()
        # binB and tenant t1 go bad; binA stays clean
        feed["text"] = expo({"binA": (100, 0), "binB": (0, 100)},
                            {"t1": (0, 50)})
        transitions = eng.sweep()
        assert {(t["objective"], tuple(sorted(t["labels"].items())))
                for t in transitions} == {
            ("bin-p99", (("bin", "binB"), ("job", job_id[:8]))),
            ("ten-p99", (("job", job_id[:8]), ("tenant", "t1")))}
        # the violating BIN is the autoscaler's pressure target
        assert eng.slo_pressure(job_id) == "binB"
        # the other frontend's tenant never became an instance here
        assert not any(i["labels"].get("tenant") == "intruder"
                       for o in eng.snapshot()["objectives"]
                       for i in o["instances"])
        g = registry().find("rafiki_tpu_slo_budget_remaining_ratio")
        assert g.value(objective="bin-p99", job=job_id[:8],
                       bin="binA") == 1.0
        assert g.value(objective="bin-p99", job=job_id[:8],
                       bin="binB") == 0.0
    finally:
        eng.close()


def test_engine_prunes_departed_jobs_and_their_gauges(monkeypatch):
    feed = {"text": _http_expo([("0.025", 0), ("+Inf", 0)])}
    meta = _Meta([{"id": "j4" * 6, "predictor_host": "x:1"}])
    objectives = slo.parse_rules("p99:p99<25ms,window=30,fast=5,"
                                 "slow=10")
    eng = SloEngine(_Services(), meta, objectives)
    monkeypatch.setattr(
        SloEngine, "_scrape",
        lambda self, host, path:
        {"service": "svc1", "http_service": "http1"}
        if path == "/stats" else feed["text"])
    try:
        eng.sweep()
        feed["text"] = _http_expo([("0.025", 10), ("+Inf", 10)])
        eng.sweep()
        g = registry().find("rafiki_tpu_slo_budget_remaining_ratio")
        assert g.samples() != []
        meta.jobs = []  # job stopped
        eng.sweep()
        assert g.samples() == []
    finally:
        eng.close()


def test_alert_sink_jsonl_and_webhook(monkeypatch, tmp_path):
    hits = []

    class _Handler:
        pass

    from rafiki_tpu.utils.service import JsonHttpServer

    server = JsonHttpServer(
        [("POST", "/hook",
          lambda params, body, ctx: (hits.append(body) or
                                     (200, {"ok": True})))],
        host="127.0.0.1", name="hook").start()
    try:
        feed = {"text": _http_expo([("0.025", 0), ("+Inf", 0)])}

        class _Svc:
            log_dir = str(tmp_path)

        objectives = slo.parse_rules(
            "p99:p99<25ms,window=30,fast=5,slow=10,burn=1,for=0,"
            "resolve=3600")
        eng = SloEngine(_Svc(), _Meta([{"id": "j5" * 6,
                                        "predictor_host": "x:1"}]),
                        objectives,
                        webhook_url=f"http://127.0.0.1:{server.port}"
                                    f"/hook")
        monkeypatch.setattr(
            SloEngine, "_scrape",
            lambda self, host, path:
            {"service": "svc1", "http_service": "http1"}
            if path == "/stats" else feed["text"])
        try:
            eng.sweep()
            feed["text"] = _http_expo([("0.025", 0), ("+Inf", 100)])
            [tr] = eng.sweep()
            assert tr["transition"] == "firing"
            log = (tmp_path / "alerts.jsonl").read_text().splitlines()
            assert json.loads(log[-1])["transition"] == "firing"
            # the webhook rides a sender thread OFF the supervise
            # thread (a slow pager must not stall the sweep): poll
            deadline = time.time() + 10
            while not hits and time.time() < deadline:
                time.sleep(0.05)
            assert hits and hits[0]["objective"] == "p99"
            assert hits[0]["trace_id"]
        finally:
            eng.close()
    finally:
        server.stop()


# --- Autoscaler consumption -------------------------------------------

def test_policy_slo_firing_outranks_queue_signals():
    p = AutoscalePolicy(PolicyKnobs(up_cooldown_s=0.0))
    # a dead-idle queue still scales up while the SLO fires
    sig = JobSignals(queue_depth=0, queue_cap=100, slo_firing="")
    out = p.decide(sig, {"a": 1, "b": 2}, JobState(), now=0.0)
    assert [(d.action, d.bin, d.reason) for d in out] == \
        [("scale_up", "a", "slo_firing")]
    # classify: slo wins over backpressure's reason
    sig2 = JobSignals(queue_depth=0, queue_cap=100,
                      backpressure_delta=5, slo_firing="")
    assert p.classify(sig2) == ("up", "slo_firing")
    # no firing alert -> unchanged legacy behavior
    sig3 = JobSignals(queue_depth=0, queue_cap=100)
    assert p.classify(sig3)[0] == "down"


def test_policy_slo_bin_scoped_alert_targets_violating_bin():
    p = AutoscalePolicy(PolicyKnobs(up_cooldown_s=0.0))
    # "hot" has FEWER replicas (the legacy first pick) but the alert
    # names "cold2" — the violating bin takes the capacity.
    sig = JobSignals(queue_depth=0, queue_cap=100, slo_firing="cold2")
    out = p.decide(sig, {"hot": 1, "cold2": 2}, JobState(), now=0.0)
    assert [(d.action, d.bin) for d in out] == [("scale_up", "cold2")]
    # an alert naming an unknown bin degrades to the legacy order
    sig2 = JobSignals(queue_depth=0, queue_cap=100, slo_firing="gone")
    out = p.decide(sig2, {"hot": 1, "cold2": 2}, JobState(), now=0.0)
    assert out[0].bin == "hot"


def test_policy_slo_firing_respects_cooldown_and_ceiling():
    p = AutoscalePolicy(PolicyKnobs(up_cooldown_s=10.0,
                                    max_replicas=2))
    sig = JobSignals(queue_depth=0, queue_cap=100, slo_firing="")
    state = JobState()
    state.last_up_mono = 0.0
    assert p.decide(sig, {"a": 1}, state, now=5.0) == []
    assert p.decide(sig, {"a": 2}, state, now=20.0) == []  # ceiling
    assert p.decide(sig, {"a": 1}, state, now=20.0)


# --- Disabled plane + platform wiring ---------------------------------

def test_disabled_plane_zero_series_and_supervise_unchanged(tmp_path,
                                                            monkeypatch):
    monkeypatch.delenv("RAFIKI_TPU_SLO_RULES", raising=False)
    from rafiki_tpu.platform import LocalPlatform

    plat = LocalPlatform(workdir=str(tmp_path / "p"),
                         supervise_interval=0)
    try:
        assert plat.slo_engine is None
        assert plat.services.slo_engine is None
        assert plat.services.supervise() == []
        assert all(s == [] for s in _slo_samples().values())
        assert plat.admin.get_slo() == {"enabled": False}
        assert plat.admin.get_alerts() == {"enabled": False}
    finally:
        plat.shutdown()


def test_platform_constructs_engine_from_env_and_serves_routes(
        tmp_path, monkeypatch):
    monkeypatch.setenv("RAFIKI_TPU_SLO_RULES",
                       "p99:p99<50ms,window=60,fast=5,slow=30")
    from rafiki_tpu.platform import LocalPlatform

    plat = LocalPlatform(workdir=str(tmp_path / "p"), http=True,
                         supervise_interval=0)
    try:
        assert plat.slo_engine is not None
        assert plat.services.slo_engine is plat.slo_engine
        assert [o.name for o in plat.slo_engine.objectives] == ["p99"]
        # supervise drives the sweep (no jobs: epoch still advances)
        plat.services.supervise()
        assert plat.slo_engine.epoch == 1
        token = plat.admin.authenticate(
            "superadmin@rafiki", "rafiki")["token"]
        headers = {"Authorization": f"Bearer {token}"}
        r = requests.get(
            f"http://127.0.0.1:{plat.admin_port}/slo",
            headers=headers, timeout=10).json()
        assert r["enabled"] and r["objectives"][0]["name"] == "p99"
        r = requests.get(
            f"http://127.0.0.1:{plat.admin_port}/alerts",
            headers=headers, timeout=10).json()
        assert r["enabled"] and r["alerts"] == []
    finally:
        plat.shutdown()
    # close() ran: no stale slo series survive the platform
    assert all(s == [] for s in _slo_samples().values())


# --- Per-tenant p99 end-to-end over a real frontend -------------------

class _EchoWorker:
    """Bus-level inference worker echoing predictions instantly."""

    def __init__(self, bus, worker_id="w1", job_id="job"):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.job_id = job_id
        self.stop_flag = threading.Event()
        self.cache.register_worker(job_id, worker_id,
                                   info={"trial_id": "t1"})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            attr.extract_frames_tenants(items)
            for it in items:
                if "queries" not in it:
                    continue
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [[float(q), 0.0] for q in it["queries"]],
                    shard=it.get("shard"))

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


def test_tenant_p99_objective_end_to_end(monkeypatch):
    """The r17 carry 'tenant-labeled p99 SLO tracking', closed: real
    requests under a client header land in the tenant latency
    histogram, and a tenant-scoped objective scraping the REAL
    /metrics over HTTP evaluates per tenant hash — breaching for the
    tight threshold, healthy for the loose one."""
    from rafiki_tpu.predictor.app import PredictorService

    monkeypatch.setenv(attr.ATTRIBUTION_ENV, "1")
    attr.reset_for_tests()
    bus = MemoryBus()
    worker = _EchoWorker(bus)
    svc = PredictorService("slosvc", "job", meta=None, bus=bus,
                           host="127.0.0.1", client_header="X-Client")
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    svc.batcher.start()
    svc._http.start()
    eng = None
    try:
        url = f"http://127.0.0.1:{svc.port}/predict"
        for _ in range(8):
            r = requests.post(url, json={"queries": [1, 2]},
                              headers={"X-Client": "alice"},
                              timeout=30)
            assert r.status_code == 200
        t = attr.tenant_key("alice")
        h = registry().find("rafiki_tpu_serving_tenant_request_seconds")
        assert h.count(tenant=t, service=svc.stats.service) == 8

        # a sub-microsecond threshold every real request breaches, and
        # a 100 s threshold none does — one engine, two objectives
        objectives = slo.parse_rules(
            "tight:p99<0.001ms,scope=tenant,window=30,fast=5,slow=10,"
            "burn=1,for=0,resolve=3600;"
            "loose:p99<100000ms,scope=tenant,window=30,fast=5,slow=10,"
            "burn=1,for=0,resolve=3600")
        eng = SloEngine(_Services(),
                        _Meta([{"id": "jobe2e",
                                "predictor_host":
                                    f"127.0.0.1:{svc.port}"}]),
                        objectives)
        eng.sweep()  # basis (scrapes the real /metrics over HTTP)
        for _ in range(8):
            requests.post(url, json={"queries": [1]},
                          headers={"X-Client": "alice"}, timeout=30)
        transitions = eng.sweep()
        assert [(tr["objective"], tr["transition"])
                for tr in transitions] == [("tight", "firing")]
        [inst] = [i for o in eng.snapshot()["objectives"]
                  if o["name"] == "tight" for i in o["instances"]]
        assert inst["labels"]["tenant"] == t
        assert inst["state"] == "firing"
        [linst] = [i for o in eng.snapshot()["objectives"]
                   if o["name"] == "loose" for i in o["instances"]]
        assert linst["state"] == "ok"
        assert linst["budget_remaining"] == 1.0
    finally:
        if eng is not None:
            eng.close()
        svc._http.stop()
        svc.batcher.stop()
        svc.stats.close()
        svc.predictor.close()
        worker.stop()
        attr.reset_for_tests()
        for fam in ("rafiki_tpu_serving_tenant_request_seconds",
                    "rafiki_tpu_serving_tenant_requests_total",
                    "rafiki_tpu_serving_bin_queries_total",
                    "rafiki_tpu_serving_bin_queue_seconds_total"):
            m = registry().find(fam)
            if m is not None:
                m.remove()
