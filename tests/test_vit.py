"""JaxViT: Vision Transformer zoo model with traced depth mask."""

import numpy as np
import pytest

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import load_image_dataset, test_model_class
from rafiki_tpu.models import JaxViT

KNOBS = {"depth": 3, "learning_rate": 1e-3, "batch_size": 64,
         "weight_decay": 1e-4, "max_epochs": 10, "early_stop_epochs": 5}


@pytest.mark.slow
def test_vit_end_to_end(synth_image_data):
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(2)]
    result = test_model_class(
        JaxViT, TaskType.IMAGE_CLASSIFICATION, train_path, val_path,
        test_queries=queries, knobs=KNOBS)
    assert result.score > 0.5  # 4 classes; chance is 0.25
    for pred in result.predictions:
        assert len(pred) == ds.n_classes
        assert abs(sum(pred) - 1.0) < 1e-3


@pytest.mark.slow
def test_vit_depth_mask_shares_one_executable(synth_image_data):
    """Different depth knobs reuse the SAME compiled train step (depth
    rides extra_apply_inputs as a traced block mask)."""
    train_path, _ = synth_image_data
    from rafiki_tpu.model.jax_model import _STEP_CACHE, clear_step_cache

    clear_step_cache()
    base = dict(KNOBS, max_epochs=1, early_stop_epochs=0)
    m1 = JaxViT(**dict(base, depth=2))
    m1.train(train_path)
    n_after_first = len(_STEP_CACHE)
    m1.destroy()
    m2 = JaxViT(**dict(base, depth=5))
    m2.train(train_path)
    assert len(_STEP_CACHE) == n_after_first, (
        "depth change recompiled the train step")
    m2.destroy()


def test_vit_depth_mask_is_identity_for_masked_blocks(synth_image_data):
    """A masked block is exactly the identity: the full supernet with
    depth mask d equals a module TRUNCATED to d blocks running the same
    (sliced) parameters."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models.vit import MAX_DEPTH, _ViT

    d = 2
    module = _ViT(n_classes=4, d_model=32, n_heads=2, patch=4,
                  n_tokens=1 + 9)
    rng = jax.random.key(0)
    x = jnp.asarray(np.random.default_rng(0).random((2, 12, 12, 1)),
                    jnp.float32)
    v = module.init(rng, x, depth=jnp.ones((MAX_DEPTH,)))
    masked = module.apply(v, x, depth=jnp.asarray(
        (np.arange(MAX_DEPTH) < d).astype(np.float32)))

    truncated = _ViT(n_classes=4, d_model=32, n_heads=2, patch=4,
                     n_tokens=1 + 9, max_depth=d)
    keep = {"Conv_0", "cls", "pos_embed", "LayerNorm_0", "Dense_0"} | {
        f"_EncoderBlock_{i}" for i in range(d)}
    v_trunc = {"params": {k: v["params"][k] for k in keep}}
    exact = truncated.apply(v_trunc, x, depth=jnp.ones((d,)))
    np.testing.assert_allclose(np.asarray(masked), np.asarray(exact),
                               atol=1e-5, rtol=1e-5)
    # And the mask genuinely changes the function vs full depth.
    full = module.apply(v, x, depth=jnp.ones((MAX_DEPTH,)))
    assert not np.allclose(np.asarray(full), np.asarray(masked))


def test_vit_rejects_indivisible_patch():
    m = JaxViT(**JaxViT.validate_knobs(dict(KNOBS, depth=2)))
    with pytest.raises(ValueError, match="divisible"):
        m.create_module(4, (13, 13, 1))
