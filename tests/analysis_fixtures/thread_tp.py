"""True positives for RTA2xx: a thread neither daemonized nor joined,
and an executor the class never shuts down."""

import threading
from concurrent.futures import ThreadPoolExecutor


class WedgesOnExit:
    def start(self):
        self._worker = threading.Thread(target=self._run)  # <- RTA201
        self._worker.start()

    def _run(self):
        pass


class LeakedPool:
    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1)     # <- RTA202

    def submit(self, fn):
        return self._pool.submit(fn)
