"""False-positive guards for RTA4xx. NO findings expected: only the
per-call train state is donated, cache arrays ride non-donated
positions, and every donated name is rebound by its call (the
``state, m = step(state, ...)`` idiom) — including inside a loop."""

from functools import partial

import jax

_STAGE_CACHE = {}


def staged_dataset_arrays(key):
    return _STAGE_CACHE[key]


@partial(jax.jit, donate_argnums=(0,))
def train_chunk(state, data, sels):
    return state, 0.0


def dispatch(state, data, sels):
    exe = train_chunk
    return exe(state, data, sels)


def train(key, steps):
    data_dev, labels_dev = staged_dataset_arrays(key)
    state = object()
    for _ in range(steps):
        # cache arrays at NON-donated positions; state rebound by the
        # same statement that donates it.
        state, loss = dispatch(state, data_dev, labels_dev)
    return state, loss
