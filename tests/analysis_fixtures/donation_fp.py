"""False-positive guards for RTA4xx. NO findings expected: only the
per-call train state is donated, cache arrays ride non-donated
positions, and every donated name is rebound by its call (the
``state, m = step(state, ...)`` idiom) — including inside a loop."""

from functools import partial

import jax

_STAGE_CACHE = {}


def staged_dataset_arrays(key):
    return _STAGE_CACHE[key]


@partial(jax.jit, donate_argnums=(0,))
def train_chunk(state, data, sels):
    return state, 0.0


def dispatch(state, data, sels):
    exe = train_chunk
    return exe(state, data, sels)


def train(key, steps):
    data_dev, labels_dev = staged_dataset_arrays(key)
    state = object()
    for _ in range(steps):
        # cache arrays at NON-donated positions; state rebound by the
        # same statement that donates it.
        state, loss = dispatch(state, data_dev, labels_dev)
    return state, loss


_BUFS = {}


def staging_buffer(bucket, shape):
    """The r13 predict_into shape: the helper's NAME matches the taint
    regex ("staging"), so its callers are tainted — but the buffer only
    ever rides NON-donated predict calls, which must not fire."""
    buf = _BUFS.get((bucket, shape))
    if buf is None:
        buf = _BUFS[(bucket, shape)] = bytearray(bucket)
    return buf


def fresh_rows(bucket, shape):
    """Neutral name + return-taint via the staging helper: tainted by
    the r13 pass, also only ever at non-donated positions."""
    return staging_buffer(bucket, shape)


def predict_staged(state, bucket):
    buf = fresh_rows(bucket, (8, 8))
    out = train_chunk(state, buf, [0])  # buf at NON-donated position 1
    return out
