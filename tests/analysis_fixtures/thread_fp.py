"""False-positive guards for RTA2xx: daemonized, joined (directly and
via the loop-over-a-tuple idiom), daemon-assigned-later, local joined
threads, and a shut-down executor. NO findings expected."""

import threading
from concurrent.futures import ThreadPoolExecutor


class DaemonThread:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        pass


class JoinedPair:
    """The micro-batcher pattern: two threads joined from stop() via a
    loop over a tuple."""

    def __init__(self):
        self._batcher = threading.Thread(target=self._run)
        self._gatherer = threading.Thread(target=self._run)
        self._batcher.daemon = True
        self._gatherer.daemon = True

    def _run(self):
        pass

    def stop(self):
        for t in (self._batcher, self._gatherer):
            if t.is_alive():
                t.join(timeout=5.0)


class DirectJoin:
    def start(self):
        self._writer = threading.Thread(target=self._run)
        self._writer.start()

    def _run(self):
        pass

    def close(self):
        self._writer.join(timeout=10.0)


class ShutdownPool:
    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1)

    def close(self):
        self._pool.shutdown(wait=True)


def scoped_worker():
    t = threading.Thread(target=print)
    t.start()
    t.join()
