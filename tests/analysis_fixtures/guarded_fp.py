"""False-positive guards for RTA1xx: everything here is correct and
must produce NO findings.

Covers the repo's real idioms: __init__ publication, the
caller-holds-the-lock private helper, Condition.wait under the lock,
atomic primitives (Event/Queue), sequential (non-nested) lock use, and
the snapshot-under-lock-act-outside pattern.
"""

import queue
import threading
import time


class ProperlyGuarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._stop = threading.Event()       # atomic: never "guarded"
        self._inbox = queue.Queue()          # atomic: never "guarded"
        self._items = []
        self._depth = 0

    def put(self, item):
        with self._cond:
            self._items.append(item)
            self._depth += 1
            self._cond.notify_all()

    def take(self):
        with self._cond:
            while not self._items:
                if self._stop.is_set():      # Event read: fine anywhere
                    return None
                self._cond.wait(0.1)         # Condition.wait releases
            return self._drain_locked()

    def _drain_locked(self):
        # Private helper: every call site holds _cond, so touching
        # _items/_depth here is correct (the _drain_into pattern).
        out = list(self._items)
        self._items.clear()
        self._depth = 0
        return out

    def snapshot_then_act(self):
        with self._cond:
            snapshot = list(self._items)
        # Blocking work AFTER release — correct, must not be RTA102.
        time.sleep(0.01)
        return snapshot

    def stop(self):
        self._stop.set()                     # atomic; no lock needed
        self._inbox.put(None)                # queue is thread-safe


class SequentialLocks:
    """Takes two locks one AFTER the other (never nested): no ordering
    edge, no cycle."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._x = 0
        self._y = 0

    def both(self):
        with self._a:
            self._x += 1
        with self._b:
            self._y += 1

    def both_reversed(self):
        with self._b:
            self._y -= 1
        with self._a:
            self._x -= 1


class ReentrantHelper:
    """RLock re-acquisition is legal — must not be RTA103."""

    def __init__(self):
        self._lock = threading.RLock()
        self._rows = []

    def insert(self, row):
        with self._lock:
            self._insert_locked(row)

    def _insert_locked(self, row):
        with self._lock:
            self._rows.append(row)


class ForeignConditionWaiter:
    """Waiting on a COLLABORATOR's condition releases it — the same
    release-and-wait idiom as an own-lock wait; must not be RTA102
    (review-fix regression: foreign lock tokens enter the held set,
    and the wait exemption must follow them)."""

    def __init__(self, owner):
        self._lock = threading.Lock()
        self.owner = owner
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def wait_owner(self):
        with self.owner._cond:
            self.owner._cond.wait()


# --- module-global discipline (whole-program arm of RTA101) ----------

_MOD_LOCK = threading.Lock()
_mod_shared = 0
_mod_bare = 0


def mod_inc():
    global _mod_shared
    with _MOD_LOCK:
        _mod_shared += 1


def mod_read():
    with _MOD_LOCK:
        return _mod_shared


def mod_bump_bare():
    """No lock discipline on ``_mod_bare`` anywhere — consistently
    bare globals are out of scope by design and must not flag."""
    global _mod_bare
    _mod_bare += 1
