"""Fixture SLO vocabulary: every consumed series is registered (and a
histogram's exposition ``_bucket`` suffix resolves to its base name).
NO findings expected."""

CONSUMED_SERIES = {
    ("latency", "job"): "rafiki_tpu_bus_wait_seconds",
    ("ratio", "good"): "rafiki_tpu_bus_retries_total",
}

BUCKET_NAME = "rafiki_tpu_bus_wait_seconds_bucket"
