"""Fixture NodeConfig with every contract honored: the knob is
documented and exported by apply_env. NO findings expected."""

import os
from dataclasses import dataclass

_PREFIX = "RAFIKI_TPU_"


@dataclass(frozen=True)
class NodeConfig:
    workdir: str = "./rafiki_workdir"
    tidy_knob: int = 7

    _ENV_MAP = {}

    @classmethod
    def env_name(cls, field: str) -> str:
        return cls._ENV_MAP.get(field, _PREFIX + field.upper())

    def apply_env(self) -> None:
        os.environ[self.env_name("workdir")] = self.workdir
        os.environ[self.env_name("tidy_knob")] = str(self.tidy_knob)
