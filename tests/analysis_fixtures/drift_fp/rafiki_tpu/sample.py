"""Fixture with clean metric names and a properly-plumbed knob read.
NO findings expected."""

import os


def register(reg):
    reg.counter("rafiki_tpu_bus_retries_total")
    reg.histogram("rafiki_tpu_bus_wait_seconds")
    reg.gauge("rafiki_tpu_serving_queue_depth_queries")


def knobs():
    return os.environ.get("RAFIKI_TPU_TIDY_KNOB", "7")
