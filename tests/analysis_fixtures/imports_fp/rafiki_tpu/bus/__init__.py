"""Fixture bus package (reachability root; clean)."""
