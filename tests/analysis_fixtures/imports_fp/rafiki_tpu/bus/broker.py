"""RTA602 FP guard: a TYPE_CHECKING jax import (never executes) and a
LAZY function-scoped import of the jax-heavy module — the sanctioned
observe/__init__ pattern."""

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import jax  # noqa: F401


def serve():
    from .. import heavy

    return heavy.helper()
