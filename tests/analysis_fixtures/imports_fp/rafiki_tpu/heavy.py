"""Eagerly imports jax, but NOT import-time reachable from the bus
package (only the lazy function in broker.py touches it) — no
RTA602."""

import jax


def helper():
    return jax.devices()
