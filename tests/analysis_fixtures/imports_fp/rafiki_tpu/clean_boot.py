"""RTA601 FP guard: the same effects placed correctly — env and
threads resolved inside functions, the module-level thread under the
``__main__`` guard (it never runs on a bare import)."""

import os
import threading


def serve():
    t = threading.Thread(target=print, daemon=True)
    t.start()
    return os.environ.get("APP_DEBUG")


class Registry:
    def __init__(self):
        self.lease = float(os.environ.get("APP_LEASE", "5"))


if __name__ == "__main__":
    MAIN = threading.Thread(target=serve)
    MAIN.start()
