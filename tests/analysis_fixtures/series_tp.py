"""True positive for RTA3xx: per-instance labeled series with no
.remove() anywhere in the module — the r7 leak class verbatim, plus
the r17 bin/tenant-ledger variant (a hashed-key label is exactly as
unbounded as a service id when the module never removes it)."""

from rafiki_tpu.observe import metrics


class LeakyStats:
    def __init__(self, service):
        self.service = service
        self._requests = metrics.registry().counter(
            "rafiki_tpu_serving_requests_total")

    def admitted(self):
        self._requests.inc(service=self.service)  # <- RTA301

    def stop(self):
        pass  # no .remove(service=...): series outlive every instance


class LeakyTenantLedger:
    """The r17 attribution shape done WRONG: per-tenant (hashed client
    key) series with no LRU eviction remove and no close-path remove —
    a rotating-key client grows the registry without bound. The
    ``os.remove`` below must NOT read as series cleanup (a
    positional-arg ``.remove(x)`` is never the metric API)."""

    def __init__(self):
        self._tenant = metrics.registry().counter(
            "rafiki_tpu_serving_tenant_requests_total")

    def account(self, tenant_hash, bin_id):
        self._tenant.inc(tenant=tenant_hash)  # <- RTA301
        self._tenant.inc(bin=bin_id)  # <- RTA301

    def cleanup_files(self, path):
        import os

        os.remove(path)  # positional remove: not a splat remove
