"""True positive for RTA3xx: per-instance labeled series with no
.remove() anywhere in the module — the r7 leak class verbatim."""

from rafiki_tpu.observe import metrics


class LeakyStats:
    def __init__(self, service):
        self.service = service
        self._requests = metrics.registry().counter(
            "rafiki_tpu_serving_requests_total")

    def admitted(self):
        self._requests.inc(service=self.service)  # <- RTA301

    def stop(self):
        pass  # no .remove(service=...): series outlive every instance
