"""Eager jax import, reachable from rafiki_tpu/bus/ — RTA602."""

import jax


def helper():
    return jax.devices()
