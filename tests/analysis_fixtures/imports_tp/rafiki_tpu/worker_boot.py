"""RTA601 TPs: a thread built+started, a socket bound, a process
spawned, an env var read — all at import time — plus a class-body env
read (class bodies execute on import: the NODE_LEASE bug shape)."""

import os
import socket
import subprocess
import threading

HEARTBEAT = threading.Thread(target=print)
HEARTBEAT.start()

_SOCK = socket.socket()
_SOCK.bind(("127.0.0.1", 0))

TOOLCHAIN = subprocess.run(["true"], capture_output=True)

DEBUG = os.environ.get("APP_DEBUG", "0")

SUB_LEASE = float(os.environ["APP_SUB_LEASE"])


class Registry:
    LEASE = float(os.environ.get("APP_LEASE", "5"))


# Guard-polarity regressions (review fix): the else-arm of a __main__
# guard and the body of an INVERTED guard both execute on import.
if __name__ == "__main__":
    pass
else:
    ELSE_ARM = os.environ.get("APP_ELSE")

if __name__ != "__main__":
    INVERTED = os.environ.get("APP_INVERTED")
