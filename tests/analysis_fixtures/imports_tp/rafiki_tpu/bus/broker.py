"""A broker module that (transitively) drags jax in at import time —
the RTA602 TP: ``heavy`` is import-time reachable from the bus root
and eagerly imports jax."""

from ..heavy import helper


def serve():
    return helper()
