"""Fixture bus package (the RTA602 reachability root)."""
