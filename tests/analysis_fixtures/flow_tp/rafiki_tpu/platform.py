"""RTA703 true positive: the owned class constructed without the flag
gate — the off path would pay for the fabric."""

from .admin.nodes import NodeRegistry


class Platform:
    def __init__(self):
        self.node_registry = NodeRegistry("n0")
