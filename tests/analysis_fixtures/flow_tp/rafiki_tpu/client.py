"""RTA702 true positive: a typo'd client path (served is /things)."""


class MiniClient:
    def __init__(self, base: str):
        self._base = base

    def _call(self, method: str, path: str, **body):
        return method, self._base + path, body

    def ok(self):
        return self._call("GET", "/things")

    def things(self):
        return self._call("GET", "/thingz")  # typo
