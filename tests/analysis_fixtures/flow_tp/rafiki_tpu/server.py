"""RTA702 true positive: a served route no in-tree caller hits."""


class MiniApp:
    def __init__(self, server_cls):
        self._http = server_cls([
            ("GET", "/things", self._things),
            ("POST", "/orphan", self._orphan),
        ])

    def _things(self, params, body, ctx):
        return 200, {"things": []}

    def _orphan(self, params, body, ctx):
        return 200, {}
