"""RTA703 true positives inside the flag-owned module: an import-time
thread, and effects in never-gated functions."""

import threading

from ..observelike import registry

_PINGER = threading.Thread(target=lambda: None, daemon=True)


class NodeRegistry:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self._peers_gauge = registry().gauge(
            "rafiki_tpu_node_peers", "live peers")


def spawn_pinger():
    t = threading.Thread(target=lambda: None, daemon=True)
    t.start()
    return t
