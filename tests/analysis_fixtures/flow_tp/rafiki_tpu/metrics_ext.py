"""RTA703 true positive: a flag-owned series prefix registered
outside the owned module with no gate."""

from .observelike import registry


class FabricStats:
    def __init__(self):
        self._m = registry().counter(
            "rafiki_tpu_serving_fabric_total", "fabric requests")
