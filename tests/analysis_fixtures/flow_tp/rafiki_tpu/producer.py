"""RTA701 true positives: an orphan producer family, a dead consumer
family, and one-sided control tokens."""

from .bus.base import Bus

FLUSH = "__flush__"    # pushed below, but nothing ever dispatches it
DRAIN2 = "__drain2__"  # dispatched below, but nothing ever pushes it


class WorkFan:
    def __init__(self, bus: Bus):
        self.bus = bus

    def submit(self, i: int) -> None:
        # Orphan producer: no in-tree consumer pops work:*.
        self.bus.push(f"work:{i}", {"i": i})
        self.bus.push(f"work:{i}", {FLUSH: 1})

    def reap(self):
        # Dead consumer: no in-tree producer pushes lost:*.
        return self.bus.pop_all("lost:jobs")

    def dispatch(self, frame) -> bool:
        return DRAIN2 in frame
