"""True positives for RTA1xx: unguarded access, blocking call under a
lock, lock-order cycle, non-reentrant re-acquisition."""

import threading
import time


class UnguardedAccess:
    """RTA101: _depth is written under _lock but read without it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._depth = 0

    def push(self):
        with self._lock:
            self._depth += 1

    def depth(self):
        return self._depth  # <- RTA101


class BlockingUnderLock:
    """RTA102: sleeps (and reads a file) while holding the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._payload = None

    def refresh(self, path):
        with self._lock:
            time.sleep(0.1)                   # <- RTA102
            with open(path) as f:             # <- RTA102
                self._payload = f.read()


class LockOrderCycle:
    """RTA103: a() takes _a then _b; b() takes _b then _a."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._n = 0

    def a(self):
        with self._a:
            with self._b:
                self._n += 1

    def b(self):
        with self._b:
            with self._a:
                self._n -= 1


class SelfDeadlock:
    """RTA103: re-acquires a non-reentrant Lock through a helper every
    caller enters with the lock already held."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []

    def insert(self, row):
        with self._lock:
            self._insert_locked(row)

    def _insert_locked(self, row):
        with self._lock:  # <- RTA103 (Lock, not RLock)
            self._rows.append(row)


# --- module-global discipline (whole-program arm of RTA101) ----------

_MOD_LOCK = threading.Lock()
_mod_depth = 0


def mod_push():
    global _mod_depth
    with _MOD_LOCK:
        _mod_depth += 1


def mod_depth():
    return _mod_depth  # <- RTA101 (module global, bare read)
