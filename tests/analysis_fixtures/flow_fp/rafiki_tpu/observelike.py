"""Stand-in metrics registry (the ``registry().counter(...)`` shape
RTA703's series-effect detection keys on)."""


class _Reg:
    def counter(self, name: str, desc: str):
        return object()

    def gauge(self, name: str, desc: str):
        return object()

    def histogram(self, name: str, desc: str):
        return object()


def registry() -> _Reg:
    return _Reg()
