"""RTA702 false-positive guard: every served route has a caller and
every caller resolves — via f-string paths (dynamic segment vs
``<param>``), a locally built path with a query suffix, a session
upload, a ``fetch`` scrape, and a peer ``urlopen`` probe."""

from urllib.request import urlopen


class Api:
    def __init__(self, server_cls):
        self._http = server_cls([
            ("GET", "/stats", self._stats),
            ("GET", "/items/<item_id>", self._item),
            ("POST", "/items", self._create),
            ("GET", "/peek", self._peek),
        ])

    def _stats(self, params, body, ctx):
        return 200, {}

    def _item(self, params, body, ctx):
        return 200, {}

    def _create(self, params, body, ctx):
        return 200, {}

    def _peek(self, params, body, ctx):
        return 200, {}


class _FakeSession:
    def post(self, url, data=None):
        return url


class ApiClient:
    def __init__(self, base: str):
        self._base = base
        self._session = _FakeSession()

    def _call(self, method: str, path: str, **body):
        return method, path

    def stats(self):
        return self._call("GET", "/stats")

    def item(self, item_id: str):
        return self._call("GET", f"/items/{item_id}")

    def create(self, task=None):
        path = "/items" + (f"?task={task}" if task else "")
        return self._call("POST", path)

    def upload(self, fh):
        return self._session.post(self._base + "/items?src=upload",
                                  data=fh)

    def peek(self, addr: str, key: str):
        return urlopen(f"http://{addr}/peek?key={key}", timeout=1.0)


def fetch(host: str, path: str):
    return host, path


def scrape(host: str):
    return fetch(host, "/stats")
