"""RTA703 false-positive guard: gate-derived attributes. ``_fabric``
(every truthy assignment under the gate) and ``_node`` (IfExp on a
gate-derived local) make later ``if self._fabric:`` tests count as
flag gates; the owned-prefix series registers only under the gate."""

import os

from .observelike import registry


def _parse_bool(raw: str) -> bool:
    return raw not in ("", "0")


class EdgeApp:
    def __init__(self):
        self._fabric = False
        self._m_fabric = None
        cluster_on = _parse_bool(os.environ.get(
            "RAFIKI_TPU_CLUSTER_FABRIC", "0"))
        self._node = f"n-{os.getpid()}" if cluster_on else ""
        if cluster_on:
            self._fabric = True
            self._m_fabric = registry().counter(
                "rafiki_tpu_serving_fabric_total", "fabric requests")

    def note(self):
        if self._fabric:
            self._m_fabric.inc()
