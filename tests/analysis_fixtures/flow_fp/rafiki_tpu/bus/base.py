"""Minimal bus for the flow fixtures — the queue-op surface the
checker types receivers against."""

import threading
from typing import Any, Dict, List, Optional, Tuple


class Bus:
    def __init__(self):
        self._lock = threading.Lock()
        self._queues: Dict[str, List[Any]] = {}

    def push(self, queue: str, value: Any) -> None:
        with self._lock:
            self._queues.setdefault(queue, []).append(value)

    def push_many(self, items: List[Tuple[str, Any]]) -> None:
        for queue, value in items:
            self.push(queue, value)

    def relay_push(self, node: str, queue: str, value: Any) -> None:
        self.push(queue, value)

    def pop(self, queue: str, timeout: float = 0.0) -> Optional[Any]:
        with self._lock:
            vals = self._queues.get(queue) or []
            return vals.pop(0) if vals else None

    def pop_all(self, queue: str) -> List[Any]:
        with self._lock:
            return self._queues.pop(queue, [])

    def queue_len(self, queue: str) -> int:
        with self._lock:
            return len(self._queues.get(queue) or [])

    def delete_queue(self, queue: str) -> None:
        with self._lock:
            self._queues.pop(queue, None)
