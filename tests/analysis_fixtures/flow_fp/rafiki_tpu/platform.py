"""RTA703 false-positive guard: the owned class is only constructed
under the flag gate, so its methods are protected on-path code."""

import os

from .admin.nodes import NodeRegistry


def _pb(raw: str) -> bool:
    return raw.strip().lower() not in ("", "0", "false")


class Platform:
    def __init__(self):
        self.node_registry = None
        if _pb(os.environ.get("RAFIKI_TPU_CLUSTER_FABRIC", "0")):
            self.node_registry = NodeRegistry("n0")

    def shutdown(self):
        if self.node_registry is not None:
            self.node_registry.close()
