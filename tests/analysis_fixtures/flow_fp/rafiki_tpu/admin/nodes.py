"""RTA703 false-positive guard: the owned module's effects are all
reached through construction gating (the class is only built under
the flag)."""

import threading

from ..observelike import registry


class NodeRegistry:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self._peers_gauge = registry().gauge(
            "rafiki_tpu_node_peers", "live peers")
        self._beat = threading.Thread(target=self._tick, daemon=True)

    def _tick(self):
        pass

    def close(self):
        pass
