"""RTA701 false-positive guard: every family balances through the
resolution machinery — a helper forwarding its ``queue`` parameter, a
name-building helper function, a push_many tuple scan, and a fully
dynamic name that is exempt by design."""

from typing import Any, Dict, List, Tuple

from .bus.base import Bus

DRAIN = "__drain__"  # pushed AND dispatched below


def _req_queue(sub_id: str) -> str:
    return f"adv:{sub_id}:req"


class Producer:
    def __init__(self, bus: Bus):
        self.bus = bus

    def emit(self, wid: str) -> None:
        self._forward(f"q:{wid}", {"x": 1})

    def _forward(self, queue: str, frame: Dict[str, Any]) -> None:
        # The q: name must attribute through this parameter to emit().
        self.bus.push(queue, frame)

    def emit_many(self, wids) -> None:
        writes: List[Tuple[str, Any]] = []
        for w in wids:
            writes.append((f"q:{w}", {"w": w}))
        self.bus.push_many(writes)

    def ask(self, sub_id: str) -> None:
        self.bus.push(_req_queue(sub_id), {"req": 1})

    def drain(self, wid: str) -> None:
        self.bus.push(f"q:{wid}", {DRAIN: 1})

    def dynamic(self, name: str) -> None:
        # Fully dynamic name (empty literal prefix): exempt.
        self.bus.push(f"{name}", {"x": 1})


class Consumer:
    def __init__(self, bus: Bus):
        self.bus = bus

    def loop(self, wid: str) -> None:
        for frame in self.bus.pop_all(f"q:{wid}"):
            if DRAIN in frame:
                return

    def serve(self, sub_id: str) -> None:
        req = self.bus.pop(_req_queue(sub_id), timeout=0.1)
        if req:
            pass
