"""Fixture NodeConfig: ``mystery_knob`` is undocumented (RTA503) and
read by sample.py without an apply_env export (RTA505)."""

import os
from dataclasses import dataclass

_PREFIX = "RAFIKI_TPU_"


@dataclass(frozen=True)
class NodeConfig:
    workdir: str = "./rafiki_workdir"
    mystery_knob: int = 7

    _ENV_MAP = {}

    @classmethod
    def env_name(cls, field: str) -> str:
        return cls._ENV_MAP.get(field, _PREFIX + field.upper())

    def apply_env(self) -> None:
        os.environ[self.env_name("workdir")] = self.workdir
