"""Fixture SLO vocabulary consuming a metric nobody registers
(RTA506) next to one that IS registered (sample.py's histogram)."""

CONSUMED_SERIES = {
    ("latency", "job"): "rafiki_tpu_bus_wait_seconds",       # ok
    ("latency", "bin"): "rafiki_tpu_serving_gone_seconds",   # RTA506
}
