"""Fixture drift violations: bad metric names (RTA501), a rogue env
literal (RTA504), and a NodeConfig knob read that apply_env never
exports (RTA505)."""

import os


def register(reg):
    reg.counter("rafiki_tpu_serving_widgets")        # RTA501: no unit
    reg.gauge("rafiki_tpu_mystery_thing_ratio")      # RTA501: subsystem
    reg.counter("rafiki_tpu_bus_retries_seconds")    # RTA501: not _total
    reg.histogram("rafiki_tpu_bus_wait_seconds")     # ok


def knobs():
    rogue = os.environ.get("RAFIKI_TPU_ROGUE_TWEAK", "1")   # RTA504
    known = os.environ.get("RAFIKI_TPU_MYSTERY_KNOB", "7")  # RTA505
    return rogue, known
