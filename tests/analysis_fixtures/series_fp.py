"""False-positive guards for RTA3xx. NO findings expected:

- literal label values are bounded vocabularies;
- a .remove(service=...) covers the service-labeled series AND the
  calls whose extra dynamic labels (stage=) co-occur with service=
  (subset removal kills the whole label set);
- label_context bindings cleaned up in the same module.
"""

from rafiki_tpu.observe import metrics


class CleanStats:
    def __init__(self, service):
        self.service = service
        r = metrics.registry()
        self._stage = r.histogram("rafiki_tpu_serving_stage_seconds")
        self._total = r.counter("rafiki_tpu_serving_requests_total")

    def record(self, stage, seconds):
        # dynamic stage= rides the same series set as service= — the
        # close() remove below covers it by label subset.
        self._stage.observe(seconds, service=self.service, stage=stage)
        self._total.inc(service=self.service)

    def literal_only(self):
        self._total.inc(kind="query")  # literal label: bounded, fine

    def close(self):
        for m in (self._stage, self._total):
            m.remove(service=self.service)


def run_trial(trial_id):
    with metrics.label_context(trial=trial_id):
        pass
    for name in ("rafiki_tpu_train_mfu_ratio",):
        m = metrics.registry().find(name)
        if m is not None:
            m.remove(trial=trial_id)


class TenantLedger:
    """The r17 attribution shape done RIGHT: the LRU eviction path
    removes a tenant's series, and the last-owner close path calls a
    BARE .remove() — which matches the empty label subset and drops
    every series of the metric, covering the dynamic label (the r17
    checker extension recognizes it)."""

    def __init__(self):
        self._tenant = metrics.registry().counter(
            "rafiki_tpu_serving_tenant_requests_total")
        self._bin = metrics.registry().counter(
            "rafiki_tpu_serving_bin_requests_total")
        self._lru = []

    def account(self, tenant_hash, bin_id):
        self._tenant.inc(tenant=tenant_hash)
        self._bin.inc(bin=bin_id)
        self._lru.append(tenant_hash)
        if len(self._lru) > 64:
            evicted = self._lru.pop(0)
            self._tenant.remove(tenant=evicted)

    def close(self):
        self._bin.remove()  # bare remove = every series of the metric
