"""RTA003 false-positive guard: reasoned waivers that DO suppress a
live finding must not be reported as stale — in either placement form
(same line, or the comment-above form)."""

import threading


class StillRacy:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def a(self):
        with self._lock:
            self._n += 1

    def b(self):
        # rta: disable=RTA101 benign monotonic peek
        return self._n

    def c(self):
        return self._n  # rta: disable=RTA101 benign monotonic peek
