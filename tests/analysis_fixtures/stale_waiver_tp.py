"""RTA003 true positives: reasoned waivers that suppress nothing.

The access under the first waiver IS locked (the defect the comment
once guarded was fixed, the comment rotted in place); the second
waiver names a code no checker emits (a typo'd disable never guarded
anything). Both must be reported as stale instead of silently
pre-waiving the next regression on their lines.
"""

import threading


class FixedLongAgo:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def a(self):
        with self._lock:
            self._n += 1

    def b(self):
        # rta: disable=RTA101 benign monotonic peek
        with self._lock:
            return self._n

    def c(self):
        # rta: disable=RTA999 this code does not exist
        with self._lock:
            return self._n
