"""RTA104 TP (module<->module lock cycle): two MODULE-level locks
acquired in opposite orders by free functions — no class anywhere, so
only the module-owner arm of the whole-program cycle pass sees both
directions."""

import threading

_INGEST_LOCK = threading.Lock()
_FLUSH_LOCK = threading.Lock()
_rows = []


def ingest(row):
    with _INGEST_LOCK:
        with _FLUSH_LOCK:
            _rows.append(row)


def flush():
    with _FLUSH_LOCK:
        with _INGEST_LOCK:
            _rows.clear()
