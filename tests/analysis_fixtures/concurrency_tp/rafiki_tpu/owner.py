"""The registering half of the cross-class RTA106 TP: the owner
builds the consumer AND the thread that runs its loop."""

import threading

from .consumer import BusConsumer, SubmitConsumer


class ConsumerOwner:
    def __init__(self):
        self.consumer = BusConsumer()
        self._t = threading.Thread(target=self.consumer.loop,
                                   daemon=True)
        self._t.start()


class StageOwner:
    """The registering half of the executor-submit cross-class TP:
    the owner builds the consumer and hops ``drain`` onto a pool
    thread — a root the consumer's own class never shows."""

    def __init__(self, pool):
        self.stage = SubmitConsumer()
        self._pool = pool
        self._pool.submit(self.stage.drain)
