"""The registering half of the cross-class RTA106 TP: the owner
builds the consumer AND the thread that runs its loop."""

import threading

from .consumer import BusConsumer


class ConsumerOwner:
    def __init__(self):
        self.consumer = BusConsumer()
        self._t = threading.Thread(target=self.consumer.loop,
                                   daemon=True)
        self._t.start()
