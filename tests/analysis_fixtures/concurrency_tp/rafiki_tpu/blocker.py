"""RTA105 TP: blocking reached through the call graph under a lock —
``admit`` holds ``_gate`` while ``_backoff`` -> ``_pause`` (two frames
of module-level helpers) reaches ``time.sleep``. RTA102 cannot see it:
no blocking call appears IN ``admit``."""

import threading
import time


def _backoff():
    _pause()


def _pause():
    time.sleep(0.1)


class Admission:
    def __init__(self):
        self._gate = threading.Lock()
        self._tie_gate = threading.Lock()
        self._n = 0

    def admit(self):
        with self._gate:
            self._n += 1
            _backoff()

    def admit_both(self):
        """Module function AND method reach the same terminal sleep at
        equal chain depth — the dedup tie a review pass found crashing
        (None-vs-str method-key comparison); kept as the regression.
        Own lock, so it groups separately from admit()'s finding."""
        with self._tie_gate:
            _backoff()
            self._local()

    def _local(self):
        _pause()
