"""RTA104 TP: cross-class lock-order cycle, >=3 frames, cross-module.

``Coordinator.advance`` holds ``Coordinator._lock`` while a helper
chain three frames deep (``_tick`` -> ``_note`` -> ``sink.record``)
acquires ``StatsSink._lock`` in the OTHER module; ``StatsSink.flush``
orders them the other way. Neither class alone looks wrong — exactly
the shape RTA103 cannot see.
"""

import threading

from .sink import StatsSink


class Coordinator:
    def __init__(self):
        self._lock = threading.Lock()
        self.sink = StatsSink(self)
        self._epoch = 0

    def advance(self):
        with self._lock:
            self._epoch += 1
            self._tick()

    def _tick(self):
        self._note()

    def _note(self):
        self.sink.record(self._epoch)

    def kick(self):
        with self._lock:
            self._epoch += 1
