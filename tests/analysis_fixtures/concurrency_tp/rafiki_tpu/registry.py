"""Module-global-lock TPs — free functions have no class, so only the
whole-program pass can see any of this:

- RTA105 (chained): ``publish`` holds the top-level ``_REG_LOCK``
  while ``_settle`` reaches ``time.sleep``;
- RTA105 (direct): ``drain`` sleeps inside the ``with _REG_LOCK:``
  block itself — invisible to the per-class RTA102;
- RTA104: ``Journal.append`` takes ``Journal._lock -> _REG_LOCK``
  (via ``_publish_row``) while ``seal`` orders them the other way —
  a lock-order cycle between a CLASS lock and a MODULE lock.
"""

import threading
import time

_REG_LOCK = threading.Lock()
_entries = {}


def publish(name, value):
    with _REG_LOCK:
        _entries[name] = value
        _settle()


def _settle():
    time.sleep(0.05)


def drain(name):
    with _REG_LOCK:
        time.sleep(0.01)
        return _entries.get(name)


class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []

    def append(self, row):
        with self._lock:
            self._rows.append(row)
            _publish_row(row)


def _publish_row(row):
    with _REG_LOCK:
        _entries["last"] = row


def seal(journal: "Journal"):
    with _REG_LOCK:
        journal.append(1)
