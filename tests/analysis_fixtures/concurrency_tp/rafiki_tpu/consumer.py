"""RTA106 TP (cross-class root): ``BusConsumer.loop`` never
constructs a thread — its OWNER does (``owner.py``:
``Thread(target=self.consumer.loop)``) — yet its unguarded ``_seen``
is written by that thread and read by callers. The per-class
inventory is blind here; the Program-level cross-class root
registration is what makes this fire."""


class BusConsumer:
    def __init__(self):
        self._seen = 0

    def loop(self):
        while True:
            self._seen += 1

    def snapshot(self):
        return self._seen


class SubmitConsumer:
    """Same blindness, executor form: ``drain`` is only ever run via
    the OWNER's ``self._pool.submit(self.stage.drain)`` — no Thread()
    anywhere — yet its unguarded ``_polled`` is written by that pool
    thread and read by callers."""

    def __init__(self):
        self._polled = 0

    def drain(self):
        while True:
            self._polled += 1

    def polled(self):
        return self._polled
