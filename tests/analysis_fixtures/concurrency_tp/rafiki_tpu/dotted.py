"""Dotted module-global-lock TPs: the lock lives in registry.py and
is reached THROUGH the module (``registry._REG_LOCK``) — the spelling
every OTHER module actually uses.

- RTA105 (direct): ``flush`` sleeps inside ``with
  registry._REG_LOCK:`` in a free function;
- RTA104: ``Ledger.write`` takes ``Ledger._lock ->
  registry._REG_LOCK`` while ``rewind`` orders them the other way —
  the dotted reference must UNIFY with the bare-name spelling
  registry.py itself uses, or the cycle is invisible.
"""

import threading
import time

from rafiki_tpu import registry


def flush(name):
    with registry._REG_LOCK:
        time.sleep(0.01)


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []

    def write(self, row):
        with self._lock:
            with registry._REG_LOCK:
                self._rows.append(row)

    def rewind(self):
        with registry._REG_LOCK:
            with self._lock:
                self._rows.pop()
