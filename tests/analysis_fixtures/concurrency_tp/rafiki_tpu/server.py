"""socketserver handler-root TP: ``FrameHandler`` never constructs a
thread itself, but ``serve`` passes the CLASS to a ``*Server`` ctor,
which calls ``handle()`` on a per-connection thread. ``_hits`` is
written there and read by callers with no lock anywhere — RTA106,
visible only if the ctor argument registers as a thread root."""

import socketserver


class FrameHandler(socketserver.BaseRequestHandler):
    def handle(self):
        self._hits = getattr(self, "_hits", 0) + 1

    def hits(self):
        return self._hits


class FrameServer(socketserver.ThreadingTCPServer):
    daemon_threads = True


def serve(host, port):
    return FrameServer((host, port), FrameHandler)
