"""RTA106 TP: a thread-root pair sharing attributes with no lock.

``Poller._latest`` is written by the ``Thread(target=self._loop)``
body and read by callers; ``MiniService._hits`` is written by its loop
thread and read by an HTTP route handler (the ("GET", path, handler)
tuple idiom). Neither attribute is ever accessed under any lock.
"""

import threading


class Poller:
    def __init__(self):
        self._latest = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            self._latest = self._probe()

    def _probe(self):
        return 1

    def read(self):
        return self._latest


class MiniService:
    def __init__(self):
        self._hits = 0
        self.routes = [("GET", "/hits", self._get_hits)]
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self):
        while True:
            self._hits += 1

    def _get_hits(self, params, body, ctx):
        return 200, {"hits": self._hits}
