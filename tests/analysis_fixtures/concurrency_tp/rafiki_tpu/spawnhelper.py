"""RTA106 TP (spawn-PARAMETER root): ``Spawner.register_consumer``
hands its ``fn`` parameter to ``Thread(target=fn)`` — the callable an
owner passes in runs on a thread, but neither the worker's class nor
the owner ever spells ``Thread(target=self.worker.loop)``, so only
the Program-level spawn-parameter attribution can register the root
on ``ParamWorker.loop``."""

import threading


class Spawner:
    def register_consumer(self, fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        return t


class ParamWorker:
    def __init__(self):
        self._seen = 0

    def loop(self):
        while True:
            self._seen += 1

    def snapshot(self):
        return self._seen


class ParamOwner:
    """Hands the worker's loop through the helper — two classes away
    from any literal Thread() construction."""

    def __init__(self):
        self.spawner = Spawner()
        self.worker = ParamWorker()
        self.spawner.register_consumer(self.worker.loop)
