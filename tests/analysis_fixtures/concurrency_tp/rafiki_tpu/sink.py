"""The other half of the RTA104 cycle: StatsSink._lock ->
Coordinator._lock (the reverse of pipeline.py's order)."""

import threading


class StatsSink:
    def __init__(self, coord: "Coordinator"):
        self._lock = threading.Lock()
        self.coord = coord
        self._rows = []

    def record(self, epoch):
        with self._lock:
            self._rows.append(epoch)

    def flush(self):
        with self._lock:
            self.coord.kick()
