"""FP guard for DOTTED module-global locks: a collaborator module's
lock reached as ``modlock._CACHE_LOCK`` guards exactly like the
bare-name spelling — consistent holds with blocking only after
release, and a cross-root pair fully under the lock, must all stay
clean."""

import threading

from rafiki_tpu import modlock


def export_remote(path):
    with modlock._CACHE_LOCK:
        snap = dict(modlock._cache)
    with open(path, "w", encoding="utf-8") as f:
        f.write(str(snap))


class DottedLockedPoller:
    """The ModuleLockedPoller shape through a module reference: the
    loop thread and callers share ``_latest`` under the collaborator
    module's lock — the dotted spelling must count as the guard."""

    def __init__(self):
        self._latest = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            with modlock._CACHE_LOCK:
                self._latest = modlock._cache.get("k")

    def peek(self):
        with modlock._CACHE_LOCK:
            return self._latest
