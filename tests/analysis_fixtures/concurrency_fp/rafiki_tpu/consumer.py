"""FP guard for cross-class thread roots: the registered loop thread
and callers share ``_seen`` UNDER the consumer's own lock — a
cross-class root must honor held sets exactly like an own-class one.
``UntypedOwner`` registers a target through a receiver whose type
does NOT resolve (constructor param, no annotation): no root, no
finding, no crash."""

import threading


class GuardedConsumer:
    def __init__(self):
        self._lock = threading.Lock()
        self._seen = 0

    def loop(self):
        while True:
            with self._lock:
                self._seen += 1

    def snapshot(self):
        with self._lock:
            return self._seen


class GuardedOwner:
    def __init__(self):
        self.consumer = GuardedConsumer()
        self._t = threading.Thread(target=self.consumer.loop,
                                   daemon=True)
        self._t.start()


class UntypedOwner:
    def __init__(self, consumer):
        self.consumer = consumer
        self._t = threading.Thread(target=self.consumer.loop,
                                   daemon=True)
        self._t.start()


class SubmitGuardedOwner:
    """FP guard (executor form): the pool-submitted loop and callers
    share ``_seen`` under the consumer's own lock — a submit-
    registered cross-class root must honor held sets exactly like a
    Thread-registered one."""

    def __init__(self, pool):
        self.consumer = GuardedConsumer()
        self._pool = pool
        self._pool.submit(self.consumer.loop)


class Tracker:
    def __init__(self):
        self._notes = []

    def note(self, x):
        self._notes.append(x)

    def notes(self):
        return list(self._notes)


class RouterOwner:
    """FP guard (receiver shape): ``submit`` on a NON-executor
    receiver is an app method, not a thread hop — ``Tracker.note``
    must NOT become a root (its unguarded ``_notes`` would otherwise
    read as a cross-root race)."""

    def __init__(self):
        self.tracker = Tracker()
        self.router = object()

    def route(self, x):
        self.router.submit(self.tracker.note, x)
