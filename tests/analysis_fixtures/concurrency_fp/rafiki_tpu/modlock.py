"""FP guard for module-global locks: discipline that must stay
clean — consistent single-lock holds with no blocking under them, and
the snapshot-then-block shape (``export`` opens the file only AFTER
releasing the lock)."""

import threading

_CACHE_LOCK = threading.Lock()
_cache = {}


def put(k, v):
    with _CACHE_LOCK:
        _cache[k] = v


def get(k):
    with _CACHE_LOCK:
        return _cache.get(k)


def refresh(k):
    with _CACHE_LOCK:
        _bump(k)


def _bump(k):
    _cache[k] = _cache.get(k, 0) + 1


def export(path):
    with _CACHE_LOCK:
        snap = dict(_cache)
    with open(path, "w", encoding="utf-8") as f:
        f.write(str(snap))


class ModuleLockedPoller:
    """FP guard for module-global locks inside a CLASS: the loop
    thread and callers share ``_latest`` under ``_CACHE_LOCK`` — a
    bare-Name module lock in a method guards exactly like an own
    lock, so this must not read as an unguarded cross-root race."""

    def __init__(self):
        self._latest = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            with _CACHE_LOCK:
                self._latest = get("k")

    def peek(self):
        with _CACHE_LOCK:
            return self._latest
