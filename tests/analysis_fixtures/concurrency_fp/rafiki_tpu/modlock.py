"""FP guard for module-global locks: discipline that must stay
clean — consistent single-lock holds with no blocking under them, and
the snapshot-then-block shape (``export`` opens the file only AFTER
releasing the lock)."""

import threading

_CACHE_LOCK = threading.Lock()
_cache = {}


def put(k, v):
    with _CACHE_LOCK:
        _cache[k] = v


def get(k):
    with _CACHE_LOCK:
        return _cache.get(k)


def refresh(k):
    with _CACHE_LOCK:
        _bump(k)


def _bump(k):
    _cache[k] = _cache.get(k, 0) + 1


def export(path):
    with _CACHE_LOCK:
        snap = dict(_cache)
    with open(path, "w", encoding="utf-8") as f:
        f.write(str(snap))
