"""RTA106 FP guard: cross-thread state with a common lock, a Queue
handoff, and thread-config frozen in __init__ before start()."""

import queue
import threading


class GuardedPoller:
    """The loop thread and callers share _latest UNDER one lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latest = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                self._latest = 1

    def read(self):
        with self._lock:
            return self._latest


class ForeignGuardedPoller:
    """Both sides guard shared state with a COLLABORATOR's lock — a
    real guard the checker must honor (review-fix regression: the
    foreign acquisition enters the held set)."""

    def __init__(self, owner: "GuardedPoller"):
        self.owner = owner
        self._v = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self.owner._lock:
                self._v += 1

    def read(self):
        with self.owner._lock:
            return self._v


class QueueWorker:
    """Handoff through an atomic primitive; the interval is bound in
    __init__ (before start) and only READ afterwards — config, not
    shared state."""

    def __init__(self, interval):
        self._q = queue.Queue()
        self.interval = interval
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return

    def put(self, item):
        self._q.put(item)

    def describe(self):
        return self.interval
