"""FP half: StatsSink never calls out while holding its lock."""

import threading


class StatsSink:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = []

    def record(self, epoch):
        with self._lock:
            self._rows.append(epoch)

    def snapshot(self):
        with self._lock:
            return list(self._rows)
