"""FP guards for the spawn-parameter root and the module<->module
cycle arm: a worker handed through a spawn helper stays clean when it
guards its own state, and two module locks taken in the SAME order
everywhere must not read as a cycle."""

import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()
_staged = []


def stage(row):
    with _A_LOCK:
        with _B_LOCK:
            _staged.append(row)


def commit():
    with _A_LOCK:
        with _B_LOCK:
            _staged.clear()


class CleanSpawner:
    def register_consumer(self, fn):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        return t


class GuardedParamWorker:
    """The spawn-parameter root must honor held sets exactly like a
    literal Thread root: every ``_seen`` touch is under the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seen = 0

    def loop(self):
        while True:
            with self._lock:
                self._seen += 1

    def snapshot(self):
        with self._lock:
            return self._seen


class CleanParamOwner:
    def __init__(self):
        self.spawner = CleanSpawner()
        self.worker = GuardedParamWorker()
        self.spawner.register_consumer(self.worker.loop)
