"""Concurrency FP guard: the same shapes as concurrency_tp, done
right — one global lock order, blocking after release, a common guard
on cross-thread state, and a Queue handoff. Must stay finding-free."""

import threading
import time

from .sink import StatsSink


class Coordinator:
    """Both cross-class paths order Coordinator._lock ->
    StatsSink._lock; no cycle."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sink = StatsSink()
        self._epoch = 0

    def advance(self):
        with self._lock:
            self._epoch += 1
            self._tick()

    def _tick(self):
        self.sink.record(self._epoch)

    def flush(self):
        with self._lock:
            self.sink.record(self._epoch)


class Admission:
    """Snapshot under the lock, block AFTER release — the RTA105 fix
    shape."""

    def __init__(self):
        self._gate = threading.Lock()
        self._n = 0

    def admit(self):
        with self._gate:
            self._n += 1
            n = self._n
        _backoff(n)
        return n


def _backoff(n):
    time.sleep(0.001 * n)
