"""FP guard for handler-class ctor args: only a ``*Server`` ctor
makes a passed class's ``handle`` a per-connection thread root. A
plain pipeline taking a worker class must NOT — ``Worker._count``
then has a single (caller) side and stays clean."""


class Pipeline:
    def __init__(self, worker_cls):
        self.worker_cls = worker_cls


class Worker:
    def __init__(self):
        self._count = 0

    def handle(self):
        self._count += 1

    def count(self):
        return self._count


def build():
    return Pipeline(Worker)
