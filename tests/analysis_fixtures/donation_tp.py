"""True positives for RTA4xx: a cache-resident array at a donated
position (through the AOT-dispatch forwarder, the r9 hazard shape) and
a read-after-donate."""

from functools import partial

import jax

_STAGE_CACHE = {}


def staged_dataset_arrays(key):
    return _STAGE_CACHE[key]


@partial(jax.jit, donate_argnums=(0, 1))
def train_chunk(state, data, sels):
    return state


def dispatch(state, data, sels):
    exe = train_chunk  # AOT fallback alias: dispatch forwards donation
    return exe(state, data, sels)


def train(key):
    data_dev, labels_dev = staged_dataset_arrays(key)
    state = object()
    state = dispatch(state, data_dev, [0])      # <- RTA401 (pos 1)
    return state, labels_dev


def use_after_donate():
    state = object()
    out = train_chunk(state, [1], [0])          # donates state...
    return state, out                           # <- RTA402 (state read)
