"""True positives for RTA4xx: a cache-resident array at a donated
position (through the AOT-dispatch forwarder, the r9 hazard shape) and
a read-after-donate."""

from functools import partial

import jax

_STAGE_CACHE = {}


def staged_dataset_arrays(key):
    return _STAGE_CACHE[key]


@partial(jax.jit, donate_argnums=(0, 1))
def train_chunk(state, data, sels):
    return state


def dispatch(state, data, sels):
    exe = train_chunk  # AOT fallback alias: dispatch forwards donation
    return exe(state, data, sels)


def train(key):
    data_dev, labels_dev = staged_dataset_arrays(key)
    state = object()
    state = dispatch(state, data_dev, [0])      # <- RTA401 (pos 1)
    return state, labels_dev


def use_after_donate():
    state = object()
    out = train_chunk(state, [1], [0])          # donates state...
    return state, out                           # <- RTA402 (state read)


def grab(key):
    """Defined BEFORE its callee on purpose: a depth-3 chain in
    worst-case source order only resolves under a true fixpoint."""
    return fetch_resident(key)  # helper-calls-helper chain


def fetch_resident(key):
    """Neutral name: no stage/cache in it — taint must flow through
    the RETURN (r13)."""
    return hold(key)


def hold(key):
    return _STAGE_CACHE[key]


def train_via_helper(key):
    resident = grab(key)
    state = object()
    state = train_chunk(state, resident, [0])   # <- RTA401 (pos 1)
    return state
