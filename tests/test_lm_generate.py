"""Paged-KV generation engine (models/lm_generate.py).

Two contracts pinned here:

- **Allocator**: refcounted single-page granularity — alloc/free,
  sharing, exhaustion, interleaved churn (no fragmentation possible),
  page 0 reserved.
- **Decode parity**: incremental decode through the paged cache must
  reproduce the full forward pass's next-token logits at EVERY step
  (tolerance-bounded — bf16 compute, flash-kernel vs gather-attention
  reduction orders differ) and the greedy token chain exactly.

Tiny shapes on the CPU mesh, untrained (device-init) params — parity
is a pure-math property, training would only slow the suite down.
"""

import numpy as np
import pytest

from rafiki_tpu.models import JaxTransformerLM
from rafiki_tpu.models.lm_generate import (LMGenerator, PagePool,
                                           PoolExhausted)

TINY = {"d_model": 256, "n_layers": 2, "seq_len": 256, "batch_size": 2,
        "learning_rate": 1e-3, "train_steps": 20, "vocab_size": 512,
        "quick_train": False}


# ---- PagePool ---------------------------------------------------------


def test_pool_alloc_free_roundtrip():
    pool = PagePool(8)
    assert pool.free_pages == 7  # page 0 reserved
    pages = [pool.alloc() for _ in range(7)]
    assert 0 not in pages and sorted(pages) == list(range(1, 8))
    assert pool.used_pages == 7
    for p in pages:
        pool.free(p)
    assert pool.free_pages == 7 and pool.used_pages == 0


def test_pool_exhaustion_and_recovery():
    pool = PagePool(4)
    got = [pool.alloc() for _ in range(3)]
    with pytest.raises(PoolExhausted):
        pool.alloc()
    pool.free(got[1])
    assert pool.alloc() == got[1]  # any free page serves any request


def test_pool_refcount_sharing():
    pool = PagePool(4)
    p = pool.alloc()
    pool.retain(p)
    assert pool.refcount(p) == 2
    pool.free(p)           # one holder left — page stays allocated
    assert pool.refcount(p) == 1 and pool.free_pages == 2
    pool.free(p)           # last holder — page recycled
    assert pool.refcount(p) == 0 and pool.free_pages == 3


def test_pool_interleaved_churn_no_fragmentation():
    """Single-page granularity: after ANY interleaving of allocs and
    frees, every free page is usable — the pool never strands
    capacity the way a contiguous allocator would."""
    pool = PagePool(16)
    held = [pool.alloc() for _ in range(15)]
    for p in held[::2]:    # free every other page (worst-case holes)
        pool.free(p)
    refill = [pool.alloc() for _ in range(8)]
    assert pool.free_pages == 0 and len(set(refill)) == 8
    with pytest.raises(PoolExhausted):
        pool.alloc()


def test_pool_guards_misuse():
    pool = PagePool(4)
    with pytest.raises(ValueError):
        pool.free(3)       # never allocated
    with pytest.raises(ValueError):
        pool.retain(2)
    with pytest.raises(ValueError):
        PagePool(1)        # page 0 alone is not a pool


# ---- engine -----------------------------------------------------------


@pytest.fixture(scope="module")
def lm():
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m._params = m._init_params()  # untrained: parity is about math
    yield m
    m.destroy()


@pytest.fixture(scope="module")
def gen(lm):
    """One shared engine: decode-program compile is the expensive part
    and the step cache keys on shape, so tests share a config."""
    g = lm.make_generator(page_size=4, n_pages=64, decode_batch=2,
                          max_new_cap=16, prefix_cache_entries=4)
    yield g
    g.close()


def _drain(gen, live):
    """Run decode steps until the given seq_ids all finish; returns
    {seq_id: [tokens...]} including the admit-time first token."""
    out = {}
    live = set(live)
    guard = 0
    while live:
        guard += 1
        assert guard < 200, "decode loop did not converge"
        results, evicted = gen.step()
        assert not evicted
        for sid, tok, fin in results:
            out.setdefault(sid, []).append(tok)
            if fin is not None and sid in live:
                live.remove(sid)
    return out


def test_decode_parity_with_full_forward(lm, gen):
    """The tentpole contract: at every step, the paged-KV decode's
    logits match a from-scratch forward over the whole prefix, and the
    greedy chain is exactly the full-forward argmax chain. Prompt
    length 11 is deliberately page-unaligned (page_size=4)."""
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, 512, size=11).tolist()
    sid, first = gen.admit(prompt, max_new=8, temperature=0.0)

    import jax.numpy as jnp
    params = gen._params

    def full_logits(toks):
        ids = jnp.asarray(np.asarray(toks, np.int32)[None])
        return np.asarray(lm._forward(params, ids))[0, len(toks) - 1]

    ref = full_logits(prompt)
    np.testing.assert_allclose(gen.last_logits[sid], ref,
                               atol=0.08, rtol=0.05)
    assert first == int(np.argmax(ref))

    toks = list(prompt) + [first]
    done = False
    while not done:
        before = list(toks)
        results, evicted = gen.step()
        assert not evicted
        (rsid, tok, fin), = results
        assert rsid == sid
        ref = full_logits(before)
        np.testing.assert_allclose(gen.last_logits[sid], ref,
                                   atol=0.08, rtol=0.05)
        assert tok == int(np.argmax(ref)), \
            f"greedy divergence at position {len(before)}"
        toks.append(tok)
        done = fin is not None
    assert len(toks) == len(prompt) + 8  # max_new honored


def test_continuous_admission_mid_decode(lm, gen):
    """Per-step admission: a second prompt joins while the first is
    mid-decode, and BOTH finish with the same tokens they'd produce
    alone (lane packing must not leak across sequences)."""
    rng = np.random.default_rng(11)
    p1 = rng.integers(0, 512, size=9).tolist()
    p2 = rng.integers(0, 512, size=6).tolist()

    sid1, t1 = gen.admit(p1, max_new=6, temperature=0.0)
    solo1 = [t1] + _drain(gen, [sid1])[sid1]

    sid1, t1 = gen.admit(p1, max_new=6, temperature=0.0)
    r1, _ = gen.step()  # sid1 decodes alone for a step...
    pre = [tok for s, tok, _ in r1 if s == sid1]
    sid2, t2 = gen.admit(p2, max_new=3, temperature=0.0)
    mixed = _drain(gen, [sid1, sid2])
    assert [t1] + pre + mixed[sid1] == solo1
    # ...and the shorter request finished while sid1 was resident:
    # its last frame arrived no later than sid1's.
    assert len(mixed[sid2]) + 1 == 3  # max_new incl. the admit token


def test_prefix_cache_skips_prefill(lm, gen):
    """Same prompt twice: the second admission must skip prefill
    (digest hit), share the full pages by refcount, and still produce
    the identical greedy continuation."""
    rng = np.random.default_rng(13)
    prompt = rng.integers(0, 512, size=11).tolist()  # 2 full + 1 partial page
    skipped0 = gen.prefill_skipped_total
    prefills0 = gen.prefills_total

    sid_a, ta = gen.admit(prompt, max_new=4, temperature=0.0)
    toks_a = [ta] + _drain(gen, [sid_a])[sid_a]
    assert gen.prefills_total == prefills0 + 1

    sid_b, tb = gen.admit(prompt, max_new=4, temperature=0.0)
    assert gen.prefill_skipped_total == skipped0 + 1
    assert gen.prefills_total == prefills0 + 1  # no second prefill
    # Cache + resident seq share the FULL prompt pages.
    seq = gen._seqs[sid_b]
    for page in seq.pages[:len(prompt) // gen.page_size]:
        assert gen.pool.refcount(page) >= 2
    toks_b = [tb] + _drain(gen, [sid_b])[sid_b]
    assert toks_a == toks_b


def test_eviction_under_pool_pressure(lm):
    """Pool sized so two growing sequences cannot both extend: the
    YOUNGEST is preempted with its full token trail (recompute-style
    restart state), the older one keeps decoding to completion."""
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m._params = m._init_params()
    g = m.make_generator(page_size=4, n_pages=6, decode_batch=2,
                         max_new_cap=16, prefix_cache_entries=0)
    try:
        rng = np.random.default_rng(17)
        p1 = rng.integers(0, 512, size=4).tolist()
        p2 = rng.integers(0, 512, size=4).tolist()
        sid1, _ = g.admit(p1, max_new=12, temperature=0.0)
        sid2, _ = g.admit(p2, max_new=12, temperature=0.0)
        assert g.pool.free_pages == 1  # 2 pages each, 5 usable
        evicted_all = []
        for _ in range(40):
            results, evicted = g.step()
            evicted_all.extend(evicted)
            if not g._seqs:
                break
        assert evicted_all, "pool pressure never triggered preemption"
        ev = evicted_all[0]
        assert ev["seq_id"] == sid2  # youngest goes first
        assert ev["tokens"][:4] == [int(t) for t in p2]
        assert ev["n_done"] >= 1 and ev["max_new"] == 12
        assert g.evictions_total >= 1
        assert sid1 not in g._seqs  # the survivor ran to completion
    finally:
        g.close()
        m.destroy()


def test_admission_gate_reclaims_prefix_cache(lm):
    """Live sequences outrank cached prefixes: when the pool is full
    of cache-held pages, can_admit spills the cache instead of
    refusing admission."""
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m._params = m._init_params()
    g = m.make_generator(page_size=4, n_pages=6, decode_batch=2,
                         max_new_cap=8, prefix_cache_entries=4)
    try:
        rng = np.random.default_rng(19)
        p1 = rng.integers(0, 512, size=6).tolist()
        sid1, t1 = g.admit(p1, max_new=2, temperature=0.0)
        _drain(g, [sid1])
        # Sequence finished; its pages persist ONLY via the cache.
        assert g.pool.used_pages > 0 and not g._seqs
        p2 = rng.integers(0, 512, size=12).tolist()  # needs 4 pages
        assert g.can_admit(len(p2))  # spilled the cache to say yes
        sid2, _ = g.admit(p2, max_new=2, temperature=0.0)
        assert sid2 in g._seqs
    finally:
        g.close()
        m.destroy()


def test_generator_close_returns_all_pages(lm, gen):
    """After every test above, close() must leave zero leaked pages —
    checked on a fresh engine to keep the shared fixture usable."""
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(TINY))
    m._params = m._init_params()
    g = m.make_generator(page_size=4, n_pages=16, decode_batch=2,
                         max_new_cap=8)
    prompt = list(range(1, 8))
    g.admit(prompt, max_new=4, temperature=0.0)
    g.step()
    g.close()
    assert g.pool.used_pages == 0
    m.destroy()
