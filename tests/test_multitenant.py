"""Multi-tenant chip-scheduler stress (BASELINE config[4], SURVEY.md §7
step 10): concurrent train jobs from different users contending for the
slice's chip ranges; fairness, graceful degradation, and accounting.
"""

import time

import pytest

from rafiki_tpu.constants import BudgetOption, TaskType, UserType
from rafiki_tpu.platform import LocalPlatform

FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"

FAST_BUDGET = {BudgetOption.MODEL_TRIAL_COUNT: 4}


@pytest.fixture()
def platform(tmp_path):
    p = LocalPlatform(workdir=str(tmp_path / "plat"))
    yield p
    p.shutdown()


def _tenant(platform, i):
    user = platform.admin.create_user(f"t{i}@x.c", "pw",
                                      UserType.MODEL_DEVELOPER)
    model = platform.admin.create_model(
        user["id"], f"ff{i}", TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
    return user, model


@pytest.mark.slow
def test_two_tenants_contend_and_complete(platform, synth_image_data):
    """Two jobs each claim half the slice; both run concurrently at full
    utilization and both finish with all trials completed."""
    train_path, val_path = synth_image_data
    jobs = []
    for i in range(2):
        user, model = _tenant(platform, i)
        job = platform.admin.create_train_job(
            user["id"], f"app{i}", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]],
            {**FAST_BUDGET, BudgetOption.CHIP_COUNT: 4},
            train_path, val_path)
        jobs.append(job)

    # Both jobs hold their ranges simultaneously: the slice is full.
    assert platform.services.chip_utilization() == 1.0
    assert platform.allocator.free_chips == 0

    max_util = 0.0
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        max_util = max(max_util, platform.services.chip_utilization())
        done = [platform.admin.get_train_job(j["id"])["status"] == "STOPPED"
                for j in jobs]
        if all(done):
            break
        time.sleep(0.5)
    assert all(platform.admin.get_train_job(j["id"])["status"] == "STOPPED"
               for j in jobs), "jobs did not finish under contention"
    assert max_util == 1.0

    for j in jobs:
        detail = platform.admin.get_train_job(j["id"])
        assert detail["sub_train_jobs"][0]["n_completed"] == \
            FAST_BUDGET[BudgetOption.MODEL_TRIAL_COUNT]
        assert detail["sub_train_jobs"][0]["n_errored"] == 0
    # Every chip returned to the pool.
    assert platform.allocator.free_chips == platform.allocator.n_chips


@pytest.mark.slow
def test_oversubscribed_job_degrades_gracefully(platform, synth_image_data):
    """A job asking for more chips than the slice holds runs with fewer
    workers instead of failing (trials queue behind the smaller pool)."""
    train_path, val_path = synth_image_data
    user, model = _tenant(platform, 0)
    job = platform.admin.create_train_job(
        user["id"], "big", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {**FAST_BUDGET, BudgetOption.CHIP_COUNT: 2 * platform.allocator.n_chips},
        train_path, val_path)
    # The whole slice is working, but nothing was over-allocated.
    assert platform.allocator.free_chips == 0
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    detail = platform.admin.get_train_job(job["id"])
    assert detail["sub_train_jobs"][0]["n_completed"] == \
        FAST_BUDGET[BudgetOption.MODEL_TRIAL_COUNT]
    assert platform.allocator.free_chips == platform.allocator.n_chips


def test_job_rejected_when_slice_full_no_leak(platform, synth_image_data,
                                              monkeypatch):
    """With zero free chips AND sharing disabled a new job fails fast —
    and leaks neither chips nor running services."""
    monkeypatch.setenv("RAFIKI_TPU_CHIP_SHARE", "0")
    train_path, val_path = synth_image_data
    hold = platform.allocator.allocate(platform.allocator.n_chips,
                                       name="hog")
    assert hold is not None
    user, model = _tenant(platform, 0)
    with pytest.raises(RuntimeError, match="no chips"):
        platform.admin.create_train_job(
            user["id"], "starved", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], dict(FAST_BUDGET), train_path, val_path)
    assert platform.allocator.free_chips == 0  # only the hog's chips held

    # Once the hog releases, the same tenant's next job succeeds.
    platform.allocator.release("hog")
    job = platform.admin.create_train_job(
        user["id"], "starved", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]], dict(FAST_BUDGET), train_path, val_path)
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    assert platform.allocator.free_chips == platform.allocator.n_chips


def test_full_slice_admits_second_tenant_time_sliced(platform,
                                                     synth_image_data):
    """Sharing (the default in resident-runner mode): a job arriving at
    a fully-subscribed slice is admitted on co-owned chips instead of
    rejected — single-chip multi-tenancy (BASELINE config[5] on a
    v5e-1). The shared group is a liveness fallback: one worker,
    time-sliced against the incumbent."""
    train_path, val_path = synth_image_data
    hold = platform.allocator.allocate(platform.allocator.n_chips,
                                       name="hog")
    assert hold is not None
    user, model = _tenant(platform, 0)
    job = platform.admin.create_train_job(
        user["id"], "shared", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]], dict(FAST_BUDGET), train_path, val_path)
    # No exclusive chips existed, so the worker co-owns: free count is
    # still zero and some chip carries two owners.
    assert platform.allocator.free_chips == 0
    assert any(len(o) >= 2 for o in platform.allocator._owners)
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    detail = platform.admin.get_train_job(job["id"])
    assert detail["sub_train_jobs"][0]["n_completed"] == \
        FAST_BUDGET[BudgetOption.MODEL_TRIAL_COUNT]
    platform.allocator.release("hog")
    assert platform.allocator.free_chips == platform.allocator.n_chips


@pytest.mark.slow
def test_single_chip_two_tenants_fair_interleave(tmp_path,
                                                 synth_image_data):
    """Two tenants on a ONE-chip allocator (the v5e-1 shape): both jobs
    complete, and their execution windows overlap — trials interleave
    on the shared chip rather than job B waiting for job A to finish."""
    train_path, val_path = synth_image_data
    p = LocalPlatform(workdir=str(tmp_path / "plat1"), n_chips=1)
    try:
        jobs = []
        for i in range(2):
            user, model = _tenant(p, i)
            jobs.append(p.admin.create_train_job(
                user["id"], f"app{i}", TaskType.IMAGE_CLASSIFICATION,
                [model["id"]], dict(FAST_BUDGET), train_path, val_path))
        for j in jobs:
            assert p.admin.wait_until_train_job_done(j["id"], timeout=600)
        windows = []
        for j in jobs:
            trials = p.meta.get_trials_of_train_job(j["id"])
            assert len(trials) == FAST_BUDGET[
                BudgetOption.MODEL_TRIAL_COUNT]
            starts = [t["started_at"] for t in trials]
            ends = [t["finished_at"] for t in trials]
            windows.append((min(starts), max(ends)))
        # Overlap: each job started before the other finished.
        (a0, a1), (b0, b1) = windows
        assert a0 < b1 and b0 < a1, \
            f"jobs serialized: {windows} (no time-slicing)"
    finally:
        p.shutdown()
