"""Advisor subsystem tests: proposal contract, GP convergence, ENAS policy.

Mirrors SURVEY.md §4's implication (a): pure-Python unit tests for the
advisor, no cluster needed.
"""

import numpy as np
import pytest

from rafiki_tpu.advisor import (BayesOptAdvisor, EnasAdvisor, RandomAdvisor,
                                make_advisor)
from rafiki_tpu.constants import ParamsType
from rafiki_tpu.model import (ArchKnob, CategoricalKnob, FixedKnob, FloatKnob,
                              IntegerKnob, PolicyKnob)

CONFIG = {
    "lr": FloatKnob(1e-4, 1e-1, is_exp=True),
    "units": IntegerKnob(8, 64),
    "act": CategoricalKnob(["relu", "tanh"]),
    "epochs": FixedKnob(3),
}


def _quadratic_score(knobs):
    # Max at lr=1e-2, units=32: a smooth landscape the GP should climb.
    lr_term = -(np.log10(knobs["lr"]) + 2.0) ** 2
    units_term = -((knobs["units"] - 32) / 16.0) ** 2
    return float(lr_term + units_term)


def test_random_advisor_proposals_valid():
    adv = RandomAdvisor(CONFIG, seed=0)
    seen = set()
    for i in range(20):
        p = adv.propose()
        assert p.trial_no == i + 1
        assert set(p.knobs) == set(CONFIG)
        assert p.knobs["epochs"] == 3
        adv.feedback(p, _quadratic_score(p.knobs))
        seen.add((p.knobs["units"], p.knobs["act"]))
    assert len(seen) > 5, "random search should produce diverse proposals"
    assert adv.best() is not None


def test_bayes_advisor_beats_random_on_smooth_landscape():
    def run(adv, n=30):
        best = -np.inf
        for _ in range(n):
            p = adv.propose()
            s = _quadratic_score(p.knobs)
            adv.feedback(p, s)
            best = max(best, s)
        return best

    bayes_best = run(BayesOptAdvisor(CONFIG, seed=1, n_initial=6))
    # The optimum is 0.0; GP should get close.
    assert bayes_best > -0.5, f"GP failed to climb: best={bayes_best}"


def test_bayes_advisor_proposals_validate():
    adv = BayesOptAdvisor(CONFIG, seed=2, n_initial=3)
    for _ in range(10):
        p = adv.propose()
        # validate_knobs raises if anything is off-spec
        from rafiki_tpu.model.knobs import validate_knobs
        validate_knobs(CONFIG, p.knobs)
        adv.feedback(p, _quadratic_score(p.knobs))


ENAS_CONFIG = {
    "arch": ArchKnob([[0, 1, 2], [0, 1], [0, 1, 2, 3]]),
    "lr": FixedKnob(1e-3),
    "share": PolicyKnob("SHARE_PARAMS"),
    "quick": PolicyKnob("QUICK_TRAIN"),
}


def test_enas_advisor_learns_good_arch():
    adv = EnasAdvisor(ENAS_CONFIG, seed=0, total_trials=None, lr=5e-2)
    target = [2, 1, 3]

    def score(arch):
        return float(sum(a == t for a, t in zip(arch, target)) / 3.0)

    for _ in range(60):
        p = adv.propose()
        assert p.params_type == ParamsType.GLOBAL_RECENT
        assert p.knobs["share"] is True and p.knobs["quick"] is True
        adv.feedback(p, score(p.knobs["arch"]))

    probs = adv.arch_probs()
    # Policy should have shifted meaningfully toward the target choices.
    assert probs[0, 2] > 0.4 and probs[2, 3] > 0.35, f"probs: {probs}"


def test_enas_final_phase_full_train():
    adv = EnasAdvisor(ENAS_CONFIG, seed=0, total_trials=10,
                      final_train_frac=0.2)
    for i in range(8):
        p = adv.propose()
        adv.feedback(p, float(i) / 10)
    best_arch = adv.best()[0]["arch"]
    p9 = adv.propose()
    assert p9.params_type == ParamsType.NONE
    assert p9.knobs["share"] is False and p9.knobs["quick"] is False
    assert p9.knobs["arch"] == best_arch


def test_make_advisor_selection():
    assert isinstance(make_advisor(ENAS_CONFIG), EnasAdvisor)
    assert isinstance(make_advisor(CONFIG), BayesOptAdvisor)
    fixed_only = {"epochs": FixedKnob(3)}
    assert isinstance(make_advisor(fixed_only), RandomAdvisor)
    assert isinstance(make_advisor(CONFIG, advisor_type="random"), RandomAdvisor)
    with pytest.raises(ValueError):
        make_advisor(CONFIG, advisor_type="nope")


def test_prefetch_advisor_pipelines_and_balances():
    """PrefetchAdvisor (SURVEY §7 async proposal queue): proposal N+1
    computes while trial N runs; delegation is transparent; close()
    forgets the dangling prefetched proposal so budget slots balance."""
    import threading
    import time as _time

    from rafiki_tpu.advisor import PrefetchAdvisor
    from rafiki_tpu.advisor.base import BaseAdvisor
    from rafiki_tpu.model.knobs import IntegerKnob

    calls = {"propose": 0, "forgotten": []}

    class SlowAdvisor(BaseAdvisor):
        def _propose_knobs(self, trial_no):
            calls["propose"] += 1
            _time.sleep(0.2)
            return {"width": 8 + trial_no}

        def _forget(self, proposal):
            calls["forgotten"].append(proposal.trial_no)

    adv = PrefetchAdvisor(SlowAdvisor({"width": IntegerKnob(8, 64)},
                                      seed=0, total_trials=4))
    p1 = adv.propose()        # sync (nothing prefetched yet)
    t0 = _time.time()
    _time.sleep(0.8)          # "training" — prefetch runs during this
    p2 = adv.propose()
    waited = _time.time() - t0 - 0.8
    assert waited < 0.15, waited  # p2 was ready, not computed inline
    assert p2.trial_no == p1.trial_no + 1
    adv.feedback(p1, 0.5)
    adv.feedback(p2, 0.6)
    # best() delegates through to the wrapped advisor.
    knobs, score = adv.best()
    assert score == 0.6
    adv.close()
    # close() forgot exactly the one prefetched-but-unused proposal.
    assert len(calls["forgotten"]) == 1
    with pytest.raises(RuntimeError):
        adv.propose()


def test_prefetch_refreshes_stale_none_after_refund():
    """Review finding r4: at the budget boundary the buffer can hold a
    None computed BEFORE an errored trial's forget() refunded its slot;
    propose() must re-ask live so the refund is honored and the search
    does not end one trial short."""
    from rafiki_tpu.advisor import PrefetchAdvisor, RandomAdvisor
    from rafiki_tpu.model.knobs import IntegerKnob

    adv = PrefetchAdvisor(RandomAdvisor({"width": IntegerKnob(8, 64)},
                                        seed=0, total_trials=2))
    p1 = adv.propose()
    p2 = adv.propose()          # buffer now prefetches proposal #3: None
    assert p1 is not None and p2 is not None
    import time

    time.sleep(0.1)             # let the None land in the buffer
    adv.forget(p2)              # errored trial refunds its slot
    p3 = adv.propose()          # must NOT serve the stale buffered None
    assert p3 is not None, "stale buffered None ended the search early"
    adv.feedback(p1, 0.5)
    adv.feedback(p3, 0.6)
    assert adv.propose() is None  # budget genuinely spent now
    adv.close()
