"""Chaos matrix: injected faults driving the closed recovery loop.

The fault plane (rafiki_tpu/faults.py) injures the real stack — no
mocks — and the assertions are on RECOVERY, not the injury: a replica
killed mid-load must come back via supervise respawn + Predictor
replan with zero dropped queries; a broker restart must heal through
the tcp client's frame-unsent retry and the workers' registration
lease; a respawn with no chip capacity must degrade loudly, not crash
the sweep."""

import time

import pytest
import requests

from rafiki_tpu import faults
from rafiki_tpu.cache import Cache, encode_payload
from rafiki_tpu.constants import (BudgetOption, InferenceJobStatus,
                                  ServiceStatus, ServiceType, TaskType,
                                  UserType)
from rafiki_tpu.model import load_image_dataset
from rafiki_tpu.platform import LocalPlatform

FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    faults.reset()
    yield
    faults.reset()


def _trained_job(platform, synth_image_data, n_trials=1, name="ff-chaos"):
    train_path, val_path = synth_image_data
    dev = platform.admin.create_user(f"{name}@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
    model = platform.admin.create_model(
        dev["id"], name, TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
    job = platform.admin.create_train_job(
        dev["id"], name, TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: n_trials},
        train_path, val_path)
    assert platform.admin.wait_until_train_job_done(job["id"],
                                                    timeout=600)
    return dev, job


def test_replica_killed_midload_respawns_and_replans(tmp_path,
                                                     synth_image_data):
    """The tentpole loop, end to end: an injected hard crash kills one
    of two single-replica trial bins mid-load (meta row left RUNNING,
    bus registration stale — a kill -9). Every in-flight and subsequent
    query must still be answered (partial-bin degrade), supervise()
    must notice the dead thread, respawn a replica for the SAME trial
    bin and reap the stale registration, and the Predictor's next plans
    must fold the respawned replica back in — full-bin ensembles
    restored, zero dropped queries throughout."""
    platform = LocalPlatform(workdir=str(tmp_path / "plat"), http=True,
                             supervise_interval=0)
    try:
        dev, job = _trained_job(platform, synth_image_data, n_trials=2)
        # Arm the plane QUIETLY before the serving stack is built: the
        # workers' construction-time hooks exist, nothing fires yet.
        faults.set_plan("")
        inf = platform.admin.create_inference_job(dev["id"], job["id"],
                                                  max_models=2)
        host = platform.admin.get_inference_job(
            inf["id"])["predictor_host"]
        pred_svc = next(s for s in platform.meta.get_services()
                        if s["service_type"] == ServiceType.PREDICT)
        psvc = platform.container.get(pred_svc["id"])
        # Short gather timeout: the dead bin has no sibling, so queries
        # caught mid-crash wait one full gather before degrading to
        # partial-bin — keep that window test-sized.
        psvc.predictor.gather_timeout = 4.0

        _, val_path = synth_image_data
        ds = load_image_dataset(val_path)
        batch = [encode_payload(ds.images[i]) for i in range(3)]

        def predict():
            r = requests.post(f"http://{host}/predict",
                              json={"queries": batch}, timeout=180)
            assert r.status_code == 200, r.text
            preds = r.json()["predictions"]
            assert len(preds) == len(batch)
            assert all(p is not None for p in preds), \
                "dropped query (no surviving bin voted)"
            return preds

        predict()  # warm path: both bins serve, EWMAs seeded
        cache = Cache(platform.bus)
        workers0 = set(cache.running_workers(inf["id"]))
        assert len(workers0) == 2
        inf_svcs = {s["id"]: s for s in platform.meta.get_services()
                    if s["service_type"] == ServiceType.INFERENCE}
        assert set(inf_svcs) == workers0

        # Kill exactly ONE replica on its next predict dispatch.
        faults.set_plan("worker.crash:n=1")
        deadline = time.monotonic() + 60
        dead_id = None
        while dead_id is None and time.monotonic() < deadline:
            predict()  # zero dropped queries, before/during/after
            for sid in workers0:
                worker = platform.container.get(sid)
                if worker is not None and not worker.running:
                    dead_id = sid
            time.sleep(0.05)
        assert dead_id is not None, "injected crash never fired"

        # Hard death: the row is still RUNNING (no graceful ERRORED
        # update) and the registration is stale — supervise's problem.
        assert platform.meta.get_service(dead_id)["status"] == \
            ServiceStatus.RUNNING
        assert dead_id in set(cache.running_workers(inf["id"]))

        restarted = platform.services.supervise()
        assert len(restarted) == 1
        new_svc = platform.meta.get_service(restarted[0])
        assert new_svc["service_type"] == ServiceType.INFERENCE
        assert platform.meta.get_service(dead_id)["status"] == \
            ServiceStatus.ERRORED
        # Same trial bin as the dead replica, and the stale
        # registration was reaped.
        dead_bin = next(
            w["trial_id"] for w in
            platform.meta.get_inference_job_workers(inf["id"])
            if w["service_id"] == dead_id)
        new_bin = next(
            w["trial_id"] for w in
            platform.meta.get_inference_job_workers(inf["id"])
            if w["service_id"] == new_svc["id"])
        assert new_bin == dead_bin
        assert dead_id not in set(cache.running_workers(inf["id"]))

        # The respawned replica registers after its (warm) model load;
        # the Predictor's registry scan then plans both bins again.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            live = set(cache.running_workers(inf["id"]))
            if new_svc["id"] in live:
                break
            time.sleep(0.2)
        assert new_svc["id"] in set(cache.running_workers(inf["id"]))
        assert len(psvc.predictor._choose_workers()) == 2
        preds = predict()  # full-bin ensembles again
        assert len(preds) == len(batch)

        # Recovery was counted (closed loop is observable).
        from rafiki_tpu.observe.metrics import registry
        c = registry().find("rafiki_tpu_node_restarts_total")
        assert c is not None
        assert c.value(service_type=ServiceType.INFERENCE) >= 1
        platform.admin.stop_inference_job(inf["id"])
    finally:
        platform.shutdown()


def test_broker_restart_mid_scatter_recovers(tmp_path, synth_image_data,
                                             monkeypatch):
    """A broker restart between scatters must heal transparently: the
    predictor's next push_many hits its stale socket (frame UNSENT —
    the send itself fails), reconnects on the bounded backoff, and
    resends safely; the workers' registration lease re-populates the
    fresh broker. ONE post-restart request must succeed end to end —
    no request-level retry loop — and each query gets exactly one
    prediction (no duplicated non-idempotent ops)."""
    from rafiki_tpu.bus import serve_broker

    monkeypatch.setenv("RAFIKI_TPU_WORKER_REREGISTER", "1.0")
    broker = serve_broker("127.0.0.1", 0, native=False)
    port = broker.port
    platform = LocalPlatform(workdir=str(tmp_path / "plat"),
                             bus_uri=broker.uri, http=True,
                             supervise_interval=0)
    try:
        dev, job = _trained_job(platform, synth_image_data, n_trials=1,
                                name="ff-broker")
        inf = platform.admin.create_inference_job(dev["id"], job["id"],
                                                  max_models=1)
        host = platform.admin.get_inference_job(
            inf["id"])["predictor_host"]
        _, val_path = synth_image_data
        ds = load_image_dataset(val_path)
        batch = [encode_payload(ds.images[i]) for i in range(4)]

        r = requests.post(f"http://{host}/predict",
                          json={"queries": batch}, timeout=180)
        assert r.status_code == 200

        broker.stop()
        time.sleep(0.5)
        broker = serve_broker("127.0.0.1", port, native=False)

        # One request, no retries: the scatter's transport retry plus
        # the worker's 1s re-registration lease carry it through.
        r = requests.post(f"http://{host}/predict",
                          json={"queries": batch}, timeout=180)
        assert r.status_code == 200, r.text
        preds = r.json()["predictions"]
        assert len(preds) == len(batch)
        assert all(p is not None for p in preds)
        platform.admin.stop_inference_job(inf["id"])
    finally:
        platform.shutdown()
        broker.stop()


def test_supervise_inference_respawn_no_capacity_and_stopped_job(
        tmp_path, monkeypatch):
    """The two guarded edges of the inference-respawn path, on
    fabricated meta rows (no training, fast):

    - no capacity: the allocator returns None -> the sweep marks the
      dead replica ERRORED, restarts nothing, and does not crash —
      but queues the replica, and the NEXT sweep respawns it once
      capacity frees (the ERRORED row is invisible to the RUNNING
      scan, so only the pending queue can ever retry it);
    - stopped job: a dead replica of a STOPPED job is never
      resurrected (no allocation is even attempted), and a pending
      respawn of a stopped job is dropped, not retried forever."""
    platform = LocalPlatform(workdir=str(tmp_path / "plat"), http=False,
                             supervise_interval=0)
    try:
        meta = platform.meta
        node = platform.services.node_id
        job = meta.create_inference_job("u-x", "tj-x",
                                        InferenceJobStatus.RUNNING)
        svc = meta.create_service(ServiceType.INFERENCE,
                                  ServiceStatus.RUNNING, chips=[0],
                                  node_id=node)
        meta.add_inference_job_worker(svc["id"], job["id"], "trial-x")

        # --- no capacity: allocate() -> None ---
        monkeypatch.setattr(platform.services.allocator, "allocate",
                            lambda *a, **kw: None)
        restarted = platform.services.supervise()
        assert restarted == []
        assert meta.get_service(svc["id"])["status"] == \
            ServiceStatus.ERRORED
        live = [s for s in meta.get_services()
                if s["service_type"] == ServiceType.INFERENCE
                and s["status"] in (ServiceStatus.DEPLOYING,
                                    ServiceStatus.RUNNING)]
        assert live == []
        assert [p["id"] for p in platform.services._pending_respawns] \
            == [svc["id"]]

        # --- a sweep that dies mid-scan must not orphan the queue
        # (the ERRORED row can never re-enter the RUNNING scan, so a
        # dropped queue entry would be permanent degradation) ---
        monkeypatch.setattr(
            platform.services.meta, "get_services",
            lambda **kw: (_ for _ in ()).throw(RuntimeError("db busy")))
        with pytest.raises(RuntimeError, match="db busy"):
            platform.services.supervise()
        assert [p["id"] for p in platform.services._pending_respawns] \
            == [svc["id"]]

        # --- capacity frees: the next sweep retries the pending
        # respawn (stubbed admission — the real path needs a trained
        # trial; what's under test is the retry wiring) ---
        monkeypatch.undo()
        admitted = []
        monkeypatch.setattr(
            platform.services, "add_inference_worker",
            lambda job_id, trial_id, **kw: (
                admitted.append((job_id, trial_id)) or {"id": "svc-new"}))
        restarted = platform.services.supervise()
        assert restarted == ["svc-new"]
        assert admitted == [(job["id"], "trial-x")]
        assert platform.services._pending_respawns == []

        # --- stopped job: status gate short-circuits ---
        monkeypatch.undo()
        meta.update_inference_job(job["id"],
                                  status=InferenceJobStatus.STOPPED)
        svc2 = meta.create_service(ServiceType.INFERENCE,
                                   ServiceStatus.RUNNING, chips=[0],
                                   node_id=node)
        meta.add_inference_job_worker(svc2["id"], job["id"], "trial-x")
        free_before = platform.allocator.free_chips
        restarted = platform.services.supervise()
        assert restarted == []
        assert meta.get_service(svc2["id"])["status"] == \
            ServiceStatus.ERRORED
        assert platform.allocator.free_chips == free_before
        assert platform.services._pending_respawns == []
    finally:
        platform.shutdown()
