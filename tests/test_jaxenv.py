"""Platform-resolution guard (rafiki_tpu.jaxenv).

The environment's site hook latches ``jax_platforms`` to the accelerator
at interpreter startup regardless of ``JAX_PLATFORMS`` — and a dead
accelerator tunnel *hangs* backend init rather than raising. These tests
pin the guard's contract: env intent is honored, the fallback never
blocks, and the verdict is inherited by children.
"""

import os
import subprocess
import sys

from rafiki_tpu import jaxenv

TIMEOUT = 120


def _child(code: str, **env_overrides) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.pop(jaxenv.RESOLVED_ENV, None)
    env.update(env_overrides)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=TIMEOUT)


def test_accel_platform_parsing(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert jaxenv.accel_platform() == "axon"
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert jaxenv.accel_platform() == "axon"  # default accel name
    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    assert jaxenv.accel_platform() == "tpu"


def test_initialized_backend_wins():
    import jax

    jax.devices()  # force backend init (conftest pinned cpu config)
    assert jaxenv.backend_initialized()
    assert jaxenv.ensure_platform() == "cpu"
    assert jaxenv.ensure_platform("cpu") == "cpu"


def test_env_cpu_request_honored_despite_site_latch():
    """JAX_PLATFORMS=cpu in the env must yield the CPU backend without
    probing (fast) even though the site hook latched the accelerator."""
    r = _child(
        "from rafiki_tpu.jaxenv import ensure_platform\n"
        "import jax\n"
        "p = ensure_platform()\n"
        "assert p == 'cpu', p\n"
        "assert jax.devices()[0].platform == 'cpu'\n"
        "print('OK')\n",
        JAX_PLATFORMS="cpu")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_cpu_resolution_inherited_by_children():
    """A parent that pinned cpu exports BOTH JAX_PLATFORMS=cpu and the
    RESOLVED_ENV marker (what _pin_cpu does); the child resolves cpu
    instantly — no probe subprocess, no accelerator attempt."""
    r = _child(
        "from rafiki_tpu.jaxenv import ensure_platform\n"
        "import jax\n"
        "assert ensure_platform() == 'cpu'\n"
        "assert jax.default_backend() == 'cpu'\n"
        "print('OK')\n",
        JAX_PLATFORMS="cpu", **{jaxenv.RESOLVED_ENV: "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_virtual_device_pool_sizing():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    r = subprocess.run(
        [sys.executable, "-c",
         "from rafiki_tpu.jaxenv import ensure_platform\n"
         "import jax\n"
         "ensure_platform('cpu', n_virtual_devices=4)\n"
         "assert len(jax.devices()) == 4, jax.devices()\n"
         "print('OK')\n"],
        env={**env, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=TIMEOUT)
    assert r.returncode == 0, r.stdout + r.stderr


def test_failed_probe_falls_back_to_cpu():
    """With the probe forced to fail fast (tiny timeout and a bogus
    accel), auto resolution lands on cpu instead of hanging."""
    r = _child(
        "from rafiki_tpu import jaxenv\n"
        "import jax\n"
        "p = jaxenv.ensure_platform(probe_timeout=3.0)\n"
        "assert p == 'cpu', p\n"
        "assert jax.default_backend() == 'cpu'\n"
        "print('OK')\n",
        JAX_PLATFORMS="nosuchplatform")
    assert r.returncode == 0, r.stdout + r.stderr


def test_explicit_cpu_env_beats_inherited_resolution():
    """JAX_PLATFORMS=cpu (operator intent) wins over a leaked
    RAFIKI_TPU_PLATFORM=accel verdict from a parent process."""
    r = _child(
        "from rafiki_tpu.jaxenv import ensure_platform\n"
        "import jax\n"
        "assert ensure_platform() == 'cpu'\n"
        "assert jax.default_backend() == 'cpu'\n"
        "print('OK')\n",
        JAX_PLATFORMS="cpu", **{jaxenv.RESOLVED_ENV: "axon"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_force_cpu_device_count_after_init():
    """entry()-then-dryrun in one process: a 1-device backend already
    initialized must be replaceable by an 8-device virtual CPU pool."""
    r = _child(
        "import jax\n"
        "from rafiki_tpu import jaxenv\n"
        "jaxenv.ensure_platform('cpu')\n"
        "assert len(jax.devices()) == 1, jax.devices()\n"
        "jaxenv.force_cpu_device_count(8)\n"
        "assert len(jax.devices()) == 8, jax.devices()\n"
        "import numpy as np\n"
        "x = jax.jit(lambda a: a * 2)(np.arange(4.0))\n"
        "assert float(x.sum()) == 12.0\n"
        "print('OK')\n",
        JAX_PLATFORMS="cpu", XLA_FLAGS="")
    assert r.returncode == 0, r.stdout + r.stderr


def test_explicit_accel_raises_when_unreachable():
    r = _child(
        "from rafiki_tpu import jaxenv\n"
        "try:\n"
        "    jaxenv.ensure_platform('accel', probe_timeout=3.0)\n"
        "except RuntimeError as e:\n"
        "    print('RAISED', e)\n",
        JAX_PLATFORMS="nosuchplatform")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RAISED" in r.stdout
