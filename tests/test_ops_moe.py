"""Switch-MoE op: routing exactness, capacity, aux loss, ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.ops import switch_moe
from rafiki_tpu.parallel import build_mesh, shard_variables


def _params(rng, e=4, d=8, f=16, dtype=jnp.float32):
    def r(*shape):
        return jnp.asarray(rng.standard_normal(shape) * 0.3, dtype)

    return {"gate_w": r(d, e), "w1": r(e, d, f), "b1": r(e, f),
            "w2": r(e, f, d), "b2": r(e, d)}


def _manual(x, p):
    """Per-token reference: gate prob × its top-1 expert's FFN."""
    logits = np.asarray(x, np.float32) @ np.asarray(p["gate_w"],
                                                    np.float32)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(np.asarray(x, np.float32))
    for i in range(x.shape[0]):
        e = int(np.argmax(probs[i]))
        h = np.asarray(x[i], np.float32) @ np.asarray(p["w1"][e],
                                                      np.float32) \
            + np.asarray(p["b1"][e], np.float32)
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        y = h @ np.asarray(p["w2"][e], np.float32) \
            + np.asarray(p["b2"][e], np.float32)
        out[i] = probs[i, e] * y
    return out


def test_moe_matches_per_token_reference(rng):
    x = jnp.asarray(rng.standard_normal((24, 8)), jnp.float32)
    p = _params(rng)
    # Ample capacity: no token is dropped, output must equal the
    # per-token reference exactly.
    out, aux = switch_moe(x, **p, capacity_factor=4.0)
    np.testing.assert_allclose(np.asarray(out), _manual(x, p),
                               atol=1e-5, rtol=1e-4)
    assert float(aux) > 0.0  # aux ~1 at uniform routing (not a bound)


def test_moe_capacity_drops_to_zero_rows(rng):
    x = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    p = _params(rng)
    # Capacity 1 slot per expert: at most E tokens survive; dropped
    # tokens are exact zero rows (callers' residual passes them
    # through).
    out, _ = switch_moe(x, **p, capacity_factor=1.0 / 8)
    nonzero = np.abs(np.asarray(out)).sum(axis=1) > 0
    assert nonzero.sum() <= p["gate_w"].shape[1]
    full, _ = switch_moe(x, **p, capacity_factor=4.0)
    surviving = np.where(nonzero)[0]
    np.testing.assert_allclose(np.asarray(out)[surviving],
                               np.asarray(full)[surviving], atol=1e-5)


def test_moe_aux_penalizes_skew(rng):
    # Positive features so adding a large weight to expert 0's gate
    # column guarantees every token routes there.
    x = jnp.asarray(np.abs(rng.standard_normal((64, 8))) + 0.1,
                    jnp.float32)
    p = _params(rng)
    _, aux_rand = switch_moe(x, **p)
    p_skew = dict(p, gate_w=p["gate_w"].at[:, 0].add(100.0))
    _, aux_skew = switch_moe(x, **p_skew)
    assert float(aux_skew) > float(aux_rand)
    assert float(aux_skew) > 3.5  # all mass on one of E=4 experts


def test_moe_ep_sharded_matches_replicated(rng):
    """Experts sharded over an ep=4 mesh produce the same output as the
    single-device run — XLA inserts the dispatch/combine collectives."""
    mesh = build_mesh(jax.devices(), ep=4)
    x = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    p = _params(rng)
    ref, _ = switch_moe(x, **p, capacity_factor=4.0)

    named = {"expert_" + k if k != "gate_w" else k: v
             for k, v in p.items()}
    placed = shard_variables(named, mesh)
    assert "ep" in str(placed["expert_w1"].sharding.spec)

    @jax.jit
    def run(x, prm):
        return switch_moe(
            x, prm["gate_w"], prm["expert_w1"], prm["expert_b1"],
            prm["expert_w2"], prm["expert_b2"], capacity_factor=4.0)[0]

    out = run(x, placed)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_moe_grads_finite(rng):
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    p = _params(rng)

    def loss(prm):
        out, aux = switch_moe(x, **prm)
        return out.sum() + 0.01 * aux

    grads = jax.grad(loss)(p)
    for k, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), k
    # The router receives gradient through both the gate value and aux.
    assert np.abs(np.asarray(grads["gate_w"])).sum() > 0


def test_moe_masked_tokens_never_claim_capacity(rng):
    """Padding tokens must not consume expert slots or router stats:
    with capacity for exactly the real tokens, every real token
    survives no matter how much padding follows it in cumsum order."""
    d = 8
    real = jnp.asarray(rng.standard_normal((8, d)), jnp.float32)
    padding = jnp.zeros((56, d), jnp.float32)
    x = jnp.concatenate([padding, real])     # pads FIRST in cumsum order
    mask = jnp.concatenate([jnp.zeros(56, bool), jnp.ones(8, bool)])
    p = _params(rng, e=4, d=d)
    # capacity_factor 2/4 * 64/4 = 8 slots/expert: enough for all 8 real
    # tokens even if they all pick one expert.
    out, aux = switch_moe(x, **p, capacity_factor=0.5, token_mask=mask)
    out = np.asarray(out)
    assert (np.abs(out[:56]).sum(axis=1) == 0).all()  # pads: zero rows
    ref = _manual(real, p)
    np.testing.assert_allclose(out[56:], ref, atol=1e-5, rtol=1e-4)
    assert np.isfinite(float(aux))


def test_moe_group_local_routing_bounds_memory(rng):
    """Groups route independently (the O(N·group) memory form): output
    equals running each group alone."""
    x = jnp.asarray(rng.standard_normal((64, 8)), jnp.float32)
    p = _params(rng)
    out, _ = switch_moe(x, **p, capacity_factor=4.0, group_size=16)
    per_group = [switch_moe(x[i:i + 16], **p, capacity_factor=4.0,
                            group_size=16)[0] for i in range(0, 64, 16)]
    np.testing.assert_allclose(np.asarray(out),
                               np.concatenate([np.asarray(o)
                                               for o in per_group]),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.slow
def test_moe_transformer_model_trains(tmp_path):
    """Zoo integration: moe_experts > 0 trains, beats chance, and
    round-trips its expert params."""
    from rafiki_tpu.datasets import make_synthetic_corpus_dataset
    from rafiki_tpu.models import JaxTransformerTagger

    train, val = make_synthetic_corpus_dataset(
        str(tmp_path), n_train=96, n_val=24, vocab=64, n_tags=4,
        max_len=24)
    kw = dict(d_model=64, n_heads=4, n_layers=1, learning_rate=1e-2,
              batch_size=16, max_epochs=6, max_len=32, dropout=0.0,
              vocab_size=1024, moe_experts=4, expert_parallel=2)
    m = JaxTransformerTagger(**kw)
    assert m.mesh.shape["ep"] == 2
    m.train(train)
    assert float(m.evaluate(val)) > 0.5
    params = m.dump_parameters()
    assert any("expert_w1" in k for k in params)
    m2 = JaxTransformerTagger(**kw)
    m2.load_parameters(params)
    from rafiki_tpu.model import load_corpus_dataset

    s = load_corpus_dataset(val).sentences[:2]
    np.testing.assert_allclose(np.asarray(m.predict(s)[0]),
                               np.asarray(m2.predict(s)[0]), atol=1e-5)
    m.destroy()
    m2.destroy()


def test_moe_rejects_indivisible_expert_parallel():
    from rafiki_tpu.models import JaxTransformerTagger

    m = JaxTransformerTagger(d_model=64, n_heads=4, n_layers=1,
                             learning_rate=1e-2, batch_size=16,
                             max_epochs=1, max_len=32, dropout=0.0,
                             vocab_size=1024, moe_experts=4,
                             expert_parallel=8)
    with pytest.raises(ValueError, match="divisible"):
        m.mesh
