"""End-to-end platform tests: the §3.1/§3.2/§3.3 call stacks for real.

No mocks (SURVEY.md §4): real advisor + train workers (threads), real
stores, real bus, real HTTP predictor — scaled down to the 8-virtual-CPU
mesh and a tiny synthetic dataset.
"""

import time

import numpy as np
import pytest
import requests

from rafiki_tpu.constants import (BudgetOption, ServiceStatus, ServiceType,
                                  TaskType, TrialStatus, UserType)
from rafiki_tpu.model import load_image_dataset
from rafiki_tpu.platform import LocalPlatform

FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"


@pytest.fixture()
def platform(tmp_path):
    p = LocalPlatform(workdir=str(tmp_path / "plat"), http=True,
                      supervise_interval=0)
    yield p
    p.shutdown()


def _register_model(platform, name="ff"):
    dev = platform.admin.create_user("dev@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
    model = platform.admin.create_model(
        dev["id"], name, TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
    return dev, model


def test_full_automl_job_and_serving(platform, synth_image_data):
    train_path, val_path = synth_image_data
    dev, model = _register_model(platform)

    job = platform.admin.create_train_job(
        dev["id"], "fashion-app", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
        train_path, val_path)

    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    detail = platform.admin.get_train_job(job["id"])
    assert detail["status"] == "STOPPED"
    assert detail["sub_train_jobs"][0]["n_completed"] == 2
    assert detail["sub_train_jobs"][0]["n_errored"] == 0

    best = platform.admin.get_best_trials(job["id"], max_count=2)
    assert len(best) == 2 and best[0]["score"] >= best[1]["score"]
    # trial logs made it into the meta store
    logs = platform.admin.get_trial_logs(best[0]["id"])
    assert any(r["record"].get("type") == "plot" for r in logs)

    # chips were released after the job stopped
    assert platform.allocator.free_chips == platform.allocator.n_chips

    # --- Serving (§3.2 + §3.3) ---
    inf = platform.admin.create_inference_job(dev["id"], job["id"],
                                              max_models=2)
    inf_detail = platform.admin.get_inference_job(inf["id"])
    assert inf_detail["status"] == "RUNNING"
    host = inf_detail["predictor_host"]
    assert host

    # wait for workers to warm up + register
    from rafiki_tpu.cache import Cache
    cache = Cache(platform.bus)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if len(cache.running_workers(inf["id"])) == 2:
            break
        time.sleep(0.2)
    assert len(cache.running_workers(inf["id"])) == 2

    val = load_image_dataset(synth_image_data[1])
    from rafiki_tpu.cache import encode_payload
    resp = requests.post(
        f"http://{host}/predict",
        json={"queries": [encode_payload(val.images[i]) for i in range(8)]},
        timeout=120)
    assert resp.status_code == 200, resp.text
    preds = resp.json()["predictions"]
    assert len(preds) == 8
    acc = np.mean([int(np.argmax(p)) == val.labels[i]
                   for i, p in enumerate(preds)])
    assert acc > 0.3  # ensembled learnable-synth accuracy

    # --- On-demand device profiling (r17): the admin path queues a
    # __profile__ control frame on a LIVE worker; the artifact appears
    # and serving is undisturbed — every request during the session is
    # answered (counter-proven against the frontend's own stats).
    import os

    before = requests.get(f"http://{host}/stats",
                          timeout=30).json()["requests"]
    out = platform.admin.profile_inference_job(inf["id"],
                                               duration_s=1.0)
    assert out["service_id"] and out["profile_dir"]
    for _ in range(4):  # traffic INSIDE and after the session window
        resp = requests.post(
            f"http://{host}/predict",
            json={"queries": [encode_payload(val.images[0])]},
            timeout=120)
        assert resp.status_code == 200, resp.text
        time.sleep(0.4)
    after = requests.get(f"http://{host}/stats",
                         timeout=30).json()["requests"]
    assert after - before == 4  # nothing rejected, nothing stalled
    deadline = time.monotonic() + 20
    files = []
    while time.monotonic() < deadline and not files:
        files = [os.path.join(r, f)
                 for r, _, fs in os.walk(out["profile_dir"])
                 for f in fs]
        time.sleep(0.2)
    assert files, "profile session produced no artifact"
    # a bogus duration clamps instead of erroring; a stopped job 400s
    with pytest.raises(ValueError):
        platform.admin.profile_inference_job("nope", duration_s=1.0)

    platform.admin.stop_inference_job(inf["id"])
    assert platform.admin.get_inference_job(inf["id"])["status"] == "STOPPED"
    # all chips free again
    assert platform.allocator.free_chips == platform.allocator.n_chips


def test_rest_client_roundtrip(platform, synth_image_data):
    """The same flow through the REST API + Client SDK (upstream
    quickstart shape)."""
    from rafiki_tpu.client import Client

    train_path, val_path = synth_image_data
    client = Client(admin_port=platform.admin_port)
    client.login("superadmin@rafiki", "rafiki")
    client.create_user("mdev@x.c", "pw", UserType.MODEL_DEVELOPER)

    client2 = Client(admin_port=platform.admin_port)
    client2.login("mdev@x.c", "pw")
    model = client2.create_model("ff-rest", TaskType.IMAGE_CLASSIFICATION,
                                 FF_CLASS)
    models = client2.get_models(task=TaskType.IMAGE_CLASSIFICATION)
    assert any(m["id"] == model["id"] for m in models)

    job = client2.create_train_job(
        "rest-app", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 1}, train_path, val_path)
    done = client2.wait_until_train_job_done(job["id"], timeout=600)
    assert done["status"] == "STOPPED"
    best = client2.get_best_trials_of_train_job(job["id"], max_count=1)
    assert best and best[0]["score"] > 0.3

    inf = client2.create_inference_job(job["id"], max_models=1)
    host = client2.get_inference_job(inf["id"])["predictor_host"]

    val = load_image_dataset(val_path)
    out = client2.predict(host, query=val.images[0])
    assert len(out["prediction"]) == val.n_classes
    client2.stop_inference_job(inf["id"])
    client2.stop_train_job(job["id"])


def test_auth_rejections(platform):
    from rafiki_tpu.client import Client, ClientError

    client = Client(admin_port=platform.admin_port)
    with pytest.raises(ClientError) as e:
        client.login("superadmin@rafiki", "wrong")
    assert e.value.status == 401
    # no token → 401
    with pytest.raises(ClientError) as e:
        client.get_models()
    assert e.value.status == 401
    # app developer cannot create users
    client.login("superadmin@rafiki", "rafiki")
    client.create_user("app@x.c", "pw", UserType.APP_DEVELOPER)
    client3 = Client(admin_port=platform.admin_port)
    client3.login("app@x.c", "pw")
    with pytest.raises(ClientError) as e:
        client3.create_user("x@y.z", "pw", UserType.ADMIN)
    assert e.value.status == 403


def test_ownership_enforced(platform, synth_image_data):
    """A non-admin user cannot read or stop another user's jobs."""
    from rafiki_tpu.client import Client, ClientError

    train_path, val_path = synth_image_data
    dev, model = _register_model(platform, name="ff-own")
    job = platform.admin.create_train_job(
        dev["id"], "own-app", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 1}, train_path, val_path)

    root = Client(admin_port=platform.admin_port)
    root.login("superadmin@rafiki", "rafiki")
    root.create_user("other@x.c", "pw", UserType.APP_DEVELOPER)
    other = Client(admin_port=platform.admin_port)
    other.login("other@x.c", "pw")
    for fn in (lambda: other.get_train_job(job["id"]),
               lambda: other.stop_train_job(job["id"]),
               lambda: other.get_best_trials_of_train_job(job["id"]),
               lambda: other.create_inference_job(job["id"])):
        with pytest.raises(ClientError) as e:
            fn()
        assert e.value.status == 403
    # admins can read anyone's job
    assert root.get_train_job(job["id"])["id"] == job["id"]
    platform.admin.wait_until_train_job_done(job["id"], timeout=600)


def test_failing_model_trips_circuit_breaker(platform, synth_image_data):
    """A deterministically failing model must not spin forever: the
    worker gives up after max_consecutive_errors."""
    train_path, val_path = synth_image_data
    dev = platform.admin.create_user("fdev@x.c", "pw",
                                     UserType.MODEL_DEVELOPER)
    model = platform.admin.create_model(
        dev["id"], "boom", TaskType.IMAGE_CLASSIFICATION, "AlwaysFails",
        model_source=(
            "from rafiki_tpu.model import BaseModel, FixedKnob\n"
            "class AlwaysFails(BaseModel):\n"
            "    @staticmethod\n"
            "    def get_knob_config():\n"
            "        return {'k': FixedKnob(1)}\n"
            "    def train(self, p, **kw): raise RuntimeError('broken')\n"
            "    def evaluate(self, p): return 0.0\n"
            "    def predict(self, qs): return []\n"
            "    def dump_parameters(self): return {}\n"
            "    def load_parameters(self, p): pass\n"))
    job = platform.admin.create_train_job(
        dev["id"], "boom-app", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 100},
        train_path, val_path)
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=120)
    trials = platform.meta.get_trials_of_train_job(job["id"])
    assert 1 <= len(trials) <= 5  # capped, not 100
    assert all(t["status"] == TrialStatus.ERRORED for t in trials)


def test_gpu_count_budget_alias(platform, synth_image_data):
    """Reference scripts pass GPU_COUNT; it maps to CHIP_COUNT."""
    from rafiki_tpu.admin.services_manager import normalize_budget

    b = normalize_budget({"GPU_COUNT": 4, "MODEL_TRIAL_COUNT": 2})
    assert b == {"CHIP_COUNT": 4, "MODEL_TRIAL_COUNT": 2}


def test_parallel_workers_respect_trial_budget(platform, synth_image_data):
    """N workers sharing one advisor must not overshoot MODEL_TRIAL_COUNT
    (the proposal-issuance cap lives in the advisor, the single
    coordinator — worker-side checks alone race)."""
    train_path, val_path = synth_image_data
    dev, model = _register_model(platform, name="ff-budget")
    job = platform.admin.create_train_job(
        dev["id"], "budget-app", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 3, BudgetOption.CHIP_COUNT: 3},
        train_path, val_path)
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    trials = platform.meta.get_trials_of_train_job(job["id"])
    assert len(trials) == 3
    assert all(t["status"] == TrialStatus.COMPLETED for t in trials)
    # three distinct workers existed
    train_svcs = [s for s in platform.meta.get_services()
                  if s["service_type"] == ServiceType.TRAIN]
    assert len(train_svcs) == 3


def test_weighted_ensemble_combiner():
    from rafiki_tpu.predictor.predictor import ensemble_predictions

    # A packed worker's reply (weight 2, already the mean of 2 members)
    # plus a single-model worker: result = unweighted mean over 3 trials.
    packed = [0.6, 0.4]   # mean of two members
    single = [0.0, 1.0]
    out = ensemble_predictions([packed, single], weights=[2, 1])
    np.testing.assert_allclose(out, [(0.6 * 2 + 0.0) / 3,
                                     (0.4 * 2 + 1.0) / 3])
    # errors are dropped with their weights
    out = ensemble_predictions([{"error": "x"}, single], weights=[2, 1])
    np.testing.assert_allclose(out, single)
    # non-numeric: weighted majority vote
    assert ensemble_predictions(["a", "b", "a"], weights=[1, 5, 1]) == "b"
    # packed non-numeric members arrive un-combined and vote per trial
    assert ensemble_predictions(
        [{"__members__": ["a", "b"]}, "b"], weights=[2, 1]) == "b"


def test_ensemble_packs_onto_one_chip_group(tmp_path, synth_image_data):
    """With 1 chip and a 2-model ensemble, one worker serves both trials
    (packed) and the endpoint still returns the full-ensemble mean."""
    train_path, val_path = synth_image_data
    p = LocalPlatform(workdir=str(tmp_path / "plat"), http=True,
                      n_chips=1, supervise_interval=0)
    try:
        dev, model = _register_model(p)
        job = p.admin.create_train_job(
            dev["id"], "pack-app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
            train_path, val_path)
        assert p.admin.wait_until_train_job_done(job["id"], timeout=600)
        inf = p.admin.create_inference_job(dev["id"], job["id"],
                                           max_models=2)
        assert len(inf["trial_ids"]) == 2
        # One packed worker (plus the predictor service row), not two:
        workers = [w for w in p.meta.get_inference_job_workers(inf["id"])
                   if w["trial_id"] != "__predictor__"]
        assert len(workers) == 1
        assert set(workers[0]["trial_id"].split(",")) == \
            set(inf["trial_ids"])
        host = p.admin.get_inference_job(inf["id"])["predictor_host"]
        ds = load_image_dataset(val_path)
        from rafiki_tpu.cache import encode_payload
        r = requests.post(f"http://{host}/predict",
                          json={"queries": [encode_payload(ds.images[0])]},
                          timeout=300)
        r.raise_for_status()
        probs = r.json()["predictions"][0]
        assert len(probs) == ds.n_classes
        assert abs(sum(probs) - 1.0) < 1e-3
        p.admin.stop_inference_job(inf["id"])
    finally:
        p.shutdown()


def test_supervise_restarts_dead_train_worker(platform, synth_image_data):
    train_path, val_path = synth_image_data
    dev, model = _register_model(platform, name="ff-sup")
    job = platform.admin.create_train_job(
        dev["id"], "sup-app", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 3},
        train_path, val_path)

    # find the running TRAIN service and simulate a dead container: remove
    # it from the runtime without letting it update its status
    train_svcs = [s for s in platform.meta.get_services()
                  if s["service_type"] == ServiceType.TRAIN]
    assert len(train_svcs) == 1
    svc = train_svcs[0]
    worker = platform.container.get(svc["container_id"])
    worker.stop_flag.set()  # silence the thread
    # wait for the thread to die, then force status back to RUNNING as if
    # the process was SIGKILLed before it could report
    deadline = time.monotonic() + 120
    while worker.running and time.monotonic() < deadline:
        time.sleep(0.1)
    with platform.container._lock:
        platform.container._services.pop(svc["id"], None)
    platform.meta.update_service(svc["id"], status=ServiceStatus.RUNNING)

    restarted = platform.services.supervise()
    assert len(restarted) == 1
    assert platform.meta.get_service(svc["id"])["status"] == \
        ServiceStatus.ERRORED
    new_svc = platform.meta.get_service(restarted[0])
    assert new_svc["service_type"] == ServiceType.TRAIN

    # the restarted worker finishes the job
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    completed = platform.meta.get_trials_of_train_job(
        job["id"], status=TrialStatus.COMPLETED)
    assert len(completed) == 3


def test_supervisor_thread_sweeps_automatically(tmp_path, synth_image_data):
    """A platform with a supervise interval detects a dead worker without
    anyone calling supervise() by hand (the serve-node path)."""
    train_path, val_path = synth_image_data
    p = LocalPlatform(workdir=str(tmp_path / "sup"),
                      supervise_interval=0.2)
    try:
        dev, model = _register_model(p, name="ff-auto-sup")
        job = p.admin.create_train_job(
            dev["id"], "auto-sup", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 2},
            train_path, val_path)
        svc = [s for s in p.meta.get_services()
               if s["service_type"] == ServiceType.TRAIN][0]
        worker = p.container.get(svc["container_id"])
        worker.stop_flag.set()
        deadline = time.monotonic() + 120
        while worker.running and time.monotonic() < deadline:
            time.sleep(0.1)
        with p.container._lock:
            p.container._services.pop(svc["id"], None)
        p.meta.update_service(svc["id"], status=ServiceStatus.RUNNING)

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if p.meta.get_service(svc["id"])["status"] == \
                    ServiceStatus.ERRORED:
                break
            time.sleep(0.2)
        assert p.meta.get_service(svc["id"])["status"] == \
            ServiceStatus.ERRORED, "supervisor thread never swept"
        assert p.admin.wait_until_train_job_done(job["id"], timeout=600)
    finally:
        p.shutdown()


def test_inference_pipeline_env_toggle(monkeypatch):
    """RAFIKI_TPU_SERVING_PIPELINE: 0/1 force the one-burst-in-flight
    overlap off/on (the bench's on-vs-off comparison rides this);
    the default "auto" defers to a startup sync-latency measurement
    (pipeline is None until the worker's run() resolves it)."""
    from rafiki_tpu.bus import MemoryBus
    from rafiki_tpu.worker.inference import InferenceWorker

    bus = MemoryBus()
    # The operator env tunable may be exported in the ambient shell;
    # the default-behavior assertion needs it absent.
    monkeypatch.delenv("RAFIKI_TPU_SERVING_PIPELINE", raising=False)
    w = InferenceWorker("s", "j", "t", None, None, bus)
    assert w.pipeline is None  # default: auto, resolved at startup
    monkeypatch.setenv("RAFIKI_TPU_SERVING_PIPELINE", "0")
    assert InferenceWorker("s", "j", "t", None, None, bus).pipeline \
        is False
    monkeypatch.setenv("RAFIKI_TPU_SERVING_PIPELINE", "1")
    assert InferenceWorker("s", "j", "t", None, None, bus).pipeline \
        is True
    # An explicit constructor arg beats the env var.
    monkeypatch.setenv("RAFIKI_TPU_SERVING_PIPELINE", "0")
    assert InferenceWorker("s", "j", "t", None, None, bus,
                           pipeline=True).pipeline is True
    # The auto measurement itself: a tiny dispatch round-trip, finite
    # and non-negative (on the CPU test backend it is ~microseconds,
    # which correctly resolves auto to pipelining OFF).
    from rafiki_tpu.worker.inference import _sync_latency

    lat = _sync_latency()
    assert 0.0 <= lat < 5.0


def test_predictor_round_robins_same_bin_replicas():
    """Same-trial-bin workers are REPLICAS: each request picks one per
    bin (rotating), never all — replicas must not double-weight their
    trials in the ensemble."""
    from rafiki_tpu.bus import MemoryBus
    from rafiki_tpu.cache import Cache
    from rafiki_tpu.predictor.predictor import Predictor

    bus = MemoryBus()
    cache = Cache(bus)
    cache.register_worker("job", "wA1", info={"trial_id": "tA"})
    cache.register_worker("job", "wA2", info={"trial_id": "tA"})
    cache.register_worker("job", "wB", info={"trial_id": "tB"})
    p = Predictor("job", bus, worker_wait_timeout=1.0)
    picks = [tuple(sorted(p._choose_workers())) for _ in range(4)]
    for pick in picks:
        assert len(pick) == 2          # one per bin, not three workers
        assert "wB" in pick            # the singleton bin always serves
        assert ("wA1" in pick) != ("wA2" in pick)
    # The replica choice rotates across requests.
    assert len(set(picks)) == 2


def test_predictor_prunes_bins_of_departed_workers():
    """The worker->bin memo must not grow monotonically across worker
    restarts (a long-lived predictor under churn would otherwise leak a
    row per restart, forever)."""
    from rafiki_tpu.bus import MemoryBus
    from rafiki_tpu.cache import Cache
    from rafiki_tpu.predictor.predictor import Predictor

    bus = MemoryBus()
    cache = Cache(bus)
    cache.register_worker("job", "w-live", info={"trial_id": "t"})
    p = Predictor("job", bus, worker_wait_timeout=1.0)
    for i in range(40):  # churned-away workers, memoized then gone
        p._bins[f"w-dead-{i}"] = "t-old"
    assert p._choose_workers() == ["w-live"]
    assert set(p._bins) == {"w-live"}


def test_second_primary_on_same_workdir_is_refused(tmp_path):
    """Two primaries sharing one workdir share a node_id by design
    (restart stability) — so a LIVE second one must be refused at
    startup, before its supervise sweep can kill the first's workers."""
    from rafiki_tpu.platform import LocalPlatform

    p1 = LocalPlatform(workdir=str(tmp_path / "w"), supervise_interval=0)
    try:
        with pytest.raises(RuntimeError, match="another primary"):
            LocalPlatform(workdir=str(tmp_path / "w"),
                          supervise_interval=0)
    finally:
        p1.shutdown()
    # A clean restart of the SAME node (after shutdown) is legitimate.
    LocalPlatform(workdir=str(tmp_path / "w"),
                  supervise_interval=0).shutdown()


@pytest.mark.slow
def test_inference_replica_attach_keeps_ensemble_semantics(
        platform, synth_image_data):
    """attach_inference_workers adds a same-bin replica: predictions
    stay numerically consistent (no double weighting) and the extra
    worker takes live traffic."""
    import requests as rq

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.model import load_image_dataset

    train_path, val_path = synth_image_data
    dev, model = _register_model(platform)
    job = platform.admin.create_train_job(
        dev["id"], "rep-app", TaskType.IMAGE_CLASSIFICATION,
        [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 1},
        train_path, val_path)
    assert platform.admin.wait_until_train_job_done(job["id"], timeout=600)
    inf = platform.admin.create_inference_job(dev["id"], job["id"],
                                              max_models=1)
    host = platform.admin.get_inference_job(inf["id"])["predictor_host"]
    cache = Cache(platform.bus)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline and \
            len(cache.running_workers(inf["id"])) < 1:
        time.sleep(0.2)

    val = load_image_dataset(val_path)
    q = {"queries": [encode_payload(val.images[i]) for i in range(4)]}
    before = rq.post(f"http://{host}/predict", json=q,
                     timeout=120).json()["predictions"]

    attached = platform.admin.attach_inference_workers(inf["id"])
    assert len(attached) == 1
    while time.monotonic() < deadline and \
            len(cache.running_workers(inf["id"])) < 2:
        time.sleep(0.2)
    assert len(cache.running_workers(inf["id"])) == 2

    # Several requests: all succeed (both replicas serve) and match the
    # pre-replica ensemble output — a replica is capacity, not weight.
    for _ in range(4):
        after = rq.post(f"http://{host}/predict", json=q,
                        timeout=120).json()["predictions"]
        np.testing.assert_allclose(np.asarray(after),
                                   np.asarray(before), atol=1e-5)
    platform.admin.stop_inference_job(inf["id"])
