"""Two-node scale-out rehearsal (VERDICT r1 item 9; SURVEY.md §2.10).

Node A (primary) runs the admin + advisor + one train worker; node B is
a real ``python -m rafiki_tpu join`` subprocess sharing A's meta store
(sqlite file), params dir and TCP bus across a socket boundary. One
train job's trials land on BOTH nodes' workers, coordinated by the one
bus-hosted advisor.
"""

import os
import subprocess
import sys
import time

import pytest

from rafiki_tpu.bus import serve_broker
from rafiki_tpu.constants import BudgetOption, TaskType, UserType
from rafiki_tpu.platform import LocalPlatform

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"


@pytest.fixture()
def broker():
    server = serve_broker("127.0.0.1", 0, native=False)
    yield server
    server.stop()


@pytest.mark.slow
@pytest.mark.slower
def test_one_job_split_across_two_nodes(tmp_path, synth_image_data,
                                        broker):
    train_path, val_path = synth_image_data
    shared = str(tmp_path / "shared")

    node_a = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                           supervise_interval=0)
    proc = None
    try:
        dev = node_a.admin.create_user("dev@x.c", "pw",
                                       UserType.MODEL_DEVELOPER)
        model = node_a.admin.create_model(
            dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
        job = node_a.admin.create_train_job(
            dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 10},
            train_path, val_path)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("RAFIKI_TPU_PLATFORM", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "rafiki_tpu", "join",
             "--workdir", shared, "--bus", broker.uri,
             "--train-job", job["id"], "--timeout", "540"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        assert node_a.admin.wait_until_train_job_done(job["id"],
                                                      timeout=600)
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, out.decode()
        assert b"attached 1 worker" in out, out.decode()

        sub = node_a.meta.get_sub_train_jobs(job["id"])[0]
        trials = node_a.meta.get_trials(sub["id"])
        done = [t for t in trials if t["status"] == "COMPLETED"]
        assert len(done) == 10

        # Trials ran on BOTH nodes: the worker ids behind the completed
        # trials must span services from two distinct node_ids.
        node_ids = set()
        for t in done:
            svc = node_a.meta.get_service(t["worker_id"])
            if svc is not None:
                node_ids.add(svc["node_id"])
        assert len(node_ids) >= 2, (
            f"all trials ran on one node: {node_ids}")
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.communicate()
        node_a.shutdown()


def test_secondary_shutdown_leaves_no_running_rows(tmp_path,
                                                   synth_image_data,
                                                   broker):
    """Review finding r2: a join node leaving mid-job (timeout, crash
    path through shutdown) must stop ITS services — leaked RUNNING rows
    would read as a live remote worker forever and block the primary's
    job-completion detection."""
    train_path, val_path = synth_image_data
    shared = str(tmp_path / "shared")
    node_a = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                           supervise_interval=0)
    node_b = None
    try:
        dev = node_a.admin.create_user("dev@x.c", "pw",
                                       UserType.MODEL_DEVELOPER)
        model = node_a.admin.create_model(
            dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
        job = node_a.admin.create_train_job(
            dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 6},
            train_path, val_path)

        node_b = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                               supervise_interval=0,
                               stop_jobs_on_shutdown=False,
                               node_id="vm/join-test")
        attached = node_b.admin.attach_workers(job["id"])
        assert attached
        node_b.shutdown()  # leaves mid-job
        node_b = None

        rows = node_a.meta.get_services(node_id="vm/join-test")
        assert rows and all(r["status"] not in
                            ("RUNNING", "DEPLOYING", "STARTED")
                            for r in rows), rows
        # And the primary still completes the job on its own workers.
        assert node_a.admin.wait_until_train_job_done(job["id"],
                                                      timeout=600)
    finally:
        if node_b is not None:
            node_b.shutdown()
        node_a.shutdown()


def test_restarted_node_sweeps_its_stale_rows(tmp_path):
    """Review finding r2: node identity is stable across restarts of
    the same host+workdir, so a crashed node's RUNNING rows are swept
    (not orphaned) by the restarted process's supervise."""
    from rafiki_tpu.constants import ServiceStatus, ServiceType

    from rafiki_tpu.store import MetaStore

    shared = str(tmp_path / "node")
    p1 = LocalPlatform(workdir=shared, supervise_interval=0)
    node_id = p1.services.node_id
    p1.shutdown()
    # Simulate a crash's aftermath: a RUNNING row (written before the
    # crash) whose container no restarted process knows.
    meta = MetaStore(shared + "/meta.db")
    stale = meta.create_service(ServiceType.ADVISOR,
                                ServiceStatus.RUNNING,
                                container_id="gone", node_id=node_id)
    meta.close()

    p2 = LocalPlatform(workdir=shared, supervise_interval=0)
    try:
        assert p2.services.node_id == node_id  # stable identity
        p2.services.supervise()
        assert p2.meta.get_service(stale["id"])["status"] == \
            ServiceStatus.ERRORED
    finally:
        p2.shutdown()


def test_dead_foreign_node_lease_expires(tmp_path):
    """Review finding r2: a join node that dies WITHOUT shutdown
    (SIGKILL, power loss) must not block the primary forever — its
    RUNNING rows are credible only while its heartbeat lease is fresh;
    expiry makes train_services_active False and supervise marks the
    rows ERRORED."""
    import time as _time

    from rafiki_tpu.constants import ServiceStatus, ServiceType

    p = LocalPlatform(workdir=str(tmp_path / "n"), supervise_interval=0)
    try:
        job = p.meta.create_train_job("u", "app", "IMAGE_CLASSIFICATION",
                                      {}, "tr", "va", status="RUNNING")
        sub = p.meta.create_sub_train_job(job["id"], "m",
                                          status="RUNNING")
        svc = p.meta.create_service(ServiceType.TRAIN,
                                    ServiceStatus.RUNNING,
                                    container_id="gone",
                                    node_id="otherhost/deadbeef")
        p.meta.add_train_job_worker(svc["id"], sub["id"])

        # Fresh lease (set at creation): trusted as live.
        assert p.services.train_services_active(job["id"])
        p.services.supervise()
        assert p.meta.get_service(svc["id"])["status"] == \
            ServiceStatus.RUNNING

        # Lease expires: no longer live; sweep marks it errored.
        p.meta.update_service(
            svc["id"],
            heartbeat_at=_time.time() - p.services.NODE_LEASE - 1)
        assert not p.services.train_services_active(job["id"])
        p.services.supervise()
        assert p.meta.get_service(svc["id"])["status"] == \
            ServiceStatus.ERRORED

        # A heartbeat refreshes the lease for a node's own rows.
        svc2 = p.meta.create_service(ServiceType.TRAIN,
                                     ServiceStatus.RUNNING,
                                     node_id="otherhost/deadbeef")
        p.meta.update_service(
            svc2["id"],
            heartbeat_at=_time.time() - p.services.NODE_LEASE - 1)
        p.meta.touch_node_services("otherhost/deadbeef")
        fresh = p.meta.get_service(svc2["id"])["heartbeat_at"]
        assert _time.time() - fresh < 5
    finally:
        p.shutdown()


def test_jax_distributed_cpu_pair(tmp_path):
    """The multi-host wiring (jax.distributed.initialize, the flags the
    serve CLI passes) on a CPU pair: two processes, one coordinator,
    global device count = 2."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    code = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.distributed.initialize(\n"
        "    coordinator_address='127.0.0.1:%d',\n"
        "    num_processes=2, process_id=int(sys.argv[1]))\n"
        "print('GLOBAL', jax.device_count(), 'LOCAL',\n"
        "      jax.local_device_count())\n" % port)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local CPU device per process
    procs = [subprocess.Popen([sys.executable, "-c", code, str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for i in range(2)]
    outs = []
    deadline = time.time() + 180
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(5.0,
                                               deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out.decode())
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "GLOBAL 2 LOCAL 1" in out, out


def test_status_reports_cluster_nodes(tmp_path, synth_image_data,
                                      broker):
    """/status carries the per-node cluster view when several nodes
    share the meta store: each node's service count + heartbeat age.

    Trials block on a gate file until the joined node has been observed
    in /status — without the gate, node_a's workers can spend the whole
    4-trial budget before node_b's worker ever reaches RUNNING, and the
    poll below can never succeed (the r4 flake)."""
    train_path, val_path = synth_image_data
    shared = str(tmp_path / "shared")
    gate = str(tmp_path / "gate")
    gated_source = (
        "import os, time\n"
        "from rafiki_tpu.model import BaseModel, FixedKnob\n"
        "class GatedFF(BaseModel):\n"
        "    @staticmethod\n"
        "    def get_knob_config():\n"
        "        return {'k': FixedKnob(1)}\n"
        "    def train(self, p, **kw):\n"
        f"        while not os.path.exists({gate!r}):\n"
        "            time.sleep(0.05)\n"
        "    def evaluate(self, p): return 0.5\n"
        "    def predict(self, qs): return [0.0 for _ in qs]\n"
        "    def dump_parameters(self): return {}\n"
        "    def load_parameters(self, p): pass\n")
    node_a = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                           supervise_interval=0)
    node_b = None
    try:
        dev = node_a.admin.create_user("dev@x.c", "pw",
                                       UserType.MODEL_DEVELOPER)
        model = node_a.admin.create_model(
            dev["id"], "ff", TaskType.IMAGE_CLASSIFICATION, "GatedFF",
            model_source=gated_source)
        job = node_a.admin.create_train_job(
            dev["id"], "app", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 4},
            train_path, val_path)
        node_b = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                               supervise_interval=0,
                               stop_jobs_on_shutdown=False,
                               node_id="vm/join-status")
        assert node_b.admin.attach_workers(job["id"])
        # The joined worker reaches RUNNING asynchronously — poll. It
        # CANNOT exit early: every trial is blocked on the gate file, so
        # the budget is still open when it starts.
        deadline = time.monotonic() + 120
        status = node_a.admin.get_status()
        while "vm/join-status" not in status["nodes"] \
                and time.monotonic() < deadline:
            time.sleep(0.2)
            status = node_a.admin.get_status()
        assert status["node_id"] == node_a.services.node_id
        assert "vm/join-status" in status["nodes"]
        joined = status["nodes"]["vm/join-status"]
        assert joined["services"] >= 1
        assert joined["heartbeat_age_s"] is not None
        assert joined["heartbeat_age_s"] < 60
        with open(gate, "w"):
            pass  # open the gate: let all trials complete
        assert node_a.admin.wait_until_train_job_done(job["id"],
                                                      timeout=600)
    finally:
        # The gate must open even when an assertion above failed, or
        # every blocked trial thread would spin on os.path.exists for
        # the rest of the pytest session.
        with open(gate, "w"):
            pass
        if node_b is not None:
            node_b.shutdown()
        node_a.shutdown()


@pytest.mark.slow
def test_broker_restart_mid_serving_recovers(tmp_path, synth_image_data,
                                             monkeypatch):
    """SURVEY.md §2.10 durability (r2 verdict item 4): the broker holds
    queue/registry state in memory, so killing it mid-serving forgets
    every worker registration. Workers must re-register against the
    restarted broker (lease-style re-assertion + error-path recovery)
    and serving must resume — no supervise restart, no stranded
    workers."""
    import requests

    from rafiki_tpu.bus import serve_broker
    from rafiki_tpu.cache import encode_payload
    from rafiki_tpu.model import load_image_dataset

    monkeypatch.setenv("RAFIKI_TPU_WORKER_REREGISTER", "1.0")
    train_path, val_path = synth_image_data
    broker = serve_broker("127.0.0.1", 0, native=False)
    port = broker.port
    platform = LocalPlatform(workdir=str(tmp_path / "plat"),
                             bus_uri=broker.uri, http=True,
                             supervise_interval=0)
    try:
        user = platform.admin.create_user("b@x.c", "pw",
                                          UserType.MODEL_DEVELOPER)
        model = platform.admin.create_model(
            user["id"], "ff", TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
        job = platform.admin.create_train_job(
            user["id"], "serve", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 1},
            train_path, val_path)
        assert platform.admin.wait_until_train_job_done(job["id"],
                                                        timeout=600)
        inf = platform.admin.create_inference_job(user["id"], job["id"],
                                                  max_models=1)
        host = platform.admin.get_inference_job(
            inf["id"])["predictor_host"]
        ds = load_image_dataset(val_path)
        batch = [encode_payload(ds.images[i]) for i in range(4)]

        def predict_ok(timeout: float) -> bool:
            try:
                r = requests.post(f"http://{host}/predict",
                                  json={"queries": batch},
                                  timeout=timeout)
                return (r.status_code == 200
                        and len(r.json()["predictions"]) == 4)
            except Exception:
                return False

        deadline = time.time() + 120
        while not predict_ok(60) and time.time() < deadline:
            time.sleep(0.5)
        assert predict_ok(60), "serving never became ready"

        # Kill the broker: every registration and queued burst dies
        # with its in-memory state. Restart EMPTY on the same port.
        broker.stop()
        time.sleep(1.0)
        broker = serve_broker("127.0.0.1", port, native=False)

        # QPS must recover: the workers' 1s re-registration lease
        # re-populates the fresh broker's registry, and the predictor's
        # next scan finds them.
        deadline = time.time() + 60
        recovered = False
        while time.time() < deadline:
            if predict_ok(30):
                recovered = True
                break
            time.sleep(1.0)
        assert recovered, "serving did not recover after broker restart"
        platform.admin.stop_inference_job(inf["id"])
    finally:
        platform.shutdown()
        broker.stop()


def test_persistent_bus_op_error_escalates_to_errored():
    """ADVICE r3: a broker that persistently REPORTS op failures
    (protocol/version skew — BusOpError, not a transport outage) must
    not leave the worker warn-looping as RUNNING forever: after
    max_op_errors consecutive laps with no successful iteration the
    serve loop re-raises and the service goes ERRORED. Transport
    failures (ConnectionError) keep retrying indefinitely."""
    from rafiki_tpu.bus import BusOpError, MemoryBus
    from rafiki_tpu.worker.inference import InferenceWorker

    class FakeMeta:
        def __init__(self):
            self.statuses = []

        def update_service(self, service_id, **fields):
            self.statuses.append(fields.get("status"))

    def make_worker(exc_factory, fail_forever=True, n_failures=0):
        w = InferenceWorker("svc", "ij", "tr", FakeMeta(), None,
                            MemoryBus(), batch_timeout=0.0)
        w.max_op_errors = 3
        w._load_model = lambda: type(
            "M", (), {"predict_submit": staticmethod(
                lambda q: (lambda: [0] * len(q)))})()
        calls = {"n": 0}

        class FlakyCache:
            def register_worker(self, *a, **k):
                pass

            def unregister_worker(self, *a, **k):
                pass

            def pop_queries(self, *a, **k):
                calls["n"] += 1
                if fail_forever or calls["n"] <= n_failures:
                    raise exc_factory()
                w.stop_flag.set()
                return []

        w.cache = FlakyCache()
        # Recovery laps sleep via stop_flag.wait(1.0); shrink it so the
        # test runs in well under a second.
        real_wait = w.stop_flag.wait
        w.stop_flag.wait = lambda t=None: real_wait(0.01)
        return w

    # Persistent op errors: escalates after max_op_errors laps.
    w = make_worker(lambda: BusOpError("bus error: unknown op"))
    with pytest.raises(BusOpError):
        w.run()
    assert w.meta.statuses[-1] == "ERRORED"

    # Transport errors beyond the cap: never escalates; a later stop
    # lands STOPPED.
    w2 = make_worker(lambda: ConnectionError("broker down"),
                     fail_forever=False, n_failures=6)
    w2.run()
    assert w2.meta.statuses[-1] == "STOPPED"
