"""GPipe pipeline schedule (rafiki_tpu.ops.pipeline): exactness, grads,
pp sharding placement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.ops import pipelined
from rafiki_tpu.parallel import build_mesh, shard_variables

D = 16


def _stacked_params(rng, s=4):
    return {"stage_w": jnp.asarray(rng.standard_normal((s, D, D)) * 0.3,
                                   jnp.float32),
            "stage_b": jnp.asarray(rng.standard_normal((s, D)) * 0.1,
                                   jnp.float32)}


def _stage_fn(params, x):
    return jnp.tanh(x @ params["stage_w"] + params["stage_b"])


def _sequential(params, x):
    for i in range(params["stage_w"].shape[0]):
        x = _stage_fn(jax.tree_util.tree_map(lambda a: a[i], params), x)
    return x


def test_pipeline_matches_sequential(rng):
    mesh = build_mesh(jax.devices(), pp=4)
    params = _stacked_params(rng, s=4)
    x = jnp.asarray(rng.standard_normal((32, D)), jnp.float32)
    run = pipelined(_stage_fn, mesh, n_microbatches=8)
    out = run(params, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_full_depth(rng):
    """pp = all 8 devices, microbatches == stages (worst bubble)."""
    mesh = build_mesh(jax.devices(), pp=8)
    params = _stacked_params(rng, s=8)
    x = jnp.asarray(rng.standard_normal((16, D)), jnp.float32)
    out = pipelined(_stage_fn, mesh, n_microbatches=8)(params, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_sequential(rng):
    mesh = build_mesh(jax.devices(), pp=4)
    params = _stacked_params(rng, s=4)
    x = jnp.asarray(rng.standard_normal((16, D)), jnp.float32)
    run = pipelined(_stage_fn, mesh, n_microbatches=4)

    g_pipe = jax.grad(lambda p: (run(p, x) ** 2).sum())(params)
    g_seq = jax.grad(lambda p: (_sequential(p, x) ** 2).sum())(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   atol=1e-4, rtol=1e-4)


def test_pipeline_jit_with_pp_sharded_params(rng):
    """The production composition: stage-stacked params placed with
    P('pp', ...) by the sharding rules, pipeline under jit."""
    mesh = build_mesh(jax.devices(), pp=4)
    params = _stacked_params(rng, s=4)
    placed = shard_variables(params, mesh)
    assert "pp" in str(placed["stage_w"].sharding.spec)
    assert "pp" in str(placed["stage_b"].sharding.spec)
    x = jnp.asarray(rng.standard_normal((32, D)), jnp.float32)
    run = jax.jit(pipelined(_stage_fn, mesh, n_microbatches=8))
    out = run(placed, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(params, x)),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_batch_not_divisible_raises(rng):
    mesh = build_mesh(jax.devices(), pp=4)
    params = _stacked_params(rng, s=4)
    x = jnp.asarray(rng.standard_normal((30, D)), jnp.float32)
    with pytest.raises(Exception):
        pipelined(_stage_fn, mesh, n_microbatches=8)(params, x)


def test_pipeline_rejects_over_stacked_params(rng):
    """Stacking more stages than mesh pp must be loud, not silently
    drop layers."""
    mesh = build_mesh(jax.devices(), pp=4)
    params = _stacked_params(rng, s=8)  # 8 stages on a pp=4 mesh
    x = jnp.asarray(rng.standard_normal((32, D)), jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        pipelined(_stage_fn, mesh, n_microbatches=8)(params, x)
