import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import load_image_dataset, test_model_class
from rafiki_tpu.models import JaxFeedForward


def test_feedforward_end_to_end(synth_image_data):
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(4)]
    result = test_model_class(
        JaxFeedForward, TaskType.IMAGE_CLASSIFICATION,
        train_path, val_path, test_queries=queries,
        knobs={"hidden_layer_count": 1, "hidden_layer_units": 32,
               "learning_rate": 3e-3, "batch_size": 32, "max_epochs": 5})
    # Synthetic data is learnable: must beat chance (0.25) comfortably.
    assert result.score > 0.5, f"score too low: {result.score}"
    assert len(result.predictions) == 4
    for p in result.predictions:
        assert len(p) == 4
        assert abs(sum(p) - 1.0) < 1e-3
    # Training logged plot definitions + per-epoch values.
    types = {r["type"] for r in result.log_records}
    assert "plot" in types and "values" in types


def test_small_dataset_still_trains(tmp_path):
    # Regression: dataset smaller than batch_size must still take real steps.
    from rafiki_tpu.datasets import make_synthetic_image_dataset
    train_path, val_path = make_synthetic_image_dataset(
        str(tmp_path), n_train=48, n_val=32, image_shape=(8, 8, 1),
        n_classes=2, noise=0.1)
    m = JaxFeedForward(hidden_layer_count=1, hidden_layer_units=32,
                       learning_rate=5e-3, batch_size=128, max_epochs=8)
    m.train(train_path)
    assert m.evaluate(val_path) > 0.8


def test_predict_empty_queries(synth_image_data):
    train_path, _ = synth_image_data
    m = JaxFeedForward(hidden_layer_count=1, hidden_layer_units=16,
                       learning_rate=1e-3, batch_size=64, max_epochs=1)
    m.train(train_path)
    assert m.predict([]) == []


def test_param_roundtrip_exact(synth_image_data):
    train_path, val_path = synth_image_data
    knobs = {"hidden_layer_count": 1, "hidden_layer_units": 16,
             "learning_rate": 1e-3, "batch_size": 64, "max_epochs": 1}
    m = JaxFeedForward(**knobs)
    m.train(train_path)
    params = m.dump_parameters()
    # r5 contract: leaves are array-likes — numpy, or still-device jax
    # arrays (the ParamStore's write-behind flush pulls them in the
    # background); every consumer normalises via np.asarray.
    import jax

    assert all(isinstance(v, (np.ndarray, jax.Array))
               for v in params.values())

    m2 = JaxFeedForward(**knobs)
    m2.load_parameters(params)
    ds = load_image_dataset(val_path)
    p1 = m.predict_proba(ds.normalized()[:8])
    p2 = m2.predict_proba(ds.normalized()[:8])
    np.testing.assert_allclose(p1, p2, atol=1e-5)


def test_augmentation_skips_tiny_images():
    """Parity-regression guard (r4): the CIFAR crop recipe's ±4-pixel
    crop is half the content of an 8x8 digit scan — measured on UCI
    digits it drove an ENAS child from 0.93 to 0.21 accuracy. Images
    below the 16-pixel floor pass through untouched; CIFAR/fashion
    scales still augment."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.model.jax_model import pad_crop_flip_graph

    rng = jax.random.key(0)
    tiny = jnp.arange(2 * 8 * 8 * 1, dtype=jnp.float32).reshape(2, 8, 8, 1)
    out = pad_crop_flip_graph(tiny, rng)
    assert out is tiny  # untouched, not even a copy
    cifar = jnp.zeros((2, 32, 32, 3), jnp.float32)
    assert pad_crop_flip_graph(cifar, rng).shape == cifar.shape
