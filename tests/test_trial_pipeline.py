"""Cross-trial dataset residency + pipelined trial lifecycle (r9).

Covers the two caches (host dataset cache in ``model/dataset.py``,
device staging cache in ``model/jax_model.py``) and the TrialRunner's
single-slot persist stage: LRU/byte-budget behavior, invalidation
rules (file rewrite, mesh change), the never-donated guarantee, the
counter-based zero-disk-load / zero-H2D regression for trial 2..N,
and persist ordering / drain / retroactive-error semantics.
"""

import threading
import time

import numpy as np
import pytest

import jax

from rafiki_tpu.advisor.base import Proposal
from rafiki_tpu.constants import BudgetOption, TrialStatus
from rafiki_tpu.model import dataset as mod_dataset
from rafiki_tpu.model import jax_model as mod_jax
from rafiki_tpu.model.base import BaseModel
from rafiki_tpu.model.dataset import (load_image_dataset,
                                      write_image_dataset_npz)
from rafiki_tpu.model.knobs import FixedKnob
from rafiki_tpu.model.logger import logger
from rafiki_tpu.models.feedforward import JaxFeedForward
from rafiki_tpu.observe import phases
from rafiki_tpu.parallel import build_mesh
from rafiki_tpu.store import MetaStore, ParamStore
from rafiki_tpu.worker.runner import TrialRunner


@pytest.fixture(autouse=True)
def _fresh_caches():
    mod_dataset.clear_dataset_cache()
    mod_jax.clear_stage_cache()
    yield
    mod_dataset.clear_dataset_cache()
    mod_jax.clear_stage_cache()


def _write_ds(path, n=12, seed=0, hw=8):
    rng = np.random.default_rng(seed)
    imgs = rng.integers(0, 255, (n, hw, hw, 1), dtype=np.uint8)
    labels = np.arange(n) % 3
    return write_image_dataset_npz(imgs, labels, str(path), 3)


# --- Device staging cache ---

def test_stage_cache_hits_and_mesh_change_invalidates(tmp_path):
    p = _write_ds(tmp_path / "a.npz")
    ds = load_image_dataset(p)
    mesh8 = build_mesh(jax.devices())
    d1, l1 = mod_jax.staged_dataset_arrays(p, ds, mesh8)
    d2, l2 = mod_jax.staged_dataset_arrays(p, ds, mesh8)
    assert d2 is d1 and l2 is l1  # resident across calls
    np.testing.assert_array_equal(np.asarray(d1), ds.images)
    np.testing.assert_array_equal(np.asarray(l1),
                                  ds.labels.astype(np.int32))
    # A different chip group is a different key: staged arrays are
    # never served across a mesh change.
    mesh4 = build_mesh(jax.devices()[:4])
    d3, _ = mod_jax.staged_dataset_arrays(p, ds, mesh4)
    assert d3 is not d1
    assert mod_jax.stage_cache_info()["entries"] == 2


def test_stage_cache_byte_budget_lru_eviction(tmp_path, monkeypatch):
    pa = _write_ds(tmp_path / "a.npz", seed=1)
    pb = _write_ds(tmp_path / "b.npz", seed=2)
    dsa, dsb = load_image_dataset(pa), load_image_dataset(pb)
    one = int(dsa.images.nbytes) + 4 * dsa.size
    monkeypatch.setenv(mod_jax.STAGE_CACHE_ENV, str(one + 8))
    mesh = build_mesh(jax.devices())
    da1, _ = mod_jax.staged_dataset_arrays(pa, dsa, mesh)
    mod_jax.staged_dataset_arrays(pb, dsb, mesh)  # evicts a (LRU)
    assert mod_jax.stage_cache_info()["entries"] == 1
    da2, _ = mod_jax.staged_dataset_arrays(pa, dsa, mesh)
    assert da2 is not da1  # a was re-staged after eviction


def test_stage_cache_disabled_by_zero_budget(tmp_path, monkeypatch):
    monkeypatch.setenv(mod_jax.STAGE_CACHE_ENV, "0")
    p = _write_ds(tmp_path / "a.npz")
    ds = load_image_dataset(p)
    mesh = build_mesh(jax.devices())
    d1, _ = mod_jax.staged_dataset_arrays(p, ds, mesh)
    d2, _ = mod_jax.staged_dataset_arrays(p, ds, mesh)
    assert d2 is not d1
    assert mod_jax.stage_cache_info()["entries"] == 0


def _write_tokens(path, n=1200, vocab=512, seed=0):
    from rafiki_tpu.model.dataset import write_token_dataset
    rng = np.random.default_rng(seed)
    return write_token_dataset(rng.integers(0, vocab, n), vocab,
                               str(path))


def test_token_stage_cache_hits_and_mesh_change_invalidates(tmp_path):
    from rafiki_tpu.model.dataset import load_token_dataset

    p = _write_tokens(tmp_path / "tok.npz")
    ds = load_token_dataset(p)
    mesh8 = build_mesh(jax.devices())
    d1 = mod_jax.staged_token_ids(p, ds, mesh8)
    d2 = mod_jax.staged_token_ids(p, ds, mesh8)
    assert d2 is d1  # resident across calls
    np.testing.assert_array_equal(np.asarray(d1),
                                  ds.ids.astype(np.int32))
    mesh4 = build_mesh(jax.devices()[:4])
    assert mod_jax.staged_token_ids(p, ds, mesh4) is not d1
    assert mod_jax.stage_cache_info()["entries"] == 2


def test_token_stage_cache_disabled_by_zero_budget(tmp_path,
                                                   monkeypatch):
    from rafiki_tpu.model.dataset import load_token_dataset

    monkeypatch.setenv(mod_jax.STAGE_CACHE_ENV, "0")
    p = _write_tokens(tmp_path / "tok.npz")
    ds = load_token_dataset(p)
    mesh = build_mesh(jax.devices())
    d1 = mod_jax.staged_token_ids(p, ds, mesh)
    assert mod_jax.staged_token_ids(p, ds, mesh) is not d1
    assert mod_jax.stage_cache_info()["entries"] == 0


def test_lm_eval_2_zero_disk_loads_and_zero_h2d(tmp_path):
    """The r9 trial-2 regression, cloned for the token/LM path: the
    SECOND evaluate of one dataset on one mesh pays no dataset parse
    (host cache hit) and no token H2D (staged stream hit — eval
    windows gather in-graph from device-computed iota indices), and
    both paths agree bit-for-bit with the unstaged host fallback."""
    from rafiki_tpu.models import JaxTransformerLM

    p = _write_tokens(tmp_path / "tok.npz")
    tiny = {"d_model": 256, "n_layers": 2, "seq_len": 256,
            "batch_size": 4, "learning_rate": 1e-2, "train_steps": 20,
            "vocab_size": 512, "quick_train": False}
    m = JaxTransformerLM(**JaxTransformerLM.validate_knobs(tiny))
    m._params = m._init_params()  # eval-only: training is not under test
    ds_b0 = phases.cache_counts("dataset")
    st_b0 = phases.cache_counts("stage")
    acc1 = m.evaluate(p)  # eval 1 pays the misses
    ds_b1 = phases.cache_counts("dataset")
    st_b1 = phases.cache_counts("stage")
    assert st_b1.get("miss", 0) == st_b0.get("miss", 0) + 1
    acc2 = m.evaluate(p)  # eval 2 must be fully resident
    ds_b2 = phases.cache_counts("dataset")
    st_b2 = phases.cache_counts("stage")
    assert acc2 == acc1
    assert ds_b2.get("miss", 0) == ds_b1.get("miss", 0)
    assert st_b2.get("miss", 0) == st_b1.get("miss", 0)
    assert st_b2.get("hit", 0) >= st_b1.get("hit", 0) + 1
    assert ds_b2.get("hit", 0) >= ds_b1.get("hit", 0) + 1
    # Oversized-stream fallback (host np.stack path) agrees exactly.
    import os

    os.environ["RAFIKI_TPU_STAGE_BYTES"] = "0"
    try:
        assert m.evaluate(p) == acc1
    finally:
        os.environ.pop("RAFIKI_TPU_STAGE_BYTES", None)
    # Cache DISABLED must also take the host path: staging would
    # device_put the whole stream uncached on every eval (review
    # finding) — stage counters must not move.
    os.environ[mod_jax.STAGE_CACHE_ENV] = "0"
    try:
        before = phases.cache_counts("stage")
        assert m.evaluate(p) == acc1
        assert phases.cache_counts("stage") == before
    finally:
        os.environ.pop(mod_jax.STAGE_CACHE_ENV, None)


FAST_KNOBS = {"hidden_layer_count": 1, "hidden_layer_units": 16,
              "learning_rate": 3e-3, "batch_size": 64, "max_epochs": 5}


def test_staged_arrays_never_donated_across_trainings(synth_image_data):
    """Train twice on the same dataset: the second training (and its
    eval) must find the FIRST training's staged buffers still valid —
    nothing may have donated or deleted them."""
    train_path, val_path = synth_image_data
    scores = []
    for _ in range(2):
        m = JaxFeedForward(**JaxFeedForward.validate_knobs(FAST_KNOBS))
        m.train(train_path)
        scores.append(float(m.evaluate(val_path)))
        m.destroy()
    assert mod_jax.stage_cache_info()["entries"] == 2  # train + val
    for data, labels in mod_jax._STAGE_CACHE.values():
        assert not data.is_deleted() and not labels.is_deleted()
        np.asarray(data)  # still readable end to end
    # identical data + seed -> the cached path reproduces the score
    assert scores[0] == pytest.approx(scores[1], abs=1e-6)


# --- Zero disk loads / zero full-dataset H2D for trial 2..N ---

class _FixedAdvisor:
    def __init__(self, knobs):
        self.knobs = knobs
        self.n = 0
        self.feedbacks = []

    def propose(self):
        self.n += 1
        return Proposal(trial_no=self.n, knobs=dict(self.knobs))

    def feedback(self, proposal, score):
        self.feedbacks.append((proposal.trial_no, score))


def test_trial_2_zero_disk_loads_and_zero_h2d(tmp_path,
                                              synth_image_data):
    train_path, val_path = synth_image_data
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "p"))
    runner = TrialRunner(JaxFeedForward, _FixedAdvisor(FAST_KNOBS),
                         train_path, val_path, meta, params, "sub-r9",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 3})
    runner.run_one()  # trial 1 pays the misses
    ds_before = phases.cache_counts("dataset")
    st_before = phases.cache_counts("stage")
    runner.run_one()  # trial 2 must be fully resident
    ds_after = phases.cache_counts("dataset")
    st_after = phases.cache_counts("stage")
    assert ds_after.get("miss", 0) == ds_before.get("miss", 0)
    assert st_after.get("miss", 0) == st_before.get("miss", 0)
    # train + eval each hit both caches
    assert ds_after.get("hit", 0) >= ds_before.get("hit", 0) + 2
    assert st_after.get("hit", 0) >= st_before.get("hit", 0) + 2
    meta.close()
    params.close()


# --- Pipelined persist tail ---

CONFIG = {"width": FixedKnob(32)}


def _fake_model(events):
    class _Fake(BaseModel):
        @staticmethod
        def get_knob_config():
            return CONFIG

        def train(self, path, *, shared_params=None, **kw):
            events.append(("train", time.monotonic()))
            logger.log(msg="fake trained")
            self._params = {"w": np.asarray(1.0)}

        def evaluate(self, path):
            return 0.5

        def predict(self, queries):
            return [0 for _ in queries]

        def dump_parameters(self):
            return dict(self._params)

        def load_parameters(self, params):
            self._params = dict(params)

    return _Fake


def test_persist_pipeline_overlaps_orders_and_drains(tmp_path,
                                                     monkeypatch):
    """Trial N+1's work overlaps trial N's (slow) persistence, meta
    commits stay in trial order, the budget stays exact, and run()
    drains — no RUNNING rows survive it."""
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "p"))
    events = []
    orig_save = params.save

    def slow_save(ps, **kw):
        events.append(("save_start", time.monotonic()))
        time.sleep(0.15)
        out = orig_save(ps, **kw)
        events.append(("save_end", time.monotonic()))
        return out

    monkeypatch.setattr(params, "save", slow_save)
    advisor = _FixedAdvisor({"width": 32})
    runner = TrialRunner(_fake_model(events), advisor, "tr", "va",
                         meta, params, "sub-pipe",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 3},
                         pipeline_persist=True)
    rows = runner.run()
    runner.close()
    # run() returns POST-drain rows: terminal status + params id, not
    # the pre-commit RUNNING snapshots run_one took.
    assert [r["status"] for r in rows] == [TrialStatus.COMPLETED] * 3
    assert all(r["params_id"] for r in rows)
    trials = sorted(meta.get_trials("sub-pipe"), key=lambda t: t["no"])
    assert [t["status"] for t in trials] == [TrialStatus.COMPLETED] * 3
    # budget exact despite the pipelined (meta-invisible) completions
    assert advisor.n == 3
    # strict per-trial ordering of the persisted commits
    finished = [t["finished_at"] for t in trials]
    assert finished == sorted(finished)
    # overlap actually happened: some trial trained while the previous
    # trial's save was still in flight
    saves = [(t0, next(t1 for n1, t1 in events
                       if n1 == "save_end" and t1 > t0))
             for n0, t0 in events if n0 == "save_start"]
    trains = [t for n, t in events if n == "train"]
    assert any(s0 < t < s1 for t in trains for s0, s1 in saves), \
        (events,)
    # buffered trial logs were flushed by the tail
    logs = meta.get_trial_logs(trials[0]["id"])
    assert any(r["record"].get("values", {}).get("msg") ==
               "fake trained" or "fake trained" in str(r["record"])
               for r in logs)
    meta.close()
    params.close()


def test_persist_failure_retroactively_errors_trial(tmp_path,
                                                    monkeypatch):
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "p"))

    def bad_save(ps, **kw):
        raise RuntimeError("disk full (injected)")

    monkeypatch.setattr(params, "save", bad_save)
    advisor = _FixedAdvisor({"width": 32})
    runner = TrialRunner(_fake_model([]), advisor, "tr", "va", meta,
                         params, "sub-err",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 1},
                         pipeline_persist=True)
    row = runner.run_one()
    assert row is not None
    runner.drain_persist()
    runner.close()
    trial = meta.get_trials("sub-err")[0]
    assert trial["status"] == TrialStatus.ERRORED
    assert "disk full" in trial["error"]
    # the score was real: feedback reached the advisor anyway
    assert advisor.feedbacks == [(1, 0.5)]
    meta.close()
    params.close()


def test_stop_flag_drains_no_running_rows(tmp_path, monkeypatch):
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "p"))
    orig_save = params.save
    monkeypatch.setattr(
        params, "save",
        lambda ps, **kw: (time.sleep(0.2), orig_save(ps, **kw))[1])
    stop = threading.Event()

    class _StopAfterOne(_FixedAdvisor):
        def feedback(self, proposal, score):
            super().feedback(proposal, score)
            stop.set()  # supervisor stops the job mid-persist

    runner = TrialRunner(_fake_model([]), _StopAfterOne({"width": 32}),
                         "tr", "va", meta, params, "sub-stop",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 50},
                         stop_flag=stop, pipeline_persist=True)
    runner.run()
    runner.close()
    trials = meta.get_trials("sub-stop")
    assert trials and all(t["status"] != TrialStatus.RUNNING
                          for t in trials)
    meta.close()
    params.close()


def test_repeated_tail_failures_trip_circuit_breaker(tmp_path,
                                                     monkeypatch):
    """A deterministic persist failure (disk full) must stop the loop
    via the consecutive-error breaker even though each run_one snapshot
    still said RUNNING — not spin forever against a trial-count budget
    that can never be satisfied."""
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "p"))
    monkeypatch.setattr(
        params, "save",
        lambda ps, **kw: (_ for _ in ()).throw(
            RuntimeError("disk full (injected)")))
    runner = TrialRunner(_fake_model([]), _FixedAdvisor({"width": 32}),
                         "tr", "va", meta, params, "sub-breaker",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 50},
                         pipeline_persist=True)
    runner.run()  # must terminate
    runner.close()
    trials = meta.get_trials("sub-breaker")
    assert 3 <= len(trials) <= 5  # breaker fired, not the 50-budget
    assert all(t["status"] == TrialStatus.ERRORED for t in trials)
    meta.close()
    params.close()


def test_failed_final_tail_refunds_budget_slot(tmp_path, monkeypatch):
    """A persist failure on the trial that LOOKED like it satisfied the
    budget must refund its slot after the drain (pre-pipelining
    semantics): the loop runs a replacement trial instead of
    under-delivering MODEL_TRIAL_COUNT."""
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "p"))
    orig_save = params.save
    calls = [0]

    def flaky_save(ps, **kw):
        calls[0] += 1
        if calls[0] == 1:
            raise RuntimeError("transient disk error (injected)")
        return orig_save(ps, **kw)

    monkeypatch.setattr(params, "save", flaky_save)
    runner = TrialRunner(_fake_model([]), _FixedAdvisor({"width": 32}),
                         "tr", "va", meta, params, "sub-refund",
                         budget={BudgetOption.MODEL_TRIAL_COUNT: 2},
                         pipeline_persist=True)
    runner.run()
    runner.close()
    trials = meta.get_trials("sub-refund")
    by_status = {}
    for t in trials:
        by_status[t["status"]] = by_status.get(t["status"], 0) + 1
    assert by_status.get(TrialStatus.COMPLETED) == 2, by_status
    assert by_status.get(TrialStatus.ERRORED) == 1, by_status
    meta.close()
    params.close()
