import jax
import numpy as np
import pytest

from rafiki_tpu.parallel import (ChipAllocator, ChipGroup, build_mesh,
                                 param_spec, shard_variables)


def test_allocator_first_fit_and_release():
    a = ChipAllocator(8)
    g1 = a.allocate(4, "t1")
    g2 = a.allocate(2, "t2")
    assert g1.indices == (0, 1, 2, 3)
    assert g2.indices == (4, 5)
    assert a.allocate(4, "t3") is None  # only 2 free
    a.release("t1")
    g3 = a.allocate(3, "t3")
    assert g3.indices == (0, 1, 2)
    assert a.free_chips == 3  # chips 3, 6, 7
    assert a.utilization() == pytest.approx(5 / 8)


def test_allocator_rejects_name_reuse():
    a = ChipAllocator(4)
    a.allocate(2, "svc")
    with pytest.raises(ValueError):
        a.allocate(2, "svc")
    a.release("svc")
    assert a.allocate(2, "svc") is not None
    a.release("missing")  # no-op, no raise


def _v5e_2x4():
    """Coords of an 8-chip v5e slice: 4 wide (x), 2 tall (y), z=0."""
    return [(x, y, 0) for y in range(2) for x in range(4)]


def test_allocator_topology_squares_on_2x4():
    """VERDICT r1 item 6: an 8-chip 2×4 slice carves into 2×2 squares
    (ICI-compact), not linear index runs that straddle torus rows."""
    a = ChipAllocator(8, topology=_v5e_2x4())
    g1 = a.allocate(4, "t1")
    g2 = a.allocate(4, "t2")
    # Device order is snake (boustrophedon) within each 2x2 square, so
    # every group-order hop — including the ring wraparound — is a
    # single ICI link.
    assert g1.indices == (0, 1, 5, 4)  # (0,0),(1,0),(1,1),(0,1)
    assert g2.indices == (2, 3, 7, 6)  # (2,0),(3,0),(3,1),(2,1)
    assert a.free_chips == 0
    a.release("t1")
    # A pair lands on an adjacent (1x2 / 2x1) placement inside the hole.
    g3 = a.allocate(2, "t3")
    coords = {0: (0, 0), 1: (1, 0), 4: (0, 1), 5: (1, 1)}
    (x0, y0), (x1, y1) = coords[g3.indices[0]], coords[g3.indices[1]]
    assert abs(x0 - x1) + abs(y0 - y1) == 1


def _assert_connected(group, topology):
    """Every member has an in-group torus neighbour (6-neighbour)."""
    coords = [topology[i] for i in group.indices]
    for c in coords:
        assert any(sum(abs(a - b) for a, b in zip(c, c2)) == 1
                   for c2 in coords if c2 != c), (c, coords)


def test_allocator_topology_fragmented_blob():
    """VERDICT r3 item 5: with the left 2×2 square taken, no 1×3 line
    fits the remaining 2×2 column — but a connected 3-blob does, so
    the allocator places one (ICI-internal, non-minimal diameter)
    instead of queueing the trial forever."""
    topo = _v5e_2x4()
    a = ChipAllocator(8, topology=topo)
    a.allocate(4, "sq")                  # takes x∈{0,1} × y∈{0,1}
    g = a.allocate(3, "odd")             # no 1x3 line — blob fallback
    assert g is not None
    _assert_connected(g, topo)
    assert a.allocate(2, "p1") is None   # only 1 chip left
    a.release("odd")
    g1, g2 = a.allocate(2, "p1"), a.allocate(2, "p2")
    assert g1 is not None and g2 is not None
    assert a.free_chips == 0


def test_allocator_topology_never_straddles_rows():
    """Review finding r2: with topology known there is NO linear
    fallback — an index run like (1,2,3,4) on a 2×4 grid crosses the
    row boundary ((3,0)→(0,1) are not torus neighbours). The blob
    fallback (r4) means the allocation now succeeds, but only as a
    CONNECTED region, never as that disconnected index run."""
    topo = _v5e_2x4()
    a = ChipAllocator(8, topology=topo)
    # Occupy (0,0)=idx0 and (2,1)=idx6: indices 1..4 stay free and
    # linearly contiguous, but no free 2x2 / 1x4 rectangle exists.
    a._owners[0] = ["x"]
    a._owners[6] = ["y"]
    g = a.allocate(4, "t")
    assert g is not None
    assert set(g.indices) != {1, 2, 3, 4}  # the disconnected run
    _assert_connected(g, topo)


def test_allocator_full_slice_rectangle():
    a = ChipAllocator(8, topology=_v5e_2x4())
    g = a.allocate(8, "all")
    assert len(g.indices) == 8
    assert sorted(g.indices) == list(range(8))


def test_discover_topology_rejects_cpu():
    from rafiki_tpu.parallel.chips import discover_topology

    assert discover_topology(jax.devices()) is None  # virtual CPU devs


def test_chip_group_env_roundtrip():
    g = ChipGroup(indices=(2, 3, 4))
    assert g.to_env() == "2,3,4"
    g2 = ChipGroup.from_env("2,3,4")
    assert g2.indices == (2, 3, 4)
    g_all = ChipGroup.from_env("")
    assert g_all.n_chips == len(jax.devices())


def test_build_mesh_shapes():
    mesh = build_mesh(jax.devices(), tp=2)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    mesh = build_mesh(jax.devices())
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1
    with pytest.raises(ValueError):
        build_mesh(jax.devices(), tp=3)


def test_param_spec_rules():
    big = np.zeros((128, 512))
    small = np.zeros((16, 8))
    bias = np.zeros((512,))
    assert param_spec(big, tp=2) == jax.sharding.PartitionSpec(None, "tp")
    assert param_spec(small, tp=2) == jax.sharding.PartitionSpec()
    assert param_spec(bias, tp=2) == jax.sharding.PartitionSpec()
    assert param_spec(big, tp=1) == jax.sharding.PartitionSpec()


def test_shard_variables_places_on_mesh():
    mesh = build_mesh(jax.devices(), tp=2)
    variables = {"params": {"dense": {"kernel": np.zeros((64, 512)),
                                      "bias": np.zeros((512,))}}}
    placed = shard_variables(variables, mesh)
    kernel = placed["params"]["dense"]["kernel"]
    assert len(kernel.sharding.device_set) == 8
    # Sharded over tp on last axis: per-device shard is (64, 256).
    assert kernel.addressable_shards[0].data.shape == (64, 256)


def test_allocator_blob_for_non_rectangular_sizes():
    """Sizes with no feasible rectangle (5 or 7 on a 2x4 grid) place as
    a CONNECTED blob instead of being rejected forever."""
    from rafiki_tpu.parallel.chips import _rect_shapes

    a = ChipAllocator(8, topology=_v5e_2x4())
    g = a.allocate(5, "odd")
    assert g is not None and len(g.indices) == 5
    # Connectivity: every member has a 4-neighbour inside the group.
    coords = [_v5e_2x4()[i][:2] for i in g.indices]
    for (x, y) in coords:
        assert any(abs(x - x2) + abs(y - y2) == 1 for (x2, y2) in coords
                   if (x2, y2) != (x, y))
    # Too few free chips still refuses outright.
    assert a.allocate(4, "sq") is None  # only 3 free
    a.release("odd")
    assert a.allocate(4, "sq") is not None
    assert _rect_shapes(6)[0] == (2, 3) or _rect_shapes(6)[0] == (3, 2)


def _v4_2x2x2():
    """Coords of an 8-chip v4 cube: a genuine 3-D (z-varying) torus."""
    return [(x, y, z) for z in range(2) for y in range(2) for x in range(2)]


def test_allocator_3d_carves_cube_into_planes():
    """VERDICT r3 item 4: a 2×2×2 v4 cube carves into two 2×2×1 plane
    groups (most cube-like boxes for n=4), each fully ICI-adjacent —
    not discarded to linear placement as before."""
    topo = _v4_2x2x2()
    a = ChipAllocator(8, topology=topo)
    g1 = a.allocate(4, "t1")
    g2 = a.allocate(4, "t2")
    assert a.free_chips == 0
    for g in (g1, g2):
        coords = [topo[i] for i in g.indices]
        assert len({c[2] for c in coords}) == 1  # one z-plane each
        _assert_connected(g, topo)
        # Snake order: every group-order hop is a single ICI link.
        for c, c2 in zip(coords, coords[1:]):
            assert sum(abs(u - v) for u, v in zip(c, c2)) == 1


def test_allocator_3d_full_cube_snake():
    """The whole cube allocates as one 2×2×2 box whose snake order is
    single-hop at every step, including the z-plane turn."""
    topo = _v4_2x2x2()
    a = ChipAllocator(8, topology=topo)
    g = a.allocate(8, "all")
    assert sorted(g.indices) == list(range(8))
    coords = [topo[i] for i in g.indices]
    for c, c2 in zip(coords, coords[1:]):
        assert sum(abs(u - v) for u, v in zip(c, c2)) == 1


def test_allocator_3d_blob_spans_planes():
    """An awkward size on the cube (5) comes back as a connected blob
    spanning z-planes via vertical ICI links."""
    topo = _v4_2x2x2()
    a = ChipAllocator(8, topology=topo)
    g = a.allocate(5, "odd")
    assert g is not None and len(g.indices) == 5
    _assert_connected(g, topo)


def test_device_get_tree_roundtrip():
    """Packed single-transfer pull: values, shapes, dtypes and tree
    structure must match a per-leaf jax.device_get exactly (mixed
    dtypes, scalars, host leaves pass through)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.parallel import device_get_tree

    tree = {
        "a": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        "b": {"c": jnp.ones((2, 2, 2), jnp.bfloat16) * 1.5,
              "d": jnp.asarray(7, jnp.int32)},
        "host": np.arange(3),
        "e": [jnp.full((5,), -2.0, jnp.float32)],
    }
    got = device_get_tree(tree)
    want = jax.tree.map(np.asarray, tree)
    assert jax.tree.structure(got) == jax.tree.structure(want)
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        assert g.shape == w.shape and g.dtype == w.dtype
        assert np.array_equal(np.asarray(g, np.float64),
                              np.asarray(w, np.float64))


def test_device_get_tree_cache_keyed_on_device_leaf_mix():
    """Two trees with the SAME treedef and coinciding device-leaf
    (shape, dtype) sequences but a different device/host mix must not
    share a pack-cache entry (ADVICE r5: the cached groups packed the
    wrong leaves, leaving None holes in the unflattened tree)."""
    import jax.numpy as jnp
    import numpy as np

    from rafiki_tpu.parallel import device_get_tree

    # mix 1: 'a' host, 'b' device — primes the cache
    t1 = {"a": np.arange(4, dtype=np.float32),
          "b": jnp.full((4,), 2.0, jnp.float32)}
    g1 = device_get_tree(t1)
    np.testing.assert_array_equal(g1["a"], t1["a"])
    np.testing.assert_array_equal(g1["b"], np.full(4, 2.0, np.float32))
    # mix 2: identical treedef + device-leaf signature, swapped mix
    t2 = {"a": jnp.full((4,), 3.0, jnp.float32),
          "b": np.arange(4, dtype=np.float32)}
    g2 = device_get_tree(t2)
    assert g2["a"] is not None and g2["b"] is not None
    np.testing.assert_array_equal(g2["a"], np.full(4, 3.0, np.float32))
    np.testing.assert_array_equal(g2["b"], t2["b"])
