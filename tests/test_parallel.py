import jax
import numpy as np
import pytest

from rafiki_tpu.parallel import (ChipAllocator, ChipGroup, build_mesh,
                                 param_spec, shard_variables)


def test_allocator_first_fit_and_release():
    a = ChipAllocator(8)
    g1 = a.allocate(4, "t1")
    g2 = a.allocate(2, "t2")
    assert g1.indices == (0, 1, 2, 3)
    assert g2.indices == (4, 5)
    assert a.allocate(4, "t3") is None  # only 2 free
    a.release("t1")
    g3 = a.allocate(3, "t3")
    assert g3.indices == (0, 1, 2)
    assert a.free_chips == 3  # chips 3, 6, 7
    assert a.utilization() == pytest.approx(5 / 8)


def test_allocator_rejects_name_reuse():
    a = ChipAllocator(4)
    a.allocate(2, "svc")
    with pytest.raises(ValueError):
        a.allocate(2, "svc")
    a.release("svc")
    assert a.allocate(2, "svc") is not None
    a.release("missing")  # no-op, no raise


def test_chip_group_env_roundtrip():
    g = ChipGroup(indices=(2, 3, 4))
    assert g.to_env() == "2,3,4"
    g2 = ChipGroup.from_env("2,3,4")
    assert g2.indices == (2, 3, 4)
    g_all = ChipGroup.from_env("")
    assert g_all.n_chips == len(jax.devices())


def test_build_mesh_shapes():
    mesh = build_mesh(jax.devices(), tp=2)
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    mesh = build_mesh(jax.devices())
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1
    with pytest.raises(ValueError):
        build_mesh(jax.devices(), tp=3)


def test_param_spec_rules():
    big = np.zeros((128, 512))
    small = np.zeros((16, 8))
    bias = np.zeros((512,))
    assert param_spec(big, tp=2) == jax.sharding.PartitionSpec(None, "tp")
    assert param_spec(small, tp=2) == jax.sharding.PartitionSpec()
    assert param_spec(bias, tp=2) == jax.sharding.PartitionSpec()
    assert param_spec(big, tp=1) == jax.sharding.PartitionSpec()


def test_shard_variables_places_on_mesh():
    mesh = build_mesh(jax.devices(), tp=2)
    variables = {"params": {"dense": {"kernel": np.zeros((64, 512)),
                                      "bias": np.zeros((512,))}}}
    placed = shard_variables(variables, mesh)
    kernel = placed["params"]["dense"]["kernel"]
    assert len(kernel.sharding.device_set) == 8
    # Sharded over tp on last axis: per-device shard is (64, 256).
    assert kernel.addressable_shards[0].data.shape == (64, 256)
