"""Continuous micro-batching in the serving path (predictor/batcher.py).

Real components, no mocks: a MemoryBus, a worker thread speaking the
cache protocol, the actual PredictorService HTTP frontend. The
invariants under test are the ones concurrency breaks silently:
per-request slicing (no cross-request result bleed), bounded admission
(429 + Retry-After instead of unbounded pileup), and a race-free
replica rotation.
"""

import threading
import time

import pytest
import requests

from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.predictor import Backpressure, MicroBatcher, Predictor
from rafiki_tpu.predictor.app import PredictorService


class EchoWorker:
    """Minimal InferenceWorker stand-in: pops query batches off the bus
    and replies ``[value, value + 0.5]`` per query (so a reply is
    attributable to its query). ``delay`` simulates model latency;
    ``trial_id`` sets the replica bin; ``dead=True`` swallows frames (a
    replica that crashed mid-gather); ``echo_shard=False`` mimics a
    pre-shard worker that doesn't echo the shard id."""

    def __init__(self, bus, worker_id="w1", job_id="job", delay=0.0,
                 trial_id="t1", dead=False, echo_shard=True):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.delay = delay
        self.dead = dead
        self.echo_shard = echo_shard
        self.stop_flag = threading.Event()
        self.served_batches = 0
        self.served_sizes = []
        self.cache.register_worker(job_id, worker_id,
                                   info={"trial_id": trial_id})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            for it in items:
                if self.dead:
                    continue
                if self.delay:
                    time.sleep(self.delay)
                self.served_batches += 1
                self.served_sizes.append(len(it["queries"]))
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [[float(q), float(q) + 0.5] for q in it["queries"]],
                    shard=it.get("shard") if self.echo_shard else None)

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


@pytest.fixture()
def bus():
    return MemoryBus()


def _predictor(bus, **kw):
    kw.setdefault("worker_wait_timeout", 5.0)
    kw.setdefault("gather_timeout", 5.0)
    return Predictor("job", bus, **kw)


def _service(bus, **kw):
    """PredictorService on a free port, lifecycle managed by the test
    (meta is not exercised: the routes under test never touch it)."""
    svc = PredictorService("svc", "job", meta=None, bus=bus,
                           host="127.0.0.1", **kw)
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    if svc.batcher is not None:
        svc.batcher.start()
    svc._http.start()
    return svc


def _teardown(svc):
    svc._http.stop()
    if svc.batcher is not None:
        svc.batcher.stop()


def test_concurrent_predict_no_cross_request_bleed(bus):
    """N handler threads hammering one PredictorService must each get
    exactly their own slice of the coalesced super-batch."""
    worker = EchoWorker(bus)
    svc = _service(bus)
    url = f"http://127.0.0.1:{svc.port}/predict"
    results = {}
    errors = []

    def client(i):
        try:
            qs = [i * 100 + j for j in range(1 + i % 4)]  # ragged sizes
            r = requests.post(url, json={"queries": qs}, timeout=30)
            r.raise_for_status()
            results[i] = (qs, r.json()["predictions"])
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(16)]
    try:
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errors, errors
        assert len(results) == 16
        for i, (qs, preds) in results.items():
            assert preds == [[float(q), float(q) + 0.5] for q in qs], \
                f"client {i} got another request's slice"
    finally:
        _teardown(svc)
        worker.stop()


def test_microbatcher_coalesces_concurrent_requests(bus):
    """Concurrent submits within one fill window ride ONE scatter-gather
    super-batch (requests >> batches; worker sees few batch frames)."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.05, max_batch=256,
                      max_inflight=2, queue_cap=1024).start()
    try:
        out = {}
        barrier = threading.Barrier(12)

        def client(i):
            barrier.wait()
            out[i] = mb.submit([i, i + 1000], timeout=15)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert len(out) == 12
        for i in range(12):
            assert out[i] == [[float(i), float(i) + 0.5],
                              [float(i + 1000), float(i + 1000) + 0.5]]
        snap = mb.stats.snapshot()
        assert snap["requests"] == 12
        assert snap["batches"] < 12, "no coalescing happened"
        assert snap["coalescing_factor"] > 1.5
        # the worker saw one frame per super-batch, not one per request
        assert worker.served_batches == snap["batches"]
    finally:
        mb.stop()
        worker.stop()


def test_keep_n_in_flight_overlaps_gather_with_next_scatter(bus):
    """With a slow worker and max_inflight=2, super-batch K+1 must be
    scattered while K's gather is still blocking."""
    worker = EchoWorker(bus, delay=0.15)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=2,
                      max_inflight=2, queue_cap=1024).start()
    try:
        threads = [threading.Thread(
            target=lambda i=i: mb.submit([i], timeout=30))
            for i in range(8)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        snap = mb.stats.snapshot()
        assert snap["inflight_peak"] == 2, snap
    finally:
        mb.stop()
        worker.stop()


def test_backpressure_returns_429_with_retry_after(bus):
    """Sustained overload must bounce with 429 + Retry-After while the
    admission queue stays bounded — not grow latency without bound."""
    worker = EchoWorker(bus, delay=0.25)  # each super-batch is slow
    svc = _service(bus, queue_cap=6, max_inflight=1, fill_window=0.01,
                   max_batch=4)
    url = f"http://127.0.0.1:{svc.port}/predict"
    codes = []
    codes_lock = threading.Lock()

    def client(i):
        r = requests.post(url, json={"queries": [i, i, i]}, timeout=60)
        with codes_lock:
            codes.append((r.status_code, r.headers.get("Retry-After"),
                          r.json()))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(24)]
    try:
        [t.start() for t in threads]
        [t.join(timeout=60) for t in threads]
        assert len(codes) == 24
        rejected = [c for c in codes if c[0] == 429]
        served = [c for c in codes if c[0] == 200]
        assert rejected, "overload never produced a 429"
        assert served, "every request was rejected"
        for status, retry_after, body in rejected:
            assert retry_after is not None and int(retry_after) >= 1
            assert body["queue_cap"] == 6
        # bounded queue: admitted depth never exceeded the cap
        assert svc.stats.queue_depth_peak <= 6
        assert svc.stats.rejected == len(rejected)
    finally:
        _teardown(svc)
        worker.stop()


def test_microbatch_disabled_restores_direct_path(bus):
    """RAFIKI_TPU_SERVING_MICROBATCH=0: no batcher, requests scatter
    directly — the bench's A/B baseline."""
    worker = EchoWorker(bus)
    svc = _service(bus, microbatch=False)
    url = f"http://127.0.0.1:{svc.port}"
    try:
        assert svc.batcher is None
        r = requests.post(f"{url}/predict", json={"queries": [1, 2]},
                          timeout=30)
        assert r.status_code == 200
        assert r.json()["predictions"] == [[1.0, 1.5], [2.0, 2.5]]
        stats = requests.get(f"{url}/stats", timeout=10).json()
        assert stats["microbatch"] is False
        assert stats["batches"] == 0 and stats["requests"] == 1
    finally:
        _teardown(svc)
        worker.stop()


def test_microbatch_env_toggle(bus, monkeypatch):
    monkeypatch.delenv("RAFIKI_TPU_SERVING_MICROBATCH", raising=False)
    assert PredictorService("s", "j", None, bus).batcher is not None
    monkeypatch.setenv("RAFIKI_TPU_SERVING_MICROBATCH", "0")
    assert PredictorService("s", "j", None, bus).batcher is None
    # constructor arg beats env
    assert PredictorService("s", "j", None, bus,
                            microbatch=True).batcher is not None
    # knob envs reach the batcher
    monkeypatch.setenv("RAFIKI_TPU_SERVING_MICROBATCH", "1")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_FILL_WINDOW", "0.02")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_QUEUE_CAP", "99")
    b = PredictorService("s", "j", None, bus).batcher
    assert b.fill_window == 0.02 and b.queue_cap == 99


def test_choose_workers_race_free(bus):
    """_rr/_bins are mutated from every handler thread in batcher-off
    mode; concurrent rotation must lose no increments and the per-bin
    replica pick must stay valid throughout."""
    cache = Cache(bus)
    cache.register_worker("job", "wA1", info={"trial_id": "tA"})
    cache.register_worker("job", "wA2", info={"trial_id": "tA"})
    cache.register_worker("job", "wB", info={"trial_id": "tB"})
    p = _predictor(bus)
    bad = []

    def spin():
        for _ in range(50):
            pick = p._choose_workers()
            if len(pick) != 2 or "wB" not in pick or \
                    (("wA1" in pick) == ("wA2" in pick)):
                bad.append(pick)

    threads = [threading.Thread(target=spin) for _ in range(8)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert not bad, bad[:3]
    assert p._rr == 8 * 50, "lost round-robin increments under races"


def test_backpressure_exception_fields():
    e = Backpressure(2.0, depth=10, cap=8)
    assert e.retry_after == 2.0 and e.depth == 10 and e.cap == 8
    assert "retry after" in str(e)


def test_stop_fails_waiters_fast_and_rejects_late_submits(bus):
    """stop() must promptly fail BOTH queued requests and already-
    scattered super-batches (never leave a handler blocked until its
    full timeout), and submits after stop must raise immediately."""
    cache = Cache(bus)
    cache.register_worker("job", "w1", info={"trial_id": "t1"})
    # no worker thread: scattered batches never get replies
    p = _predictor(bus, gather_timeout=30.0)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=2, max_inflight=1,
                      queue_cap=64).start()
    outcomes = []

    def client(i):
        t0 = time.time()
        try:
            mb.submit([i], timeout=60)
            outcomes.append(("ok", time.time() - t0))
        except RuntimeError as e:
            outcomes.append((str(e), time.time() - t0))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(4)]
    [t.start() for t in threads]
    time.sleep(0.5)  # first batch scattered + in flight, rest queued
    mb.stop()
    [t.join(timeout=10) for t in threads]
    assert len(outcomes) == 4
    for msg, elapsed in outcomes:
        assert "micro-batcher stopped" in msg
        assert elapsed < 15, "waiter hung past stop()"
    with pytest.raises(RuntimeError, match="stopped"):
        mb.submit([1], timeout=5)


# --- Replica-sharded scatter (data-parallel serving) ---


def _expected(qs):
    return [[float(q), float(q) + 0.5] for q in qs]


def test_shard_split_across_same_bin_replicas(bus):
    """With 2 same-bin replicas, one batch is sliced across BOTH (each
    sees a strict subset) and reassembles in request order."""
    wa = EchoWorker(bus, "wA1", trial_id="tA")
    wb = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus)
    try:
        qs = list(range(10))
        assert p.predict(qs) == _expected(qs)
        assert wa.served_sizes and wb.served_sizes, \
            "a replica idled through a sharded batch"
        assert max(wa.served_sizes) < 10 and max(wb.served_sizes) < 10
        assert sum(wa.served_sizes) + sum(wb.served_sizes) == 10
    finally:
        wa.stop()
        wb.stop()


def test_shard_uneven_replica_counts_and_order(bus):
    """Bins with 3 and 1 replicas: every query still gets exactly one
    vote per bin, results in request order, ensemble across bins."""
    workers = [EchoWorker(bus, f"wA{i}", trial_id="tA")
               for i in range(3)]
    workers.append(EchoWorker(bus, "wB", trial_id="tB"))
    p = _predictor(bus)
    try:
        for n in (1, 2, 7):  # fewer queries than replicas, uneven splits
            qs = list(range(100, 100 + n))
            assert p.predict(qs) == _expected(qs), f"n={n}"
        # the single-replica bin always served full batches
        assert all(s in (1, 2, 7)
                   for s in workers[-1].served_sizes)
    finally:
        [w.stop() for w in workers]


def test_shard_replicas_off_restores_one_pick_per_bin(bus):
    """shard_replicas=False: the pre-shard behavior — one rotating
    replica serves the WHOLE batch."""
    wa = EchoWorker(bus, "wA1", trial_id="tA")
    wb = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus, shard_replicas=False)
    try:
        qs = list(range(8))
        assert p.predict(qs) == _expected(qs)
        sizes = wa.served_sizes + wb.served_sizes
        assert sizes == [8], sizes
    finally:
        wa.stop()
        wb.stop()


def test_shard_env_knob(bus, monkeypatch):
    monkeypatch.setenv("RAFIKI_TPU_SERVING_SHARD_REPLICAS", "0")
    assert _predictor(bus).shard_replicas is False
    monkeypatch.setenv("RAFIKI_TPU_SERVING_SHARD_REPLICAS", "1")
    assert _predictor(bus).shard_replicas is True
    # constructor beats env
    assert _predictor(bus, shard_replicas=False).shard_replicas is False


def test_replica_death_mid_gather_resubmits_to_sibling(bus):
    """A dead replica's shard is resubmitted to its sibling at the
    partial-gather deadline: the batch completes with FULL results,
    well before the full gather timeout, and the dead replica is
    latency-penalized out of the next plan."""
    dead = EchoWorker(bus, "wA1", trial_id="tA", dead=True)
    live = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus, gather_timeout=4.0)
    try:
        qs = list(range(8))
        t0 = time.monotonic()
        assert p.predict(qs) == _expected(qs)
        elapsed = time.monotonic() - t0
        assert elapsed < 3.5, \
            f"resubmit did not beat the full gather timeout ({elapsed})"
        # the penalized replica gets no slice on the next batch
        live.served_sizes.clear()
        dead_sizes_before = list(dead.served_sizes)
        assert p.predict(qs) == _expected(qs)
        assert live.served_sizes == [8]
        assert dead.served_sizes == dead_sizes_before
    finally:
        dead.stop()
        live.stop()


def test_resubmit_skips_co_missing_siblings(bus):
    """Two replicas dying in the SAME batch must both resubmit to the
    remaining live sibling — never to each other (a co-missing worker
    is no rescue, whatever its historical EWMA says)."""
    dead1 = EchoWorker(bus, "wA1", trial_id="tA", dead=True)
    dead2 = EchoWorker(bus, "wA2", trial_id="tA", dead=True)
    live = EchoWorker(bus, "wA3", trial_id="tA")
    p = _predictor(bus, gather_timeout=4.0)
    qs = list(range(9))
    try:
        t0 = time.monotonic()
        assert p.predict(qs) == _expected(qs)
        assert time.monotonic() - t0 < 3.5
        assert sum(live.served_sizes) == 9, live.served_sizes
    finally:
        dead1.stop()
        dead2.stop()
        live.stop()


def test_penalized_replica_recovers_after_probe_interval(bus):
    """One transient timeout must not starve a replica forever: the
    penalty (whose ~zero slice means its latency EWMA can never
    refresh on its own) expires after one probe interval and the
    recovered replica rejoins the plan."""
    flaky = EchoWorker(bus, "wA1", trial_id="tA", dead=True)
    steady = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus, gather_timeout=1.0)
    qs = list(range(8))
    try:
        assert p.predict(qs) == _expected(qs)  # resubmit covered it
        assert "wA1" in p._penalized
        flaky.dead = False  # the replica comes back
        assert p.predict(qs) == _expected(qs)
        assert not flaky.served_sizes, "penalty ignored"
        time.sleep(1.1)  # one probe interval (== gather_timeout)
        assert p.predict(qs) == _expected(qs)
        assert flaky.served_sizes, "recovered replica never rejoined"
        assert "wA1" not in p._penalized
    finally:
        flaky.stop()
        steady.stop()


def test_quarantine_backoff_doubles_per_strike_and_resets(bus):
    """A still-dead replica must stop costing one partial deadline per
    gather timeout: each consecutive missed probe doubles its
    quarantine (capped), and one real reply resets the ladder."""
    from rafiki_tpu.predictor.predictor import _QUARANTINE_MAX_MULT

    p = _predictor(bus, gather_timeout=1.0)
    try:
        p._penalize("w")
        assert p._quarantine_s("w") == 1.0  # first strike: one timeout
        p._penalize("w")
        assert p._quarantine_s("w") == 2.0  # probe missed again
        for _ in range(10):
            p._penalize("w")
        assert p._quarantine_s("w") == float(_QUARANTINE_MAX_MULT)
        p._note_latency("w", 0.01)  # a real reply proves it alive
        assert "w" not in p._strikes
        p._penalize("w")
        assert p._quarantine_s("w") == 1.0  # ladder starts over
        # Strikes outlive penalty expiry on purpose: expiry IS the
        # probe, so only a reply (not mere re-planning) resets them.
        p._penalized.pop("w")
        p._penalize("w")
        assert p._quarantine_s("w") == 2.0
    finally:
        p.close()


def test_partial_bin_degrades_not_stalls(bus):
    """A dead single-replica bin (no sibling to resubmit to) costs only
    its own vote: the other bin's predictions still come back."""
    dead = EchoWorker(bus, "wA", trial_id="tA", dead=True)
    live = EchoWorker(bus, "wB", trial_id="tB")
    p = _predictor(bus, gather_timeout=2.0)
    try:
        qs = [1, 2, 3]
        out = p.predict(qs)
        assert out == _expected(qs), out  # tB's votes survived
    finally:
        dead.stop()
        live.stop()


def test_old_worker_without_shard_echo_still_matches(bus):
    """Pre-shard workers reply without the shard id; the gatherer falls
    back to matching by worker id (one shard per worker per batch)."""
    wa = EchoWorker(bus, "wA1", trial_id="tA", echo_shard=False)
    wb = EchoWorker(bus, "wA2", trial_id="tA", echo_shard=False)
    p = _predictor(bus)
    try:
        qs = list(range(6))
        assert p.predict(qs) == _expected(qs)
    finally:
        wa.stop()
        wb.stop()


def test_latency_weighted_split_prefers_fast_replica(bus):
    """A slow replica's EWMA shrinks its slice: after a few batches the
    fast replica serves most of the queries."""
    slow = EchoWorker(bus, "wA1", trial_id="tA", delay=0.20)
    fast = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus)
    try:
        qs = list(range(12))
        for _ in range(4):
            assert p.predict(qs) == _expected(qs)
        # steady state: the fast replica served most of the queries
        # (the slow one may even drop out of the plan entirely)
        assert sum(fast.served_sizes) > sum(slow.served_sizes), \
            (fast.served_sizes, slow.served_sizes)
    finally:
        slow.stop()
        fast.stop()


def test_sharded_scatter_through_microbatcher(bus):
    """End to end: concurrent ragged requests through the micro-batcher
    over 2 same-bin replicas — per-request slices intact (the
    order-preserving reassembly under mixed request sizes)."""
    wa = EchoWorker(bus, "wA1", trial_id="tA")
    wb = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.05, max_batch=256,
                      max_inflight=2, queue_cap=1024).start()
    try:
        out = {}
        errors = []
        barrier = threading.Barrier(10)

        def client(i):
            try:
                barrier.wait()
                qs = [i * 100 + j for j in range(1 + i % 5)]
                out[i] = (qs, mb.submit(qs, timeout=15))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(10)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errors, errors
        assert len(out) == 10
        for i, (qs, preds) in out.items():
            assert preds == _expected(qs), \
                f"client {i} got another request's slice"
        assert wa.served_sizes and wb.served_sizes
    finally:
        mb.stop()
        wa.stop()
        wb.stop()


# --- Adaptive fill window ---


def test_adaptive_window_converges_trickle_vs_burst(bus):
    """Trickle arrivals (inter-arrival >> ceiling) collapse the window
    to the floor; a tight burst opens it toward the ceiling."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window_min=0.0, fill_window_max=0.05,
                      max_batch=256, max_inflight=2,
                      queue_cap=1024).start()
    try:
        # Trickle: arrivals 0.1s apart, far beyond the 50ms ceiling.
        for i in range(6):
            mb.submit([i], timeout=10)
            time.sleep(0.1)
        assert mb.current_fill_window() <= 0.005, \
            mb.current_fill_window()
        trickle_stats = mb.stats.snapshot()
        assert trickle_stats["fill_window_s"] <= 0.005
        # Burst: concurrent clients hammering — the EWMA tightens and
        # the window opens.
        barrier = threading.Barrier(8)

        def client(i):
            barrier.wait()
            for j in range(6):
                mb.submit([i * 10 + j], timeout=10)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert mb.current_fill_window() > 0.02, \
            mb.current_fill_window()
    finally:
        mb.stop()
        worker.stop()


def test_pinned_window_stays_fixed(bus):
    """fill_window_min == fill_window_max restores the fixed window
    regardless of load."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window_min=0.02, fill_window_max=0.02,
                      max_batch=64, max_inflight=2,
                      queue_cap=256).start()
    try:
        for i in range(3):
            mb.submit([i], timeout=10)
            time.sleep(0.05)
        assert mb.current_fill_window() == 0.02
    finally:
        mb.stop()
        worker.stop()


def test_adaptive_window_env_knobs(bus, monkeypatch):
    monkeypatch.setenv("RAFIKI_TPU_SERVING_FILL_WINDOW_MIN", "0.001")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_FILL_WINDOW_MAX", "0.03")
    b = PredictorService("s", "j", None, bus).batcher
    assert b.fill_window_min == 0.001 and b.fill_window_max == 0.03
    # ceiling defaults to the legacy fixed knob when MAX is unset
    monkeypatch.delenv("RAFIKI_TPU_SERVING_FILL_WINDOW_MAX")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_FILL_WINDOW", "0.02")
    b = PredictorService("s", "j", None, bus).batcher
    assert b.fill_window_max == 0.02


# --- Per-client fairness under backpressure ---


def test_client_share_caps_one_client_not_others(bus):
    """With fairness on, one client key may hold at most its share of
    the admission queue: its overflow bounces with
    reason=client_share while other clients keep being admitted."""
    worker = EchoWorker(bus, delay=0.3)  # slow: the queue backs up
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window_min=0.0, fill_window_max=0.01,
                      max_batch=4, max_inflight=1, queue_cap=40,
                      client_share=0.25).start()  # 10 queries per key
    results = {"hog_429": 0, "hog_ok": 0, "other_ok": 0,
               "other_429": 0}
    lock = threading.Lock()

    def hog(i):
        try:
            mb.submit([i] * 5, timeout=30, client="hog")
            with lock:
                results["hog_ok"] += 1
        except Backpressure as e:
            assert e.reason == "client_share", e.reason
            with lock:
                results["hog_429"] += 1

    def other(i):
        try:
            mb.submit([i], timeout=30, client=f"c{i}")
            with lock:
                results["other_ok"] += 1
        except Backpressure:
            with lock:
                results["other_429"] += 1

    try:
        hogs = [threading.Thread(target=hog, args=(i,))
                for i in range(8)]
        [t.start() for t in hogs]
        time.sleep(0.15)  # hog floods first
        others = [threading.Thread(target=other, args=(i,))
                  for i in range(6)]
        [t.start() for t in others]
        [t.join(timeout=60) for t in hogs + others]
        assert results["hog_429"] > 0, results
        assert results["other_ok"] == 6, results
        snap = mb.stats.snapshot()
        assert snap["rejected_by_reason"].get("client_share", 0) == \
            results["hog_429"]
    finally:
        mb.stop()
        worker.stop()


def test_client_share_off_by_default(bus):
    """Without a client_share knob the client key is ignored — no
    per-key bound, only the global cap."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=64,
                      queue_cap=64).start()
    try:
        assert mb.submit([1, 2, 3], timeout=10,
                         client="x") == _expected([1, 2, 3])
        assert mb._client_pending == {}
    finally:
        mb.stop()
        worker.stop()


def test_client_header_knob_reaches_service(bus, monkeypatch):
    monkeypatch.setenv("RAFIKI_TPU_SERVING_CLIENT_HEADER",
                       "X-Client-Id")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_CLIENT_SHARE", "0.5")
    svc = PredictorService("s", "j", None, bus)
    assert svc.client_header == "X-Client-Id"
    assert svc.batcher.client_share == 0.5
    monkeypatch.delenv("RAFIKI_TPU_SERVING_CLIENT_HEADER")
    svc = PredictorService("s", "j", None, bus)
    assert svc.client_header == ""
    assert svc.batcher.client_share == 0.0  # fairness off sans header


def test_empty_and_oversized_requests(bus):
    """Empty submit returns []; a single request larger than the whole
    queue cap is still admitted when the queue is empty (it could never
    be served otherwise)."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=4, max_inflight=1,
                      queue_cap=4).start()
    try:
        assert mb.submit([], timeout=5) == []
        big = list(range(10))  # > queue_cap AND > max_batch
        out = mb.submit(big, timeout=15)
        assert out == [[float(q), float(q) + 0.5] for q in big]
    finally:
        mb.stop()
        worker.stop()


# --- Straggler detection: latency-relative resubmit deadline (r9) ---

def test_partial_wait_latency_relative_with_full_ewma(bus):
    """With every planned replica measured, the straggler deadline is
    K x the slowest planned EWMA (floored), not the fixed half-timeout
    fraction — a fast fleet resubmits in milliseconds."""
    from rafiki_tpu.predictor import predictor as pred_mod
    from rafiki_tpu.predictor.predictor import _Shard

    p = _predictor(bus, gather_timeout=30.0)
    p._note_latency("wA1", 0.010)
    p._note_latency("wA2", 0.020)
    plan = [_Shard("wA1", "tA", 0, 4), _Shard("wA2", "tA", 4, 4)]
    wait = p._partial_wait(plan)
    assert wait == pytest.approx(
        max(pred_mod._STRAGGLER_K * 0.020, pred_mod._STRAGGLER_MIN))
    assert wait < 1.0  # nowhere near 0.5 * 30s


def test_partial_wait_falls_back_without_full_ewma(bus):
    """Any never-measured replica in the plan means no honest latency
    basis yet: the fixed fraction stays — and it is also the ceiling
    when EWMAs are huge (a penalized replica's inflated value must not
    push the deadline PAST the fixed fraction)."""
    from rafiki_tpu.predictor import predictor as pred_mod
    from rafiki_tpu.predictor.predictor import _Shard

    p = _predictor(bus, gather_timeout=10.0)
    p._note_latency("wA1", 0.010)
    plan = [_Shard("wA1", "tA", 0, 4), _Shard("wA2", "tA", 4, 4)]
    assert p._partial_wait(plan) == pytest.approx(
        10.0 * pred_mod._RESUBMIT_AT)
    p._note_latency("wA2", 100.0)  # measured, but absurdly slow
    assert p._partial_wait(plan) == pytest.approx(
        10.0 * pred_mod._RESUBMIT_AT)


def test_fast_fleet_resubmits_well_before_fixed_fraction(bus):
    """End to end: after one warm batch establishes millisecond EWMAs,
    a replica dying mid-gather is re-covered by its sibling far sooner
    than the fixed half-timeout deadline (10s here) would allow."""
    w1 = EchoWorker(bus, "wA1", trial_id="tA")
    w2 = EchoWorker(bus, "wA2", trial_id="tA")
    p = _predictor(bus, gather_timeout=20.0)
    qs = list(range(8))
    try:
        assert p.predict(qs) == _expected(qs)  # warm: EWMAs for both
        w1.dead = True
        t0 = time.monotonic()
        assert p.predict(qs) == _expected(qs)
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, \
            f"latency-relative deadline did not engage ({elapsed:.2f}s)"
    finally:
        w1.stop()
        w2.stop()


# --- Batcher-off direct path: per-client fairness (r9) ---

def test_direct_path_client_share_caps_inflight(bus):
    """With the micro-batcher OFF, the same client_share caps one
    client key's in-flight queries: the hog's overflow bounces with
    429 reason=client_share while another client keeps being served."""
    worker = EchoWorker(bus, delay=0.4)  # slow: requests stay in flight
    svc = _service(bus, microbatch=False, client_header="X-Client-Id",
                   client_share=0.25, queue_cap=16)  # cap = 4 queries
    url = f"http://127.0.0.1:{svc.port}/predict"
    results = {"hog_ok": 0, "hog_429": 0, "other_ok": 0}
    lock = threading.Lock()

    def post(n, client, key):
        r = requests.post(url, json={"queries": list(range(n))},
                          headers={"X-Client-Id": client}, timeout=30)
        if r.status_code == 429:
            body = r.json()
            assert body["reason"] == "client_share", body
            assert r.headers.get("Retry-After"), "missing Retry-After"
            with lock:
                results[key.replace("ok", "429")] += 1
        else:
            r.raise_for_status()
            with lock:
                results[key] += 1

    try:
        assert svc.batcher is None and svc._direct_cap == 4
        hogs = [threading.Thread(target=post, args=(3, "hog", "hog_ok"))
                for _ in range(6)]
        [t.start() for t in hogs]
        time.sleep(0.1)  # hog floods first; its slices are in flight
        others = [threading.Thread(target=post,
                                   args=(1, f"c{i}", "other_ok"))
                  for i in range(4)]
        [t.start() for t in others]
        [t.join(timeout=30) for t in hogs + others]
        assert results["hog_429"] > 0, results
        assert results["other_ok"] == 4, results
        assert svc.stats.snapshot()["rejected_by_reason"].get(
            "client_share", 0) == results["hog_429"]
        assert svc._direct_pending == {}  # fully released
    finally:
        _teardown(svc)
        worker.stop()


def test_direct_path_fairness_off_without_header(bus):
    """No client header configured -> no per-key bound on the direct
    path (pre-r9 behavior)."""
    worker = EchoWorker(bus)
    svc = _service(bus, microbatch=False)
    url = f"http://127.0.0.1:{svc.port}/predict"
    try:
        assert svc._direct_cap == 0
        r = requests.post(url, json={"queries": list(range(64))},
                          headers={"X-Client-Id": "hog"}, timeout=30)
        assert r.status_code == 200
    finally:
        _teardown(svc)
        worker.stop()
