"""Continuous micro-batching in the serving path (predictor/batcher.py).

Real components, no mocks: a MemoryBus, a worker thread speaking the
cache protocol, the actual PredictorService HTTP frontend. The
invariants under test are the ones concurrency breaks silently:
per-request slicing (no cross-request result bleed), bounded admission
(429 + Retry-After instead of unbounded pileup), and a race-free
replica rotation.
"""

import threading
import time

import pytest
import requests

from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.predictor import Backpressure, MicroBatcher, Predictor
from rafiki_tpu.predictor.app import PredictorService


class EchoWorker:
    """Minimal InferenceWorker stand-in: pops query batches off the bus
    and replies ``[value, value + 0.5]`` per query (so a reply is
    attributable to its query). ``delay`` simulates model latency."""

    def __init__(self, bus, worker_id="w1", job_id="job", delay=0.0):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.delay = delay
        self.stop_flag = threading.Event()
        self.served_batches = 0
        self.cache.register_worker(job_id, worker_id,
                                   info={"trial_id": "t1"})
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            for it in items:
                if self.delay:
                    time.sleep(self.delay)
                self.served_batches += 1
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [[float(q), float(q) + 0.5] for q in it["queries"]])

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


@pytest.fixture()
def bus():
    return MemoryBus()


def _predictor(bus, **kw):
    kw.setdefault("worker_wait_timeout", 5.0)
    kw.setdefault("gather_timeout", 5.0)
    return Predictor("job", bus, **kw)


def _service(bus, **kw):
    """PredictorService on a free port, lifecycle managed by the test
    (meta is not exercised: the routes under test never touch it)."""
    svc = PredictorService("svc", "job", meta=None, bus=bus,
                           host="127.0.0.1", **kw)
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    if svc.batcher is not None:
        svc.batcher.start()
    svc._http.start()
    return svc


def _teardown(svc):
    svc._http.stop()
    if svc.batcher is not None:
        svc.batcher.stop()


def test_concurrent_predict_no_cross_request_bleed(bus):
    """N handler threads hammering one PredictorService must each get
    exactly their own slice of the coalesced super-batch."""
    worker = EchoWorker(bus)
    svc = _service(bus)
    url = f"http://127.0.0.1:{svc.port}/predict"
    results = {}
    errors = []

    def client(i):
        try:
            qs = [i * 100 + j for j in range(1 + i % 4)]  # ragged sizes
            r = requests.post(url, json={"queries": qs}, timeout=30)
            r.raise_for_status()
            results[i] = (qs, r.json()["predictions"])
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(16)]
    try:
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errors, errors
        assert len(results) == 16
        for i, (qs, preds) in results.items():
            assert preds == [[float(q), float(q) + 0.5] for q in qs], \
                f"client {i} got another request's slice"
    finally:
        _teardown(svc)
        worker.stop()


def test_microbatcher_coalesces_concurrent_requests(bus):
    """Concurrent submits within one fill window ride ONE scatter-gather
    super-batch (requests >> batches; worker sees few batch frames)."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.05, max_batch=256,
                      max_inflight=2, queue_cap=1024).start()
    try:
        out = {}
        barrier = threading.Barrier(12)

        def client(i):
            barrier.wait()
            out[i] = mb.submit([i, i + 1000], timeout=15)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert len(out) == 12
        for i in range(12):
            assert out[i] == [[float(i), float(i) + 0.5],
                              [float(i + 1000), float(i + 1000) + 0.5]]
        snap = mb.stats.snapshot()
        assert snap["requests"] == 12
        assert snap["batches"] < 12, "no coalescing happened"
        assert snap["coalescing_factor"] > 1.5
        # the worker saw one frame per super-batch, not one per request
        assert worker.served_batches == snap["batches"]
    finally:
        mb.stop()
        worker.stop()


def test_keep_n_in_flight_overlaps_gather_with_next_scatter(bus):
    """With a slow worker and max_inflight=2, super-batch K+1 must be
    scattered while K's gather is still blocking."""
    worker = EchoWorker(bus, delay=0.15)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=2,
                      max_inflight=2, queue_cap=1024).start()
    try:
        threads = [threading.Thread(
            target=lambda i=i: mb.submit([i], timeout=30))
            for i in range(8)]
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        snap = mb.stats.snapshot()
        assert snap["inflight_peak"] == 2, snap
    finally:
        mb.stop()
        worker.stop()


def test_backpressure_returns_429_with_retry_after(bus):
    """Sustained overload must bounce with 429 + Retry-After while the
    admission queue stays bounded — not grow latency without bound."""
    worker = EchoWorker(bus, delay=0.25)  # each super-batch is slow
    svc = _service(bus, queue_cap=6, max_inflight=1, fill_window=0.01,
                   max_batch=4)
    url = f"http://127.0.0.1:{svc.port}/predict"
    codes = []
    codes_lock = threading.Lock()

    def client(i):
        r = requests.post(url, json={"queries": [i, i, i]}, timeout=60)
        with codes_lock:
            codes.append((r.status_code, r.headers.get("Retry-After"),
                          r.json()))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(24)]
    try:
        [t.start() for t in threads]
        [t.join(timeout=60) for t in threads]
        assert len(codes) == 24
        rejected = [c for c in codes if c[0] == 429]
        served = [c for c in codes if c[0] == 200]
        assert rejected, "overload never produced a 429"
        assert served, "every request was rejected"
        for status, retry_after, body in rejected:
            assert retry_after is not None and int(retry_after) >= 1
            assert body["queue_cap"] == 6
        # bounded queue: admitted depth never exceeded the cap
        assert svc.stats.queue_depth_peak <= 6
        assert svc.stats.rejected == len(rejected)
    finally:
        _teardown(svc)
        worker.stop()


def test_microbatch_disabled_restores_direct_path(bus):
    """RAFIKI_TPU_SERVING_MICROBATCH=0: no batcher, requests scatter
    directly — the bench's A/B baseline."""
    worker = EchoWorker(bus)
    svc = _service(bus, microbatch=False)
    url = f"http://127.0.0.1:{svc.port}"
    try:
        assert svc.batcher is None
        r = requests.post(f"{url}/predict", json={"queries": [1, 2]},
                          timeout=30)
        assert r.status_code == 200
        assert r.json()["predictions"] == [[1.0, 1.5], [2.0, 2.5]]
        stats = requests.get(f"{url}/stats", timeout=10).json()
        assert stats["microbatch"] is False
        assert stats["batches"] == 0 and stats["requests"] == 1
    finally:
        _teardown(svc)
        worker.stop()


def test_microbatch_env_toggle(bus, monkeypatch):
    monkeypatch.delenv("RAFIKI_TPU_SERVING_MICROBATCH", raising=False)
    assert PredictorService("s", "j", None, bus).batcher is not None
    monkeypatch.setenv("RAFIKI_TPU_SERVING_MICROBATCH", "0")
    assert PredictorService("s", "j", None, bus).batcher is None
    # constructor arg beats env
    assert PredictorService("s", "j", None, bus,
                            microbatch=True).batcher is not None
    # knob envs reach the batcher
    monkeypatch.setenv("RAFIKI_TPU_SERVING_MICROBATCH", "1")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_FILL_WINDOW", "0.02")
    monkeypatch.setenv("RAFIKI_TPU_SERVING_QUEUE_CAP", "99")
    b = PredictorService("s", "j", None, bus).batcher
    assert b.fill_window == 0.02 and b.queue_cap == 99


def test_choose_workers_race_free(bus):
    """_rr/_bins are mutated from every handler thread in batcher-off
    mode; concurrent rotation must lose no increments and the per-bin
    replica pick must stay valid throughout."""
    cache = Cache(bus)
    cache.register_worker("job", "wA1", info={"trial_id": "tA"})
    cache.register_worker("job", "wA2", info={"trial_id": "tA"})
    cache.register_worker("job", "wB", info={"trial_id": "tB"})
    p = _predictor(bus)
    bad = []

    def spin():
        for _ in range(50):
            pick = p._choose_workers()
            if len(pick) != 2 or "wB" not in pick or \
                    (("wA1" in pick) == ("wA2" in pick)):
                bad.append(pick)

    threads = [threading.Thread(target=spin) for _ in range(8)]
    [t.start() for t in threads]
    [t.join(timeout=30) for t in threads]
    assert not bad, bad[:3]
    assert p._rr == 8 * 50, "lost round-robin increments under races"


def test_backpressure_exception_fields():
    e = Backpressure(2.0, depth=10, cap=8)
    assert e.retry_after == 2.0 and e.depth == 10 and e.cap == 8
    assert "retry after" in str(e)


def test_stop_fails_waiters_fast_and_rejects_late_submits(bus):
    """stop() must promptly fail BOTH queued requests and already-
    scattered super-batches (never leave a handler blocked until its
    full timeout), and submits after stop must raise immediately."""
    cache = Cache(bus)
    cache.register_worker("job", "w1", info={"trial_id": "t1"})
    # no worker thread: scattered batches never get replies
    p = _predictor(bus, gather_timeout=30.0)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=2, max_inflight=1,
                      queue_cap=64).start()
    outcomes = []

    def client(i):
        t0 = time.time()
        try:
            mb.submit([i], timeout=60)
            outcomes.append(("ok", time.time() - t0))
        except RuntimeError as e:
            outcomes.append((str(e), time.time() - t0))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(4)]
    [t.start() for t in threads]
    time.sleep(0.5)  # first batch scattered + in flight, rest queued
    mb.stop()
    [t.join(timeout=10) for t in threads]
    assert len(outcomes) == 4
    for msg, elapsed in outcomes:
        assert "micro-batcher stopped" in msg
        assert elapsed < 15, "waiter hung past stop()"
    with pytest.raises(RuntimeError, match="stopped"):
        mb.submit([1], timeout=5)


def test_empty_and_oversized_requests(bus):
    """Empty submit returns []; a single request larger than the whole
    queue cap is still admitted when the queue is empty (it could never
    be served otherwise)."""
    worker = EchoWorker(bus)
    p = _predictor(bus)
    mb = MicroBatcher(p, fill_window=0.01, max_batch=4, max_inflight=1,
                      queue_cap=4).start()
    try:
        assert mb.submit([], timeout=5) == []
        big = list(range(10))  # > queue_cap AND > max_batch
        out = mb.submit(big, timeout=15)
        assert out == [[float(q), float(q) + 0.5] for q in big]
    finally:
        mb.stop()
        worker.stop()
