"""Serving attribution ledger (ISSUE r17): per-bin / per-tenant
request accounting, the ``_tenant`` envelope carry, series lifecycle
(zero series when off, dropped on stop), and the on-demand device
profiling control frame.
"""

import os
import threading
import time

import pytest
import requests

from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.cache import Cache
from rafiki_tpu.observe import attribution as attr
from rafiki_tpu.observe import trace
from rafiki_tpu.observe.metrics import registry

FAMILIES = (
    "rafiki_tpu_serving_bin_queries_total",
    "rafiki_tpu_serving_bin_queue_seconds_total",
    "rafiki_tpu_serving_bin_rejected_total",
    "rafiki_tpu_serving_bin_requests_total",
    "rafiki_tpu_serving_bin_compute_seconds_total",
    "rafiki_tpu_serving_bin_device_seconds",
    "rafiki_tpu_serving_tenant_requests_total",
    "rafiki_tpu_serving_tenant_device_seconds_total",
    "rafiki_tpu_serving_tenant_request_seconds",
)


def _samples(name):
    m = registry().find(name)
    if m is None:
        return []
    if hasattr(m, "samples"):
        return m.samples()
    with m._lock:  # histogram: series keys stand in for samples
        return [(dict(k), None) for k in m._series]


def _wipe():
    """Remove every ledger series from the process registry (tests
    share one registry; each test starts from a clean slate)."""
    for name in FAMILIES:
        m = registry().find(name)
        if m is not None:
            m.remove()


@pytest.fixture()
def ledger(monkeypatch):
    monkeypatch.setenv(attr.ATTRIBUTION_ENV, "1")
    attr.reset_for_tests()
    _wipe()
    yield attr
    _wipe()
    attr.reset_for_tests()


@pytest.fixture()
def ledger_off(monkeypatch):
    monkeypatch.delenv(attr.ATTRIBUTION_ENV, raising=False)
    attr.reset_for_tests()
    yield attr
    attr.reset_for_tests()


# --- Unit: keys, envelope, gating ------------------------------------

def test_tenant_key_is_bounded_hash():
    k = attr.tenant_key("client-api-key-SECRET")
    assert k and len(k) == 12 and "SECRET" not in k
    assert attr.tenant_key("client-api-key-SECRET") == k  # stable
    assert attr.tenant_key("") is None and attr.tenant_key(None) is None


def test_tenant_envelope_roundtrip_cap_and_malformed():
    env = attr.inject_tenants([("a", 3), ("b", 1), ("a", 2)])
    assert env == [["a", 5], ["b", 1]]  # merged, largest first
    frame = {"batch_id": "x", attr.ENVELOPE_KEY: env}
    assert attr.extract_tenants(frame) == [("a", 5), ("b", 1)]
    assert attr.ENVELOPE_KEY not in frame  # popped
    # cap: only the top MAX_ENVELOPE_TENANTS ride
    many = [(f"t{i:02d}", i + 1) for i in range(20)]
    env = attr.inject_tenants(many)
    assert len(env) == attr.MAX_ENVELOPE_TENANTS
    assert env[0] == ["t19", 20]
    # malformed / absent / old frames degrade to []
    assert attr.inject_tenants(None) is None
    assert attr.inject_tenants([("", 3), ("x", 0)]) is None
    assert attr.extract_tenants({"batch_id": "x"}) == []
    assert attr.extract_tenants({attr.ENVELOPE_KEY: "bogus"}) == []
    assert attr.extract_tenants({attr.ENVELOPE_KEY: [["a"]]}) == []
    merged = attr.extract_frames_tenants([
        {attr.ENVELOPE_KEY: [["a", 2]]},
        {attr.ENVELOPE_KEY: [["a", 1], ["b", 4]]}, {"old": 1}])
    assert merged == [("b", 4), ("a", 3)]


def test_disabled_ledger_is_inert(ledger_off):
    assert attr._families() is None
    before = {n: len(_samples(n)) for n in FAMILIES}
    attr.open_owner()
    attr.account_admitted("deadbeef", 3)
    attr.account_rejected("svc", "queue_full")
    attr.account_scatter("svc", {"t1": 4}, queue_wait_s=0.5)
    attr.account_burst("job", "t1", 4, 0.01, bucket=8, dtype="f32")
    attr.account_tenant_device([("x", 2)], 0.01, 4)
    attr.close_service("svc")
    attr.close_worker("job", "t1")
    assert {n: len(_samples(n)) for n in FAMILIES} == before


# --- Unit: accounting + lifecycle ------------------------------------

def test_ledger_accounts_and_lifecycle(ledger):
    attr.open_owner()  # the frontend
    attr.open_owner()  # the worker
    t = attr.tenant_key("alice")
    attr.account_admitted(t)
    attr.account_admitted(t)
    attr.account_scatter("svcA", {"t1": 4, "t2": 4}, queue_wait_s=0.25)
    attr.account_rejected("svcA", "client_share")
    attr.account_burst("job12345", "t1", 4, 0.02, bucket=8,
                       dtype="float32", quant="int8", mode="stacked")
    attr.account_tenant_device([(t, 2)], 0.02, 4)

    q = registry().find("rafiki_tpu_serving_bin_queries_total")
    assert q.value(service="svcA", bin="t1") == 4
    assert q.value(service="svcA", bin="t2") == 4
    w = registry().find("rafiki_tpu_serving_bin_queue_seconds_total")
    assert w.value(service="svcA", bin="t1") == pytest.approx(0.25)
    r = registry().find("rafiki_tpu_serving_tenant_requests_total")
    assert r.value(tenant=t) == 2
    b = registry().find("rafiki_tpu_serving_bin_requests_total")
    assert b.value(job="job12345", bin="t1") == 4
    h = registry().find("rafiki_tpu_serving_bin_device_seconds")
    assert h.count(job="job12345", bin="t1", bucket="8",
                   dtype="float32", quant="int8", mode="stacked") == 1
    d = registry().find(
        "rafiki_tpu_serving_tenant_device_seconds_total")
    assert d.value(tenant=t) == pytest.approx(0.02 * 2 / 4)

    # Frontend stop drops ITS service-labeled series only.
    attr.close_service("svcA")
    assert q.value(service="svcA", bin="t1") == 0
    assert b.value(job="job12345", bin="t1") == 4  # worker side intact
    assert r.value(tenant=t) == 2  # one owner still open
    # Last owner out clears the process-global tenant rollup.
    attr.close_worker("job12345", "t1")
    assert b.value(job="job12345", bin="t1") == 0
    assert _samples("rafiki_tpu_serving_tenant_requests_total") == []
    assert _samples(
        "rafiki_tpu_serving_tenant_device_seconds_total") == []


def test_restack_drops_old_bin_series_without_owner_close(ledger):
    """The promote-path restack swaps a live worker's bin in place:
    the OLD bin's (job, bin) series must drop (promotion churn can
    never grow the scrape), but the worker stays an owner — the
    tenant rollup must survive."""
    attr.open_owner()
    t = attr.tenant_key("carol")
    attr.account_admitted(t)
    attr.account_burst("jobP", "tOLD", 4, 0.01)
    attr.drop_worker_bin("jobP", "tOLD")
    b = registry().find("rafiki_tpu_serving_bin_requests_total")
    assert all(labels.get("bin") != "tOLD" for labels, _ in b.samples())
    # owner refcount untouched: the tenant rollup is still live
    r = registry().find("rafiki_tpu_serving_tenant_requests_total")
    assert r.value(tenant=t) == 1
    attr.close_worker("jobP", "tNEW")
    assert _samples("rafiki_tpu_serving_tenant_requests_total") == []


def test_close_worker_matches_truncated_labels(ledger):
    """account_burst truncates job/bin labels to 12 chars (bounded
    cardinality); close_worker must truncate identically or the
    removal never matches the series (regression: real ids are 32-hex
    uuids)."""
    job = "a" * 32
    bin_id = "b" * 32 + "," + "c" * 32  # a packed multi-member bin
    attr.open_owner()
    attr.account_burst(job, bin_id, 4, 0.01)
    b = registry().find("rafiki_tpu_serving_bin_requests_total")
    assert b.value(job=job[:12], bin=bin_id[:12]) == 4
    attr.close_worker(job, bin_id)
    assert _samples("rafiki_tpu_serving_bin_requests_total") == []
    assert _samples(
        "rafiki_tpu_serving_bin_compute_seconds_total") == []


def test_tenant_lru_cap_evicts_series(ledger):
    attr.open_owner()
    try:
        for i in range(attr.TENANT_CAP + 10):
            attr.account_admitted(f"tenant{i:03d}")
        rollup = _samples("rafiki_tpu_serving_tenant_requests_total")
        assert len(rollup) == attr.TENANT_CAP
        tenants = {labels["tenant"] for labels, _ in rollup}
        assert "tenant000" not in tenants  # oldest evicted
        assert f"tenant{attr.TENANT_CAP + 9:03d}" in tenants
        # touching keeps a tenant alive
        attr.account_admitted(f"tenant{attr.TENANT_CAP + 9:03d}")
        assert len(_samples(
            "rafiki_tpu_serving_tenant_requests_total")) == attr.TENANT_CAP
    finally:
        attr.close_owner()


# --- Worker side: envelope -> (job, bin) + tenant device time ---------

def test_worker_burst_accounts_bin_and_tenants(ledger):
    from rafiki_tpu.worker.inference import InferenceWorker

    bus = MemoryBus()
    worker = InferenceWorker("wsvc", "jobXYZ", "t1", meta=None,
                             params=None, bus=bus)

    class _Model:
        def predict_submit(self, queries):
            return lambda: [[float(q), 0.0] for q in queries]

    worker._model = _Model()
    t = attr.tenant_key("bob")
    items = [{"batch_id": "b1", "queries": [1, 2, 3],
              attr.ENVELOPE_KEY: [[t, 3]]}]
    handle = worker._dispatch_batch(items)
    worker._complete_batch(*handle)
    b = registry().find("rafiki_tpu_serving_bin_requests_total")
    assert b.value(job="jobXYZ", bin="t1") == 3
    c = registry().find("rafiki_tpu_serving_bin_compute_seconds_total")
    assert c.value(job="jobXYZ", bin="t1") > 0
    d = registry().find(
        "rafiki_tpu_serving_tenant_device_seconds_total")
    assert d.value(tenant=t) > 0
    h = registry().find("rafiki_tpu_serving_bin_device_seconds")
    assert h.count(job="jobXYZ", bin="t1", bucket="-", dtype="-",
                   quant="-", mode="single") == 1
    # the reply still went out, untouched by the envelope pop
    reply = bus.pop("r:b1", timeout=2.0)
    assert len(reply["predictions"]) == 3


# --- Frontend e2e: header -> tenant hash -> envelope -> series --------

class _LedgerEchoWorker:
    """Bus-level worker recording the tenant envelopes it receives."""

    def __init__(self, bus, worker_id="w1", job_id="job",
                 trial_id="t1", score=None):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.stop_flag = threading.Event()
        self.tenants = []
        info = {"trial_id": trial_id}
        if score is not None:
            info["score"] = score
        self.cache.register_worker(job_id, worker_id, info=info)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            self.tenants.extend(attr.extract_frames_tenants(items))
            for it in items:
                if "queries" not in it:
                    continue
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [[float(q), 0.0] for q in it["queries"]],
                    shard=it.get("shard"))

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


def test_frontend_attribution_e2e_and_stop_drops_series(ledger):
    from rafiki_tpu.predictor.app import PredictorService

    bus = MemoryBus()
    worker = _LedgerEchoWorker(bus)
    svc = PredictorService("asvc", "job", meta=None, bus=bus,
                           host="127.0.0.1", client_header="X-Client")
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    svc.batcher.start()
    svc._http.start()
    try:
        r = requests.post(
            f"http://127.0.0.1:{svc.port}/predict",
            json={"queries": [1, 2]},
            headers={"X-Client": "alice"}, timeout=30)
        assert r.status_code == 200
        t = attr.tenant_key("alice")
        # tenant rollup accounted at admission
        tr = registry().find("rafiki_tpu_serving_tenant_requests_total")
        assert tr.value(tenant=t) == 1
        # per-bin frontend series under THIS frontend's service label
        service = svc.stats.service
        q = registry().find("rafiki_tpu_serving_bin_queries_total")
        assert q.value(service=service, bin="t1") == 2
        qw = registry().find(
            "rafiki_tpu_serving_bin_queue_seconds_total")
        assert qw.value(service=service, bin="t1") > 0
        # the tenant envelope reached the worker's frames
        deadline = time.time() + 5
        while time.time() < deadline and not worker.tenants:
            time.sleep(0.05)
        assert (t, 2) in worker.tenants
        # an anonymous request accounts no tenant but still scatters
        r = requests.post(f"http://127.0.0.1:{svc.port}/predict",
                          json={"queries": [3]}, timeout=30)
        assert r.status_code == 200
        assert q.value(service=service, bin="t1") == 3
        assert tr.value(tenant=t) == 1
        # a malformed body (400) must not inflate the tenant rollup
        r = requests.post(f"http://127.0.0.1:{svc.port}/predict",
                          json={"bogus": 1},
                          headers={"X-Client": "alice"}, timeout=30)
        assert r.status_code == 400
        assert tr.value(tenant=t) == 1
    finally:
        svc._http.stop()
        svc.batcher.stop()
        svc.stats.close()
        svc.predictor.close()
        worker.stop()
    # stop dropped the frontend's series; last owner cleared tenants
    q = registry().find("rafiki_tpu_serving_bin_queries_total")
    assert all(labels.get("service") != service
               for labels, _ in q.samples())
    assert _samples("rafiki_tpu_serving_tenant_requests_total") == []


def test_tiered_escalation_carries_tenant_envelope(ledger):
    """ISSUE r19 satellite (the r17 'under-attributed by design'
    carry): the tiered path's SECOND scatter re-derives the escalated
    subset's tenant mix from the per-query tenant column, so the
    escalation bin's worker receives a ``_tenant`` envelope too —
    before the fix it received none and the escalated queries' device
    time went unattributed."""
    from rafiki_tpu.predictor.predictor import Predictor

    bus = MemoryBus()
    best = _LedgerEchoWorker(bus, worker_id="wbest", trial_id="tbest",
                             score=0.9)
    other = _LedgerEchoWorker(bus, worker_id="wother",
                              trial_id="tother", score=0.5)
    pred = Predictor("job", bus, gather_timeout=5.0,
                     worker_wait_timeout=5.0, tier_threshold=0.5)
    try:
        ta, tb = attr.tenant_key("alice"), attr.tenant_key("bob")
        # echo replies carry NO confidence -> every query escalates;
        # alice owns queries 0-1, bob query 2.
        out = pred.predict([1, 2, 3],
                           tenants=[(ta, 2), (tb, 1)],
                           tenant_rows=[ta, ta, tb])
        assert len(out) == 3 and all(v is not None for v in out)
        deadline = time.time() + 5
        while time.time() < deadline and \
                (not best.tenants or not other.tenants):
            time.sleep(0.05)
        # phase 1 (best bin) carried the whole batch's mix...
        assert (ta, 2) in best.tenants and (tb, 1) in best.tenants
        # ...and the ESCALATION scatter carried the subset's own mix
        assert (ta, 2) in other.tenants and (tb, 1) in other.tenants
        # counter-pinned: the escalation bin's scatter accounted its
        # per-bin queries under the frontend label too
        q = registry().find("rafiki_tpu_serving_bin_queries_total")
        assert q.value(service=pred.service, bin="tbest") == 3
        assert q.value(service=pred.service, bin="tother") == 3
    finally:
        pred.close()
        best.stop()
        other.stop()


def test_zero_series_when_attribution_off_e2e(ledger_off):
    """The acceptance gate at the service level: a full serve with the
    ledger OFF adds not one bin/tenant sample."""
    from rafiki_tpu.predictor.app import PredictorService

    before = {n: len(_samples(n)) for n in FAMILIES}
    bus = MemoryBus()
    worker = _LedgerEchoWorker(bus)
    svc = PredictorService("zsvc", "job", meta=None, bus=bus,
                           host="127.0.0.1", client_header="X-Client")
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    svc.batcher.start()
    svc._http.start()
    try:
        r = requests.post(
            f"http://127.0.0.1:{svc.port}/predict",
            json={"queries": [1, 2]},
            headers={"X-Client": "alice"}, timeout=30)
        assert r.status_code == 200
    finally:
        svc._http.stop()
        svc.batcher.stop()
        svc.stats.close()
        svc.predictor.close()
        worker.stop()
    assert {n: len(_samples(n)) for n in FAMILIES} == before


# --- On-demand device profiling (worker serve loop) -------------------

class _FakeMeta:
    def update_service(self, *a, **k):
        pass

    def update_inference_job_worker(self, *a, **k):
        pass


def test_profile_control_frame_on_live_worker(tmp_path, ledger_off):
    """A ``__profile__`` frame starts a bounded jax.profiler session on
    the live serve loop: the artifact dir fills with a readable
    profile, and serving is undisturbed (every query before, during,
    and after the session is answered) — the r17 acceptance leg at the
    worker level; the admin route is exercised in test_platform."""
    from rafiki_tpu.worker.inference import InferenceWorker

    class _Model:
        def predict_submit(self, queries):
            import jax.numpy as jnp

            x = jnp.ones((8, 8))
            y = (x @ x).sum()  # real device work inside the window
            return lambda: [[float(q), float(y) * 0.0]
                            for q in queries]

    class _Worker(InferenceWorker):
        def _load_model(self):
            return _Model()

    bus = MemoryBus()
    worker = _Worker("psvc", "job", "t1", meta=_FakeMeta(),
                     params=None, bus=bus, batch_timeout=0.1,
                     pipeline=False)
    worker.start()
    cache = Cache(bus)
    out_dir = str(tmp_path / "prof")
    try:
        deadline = time.time() + 30
        while time.time() < deadline and \
                not cache.running_workers("job"):
            time.sleep(0.05)
        assert cache.running_workers("job") == ["psvc"]

        def ask(n, tag):
            bid = cache.send_query_batch("psvc", list(range(n)),
                                         batch_id=f"{tag}")
            replies = cache.gather_prediction_batches(bid, 1,
                                                      timeout=10)
            assert replies and len(replies[0]["predictions"]) == n, tag

        ask(4, "before")
        cache.send_profile("psvc", out_dir, duration_s=1.0)
        ask(4, "during1")
        ask(4, "during2")
        time.sleep(1.5)  # session deadline passes; loop stops it
        ask(4, "after")
        # the artifact is a readable profile (TensorBoard layout)
        deadline = time.time() + 15
        files = []
        while time.time() < deadline and not files:
            files = [os.path.join(r, f)
                     for r, _, fs in os.walk(out_dir) for f in fs]
            time.sleep(0.1)
        assert any("profile" in f or f.endswith(".pb") for f in files), \
            files
        # counter-proven: the session started AND stopped, and every
        # request during it was answered (asserted in ask()).
        sessions = registry().find("rafiki_tpu_profile_sessions_total")
        assert sessions is not None
        assert sessions.value(event="start") >= 1
        assert sessions.value(event="stop") >= 1
    finally:
        worker.stop()
