"""Cluster serving fabric (docs/cluster.md): node registry, inter-node
relay, the shared edge-cache fabric, per-node scrape grouping, and the
``node.kill`` chaos site.

The zero-series / zero-thread contract is asserted at the CONSTRUCTION
level here (``relay_counter is None``, ``_fabric is False``) rather
than by grepping the process-global metrics registry, because sibling
tests in one pytest process legitimately register cluster series; the
registry-global form of the contract is asserted by
``bench.py --config cluster``, which owns its process.
"""

import os
import threading
import time

import pytest
import requests

from rafiki_tpu import faults
from rafiki_tpu.admin.nodes import NodeRegistry, node_key
from rafiki_tpu.admin.scrape import (merge_worker_expositions,
                                     worker_scrape_targets)
from rafiki_tpu.bus import connect, serve_broker
from rafiki_tpu.bus.memory import MemoryBus
from rafiki_tpu.cache import Cache, encode_payload
from rafiki_tpu.constants import (BudgetOption, ServiceStatus, ServiceType,
                                  TaskType, UserType)
from rafiki_tpu.model import load_image_dataset
from rafiki_tpu.observe.metrics import registry as metrics_registry
from rafiki_tpu.platform import LocalPlatform
from rafiki_tpu.predictor.app import PredictorService
from rafiki_tpu.predictor.edge_cache import EdgeCache

FF_CLASS = "rafiki_tpu.models.feedforward:JaxFeedForward"


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    faults.reset()
    yield
    faults.reset()


# --- Node registry ------------------------------------------------------


def _registry(bus, node_id, lease_s=5.0, bus_uri=""):
    return NodeRegistry(lambda: bus, node_id, n_chips=2,
                        bus_uri=bus_uri, lease_s=lease_s)


def test_node_registry_announce_live_withdraw():
    bus = MemoryBus()
    ra = _registry(bus, "vm/a", bus_uri="tcp://127.0.0.1:1")
    rb = _registry(bus, "vm/b", bus_uri="tcp://127.0.0.1:2")
    try:
        ra.announce()
        rb.announce()
        nodes = ra.nodes()
        assert set(nodes) == {"vm/a", "vm/b"}
        assert all(r["live"] for r in nodes.values())
        assert nodes["vm/b"]["chips"] == 2
        assert ra.live_nodes() == ["vm/a", "vm/b"]
        # relay_peers excludes self and carries the peer's broker URI.
        assert ra.relay_peers() == {"vm/b": "tcp://127.0.0.1:2"}
        # A heartbeat older than the lease stops counting as live...
        rec = bus.get(node_key("vm/b"))
        rec["hb"] = time.time() - 60.0
        bus.set(node_key("vm/b"), rec)
        assert ra.live_nodes() == ["vm/a"]
        assert ra.relay_peers() == {}
        # ...and a withdrawn node disappears outright.
        rb.withdraw()
        assert set(ra.nodes()) == {"vm/a"}
        snap = ra.snapshot()
        assert snap["enabled"] and snap["node_id"] == "vm/a"
        health = ra.health()
        assert health == {"fabric": True, "nodes_registered": 1,
                          "nodes_live": 1}
    finally:
        ra.close()
        rb.close()
    assert metrics_registry().find("rafiki_tpu_node_peers") is None or \
        not list(metrics_registry().find(
            "rafiki_tpu_node_peers").samples())


def test_node_registry_spread_vote_round_robin():
    """Exactly ONE node elects itself per pressure round, and it is
    always a node holding a minimum replica count — N nodes reacting
    to the same signal lay replicas across failure domains instead of
    N-fold over-provisioning one box."""
    bus = MemoryBus()
    regs = {n: _registry(bus, n) for n in ("vm/a", "vm/b", "vm/c")}
    try:
        for r in regs.values():
            r.announce()
        # Bin has one replica on vm/a: the minimum holders are b and c;
        # the deterministic tie-break elects exactly vm/b.
        counts = {"vm/a": 1}
        votes = {n: r.spread_ok(counts) for n, r in regs.items()}
        assert votes == {"vm/a": False, "vm/b": True, "vm/c": False}
        # Even coverage: the FIRST minimum holder in sorted order acts.
        counts = {"vm/a": 1, "vm/b": 1, "vm/c": 1}
        votes = {n: r.spread_ok(counts) for n, r in regs.items()}
        assert votes == {"vm/a": True, "vm/b": False, "vm/c": False}
        # A registry that cannot see its own node never blocks scaling.
        lone = _registry(bus, "vm/ghost")
        try:
            assert lone.spread_ok({"vm/a": 9})
        finally:
            lone.close()
    finally:
        for r in regs.values():
            r.close()


def test_get_nodes_disabled_and_enabled(tmp_path, monkeypatch):
    platform = LocalPlatform(workdir=str(tmp_path / "off"),
                             supervise_interval=0)
    try:
        assert platform.node_registry is None
        assert platform.admin.get_nodes() == {"enabled": False}
    finally:
        platform.shutdown()
    monkeypatch.setenv("RAFIKI_TPU_CLUSTER_FABRIC", "1")
    platform = LocalPlatform(workdir=str(tmp_path / "on"),
                             supervise_interval=0, node_id="vm/reg")
    try:
        assert platform.node_registry is not None
        body = platform.admin.get_nodes()
        assert body["enabled"] and body["node_id"] == "vm/reg"
        assert body["nodes"]["vm/reg"]["live"]
        status = platform.admin.get_status()
        assert status["cluster"]["nodes_live"] == 1
    finally:
        platform.shutdown()
    # Shutdown withdrew the record and dropped the registry's series.
    assert metrics_registry().find("rafiki_tpu_node_peers") is None or \
        not list(metrics_registry().find(
            "rafiki_tpu_node_peers").samples())


# --- Inter-node relay ---------------------------------------------------


def _relay_counts():
    c = metrics_registry().find("rafiki_tpu_bus_relay_total")
    if c is None:
        return {}
    return {lab["direction"]: int(v) for lab, v in c.samples()}


def test_remote_scatter_pays_one_relay_hop_per_leg():
    """A shard bound for a worker on another node crosses the node
    boundary exactly ONCE per direction: the query leg is one broker→
    broker forward, the reply leg one forward back."""
    broker_a = serve_broker("127.0.0.1", 0, native=False, node_id="vm/a")
    broker_b = serve_broker("127.0.0.1", 0, native=False, node_id="vm/b")
    stop = threading.Event()
    worker = None
    try:
        broker_a.add_peer("vm/b", broker_b.uri)
        broker_b.add_peer("vm/a", broker_a.uri)
        cache_a = Cache(connect(broker_a.uri))
        cache_b = Cache(connect(broker_b.uri))
        cache_b.register_worker("job-r", "wb",
                                info={"trial_id": "t", "score": 0.9})

        def serve():
            while not stop.is_set():
                for it in cache_b.pop_queries("wb", timeout=0.1):
                    cache_b.send_prediction_batch(
                        it["batch_id"], "wb",
                        [[1.0]] * len(it["queries"]),
                        shard=it.get("shard"),
                        origin_node=it.get("onode"))

        worker = threading.Thread(target=serve, daemon=True)
        worker.start()
        base = _relay_counts()
        bid = cache_a.send_query_shards(
            [("wb", 0, 1, 0)], [encode_payload([1.0, 2.0])],
            worker_nodes={"wb": "vm/b"}, local_node="vm/a")
        replies = cache_a.gather_prediction_batches(bid, 1, timeout=10.0)
        assert len(replies) == 1
        assert replies[0]["predictions"] == [[1.0]]
        after = _relay_counts()
        assert after.get("out", 0) - base.get("out", 0) == 2, (base, after)
        assert after.get("in", 0) - base.get("in", 0) == 2, (base, after)
        assert after.get("fallback", 0) == base.get("fallback", 0)
    finally:
        stop.set()
        if worker is not None:
            worker.join(timeout=5)
        broker_b.stop()
        broker_a.stop()


def test_relay_to_dead_node_degrades_to_local_fallback():
    """Satellite (d): a relay addressed to a dead node's broker must
    neither wedge the sender nor drop the frame — the inner op executes
    against the sender's own broker (the pre-cluster behavior), counted
    as direction=fallback."""
    broker_a = serve_broker("127.0.0.1", 0, native=False, node_id="vm/a")
    broker_b = serve_broker("127.0.0.1", 0, native=False, node_id="vm/b")
    try:
        broker_a.add_peer("vm/b", broker_b.uri)
        bus_a = connect(broker_a.uri)
        broker_b.stop()
        base = _relay_counts()
        t0 = time.monotonic()
        bus_a.relay_push("vm/b", "dead-q", {"v": 7})
        elapsed = time.monotonic() - t0
        after = _relay_counts()
        assert after.get("fallback", 0) - base.get("fallback", 0) == 1
        # The frame landed on the LOCAL broker's queue...
        assert bus_a.pop("dead-q", timeout=2.0) == {"v": 7}
        # ...and the sender was bounded by the per-peer retry budget,
        # not a gather-scale timeout.
        assert elapsed < 10.0, elapsed
    finally:
        broker_b.stop()
        broker_a.stop()


def test_single_node_construction_has_no_cluster_surface(tmp_path):
    """Zero-series contract at the construction level: a default broker
    registers no relay machinery, and a fabric-off frontend neither
    registers with the fleet nor owns a fabric counter handle."""
    assert not os.environ.get("RAFIKI_TPU_CLUSTER_FABRIC")
    broker = serve_broker("127.0.0.1", 0, native=False)
    try:
        assert broker.node_id == ""
        assert broker._server.relay_counter is None
    finally:
        broker.stop()
    svc = PredictorService("zero-fab", "job-z", meta=None,
                           bus=MemoryBus(), host="127.0.0.1",
                           cache_bytes=1 << 16, microbatch=False)
    try:
        assert svc._fabric is False
        assert svc._m_fabric is None
        assert svc.edge_cache is not None  # the cache itself is r16
    finally:
        svc.stats.close()
        svc.predictor.close()
        svc.edge_cache.close()


# --- Edge-cache fabric --------------------------------------------------


def _make_frontend(bus, sid, job):
    svc = PredictorService(sid, job, meta=None, bus=bus,
                           host="127.0.0.1", cache_bytes=1 << 20,
                           cache_admit_after=1, microbatch=False)
    svc.predictor.worker_wait_timeout = 10.0
    svc.predictor.gather_timeout = 10.0
    svc._http.start()
    if svc._fabric:
        svc.predictor.cache.register_frontend(
            job, svc.stats.service, f"127.0.0.1:{svc.port}")
    return svc


def _stop_frontend(svc, job):
    if svc._fabric:
        svc.predictor.cache.unregister_frontend(job, svc.stats.service)
    svc._http.stop()
    svc.stats.close()
    svc.predictor.close()
    svc.edge_cache.close()
    if svc._m_fabric is not None:
        svc._m_fabric.remove(service=svc.stats.service)


def _fabric_events(svc):
    c = metrics_registry().find("rafiki_tpu_serving_fabric_total")
    if c is None:
        return {}
    return {lab["event"]: int(v) for lab, v in c.samples()
            if lab.get("service") == svc.stats.service}


def test_peer_hit_and_gossiped_invalidation(monkeypatch):
    """The fabric's two data paths over two live frontends: a miss on B
    converts to a peer hit against A's cache (no second scatter), and a
    promote-path invalidation on A gossips to B, whose next query of
    the same key MISSES and rescatters — a pre-promotion answer can
    never be served from a peer after the promotion."""
    monkeypatch.setenv("RAFIKI_TPU_CLUSTER_FABRIC", "1")
    monkeypatch.setenv("RAFIKI_TPU_CLUSTER_PROBE_TIMEOUT_S", "2.0")
    bus = MemoryBus()
    wcache = Cache(bus)
    served = {"n": 0}
    stop = threading.Event()
    wcache.register_worker("job-f", "wf",
                           info={"trial_id": "t", "score": 0.9})

    def serve():
        while not stop.is_set():
            for it in wcache.pop_queries("wf", timeout=0.1):
                n = len(it["queries"])
                served["n"] += n
                wcache.send_prediction_batch(
                    it["batch_id"], "wf", [[0.8, 0.2]] * n,
                    shard=it.get("shard"), compute_s=0.001 * n)

    worker = threading.Thread(target=serve, daemon=True)
    worker.start()
    fa = fb = None
    try:
        fa = _make_frontend(bus, "cfa", "job-f")
        fb = _make_frontend(bus, "cfb", "job-f")
        assert fa._fabric and fb._fabric
        q = encode_payload([3.0, 4.0])

        def post(svc, path, payload):
            r = requests.post(f"http://127.0.0.1:{svc.port}{path}",
                              json=payload, timeout=30)
            r.raise_for_status()
            return r.json()

        post(fa, "/predict", {"query": q})
        assert served["n"] == 1
        # B's first sight of the key: peer probe converts the miss.
        post(fb, "/predict", {"query": q})
        assert served["n"] == 1, "peer hit must not scatter"
        assert _fabric_events(fb).get("peer_hit") == 1
        # Promote-path invalidation on A gossips to B...
        epoch_b = fb.edge_cache.epoch
        post(fa, "/cache/invalidate", {})
        deadline = time.monotonic() + 5
        while fb.edge_cache.epoch <= epoch_b:
            assert time.monotonic() < deadline, "gossip never landed"
            time.sleep(0.01)
        assert _fabric_events(fa).get("gossip_sent") == 1
        assert _fabric_events(fb).get("gossip_recv") == 1
        # ...so B's next query MISSES and rescatters (and its peer
        # probe finds A empty too — no resurrected entry anywhere).
        post(fb, "/predict", {"query": q})
        assert served["n"] == 2, "stale entry survived the invalidation"
    finally:
        for svc in (fa, fb):
            if svc is not None:
                _stop_frontend(svc, "job-f")
        stop.set()
        worker.join(timeout=5)


def test_gossip_racing_local_insert_never_resurrects():
    """Satellite (d), the epoch race: a gossiped invalidation that
    lands AFTER a leader captured its epoch but BEFORE it resolves
    must drop the insert — the waiters still get the (pre-promotion)
    answer, the cache never does."""
    cache = EdgeCache(max_bytes=1 << 16, admit_after=1, service="race")
    try:
        kind, flight = cache.begin("k")
        assert kind == "lead"
        epoch = cache.epoch  # leader snapshot, pre-scatter
        # The gossiped invalidation lands mid-flight.
        cache.invalidate()
        cache.resolve("k", {"answer": "stale"}, epoch, flight=flight)
        # The waiter path still completes with the in-flight answer...
        assert flight.wait(1.0) == {"answer": "stale"}
        # ...but the entry was NOT inserted: the next begin is a fresh
        # leader, not a hit on a resurrected pre-promotion value.
        kind, _ = cache.begin("k")
        assert kind == "lead"
    finally:
        cache.close()


# --- Per-node scrape grouping (satellite a) -----------------------------


class _BusServices:
    def __init__(self, bus):
        self._bus = bus

    def serving_bus(self):
        return self._bus


def test_worker_scrape_targets_group_by_node_and_merge():
    bus = MemoryBus()
    bus.set("w:job1:s1", {"metrics": "127.0.0.1:9001", "node": "vm/a"})
    bus.set("w:job1:s2", {"metrics": "127.0.0.1:9002", "node": "vm/b"})
    bus.set("w:job1:s3", {"metrics": "127.0.0.1:9003", "node": "vm/b"})
    bus.set("w:job1:s4", {"trial_id": "t"})  # resident: no endpoint
    bus.set("w:job2:sx", {"metrics": "127.0.0.1:9009", "node": "vm/c"})
    by_node, silent = worker_scrape_targets(_BusServices(bus), "job1")
    assert by_node == {"vm/a": ["127.0.0.1:9001"],
                       "vm/b": ["127.0.0.1:9002", "127.0.0.1:9003"]}
    assert silent == 1

    calls = []

    def fetch(addr, path):
        calls.append((addr, path))
        if addr.endswith("9002"):
            raise OSError("connection refused")
        return f"# metrics from {addr}"

    text, fetched, failed = merge_worker_expositions(fetch, by_node)
    assert fetched == 2 and failed == 1
    assert "9001" in text and "9003" in text
    assert sorted(a for a, _ in calls) == [
        "127.0.0.1:9001", "127.0.0.1:9002", "127.0.0.1:9003"]
    assert all(p == "/metrics" for _, p in calls)


def test_worker_scrape_targets_empty_and_broken_bus():
    assert worker_scrape_targets(_BusServices(MemoryBus()),
                                 "job-none") == ({}, 0)

    class _Broken:
        def serving_bus(self):
            raise ConnectionError("broker down")

    # A scrape sweep must survive a broker outage: no targets, not an
    # exception into the SLO engine.
    assert worker_scrape_targets(_Broken(), "job1") == ({}, 0)


# --- node.kill chaos site (satellite b) ---------------------------------


def test_node_kill_bin_vote_survives_and_respawns(tmp_path,
                                                  synth_image_data):
    """The r11 chaos plane's new ``node.kill`` site, end to end: a
    secondary node hosting one replica of a served bin dies HARD (all
    its services killed, meta rows left RUNNING, registrations stale).
    The bin's vote survives — its sibling replica on the primary keeps
    answering — and the secondary's next supervise sweep detects the
    wreckage and respawns the replica, which rejoins the shard plan."""
    train_path, val_path = synth_image_data
    shared = str(tmp_path / "shared")
    broker = serve_broker("127.0.0.1", 0, native=False)
    faults.set_plan("")  # armed-quiet before any stack exists
    node_a = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                           http=True, supervise_interval=0)
    node_b = None
    try:
        dev = node_a.admin.create_user("nodekill@x.c", "pw",
                                       UserType.MODEL_DEVELOPER)
        model = node_a.admin.create_model(
            dev["id"], "ff-nk", TaskType.IMAGE_CLASSIFICATION, FF_CLASS)
        job = node_a.admin.create_train_job(
            dev["id"], "nk", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 1},
            train_path, val_path)
        assert node_a.admin.wait_until_train_job_done(job["id"],
                                                      timeout=600)
        inf = node_a.admin.create_inference_job(dev["id"], job["id"],
                                                max_models=1)
        host = node_a.admin.get_inference_job(
            inf["id"])["predictor_host"]
        pred_svc = next(s for s in node_a.meta.get_services()
                        if s["service_type"] == ServiceType.PREDICT)
        psvc = node_a.container.get(pred_svc["id"])
        psvc.predictor.gather_timeout = 4.0
        trial_id = node_a.services.active_inference_workers(
            inf["id"])[0]["trial_id"]

        # A secondary node attaches one REPLICA of the same bin.
        node_b = LocalPlatform(workdir=shared, bus_uri=broker.uri,
                               supervise_interval=0,
                               stop_jobs_on_shutdown=False,
                               node_id="vm/chaos-b")
        svc_b = node_b.services.add_inference_worker(inf["id"], trial_id)
        assert svc_b is not None

        ds = load_image_dataset(val_path)
        batch = [encode_payload(ds.images[i]) for i in range(3)]
        url = f"http://{host}/predict"

        def predict_full() -> bool:
            r = requests.post(url, json={"queries": batch}, timeout=60)
            if r.status_code != 200:
                return False
            preds = r.json().get("predictions") or []
            return len(preds) == len(batch) and \
                all(p is not None for p in preds)

        def replicas_in_plan() -> int:
            groups, _, _ = psvc.predictor._group_replicas()
            return sum(len(members) for members in groups.values())

        deadline = time.monotonic() + 120
        while replicas_in_plan() < 2:
            assert time.monotonic() < deadline, \
                "replica on the secondary node never joined the plan"
            predict_full()
            time.sleep(0.2)

        # --- Node B dies. The op match pins the blast radius: node A's
        # sweeps consult the same plan and never fire.
        faults.set_plan("node.kill:op=vm/chaos-b,n=1")
        assert node_a.services.supervise() == []
        node_b.services.supervise()
        # Hard death: container slot gone, meta row STILL RUNNING (the
        # wreckage shape supervise respawns from).
        assert node_b.container.get(svc_b["id"]) is None
        row = node_a.meta.get_service(svc_b["id"])
        assert row["status"] == ServiceStatus.RUNNING
        c = metrics_registry().find("rafiki_tpu_fault_injections_total")
        assert c is not None and c.value(site="node", kind="kill") >= 1

        # --- The bin's vote survives the node death: the sibling
        # replica on node A answers every query in full.
        assert predict_full(), \
            "bin lost its vote when the secondary node died"

        # --- Replan-and-respawn: node B's next sweep spots its own
        # stale wreckage and respawns the replica...
        deadline = time.monotonic() + 120
        respawned = []
        while not respawned:
            assert time.monotonic() < deadline, "respawn never happened"
            respawned = node_b.services.supervise()
            time.sleep(0.2)
        assert len(respawned) == 1
        # ...which rejoins the predictor's shard plan.
        deadline = time.monotonic() + 120
        while replicas_in_plan() < 2:
            assert time.monotonic() < deadline, \
                "respawned replica never rejoined the shard plan"
            predict_full()
            time.sleep(0.2)
        assert predict_full()
    finally:
        faults.set_plan(None)
        if node_b is not None:
            node_b.shutdown()
        node_a.shutdown()
        broker.stop()
