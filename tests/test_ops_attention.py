"""Attention ops: blockwise / flash (interpret) / ring vs the naive oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rafiki_tpu.ops import (blockwise_attention, flash_attention,
                            naive_attention, ring_attention,
                            sequence_sharded_attention)
from rafiki_tpu.parallel import build_mesh


def _qkv(rng, b=2, h=2, t=64, d=32, dtype=np.float32, tkv=None):
    tkv = t if tkv is None else tkv
    q = rng.standard_normal((b, h, t, d)).astype(dtype)
    k = rng.standard_normal((b, h, tkv, d)).astype(dtype)
    v = rng.standard_normal((b, h, tkv, d)).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(rng, causal):
    q, k, v = _qkv(rng)
    out = blockwise_attention(q, k, v, causal=causal, block_kv=16)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_blockwise_ragged_kv_and_uneven_blocks(rng):
    # Tkv not divisible by block_kv exercises the -1 padded-id mask.
    q, k, v = _qkv(rng, t=24, tkv=50)
    out = blockwise_attention(q, k, v, block_kv=16)
    ref = naive_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_grads_match_naive(rng, causal):
    q, k, v = _qkv(rng, b=1, h=1, t=32, d=16)

    def loss_block(q, k, v):
        return blockwise_attention(q, k, v, causal=causal,
                                   block_kv=8).sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=causal).sum()

    g1 = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(rng, causal):
    q, k, v = _qkv(rng, t=48, d=32)  # t not a block multiple, d < 128
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_kv=16)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_flash_cross_attention_shapes(rng):
    q, k, v = _qkv(rng, t=16, tkv=40, d=8)
    out = flash_attention(q, k, v, block_q=8, block_kv=16)
    ref = naive_attention(q, k, v)
    assert out.shape == (2, 2, 16, 8)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("fn", [blockwise_attention, flash_attention])
def test_causal_cross_attention_end_aligned(rng, fn):
    # tq != tkv with causal: q positions end-align against kv (decoding
    # convention) — q token 0 of an 8-token query over a 24-token kv may
    # attend kv[0..16], not just kv[0].
    q, k, v = _qkv(rng, t=8, tkv=24, d=16)
    out = fn(q, k, v, causal=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_flash_bf16(rng):
    q, k, v = _qkv(rng, dtype=np.float32)
    q, k, v = (a.astype(jnp.bfloat16) for a in (q, k, v))
    out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32)
    ref = naive_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_flash_grads_match_naive(rng):
    q, k, v = _qkv(rng, b=1, h=1, t=32, d=16)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=8,
                               block_kv=8).sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_flash_blocking_rounds_block_q_for_backward(rng):
    """Mosaic requires the backward's lse/delta row blocks
    (1, 1, block_q) to have a 128-divisible lane dim whenever the q
    axis is actually blocked (nq > 1) — ADVICE r5: jax.grad with
    block_q=32, T=256 failed TPU lowering. _flash_blocking now rounds
    block_q up (never past one whole-q block), for forward and
    backward identically."""
    from rafiki_tpu.ops.attention import _flash_blocking

    q = jnp.zeros((1, 1, 256, 64))
    k = jnp.zeros((1, 1, 256, 64))
    for req_bq in (8, 32, 96, 100, 128, 256):
        bq, _, nq, _, _ = _flash_blocking(q, k, None, req_bq, 64)
        assert nq == 1 or bq % 128 == 0, (req_bq, bq, nq)
        assert nq * bq >= 256
    # under one whole-q block the size is unconstrained
    q8 = jnp.zeros((1, 1, 48, 64))
    bq, _, nq, _, _ = _flash_blocking(q8, q8, None, 64, 64)
    assert nq == 1 and bq == 48

    # numerics (fwd + bwd) survive the rounding: the exact ADVICE shape
    q, k, v = _qkv(rng, b=1, h=2, t=256, d=32)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=64)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    g1 = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=32, block_kv=64).sum())(q)
    g2 = jax.grad(lambda q: naive_attention(
        q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-4, rtol=1e-4)


@pytest.mark.skipif(jax.default_backend() not in ("tpu", "axon"),
                    reason="real Mosaic lowering only happens on TPU")
def test_flash_backward_lowers_on_tpu_with_small_blocks(rng):
    """TPU-only regression for the ADVICE r5 lowering failure: small
    explicit blocks with nq > 1 must compile AND differentiate on the
    real chip (the CPU interpreter cannot catch BlockSpec tiling
    violations)."""
    q, k, v = _qkv(rng, b=1, h=1, t=256, d=64)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=64,
                          interpret=False)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-2, rtol=2e-2)
    g = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=32, block_kv=64,
        interpret=False).sum())(q)
    gr = jax.grad(lambda q: naive_attention(
        q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(g, gr, atol=2e-2, rtol=2e-2)


@pytest.mark.slow
def test_kv_mask_all_tiers(rng):
    # Key-padding mask: ragged batch of real lengths; every tier must
    # equal the naive oracle with the same mask.
    q, k, v = _qkv(rng, b=3, h=2, t=32, d=16)
    lengths = np.array([32, 7, 19])
    mask = jnp.asarray(np.arange(32)[None, :] < lengths[:, None])
    ref = naive_attention(q, k, v, kv_mask=mask)
    out_b = blockwise_attention(q, k, v, block_kv=8, kv_mask=mask)
    out_f = flash_attention(q, k, v, block_q=8, block_kv=8, kv_mask=mask)
    np.testing.assert_allclose(out_b, ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(out_f, ref, atol=1e-5, rtol=1e-5)

    mesh = build_mesh(jax.devices(), sp=8)
    out_r = sequence_sharded_attention(q, k, v, mesh, batch_axis=None,
                                       kv_mask=mask)
    np.testing.assert_allclose(out_r, ref, atol=1e-5, rtol=1e-5)

    # Gradients through the masked flash path (custom vjp w/ bias arg).
    g1 = jax.grad(lambda q: flash_attention(
        q, k, v, block_q=8, block_kv=8, kv_mask=mask).sum())(q)
    g2 = jax.grad(lambda q: naive_attention(
        q, k, v, kv_mask=mask).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(rng, causal):
    mesh = build_mesh(jax.devices(), sp=8)
    q, k, v = _qkv(rng, b=2, h=2, t=64, d=16)
    out = sequence_sharded_attention(q, k, v, mesh, causal=causal,
                                     batch_axis=None)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_ring_attention_grads(rng):
    mesh = build_mesh(jax.devices(), sp=4)
    q, k, v = _qkv(rng, b=1, h=1, t=32, d=8)

    def loss_ring(q, k, v):
        return sequence_sharded_attention(
            q, k, v, mesh, causal=True, batch_axis=None).sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_ring_attention_jit_under_mesh(rng):
    # The training path runs ring attention inside jit; make sure the
    # shard_map composition compiles and executes.
    mesh = build_mesh(jax.devices(), sp=8)
    q, k, v = _qkv(rng, b=2, h=1, t=128, d=16)

    @jax.jit
    def f(q, k, v):
        return sequence_sharded_attention(q, k, v, mesh, causal=True,
                                          batch_axis=None)

    out = f(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(rng, causal):
    """All-to-all (Ulysses) SP equals full attention exactly: heads are
    re-sharded, computed whole-sequence, and re-sharded back."""
    mesh = build_mesh(jax.devices(), sp=4)
    q, k, v = _qkv(rng, b=2, h=4, t=64, d=16)  # h % sp == 0
    out = sequence_sharded_attention(q, k, v, mesh, causal=causal,
                                     batch_axis=None, mode="alltoall")
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_ulysses_with_kv_mask_matches_ring(rng):
    mesh = build_mesh(jax.devices(), sp=4)
    q, k, v = _qkv(rng, b=2, h=4, t=32, d=8)
    mask = np.ones((2, 32), bool)
    mask[0, 20:] = False
    mask[1, 7:] = False
    mask = jnp.asarray(mask)
    out_u = sequence_sharded_attention(q, k, v, mesh, batch_axis=None,
                                       kv_mask=mask, mode="alltoall")
    out_r = sequence_sharded_attention(q, k, v, mesh, batch_axis=None,
                                       kv_mask=mask, mode="ring")
    ref = naive_attention(q, k, v, kv_mask=mask)
    np.testing.assert_allclose(out_u, ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(out_u, out_r, atol=1e-5, rtol=1e-5)


def test_ulysses_grads_match_naive(rng):
    mesh = build_mesh(jax.devices(), sp=4)
    q, k, v = _qkv(rng, b=1, h=4, t=32, d=8)

    def loss_u(q, k, v):
        return sequence_sharded_attention(
            q, k, v, mesh, causal=True, batch_axis=None,
            mode="alltoall").sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads(rng):
    mesh = build_mesh(jax.devices(), sp=4)
    q, k, v = _qkv(rng, b=1, h=2, t=32, d=8)  # 2 % 4 != 0
    with pytest.raises(ValueError, match="heads"):
        sequence_sharded_attention(q, k, v, mesh, batch_axis=None,
                                   mode="alltoall")
