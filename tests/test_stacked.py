"""Compiled megabatch ensembles (r16): vmap-stacked same-family bins.

Unit layer: the congruence probe, stacked-vs-per-member numeric parity
across the zoo (f32 + int8), the dispatch-count gate (stacked mode is
STRICTLY fewer device dispatches than per-member mode for the same
burst), member-validity-mask fault isolation, in-place member restack,
and the zero-series guard for the disabled plane.

E2E layer: a real LocalPlatform packs two trials onto one worker,
registration advertises ``stacked: true``, and ``promote_trial``
surgically restacks ONE member in place — no new worker, the other
member stays resident.
"""

import time

import numpy as np
import pytest

pytest.importorskip("jax")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from rafiki_tpu.model.jax_model import (StackedMembers,  # noqa: E402
                                        stack_congruence, stack_members)
from rafiki_tpu.models.cnn import JaxCnn  # noqa: E402
from rafiki_tpu.models.feedforward import JaxFeedForward  # noqa: E402
from rafiki_tpu.models.vit import JaxViT  # noqa: E402
from rafiki_tpu.observe import metrics as obs_metrics  # noqa: E402
from rafiki_tpu.observe import wire as obs_wire  # noqa: E402
from rafiki_tpu.worker.inference import _PackedEnsemble  # noqa: E402

_SHAPES = {JaxFeedForward: (8, 8, 1), JaxCnn: (8, 8, 3),
           JaxViT: (8, 8, 1)}


def _member(cls, seed, n_classes=4, **knobs):
    """An initialized (untrained) model — serving only needs loaded
    variables, and random inits give distinct per-member outputs."""
    m = cls(**knobs)
    shape = _SHAPES[cls]
    m._ensure_module(n_classes, shape)
    extra = {k: jnp.asarray(v)
             for k, v in m.extra_apply_inputs().items()}
    variables = m._module.init(jax.random.key(seed),
                               jnp.zeros((1, *shape)), train=False,
                               **extra)
    m._variables = jax.tree.map(lambda a: np.asarray(a), variables)
    m._meta = {"n_classes": n_classes, "image_shape": list(shape)}
    return m


def _queries(shape, n=5, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.random((n, *shape)) * 255).astype(np.uint8)


def _stacked_rows(stacked, q, member):
    bucket = stacked.predict_bucket(q.shape[0], q.dtype)
    buf = np.zeros((bucket, *q.shape[1:]), q.dtype)
    buf[:q.shape[0]] = q
    handle = stacked.staged_submit(buf, q.shape[0])
    fins = stacked.member_finishers([handle])
    return np.asarray(fins[member]())


# --- Congruence probe -------------------------------------------------


def test_congruent_same_family_group_forms():
    ms = [_member(JaxFeedForward, s, hidden_layer_count=2,
                  hidden_layer_units=32) for s in (0, 1)]
    assert stack_congruence(ms) is None
    st = stack_members(ms)
    assert isinstance(st, StackedMembers) and st.n_members == 2


def test_different_trial_knobs_still_congruent():
    """Per-trial knobs are traced masks over one supernet — members
    with different widths/depths stack (the extras stack per member)."""
    a = _member(JaxFeedForward, 0, hidden_layer_count=1,
                hidden_layer_units=16)
    b = _member(JaxFeedForward, 1, hidden_layer_count=3,
                hidden_layer_units=128)
    assert stack_congruence([a, b]) is None


def test_incongruent_members_rejected_with_reason():
    ff = _member(JaxFeedForward, 0)
    cnn = _member(JaxCnn, 1)
    reason = stack_congruence([ff, cnn])
    assert reason is not None and "JaxCnn" in reason
    assert stack_members([ff, cnn]) is None
    # single member, unloaded member, sk-style (non-JaxModel) member
    assert stack_congruence([ff]) is not None

    class FakeSk:
        pass

    assert "not a JaxModel" in stack_congruence([ff, FakeSk()])
    other_classes = _member(JaxFeedForward, 2, n_classes=7)
    assert stack_congruence([ff, other_classes]) is not None


# --- Numeric parity across the zoo (f32 + int8) -----------------------


@pytest.mark.parametrize("cls,knob_sets", [
    (JaxFeedForward, [{"hidden_layer_count": 2,
                       "hidden_layer_units": 32},
                      {"hidden_layer_count": 1,
                       "hidden_layer_units": 16},
                      {"hidden_layer_count": 3,
                       "hidden_layer_units": 64}]),
    (JaxCnn, [{"width_16ths": 8}, {"width_16ths": 16}]),
])
@pytest.mark.parametrize("quant", [None, "int8"])
def test_stacked_vs_per_member_parity(cls, knob_sets, quant):
    """The acceptance gate: the ONE vmapped dispatch produces, per
    member, the same probabilities the member's own compiled runner
    produces — bit-close in f32, tolerance-bounded under int8 (both
    sides run the identical int8 graph, so they stay allclose)."""
    ms = [_member(cls, i, **k) for i, k in enumerate(knob_sets)]
    if quant:
        for m in ms:
            m.enable_serving_quant(quant)
    st = stack_members(ms)
    assert st is not None
    q = _queries(_SHAPES[cls])
    # f32 sides run one identical graph (vmapped vs not): tight. The
    # int8 side's dynamic per-row activation rounding may flip a unit
    # at a rounding boundary under vmap reassociation: int8 envelope.
    tol = dict(rtol=1e-3, atol=2e-2 if quant else 1e-4)
    for i, m in enumerate(ms):
        ref = np.asarray(m.predict_proba(q))
        got = _stacked_rows(st, q, i)
        np.testing.assert_allclose(got, ref, **tol)


def test_vit_stacked_parity_and_int8_accuracy():
    """The transformer zoo: stacked ViT members match their own
    runners, and the dequant-free int8 path (quantized_encoder_block)
    stays within the int8 accuracy envelope of f32."""
    ms = [_member(JaxViT, s, depth=2) for s in (0, 1)]
    q = _queries(_SHAPES[JaxViT], n=3)
    refs = [np.asarray(m.predict_proba(q)) for m in ms]
    st = stack_members(ms)
    assert st is not None
    for i in range(2):
        np.testing.assert_allclose(_stacked_rows(st, q, i), refs[i],
                                   rtol=1e-3, atol=1e-4)
    report = ms[0].enable_serving_quant("int8")
    # patchify conv (4-D) + per-block QKV/proj/FFN + head all int8
    assert report["n_int8"] >= 1 + 4 * 2 + 1
    p_q = np.asarray(ms[0].predict_proba(q))
    assert np.abs(p_q - refs[0]).max() < 0.05
    ms[0].enable_serving_quant("")


def test_cnn_int8_close_to_f32():
    """The conv zoo's dequant-free path (dynamic_int8_conv): int8
    serving stays within tolerance of f32 — the model-level face of
    the bench accuracy-delta gate."""
    m = _member(JaxCnn, 0, width_16ths=8)
    q = _queries(_SHAPES[JaxCnn])
    p32 = np.asarray(m.predict_proba(q))
    report = m.enable_serving_quant("int8")
    assert report["n_int8"] == 8  # 6 stage convs + 2 head denses
    p_q = np.asarray(m.predict_proba(q))
    assert np.abs(p32 - p_q).max() < 0.05
    assert (p32.argmax(-1) == p_q.argmax(-1)).all()


# --- Dispatch counting (the strictly-lower gate) ----------------------


def _count_dispatches(monkeypatch, ensemble, q):
    from rafiki_tpu.model import jax_model as jm

    calls = {"member": 0, "stacked": 0}
    orig_member = jm.JaxModel._dispatch_bucket
    orig_stacked = jm.StackedMembers._dispatch

    def member_spy(self, chunk, n):
        calls["member"] += 1
        return orig_member(self, chunk, n)

    def stacked_spy(self, chunk):
        calls["stacked"] += 1
        return orig_stacked(self, chunk)

    monkeypatch.setattr(jm.JaxModel, "_dispatch_bucket", member_spy)
    monkeypatch.setattr(jm.StackedMembers, "_dispatch", stacked_spy)
    bucket = ensemble.predict_bucket(q.shape[0], q.dtype)
    buf = np.zeros((bucket, *q.shape[1:]), q.dtype)
    buf[:q.shape[0]] = q
    preds = ensemble.predict_staged_submit(buf, q.shape[0])()
    monkeypatch.undo()
    return calls, preds


def test_stacked_burst_is_one_dispatch_per_member_is_n(monkeypatch):
    """The unit-level regression gate behind the ISSUE acceptance:
    the SAME burst costs len(members) device dispatches per-member
    and exactly ONE stacked — strictly lower for every real
    ensemble."""
    ms = [_member(JaxFeedForward, s) for s in (0, 1, 2)]
    q = _queries(_SHAPES[JaxFeedForward])
    permember = _PackedEnsemble(list(ms))
    calls_pm, preds_pm = _count_dispatches(monkeypatch, permember, q)
    assert calls_pm == {"member": 3, "stacked": 0}
    stacked = _PackedEnsemble(list(ms), stacked=stack_members(ms))
    calls_st, preds_st = _count_dispatches(monkeypatch, stacked, q)
    assert calls_st == {"member": 0, "stacked": 1}
    assert calls_st["stacked"] < calls_pm["member"]
    # ... and the served (pre-averaged) predictions agree.
    np.testing.assert_allclose(np.asarray(preds_st),
                               np.asarray(preds_pm),
                               rtol=1e-4, atol=1e-5)


def test_incongruent_bin_serves_per_member(monkeypatch):
    """The fallback contract: a bin the probe rejects serves exactly
    as before — per-member dispatches, correct ensemble output."""
    # same input shape, different head widths: truly incongruent
    ms = [_member(JaxFeedForward, 0),
          _member(JaxFeedForward, 1, n_classes=7)]
    assert stack_members(ms) is None
    ens = _PackedEnsemble(list(ms), stacked=stack_members(ms))
    q = _queries(_SHAPES[JaxFeedForward])
    calls, preds = _count_dispatches(monkeypatch, ens, q)
    assert calls == {"member": 2, "stacked": 0}
    assert len(preds) == q.shape[0]
    # mismatched vote widths ride a __members__ envelope, per member
    assert all("__members__" in p for p in preds)


# --- Member-validity mask (fault isolation) ---------------------------


def test_member_mask_drops_only_the_invalid_vote():
    ms = [_member(JaxFeedForward, s) for s in (0, 1, 2)]
    st = stack_members(ms)
    ens = _PackedEnsemble(list(ms), stacked=st)
    q = _queries(_SHAPES[JaxFeedForward])
    bucket = ens.predict_bucket(q.shape[0], q.dtype)
    buf = np.zeros((bucket, *q.shape[1:]), q.dtype)
    buf[:q.shape[0]] = q
    st.valid[1] = False
    preds = ens.predict_staged_submit(buf, q.shape[0])()
    assert ens.last_weight == 2
    refs = [np.asarray(m.predict_proba(q)) for m in ms]
    want = (refs[0] + refs[2]) / 2.0
    np.testing.assert_allclose(np.asarray(preds), want, rtol=1e-4,
                               atol=1e-5)
    st.valid[1] = True
    preds = ens.predict_staged_submit(buf, q.shape[0])()
    assert ens.last_weight == 3


# --- In-place restack -------------------------------------------------


def test_restack_swaps_one_member_others_stay_resident():
    ms = [_member(JaxFeedForward, s) for s in (0, 1)]
    st = stack_members(ms)
    q = _queries(_SHAPES[JaxFeedForward])
    ref0 = _stacked_rows(st, q, 0)
    runner_keys = set(st._runner_cache)
    assert runner_keys  # the parity fetch compiled a runner
    incoming = _member(JaxFeedForward, 9, hidden_layer_count=1,
                       hidden_layer_units=16)
    st.update_member(1, incoming)
    assert st.valid == [True, True]
    # no recompile: the runner cache still holds the same executables
    assert set(st._runner_cache) == runner_keys
    got1 = _stacked_rows(st, q, 1)
    np.testing.assert_allclose(
        got1, np.asarray(incoming.predict_proba(q)), rtol=1e-4,
        atol=1e-5)
    # member 0 untouched by the swap
    np.testing.assert_allclose(_stacked_rows(st, q, 0), ref0,
                               rtol=1e-6, atol=1e-7)


def test_restack_rejects_incongruent_member_before_touching_state():
    ms = [_member(JaxFeedForward, s) for s in (0, 1)]
    st = stack_members(ms)
    bad = _member(JaxFeedForward, 5, n_classes=7)
    with pytest.raises(ValueError, match="not congruent"):
        st.update_member(1, bad)
    assert st.valid == [True, True]  # nothing was masked
    q = _queries(_SHAPES[JaxFeedForward])
    np.testing.assert_allclose(
        _stacked_rows(st, q, 1),
        np.asarray(ms[1].predict_proba(q)), rtol=1e-4, atol=1e-5)


# --- Metric gating ----------------------------------------------------


@pytest.fixture()
def fresh_registry(monkeypatch):
    reg = obs_metrics.MetricsRegistry()
    monkeypatch.setattr(obs_metrics, "_registry", reg)
    obs_wire.reset_for_tests()
    yield reg
    obs_wire.reset_for_tests()


_STACKED_METRICS = ("rafiki_tpu_serving_stacked_dispatch_total",
                    "rafiki_tpu_serving_dispatches_per_query_ratio")


def test_stacked_off_zero_series(fresh_registry, monkeypatch):
    """RAFIKI_TPU_SERVING_STACKED=off ⇒ per-member serving and NO
    stacked series at all (the bench A/B's off-side assertion)."""
    monkeypatch.setenv(obs_wire.STACKED_ENV, "off")
    obs_wire.reset_for_tests()
    assert not obs_wire.stacked_mode()
    ms = [_member(JaxFeedForward, s) for s in (0, 1)]
    ens = _PackedEnsemble(list(ms))  # knob off: no group ever forms
    q = _queries(_SHAPES[JaxFeedForward])
    ens.predict_submit([q[i] for i in range(q.shape[0])])()
    for name in _STACKED_METRICS:
        assert fresh_registry.find(name) is None, name


def test_stacked_on_counts_dispatches(fresh_registry, monkeypatch):
    monkeypatch.setenv(obs_wire.STACKED_ENV, "on")
    obs_wire.reset_for_tests()
    ms = [_member(JaxFeedForward, s) for s in (0, 1)]
    ens = _PackedEnsemble(list(ms), stacked=stack_members(ms))
    q = _queries(_SHAPES[JaxFeedForward])
    bucket = ens.predict_bucket(q.shape[0], q.dtype)
    buf = np.zeros((bucket, *q.shape[1:]), q.dtype)
    buf[:q.shape[0]] = q
    ens.predict_staged_submit(buf, q.shape[0])()
    c = fresh_registry.find(_STACKED_METRICS[0])
    assert c is not None and c.value(mode="stacked") == 1
    g = fresh_registry.find(_STACKED_METRICS[1])
    assert g is not None and 0 < g.value() <= 1.0 / q.shape[0] + 1e-9
    # a masked-out group falls back per-member and counts it
    ens.stacked.valid = [False, False]
    ens.predict_staged_submit(buf, q.shape[0])()
    assert c.value(mode="fallback") == 2


def test_unknown_stacked_spelling_fails_safe_off(monkeypatch):
    monkeypatch.setenv(obs_wire.STACKED_ENV, "onn")
    assert obs_wire.stacked_mode() is False
    monkeypatch.setenv(obs_wire.STACKED_ENV, "on")
    assert obs_wire.stacked_mode() is True


# --- E2E: packed deploy advertises stacked, promote restacks ----------


def test_e2e_packed_bin_stacked_promote_restack(tmp_path,
                                                synth_image_data):
    import requests

    from rafiki_tpu.cache import Cache, encode_payload
    from rafiki_tpu.constants import (BudgetOption, TaskType,
                                      UserType)
    from rafiki_tpu.model import load_image_dataset
    from rafiki_tpu.platform import LocalPlatform

    platform = LocalPlatform(workdir=str(tmp_path / "plat"),
                             supervise_interval=0)
    try:
        train_path, val_path = synth_image_data
        dev = platform.admin.create_user("st@x.c", "pw",
                                         UserType.MODEL_DEVELOPER)
        model = platform.admin.create_model(
            dev["id"], "ff-st", TaskType.IMAGE_CLASSIFICATION,
            "rafiki_tpu.models.feedforward:JaxFeedForward")
        job = platform.admin.create_train_job(
            dev["id"], "ff-st", TaskType.IMAGE_CLASSIFICATION,
            [model["id"]], {BudgetOption.MODEL_TRIAL_COUNT: 3},
            train_path, val_path)
        assert platform.admin.wait_until_train_job_done(job["id"],
                                                        timeout=600)
        best = platform.admin.get_best_trials(job["id"], max_count=3)
        assert len(best) == 3
        # One worker owning the node's whole slice packs both trials
        # (the compiled-megabatch deploy shape).
        inf = platform.admin.create_inference_job(
            dev["id"], job["id"], max_models=2,
            chips_per_worker=platform.services.allocator.n_chips)
        cache = Cache(platform.bus)
        deadline = time.time() + 120
        while not cache.running_workers(inf["id"]) and \
                time.time() < deadline:
            time.sleep(0.2)
        info = cache.running_worker_info(inf["id"])
        assert len(info) == 1, "expected ONE packed worker"
        (worker_id, reg), = info.items()
        served = set(str(reg["trial_id"]).split(","))
        assert served == {best[0]["id"], best[1]["id"]}
        assert reg.get("stacked") is True

        host = platform.admin.get_inference_job(
            inf["id"])["predictor_host"]
        ds = load_image_dataset(val_path)
        q = encode_payload(ds.images[0])

        def predict():
            r = requests.post(f"http://{host}/predict",
                              json={"query": q}, timeout=180)
            assert r.status_code == 200, r.text
            return r.json()["prediction"]

        assert "error" not in str(predict())[:40]

        # Surgical promote: replace ONE member of the packed bin.
        incoming, outgoing = best[2], best[1]
        res = platform.admin.promote_trial(
            inf["id"], incoming["id"],
            replace_trial_id=outgoing["id"])
        assert res["restacked_service_ids"] == [worker_id]
        assert res["new_service_id"] is None  # no launch: in-place
        assert res["stopped_service_ids"] == []
        info = cache.running_worker_info(inf["id"])
        assert set(info) == {worker_id}, "the SAME worker serves on"
        served = set(str(info[worker_id]["trial_id"]).split(","))
        assert served == {best[0]["id"], incoming["id"]}
        # meta mapping row followed the bin
        rows = platform.services.active_inference_workers(inf["id"])
        assert {r["trial_id"] for r in rows} == \
            {str(info[worker_id]["trial_id"])}
        assert "error" not in str(predict())[:40]

        # promoting an already-served member is still rejected
        with pytest.raises(ValueError, match="already served"):
            platform.admin.promote_trial(
                inf["id"], incoming["id"],
                replace_trial_id=best[0]["id"])
    finally:
        platform.shutdown()
