"""JaxPosTagger (POS_TAGGING task parity, SURVEY.md §2 task types) tests."""

import numpy as np
import pytest

from rafiki_tpu.constants import TaskType
from rafiki_tpu.datasets import make_synthetic_corpus_dataset
from rafiki_tpu.model import test_model_class
from rafiki_tpu.model.dataset import load_corpus_dataset
from rafiki_tpu.models import JaxPosTagger

MAX_LEN = 64
KNOBS = {"embed_dim": 32, "hidden": 32, "learning_rate": 5e-3,
         "batch_size": 32, "max_epochs": 6, "max_len": MAX_LEN,
         "vocab_size": 16384}


@pytest.fixture(scope="module")
def synth_corpus_data(tmp_path_factory):
    out = tmp_path_factory.mktemp("corpus")
    return make_synthetic_corpus_dataset(str(out), n_train=192, n_val=48,
                                         vocab=80, n_tags=5, max_len=10)


@pytest.mark.slow
def test_pos_tagger_end_to_end(synth_corpus_data):
    train_path, val_path = synth_corpus_data
    ds = load_corpus_dataset(val_path)
    queries = ds.sentences[:3]
    result = test_model_class(
        JaxPosTagger, TaskType.POS_TAGGING, train_path, val_path,
        test_queries=queries, knobs=KNOBS)
    # 5 tags with a word->tag mapping signal; chance is 0.2.
    assert result.score > 0.5
    assert len(result.predictions) == 3
    for q, pred in zip(queries, result.predictions):
        assert len(pred) == min(len(q), MAX_LEN)
        for dist in pred:  # per-token tag-probability distribution
            assert len(dist) == 5
            assert abs(sum(dist) - 1.0) < 1e-3


@pytest.mark.slow
def test_pos_tagger_params_roundtrip(synth_corpus_data):
    train_path, val_path = synth_corpus_data
    m = JaxPosTagger(**JaxPosTagger.validate_knobs(
        {**KNOBS, "max_epochs": 3}))
    m.train(train_path)
    score = m.evaluate(val_path)
    params = m.dump_parameters()
    assert all(isinstance(v, np.ndarray) for v in params.values())

    m2 = JaxPosTagger(**JaxPosTagger.validate_knobs(
        {**KNOBS, "max_epochs": 3}))
    m2.load_parameters(params)
    assert abs(m2.evaluate(val_path) - score) < 1e-6
