"""Mid-trial checkpoint/resume (SURVEY.md §5 "Checkpoint / resume")."""

import numpy as np
import pytest

from rafiki_tpu.store import CheckpointManager


def test_manager_roundtrip_and_prune(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
    assert mgr.latest_step() is None
    for step in range(4):
        mgr.save(step, {"a": np.full((3,), step, np.float32),
                        "b": np.asarray(step, np.int64)})
    assert mgr.steps() == [2, 3]  # pruned to keep_last
    step, arrs = mgr.restore()
    assert step == 3
    np.testing.assert_array_equal(arrs["a"], np.full((3,), 3, np.float32))


def test_manager_restore_specific_step(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=3)
    mgr.save(5, {"x": np.ones((2,))})
    mgr.save(7, {"x": np.zeros((2,))})
    step, arrs = mgr.restore(5)
    assert step == 5 and arrs["x"].sum() == 2


class _Crash(RuntimeError):
    pass


def _knobs():
    return {"hidden_layer_count": 1, "hidden_layer_units": 16,
            "learning_rate": 1e-3, "batch_size": 64, "max_epochs": 5}


def _epochs_logged(records):
    return [r["values"]["epoch"] for r in records
            if r.get("type") == "values" and "epoch" in r.get("values", {})]


def test_train_interrupt_and_resume(tmp_path, synth_image_data):
    """A crash mid-training resumes from the last epoch snapshot, and the
    resumed model reaches a sane score."""
    from rafiki_tpu.model.logger import logger
    from rafiki_tpu.models import JaxFeedForward

    train_path, val_path = synth_image_data
    ckpt_dir = str(tmp_path / "trial_ck")

    records = []

    def crashing_sink(rec):
        records.append(rec)
        if rec.get("type") == "values" \
                and rec.get("values", {}).get("epoch") == 2:
            raise _Crash("simulated worker death after epoch 2 logged")

    m = JaxFeedForward(**JaxFeedForward.validate_knobs(_knobs()))
    logger.set_sink(crashing_sink)
    try:
        with pytest.raises(_Crash):
            m.train(train_path, checkpoint_dir=ckpt_dir)
    finally:
        logger.set_sink(None)
    mgr = CheckpointManager(ckpt_dir)
    assert mgr.latest_step() is not None  # epochs 0/1 were snapshotted

    # A fresh instance with the same knobs + dir resumes, not restarts.
    records2 = []
    m2 = JaxFeedForward(**JaxFeedForward.validate_knobs(_knobs()))
    logger.set_sink(records2.append)
    try:
        m2.train(train_path, checkpoint_dir=ckpt_dir)
    finally:
        logger.set_sink(None)
    epochs = _epochs_logged(records2)
    assert epochs[0] > 0, f"resume re-ran epoch 0: {epochs}"
    assert epochs[-1] == 4
    assert m2.evaluate(val_path) > 0.5


def test_runner_cleans_up_checkpoints(tmp_path, synth_image_data,
                                      monkeypatch):
    """With RAFIKI_TPU_CKPT=1 the runner checkpoints during the trial and
    removes the snapshot dir once the trial completes."""
    import os

    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.models import JaxFeedForward
    from rafiki_tpu.store import MetaStore, ParamStore
    from rafiki_tpu.worker.runner import TrialRunner

    monkeypatch.setenv("RAFIKI_TPU_CKPT", "1")
    train_path, val_path = synth_image_data
    meta = MetaStore(":memory:")
    params = ParamStore(str(tmp_path / "params"))
    advisor = make_advisor(JaxFeedForward.get_knob_config(), seed=0)
    runner = TrialRunner(JaxFeedForward, advisor, train_path, val_path,
                         meta, params, sub_train_job_id="s1",
                         budget={"MODEL_TRIAL_COUNT": 1})
    rows = runner.run()
    assert rows and rows[0]["status"] == "COMPLETED"
    ckpt_root = os.path.join(params.params_dir, "ckpt")
    leftovers = os.listdir(ckpt_root) if os.path.isdir(ckpt_root) else []
    assert leftovers == [], f"checkpoints not cleaned up: {leftovers}"
