"""SkDt / SkSvm (sklearn zoo parity, SURVEY.md §2) tests."""

import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.model import load_image_dataset, test_model_class
from rafiki_tpu.models import SkDt, SkSvm


def test_skdt_end_to_end(synth_image_data):
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(3)]
    result = test_model_class(
        SkDt, TaskType.IMAGE_CLASSIFICATION, train_path, val_path,
        test_queries=queries,
        knobs={"max_depth": 8, "criterion": "gini", "min_samples_leaf": 1})
    assert result.score > 0.3  # 4-class synthetic; chance 0.25
    assert len(result.predictions) == 3
    assert all(abs(sum(p) - 1.0) < 1e-3 for p in result.predictions)


def test_sksvm_end_to_end(synth_image_data):
    train_path, val_path = synth_image_data
    ds = load_image_dataset(val_path)
    queries = [ds.images[i] for i in range(2)]
    result = test_model_class(
        SkSvm, TaskType.IMAGE_CLASSIFICATION, train_path, val_path,
        test_queries=queries,
        knobs={"C": 1.0, "kernel": "linear", "max_iter": 1000})
    assert result.score > 0.3
    assert len(result.predictions) == 2


def test_sk_params_roundtrip_across_instances(synth_image_data):
    """dump_parameters from one process-instance restores into another."""
    train_path, val_path = synth_image_data
    m = SkDt(**SkDt.validate_knobs(
        {"max_depth": 6, "criterion": "gini", "min_samples_leaf": 1}))
    m.train(train_path)
    score = m.evaluate(val_path)
    params = m.dump_parameters()
    # Params must be flat name->ndarray (ParamStore/safetensors format).
    assert all(isinstance(v, np.ndarray) for v in params.values())

    m2 = SkDt(**SkDt.validate_knobs(
        {"max_depth": 6, "criterion": "gini", "min_samples_leaf": 1}))
    m2.load_parameters(params)
    assert abs(m2.evaluate(val_path) - score) < 1e-9
