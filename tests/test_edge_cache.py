"""Predictor edge cache + confidence-tiered serving (r12).

Real components, no mocks: a MemoryBus, worker threads speaking the
cache protocol, the actual PredictorService HTTP frontend, and — for
the promotion contract — a full LocalPlatform. The invariants under
test are the ones the ISSUE names: second-touch admission, in-flight
coalescing, promotion invalidation (incl. the promote-mid-flight
race), tier short-circuit/escalate/fallback semantics, and the
disabled-mode zero-series discipline.
"""

import os
import threading
import time

import pytest
import requests

from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.cache import Cache, encode_payload
from rafiki_tpu.observe import metrics as obs_metrics
from rafiki_tpu.predictor import EdgeCache, Predictor, query_key
from rafiki_tpu.predictor.app import PredictorService
from rafiki_tpu.worker.inference import prediction_confidence

CACHE_FAMILIES = ("rafiki_tpu_serving_cache_total",
                  "rafiki_tpu_serving_cache_bytes",
                  "rafiki_tpu_serving_tier_total",
                  "rafiki_tpu_serving_chip_seconds_avoided_total")


class ConfWorker:
    """Worker stand-in replying a fixed probability vector per query,
    with a controllable per-query confidence (None = a model that
    exposes no probabilities) and a registration score (None = a
    pre-score worker)."""

    def __init__(self, bus, worker_id, job_id="job", trial_id="t1",
                 vector=(0.8, 0.2), confidence=0.5, score=0.9,
                 delay=0.0):
        self.cache = Cache(bus)
        self.worker_id = worker_id
        self.vector = list(vector)
        self.confidence = confidence
        self.delay = delay
        self.served_batches = 0
        self.served_queries = 0
        self.stop_flag = threading.Event()
        info = {"trial_id": trial_id}
        if score is not None:
            info["score"] = score
        self.cache.register_worker(job_id, worker_id, info=info)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self.stop_flag.is_set():
            items = self.cache.pop_queries(self.worker_id, timeout=0.1)
            for it in items:
                if self.delay:
                    time.sleep(self.delay)
                n = len(it["queries"])
                self.served_batches += 1
                self.served_queries += n
                self.cache.send_prediction_batch(
                    it["batch_id"], self.worker_id,
                    [list(self.vector) for _ in range(n)],
                    shard=it.get("shard"),
                    confidence=[self.confidence] * n,
                    compute_s=0.004 * n)

    def stop(self):
        self.stop_flag.set()
        self._thread.join(timeout=5)


@pytest.fixture()
def bus():
    return MemoryBus()


def _service(bus, **kw):
    svc = PredictorService("svc", "job", meta=None, bus=bus,
                           host="127.0.0.1", **kw)
    svc.predictor.worker_wait_timeout = 5.0
    svc.predictor.gather_timeout = 5.0
    if svc.batcher is not None:
        svc.batcher.start()
    svc._http.start()
    return svc


def _teardown(svc):
    svc._http.stop()
    if svc.batcher is not None:
        svc.batcher.stop()
    svc.stats.close()
    svc.predictor.close()
    if svc.edge_cache is not None:
        svc.edge_cache.close()


def _series_for(service):
    """All cache/tier samples labeled with one frontend's service id."""
    out = []
    for name in CACHE_FAMILIES:
        m = obs_metrics.registry().find(name)
        if m is None:
            continue
        out.extend((name, labels) for labels, _ in m.samples()
                   if labels.get("service") == service)
    return out


# --- EdgeCache unit semantics ----------------------------------------

def test_query_key_is_content_addressed():
    import numpy as np

    a = encode_payload(np.arange(12, dtype=np.uint8).reshape(3, 4))
    b = encode_payload(np.arange(12, dtype=np.uint8).reshape(3, 4))
    c = encode_payload(np.zeros((3, 4), dtype=np.uint8))
    assert query_key(a) == query_key(b)
    assert query_key(a) != query_key(c)


def test_second_touch_admission_and_hits():
    c = EdgeCache(1 << 20, ttl_s=60, admit_after=2, service="u1")
    try:
        kind, _ = c.begin("k")
        assert kind == "lead"
        c.resolve("k", "v", c.epoch)  # first miss: NOT admitted
        kind, _ = c.begin("k")
        assert kind == "lead", "first-touch insert must not be cached"
        c.resolve("k", "v", c.epoch)  # second miss: admitted
        kind, value = c.begin("k")
        assert (kind, value) == ("hit", "v")
        ev = c.info()["events"]
        assert ev["miss"] == 2 and ev["hit"] == 1
    finally:
        c.close()


def test_first_touch_mode_and_ttl_expiry():
    c = EdgeCache(1 << 20, ttl_s=0.15, admit_after=1, service="u2")
    try:
        assert c.begin("k")[0] == "lead"
        c.resolve("k", "v", c.epoch)
        assert c.begin("k")[0] == "hit"
        time.sleep(0.2)
        kind, _ = c.begin("k")
        assert kind == "lead", "TTL-expired entry served stale"
    finally:
        c.close()


def test_byte_budget_lru_eviction():
    c = EdgeCache(220, ttl_s=60, admit_after=1, service="u3")
    try:
        for i in range(4):
            key = f"k{i}"
            assert c.begin(key)[0] == "lead"
            c.resolve(key, "x" * 60, c.epoch)  # ~66 bytes JSON each
        info = c.info()
        assert info["bytes"] <= 220
        assert info["events"]["evict"] >= 1
        # Newest entries survived; the oldest was evicted.
        assert c.begin("k3")[0] == "hit"
        assert c.begin("k0")[0] == "lead"
    finally:
        c.close()


def test_promote_midflight_race_unit():
    """The ISSUE's race, at the cache contract level: a promotion
    landing while a leader's scatter is in flight must (a) hand the
    already-coalesced waiter the pre-promotion answer, (b) DROP the
    leader's stale insert, so (c) the next request misses."""
    c = EdgeCache(1 << 20, ttl_s=60, admit_after=1, service="u4")
    try:
        kind, lead = c.begin("k")
        assert kind == "lead"
        epoch0 = c.epoch
        kind, flight = c.begin("k")
        assert kind == "wait"  # coalesced waiter attached pre-promotion
        got = {}
        waiter = threading.Thread(
            target=lambda: got.setdefault("v", flight.wait(5)))
        waiter.start()
        new_epoch = c.invalidate()  # the promotion lands mid-flight
        assert new_epoch == epoch0 + 1
        c.resolve("k", "old-ensemble", epoch0, flight=lead)
        waiter.join(timeout=5)
        assert got["v"] == "old-ensemble", \
            "in-flight coalesced waiter must get the pre-promotion " \
            "answer"
        assert c.begin("k")[0] == "lead", \
            "post-promotion request served a pre-promotion entry"
        assert c.info()["events"]["invalidate"] == 1
    finally:
        c.close()


def test_post_promotion_request_never_joins_stale_flight():
    """Review finding (r12): after invalidate() a NEW request must not
    coalesce onto a pre-promotion leader's still-running flight — it
    becomes a fresh leader; the stale leader's late resolve completes
    only ITS OWN waiters and neither inserts nor tears down the fresh
    leader's slot."""
    c = EdgeCache(1 << 20, ttl_s=60, admit_after=1, service="u7")
    try:
        kind, stale_lead = c.begin("k")
        assert kind == "lead"
        epoch0 = c.epoch
        c.invalidate()  # the promotion completes; old scatter in flight
        kind, fresh_lead = c.begin("k")
        assert kind == "lead", \
            "post-promotion request joined a pre-promotion flight"
        assert fresh_lead is not stale_lead
        # Stale leader returns late: must not displace the fresh slot.
        c.resolve("k", "old-ensemble", epoch0, flight=stale_lead)
        kind, w = c.begin("k")
        assert kind == "wait" and w is fresh_lead, \
            "stale resolve tore down the fresh leader's flight"
        c.resolve("k", "new-ensemble", c.epoch, flight=fresh_lead)
        assert c.begin("k") == ("hit", "new-ensemble")
    finally:
        c.close()


def test_failed_none_answer_is_never_cached():
    """Review finding (r12): a None ensemble answer (every shard timed
    out / every vote errored) must not poison the key for the TTL."""
    c = EdgeCache(1 << 20, ttl_s=60, admit_after=1, service="u8")
    try:
        kind, lead = c.begin("k")
        assert kind == "lead"
        c.resolve("k", None, c.epoch, flight=lead)  # transient outage
        kind, lead = c.begin("k")
        assert kind == "lead", "failure answer was served from cache"
        c.resolve("k", [0.9, 0.1], c.epoch, flight=lead)
        assert c.begin("k") == ("hit", [0.9, 0.1])
    finally:
        c.close()


def test_vector_change_invalidates():
    c = EdgeCache(1 << 20, ttl_s=60, admit_after=1, service="u5")
    try:
        c.note_vector(("t1", "t2"))
        assert c.begin("k")[0] == "lead"
        c.resolve("k", "v", c.epoch)
        c.note_vector(("t1", "t2"))  # unchanged: no-op
        assert c.begin("k")[0] == "hit"
        c.note_vector(("t2", "t3"))  # promotion observed via registry
        assert c.begin("k")[0] == "lead"
    finally:
        c.close()


def test_leader_failure_propagates_to_waiters():
    c = EdgeCache(1 << 20, ttl_s=60, admit_after=1, service="u6")
    try:
        assert c.begin("k")[0] == "lead"
        kind, flight = c.begin("k")
        assert kind == "wait"
        c.fail("k", RuntimeError("scatter blew up"))
        with pytest.raises(RuntimeError, match="scatter blew up"):
            flight.wait(5)
        # The key is retryable afterwards.
        assert c.begin("k")[0] == "lead"
    finally:
        c.close()


# --- Service-level cache behavior ------------------------------------

def test_service_cache_serves_repeats_without_scatter(bus):
    worker = ConfWorker(bus, "w1")
    svc = _service(bus, cache_bytes=1 << 20, cache_admit_after=2)
    url = f"http://127.0.0.1:{svc.port}/predict"
    q = encode_payload([1.0, 2.0])
    try:
        for _ in range(2):  # two misses: second-touch admits
            r = requests.post(url, json={"query": q}, timeout=30)
            r.raise_for_status()
        served_before = worker.served_queries
        r = requests.post(url, json={"query": q}, timeout=30)
        r.raise_for_status()
        assert r.json()["prediction"] == [0.8, 0.2]
        assert worker.served_queries == served_before, \
            "cache hit still scattered to a worker"
        ev = svc.edge_cache.info()["events"]
        assert ev["hit"] == 1 and ev["miss"] == 2
    finally:
        _teardown(svc)
        worker.stop()


def test_service_cache_coalesces_concurrent_identical(bus):
    worker = ConfWorker(bus, "w1", delay=0.3)
    svc = _service(bus, cache_bytes=1 << 20, cache_admit_after=1)
    url = f"http://127.0.0.1:{svc.port}/predict"
    q = encode_payload([3.0, 4.0])
    results, errors = [], []

    def client():
        try:
            r = requests.post(url, json={"query": q}, timeout=30)
            r.raise_for_status()
            results.append(r.json()["prediction"])
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(6)]
    try:
        [t.start() for t in threads]
        [t.join(timeout=30) for t in threads]
        assert not errors, errors
        assert results == [[0.8, 0.2]] * 6
        # ONE scatter computed all six: leader missed, the rest
        # coalesced onto its flight.
        assert worker.served_queries == 1, \
            f"coalescing failed: worker saw {worker.served_queries}"
        ev = svc.edge_cache.info()["events"]
        assert ev["miss"] == 1 and ev["coalesce"] == 5
    finally:
        _teardown(svc)
        worker.stop()


def test_mixed_request_partial_hits(bus):
    """One request mixing cached and novel queries dispatches ONLY the
    novel ones and reassembles results in request order."""
    worker = ConfWorker(bus, "w1")
    svc = _service(bus, cache_bytes=1 << 20, cache_admit_after=1)
    url = f"http://127.0.0.1:{svc.port}/predict"
    qa, qb = encode_payload([1.0]), encode_payload([2.0])
    try:
        requests.post(url, json={"query": qa}, timeout=30
                      ).raise_for_status()
        served_before = worker.served_queries
        r = requests.post(url, json={"queries": [qa, qb, qa]},
                          timeout=30)
        r.raise_for_status()
        assert r.json()["predictions"] == [[0.8, 0.2]] * 3
        assert worker.served_queries == served_before + 1, \
            "hit/duplicate queries were re-scattered"
        ev = svc.edge_cache.info()["events"]
        assert ev["hit"] >= 1
    finally:
        _teardown(svc)
        worker.stop()


def test_cache_invalidate_route_and_stats(bus):
    worker = ConfWorker(bus, "w1")
    svc = _service(bus, cache_bytes=1 << 20, cache_admit_after=1)
    base = f"http://127.0.0.1:{svc.port}"
    q = encode_payload([5.0])
    try:
        requests.post(f"{base}/predict", json={"query": q}, timeout=30
                      ).raise_for_status()
        r = requests.post(f"{base}/cache/invalidate", json={},
                          timeout=30)
        assert r.json() == {"enabled": True, "epoch": 1}
        # Post-invalidation: the same query misses again.
        requests.post(f"{base}/predict", json={"query": q}, timeout=30
                      ).raise_for_status()
        ev = svc.edge_cache.info()["events"]
        assert ev["miss"] == 2 and ev.get("hit", 0) == 0
        assert ev["invalidate"] == 1
        stats = requests.get(f"{base}/stats", timeout=30).json()
        assert stats["cache"]["epoch"] == 1
    finally:
        _teardown(svc)
        worker.stop()


def test_disabled_cache_and_tier_register_zero_series(bus,
                                                      monkeypatch):
    """The r11 discipline: with the cache and tier off (the defaults),
    the serving path must register NO cache/tier series — one attribute
    check, byte-identical metrics output."""
    for field in ("SERVING_CACHE_BYTES", "SERVING_CACHE_TTL_S",
                  "SERVING_CACHE_ADMIT_AFTER",
                  "SERVING_TIER_THRESHOLD"):
        monkeypatch.delenv(f"RAFIKI_TPU_{field}", raising=False)
    worker = ConfWorker(bus, "w1")
    svc = _service(bus)
    url = f"http://127.0.0.1:{svc.port}/predict"
    try:
        assert svc.edge_cache is None
        assert svc.predictor.tier_threshold is None
        r = requests.post(url, json={"query": encode_payload([1.0])},
                          timeout=30)
        r.raise_for_status()
        # The invalidate route answers honestly instead of 404ing
        # (promotion against a cacheless frontend is a no-op).
        r = requests.post(f"http://127.0.0.1:{svc.port}"
                          f"/cache/invalidate", json={}, timeout=30)
        assert r.json() == {"enabled": False}
        assert _series_for(svc.stats.service) == []
    finally:
        _teardown(svc)
        worker.stop()


def test_cache_series_removed_on_stop(bus):
    worker = ConfWorker(bus, "w1")
    svc = _service(bus, cache_bytes=1 << 20, cache_admit_after=1,
                   tier_threshold=0.3)
    url = f"http://127.0.0.1:{svc.port}/predict"
    try:
        requests.post(url, json={"query": encode_payload([2.0])},
                      timeout=30).raise_for_status()
        assert _series_for(svc.stats.service)
    finally:
        _teardown(svc)
        worker.stop()
    assert _series_for(svc.stats.service) == [], \
        "stop() leaked cache/tier series"


# --- Confidence-tiered serving ---------------------------------------

def _tiered_predictor(bus, threshold=0.3):
    p = Predictor("job", bus, gather_timeout=5.0,
                  worker_wait_timeout=5.0, tier_threshold=threshold)
    return p


def test_tier_short_circuits_confident_queries(bus):
    a = ConfWorker(bus, "wa", trial_id="t-best", vector=(0.9, 0.1),
                   confidence=0.8, score=0.9)
    b = ConfWorker(bus, "wb", trial_id="t-other", vector=(0.4, 0.6),
                   confidence=0.8, score=0.5)
    p = _tiered_predictor(bus)
    try:
        out = p.predict([[1.0], [2.0]])
        # Confident: answered by the best bin ALONE (its single vote).
        assert out == [[0.9, 0.1], [0.9, 0.1]]
        assert a.served_queries == 2
        assert b.served_queries == 0, \
            "confident queries still fanned out to the full ensemble"
        mix = {labels["outcome"]: int(v) for labels, v
               in p._m_tier.samples()
               if labels.get("service") == p.service}
        assert mix == {"short_circuit": 2}
    finally:
        p.close()
        a.stop()
        b.stop()


def test_tier_escalates_low_confidence_to_full_vote(bus):
    a = ConfWorker(bus, "wa", trial_id="t-best", vector=(0.6, 0.4),
                   confidence=0.05, score=0.9)
    b = ConfWorker(bus, "wb", trial_id="t-other", vector=(0.2, 0.8),
                   confidence=0.9, score=0.5)
    p = _tiered_predictor(bus, threshold=0.3)
    try:
        out = p.predict([[1.0]])
        # Escalated: one vote per bin, mean of both vectors.
        assert out == [[pytest.approx(0.4), pytest.approx(0.6)]]
        assert a.served_queries == 1 and b.served_queries == 1
        mix = {labels["outcome"]: int(v) for labels, v
               in p._m_tier.samples()
               if labels.get("service") == p.service}
        assert mix == {"escalate": 1}
    finally:
        p.close()
        a.stop()
        b.stop()


def test_tier_escalates_when_model_has_no_confidence(bus):
    """A best-bin model that exposes no probabilities (sk-style) must
    never short-circuit: None confidence always escalates."""
    a = ConfWorker(bus, "wa", trial_id="t-best", vector=(0.9, 0.1),
                   confidence=None, score=0.9)
    b = ConfWorker(bus, "wb", trial_id="t-other", vector=(0.3, 0.7),
                   confidence=0.9, score=0.5)
    p = _tiered_predictor(bus)
    try:
        out = p.predict([[1.0]])
        assert out == [[pytest.approx(0.6), pytest.approx(0.4)]]
        assert b.served_queries == 1, "no-confidence reply " \
            "short-circuited instead of escalating"
    finally:
        p.close()
        a.stop()
        b.stop()


def test_tier_falls_back_to_full_scatter_without_scores(bus):
    """A serving worker that predates score registration makes the
    best bin unknowable: the batch fans out in full (outcome=full)."""
    a = ConfWorker(bus, "wa", trial_id="t1", vector=(0.9, 0.1),
                   confidence=0.8, score=None)
    b = ConfWorker(bus, "wb", trial_id="t2", vector=(0.5, 0.5),
                   confidence=0.8, score=0.5)
    p = _tiered_predictor(bus)
    try:
        out = p.predict([[1.0]])
        assert out == [[pytest.approx(0.7), pytest.approx(0.3)]]
        assert a.served_queries == 1 and b.served_queries == 1
        mix = {labels["outcome"]: int(v) for labels, v
               in p._m_tier.samples()
               if labels.get("service") == p.service}
        assert mix == {"full": 1}
    finally:
        p.close()
        a.stop()
        b.stop()


def test_tier_disabled_predictor_has_no_tier_metrics(bus):
    a = ConfWorker(bus, "wa", trial_id="t1", score=0.9)
    p = Predictor("job", bus, gather_timeout=5.0,
                  worker_wait_timeout=5.0)
    try:
        assert p.tier_threshold is None
        assert p._m_tier is None and p._m_avoided is None
        assert p.predict([[1.0]]) == [[0.8, 0.2]]
    finally:
        p.close()
        a.stop()


def test_prediction_confidence_margins():
    assert prediction_confidence([0.7, 0.2, 0.1]) == pytest.approx(0.5)
    assert prediction_confidence([0.5, 0.5]) == pytest.approx(0.0)
    assert prediction_confidence("label") is None
    assert prediction_confidence({"error": "x"}) is None
    assert prediction_confidence({"__members__": [1, 2]}) is None
    assert prediction_confidence([0.9]) is None  # no runner-up
    assert prediction_confidence([[0.1], [0.9]]) is None  # nested
    assert prediction_confidence(None) is None


def test_chip_seconds_avoided_accrues_from_cost_ewma(bus):
    """Workers report compute_s; the predictor's per-bin EWMA prices
    short-circuits (tier) and hits (cache)."""
    a = ConfWorker(bus, "wa", trial_id="t-best", vector=(0.9, 0.1),
                   confidence=0.8, score=0.9)
    b = ConfWorker(bus, "wb", trial_id="t-other", vector=(0.4, 0.6),
                   confidence=0.8, score=0.5)
    p = _tiered_predictor(bus, threshold=0.9)  # forces escalation
    try:
        p.predict([[1.0]])  # escalates: both bins' cost EWMAs seeded
        assert p.estimate_query_cost() == pytest.approx(0.008, rel=0.3)
        p.tier_threshold = 0.3  # now confident queries short-circuit
        p.predict([[2.0]])
        avoided = {labels["source"]: v for labels, v
                   in p._m_avoided.samples()
                   if labels.get("service") == p.service}
        # One short-circuit avoided the OTHER bin's ~4ms.
        assert avoided["tier"] == pytest.approx(0.004, rel=0.3)
    finally:
        p.close()
        a.stop()
        b.stop()


def test_cost_estimates_ignore_retired_bins_and_price_tiered_hits(bus):
    """Review findings (r12): a promoted-away bin's cost EWMA must not
    inflate the avoided counters, and with tiering ON a cache hit is
    priced as the best bin alone (a miss would have short-circuited) —
    under-report, never fabricate."""
    a = ConfWorker(bus, "wa", trial_id="t-best", vector=(0.9, 0.1),
                   confidence=0.8, score=0.9)
    b = ConfWorker(bus, "wb", trial_id="t-other", vector=(0.4, 0.6),
                   confidence=0.8, score=0.5)
    p = _tiered_predictor(bus, threshold=0.9)  # escalates: seeds both
    try:
        p.predict([[1.0]])
        # Full-ensemble cost = both live bins (~4ms each)...
        assert p.estimate_query_cost() == pytest.approx(0.008, rel=0.3)
        # ...but a HIT under tiering claims only the best bin's share.
        assert p.estimate_hit_cost() == pytest.approx(0.004, rel=0.3)
        # A retired bin (promotion churn) must price as nothing even
        # before the hysteresis prune fires.
        with p._state_lock:
            p._bin_cost["t-retired"] = 5.0
        assert p.estimate_query_cost() == pytest.approx(0.008, rel=0.3)
        assert p.estimate_hit_cost() == pytest.approx(0.004, rel=0.3)
    finally:
        p.close()
        a.stop()
        b.stop()
