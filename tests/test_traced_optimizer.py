"""traced_hyperparam_optimizer must match the classic baked recipes.

The one-executable search design swaps baked optax schedules for
normalised schedules times an opt-state hyperparameter; these tests pin
the numerics to the reference chains so the refactor can never drift.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from rafiki_tpu.models import JaxDenseNet, JaxFeedForward


def _run_steps(tx, set_hyper, params, grads_seq):
    state = tx.init(params)
    if set_hyper:
        for name, value in set_hyper.items():
            state.hyperparams[name] = jnp.asarray(value, jnp.float32)
    for g in grads_seq:
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    return params


@pytest.fixture()
def problem(rng):
    params = {"w": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal((3,)), jnp.float32)}
    grads_seq = [
        {"w": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
         "b": jnp.asarray(rng.standard_normal((3,)), jnp.float32)}
        for _ in range(7)]
    return params, grads_seq


def test_traced_adam_matches_baked(problem):
    params, grads = problem
    lr = 3.7e-3
    steps, epochs = 4, 5
    total = steps * epochs

    model = JaxFeedForward(learning_rate=lr, batch_size=32, max_epochs=epochs,
                           hidden_layer_count=1, hidden_layer_units=16)
    traced = model.create_optimizer(steps, epochs)
    got = _run_steps(traced, {"learning_rate": lr}, params, grads)

    ref_tx = optax.chain(
        optax.scale_by_adam(),
        optax.scale_by_schedule(optax.cosine_decay_schedule(
            1.0, decay_steps=total, alpha=0.01)),
        optax.scale(-lr))
    want = _run_steps(ref_tx, None, params, grads)
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=1e-6, rtol=1e-6)


def test_traced_sgdm_wd_matches_baked(problem):
    params, grads = problem
    lr, wd = 0.13, 2.3e-4
    steps, epochs = 3, 8
    total = steps * epochs

    model = JaxDenseNet(arch="densenet_tiny", growth_rate=8,
                        learning_rate=lr, batch_size=64, weight_decay=wd,
                        max_epochs=epochs, early_stop_epochs=0)
    traced = model.create_optimizer(steps, epochs)
    got = _run_steps(traced, {"learning_rate": lr, "weight_decay": wd},
                     params, grads)

    # The pre-refactor DenseNet recipe: add_decayed_weights -> SGD with
    # nesterov momentum on a warmup-cosine schedule peaking at lr.
    warmup = max(1, min(total // 20, 5 * steps))
    ref_tx = optax.chain(
        optax.add_decayed_weights(wd),
        optax.trace(decay=0.9, nesterov=True),
        optax.scale_by_schedule(optax.warmup_cosine_decay_schedule(
            init_value=0.1, peak_value=1.0, warmup_steps=warmup,
            decay_steps=total, end_value=1e-3)),
        optax.scale(-lr))
    want = _run_steps(ref_tx, None, params, grads)
    for k in params:
        np.testing.assert_allclose(got[k], want[k], atol=1e-6, rtol=1e-6)


def test_hyperparams_change_behavior_without_recompile(problem):
    """Two different lrs through ONE jitted update fn must give different
    (and correct) results — the whole point of tracing them."""
    params, grads = problem
    model = JaxFeedForward(learning_rate=1e-3, batch_size=32, max_epochs=2,
                           hidden_layer_count=1, hidden_layer_units=16)
    tx = model.create_optimizer(4, 2)

    traces = []

    @jax.jit
    def one(params, state, g):
        traces.append(1)
        updates, state = tx.update(g, state, params)
        return optax.apply_updates(params, updates), state

    outs = []
    for lr in (1e-3, 1e-2):
        state = tx.init(params)
        state.hyperparams["learning_rate"] = jnp.asarray(lr, jnp.float32)
        p, _ = one(params, state, grads[0])
        outs.append(p)
    assert len(traces) == 1  # one compile serves both lrs
    assert not np.allclose(outs[0]["w"], outs[1]["w"])