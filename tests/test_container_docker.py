"""DockerContainerManager: CLI invocations via an injected fake runner."""

import subprocess

import pytest

from rafiki_tpu.container import DockerContainerManager


class FakeDocker:
    def __init__(self):
        self.calls = []
        self.running = {}
        self.daemon_down = False

    def __call__(self, args):
        self.calls.append(args)
        if args[0] == "run":
            cid = f"cid{len(self.running)}"
            self.running[cid] = True
            return cid
        if args[0] == "rm":
            self.running.pop(args[-1], None)
            return ""
        if args[0] == "inspect":
            cid = args[-1]
            if self.daemon_down:
                raise subprocess.CalledProcessError(
                    1, ["docker"],
                    stderr="Cannot connect to the Docker daemon")
            if cid not in self.running:
                raise subprocess.CalledProcessError(
                    1, ["docker"], stderr=f"No such object: {cid}")
            return "true"
        raise AssertionError(args)


def test_service_lifecycle():
    fake = FakeDocker()
    mgr = DockerContainerManager(image="rafiki-tpu:test", runner=fake)
    cid = mgr.create_service("svc0123456789abc", {
        "RAFIKI_TPU_SERVICE_TYPE": "TRAIN", "RAFIKI_TPU_CHIPS": "0,1"})
    run = fake.calls[0]
    assert run[0] == "run" and "-d" in run
    assert "--network" in run and "host" in run
    assert "-e" in run
    assert "RAFIKI_TPU_CHIPS=0,1" in run
    assert run[-3:] == ["python", "-m", "rafiki_tpu.container.services"]
    assert "rafiki-tpu:test" in run

    assert mgr.service_alive(cid)
    mgr.destroy_service(cid)
    assert not mgr.service_alive(cid)


def test_file_backed_stores_are_mounted():
    fake = FakeDocker()
    mgr = DockerContainerManager(runner=fake, volumes=["/data:/data:ro"])
    mgr.create_service("s" * 16, {
        "RAFIKI_TPU_META_URI": "/var/rafiki/meta.db",
        "RAFIKI_TPU_PARAMS_DIR": "/var/rafiki/params"})
    run = fake.calls[0]
    # env paths stay valid inside the container: host-path = container-path
    assert "-v" in run
    assert "/var/rafiki:/var/rafiki" in run
    assert "/var/rafiki/params:/var/rafiki/params" in run
    assert "/data:/data:ro" in run

    # :memory: / URI-style stores need no mount
    fake2 = FakeDocker()
    DockerContainerManager(runner=fake2).create_service("s" * 16, {
        "RAFIKI_TPU_META_URI": ":memory:",
        "RAFIKI_TPU_BUS_URI": "tcp://host:7777"})
    assert "-v" not in fake2.calls[0]


def test_transient_daemon_failure_is_not_death():
    fake = FakeDocker()
    mgr = DockerContainerManager(runner=fake)
    cid = mgr.create_service("s" * 16, {})
    fake.daemon_down = True
    # A daemon blip must NOT read as container death (the supervisor
    # would tear down healthy services).
    assert mgr.service_alive(cid)
    fake.daemon_down = False
    assert mgr.service_alive(cid)


def test_mounts_deduped_and_relative_paths_absolutised(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    fake = FakeDocker()
    mgr = DockerContainerManager(runner=fake)
    # params dir IS the meta db's parent: one mount, not two.
    mgr.create_service("s" * 16, {
        "RAFIKI_TPU_META_URI": "/data/rafiki/meta.db",
        "RAFIKI_TPU_PARAMS_DIR": "/data/rafiki"})
    run = fake.calls[0]
    assert run.count("/data/rafiki:/data/rafiki") == 1

    # relative store paths are rewritten to abspaths in the env.
    fake2 = FakeDocker()
    DockerContainerManager(runner=fake2).create_service("s" * 16, {
        "RAFIKI_TPU_META_URI": "rafiki/meta.db",
        "RAFIKI_TPU_PARAMS_DIR": "rafiki/params"})
    run2 = fake2.calls[0]
    meta_abs = str(tmp_path / "rafiki" / "meta.db")
    params_abs = str(tmp_path / "rafiki" / "params")
    assert f"RAFIKI_TPU_META_URI={meta_abs}" in run2
    assert f"RAFIKI_TPU_PARAMS_DIR={params_abs}" in run2
    assert f"{params_abs}:{params_abs}" in run2


def test_extra_args_and_missing_container():
    fake = FakeDocker()
    mgr = DockerContainerManager(runner=fake,
                                 extra_args=["--privileged"])
    cid = mgr.create_service("s" * 16, {})
    assert "--privileged" in fake.calls[0]
    assert not mgr.service_alive("nope")
    mgr.destroy_service("nope")  # logged, no raise
