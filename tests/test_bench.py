"""bench.py record semantics — the driver-facing contract.

The driver parses bench.py's one JSON line into BENCH_r{N}.json; these
tests pin the parts a human later reads off that artifact: platform-
correct vs_baseline (a CPU value compared against a TPU baseline must
read as null, not a 9x win), per-config error records that never lose
the sweep, and a utilization probe that chains rather than swallows
whatever log sink the surrounding harness installed.
"""

import json
import subprocess
import sys

import pytest

import bench
from rafiki_tpu.model.logger import logger


def test_emit_nulls_vs_baseline_off_platform():
    # Tests run on CPU (conftest), which is not in BASELINE_PLATFORMS —
    # even a metric with a recorded baseline must read null.
    rec = bench._emit("automl_trials_per_hour", 2468.0, "u")
    assert rec["platform"] == "cpu"
    assert rec["vs_baseline"] is None


def test_emit_ratio_on_baseline_platform(monkeypatch):
    monkeypatch.setattr(bench, "BASELINE_PLATFORMS", ("cpu",))
    monkeypatch.setitem(bench.BASELINES, "cpu", {"m": 268.0})
    assert bench._emit("m", 536.0, "u")["vs_baseline"] == 2.0
    # no recorded baseline = this run establishes it
    assert bench._emit("m2", 536.0, "u")["vs_baseline"] == 1.0


def test_baselines_are_per_channel():
    # The tunnel ("axon") and the direct chip ("tpu") are different
    # measurement channels; a direct-chip value must never be compared
    # against a tunnel-recorded figure (a ~5x channel artifact).
    for metric, tunnel in bench.BASELINES["axon"].items():
        assert metric in bench.BASELINES["tpu"]


def test_emit_labels_chip_util_basis(monkeypatch):
    rec = bench._emit("m", 1.0, "u", chip_util=0.5)
    assert rec["chip_util_basis"] == "calibrated-cpu-roofline"
    monkeypatch.setattr(bench, "BASELINE_PLATFORMS", ("cpu",))
    rec = bench._emit("m", 1.0, "u", chip_util=0.5)
    assert rec["chip_util_basis"] == "spec-peak"


def test_util_probe_chains_and_restores_prior_sink():
    seen = []
    logger.set_sink(seen.append)
    try:
        with bench._UtilProbe() as probe:
            logger.log(chip_util=0.42, loss=1.0)
        assert probe.values == [0.42]
        # The pre-existing sink saw the record too...
        assert seen and seen[0]["values"]["chip_util"] == 0.42
        # ...and is back in place after the probe exits.
        logger.log(loss=0.5)
        assert len(seen) == 2
    finally:
        logger.set_sink(None)


def test_run_config_captures_systemexit_as_error_record():
    rec = bench._run_config("attention", "cpu")  # needs TPU -> SystemExit
    assert rec["metric"] == "flash_attention_tflops"
    assert rec["value"] == 0.0 and rec["vs_baseline"] is None
    assert "error" in rec and "seconds" in rec


def test_sweep_emits_one_line_with_per_config_records():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    # "attn" is a deliberate typo: unknown names must be skipped with a
    # note, not crash the sweep before its one JSON line. The subset
    # under test is deliberately CHEAP (attention errors fast on the
    # CPU fallback; analysis is a ~seconds gate run) — this test pins
    # the sweep/record CONTRACT, not any config's own measurement, and
    # the tier-1 budget cannot afford a full multitenant train here
    # (r13: the suite runs within ~2% of its timeout).
    env.update({"RAFIKI_TPU_BENCH_CONFIGS": "attn,attention,analysis",
                "RAFIKI_TPU_PROBE_TIMEOUT": "5",
                "RAFIKI_TPU_BENCH_IDLE_MAX_WAIT": "2"})
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--config", "sweep"],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["sweep"] is True
    assert set(rec["configs"]) == {"attention", "analysis"}
    assert "ignoring unknown config name(s) ['attn']" in out.stderr
    # The subprocess probes the real accelerator (the conftest CPU pin
    # applies only in-process), so assert the record CONTRACT under
    # either outcome: tunnel up -> attention measures on TPU; tunnel
    # down -> attention errors on the CPU fallback. analysis is the
    # gate config: value = NEW findings, 0 on a clean tree.
    for sub in rec["configs"].values():
        assert "seconds" in sub
        if "error" in sub:
            assert sub["value"] == 0.0 and sub["vs_baseline"] is None
    assert rec["configs"]["analysis"]["value"] == 0.0
    assert "error" not in rec["configs"]["analysis"]
    attn = rec["configs"]["attention"]
    assert ("error" in attn) == (attn["platform"] not in ("axon", "tpu"))


def test_analysis_config_records_finding_counts():
    """The static-analysis gate smoke: one record, value = NEW findings
    (0 on a clean tree), per-code counts folded in for the bench
    artifact. Runs the real CLI subprocess, like production."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--config", "analysis"],
        capture_output=True, text=True, timeout=300, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["metric"] == "analysis_new_findings"
    assert rec["value"] == 0.0 and rec["exit_code"] == 0
    assert rec["unit"] == "findings"
    assert all(k.startswith("RTA") for k in rec["counts_per_code"])
    assert set(rec["by_status"]) <= {"baselined", "waived", "new"}
    assert rec["files"] > 50 and rec["checkers"]


@pytest.mark.slow
@pytest.mark.slower
def test_sweep_heavy_configs_run_on_cpu_mesh():
    """VERDICT r3 item 6: the sweep's heavy configs (serving,
    multitenant) execute END-TO-END through the real _run_config path
    on the 8-virtual-device CPU mesh — every record parses, carries no
    error, and nulls vs_baseline (CPU is not a baseline channel).
    Before this, configs 2-7 had only ever run through the stubbed
    contract test; a wedge in their platform plumbing would surface
    only when the TPU tunnel next came up."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "RAFIKI_TPU_BENCH_CONFIGS": "serving,multitenant"})
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--config", "sweep"],
        capture_output=True, text=True, timeout=1800, env=env, cwd=repo)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    for name in ("serving", "multitenant"):
        sub = rec["configs"][name]
        assert "error" not in sub, (name, sub)
        assert sub["value"] > 0
        assert sub["platform"] == "cpu"
        assert sub["vs_baseline"] is None


def test_lm_serving_config_registered_outside_sweep():
    """lm-serving is a counter-judged gate (docs/serving.md
    "Benchmarking it"), runnable via --config but never part of the
    platform sweep — same policy as analysis/chaos/autoscale."""
    fn, metric, unit = bench._CONFIGS["lm-serving"]
    assert metric == "lm_serving_tokens_per_sec" and unit == "tokens/s"
    assert fn is bench.main_lm_serving
    assert "lm-serving" not in bench._SWEEP_ORDER
