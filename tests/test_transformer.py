"""JaxTransformerTagger: flash/ring attention sequence model end-to-end."""

import jax
import numpy as np
import pytest

from rafiki_tpu.constants import TaskType
from rafiki_tpu.datasets import make_synthetic_corpus_dataset
from rafiki_tpu.model import test_model_class
from rafiki_tpu.model.dataset import load_corpus_dataset
from rafiki_tpu.models import JaxTransformerTagger

MAX_LEN = 32
KNOBS = {"d_model": 64, "n_heads": 2, "n_layers": 2, "learning_rate": 1e-2,
         "batch_size": 32, "max_epochs": 15, "max_len": MAX_LEN,
         "dropout": 0.0, "vocab_size": 16384, "sequence_parallel": 1}


@pytest.fixture(scope="module")
def synth_corpus_data(tmp_path_factory):
    out = tmp_path_factory.mktemp("corpus")
    return make_synthetic_corpus_dataset(str(out), n_train=192, n_val=48,
                                         vocab=80, n_tags=5, max_len=10)


@pytest.mark.slow
def test_transformer_tagger_end_to_end(synth_corpus_data):
    train_path, val_path = synth_corpus_data
    ds = load_corpus_dataset(val_path)
    queries = ds.sentences[:3]
    result = test_model_class(
        JaxTransformerTagger, TaskType.POS_TAGGING, train_path, val_path,
        test_queries=queries, knobs=KNOBS)
    assert result.score > 0.5  # 5 tags; chance is 0.2
    assert len(result.predictions) == 3
    for q, pred in zip(queries, result.predictions):
        assert len(pred) == min(len(q), MAX_LEN)
        for dist in pred:
            assert len(dist) == 5
            assert abs(sum(dist) - 1.0) < 1e-3


@pytest.mark.slow
@pytest.mark.parametrize("sp_schedule", ["ring", "alltoall"])
def test_transformer_tagger_sequence_parallel(synth_corpus_data,
                                               sp_schedule):
    # sp=4 on the 8-device mesh: sequence dim sharded over either
    # context-parallel schedule (ring ppermute / Ulysses all-to-all);
    # must train and score like the sp=1 model.
    train_path, val_path = synth_corpus_data
    # sequence_parallel is a deployment knob (FixedKnob(1) in the search
    # space); operators override it at construction, bypassing the
    # advisor-facing validation.
    # Ulysses re-shards heads over sp, so it needs n_heads % sp == 0.
    knobs = dict(KNOBS, sequence_parallel=4, sp_schedule=sp_schedule,
                 n_heads=4 if sp_schedule == "alltoall"
                 else KNOBS["n_heads"])
    model = JaxTransformerTagger(**knobs)
    assert model.mesh.shape["sp"] == 4
    assert model.mesh.shape["dp"] == len(jax.devices()) // 4
    model.train(train_path)
    score = model.evaluate(val_path)
    assert score > 0.5

    # dump/load round-trip preserves behavior
    params = model.dump_parameters()
    m2 = JaxTransformerTagger(**knobs)
    m2.load_parameters(params)
    ds = load_corpus_dataset(val_path)
    p1 = model.predict(ds.sentences[:2])
    p2 = m2.predict(ds.sentences[:2])
    np.testing.assert_allclose(np.asarray(p1[0]), np.asarray(p2[0]),
                               atol=1e-5)
    model.destroy()
    m2.destroy()


@pytest.mark.slow
def test_transformer_tagger_pipeline_parallel(synth_corpus_data):
    """pp=2 on the 8-device mesh (dp=4 x pp=2): encoder blocks run as a
    GPipe pipeline; scores match the non-pipelined model and the
    dump/load round-trip preserves predictions."""
    train_path, val_path = synth_corpus_data
    knobs = dict(KNOBS, pipeline_parallel=2, dropout=0.0)
    model = JaxTransformerTagger(**knobs)
    assert model.mesh.shape["pp"] == 2
    assert model.mesh.shape["dp"] == len(jax.devices()) // 2
    model.train(train_path)
    score = model.evaluate(val_path)

    base = JaxTransformerTagger(**dict(KNOBS, dropout=0.0))
    base.train(train_path)
    assert abs(score - base.evaluate(val_path)) < 0.05

    params = model.dump_parameters()
    m2 = JaxTransformerTagger(**knobs)
    m2.load_parameters(params)
    ds = load_corpus_dataset(val_path)
    p1 = model.predict(ds.sentences[:2])
    p2 = m2.predict(ds.sentences[:2])
    np.testing.assert_allclose(np.asarray(p1[0]), np.asarray(p2[0]),
                               atol=1e-5)
    model.destroy()
    base.destroy()
    m2.destroy()


def test_pipeline_parallel_knob_validation():
    with pytest.raises(ValueError, match="divide n_layers"):
        JaxTransformerTagger(**dict(KNOBS, n_layers=3,
                                    pipeline_parallel=2)).mesh
    # pp x ep composes since r4 — the mesh builds without complaint.
    mesh = JaxTransformerTagger(**dict(KNOBS, moe_experts=4,
                                       expert_parallel=2,
                                       pipeline_parallel=2)).mesh
    assert mesh.shape["pp"] == 2 and mesh.shape["ep"] == 2


def test_pipeline_parallel_params_stored_stage_sharded(synth_corpus_data):
    """pp must scale MEMORY, not just rehearse the schedule: every
    encoder-block leaf (and its optimizer state) lives stage-stacked
    with the leading axis sharded over pp, so each chip persistently
    holds ~1/pp of the block parameters."""
    train_path, _ = synth_corpus_data
    knobs = dict(KNOBS, n_layers=2, pipeline_parallel=2, max_epochs=1)
    model = JaxTransformerTagger(**knobs)
    model.train(train_path)
    pp_tree = model._pp_split(model._variables["params"])
    from rafiki_tpu.parallel import shard_variables

    placed = shard_variables(pp_tree, model.mesh)
    for leaf in jax.tree_util.tree_leaves(placed["stages"]):
        shard = leaf.addressable_shards[0].data
        assert shard.nbytes * 2 == leaf.nbytes, \
            f"stage leaf not pp-sharded: {shard.shape} of {leaf.shape}"
    model.destroy()


def test_pipeline_parallel_with_dropout_trains(synth_corpus_data):
    """Dropout inside the pipeline (per-tick rng folding) must train to
    the same quality as the non-pipelined model with dropout."""
    train_path, val_path = synth_corpus_data
    model = JaxTransformerTagger(**dict(KNOBS, pipeline_parallel=2,
                                        dropout=0.2))
    model.train(train_path)
    score = model.evaluate(val_path)
    base = JaxTransformerTagger(**dict(KNOBS, dropout=0.2))
    base.train(train_path)
    assert abs(score - base.evaluate(val_path)) < 0.07, \
        (score, base.evaluate(val_path))
    model.destroy()
    base.destroy()


@pytest.mark.slow
def test_pipeline_parallel_composes_with_sequence_parallel(
        synth_corpus_data):
    """pp=2 x sp=2 on one mesh: ring attention runs over the sp axis of
    the same shard_map that pipelines stages over pp; scores match the
    plain model."""
    train_path, val_path = synth_corpus_data
    knobs = dict(KNOBS, pipeline_parallel=2, sequence_parallel=2,
                 dropout=0.0)
    model = JaxTransformerTagger(**knobs)
    assert model.mesh.shape["pp"] == 2
    assert model.mesh.shape["sp"] == 2
    assert model.mesh.shape["dp"] == len(jax.devices()) // 4
    model.train(train_path)
    score = model.evaluate(val_path)
    base = JaxTransformerTagger(**dict(KNOBS, dropout=0.0))
    base.train(train_path)
    assert abs(score - base.evaluate(val_path)) < 0.05
    model.destroy()
    base.destroy()


@pytest.mark.slow
def test_checkpoint_resume_step_identical(synth_corpus_data, tmp_path):
    """The tagger honors the loop_ckpt contract with a NONZERO dropout:
    a run checkpointed at epoch 3 and resumed to 6 must land on exactly
    the params of an uninterrupted 6-epoch run — the resumed step_i
    keeps the dropout fold_in stream identical."""
    train_path, _ = synth_corpus_data
    knobs = dict(KNOBS, dropout=0.1)
    ck = str(tmp_path / "ck")

    leg1 = JaxTransformerTagger(**JaxTransformerTagger.validate_knobs(
        dict(knobs, max_epochs=3)))
    leg1.train(train_path, checkpoint_dir=ck, checkpoint_final_epoch=True,
               schedule_total_epochs=6)
    leg2 = JaxTransformerTagger(**JaxTransformerTagger.validate_knobs(
        dict(knobs, max_epochs=6)))
    leg2.train(train_path, checkpoint_dir=ck, checkpoint_final_epoch=True,
               schedule_total_epochs=6)

    ref = JaxTransformerTagger(**JaxTransformerTagger.validate_knobs(
        dict(knobs, max_epochs=6)))
    ref.train(train_path, schedule_total_epochs=6)

    resumed = jax.tree.leaves(leg2.dump_parameters())
    wanted = jax.tree.leaves(ref.dump_parameters())
    assert len(resumed) == len(wanted)
    for a, b in zip(resumed, wanted):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for m in (leg1, leg2, ref):
        m.destroy()

@pytest.mark.slow
def test_pipeline_parallel_composes_with_expert_parallel(
        synth_corpus_data):
    """pp=2 x ep=2 on one mesh (VERDICT r3 item 3): Switch-MoE blocks
    pipelined over pp with each stage's expert stack sharded over ep.
    Expert leaves are STORED P("pp", "ep", ...) — 1/4 per chip — and
    training quality matches the unpipelined MoE model."""
    train_path, val_path = synth_corpus_data
    knobs = dict(KNOBS, n_layers=2, pipeline_parallel=2, moe_experts=4,
                 expert_parallel=2, dropout=0.0)
    model = JaxTransformerTagger(**knobs)
    assert model.mesh.shape["pp"] == 2
    assert model.mesh.shape["ep"] == 2
    model.train(train_path)
    score = model.evaluate(val_path)

    # Storage: stage-stacked expert leaves shard over pp AND ep.
    from rafiki_tpu.parallel import shard_variables

    placed = shard_variables(
        model._pp_split(model._variables["params"]), model.mesh)
    expert_leaves = [
        (path, leaf) for path, leaf in
        jax.tree_util.tree_flatten_with_path(placed["stages"])[0]
        if "expert" in "/".join(str(getattr(p, "key", p))
                                for p in path).lower()]
    assert expert_leaves
    for _, leaf in expert_leaves:
        shard = leaf.addressable_shards[0].data
        assert shard.nbytes * 4 == leaf.nbytes, \
            f"expert leaf not pp x ep sharded: {shard.shape} of {leaf.shape}"

    # Quality: same recipe unpipelined (ep-only GSPMD path).
    base = JaxTransformerTagger(**dict(KNOBS, n_layers=2, moe_experts=4,
                                       expert_parallel=2, dropout=0.0))
    base.train(train_path)
    assert abs(score - base.evaluate(val_path)) < 0.07, \
        (score, base.evaluate(val_path))
    model.destroy()
    base.destroy()
