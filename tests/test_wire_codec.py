"""The packed serving wire (r13): codec round-trips, negotiation,
mixed-fleet e2e, the worker staging buffer, int8 serving quantization,
and the zero-new-series guard.

Codec invariants are property-style over the supported dtype/shape
matrix (incl. non-contiguous inputs); e2e tests run real Predictor /
InferenceWorker components over a MemoryBus with no mocks of the
protocol itself — only the model is a stand-in where jax would be
noise.
"""

import threading

import numpy as np
import pytest

from rafiki_tpu.bus import MemoryBus
from rafiki_tpu.cache import (WIRE_NDBATCH, Cache, PackedBatch,
                              decode_batch, decode_payload,
                              encode_payload)
from rafiki_tpu.observe import metrics as obs_metrics
from rafiki_tpu.observe import wire as obs_wire
from rafiki_tpu.predictor.predictor import Predictor
from rafiki_tpu.worker.inference import (_HostStager, _PackedEnsemble,
                                         InferenceWorker)

DTYPES = [np.uint8, np.int8, np.uint16, np.int32, np.int64,
          np.float16, np.float32, np.float64, np.bool_]
SHAPES = [(), (3,), (2, 3), (8, 8, 1), (2, 2, 2, 2)]


def _arrays(dtype, shape, n=5, seed=0):
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 2, size=(n, *shape)) if dtype == np.bool_ \
        else rng.integers(0, 100, size=(n, *shape))
    # np.array (not astype on the iterated row) so 0-d shapes stay
    # ndarrays rather than collapsing to numpy scalars.
    return [np.array(a, dtype=dtype) for a in raw]


# --- Codec round-trips -------------------------------------------------


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", SHAPES)
def test_pack_roundtrip_every_dtype_shape(dtype, shape):
    arrays = _arrays(dtype, shape)
    pb = PackedBatch.from_arrays(arrays)
    assert pb is not None and pb.n == len(arrays)
    out = decode_batch(pb.slice(0, pb.n))
    assert out.dtype == np.dtype(dtype) and out.shape == (5, *shape)
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dtype", [np.uint8, np.float32])
def test_packed_equals_perquery_format(dtype):
    """The two wire formats must decode to identical tensors — the
    mixed-fleet correctness contract."""
    arrays = _arrays(dtype, (4, 3))
    encoded = [encode_payload(a) for a in arrays]
    pb = PackedBatch.from_encoded(encoded)
    assert pb is not None
    packed_rows = decode_batch(pb.slice(0, pb.n))
    for enc, row in zip(encoded, packed_rows):
        np.testing.assert_array_equal(decode_payload(enc), row)


def test_pack_noncontiguous_inputs():
    base = np.arange(64, dtype=np.float32).reshape(8, 8)
    views = [base[::2, ::2], base.T[:4, :4], base[1:5, 2:6]]
    assert not any(v.flags["C_CONTIGUOUS"] for v in views)
    pb = PackedBatch.from_arrays(views)
    out = decode_batch(pb.slice(0, 3))
    for v, row in zip(views, out):
        np.testing.assert_array_equal(np.ascontiguousarray(v), row)


def test_slice_and_take_are_row_exact():
    arrays = _arrays(np.int32, (3,), n=7)
    pb = PackedBatch.from_arrays(arrays)
    mid = decode_batch(pb.slice(2, 4))
    for i, row in enumerate(mid):
        np.testing.assert_array_equal(arrays[2 + i], row)
    sub = pb.take([6, 0, 3])
    out = decode_batch(sub.slice(0, 3))
    for want, row in zip([arrays[6], arrays[0], arrays[3]], out):
        np.testing.assert_array_equal(want, row)


def test_from_lists_refuses_unpackable():
    a = np.zeros((2, 2), np.float32)
    assert PackedBatch.from_arrays([]) is None
    assert PackedBatch.from_arrays([a, np.zeros((2, 3), np.float32)]) \
        is None                                        # mixed shapes
    assert PackedBatch.from_arrays([a, a.astype(np.int32)]) is None
    assert PackedBatch.from_arrays([a, [1, 2]]) is None  # non-tensor
    assert PackedBatch.from_arrays(
        [np.array(["x", "y"], dtype=object)]) is None
    enc = encode_payload(a)
    assert PackedBatch.from_encoded([enc, {"no": "nd"}]) is None
    assert PackedBatch.from_encoded([enc, encode_payload(
        np.zeros((3, 3), np.float32))]) is None
    assert PackedBatch.from_encoded([1, 2]) is None
    # a lying per-query frame (payload shorter than its header) is
    # refused, not silently mis-packed
    bad = dict(enc)
    bad["__nd__"] = bad["__nd__"][:8]
    assert PackedBatch.from_encoded([bad, enc]) is None


def _good_frame(n=3):
    return PackedBatch.from_arrays(
        _arrays(np.float32, (2, 2), n=n)).slice(0, n)


@pytest.mark.parametrize("mutate", [
    lambda f: f.pop("__ndbatch__"),
    lambda f: f.update(v=2),                      # unknown version
    lambda f: f.pop("v"),
    lambda f: f.update(dtype="no-such-dtype"),
    lambda f: f.update(shape=[-1, 2]),
    lambda f: f.update(n=-1),
    lambda f: f.update(n=99),                     # truncated payload
    lambda f: f.update(__ndbatch__="!!!notb64!!!"),
    lambda f: f.update(
        __ndbatch__=f["__ndbatch__"][:len(f["__ndbatch__"]) // 2]),
    lambda f: f.update(offsets=[0, 1, 2]),        # disagree with header
    lambda f: f.update(offsets=[0]),              # wrong count
])
def test_decode_rejects_corrupt_frames(mutate):
    frame = _good_frame()
    mutate(frame)
    with pytest.raises(ValueError):
        decode_batch(frame)


def test_from_encoded_rejects_lying_header_before_allocating():
    """A client-controlled shape header must not size an allocation
    its payload doesn't vouch for (shape [1e12] over a 4-byte payload
    refuses instead of attempting a multi-TB np.empty), and negative
    dims are refused outright."""
    huge = {"__nd__": encode_payload(np.zeros((1,), np.float32))["__nd__"],
            "dtype": "float32", "shape": [10 ** 12]}
    assert PackedBatch.from_encoded([huge]) is None
    neg = {"__nd__": "AAAA", "dtype": "float32", "shape": [-1]}
    assert PackedBatch.from_encoded([neg]) is None


def test_decode_rejects_dict_offsets_as_valueerror():
    """Corrupt offsets of the wrong TYPE (a dict round-tripped through
    JSON string keys) must land in the ValueError contract, never
    escape as KeyError through the worker's serve loop."""
    frame = _good_frame()
    frame["offsets"] = {str(i): v for i, v in enumerate(frame["offsets"])}
    with pytest.raises(ValueError):
        decode_batch(frame)


def test_decode_accepts_offsetless_frame():
    """offsets are a validation aid, not load-bearing — a minimal
    well-formed header decodes."""
    frame = _good_frame()
    frame.pop("offsets")
    assert decode_batch(frame).shape == (3, 2, 2)


# --- Worker-side decode + staging --------------------------------------


class _StagedModel:
    """Stand-in model exposing the staged contract; counts entries."""
    max_predict_batch = 64

    def __init__(self):
        self.staged_calls = 0
        self.flat_calls = 0
        self.buffers = []

    def predict_bucket(self, n, dtype=None):
        if not (1 <= n <= self.max_predict_batch):
            return None
        b = 1
        while b < n:
            b *= 2
        return b

    def predict_staged_submit(self, buf, n):
        self.staged_calls += 1
        self.buffers.append(buf)
        rows = buf[:n].reshape(n, -1).astype(np.float64)
        return lambda: [[float(r.sum()), float(r.sum()) + 0.5]
                        for r in rows]

    def predict_submit(self, queries):
        self.flat_calls += 1
        return lambda: [[float(np.asarray(q, dtype=np.float64).sum()),
                         float(np.asarray(q, dtype=np.float64).sum())
                         + 0.5] for q in queries]


def _worker(bus, wid="w1", job="job", trial="t1", wire_on=True,
            model=None):
    """A real InferenceWorker wired by hand (no meta/params), its loop
    driven by the test."""
    w = InferenceWorker(wid, job, trial, meta=None, params=None,
                        bus=bus, pipeline=False)
    w._model = model if model is not None else _StagedModel()
    w._wire_formats = [WIRE_NDBATCH] if wire_on else []
    w._reg_info = {"trial_id": trial, "wire": w._wire_formats}
    w.cache.register_worker(job, wid, info=w._reg_info)

    def loop():
        while not w.stop_flag.is_set():
            items = w.cache.pop_queries(wid, timeout=0.1)
            if items:
                w._complete_batch(*w._dispatch_batch(items))

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return w


def _expected(qs):
    return [float(np.asarray(q, dtype=np.float64).sum()) for q in qs]


def test_packed_e2e_direct_and_preencoded_paths():
    bus = MemoryBus()
    w = _worker(bus)
    try:
        p = Predictor("job", bus, gather_timeout=5.0,
                      worker_wait_timeout=5.0)
        qs = [np.full((4, 3), i, np.uint8) for i in range(6)]
        res = p.predict(qs)
        assert [r[0] for r in res] == _expected(qs)
        assert w._model.staged_calls == 1 and w._model.flat_calls == 0
        res2 = p.predict([encode_payload(q) for q in qs],
                         pre_encoded=True)
        assert [r[0] for r in res2] == _expected(qs)
        assert w._model.staged_calls == 2 and w._model.flat_calls == 0
    finally:
        w.stop_flag.set()


def test_staging_buffer_reused_across_bursts():
    bus = MemoryBus()
    w = _worker(bus)
    try:
        p = Predictor("job", bus, gather_timeout=5.0,
                      worker_wait_timeout=5.0)
        qs = [np.full((2, 2), i, np.float32) for i in range(3)]
        for _ in range(4):
            p.predict(qs)
        bufs = w._model.buffers
        assert len(bufs) == 4
        # Double-buffered reuse: alternating bursts share a buffer (no
        # per-burst allocation), successive ones never do (the async
        # device_put of burst N must not race burst N+1's staging).
        assert bufs[0] is bufs[2] and bufs[1] is bufs[3]
        assert bufs[0] is not bufs[1]
    finally:
        w.stop_flag.set()


def test_mixed_fleet_old_worker_and_old_predictor(monkeypatch):
    """New predictor + one packed and one legacy worker (two bins,
    both vote); then an old-style (packed-off) predictor against the
    new workers — every combination must serve identically."""
    bus = MemoryBus()
    w_new = _worker(bus, wid="wn", trial="t-new", wire_on=True)
    w_old = _worker(bus, wid="wo", trial="t-old", wire_on=False)
    try:
        qs = [np.full((3,), i, np.float32) for i in range(5)]
        p = Predictor("job", bus, gather_timeout=5.0,
                      worker_wait_timeout=5.0)
        res = p.predict(qs)
        assert [r[0] for r in res] == _expected(qs)  # 2-bin mean of equal votes
        assert w_new._model.staged_calls >= 1   # packed frames arrived
        assert w_old._model.flat_calls >= 1     # legacy frames arrived
        assert w_old._model.staged_calls == 0   # never packed at it

        monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "off")
        obs_wire.reset_for_tests()
        p_old = Predictor("job", bus, gather_timeout=5.0,
                          worker_wait_timeout=5.0)
        res2 = p_old.predict(qs)
        assert [r[0] for r in res2] == _expected(qs)
        # the packed-capable worker happily took per-query frames
        assert w_new._model.flat_calls >= 1
    finally:
        w_new.stop_flag.set()
        w_old.stop_flag.set()
        obs_wire.reset_for_tests()


def test_packed_wire_mode_fails_safe_on_typo():
    """A hand-set worker env never passes NodeConfig validation, so an
    unrecognized spelling must not silently resolve to 'on' (a typo'd
    rollback keeping the feature alive) — it fails safe to compat."""
    assert obs_wire.packed_wire_mode("offf") == "compat"
    assert obs_wire.packed_wire_mode("onn") == "compat"
    assert obs_wire.packed_wire_mode("off") == "off"
    assert obs_wire.packed_wire_mode("0") == "off"
    assert obs_wire.packed_wire_mode("on") == "on"
    assert obs_wire.packed_wire_mode("") == "on"
    assert obs_wire.packed_wire_mode("COMPAT") == "compat"
    # quant typos fail safe to UNQUANTIZED serving (a worker must not
    # go ERRORED at model load over a hand-set env typo)
    assert obs_wire.quant_mode("int-8") == ""
    assert obs_wire.quant_mode("fp8") == ""
    assert obs_wire.quant_mode("int8") == "int8"
    assert obs_wire.quant_mode("OFF") == ""


def test_compat_mode_worker_not_advertised(monkeypatch):
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "compat")
    obs_wire.reset_for_tests()
    w = InferenceWorker("w", "j", "t", meta=None, params=None,
                        bus=MemoryBus(), pipeline=False)
    assert w._wire_formats == []
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    w2 = InferenceWorker("w2", "j", "t", meta=None, params=None,
                         bus=MemoryBus(), pipeline=False)
    assert w2._wire_formats == [WIRE_NDBATCH]
    obs_wire.reset_for_tests()


def test_wire_payload_packs_only_when_a_plan_needs_it():
    """Lazy packing (review finding): a plan that never targets a
    packed-capable worker — e.g. a tiered phase-1 against a legacy
    best bin — must not pay the assembly decode/alloc; the first plan
    that does triggers it exactly once."""
    from rafiki_tpu.predictor.predictor import _Shard, _WirePayload

    frames = [encode_payload(np.full((3,), i, np.float32))
              for i in range(4)]
    wire = _WirePayload(frames, True, frozenset({"wcap"}))
    enc, packed = wire.for_plan([_Shard("wleg", "b", 0, 4)])
    assert packed is None and enc is frames
    assert wire._packed_done is False  # assembly never ran
    enc2, packed2 = wire.for_plan([_Shard("wcap", "b", 0, 4)])
    assert enc2 is None and packed2 is not None
    assert wire.packed is packed2  # memoized, not re-assembled


def test_corrupt_packed_frame_errors_only_its_own_frame():
    """A corrupt packed frame in a burst is answered with per-query
    error dicts; co-batched frames still serve, and the worker thread
    survives."""
    bus = MemoryBus()
    w = _worker(bus)
    try:
        cache = Cache(bus)
        good = PackedBatch.from_arrays(
            [np.full((2,), 7, np.float32)]).slice(0, 1)
        bad = PackedBatch.from_arrays(
            [np.full((2,), 1, np.float32)]).slice(0, 2)  # lying n
        bad["n"] = 2
        bus.push("q:w1", {"batch_id": "bgood", "batch": good})
        bus.push("q:w1", {"batch_id": "bbad", "batch": bad})
        good_reply = bus.pop("r:bgood", timeout=5.0)
        bad_reply = bus.pop("r:bbad", timeout=5.0)
        assert good_reply["predictions"][0][0] == 14.0
        assert len(bad_reply["predictions"]) == 2
        assert all("error" in p for p in bad_reply["predictions"])
        # worker still serves after the bad frame
        p = Predictor("job", bus, gather_timeout=5.0,
                      worker_wait_timeout=5.0)
        res = p.predict([np.full((2,), 3, np.float32)])
        assert res[0][0] == 6.0
    finally:
        w.stop_flag.set()


def test_corrupt_frame_reply_size_is_capped():
    """A corrupt frame's header is untrusted: a lying n=1e9 must not
    make the error path allocate a billion error dicts."""
    from rafiki_tpu.cache import _CORRUPT_REPLY_CAP

    bus = MemoryBus()
    w = _worker(bus)
    try:
        frame = PackedBatch.from_arrays(
            [np.zeros((2,), np.float32)]).slice(0, 1)
        frame["n"] = 10 ** 9  # payload no longer matches -> corrupt
        bus.push("q:w1", {"batch_id": "bhuge", "batch": frame})
        reply = bus.pop("r:bhuge", timeout=5.0)
        assert len(reply["predictions"]) == _CORRUPT_REPLY_CAP
        assert all("error" in p for p in reply["predictions"])
    finally:
        w.stop_flag.set()


def test_fanout_packed_and_perquery_mix():
    """send_query_batch_fanout's packed path (the unsharded fanout the
    wire contract also names): capable workers get ONE shared packed
    frame, the rest the per-query list — decode-identical."""
    bus = MemoryBus()
    cache = Cache(bus)
    arrays = _arrays(np.float32, (3,), n=4)
    encoded = [encode_payload(a) for a in arrays]
    packed = PackedBatch.from_encoded(encoded)
    cache.send_query_batch_fanout(["wnew", "wold"], encoded,
                                  packed=packed, packed_ok={"wnew"})
    new_frame = bus.pop("q:wnew", timeout=2.0)
    old_frame = bus.pop("q:wold", timeout=2.0)
    assert "batch" in new_frame and "queries" not in new_frame
    assert old_frame["queries"] is encoded  # shared, not copied
    rows = decode_batch(new_frame["batch"])
    for a, row in zip(arrays, rows):
        np.testing.assert_array_equal(a, row)
    # all-capable fanout needs no per-query list at all
    cache.send_query_batch_fanout(["wnew"], None, packed=packed,
                                  packed_ok={"wnew"})
    assert "batch" in bus.pop("q:wnew", timeout=2.0)


def test_quant_host_arrays_single_pass(ff_model):
    """enable_serving_quant's report and the first compile share ONE
    host quantization pass (review finding: it used to run twice per
    worker load)."""
    ff_model.enable_serving_quant("int8")
    try:
        first = ff_model._quant_host
        assert first is not None
        assert ff_model._quant_host_arrays() is first
    finally:
        ff_model.enable_serving_quant("")


def test_packed_ensemble_staged_contract():
    m1, m2 = _StagedModel(), _StagedModel()
    pack = _PackedEnsemble([m1, m2])
    assert pack.predict_bucket(5) == 8
    buf = np.ones((8, 2), np.float32)
    out = pack.predict_staged_submit(buf, 5)()
    assert len(out) == 5 and out[0] == [2.0, 2.5]  # mean of equal votes
    assert m1.buffers[0] is m2.buffers[0]  # one shared staging buffer
    # disagreement (or a member without the entry) falls back
    m2.max_predict_batch = 2
    assert pack.predict_bucket(5) is None
    assert _PackedEnsemble([m1, object()]).predict_bucket(3) is None


def test_host_stager_keys_and_reuse():
    st = _HostStager()
    a = st.buffer(8, (2, 2), np.uint8)
    b = st.buffer(8, (2, 2), np.uint8)
    assert a.shape == (8, 2, 2) and a.dtype == np.uint8
    assert b is not a                          # double buffer rotation
    assert st.buffer(8, (2, 2), np.uint8) is a  # ...of exactly two
    assert st.buffer(8, (2, 2), np.float32) is not a
    assert st.buffer(16, (2, 2), np.uint8) is not a


# --- Metrics: accounting + the zero-new-series guard -------------------

_WIRE_METRICS = ("rafiki_tpu_serving_wire_bytes_total",
                 "rafiki_tpu_serving_host_copies_total",
                 "rafiki_tpu_serving_quant_total")


@pytest.fixture()
def fresh_registry(monkeypatch):
    """A private registry so absence-of-series is judgeable: the real
    one is process-global and other tests already fed it."""
    reg = obs_metrics.MetricsRegistry()
    monkeypatch.setattr(obs_metrics, "_registry", reg)
    obs_wire.reset_for_tests()
    yield reg
    obs_wire.reset_for_tests()


def _serve_once(packed_predictor=True):
    bus = MemoryBus()
    w = _worker(bus, wire_on=packed_predictor)
    try:
        p = Predictor("job", bus, gather_timeout=5.0,
                      worker_wait_timeout=5.0)
        p.predict([np.full((2, 2), i, np.uint8) for i in range(4)])
    finally:
        w.stop_flag.set()


def test_zero_new_series_when_disabled(fresh_registry, monkeypatch):
    """Packed wire off + quant off ⇒ a full serve registers NONE of
    the wire/copies/quant families (the r12 discipline)."""
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "off")
    monkeypatch.delenv(obs_wire.QUANT_ENV, raising=False)
    obs_wire.reset_for_tests()
    _serve_once(packed_predictor=False)
    for name in _WIRE_METRICS:
        assert fresh_registry.find(name) is None, name


def test_wire_metrics_account_both_formats(fresh_registry, monkeypatch):
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    _serve_once(packed_predictor=True)
    wire = fresh_registry.find("rafiki_tpu_serving_wire_bytes_total")
    copies = fresh_registry.find("rafiki_tpu_serving_host_copies_total")
    assert wire is not None and copies is not None
    assert wire.value(format="packed", direction="scatter") > 0
    # r14: dense float-vector replies pack too (the query frame's "rw"
    # negotiation), so the packed side's reply bytes are packed now.
    assert wire.value(format="packed", direction="reply") > 0
    assert wire.value(format="perquery", direction="reply") == 0
    # packed path: assembly decode + per-shard encode, no stack/pad
    assert copies.value(site="encode") >= 1
    assert copies.value(site="stack") == 0
    _serve_once(packed_predictor=False)  # legacy worker: perquery side
    assert wire.value(format="perquery", direction="scatter") > 0
    assert copies.value(site="decode") > 0


def test_compat_mode_accounts_without_packing(fresh_registry,
                                              monkeypatch):
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "compat")
    obs_wire.reset_for_tests()
    _serve_once(packed_predictor=False)
    wire = fresh_registry.find("rafiki_tpu_serving_wire_bytes_total")
    assert wire is not None
    assert wire.value(format="packed", direction="scatter") == 0
    assert wire.value(format="perquery", direction="scatter") > 0


def test_packed_wire_bytes_materially_lower(fresh_registry,
                                            monkeypatch):
    """The bench's judged claim, pinned as a unit property: the same
    super-batch costs materially fewer wire bytes packed than
    per-query (framing overhead amortizes to one header per shard)."""
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    cache = Cache(MemoryBus())
    qs = [np.zeros((8, 8, 1), np.uint8) for _ in range(32)]
    encoded = [encode_payload(q) for q in qs]
    packed = PackedBatch.from_encoded(encoded)
    wire = None
    cache.send_query_shards([("w1", 0, 32, "s1")], encoded)
    reg = fresh_registry.find("rafiki_tpu_serving_wire_bytes_total")
    perquery = reg.value(format="perquery", direction="scatter")
    cache.send_query_shards([("w1", 0, 32, "s2")], None,
                            packed=packed, packed_ok={"w1"})
    packed_bytes = reg.value(format="packed", direction="scatter")
    assert perquery > 0 and packed_bytes > 0
    assert packed_bytes < 0.85 * perquery, (packed_bytes, perquery)


# --- int8 serving quantization ----------------------------------------


@pytest.fixture(scope="module")
def ff_model():
    """A tiny initialized (untrained) JaxFeedForward — weights are
    random but deterministic, which is all the numeric contracts
    need."""
    import jax
    import jax.numpy as jnp

    from rafiki_tpu.models.feedforward import JaxFeedForward

    m = JaxFeedForward(hidden_layer_count=2, hidden_layer_units=32,
                       learning_rate=1e-3, batch_size=32, max_epochs=1)
    m._ensure_module(4, (8, 8, 1))
    variables = m._module.init(
        jax.random.key(0), jnp.zeros((1, 8, 8, 1)), train=False,
        **{k: jnp.asarray(v) for k, v in m.extra_apply_inputs().items()})
    m._variables = jax.tree.map(lambda a: np.asarray(a), variables)
    m._meta = {"n_classes": 4, "image_shape": [8, 8, 1]}
    yield m
    m.enable_serving_quant("")


@pytest.fixture()
def quant_queries():
    rng = np.random.default_rng(7)
    return (rng.random((6, 8, 8, 1)) * 255).astype(np.uint8)


def test_int8_quant_close_to_f32(ff_model, quant_queries):
    ff_model.enable_serving_quant("")
    p_f32 = np.asarray(ff_model.predict_proba(quant_queries))
    report = ff_model.enable_serving_quant("int8")
    assert report["mode"] == "int8" and report["n_int8"] == 4
    p_q = np.asarray(ff_model.predict_proba(quant_queries))
    assert np.abs(p_f32 - p_q).max() < 0.02
    assert (p_f32.argmax(-1) == p_q.argmax(-1)).all()
    # disabling restores the exact f32 path
    ff_model.enable_serving_quant("")
    np.testing.assert_allclose(
        np.asarray(ff_model.predict_proba(quant_queries)), p_f32)


def test_int8_generic_fallback_matches_module_path(ff_model,
                                                   quant_queries):
    """Force the generic dequantized-weights fallback (quantized_apply
    -> None) and compare with the module's dequant-free int8 path —
    both must stay near f32; the fallback is weight-only so it is
    numerically the tighter of the two."""
    ff_model.enable_serving_quant("")
    p_f32 = np.asarray(ff_model.predict_proba(quant_queries))
    ff_model.enable_serving_quant("int8")
    try:
        p_int8 = np.asarray(ff_model.predict_proba(quant_queries))
        orig = type(ff_model).quantized_apply
        type(ff_model).quantized_apply = \
            lambda self, q, s, f, x, e: None
        try:
            ff_model._predict_cache.clear()  # recompile generic variant
            p_generic = np.asarray(ff_model.predict_proba(quant_queries))
        finally:
            type(ff_model).quantized_apply = orig
            ff_model._predict_cache.clear()
        assert np.abs(p_f32 - p_generic).max() < 0.01
        assert np.abs(p_int8 - p_generic).max() < 0.02
    finally:
        ff_model.enable_serving_quant("")


def test_quant_staged_and_flat_paths_agree(ff_model, quant_queries):
    ff_model.enable_serving_quant("int8")
    try:
        flat = np.asarray(ff_model.predict_proba(quant_queries))
        n = quant_queries.shape[0]
        bucket = ff_model.predict_bucket(n, np.uint8)
        buf = np.zeros((bucket, 8, 8, 1), np.uint8)
        buf[:n] = quant_queries
        staged = np.asarray(ff_model.predict_staged_submit(buf, n)())
        np.testing.assert_allclose(staged, flat, rtol=1e-5, atol=1e-6)
    finally:
        ff_model.enable_serving_quant("")


def test_quant_mode_validation(ff_model):
    with pytest.raises(ValueError):
        ff_model.enable_serving_quant("fp4")


def test_quant_counter_only_when_active(fresh_registry, monkeypatch):
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    _serve_once()  # unquantized serving
    assert fresh_registry.find("rafiki_tpu_serving_quant_total") is None
    obs_wire.count_quant(4, "int8")
    c = fresh_registry.find("rafiki_tpu_serving_quant_total")
    assert c is not None and c.value(mode="int8") == 4


def test_worker_quantizes_at_load(monkeypatch):
    """The worker's load path applies RAFIKI_TPU_SERVING_QUANT to a
    model exposing enable_serving_quant, and its registration records
    what it serves (promotion-spawned workers recompute scales by
    construction — same code path)."""
    calls = []

    class _QModel:
        @staticmethod
        def validate_knobs(knobs):
            return knobs

        def load_parameters(self, params):
            pass

        def enable_serving_quant(self, mode):
            calls.append(mode)
            return {"mode": mode, "n_int8": 2, "n_f32": 1}

    class _Meta:
        def get_trial(self, tid):
            return {"model_id": "m", "knobs": {}, "score": 0.5,
                    "params_id": "p"}

        def get_model(self, mid):
            return {"model_class": "x:Y", "model_source": None}

    class _Params:
        def load(self, pid):
            return {}

    monkeypatch.setenv(obs_wire.QUANT_ENV, "int8")
    obs_wire.reset_for_tests()
    w = InferenceWorker("s", "j", "t", _Meta(), _Params(), MemoryBus(),
                        pipeline=False)
    monkeypatch.setattr(
        "rafiki_tpu.worker.inference.load_model_class",
        lambda cls, src: _QModel)
    w._load_model()
    assert calls == ["int8"]
    assert w._quant_active is True
    obs_wire.reset_for_tests()


# --- Reply-direction packed frames (r14) ------------------------------

def _reply_roundtrip(preds, packed_ok=True, env="on", monkeypatch=None):
    from rafiki_tpu.cache import pack_prediction_rows  # noqa: F401

    bus = MemoryBus()
    cache = Cache(bus)
    cache.send_prediction_batch("rb", "w1", preds, weight=2,
                                shard="sh", packed_ok=packed_ok)
    out = cache.gather_prediction_batches("rb", 1, timeout=2.0)
    assert len(out) == 1
    return out[0]


def test_reply_pack_roundtrip_and_metadata():
    preds = [[0.1 * i, 1.0 - 0.1 * i] for i in range(8)]
    reply = _reply_roundtrip(preds)
    assert reply["weight"] == 2 and reply["shard"] == "sh"
    got = reply["predictions"]
    assert len(got) == 8
    for g, p in zip(got, preds):
        np.testing.assert_allclose(np.asarray(g), p)


def test_reply_pack_refuses_unpackable():
    from rafiki_tpu.cache import pack_prediction_rows

    assert pack_prediction_rows([{"error": "x"}, [0.1, 0.9]]) is None
    assert pack_prediction_rows([[0.1, 0.9]]) is None          # n < 2
    assert pack_prediction_rows([[1, 2], [3, 4]]) is None      # ints
    assert pack_prediction_rows([[0.1, 0.9],
                                 [0.1, 0.9, 0.0]]) is None     # ragged
    assert pack_prediction_rows(["a", "b"]) is None
    assert pack_prediction_rows(
        [{"__members__": [[0.1], [0.9]]}] * 2) is None
    # ...and an unpackable batch still round-trips per-query.
    reply = _reply_roundtrip([{"error": "x"}, [0.1, 0.9]])
    assert reply["predictions"] == [{"error": "x"}, [0.1, 0.9]]


def test_reply_pack_negotiation_is_frame_carried(monkeypatch):
    """Workers pack replies ONLY toward senders whose query frame
    advertised `rw` (an old predictor never sets it), and only while
    their own packed mode is "on" (compat keeps per-query replies)."""
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    bus = MemoryBus()
    on = Cache(bus)
    on.send_query_shards([("wq", 0, 2, "s1")],
                         [encode_payload(np.zeros((2,), np.float32))] * 2)
    frame = bus.pop_all("q:wq", timeout=0.5)[0]
    assert frame.get("rw") == [WIRE_NDBATCH]
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "compat")
    obs_wire.reset_for_tests()
    compat = Cache(bus)
    compat.send_query_shards([("wq", 0, 2, "s2")],
                             [encode_payload(np.zeros((2,),
                                             np.float32))] * 2)
    frame = bus.pop_all("q:wq", timeout=0.5)[0]
    assert "rw" not in frame
    # compat sender side: packed_ok granted but own mode says no.
    compat.send_prediction_batch("rc", "w1", [[0.5, 0.5]] * 4,
                                 packed_ok=True)
    raw = bus.pop_all("r:rc", timeout=0.5)[0]
    assert "batch" not in raw and "predictions" in raw
    obs_wire.reset_for_tests()


def test_reply_packed_bytes_materially_lower(fresh_registry,
                                             monkeypatch):
    """The reply-direction unit gate (ISSUE r14): the same dense reply
    batch costs fewer estimated wire bytes packed than per-query."""
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    cache = Cache(MemoryBus())
    preds = [list(np.linspace(0.0, 1.0, 10) + i) for i in range(32)]
    cache.send_prediction_batch("rp", "w1", preds, packed_ok=True)
    reg = fresh_registry.find("rafiki_tpu_serving_wire_bytes_total")
    packed = reg.value(format="packed", direction="reply")
    cache.send_prediction_batch("rq", "w1", preds, packed_ok=False)
    perquery = reg.value(format="perquery", direction="reply")
    assert packed > 0 and perquery > 0
    assert packed < 0.85 * perquery, (packed, perquery)


def test_reply_corrupt_packed_frame_is_dropped(monkeypatch):
    """A corrupt packed reply is DROPPED, never returned: its shard
    must read as genuinely unanswered so the straggler resubmit /
    partial-bin machinery covers it — returning it (even with empty
    predictions) would mark the shard answered and could supersede a
    healthy in-flight retry. A good reply behind it still gathers."""
    bus = MemoryBus()
    cache = Cache(bus)
    bus.push("r:bad", {"worker_id": "w1", "weight": 1,
                       "batch": {"__ndbatch__": "!!!", "v": 1,
                                 "dtype": "float64", "shape": [2],
                                 "n": 2, "offsets": [0, 16]}})
    bus.push("r:bad", {"worker_id": "w2", "weight": 1,
                       "predictions": [[0.5, 0.5]]})
    out = cache.gather_prediction_batches("bad", 1, timeout=2.0)
    assert len(out) == 1 and out[0]["worker_id"] == "w2"


def test_reply_packed_e2e_through_real_worker(monkeypatch):
    """Real InferenceWorker + real Predictor over a MemoryBus: the
    reply rides ONE packed frame and the ensemble output is
    unchanged."""
    monkeypatch.setenv(obs_wire.PACKED_WIRE_ENV, "on")
    obs_wire.reset_for_tests()
    bus = MemoryBus()
    w = _worker(bus)
    try:
        p = Predictor("job", bus, gather_timeout=5.0,
                      worker_wait_timeout=5.0)
        qs = [np.full((2, 2), i, np.uint8) for i in range(4)]
        res = p.predict(qs)
        assert [r[0] for r in res] == _expected(qs)
        # Prove the wire actually packed the reply.
        reg = obs_metrics.registry().find(
            "rafiki_tpu_serving_wire_bytes_total")
        assert reg.value(format="packed", direction="reply") > 0
    finally:
        w.stop_flag.set()
        obs_wire.reset_for_tests()
