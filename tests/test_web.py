"""Web dashboard + serve CLI (SURVEY.md §2 "Web UI" / "Ops scripts")."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest
import requests

from rafiki_tpu.constants import UserType
from rafiki_tpu.platform import LocalPlatform


@pytest.fixture()
def http_platform(tmp_path):
    platform = LocalPlatform(workdir=str(tmp_path / "plat"), http=True)
    yield platform
    platform.shutdown()


def test_dashboard_served_unauthenticated(http_platform):
    url = f"http://127.0.0.1:{http_platform.app.port}/"
    r = requests.get(url, timeout=10)
    assert r.status_code == 200
    assert r.headers["Content-Type"].startswith("text/html")
    assert "rafiki-tpu" in r.text and "Train jobs" in r.text


def test_train_jobs_listing_route(http_platform):
    from rafiki_tpu.client import Client

    admin = http_platform.admin
    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")
    client.create_user("w@x.c", "pw", UserType.APP_DEVELOPER)
    assert client.get_train_jobs() == []
    assert admin is not None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serve_cli_starts_and_stops_gracefully(tmp_path):
    """`python -m rafiki_tpu serve` comes up, serves the dashboard and the
    REST API, and exits cleanly on SIGTERM (the stop.sh path)."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "rafiki_tpu", "serve",
         "--workdir", str(tmp_path / "node"), "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        base = f"http://127.0.0.1:{port}"
        for _ in range(120):
            try:
                if requests.get(base + "/", timeout=2).status_code == 200:
                    break
            except requests.ConnectionError:
                time.sleep(0.5)
        else:
            out = proc.stdout.read().decode() if proc.stdout else ""
            pytest.fail(f"serve never came up:\n{out}")
        r = requests.post(base + "/tokens", json={
            "email": "superadmin@rafiki", "password": "rafiki"}, timeout=10)
        assert r.status_code == 200 and "token" in r.json()
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
