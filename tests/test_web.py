"""Web dashboard + serve CLI (SURVEY.md §2 "Web UI" / "Ops scripts")."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest
import requests

from rafiki_tpu.constants import UserType
from rafiki_tpu.platform import LocalPlatform


@pytest.fixture()
def http_platform(tmp_path):
    platform = LocalPlatform(workdir=str(tmp_path / "plat"), http=True)
    yield platform
    platform.shutdown()


def test_dashboard_served_unauthenticated(http_platform):
    url = f"http://127.0.0.1:{http_platform.app.port}/"
    r = requests.get(url, timeout=10)
    assert r.status_code == 200
    assert r.headers["Content-Type"].startswith("text/html")
    assert "rafiki-tpu" in r.text and "Train jobs" in r.text


def test_train_jobs_listing_route(http_platform):
    from rafiki_tpu.client import Client

    admin = http_platform.admin
    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")
    client.create_user("w@x.c", "pw", UserType.APP_DEVELOPER)
    assert client.get_train_jobs() == []
    assert admin is not None


def test_user_admin_routes(http_platform):
    from rafiki_tpu.client import Client, ClientError

    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")
    made = client.create_user("victim@x.c", "pw", UserType.APP_DEVELOPER)
    users = client.get_users()
    assert any(u["email"] == "victim@x.c" and not u["banned"]
               for u in users)

    # a ban revokes EXISTING sessions too, not just future logins
    victim = Client(admin_port=http_platform.app.port)
    victim.login("victim@x.c", "pw")
    assert victim.get_train_jobs() == []
    client.ban_user(made["id"])
    assert any(u["email"] == "victim@x.c" and u["banned"]
               for u in client.get_users())
    with pytest.raises(ClientError):
        victim.get_train_jobs()  # live token now rejected
    with pytest.raises(ClientError):
        Client(admin_port=http_platform.app.port).login("victim@x.c",
                                                        "pw")

    # the root account and the caller themselves are unbannable
    su = next(u for u in client.get_users()
              if u["user_type"] == "SUPERADMIN")
    with pytest.raises(ClientError):
        client.ban_user(su["id"])
    admin2 = client.create_user("adm2@x.c", "pw", UserType.ADMIN)
    c2 = Client(admin_port=http_platform.app.port)
    c2.login("adm2@x.c", "pw")
    with pytest.raises(ClientError):
        c2.ban_user(admin2["id"])  # self-ban

    # non-admins get 403 on the users routes
    client.create_user("plain@x.c", "pw", UserType.APP_DEVELOPER)
    plain = Client(admin_port=http_platform.app.port)
    plain.login("plain@x.c", "pw")
    with pytest.raises(ClientError) as e:
        plain.get_users()
    assert e.value.status == 403


def test_status_route(http_platform):
    from rafiki_tpu.client import Client

    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")
    s = client.get_status()
    assert s["n_chips"] >= 1
    assert 0.0 <= s["chip_allocation"] <= 1.0
    assert isinstance(s["services_running"], dict)


def test_inference_jobs_listing(http_platform, synth_image_data):
    from rafiki_tpu.client import Client
    from rafiki_tpu.constants import BudgetOption, TaskType

    train_path, val_path = synth_image_data
    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")
    assert client.get_inference_jobs() == []
    model = client.create_model(
        "ff", TaskType.IMAGE_CLASSIFICATION,
        "rafiki_tpu.models.feedforward:JaxFeedForward")
    job = client.create_train_job(
        "app", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 1}, train_path, val_path)
    assert client.wait_until_train_job_done(job["id"], timeout=600)
    inf = client.create_inference_job(job["id"], max_models=1)
    listed = client.get_inference_jobs()
    assert [j["id"] for j in listed] == [inf["id"]]
    assert listed[0]["status"] == "RUNNING"
    assert listed[0]["predictor_host"]
    client.stop_inference_job(inf["id"])
    assert client.get_inference_jobs()[0]["status"] == "STOPPED"


def test_dashboard_write_path_forms(http_platform):
    """VERDICT r1 item 7: the dashboard carries every write-path form an
    app/model developer needs for the browser-only quickstart flow."""
    url = f"http://127.0.0.1:{http_platform.app.port}/"
    text = requests.get(url, timeout=10).text
    for el in ("nm-create",   # model registration (with source textarea)
               "nm-source",
               "nj-create",   # train-job creation
               "job-stop",
               "inf-create",  # ensemble deploy
               "nu-create",   # user admin
               "cmp-go",      # trial knob/plot comparison
               "cmp-knobs", "cmp-chart"):
        assert f'id="{el}"' in text, f"missing dashboard element #{el}"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_serve_cli_starts_and_stops_gracefully(tmp_path):
    """`python -m rafiki_tpu serve` comes up, serves the dashboard and the
    REST API, and exits cleanly on SIGTERM (the stop.sh path)."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "rafiki_tpu", "serve",
         "--workdir", str(tmp_path / "node"), "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        base = f"http://127.0.0.1:{port}"
        for _ in range(120):
            try:
                if requests.get(base + "/", timeout=2).status_code == 200:
                    break
            except requests.ConnectionError:
                time.sleep(0.5)
        else:
            proc.kill()  # read() on a live child would block forever
            out, _ = proc.communicate(timeout=10)
            pytest.fail(f"serve never came up:\n{out.decode()}")
        r = requests.post(base + "/tokens", json={
            "email": "superadmin@rafiki", "password": "rafiki"}, timeout=10)
        assert r.status_code == 200 and "token" in r.json()
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_dataset_upload_and_browser_only_flow(http_platform,
                                              synth_image_data):
    """VERDICT r3 item 1: dataset upload → meta row + stored file, and
    the uploaded paths drive a full train job — every quickstart step
    is doable through the REST surface the browser uses."""
    from rafiki_tpu.client import Client
    from rafiki_tpu.constants import BudgetOption, TaskType

    train_path, val_path = synth_image_data
    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")

    up_train = client.create_dataset(
        "synth-train", TaskType.IMAGE_CLASSIFICATION, train_path)
    up_val = client.create_dataset(
        "synth-val", TaskType.IMAGE_CLASSIFICATION, val_path)
    # Stored under the node's datasets dir, byte-identical to the upload.
    assert up_train["path"] != train_path
    assert up_train["path"].startswith(http_platform.workdir)
    assert os.path.getsize(up_train["path"]) == os.path.getsize(train_path)
    assert up_train["size_bytes"] == os.path.getsize(train_path)
    listed = client.get_datasets(task=TaskType.IMAGE_CLASSIFICATION)
    assert {d["name"] for d in listed} == {"synth-train", "synth-val"}

    model = client.create_model(
        "ff-up", TaskType.IMAGE_CLASSIFICATION,
        "rafiki_tpu.models.feedforward:JaxFeedForward")
    job = client.create_train_job(
        "upapp", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 1},
        up_train["path"], up_val["path"])
    done = client.wait_until_train_job_done(job["id"], timeout=600)
    assert done["status"] == "STOPPED"
    best = client.get_best_trials_of_train_job(job["id"], max_count=1)
    assert best and best[0]["score"] is not None


def test_dataset_upload_requires_auth_and_body(http_platform, tmp_path):
    import requests as rq

    base = f"http://127.0.0.1:{http_platform.app.port}"
    r = rq.post(base + "/datasets?name=x&task=IMAGE_CLASSIFICATION",
                data=b"zz", timeout=10,
                headers={"Content-Type": "application/octet-stream"})
    assert r.status_code == 401
    from rafiki_tpu.client import Client
    client = Client(admin_port=http_platform.app.port)
    tok = client.login("superadmin@rafiki", "rafiki")["token"]
    # Missing body / missing metadata are 400s, not crashes.
    hdr = {"Authorization": f"Bearer {tok}",
           "Content-Type": "application/octet-stream"}
    r = rq.post(base + "/datasets?name=x&task=T", timeout=10, headers=hdr)
    assert r.status_code == 400
    r = rq.post(base + "/datasets?name=x", data=b"zz", timeout=10,
                headers=hdr)
    assert r.status_code == 400
    # A hostile filename cannot traverse out of the datasets dir.
    ds = rq.post(base + "/datasets?name=evil&task=T"
                 "&filename=..%2F..%2Fpwn.zip", data=b"zz",
                 timeout=10, headers=hdr).json()
    import os as _os
    assert _os.path.dirname(ds["path"]) == \
        _os.path.join(http_platform.workdir, "datasets")


def test_service_log_view(http_platform, synth_image_data):
    """VERDICT r3 item 1: every service the platform launches captures
    a per-service log file the dashboard can tail over REST."""
    from rafiki_tpu.client import Client
    from rafiki_tpu.constants import BudgetOption, TaskType

    train_path, val_path = synth_image_data
    client = Client(admin_port=http_platform.app.port)
    client.login("superadmin@rafiki", "rafiki")
    model = client.create_model(
        "ff-logs", TaskType.IMAGE_CLASSIFICATION,
        "rafiki_tpu.models.feedforward:JaxFeedForward")
    job = client.create_train_job(
        "logapp", TaskType.IMAGE_CLASSIFICATION, [model["id"]],
        {BudgetOption.MODEL_TRIAL_COUNT: 1}, train_path, val_path)
    client.wait_until_train_job_done(job["id"], timeout=600)

    services = client.get_services()
    train_svcs = [s for s in services if s["service_type"] == "TRAIN"]
    assert train_svcs, f"no train service rows in {services}"
    logs = client.get_service_logs(train_svcs[0]["id"])
    assert logs["captured"], "train worker wrote no service log"
    # The trial lifecycle (runner INFO records) landed in THIS
    # service's file.
    assert "trial" in logs["log"]
    # Unknown ids are a clean 400-class error, not a 500.
    from rafiki_tpu.client import ClientError
    with pytest.raises(ClientError):
        client.get_service_logs("nope")

    # Tenant scoping: another (non-admin) user sees neither the service
    # rows nor the logs of this user's job — logs carry trial knobs,
    # scores and dataset paths.
    client.create_user("peek@x.c", "pw", UserType.APP_DEVELOPER)
    other = Client(admin_port=http_platform.app.port)
    other.login("peek@x.c", "pw")
    assert other.get_services() == []
    with pytest.raises(ClientError) as e:
        other.get_service_logs(train_svcs[0]["id"])
    assert e.value.status == 403


def test_dashboard_upload_and_log_elements(http_platform):
    """The browser-only flow's UI hooks exist in the served page."""
    url = f"http://127.0.0.1:{http_platform.app.port}/"
    text = requests.get(url, timeout=10).text
    for el in ("nd-upload", "nd-file", "nd-name", "nd-task",  # datasets
               "nm-src-file",                 # model .py file upload
               "services", "svclog",          # per-service log view
               "infstats", "infstats-summary",  # serving stats panel
               "phases", "phases-caches"):      # trial phase breakdown
        assert f'id="{el}"' in text, f"missing dashboard element #{el}"
    # the panel is fed by the admin's server-side /stats proxy
    assert "/stats" in text and "refreshInfStats" in text
    # the phase panel reads the admin's /trial_phases aggregation
    assert "/trial_phases" in text and "refreshTrialPhases" in text
    # the autoscale panel renders GET /autoscale's decision ring
    assert "/autoscale" in text and "refreshAutoscale" in text
    assert 'id="autoscale-card"' in text
    # the paste-a-trace-id panel renders GET /trace/<id> (r12: the
    # carried r7 item; cache/tier spans land in its timeline)
    for el in ("trace-id", "trace-go", "trace-spans"):
        assert f'id="{el}"' in text, f"missing dashboard element #{el}"
    assert "/trace/" in text


def test_oversized_upload_rejected_413(http_platform):
    """Review finding r4: request bodies are buffered in memory, so an
    oversized (or forged-huge Content-Length) upload must be rejected
    with 413 BEFORE any body byte is read — one multi-GB POST must not
    be able to OOM the admin process that supervises every service."""
    base = f"http://127.0.0.1:{http_platform.app.port}"
    # A forged Content-Length far over the cap: the server must answer
    # 413 without waiting for (or reading) the body.
    conn = socket.create_connection(("127.0.0.1",
                                     http_platform.app.port), timeout=10)
    try:
        conn.sendall((
            "POST /datasets?name=x&task=T HTTP/1.1\r\n"
            f"Host: 127.0.0.1\r\n"
            "Content-Type: application/octet-stream\r\n"
            "Content-Length: 99999999999\r\n\r\n").encode())
        reply = conn.recv(4096).decode()
    finally:
        conn.close()
    assert " 413 " in reply.splitlines()[0]
    # Within the cap still works (the normal-path guard is not overeager).
    r = requests.post(base + "/datasets?name=x&task=T", data=b"zz",
                      timeout=10,
                      headers={"Content-Type": "application/octet-stream"})
    assert r.status_code == 401  # small body reaches auth as before


def test_legacy_content_type_json_still_parses(http_platform):
    """Review finding r4: curl -d sends JSON bodies under
    x-www-form-urlencoded; the Content-Type gate for uploads must not
    break those legacy clients."""
    base = f"http://127.0.0.1:{http_platform.app.port}"
    r = requests.post(
        base + "/tokens",
        data='{"email": "superadmin@rafiki", "password": "rafiki"}',
        headers={"Content-Type": "application/x-www-form-urlencoded"},
        timeout=10)
    assert r.status_code == 200 and "token" in r.json()
